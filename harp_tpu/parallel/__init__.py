"""Parallel substrate: device mesh, collective verbs, rotation pipeline.

This package is the TPU-native replacement for Harp's L0–L3 communication
stack (SURVEY.md §2): ``edu.iu.harp.worker`` (membership),
``edu.iu.harp.io``/``.client``/``.server`` (Netty-socket transport + event
queue), and ``edu.iu.harp.collective`` (the collective algorithms).  On TPU
the transport is the ICI/DCN fabric driven by XLA, so all of L1 collapses
into compiled collective ops and only the *semantics* (the verbs and their
combiner behavior) survive as API.
"""

from harp_tpu.parallel.mesh import (
    WorkerMesh,
    current_mesh,
    init_distributed,
    mesh_2d,
    set_mesh,
)
from harp_tpu.parallel.collective import (
    Combiner,
    ShardSpec,
    allreduce,
    allreduce_hier,
    allgather,
    broadcast,
    match_reshard_rules,
    reduce,
    regroup,
    regroup_quantized,
    reshard,
    reshard_reference,
    rotate,
    rotate_quantized,
    push,
    pull,
    barrier,
)
from harp_tpu.parallel.pipeline import pipeline_forward, pipeline_loss_and_grads
from harp_tpu.parallel.rotate import resident_chunk_index, rotate_pipeline

__all__ = [
    "WorkerMesh",
    "current_mesh",
    "set_mesh",
    "init_distributed",
    "mesh_2d",
    "pipeline_forward",
    "pipeline_loss_and_grads",
    "Combiner",
    "ShardSpec",
    "allreduce",
    "allreduce_hier",
    "allgather",
    "match_reshard_rules",
    "reshard",
    "reshard_reference",
    "broadcast",
    "reduce",
    "regroup",
    "regroup_quantized",
    "rotate",
    "rotate_quantized",
    "push",
    "pull",
    "barrier",
    "resident_chunk_index",
    "rotate_pipeline",
]
