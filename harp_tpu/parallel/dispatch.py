"""Capacity-bounded destination bucketing — the all_to_all dispatch core.

Reference parity (SURVEY.md §3.5): Harp's ``regroup`` repartitions table
entries to their owning worker; the same all-to-all pattern underlies
expert-parallel dispatch.  This module is the one implementation of the
routing math shared by MoE dispatch (:mod:`harp_tpu.ops.moe`) and the
device-side KV shuffle (:func:`harp_tpu.table.regroup_by_key`): items
carry a destination id, each (source, destination) bucket holds a STATIC
``capacity`` slots (XLA needs static shapes), over-capacity items are
dropped via a trash slot that is sliced off before the exchange.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.utils import flightrec, telemetry


def bucket_by_destination(dest, payloads, capacity: int, n_dest: int,
                          valid=None):
    """Pack items into per-destination capacity buckets.

    Args:
      dest: [n] int — destination id per item (0 <= dest < n_dest).
      payloads: tuple of arrays with leading dim n (any trailing shape).
      capacity: slots per destination bucket.
      n_dest: number of destinations.
      valid: optional [n] bool — False items are intentionally skipped:
        they take no bucket slot, send nothing, and are NOT counted as
        dropped (capacity-drop accounting stays meaningful for padding-
        heavy callers like LDA pushpull chunks).
    Returns ``(bufs, keep, slot, dropped_local)``:
      bufs — tuple of [n_dest, capacity, ...] arrays, item i stored at
      ``(dest[i], slot[i])`` when kept, zeros elsewhere;
      keep — [n] bool, False for over-capacity (and invalid) items;
      slot — [n] int, the in-bucket position (== capacity for dropped
      items; pair with ``keep`` when gathering back);
      dropped_local — scalar count of THIS shard's dropped VALID items.
    """
    n = dest.shape[0]
    # flight recorder (trace time, static shapes only): the staged
    # exchange buffers are what the fabric moves — capacity slots ride
    # the wire whether or not they carry items, so the report can show
    # how much of the dispatch payload is padding
    if telemetry.enabled():
        flightrec.record_bucket(sum(
            n_dest * capacity * int(np.prod(p.shape[1:], dtype=np.int64))
            * jnp.dtype(p.dtype).itemsize for p in payloads))
    onehot = jax.nn.one_hot(dest, n_dest, dtype=jnp.int32)     # [n, n_dest]
    if valid is None:
        valid = jnp.ones(n, bool)
    else:
        onehot = onehot * valid[:, None].astype(onehot.dtype)
    # compact slots over VALID items only (invalid rows are all-zero in
    # the cumsum, so they never displace a valid item's position)
    pos = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(n), dest]
    keep = (pos < capacity) & valid
    slot = jnp.where(keep, pos, capacity)  # trash slot, sliced off below

    bufs = []
    for p in payloads:
        buf = jnp.zeros((n_dest, capacity + 1) + p.shape[1:], p.dtype)
        masked = p * keep.reshape((n,) + (1,) * (p.ndim - 1)).astype(p.dtype)
        bufs.append(buf.at[dest, slot].set(masked)[:, :capacity])
    return tuple(bufs), keep, slot, jnp.sum(~keep & valid)
