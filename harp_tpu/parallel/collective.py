"""The Harp collective verbs, TPU-native.

Reference parity (SURVEY.md §3.1, §3.6): ``edu.iu.harp.collective`` implements
allreduce (regroup-allgather and bidirectional-exchange algorithms), bucket
allgather, chain + MST broadcast, reduce, regroup (all-to-all by partitioner),
rotate (ring shift), push/pull (``LocalGlobalSyncCollective``), and barrier —
all as synchronous phases exchanging serialized Table partitions over Netty
TCP sockets, with a ``PartitionCombiner`` giving each op its reduction
semantics.

Here every verb lowers to a single XLA collective over ICI/DCN:

==============  =======================================================
Harp verb       XLA lowering (inside ``shard_map``)
==============  =======================================================
allreduce       ``psum`` / ``pmax`` / ``pmin`` / mean  (combiner picks)
allgather       ``all_gather``
broadcast       masked ``psum`` from root (chain/MST fan-out is XLA's
                problem, not user space's)
reduce          ``psum`` then keep-on-root mask
regroup         ``all_to_all`` (repartition by partitioner)
rotate          ``ppermute`` ring shift
push            ``psum_scatter`` (local deltas → owner shard)
pull            ``all_gather`` (owner shards → local replica)
barrier         trivial ``psum``; host-level: ``block_until_ready``
==============  =======================================================

All verbs are **pytree-polymorphic**: they accept any pytree of arrays, the
way Harp verbs accept any ``Table``.  They must be called from inside a
``shard_map`` region (device view) — see ``WorkerMesh.shard_map``.  There is
no algorithm selection surface (chain vs MST, regroup-allgather vs
bidirectional exchange): choosing the wire algorithm is XLA's job, informed
by the physical topology, which is precisely the layer Harp had to hand-roll
in user space.
"""

from __future__ import annotations

import dataclasses
import enum
import re
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel.mesh import WORKER_AXIS
from harp_tpu.utils.telemetry import record_comm


# ---------------------------------------------------------------------------
# The verbs' wire surface, as harplint's CommGraph layer sees it.
#
# Every verb lowers to one (or a few) of these jaxpr primitives; the
# static communication auditor (harp_tpu.analysis.commgraph) keys its
# schedule extraction on this map and matches each primitive eqn back to
# the CommLedger record at the same call site (telemetry.site_key is the
# shared key shape).  Keep this in sync when a verb gains a new lowering
# — an unmapped primitive is an untracked wire (HL301).
# ---------------------------------------------------------------------------

PRIMITIVE_VERBS: dict[str, tuple[str, ...]] = {
    "psum": ("allreduce", "allreduce_quantized", "reduce", "broadcast",
             "barrier", "push", "push_quantized",
             # the planner's hierarchical two-stage schedule (PR 11):
             # two grouped psums at one call site
             "allreduce_hier"),
    "pmax": ("allreduce", "reduce", "push",
             # the int8 wires' stacked per-leaf scale exchange
             "allreduce_quantized", "push_quantized", "rotate_quantized",
             "regroup_quantized", "reshard"),
    "pmin": ("allreduce", "reduce", "push"),
    "ppermute": ("rotate", "rotate_quantized", "reshard"),
    "all_gather": ("allgather", "pull", "reshard",
                   "allreduce"),  # the MULTIPLY combiner's gather+prod
    "all_to_all": ("regroup", "regroup_quantized", "reshard"),
    "reduce_scatter": ("push", "push_quantized"),  # lax.psum_scatter
}

#: the jaxpr primitives that move bytes over the worker axis
COLLECTIVE_PRIMS = frozenset(PRIMITIVE_VERBS)


class Combiner(enum.Enum):
    """Reduction semantics — Harp's ``PartitionCombiner`` / ``ValCombiner``.

    In Harp a combiner is a class resolving what happens when two partitions
    with the same ID meet during a collective.  Here it selects the XLA
    reduction op.
    """

    ADD = "add"
    MAX = "max"
    MIN = "min"
    AVG = "avg"
    MULTIPLY = "multiply"

    def reduce_over_axis(self, x, axis: str):
        if x.dtype == jnp.bool_:
            # psum/pmax promote bool; reduce in int32 and restore the dtype so
            # the verb API has one consistent contract (ADD≡any, MULTIPLY/MIN≡all).
            out = self.reduce_over_axis(x.astype(jnp.int32), axis)
            return out.astype(jnp.bool_)
        if self is Combiner.ADD:
            return lax.psum(x, axis)
        if self is Combiner.MAX:
            return lax.pmax(x, axis)
        if self is Combiner.MIN:
            return lax.pmin(x, axis)
        if self is Combiner.AVG:
            return lax.pmean(x, axis)
        if self is Combiner.MULTIPLY:
            # No pprod primitive: log-space would lose sign; use all_gather+prod.
            return jnp.prod(lax.all_gather(x, axis), axis=0)
        raise AssertionError(self)


def _as_combiner(op: "Combiner | str") -> Combiner:
    return op if isinstance(op, Combiner) else Combiner(str(op).lower())


# ---------------------------------------------------------------------------
# The nine Harp verbs + their quantized-wire twins (device view — call
# inside shard_map).
# ---------------------------------------------------------------------------

def allreduce(tree: Any, op: "Combiner | str" = Combiner.ADD, *, axis: str = WORKER_AXIS):
    """All workers end with the combined value — Harp ``allreduce(table)``.

    Harp implements this as regroup+allgather or bidirectional exchange over
    sockets; on TPU it is one fused ``psum`` riding ICI.
    """
    comb = _as_combiner(op)
    record_comm("allreduce", tree, axis=axis, combiner=comb.value)
    return jax.tree.map(lambda x: comb.reduce_over_axis(x, axis), tree)


def allreduce_quantized(tree: Any, *, wire_dtype: Any = jnp.bfloat16,
                        axis: str = WORKER_AXIS):
    """ADD-allreduce with a quantized wire format — EQuARX-style (PAPERS.md:
    "Efficient Quantized AllReduce in XLA", arXiv:2506.17615; pattern only,
    no code taken).  Cuts ICI/DCN bytes 2× (bf16) or 4× (int8) for
    bandwidth-bound gradient allreduces.

    - ``wire_dtype=jnp.bfloat16``: cast → psum → cast back.  Wire AND
      accumulation are bf16 (psum reduces in the operand dtype), so the
      error grows with ring size — the standard bf16 grad-allreduce trade,
      fine when gradient noise dominates, but NOT "rounds once".
    - ``wire_dtype=jnp.int8``: symmetric quantization with a worker-shared
      per-leaf scale: all float leaves' |max| values ride ONE stacked
      ``pmax`` (a single tiny collective regardless of tree size),
      contributions quantize to int8, ``psum`` accumulates in int32
      (exact), dequantize.  Per-worker error ≤ scale/2 with
      ``scale = global_max/127``.

    Non-float leaves reduce through the exact ADD combiner (bool stays
    bool, as in :func:`allreduce`).  This is a separate opt-in verb:
    Harp's allreduce contract (and ours) is full-precision by default.
    """
    return _quantized_reduce(
        tree, wire_dtype, axis, verb="allreduce_quantized",
        reduce_float=lambda x: lax.psum(x, axis),
        reduce_exact=lambda x: Combiner.ADD.reduce_over_axis(x, axis))


def _quantized_reduce(tree, wire_dtype, axis, reduce_float, reduce_exact,
                      verb):
    """Shared engine of :func:`allreduce_quantized` / :func:`push_quantized`
    — per-leaf scales via ONE stacked pmax, bf16 or exact-int32 int8
    accumulation; only the reduction primitive differs between the verbs."""
    wd = jnp.dtype(wire_dtype)
    if wd not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.int8)):
        raise ValueError(f"unsupported wire_dtype {wire_dtype!r} "
                         "(use jnp.bfloat16 or jnp.int8)")
    # recorded after the wire validation so a bad dtype raises the verb's
    # ValueError whether or not telemetry is on; ADD is both twins' only op
    record_comm(verb, tree, axis=axis, combiner="add", wire_dtype=wd)
    leaves, treedef = jax.tree.flatten(tree)
    is_float = [jnp.issubdtype(x.dtype, jnp.floating) for x in leaves]

    amaxes = None
    if wd == jnp.dtype(jnp.int8) and any(is_float):
        # one fused collective for every leaf's scale, not one per leaf
        amax = jnp.stack([jnp.max(jnp.abs(x)).astype(jnp.float32)
                          for x, f in zip(leaves, is_float) if f])
        amaxes = iter(lax.pmax(amax, axis))

    out = []
    for x, f in zip(leaves, is_float):
        if not f:
            out.append(reduce_exact(x))
        elif wd == jnp.dtype(jnp.bfloat16):
            out.append(reduce_float(x.astype(jnp.bfloat16)).astype(x.dtype))
        else:
            q, scale = quantize_to_int8(x, next(amaxes))
            total = reduce_float(q.astype(jnp.int32))
            out.append((total.astype(jnp.float32) * scale).astype(x.dtype))
    return jax.tree.unflatten(treedef, out)


def push_quantized(tree: Any, *, wire_dtype: Any = jnp.bfloat16,
                   axis: str = WORKER_AXIS, scatter_dim: int = 0):
    """ADD-``push`` (reduce-scatter) on a quantized wire — the
    :func:`allreduce_quantized` trade applied to the scatter half.

    The ZeRO-1 optimizer path (``MLPConfig.zero1``) reduces gradients
    with ``push`` instead of ``allreduce``; this is its narrow-wire
    option.  Semantics per dtype match the allreduce twin exactly:
    bf16 = cast → psum_scatter → cast back (wire AND accumulation bf16);
    int8 = worker-shared per-leaf scale via one stacked ``pmax``,
    int8 contributions, ``psum_scatter`` accumulates in exact int32,
    dequantize (per-worker error ≤ scale/2).  Non-float leaves take the
    exact ADD path.  ADD only — divide by ``axis_size`` for AVG, like
    the quantized allreduce's callers do.
    """
    def scatter(x):
        return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)

    def scatter_exact(x):
        # bool rides the wire as int32 and comes back bool (scattered OR) —
        # the same round-trip Combiner.ADD gives allreduce_quantized's
        # exact path, so the twins' docstring promise actually holds
        if x.dtype == jnp.bool_:
            return scatter(x.astype(jnp.int32)).astype(jnp.bool_)
        return scatter(x)

    return _quantized_reduce(tree, wire_dtype, axis, verb="push_quantized",
                             reduce_float=scatter,
                             reduce_exact=scatter_exact)


def _quantized_move(tree, wire_dtype, axis, move, verb):
    """Shared engine of :func:`rotate_quantized` / :func:`regroup_quantized`
    — pure **data movement** on a narrow wire, the EQuARX trade
    (PAPERS.md arXiv:2506.17615) applied to the permutation collectives.

    Unlike :func:`_quantized_reduce` nothing accumulates over the ring, so
    both formats round exactly ONCE per call and the error is independent
    of the ring size: bf16 is one cast each way; int8 uses a worker-shared
    per-leaf scale (all float leaves' |max| ride ONE stacked ``pmax``, so
    sender and receiver dequantize with the same replicated scale and no
    scale rides the wire) with error ≤ ``scale/2 = global_max/254`` per
    element.  Non-float leaves move exact at their own width.
    """
    wd = jnp.dtype(wire_dtype)
    if wd not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.int8)):
        raise ValueError(f"unsupported wire_dtype {wire_dtype!r} "
                         "(use jnp.bfloat16 or jnp.int8)")
    record_comm(verb, tree, axis=axis, wire_dtype=wd)
    leaves, treedef = jax.tree.flatten(tree)
    is_float = [jnp.issubdtype(x.dtype, jnp.floating) for x in leaves]

    amaxes = None
    if wd == jnp.dtype(jnp.int8) and any(is_float):
        # one fused collective for every leaf's scale, not one per leaf
        amax = jnp.stack([jnp.max(jnp.abs(x)).astype(jnp.float32)
                          for x, f in zip(leaves, is_float) if f])
        amaxes = iter(lax.pmax(amax, axis))

    out = []
    for x, f in zip(leaves, is_float):
        if not f:
            out.append(move(x))
        elif wd == jnp.dtype(jnp.bfloat16):
            out.append(move(x.astype(jnp.bfloat16)).astype(x.dtype))
        else:
            q, scale = quantize_to_int8(x, next(amaxes))
            out.append((move(q).astype(jnp.float32) * scale).astype(x.dtype))
    return jax.tree.unflatten(treedef, out)


def rotate_quantized(tree: Any, shift: int = 1, *,
                     wire_dtype: Any = jnp.bfloat16,
                     axis: str = WORKER_AXIS):
    """:func:`rotate` on a quantized wire — half (bf16) or a quarter (int8)
    of the ICI/DCN bytes per ring hop for bandwidth-bound model rotation.

    Rotation is pure data movement, so unlike :func:`allreduce_quantized`
    the error is a SINGLE rounding per call, independent of the ring size
    (bf16: one cast each way; int8: symmetric quantization against a
    worker-shared per-leaf ``pmax`` scale, error ≤ ``global_max/254`` per
    element) — strictly better conditioned than the reduce-side trade.
    Non-float leaves ride exact.  This is a separate opt-in verb: Harp's
    rotate contract (and ours) is full-precision by default; the chunked
    ``rotate_pipeline(wire=...)`` is the intended caller.
    """
    def move(x):
        n = lax.axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, axis, perm)

    return _quantized_move(tree, wire_dtype, axis, move, "rotate_quantized")


def regroup_quantized(tree: Any, *, wire_dtype: Any = jnp.bfloat16,
                      axis: str = WORKER_AXIS, split_dim: int = 0,
                      concat_dim: int | None = None):
    """:func:`regroup` (all-to-all repartition) on a quantized wire.

    Same single-rounding contract as :func:`rotate_quantized` — the
    shuffle moves data, it never accumulates, and the per-leaf int8 scale
    is ``pmax``'d over the axis so every (sender, receiver) pair agrees on
    it without shipping scales.  Non-float leaves ride exact.
    """
    cd = split_dim if concat_dim is None else concat_dim

    def move(x):
        return lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=cd, tiled=True)

    return _quantized_move(tree, wire_dtype, axis, move, "regroup_quantized")


def allgather(tree: Any, *, axis: str = WORKER_AXIS, tiled: bool = True):
    """Concatenate every worker's partitions on all workers — Harp allgather.

    With ``tiled=True`` (default) shards concatenate along their leading dim,
    matching Harp's "table ends up holding all partitions" semantics; with
    ``tiled=False`` a new leading worker axis is added.
    """
    record_comm("allgather", tree, axis=axis)
    return jax.tree.map(lambda x: lax.all_gather(x, axis, tiled=tiled), tree)


_UINT_OF_WIDTH = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def quantize_to_int8(x, amax):
    """Symmetric int8 quantization against a precomputed |max|:
    ``(q, scale)`` with ``scale = max(amax, 1e-30)/127`` and
    ``x ≈ q * scale`` (broadcasting ``amax``'s shape).  The one formula
    behind the quantized wire and the int8 compute paths — callers pick
    the amax granularity (global, per-row, per-feature, pmax'd)."""
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


@partial(jax.custom_jvp, nondiff_argnums=(1, 2))
def _broadcast_float(x, root: int, axis: str):
    """Bit-exact float broadcast: the payload rides the masked psum as a
    same-width integer (XLA CPU runs with FTZ/DAZ, so a float sum would
    flush subnormal payloads to zero — broadcast is data movement, not
    arithmetic).  bitcast has no derivative, hence the custom JVP below:
    broadcast is linear, so the tangent is the plain float masked-psum
    broadcast of the tangent — and because that formulation is
    transposable, reverse-mode (grad) falls out of it too, unlike a
    custom_vjp which would reject jvp/jacfwd/hessian."""
    keep = lax.axis_index(axis) == root
    bits = lax.bitcast_convert_type(x, _UINT_OF_WIDTH[jnp.dtype(x.dtype).itemsize])
    out = lax.psum(jnp.where(keep, bits, jnp.zeros_like(bits)), axis)
    return lax.bitcast_convert_type(out, x.dtype)


@_broadcast_float.defjvp
def _broadcast_float_jvp(root, axis, primals, tangents):
    (x,), (xd,) = primals, tangents
    keep = lax.axis_index(axis) == root
    tangent = lax.psum(jnp.where(keep, xd, jnp.zeros_like(xd)), axis)
    return _broadcast_float(x, root, axis), tangent


def broadcast(tree: Any, root: int = 0, *, axis: str = WORKER_AXIS):
    """Every worker receives root's value — Harp chain/MST ``broadcast``."""
    record_comm("broadcast", tree, axis=axis)

    def bcast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return _broadcast_float(x, root, axis)
        keep = lax.axis_index(axis) == root
        y = x.astype(jnp.int32) if x.dtype == jnp.bool_ else x
        # where (not multiply-by-mask): non-root buffers may hold NaN/inf
        # garbage that must be discarded, not zero-multiplied into NaN.
        out = lax.psum(jnp.where(keep, y, jnp.zeros_like(y)), axis)
        return out.astype(x.dtype)

    return jax.tree.map(bcast, tree)


def reduce(tree: Any, op: "Combiner | str" = Combiner.ADD, root: int = 0,
           *, axis: str = WORKER_AXIS):
    """Combine onto root; non-root workers get zeros — Harp ``reduce``.

    (Harp leaves non-root tables empty; zeros are the dense analogue.)
    """
    comb = _as_combiner(op)
    record_comm("reduce", tree, axis=axis, combiner=comb.value)

    def red(x):
        y = x.astype(jnp.int32) if x.dtype == jnp.bool_ else x
        total = comb.reduce_over_axis(y, axis)
        keep = lax.axis_index(axis) == root
        return jnp.where(keep, total, jnp.zeros_like(total)).astype(x.dtype)

    return jax.tree.map(red, tree)


def regroup(tree: Any, *, axis: str = WORKER_AXIS, split_dim: int = 0,
            concat_dim: int | None = None):
    """Repartition by owner — Harp ``regroup`` (the shuffle equivalent).

    Each worker's leading (``split_dim``) axis must be laid out in
    destination order: block *j* of the local array is sent to worker *j*
    (Harp's default ``Partitioner``: ``partition_id % num_workers``).  Lowers
    to one ``all_to_all``.
    """
    cd = split_dim if concat_dim is None else concat_dim
    record_comm("regroup", tree, axis=axis)
    return jax.tree.map(
        lambda x: lax.all_to_all(x, axis, split_axis=split_dim,
                                 concat_axis=cd, tiled=True),
        tree,
    )


def rotate(tree: Any, shift: int = 1, *, axis: str = WORKER_AXIS):
    """Ring-shift partitions to the next worker — Harp ``rotate``.

    The signature Harp primitive (dymoro model rotation, SURVEY.md §3.5):
    worker *i*'s data goes to worker *(i + shift) % N*.  Lowers to
    ``ppermute``, the same primitive ring attention is built on.
    """
    record_comm("rotate", tree, axis=axis)

    def rot(x):
        n = lax.axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, axis, perm)

    return jax.tree.map(rot, tree)


def push(tree: Any, op: "Combiner | str" = Combiner.ADD, *, axis: str = WORKER_AXIS,
         scatter_dim: int = 0):
    """Local contributions → combined owner shards — Harp ``push``.

    In Harp, ``LocalGlobalSyncCollective.push`` sends each locally-cached
    partition of a *global* (distributed) table back to its owner, combining
    with the owner's copy.  Dense analogue: every worker holds a full-size
    local contribution; the owner of each row-block receives the combined
    block.  ``psum_scatter`` does exactly this in one op.
    """
    comb = _as_combiner(op)
    record_comm("push", tree, axis=axis, combiner=comb.value)

    def do_push(x):
        if comb is Combiner.ADD:
            return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)
        if comb is Combiner.AVG:
            s = lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)
            return s / lax.axis_size(axis)
        # MAX/MIN have no fused reduce-scatter primitive: reduce, then slice
        # out our own block.
        total = comb.reduce_over_axis(x, axis)
        n = lax.axis_size(axis)
        if total.shape[scatter_dim] % n != 0:
            raise ValueError(
                f"push: scatter dimension size {total.shape[scatter_dim]} must "
                f"be divisible by the worker count {n}"
            )
        block = total.shape[scatter_dim] // n
        idx = lax.axis_index(axis) * block
        return lax.dynamic_slice_in_dim(total, idx, block, axis=scatter_dim)

    return jax.tree.map(do_push, tree)


def pull(tree: Any, *, axis: str = WORKER_AXIS, concat_dim: int = 0):
    """Owner shards → full local replica — Harp ``pull``.

    ``LocalGlobalSyncCollective.pull`` fetches the rows of the global table a
    worker needs into its local cache; the dense analogue materializes the
    whole global table locally via ``all_gather``.  For sparse row-subset
    pulls, gather rows *after* pulling (XLA keeps it fused) or use
    :func:`harp_tpu.table.pull_rows`.
    """
    record_comm("pull", tree, axis=axis)
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis, axis=concat_dim, tiled=True), tree
    )


def barrier(*, axis: str = WORKER_AXIS):
    """Synchronize all workers — Harp ``barrier``.

    Inside a compiled SPMD program workers are already in lockstep, so this
    is a semantic no-op implemented as a tiny psum (it forces a collective
    boundary, which is occasionally useful for profiling phase separation).
    Host-level synchronization is ``jax.block_until_ready`` on any output.
    """
    z = jnp.zeros((), jnp.int32)
    record_comm("barrier", z, axis=axis)
    return lax.psum(z, axis)


# ---------------------------------------------------------------------------
# reshard — the general redistribution verb (PR 11).
#
# Harp repartitions by hand-rolled plumbing per app (mfsgd/lda rotate
# their model slices, the KV tables regroup, pull replicates); the
# portable-redistribution paper (PAPERS.md arXiv:2112.01075) shows the
# whole family is ONE operation between two sharding layouts.  A
# :class:`ShardSpec` names a layout of a logical global array over the
# 1-D worker ring; ``reshard(x, src, dst)`` lowers to the cheapest legal
# move between the two — the decision table the collective planner
# (:mod:`harp_tpu.plan`) prices per site.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """One leaf's layout over the worker ring (device view).

    ``dim=None``: replicated — every worker holds the full array.
    ``dim=d``: block-partitioned along ``d`` into ``num_workers`` equal
    blocks; ``shift=s`` is the ring offset — worker ``w`` holds global
    block ``(w - s) % num_workers`` (``s=0`` is the home layout; the
    layout after ``rotate(shift=s)`` is exactly ``shift=s``).
    """

    dim: int | None = 0
    shift: int = 0

    def __post_init__(self):
        if self.dim is None and self.shift:
            raise ValueError("a replicated ShardSpec has no ring shift")

    @classmethod
    def replicated(cls) -> "ShardSpec":
        return cls(dim=None)

    @classmethod
    def blocked(cls, dim: int = 0, shift: int = 0) -> "ShardSpec":
        return cls(dim=dim, shift=shift)


def _leaf_path_name(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


def match_reshard_rules(rules, tree):
    """Regex partition-rule matching over a pytree — the SNIPPETS.md [1]
    ``match_partition_rules`` pattern (fmengine-style; pattern only, no
    code taken) applied to :class:`ShardSpec`.

    ``rules``: ordered ``[(regex, ShardSpec), ...]``; each leaf's
    '/'-joined key path is matched with ``re.search``, first hit wins.
    Scalar leaves (rank 0 or one element) are never partitioned — they
    resolve to the replicated spec, as the reference helper does.
    Raises on an unmatched non-scalar leaf: a silently-unsharded table
    is exactly the bug rule matching exists to prevent.
    """
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)

    def spec_for(path, leaf) -> ShardSpec:
        shape = getattr(leaf, "shape", np.shape(leaf))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return ShardSpec.replicated()
        name = _leaf_path_name(path)
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"no reshard rule matches leaf {name!r}")

    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(p, x) for p, x in flat])


#: reshard wire formats (shared vocabulary with the rotate pipeline)
RESHARD_WIRES = ("exact", "bf16", "int8")

_WIRE_DTYPES = {"bf16": jnp.bfloat16, "int8": jnp.int8}


def _spec_trees(tree, spec):
    """Broadcast a single ShardSpec over ``tree``, or pass a matching
    pytree of specs through (the ``match_reshard_rules`` output)."""
    if isinstance(spec, ShardSpec):
        return jax.tree.map(lambda _: spec, tree)
    return spec


def _reshard_plan(src: ShardSpec, dst: ShardSpec, n: int) -> tuple:
    """(kind, *params) for one leaf.  Kinds: "identity", "slice" (local
    dynamic_slice, no wire), "rotate" (ppermute ring shift), "a2a" (one
    all_to_all), "gather" (all_gather + static roll), "gather_slice"
    (the always-legal fallback: replicate, then slice locally)."""
    s_src = 0 if src.dim is None else src.shift % n
    s_dst = 0 if dst.dim is None else dst.shift % n
    if src.dim is None and dst.dim is None:
        return ("identity",)
    if src.dim == dst.dim and s_src == s_dst:
        return ("identity",)
    if src.dim is None:
        return ("slice", dst.dim, s_dst)
    if dst.dim is None:
        return ("gather", src.dim, s_src)
    if src.dim == dst.dim:
        return ("rotate", (s_dst - s_src) % n)
    if s_src == 0 and s_dst == 0:
        return ("a2a", src.dim, dst.dim)
    return ("gather_slice", src.dim, s_src, dst.dim, s_dst)


def _block_size(x, dim: int, n: int, what: str) -> int:
    if dim >= x.ndim:
        raise ValueError(
            f"reshard: {what} dim {dim} out of range for rank-{x.ndim} leaf")
    if x.shape[dim] % n:
        raise ValueError(
            f"reshard: leaf dim {dim} of size {x.shape[dim]} does not "
            f"split into {n} worker blocks")
    return x.shape[dim] // n


def _chunked_ring_move(x, dim: int, n_chunks: int, move):
    """The chunked ppermute pipeline lowering: split the leaf along its
    sharded dim into ``n_chunks`` sub-chunks and ship them through a
    scan — TACCL's chunked-pipelining observation (PAPERS.md
    arXiv:2111.04867) applied to a bare redistribution, so a planner
    schedule can overlap the hops of one large move."""
    if x.shape[dim] % n_chunks:
        raise ValueError(
            f"reshard: n_chunks={n_chunks} does not divide leaf dim "
            f"{dim} of size {x.shape[dim]}")
    m = x.shape[dim] // n_chunks
    shape = x.shape[:dim] + (n_chunks, m) + x.shape[dim + 1:]
    chunks = jnp.moveaxis(x.reshape(shape), dim, 0)

    def body(_, c):
        return None, move(c)

    _, out = lax.scan(body, None, chunks)
    out = jnp.moveaxis(out, 0, dim)
    return out.reshape(x.shape)


def _wire_move(x, wire: str, move, amax=None):
    """Apply ``move`` on the selected wire format — the one-rounding
    :func:`_quantized_move` trade, inlined so reshard emits exactly one
    collective per leaf (plus the shared scale pmax for int8)."""
    if wire == "exact" or not jnp.issubdtype(x.dtype, jnp.floating):
        return move(x)
    if wire == "bf16":
        return move(x.astype(jnp.bfloat16)).astype(x.dtype)
    q, scale = quantize_to_int8(x, amax)
    return (move(q).astype(jnp.float32) * scale).astype(x.dtype)


def reshard(tree: Any, src_spec, dst_spec, *, axis: str = WORKER_AXIS,
            wire: str = "exact", n_chunks: int = 1):
    """Move a pytree from one :class:`ShardSpec` layout to another —
    the general repartition verb behind the collective planner.

    ``src_spec`` / ``dst_spec``: one spec applied to every leaf, or a
    matching pytree of specs (see :func:`match_reshard_rules`).  Lowers
    per leaf to the cheapest legal move:

    ==============================  ====================================
    (src, dst)                      lowering
    ==============================  ====================================
    equal layouts                   identity (no wire)
    replicated → blocked            local ``dynamic_slice`` (no wire)
    same dim, shifts differ         ``ppermute`` ring rotation
    blocked dim a → blocked dim b   one ``all_to_all``  (shifts 0)
    blocked → replicated            ``all_gather`` + static roll
    anything else                   all_gather + local slice (fallback)
    ==============================  ====================================

    ``wire`` ("exact" | "bf16" | "int8") narrows the moving payload the
    :func:`rotate_quantized` way — pure data movement, one rounding per
    call, int8 scales ride ONE stacked pmax shared by all float leaves.
    ``n_chunks > 1`` lowers ring rotations as a chunked ppermute
    pipeline (a scan of sub-chunk hops — the planner's
    ``chunked_pipeline`` schedule); it is rotation-only and requires
    the sharded dim to split evenly.

    Every lowering is bit-identical to the naive
    :func:`reshard_reference` (all_gather + slice) on the exact wire —
    pinned pairwise by tests/test_reshard.py.  Must be called inside
    ``shard_map`` (device view).
    """
    if wire not in RESHARD_WIRES:
        raise ValueError(f"wire must be one of {RESHARD_WIRES}, "
                         f"got {wire!r}")
    if n_chunks < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    n = lax.axis_size(axis)
    leaves, treedef = jax.tree.flatten(tree)
    src_l = jax.tree.leaves(_spec_trees(tree, src_spec),
                            is_leaf=lambda s: isinstance(s, ShardSpec))
    dst_l = jax.tree.leaves(_spec_trees(tree, dst_spec),
                            is_leaf=lambda s: isinstance(s, ShardSpec))
    if not (len(leaves) == len(src_l) == len(dst_l)):
        raise ValueError("reshard: spec trees do not match the data tree")
    plans = [_reshard_plan(s, d, n) for s, d in zip(src_l, dst_l)]

    # one ledger record for the wire the move actually rides: chunked
    # rotations record the chunk-sized payload (what the traced ppermute
    # eqn carries per scan step — the HL302 cross-check is byte-exact),
    # local moves (identity/slice) record nothing.
    moving = []
    for x, src, plan in zip(leaves, src_l, plans):
        kind = plan[0]
        if kind in ("identity", "slice"):
            continue
        shape = x.shape
        if kind == "rotate" and n_chunks > 1:
            dim = _record_rotate_dim(x, src)
            shape = shape[:dim] + (shape[dim] // n_chunks,) + shape[dim + 1:]
        moving.append(jax.ShapeDtypeStruct(shape, x.dtype))
    if moving:
        record_comm("reshard", tuple(moving), axis=axis,
                    wire_dtype=None if wire == "exact"
                    else _WIRE_DTYPES[wire])

    # shared int8 scales: every moving float leaf's |max| rides ONE
    # stacked pmax (the _quantized_move idiom)
    amaxes = None
    if wire == "int8":
        flt = [x for x, p in zip(leaves, plans)
               if p[0] not in ("identity", "slice")
               and jnp.issubdtype(x.dtype, jnp.floating)]
        if flt:
            amax = jnp.stack([jnp.max(jnp.abs(x)).astype(jnp.float32)
                              for x in flt])
            amaxes = iter(lax.pmax(amax, axis))

    me = lax.axis_index(axis)
    out = []
    for x, src, dst, plan in zip(leaves, src_l, dst_l, plans):
        kind = plan[0]
        if kind == "identity":
            out.append(x)
            continue
        if kind == "slice":
            _, dim, s = plan
            bs = _block_size(x, dim, n, "dst")
            idx = ((me - s) % n) * bs
            out.append(lax.dynamic_slice_in_dim(x, idx, bs, axis=dim))
            continue
        amax = (next(amaxes) if amaxes is not None
                and jnp.issubdtype(x.dtype, jnp.floating) else None)
        if kind == "rotate":
            delta = plan[1]  # never 0: equal layouts plan as "identity"
            perm = [(i, (i + delta) % n) for i in range(n)]

            def hop(c, perm=perm):
                return lax.ppermute(c, axis, perm)

            def move(y, hop=hop):
                if n_chunks > 1:
                    dim = _record_rotate_dim(y, src)
                    return _chunked_ring_move(y, dim, n_chunks, hop)
                return hop(y)

            out.append(_wire_move(x, wire, move, amax))
            continue
        if n_chunks > 1:
            raise ValueError(
                "reshard: n_chunks applies to ring rotations only "
                f"(this leaf lowers to {kind!r})")
        if kind == "a2a":
            _, sd, dd = plan
            _block_size(x, dd, n, "dst")

            def move(y, sd=sd, dd=dd):
                return lax.all_to_all(y, axis, split_axis=dd,
                                      concat_axis=sd, tiled=True)

            out.append(_wire_move(x, wire, move, amax))
            continue
        # gather / gather_slice: replicate (all_gather + static roll for
        # a shifted source), then slice the destination block locally
        dim, s = plan[1], plan[2]

        def move(y, dim=dim):
            return lax.all_gather(y, axis, axis=dim, tiled=True)

        full = _wire_move(x, wire, move, amax)
        if s:
            full = jnp.roll(full, -s * x.shape[dim], axis=dim)
        if kind == "gather_slice":
            _, _, _, ddim, ds = plan
            bs = _block_size(full, ddim, n, "dst")
            idx = ((me - ds) % n) * bs
            full = lax.dynamic_slice_in_dim(full, idx, bs, axis=ddim)
        out.append(full)
    return jax.tree.unflatten(treedef, out)


def _record_rotate_dim(x, src: ShardSpec) -> int:
    """The dim a chunked rotation splits: the spec's sharded dim,
    clamped into range for low-rank leaves (a scalar ring hop cannot
    chunk — it degenerates to dim 0 and the divisibility check fires)."""
    dim = 0 if src.dim is None else src.dim
    if dim >= max(x.ndim, 1):
        raise ValueError(
            f"reshard: cannot chunk a rank-{x.ndim} leaf along dim {dim}")
    return dim


def reshard_reference(tree: Any, src_spec, dst_spec, *,
                      axis: str = WORKER_AXIS):
    """The naive lowering every :func:`reshard` path must reproduce
    bit-for-bit on the exact wire: replicate (all_gather + roll), then
    slice the destination block.  Test oracle only — it is deliberately
    unrecorded (no CommLedger entry) and always moves O(global) bytes.
    """
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    src_l = _spec_trees(tree, src_spec)
    dst_l = _spec_trees(tree, dst_spec)

    def one(x, src: ShardSpec, dst: ShardSpec):
        full = x
        if src.dim is not None:
            full = lax.all_gather(x, axis, axis=src.dim, tiled=True)
            if src.shift % n:
                full = jnp.roll(full, -(src.shift % n) * x.shape[src.dim],
                                axis=src.dim)
        if dst.dim is None:
            return full
        bs = _block_size(full, dst.dim, n, "dst")
        idx = ((me - dst.shift) % n) * bs
        return lax.dynamic_slice_in_dim(full, idx, bs, axis=dst.dim)

    return jax.tree.map(one, tree, src_l, dst_l)


def allreduce_hier(tree: Any, *, group_size: int | None = None,
                   axis: str = WORKER_AXIS):
    """ADD-allreduce as a hierarchical two-stage psum — the planner's
    ``hier_psum`` schedule (TACCL-style sketch, PAPERS.md
    arXiv:2111.04867): stage 1 reduces within contiguous groups of
    ``group_size`` workers (the intra-host link class), stage 2 reduces
    the group sums across groups (the inter-host class), so the payload
    crosses the slow link class once per group instead of once per
    worker.  On a FLAT ring this moves ~2× the one-shot psum's bytes
    (analytic ring algebra, 2026-08-04 — no silicon number yet) — it
    wins only when inter-host links are slower, which is exactly why
    it is a fail-closed flip candidate (``kmeans_hier_psum``), never a
    default.  ADD only; float sums reassociate across the two stages
    (ints are exact), the same tolerance class as any ring-order change.
    ``group_size`` must divide the axis size; ``None`` picks the largest
    divisor ≤ √n (the balanced two-stage split).
    """
    n = lax.axis_size(axis)
    if group_size is None:
        group_size = next(g for g in range(int(n ** 0.5), 0, -1)
                          if n % g == 0)
    if group_size < 1 or n % group_size:
        raise ValueError(
            f"group_size={group_size} must divide the axis size {n}")
    # both stages' payload rides the wire: account both (the CommGraph
    # byte sheet sees two psum eqns at this site and HL302 checks the
    # ledger to the byte)
    record_comm("allreduce_hier", (tree, tree), axis=axis, combiner="add")
    if group_size in (1, n):
        # degenerate split: one of the stages is a no-op group-of-one —
        # still TWO psums so the byte sheet matches the recorded wire
        intra = [[i] for i in range(n)] if group_size == 1 else [list(range(n))]
        inter = [list(range(n))] if group_size == 1 else [[i] for i in range(n)]
    else:
        intra = [list(range(g * group_size, (g + 1) * group_size))
                 for g in range(n // group_size)]
        inter = [list(range(i, n, group_size)) for i in range(group_size)]

    def two_stage(x):
        y = x.astype(jnp.int32) if x.dtype == jnp.bool_ else x
        y = lax.psum(y, axis, axis_index_groups=intra)
        y = lax.psum(y, axis, axis_index_groups=inter)
        return y.astype(x.dtype) if x.dtype == jnp.bool_ else y

    return jax.tree.map(two_stage, tree)


# ---------------------------------------------------------------------------
# Host-view wrappers: run ONE verb as a standalone pjit'd program on sharded
# host arrays.  Apps normally call the device-view verbs inside a larger
# jitted step (that is the whole point — zero host round-trips in the hot
# loop); these wrappers exist for interactive use, tests, and the benchmark
# app (edu.iu.benchmark parity).
# ---------------------------------------------------------------------------

def host_op(mesh, verb, *, in_dim: int | None = 0, out_dim: int | None = 0,
            **verb_kwargs):
    """Compile ``verb`` into a standalone shard_mapped callable.

    ``in_dim`` / ``out_dim`` give the worker-sharded dimension of the
    input/output (``None`` = replicated), e.g. allreduce is ``(0, None)``
    per-shard-in, replicated-out.

    Multi-process note: the returned callable produces a *global* array.
    Under ``jax.distributed`` (multi-host) a host can only read its own
    shards — use ``out.addressable_shards[i].data`` (or
    ``multihost_utils.process_allgather``) instead of ``np.asarray(out)``,
    which raises on non-addressable arrays (see tests/multiproc_worker.py).
    """
    fn = partial(verb, axis=mesh.axis, **verb_kwargs)
    in_spec = mesh.spec(in_dim) if in_dim is not None else jax.sharding.PartitionSpec()
    out_spec = mesh.spec(out_dim) if out_dim is not None else jax.sharding.PartitionSpec()
    return jax.jit(mesh.shard_map(fn, in_specs=(in_spec,), out_specs=out_spec))
