"""Model-rotation pipeline: Harp's dymoro, TPU-native — now chunked.

Reference parity (SURVEY.md §3.1, §3.5, §4.3): ``edu.iu.dymoro.Rotator`` +
``Scheduler`` implement Harp's signature optimization — while worker threads
update the model slice currently resident, the *next* slice is already in
flight from the ring neighbor, so communication hides behind compute.  A
timer bounds each compute phase so all workers rotate in lockstep.

TPU-native version: a ``lax.scan`` whose body runs the compute step on the
resident slice and then issues the ``ppermute``.  Overlap of transfer with
compute depends on the data flow: for **read-only** step functions XLA's
async scheduler overlaps the rotation with the next step's compute; for
**slice-updating** step functions (MF-SGD, LDA) a whole-slice rotation
serializes — a mutated partition cannot leave before the update finishes,
the constraint Harp's Rotator also has.  The cure is **chunking**
(``n_chunks > 1``): each worker's slice splits into ``n_chunks`` sub-slices
that alternate compute / in-flight roles, so the chunk updated at step
``t-1`` travels the ring while step ``t`` computes on the next one — a
software double buffer (TACCL's chunked-pipelining observation, PAPERS.md
arXiv:2111.04867, applied to the rotate collective).  ``n_chunks=2`` is
exactly the two-halves schedule MF-SGD and LDA used to hand-roll;
``wire`` selects the ring payload format (``"exact"`` ppermute, or the
quantized :func:`harp_tpu.parallel.collective.rotate_quantized` wire).
Lockstep comes free: SPMD programs advance together, so the timer-bounded
dynamic scheduling is replaced by fixed work per step (SURVEY.md §8 "hard
parts" — convergence must be validated per app, which the app tests do).

This is structurally the ring-attention ppermute pattern; long-context
sequence parallelism falls out of the same primitive (see
``harp_tpu.ops.ring_attention`` for the demonstration).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel.mesh import WORKER_AXIS
from harp_tpu.parallel.collective import ShardSpec, reshard

#: ring payload formats for the pipelined rotation (see rotate_pipeline)
ROTATE_WIRES = ("exact", "bf16", "int8")


def _wire_rotate(wire: str | None, shift: int, axis: str):
    """Resolve a ``wire`` name to the ring-hop move for in-flight chunks.

    PR 11: a ring hop IS a reshard between ring-shifted layouts, so the
    former bespoke rotate/rotate_quantized dispatch collapses into ONE
    ``reshard(blocked(0), blocked(0, shift), wire=...)`` call — the
    equivalence-pinned shim behind every rotation app (mfsgd, lda, ccd,
    ring attention ride this pipeline).  The lowering emits the exact
    same ``ppermute`` (same perm, same payload; quantized wires keep
    the one-rounding stacked-pmax contract), pinned bit-for-bit against
    the direct verb by tests/test_reshard.py and the apps' numpy
    goldens; the CommLedger verb at these sites is now ``reshard``.
    """
    if wire is None:
        wire = "exact"
    if wire not in ROTATE_WIRES:
        raise ValueError(
            f"wire must be one of {ROTATE_WIRES}, got {wire!r}")
    src, dst = ShardSpec.blocked(0), ShardSpec.blocked(0, shift=shift)
    return lambda tree: reshard(tree, src, dst, axis=axis, wire=wire)


def _split_chunks(tree: Any, n_chunks: int, axis: int):
    """Split every leaf's ``axis`` into ``n_chunks`` equal chunks, stacked
    on a new leading chunk dimension."""
    def split(x):
        if x.shape[axis] % n_chunks:
            raise ValueError(
                f"model slice dim {axis} of size {x.shape[axis]} does not "
                f"split into {n_chunks} equal rotation chunks")
        m = x.shape[axis] // n_chunks
        shape = x.shape[:axis] + (n_chunks, m) + x.shape[axis + 1:]
        return jnp.moveaxis(x.reshape(shape), axis, 0)

    return jax.tree.map(split, tree)


def _join_chunks(tree: Any, axis: int):
    """Inverse of :func:`_split_chunks`: merge the leading chunk dimension
    back into ``axis``."""
    def join(x):
        y = jnp.moveaxis(x, 0, axis)
        return y.reshape(y.shape[:axis]
                         + (y.shape[axis] * y.shape[axis + 1],)
                         + y.shape[axis + 2:])

    return jax.tree.map(join, tree)


def rotate_pipeline(
    step_fn: Callable[[Any, Any, Any], Any],
    carry: Any,
    model_slice: Any,
    *,
    n_steps: int | None = None,
    shift: int = 1,
    axis: str = WORKER_AXIS,
    n_chunks: int = 1,
    wire: str = "exact",
    chunk_axis: int = 0,
):
    """Run one rotation epoch of ``carry = step_fn(carry, chunk, t)``.

    ``n_chunks=1`` (default): each step computes on the whole resident
    slice, then rotates it onward — when ``gcd(shift, num_workers) == 1``,
    ``n_steps == num_workers`` steps visit every slice on every worker
    exactly once and leave each slice back home — one full Harp "epoch" of
    model rotation.  A ``shift`` sharing a factor with the ring size cycles
    through only ``num_workers/gcd`` slices; the default full-revolution
    mode rejects it rather than silently training on a subset of the model.
    With an update-free ``step_fn`` XLA overlaps the transfer with the next
    step's compute; with updates the handoff serializes (Harp's constraint
    too).

    ``n_chunks=C > 1``: the slice splits into C equal chunks along
    ``chunk_axis`` and the epoch becomes ``C * num_workers`` steps of a
    software double buffer — at step ``t`` the chunk updated at step
    ``t-1`` is in flight (its ``ppermute`` has no data dependency on this
    step's compute, so XLA overlaps it) while ``step_fn`` runs on the next
    resident chunk.  ``C=2`` reproduces the bespoke two-halves schedule
    bit-for-bit (``resident_half_index``); larger C shrinks each transfer
    and exposes more overlap slots at the cost of more scan steps.  Apps
    map step ``t`` to the resident chunk's global index with
    :func:`resident_chunk_index`.  ``n_steps`` must be left ``None`` (the
    full revolution) in chunked mode.

    ``wire`` selects the ring payload: ``"exact"`` (default — bit-exact
    ppermute), ``"bf16"`` or ``"int8"`` (the
    :func:`~harp_tpu.parallel.collective.rotate_quantized` formats; each
    hop re-rounds the chunk, so an epoch accumulates at most one rounding
    per hop a chunk travels).

    Args:
      step_fn: ``(carry, chunk, step_index) -> (carry, chunk)``; may update
        the chunk (MF-SGD does) — the updated chunk is what rotates onward,
        exactly like Harp rotating the mutated partition.
      carry: loop state local to the worker (e.g. W factor, rng key, loss).
      model_slice: this worker's resident slice of the global model (pytree).
      n_steps: unchunked mode only — defaults to the ring size (one full
        revolution).
      shift: ring direction/stride, as in Harp's rotate.

    Returns:
      ``(carry, model_slice)`` after the final step, chunks reassembled in
      home order.

    Must be called inside ``shard_map`` (device view).
    """
    if n_chunks < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    wrotate = _wire_rotate(wire, shift, axis)

    if n_chunks == 1:
        if n_steps is None:
            n_steps = lax.axis_size(axis)
            if math.gcd(shift % n_steps, n_steps) != 1:
                raise ValueError(
                    f"shift={shift} shares a factor with the ring size {n_steps}: "
                    f"a full revolution would visit only {n_steps // math.gcd(shift % n_steps, n_steps)} "
                    f"of {n_steps} slices; pass n_steps explicitly if that is intended"
                )

        def body(state, t):
            c, cur = state
            c, cur = step_fn(c, cur, t)
            # Rotation of the (possibly updated) slice. With an update-free
            # step_fn XLA overlaps this transfer with the next iteration's
            # compute; with updates it is the serialized handoff Harp also
            # has — use n_chunks > 1 to overlap through updates.
            nxt = wrotate(cur)
            return (c, nxt), None

        (carry, model_slice), _ = lax.scan(
            body, (carry, model_slice), jnp.arange(n_steps)
        )
        return carry, model_slice

    if n_steps is not None:
        raise ValueError(
            "chunked mode runs the full revolution (n_chunks * ring size "
            "steps); n_steps must be None")
    n = lax.axis_size(axis)
    if math.gcd(shift % n, n) != 1:
        raise ValueError(
            f"shift={shift} shares a factor with the ring size {n}: chunks "
            "would revisit a worker subset instead of covering the ring")

    buf = _split_chunks(model_slice, n_chunks, chunk_axis)
    # local chunks 0..C-2 queue up for compute; chunk C-1 starts in flight
    # (it is computed by workers w+shift .. w+n*shift and lands home on the
    # last step) — at C=2 this is exactly the former bespoke
    # computing/inflight half-slice split of mfsgd/lda.
    queue = jax.tree.map(lambda a: a[:-1], buf)
    inflight = jax.tree.map(lambda a: a[-1], buf)

    def body(state, t):
        c, q, infl = state
        received = wrotate(infl)  # no dep on this step's compute: overlaps
        cur = jax.tree.map(lambda a: a[0], q)
        c, cur = step_fn(c, cur, t)
        # pop the computed head; the received chunk joins the queue tail
        # (it computes C-1 steps from now, giving every chunk a period of
        # exactly C steps per worker hop — full (worker, chunk) coverage)
        q = jax.tree.map(
            lambda a, r: jnp.concatenate([a[1:], r[None]], axis=0),
            q, received)
        return (c, q, cur), None

    (carry, queue, inflight), _ = lax.scan(
        body, (carry, queue, inflight), jnp.arange(n_chunks * n)
    )
    # after C·n steps home chunk p sits at queue position p (p < C-1) and
    # chunk C-1 — computed on its home worker at the final step — is the
    # outgoing `inflight`; reassemble in home order
    buf = jax.tree.map(
        lambda a, b: jnp.concatenate([a, b[None]], axis=0), queue, inflight)
    return carry, _join_chunks(buf, chunk_axis)


def resident_chunk_index(t, n_chunks: int, *, shift: int = 1,
                         axis: str = WORKER_AXIS):
    """Global index of the chunk this worker computes at step ``t`` of the
    chunked ``rotate_pipeline`` (``n_chunks * num_workers`` steps/epoch).

    Chunk ``p`` of home worker ``w0`` (global index ``n_chunks*w0 + p``)
    computes every ``n_chunks`` steps, moving ``shift`` workers per period;
    the initial in-flight chunk (``p = n_chunks-1``) is one hop ahead.  So
    worker ``w`` at step ``t`` computes chunk
    ``n_chunks * ((w - (t // n_chunks + (r == n_chunks-1)) * shift) % n) + r``
    with ``r = t % n_chunks``.  ``n_chunks=2`` is the historical
    :func:`resident_half_index` schedule; ``n_chunks=1`` degenerates to
    :func:`resident_slice_index`.  The agreement between this formula and
    the pipeline's actual data movement is pinned by
    tests/test_rotate_chunked.py.
    """
    w = lax.axis_index(axis)
    n = lax.axis_size(axis)
    r = t % n_chunks
    ahead = jnp.where(r == n_chunks - 1, 1, 0) if n_chunks > 1 else 0
    home = (w - (t // n_chunks + ahead) * shift) % n
    return n_chunks * home + r


def resident_half_index(t, *, axis: str = WORKER_AXIS):
    """Half-slice resident on this worker at step ``t`` of the pipelined
    two-halves-per-worker rotation — :func:`resident_chunk_index` at
    ``n_chunks=2``, kept as the named schedule MF-SGD and LDA shipped with
    (step t computes half ``2*((w - t//2) % n)`` when t is even and
    ``2*((w - t//2 - 1) % n) + 1`` when odd; after 2n steps both halves
    are home and every (worker, half) pair met exactly once).
    """
    return resident_chunk_index(t, 2, axis=axis)


def resident_slice_index(t, *, shift: int = 1, axis: str = WORKER_AXIS):
    """Global index of the slice resident on this worker at rotation step t
    (unchunked pipeline).

    Slices start at their owners (slice *i* on worker *i*) and move ``shift``
    workers per step, so at step ``t`` worker ``w`` holds slice
    ``(w - t*shift) mod n``.  Apps use this to select the block of local
    data that touches the resident slice (MF-SGD: which rating columns;
    LDA: which vocabulary block).
    """
    n = lax.axis_size(axis)
    return (lax.axis_index(axis) - t * shift) % n
