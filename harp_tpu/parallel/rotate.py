"""Model-rotation pipeline: Harp's dymoro, TPU-native.

Reference parity (SURVEY.md §3.1, §4.3): ``edu.iu.dymoro.Rotator`` +
``Scheduler`` implement Harp's signature optimization — while worker threads
update the model slice currently resident, the *next* slice is already in
flight from the ring neighbor, so communication hides behind compute.  A
timer bounds each compute phase so all workers rotate in lockstep.

TPU-native version: a ``lax.scan`` whose body runs the compute step on the
resident slice and then issues the ``ppermute``.  Overlap of transfer with
compute depends on the data flow: for **read-only** step functions XLA's
async scheduler overlaps the rotation with the next step's compute (the
dymoro double-buffer, done by the compiler); for **slice-updating** step
functions (MF-SGD) the rotation consumes the step's output, so the handoff
serializes — exactly as it does in Harp, where a mutated partition cannot
leave before the update finishes.  Apps that want overlap with updates
should split the slice and rotate the half not being written (see
``harp_tpu.models.mfsgd``).  Lockstep comes free: SPMD programs advance
together, so the timer-bounded dynamic scheduling is replaced by fixed work
per step (SURVEY.md §8 "hard parts" — convergence must be validated per
app, which the app tests do).

This is structurally the ring-attention ppermute pattern; long-context
sequence parallelism falls out of the same primitive (see
``harp_tpu.ops.ring_attention`` for the demonstration).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel.mesh import WORKER_AXIS
from harp_tpu.parallel.collective import rotate


def rotate_pipeline(
    step_fn: Callable[[Any, Any, Any], Any],
    carry: Any,
    model_slice: Any,
    *,
    n_steps: int | None = None,
    shift: int = 1,
    axis: str = WORKER_AXIS,
):
    """Run ``n_steps`` rotation steps of ``carry = step_fn(carry, slice, t)``.

    Each step computes on the resident model slice, then rotates it onward.
    When ``gcd(shift, num_workers) == 1``, ``n_steps == num_workers`` steps
    visit every slice on every worker exactly once and leave each slice back
    home — one full Harp "epoch" of model rotation.  A ``shift`` sharing a
    factor with the ring size cycles through only ``num_workers/gcd`` slices;
    the default full-revolution mode rejects it rather than silently
    training on a subset of the model.

    Args:
      step_fn: ``(carry, model_slice, step_index) -> (carry, model_slice)``;
        may update the slice (MF-SGD does) — the updated slice is what
        rotates onward, exactly like Harp rotating the mutated partition.
      carry: loop state local to the worker (e.g. W factor, rng key, loss).
      model_slice: this worker's resident slice of the global model (pytree).
      n_steps: defaults to the ring size (one full revolution).
      shift: ring direction/stride, as in Harp's rotate.

    Returns:
      ``(carry, model_slice)`` after the final step's rotation.

    Must be called inside ``shard_map`` (device view).
    """
    if n_steps is None:
        n_steps = lax.axis_size(axis)
        if math.gcd(shift % n_steps, n_steps) != 1:
            raise ValueError(
                f"shift={shift} shares a factor with the ring size {n_steps}: "
                f"a full revolution would visit only {n_steps // math.gcd(shift % n_steps, n_steps)} "
                f"of {n_steps} slices; pass n_steps explicitly if that is intended"
            )

    def body(state, t):
        c, cur = state
        c, cur = step_fn(c, cur, t)
        # Rotation of the (possibly updated) slice. With an update-free
        # step_fn XLA overlaps this transfer with the next iteration's
        # compute; with updates it is the serialized handoff Harp also has.
        nxt = rotate(cur, shift=shift, axis=axis)
        return (c, nxt), None

    (carry, model_slice), _ = lax.scan(
        body, (carry, model_slice), jnp.arange(n_steps)
    )
    return carry, model_slice


def resident_half_index(t, *, axis: str = WORKER_AXIS):
    """Half-slice resident on this worker at step ``t`` of the pipelined
    two-halves-per-worker rotation (the schedule MF-SGD and LDA share).

    With n workers and 2n half-slices alternating compute/in-flight roles,
    step t computes half ``2*((w - t//2) % n)`` when t is even and
    ``2*((w - t//2 - 1) % n) + 1`` when odd; after 2n steps both halves
    are home and every (worker, half) pair met exactly once (see
    mfsgd._epoch_device_fn for the derivation).
    """
    w = lax.axis_index(axis)
    n = lax.axis_size(axis)
    return jnp.where(
        t % 2 == 0,
        2 * ((w - t // 2) % n),
        2 * ((w - t // 2 - 1) % n) + 1,
    )


def resident_slice_index(t, *, shift: int = 1, axis: str = WORKER_AXIS):
    """Global index of the slice resident on this worker at rotation step t.

    Slices start at their owners (slice *i* on worker *i*) and move ``shift``
    workers per step, so at step ``t`` worker ``w`` holds slice
    ``(w - t*shift) mod n``.  Apps use this to select the block of local
    data that touches the resident slice (MF-SGD: which rating columns;
    LDA: which vocabulary block).
    """
    n = lax.axis_size(axis)
    return (lax.axis_index(axis) - t * shift) % n
