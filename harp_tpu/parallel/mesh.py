"""Worker membership and device mesh.

Reference parity (SURVEY.md §3.1): ``edu.iu.harp.worker.Workers`` /
``WorkerInfo`` hold the rank→host:port membership list, the self ID, and the
master flag, populated from a nodes file during ``CollectiveMapper.setup()``'s
socket handshake.  On TPU none of that machinery is needed: membership *is*
the JAX device list, and the handshake is ``jax.distributed.initialize()``
(multi-host) plus mesh construction.  A Harp "worker" maps to one TPU chip
(BASELINE.json north star: "one Harp worker per chip via a pjit mesh").

Two views of the world:

- **Host view** (driver code): :class:`WorkerMesh` wraps a 1-D
  ``jax.sharding.Mesh`` over all chips with axis ``"workers"``; apps use it
  to shard inputs and to ``shard_map`` their step functions.
- **Device view** (inside ``shard_map``): :func:`worker_id`,
  :func:`num_workers`, :func:`is_master` — the SPMD analogues of Harp's
  ``getSelfID()`` / ``getNumWorkers()`` / ``isMaster()``.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from harp_tpu.utils import flightrec

WORKER_AXIS = "workers"


def _nbytes(x) -> int:
    """Payload bytes from shape/dtype only (never materializes ``x``)."""
    size = 1
    for s in getattr(x, "shape", np.shape(x)):
        size *= int(s)
    dt = getattr(x, "dtype", None)
    return size * (np.dtype(dt).itemsize if dt is not None
                   else np.result_type(x).itemsize)

# jax.shard_map landed as a top-level export (with check_vma) after the
# experimental era; on older jax the same callable lives in
# jax.experimental.shard_map and the replication-check kwarg is check_rep.
if hasattr(jax, "shard_map"):
    _shard_map, _CHECK_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - exercised only on old-jax environments
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

if not hasattr(lax, "axis_size"):  # pragma: no cover - old-jax only
    # lax.axis_size is a late addition; psum of the static int 1 over the
    # axis folds to the same static size on every jax that lacks it.  The
    # shim lands on lax itself so the 20+ call sites (and any Harp-style
    # app code written against the current API) need no indirection.
    def _axis_size_compat(axis_name):
        return lax.psum(1, axis_name)

    lax.axis_size = _axis_size_compat

_CURRENT_MESH: "WorkerMesh | None" = None


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join a multi-host job (DCN path).  No-op on a single host.

    Replaces Harp's worker bootstrap: the nodes-file discovery + socket
    handshake + membership barrier in ``CollectiveMapper.setup()`` becomes a
    single ``jax.distributed.initialize()`` call; XLA then routes cross-host
    collectives over DCN transparently once the mesh spans hosts.

    Args may be omitted when the standard cluster env vars (e.g. on Cloud
    TPU pods) let JAX auto-detect the topology.
    """
    explicit = coordinator_address is not None or num_processes is not None
    # auto-init only on genuinely multi-host topologies: a coordinator env
    # var, or a TPU hostname list naming more than one worker (single-host
    # TPU VMs export TPU_WORKER_HOSTNAMES=localhost)
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    auto = (
        any(v in os.environ for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"))
        or "," in hostnames
    )
    if not (explicit or auto):
        return  # single-host: nothing to do
    import jax._src.xla_bridge as xla_bridge

    if xla_bridge.backends_are_initialized():
        coord_set = any(v in os.environ for v in
                        ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"))
        if explicit or coord_set:
            # a declared multi-host topology that we can no longer join must
            # fail fast — proceeding would run N independent single-host jobs
            raise RuntimeError(
                "init_distributed must run before any JAX computation "
                "(the XLA backend is already initialized) — a coordinator "
                "address is configured, so this process would otherwise "
                "silently run single-host")
        return  # hostname-list heuristic only: assume single-host was intended
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Double-init is benign; anything else (unreachable coordinator,
        # topology mismatch) must fail fast — swallowing it would leave N
        # hosts running as N independent single-host jobs.
        if "already initialized" not in str(e).lower():
            raise


class WorkerMesh:
    """A 1-D mesh of Harp workers (one worker per chip).

    The Harp equivalents of the main members:

    ==================  =========================================
    harp-tpu            Harp (``edu.iu.harp.worker.Workers``)
    ==================  =========================================
    ``num_workers``     ``getNumWorkers()``
    ``devices``         the nodes list (rank → host:port)
    ``axis``            (implicit: the single worker group)
    ``shard_map(f)``    running ``f`` inside every worker JVM
    ==================  =========================================
    """

    def __init__(self, devices: Sequence[Any] | None = None, axis: str = WORKER_AXIS):
        if devices is None:
            devices = jax.devices()
        self.axis = axis
        self.mesh = Mesh(np.asarray(devices), (axis,))

    # -- membership ---------------------------------------------------------
    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    @property
    def num_workers(self) -> int:
        return self.mesh.devices.size

    # -- sharding helpers ---------------------------------------------------
    def spec(self, dim: int | None = 0, *, ndim: int | None = None) -> P:
        """PartitionSpec with the worker axis on ``dim`` (``None`` = replicated).

        The mesh is 1-D, so exactly one dimension can carry the worker axis.
        """
        if dim is None:
            return P()
        n = (ndim if ndim is not None else dim + 1)
        parts: list[Any] = [None] * n
        parts[dim] = self.axis
        return P(*parts)

    def sharding(self, spec: P | None = None) -> NamedSharding:
        return NamedSharding(self.mesh, spec if spec is not None else self.spec())

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_array(self, x, dim: int | None = 0):
        """Place a host array on the mesh, split along ``dim`` (None = replicate).

        Multi-host note: every process must pass the same GLOBAL ``x``;
        each contributes its addressable shards.  When each host holds
        only its own slice (sharded ingest), use
        :meth:`shard_array_local` instead.
        """
        spec = P() if dim is None else self.spec(dim, ndim=np.ndim(x))
        # flight recorder: shard_array is THE bulk ingest entry point —
        # its bytes are what the 30-40 MB/s relay tunnel actually carries;
        # record_h2d also feeds the same bytes to the memory ledger
        # (memrec, PR 19) as a 'staged' buffer entering the live set
        flightrec.record_h2d(_nbytes(x))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def shard_array_local(self, x_local, global_rows: int | None = None):
        """Assemble a dim-0-sharded global array from PER-PROCESS slices.

        The multi-host ingest primitive (Harp parity: each mapper read
        only its own HDFS split — SURVEY.md §4.2): process p passes only
        the rows its local devices own (the contiguous block
        ``[p * rows_per_process, (p+1) * rows_per_process)`` of the
        global row order), so no host ever materializes — or reads — the
        whole array.  ``global_rows`` defaults to ``local_rows *
        process_count`` (equal splits; required: dim 0 must divide
        evenly over processes).  Single-process: identical to
        ``shard_array(x, 0)``.
        """
        x_local = np.asarray(x_local)
        nproc = jax.process_count()
        gshape = ((global_rows if global_rows is not None
                   else x_local.shape[0] * nproc),) + x_local.shape[1:]
        sh = NamedSharding(self.mesh, self.spec(0, ndim=x_local.ndim))
        flightrec.record_h2d(x_local.nbytes)  # this process's slice only
        if nproc == 1:
            return jax.device_put(x_local, sh)
        return jax.make_array_from_process_local_data(sh, x_local, gshape)

    def survivors(self, lost: int) -> "WorkerMesh":
        """The submesh excluding worker ``lost`` — the elastic shrink
        (PR 15): a permanent worker loss rebuilds execution on this
        mesh instead of killing the job (Harp: YARN retried the whole
        job; here ``harp_tpu.elastic`` replays the repartition plan
        over the survivors from the last checkpoint)."""
        devs = self.devices
        if not 0 <= lost < len(devs):
            raise ValueError(
                f"lost worker {lost} is not on this mesh "
                f"({len(devs)} workers)")
        if len(devs) < 2:
            raise ValueError("cannot shrink a single-worker mesh")
        return WorkerMesh([d for i, d in enumerate(devs) if i != lost],
                          axis=self.axis)

    def shard_map(
        self,
        f: Callable,
        in_specs: Any,
        out_specs: Any,
        check_vma: bool = False,
    ) -> Callable:
        """Wrap ``f`` to run SPMD across workers (the per-worker view).

        This is the moral equivalent of Harp launching ``mapCollective()`` in
        every worker: inside ``f`` each worker sees only its shard, and the
        collective verbs (:mod:`harp_tpu.parallel.collective`) exchange data.
        """
        return _shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            **{_CHECK_KW: check_vma},
        )

    def __repr__(self) -> str:
        return f"WorkerMesh(num_workers={self.num_workers}, axis={self.axis!r})"


def mesh_2d(n_data: int, n_model: int, devices: Sequence[Any] | None = None,
            axes: tuple[str, str] = (WORKER_AXIS, "model")) -> Mesh:
    """A 2-D (data × model) ``jax.sharding.Mesh`` — the tensor-parallel
    extension beyond Harp's single worker axis (SURVEY.md §3.5: TP is not
    in the reference; this exists so model-sharded layers can ride GSPMD
    sharding annotations with no explicit collectives).
    """
    if devices is None:
        devices = jax.devices()
    if n_data * n_model > len(devices):
        raise ValueError(
            f"mesh_2d({n_data}x{n_model}) needs {n_data * n_model} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(grid, axes)


def current_mesh() -> WorkerMesh:
    """The process-wide default mesh (created over all devices on first use)."""
    global _CURRENT_MESH
    if _CURRENT_MESH is None:
        _CURRENT_MESH = WorkerMesh()
    return _CURRENT_MESH


def set_mesh(mesh: WorkerMesh | None) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


@contextlib.contextmanager
def use_mesh(mesh: WorkerMesh):
    global _CURRENT_MESH
    prev, _CURRENT_MESH = _CURRENT_MESH, mesh
    try:
        yield mesh
    finally:
        _CURRENT_MESH = prev


# -- device view (valid only inside shard_map) ------------------------------

def worker_id(axis: str = WORKER_AXIS):
    """This worker's rank — Harp's ``getSelfID()`` (device view)."""
    return lax.axis_index(axis)


def num_workers(axis: str = WORKER_AXIS):
    """Worker count — Harp's ``getNumWorkers()`` (device view)."""
    return lax.axis_size(axis)


def is_master(axis: str = WORKER_AXIS):
    """True on rank 0 — Harp's ``isMaster()`` (device view)."""
    return lax.axis_index(axis) == 0
