"""Pipeline parallelism over the worker ring — GPipe-style microbatching.

Beyond-reference extension (Harp has no inter-layer pipelining —
SURVEY.md §3.5; its closest machinery is the dymoro rotation pipeline,
which is exactly the ``ppermute`` ring this reuses): worker ``w`` owns
stage ``w``'s parameters, microbatches enter at stage 0, activations hop
worker→worker via :func:`harp_tpu.parallel.collective.rotate` each step,
and after ``S + M - 1`` steps all ``M`` microbatches have flowed through
all ``S`` stages.

Training falls out of autodiff: ``jax.grad`` through the scan
differentiates the ``ppermute``s (the transpose of a forward hop is a
backward hop), so each worker receives exactly its own stage's gradients —
no hand-written backward schedule.

Constraint: the activation that travels the ring is a single fixed-shape
array, so every stage must map ``[mb, width] → [mb, width]`` (uniform
width).  Real transformer-block pipelines satisfy this naturally.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WORKER_AXIS
from harp_tpu.utils.telemetry import span


def pipeline_forward(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                     stage_params: Any, microbatches: jnp.ndarray,
                     *, axis: str = WORKER_AXIS) -> jnp.ndarray:
    """Run microbatches through the S-stage pipeline (device view).

    Args (inside ``shard_map``; ``stage_params`` is THIS worker's stage):
      stage_fn: ``(params, [mb, width]) → [mb, width]`` — one stage.
      microbatches: ``[M, mb, width]``, replicated (stage 0 reads them).
    Returns ``[M, mb, width]`` outputs of the final stage, replicated.

    Telemetry: this function runs at trace time, so the span it opens
    measures pipeline *program construction* (S+M-1-step scan build); the
    per-hop ``rotate``/``broadcast`` wire bytes land in the CommLedger at
    their call sites below, multiplied by the host-side execution counter
    of whichever jitted step invokes the pipeline.
    """
    with span("pipeline_forward.trace",
              microbatches=int(microbatches.shape[0])):
        return _pipeline_forward(stage_fn, stage_params, microbatches,
                                 axis=axis)


def _pipeline_forward(stage_fn, stage_params, microbatches, *, axis):
    s = lax.axis_size(axis)
    me = lax.axis_index(axis)
    m, mb, width = microbatches.shape

    buf = jnp.zeros((mb, width), microbatches.dtype)
    outs = jnp.zeros_like(microbatches)

    def body(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t while it exists; later stages use
        # whatever the ring delivered last step
        inject = microbatches[jnp.minimum(t, m - 1)]
        cur = jnp.where((me == 0) & (t < m), inject, buf)
        y = stage_fn(stage_params, cur)
        # the final stage records microbatch (t - (S-1)) once it's flowed
        # through all S stages
        slot = t - (s - 1)
        take = (me == s - 1) & (slot >= 0) & (slot < m)
        outs = jnp.where(take, outs.at[jnp.clip(slot, 0, m - 1)].set(y), outs)
        return (C.rotate(y, axis=axis), outs), None

    (_, outs), _ = lax.scan(body, (buf, outs), jnp.arange(s + m - 1))
    # every worker gets the outputs (they're only valid on the last stage)
    return C.broadcast(outs, root=s - 1, axis=axis)


def pipeline_loss_and_grads(stage_fn, loss_fn, stage_params, microbatches,
                            targets, *, axis: str = WORKER_AXIS):
    """Mean loss over all microbatches + THIS worker's stage gradients.

    ``loss_fn(outputs [M, mb, width], targets) → scalar``.  Autodiff flows
    backward through the ring hops, so ``grads`` is exactly the gradient of
    the global loss w.r.t. this worker's stage parameters.

    The objective differentiated is ``loss / num_workers``: under
    ``shard_map`` every worker seeds a cotangent of 1 into the (replicated)
    loss, and the collective transposes deliver all of them to each stage —
    without the 1/S scale each worker's grads would be S× the true value
    (observed exactly 8× on an 8-worker mesh).
    """
    s = lax.axis_size(axis)

    def objective(params):
        outs = pipeline_forward(stage_fn, params, microbatches, axis=axis)
        return loss_fn(outs, targets) / s

    loss_scaled, grads = jax.value_and_grad(objective)(stage_params)
    return loss_scaled * s, grads
