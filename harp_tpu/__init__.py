"""harp-tpu: a TPU-native collective-ML framework with the capabilities of Harp.

Harp (Indiana University's "Map-Collective" framework, reference fork
imingtsou/Harp) turns Hadoop mappers into long-running iterating workers that
synchronize through in-memory collectives — allreduce, allgather, broadcast,
reduce, regroup, rotate, push/pull, barrier — with Harp-DAAL providing native
C++ compute kernels underneath.  See SURVEY.md for the full layer map.

harp-tpu is NOT a port.  It is the same capability surface re-designed for TPU:

- the worker membership list (``edu.iu.harp.worker.Workers``) becomes a
  :class:`harp_tpu.parallel.mesh.WorkerMesh` over a ``jax.sharding.Mesh``;
- the Table/Partition data model (``edu.iu.harp.partition``) becomes sharded
  arrays/pytrees with combiners mapped to XLA reduction ops
  (:mod:`harp_tpu.table`);
- the Netty-socket collectives (``edu.iu.harp.collective`` over
  ``edu.iu.harp.client``/``.server``) become XLA collectives over ICI/DCN
  inside ``shard_map`` (:mod:`harp_tpu.parallel.collective`);
- the dymoro model-rotation pipeline becomes a double-buffered ``ppermute``
  ring (:mod:`harp_tpu.parallel.rotate`);
- Intel-DAAL JNI kernels become ``jax.jit`` / Pallas compute in HBM
  (:mod:`harp_tpu.ops`, :mod:`harp_tpu.models`);
- the YARN ``CollectiveMapper`` driver becomes a plain host-side Python
  driver (:mod:`harp_tpu.mapper`).
"""

from harp_tpu.parallel.mesh import (
    WorkerMesh,
    current_mesh,
    set_mesh,
    init_distributed,
)
from harp_tpu.parallel import collective
from harp_tpu.parallel.collective import Combiner
from harp_tpu.table import (
    Int2DoubleKVTable,
    Int2FloatKVTable,
    Int2IntKVTable,
    Int2LongKVTable,
    KVTable,
    Long2DoubleKVTable,
    Long2IntKVTable,
    Partition,
    Table,
    combine_by_key,
    kv_allreduce,
    regroup_by_key,
)
from harp_tpu.mapper import CollectiveApp, KeyValReader, run_app
from harp_tpu.schedule import StaticScheduler, DynamicScheduler, Task

__version__ = "0.1.0"

__all__ = [
    "WorkerMesh",
    "current_mesh",
    "set_mesh",
    "init_distributed",
    "collective",
    "Combiner",
    "KVTable",
    "Int2IntKVTable",
    "Int2LongKVTable",
    "Int2FloatKVTable",
    "Int2DoubleKVTable",
    "Long2IntKVTable",
    "Long2DoubleKVTable",
    "kv_allreduce",
    "combine_by_key",
    "regroup_by_key",
    "Table",
    "Partition",
    "CollectiveApp",
    "KeyValReader",
    "run_app",
    "StaticScheduler",
    "DynamicScheduler",
    "Task",
    "__version__",
]
