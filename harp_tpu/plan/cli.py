"""``python -m harp_tpu plan`` — plan registered programs' collectives.

Extracts each registered driver program's CommGraph byte sheet (the
same Layer-4 walk the lint row ships), prices every site against the
selected topology, and prints a human schedule table plus ONE
provenance-stamped ``kind: "plan"`` JSON line per program (through
:func:`harp_tpu.utils.metrics.benchmark_json`, so the rows carry the
same backend/date/commit stamp as every bench row —
``scripts/check_jsonl.py`` invariant 10 validates the shape).

The jax-touching extraction forces the CPU backend (8 simulated
workers) before first backend use, exactly like the lint CLI — a
*planner* must never touch (or hang on) the relay; the topology being
priced is a model, not the backend the extraction runs on.
"""

from __future__ import annotations

import argparse
import sys


def _topology(name: str):
    from harp_tpu import plan as P

    if name == "auto":
        return P.detect()
    if name == "single_chip":
        return P.single_chip()
    if name == "sim_ring_8":
        return P.sim_ring(8)
    if name == "v4_32":
        return P.v4_32()
    raise ValueError(name)


def render(plan) -> str:
    lines = [f"== plan: {plan.program} on {plan.topology} "
             f"({plan.rates_source} rates) =="]
    if not plan.sites:
        lines.append("  (no collectives — nothing to schedule)")
    for s in plan.sites:
        alts = ", ".join(f"{k}={v:.3g}s" for k, v in
                         sorted(s.alternatives.items())) or "-"
        flip = f" -> flip candidate {s.flip_candidate}" \
            if s.flip_candidate else ""
        lines.append(
            f"  {s.site:24s} {s.primitive:14s} {s.verb or '?':18s} "
            f"{s.sheet_bytes:>12d} B  keep={s.cost_s:.3g}s  "
            f"[{alts}]{flip}")
    lines.append(f"  total predicted: {plan.predicted_bytes_total()} B; "
                 f"flip candidates: {plan.flip_candidates() or 'none'}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m harp_tpu plan",
        description="topology-aware collective planner over the "
                    "registered drivers' byte sheets (fail-closed: "
                    "decisions name flip candidates, never change "
                    "defaults)")
    p.add_argument("--program", action="append", default=None,
                   metavar="NAME",
                   help="plan only these registered driver programs "
                        "(default: all of analysis/drivers.py)")
    p.add_argument("--topology",
                   choices=("auto", "single_chip", "sim_ring_8", "v4_32"),
                   default="auto",
                   help="price list to plan against (auto = the active "
                        "mesh; v4_32 = the north-star slice with its "
                        "declared inter-host class)")
    p.add_argument("--json", action="store_true",
                   help="print only the machine-readable lines")
    args = p.parse_args(argv)

    from harp_tpu.analysis.cli import _force_cpu_backend

    _force_cpu_backend()

    from harp_tpu.analysis.drivers import DRIVERS
    from harp_tpu.plan import plan_program
    from harp_tpu.utils.metrics import benchmark_json

    names = args.program or sorted(DRIVERS)
    unknown = [n for n in names if n not in DRIVERS]
    if unknown:
        print(f"unknown program(s) {unknown}; registered: "
              f"{sorted(DRIVERS)}", file=sys.stderr)
        return 2
    topo = _topology(args.topology)
    for name in names:
        plan = plan_program(name, topo)
        if not args.json:
            print(render(plan))
        print(benchmark_json("plan", plan.row()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
