"""The collective planner: byte sheets × topology → an explicit Plan.

Reference parity (SURVEY.md §3.6, ROADMAP "topology-aware collective
planner"): Harp hard-codes one algorithm per collective call site;
TACCL (PAPERS.md arXiv:2111.04867) instead synthesizes the schedule
from a communication sketch plus a profiled topology.  harp-tpu's
sketch already ships: PR 9's CommGraph emits every registered driver
program's static collective schedule as byte-exact ``byte_sheets`` in
the lint row (HL301/HL302-gated against trace evidence, so the
planner's input cannot silently rot).  This module is the decision
side: for each site it prices today's schedule against the
alternatives the codebase can actually execute —

- ``hier_psum``        — :func:`collective.allreduce_hier`'s two-stage
  grouped psum (crosses the inter-host class once per host group);
- ``chunked_pipeline`` — the chunked ppermute pipeline
  (``rotate_pipeline(n_chunks=…)`` / ``reshard(n_chunks=…)``);
- ``wire_bf16`` / ``wire_int8`` — the EQuARX-style quantized wires
  (``reshard(wire=…)`` / ``*_quantized``, PAPERS.md arXiv:2506.17615)

— and emits a serializable :class:`Plan`.  **Every choice fails
closed**: the chosen ``schedule`` is always ``"keep"`` (bit-identical
to today's lowering); a cheaper-priced alternative only *names its
flip candidate* (the ``measure_all.py`` config that measures it), per
the repo's rule that no default changes without a relay-measured
``flip_decision`` verdict.  ``Plan.row()`` is the ``kind: "plan"``
JSONL record ``scripts/check_jsonl.py`` invariant 10 validates —
provenance-stamped, topology tag and schedules from frozen
vocabularies, and per-site predicted bytes equal to the program's byte
sheet (exactly, for the fail-closed ``keep``).
"""

from __future__ import annotations

import dataclasses

from harp_tpu.plan.topology import Topology, detect

#: the frozen schedule vocabulary (check_jsonl invariant 10 pins it);
#: "keep" is today's exact lowering — the only schedule a fail-closed
#: Plan ever *chooses*, the rest are priced alternatives.
SCHEDULES = ("keep", "hier_psum", "chunked_pipeline", "wire_bf16",
             "wire_int8")

#: per-schedule predicted per-site bytes, as a function of the sheet's
#: amplified site bytes (frozen math, mirrored standalone in
#: scripts/check_jsonl.py and sync-pinned by tests/test_plan.py):
#: keep/chunked move the same payload (chunking re-times hops, it does
#: not shrink them); hier_psum pays both stages; the narrow wires are
#: the EQuARX byte fractions (ceil — a byte sheet is integers).
def predicted_bytes(schedule: str, sheet_bytes: int) -> int:
    if schedule in ("keep", "chunked_pipeline"):
        return int(sheet_bytes)
    if schedule == "hier_psum":
        return 2 * int(sheet_bytes)
    if schedule == "wire_bf16":
        return (int(sheet_bytes) + 1) // 2
    if schedule == "wire_int8":
        return (int(sheet_bytes) + 3) // 4
    raise ValueError(f"unknown schedule {schedule!r}")


#: which alternatives each verb can legally lower to (the executable
#: surface, not a wish list: hier needs an ADD reduction; pipeline and
#: wires need a data-movement verb — reshard or its quantized twins)
_VERB_ALTERNATIVES = {
    "allreduce": ("hier_psum", "wire_bf16", "wire_int8"),
    "push": ("wire_bf16", "wire_int8"),
    "reshard": ("chunked_pipeline", "wire_bf16", "wire_int8"),
    "rotate": ("chunked_pipeline", "wire_bf16", "wire_int8"),
    "regroup": ("wire_bf16", "wire_int8"),
    "pull": (),           # replication has no narrower legal wire here
    "allgather": (),
    "broadcast": (),
    "reduce": (),
    "barrier": (),
}

#: (program, verb, schedule) → the measure_all.py config that measures
#: the alternative on silicon.  Only mapped sites can ever carry a
#: flip_candidate — an alternative with no measurement path stays a
#: priced row, never a recommendation (fail closed all the way down).
FLIP_CANDIDATE_CONFIGS = {
    ("kmeans.fit", "allreduce", "hier_psum"): "kmeans_hier_psum",
    ("mfsgd.epoch", "reshard", "chunked_pipeline"): "mfsgd_chunked_rotate",
    ("lda.epoch", "reshard", "wire_bf16"): "lda_planner_wire",
    ("lda.epoch", "reshard", "wire_int8"): "lda_rotate_int8",
    # PR 12: the last two per-app wires gain byte sheets + measurement
    # paths (ROADMAP planner item) — svm's per-round SV exchange and
    # wdamds's per-iteration coordinate exchange, both reshard
    # blocked→replicated sites gated on train_acc / final_stress
    ("svm.train", "reshard", "wire_bf16"): "svm_sv_bf16",
    ("svm.train", "reshard", "wire_int8"): "svm_sv_int8",
    ("wdamds.smacof", "reshard", "wire_bf16"): "wdamds_coord_bf16",
    ("wdamds.smacof", "reshard", "wire_int8"): "wdamds_coord_int8",
}


#: an alternative must price at least this much below "keep" before the
#: planner names its flip candidate — a ranking model's float noise (or
#: a genuinely-equal schedule like hier on a one-host ring) must never
#: read as a predicted win
CANDIDATE_MARGIN = 0.95


@dataclasses.dataclass
class SiteDecision:
    """One collective site's schedule decision (serialized per site in
    the plan row)."""

    site: str               # telemetry.site_key shape ("mfsgd.py:535")
    primitive: str
    verb: str | None
    sheet_bytes: int        # amplified per-site bytes FROM the byte sheet
    schedule: str = "keep"  # fail-closed: always "keep" today
    predicted_bytes: int = 0
    cost_s: float = 0.0     # topology price of the chosen schedule
    alternatives: dict = dataclasses.field(default_factory=dict)
    #: schedule -> measure_all config, one entry per alternative that
    #: both prices under the margin AND has a measurement path
    candidates: dict = dataclasses.field(default_factory=dict)
    flip_candidate: str | None = None   # the cheapest of `candidates`

    def row(self) -> dict:
        return {
            "site": self.site, "primitive": self.primitive,
            "verb": self.verb, "schedule": self.schedule,
            "sheet_bytes": self.sheet_bytes,
            "predicted_bytes": self.predicted_bytes,
            "cost_s": round(self.cost_s, 9),
            "alternatives": {k: round(v, 9)
                             for k, v in sorted(self.alternatives.items())},
            "candidates": dict(sorted(self.candidates.items())),
            "flip_candidate": self.flip_candidate,
        }


@dataclasses.dataclass
class Plan:
    """One program's explicit, serializable schedule plan."""

    program: str
    topology: str
    rates_source: str
    sites: list

    def predicted_bytes_total(self) -> int:
        return sum(s.predicted_bytes for s in self.sites)

    def flip_candidates(self) -> list:
        out: set = set()
        for s in self.sites:
            out.update(s.candidates.values())
        return sorted(out)

    def row(self) -> dict:
        """The ``kind: "plan"`` record (check_jsonl invariant 10)."""
        return {
            "kind": "plan",
            "program": self.program,
            "topology": self.topology,
            "rates_source": self.rates_source,
            "sites": [s.row() for s in self.sites],
            "predicted_bytes_total": self.predicted_bytes_total(),
            "flip_candidates": self.flip_candidates(),
        }


def _site_cost(topo: Topology, primitive: str, schedule: str,
               sheet_bytes: int) -> float:
    """Price one (site, schedule) pair — delegates to the SHARED wire
    oracle (PR 13): the Plan rows' cost column and the perfmodel's wire
    term are one function (``perfmodel.model.wire_cost_s``), so the
    planner and the predictor can never price the same site
    differently.  The sheet's bytes are already amplification-folded,
    so the topology sees amplification=1 here."""
    from harp_tpu.perfmodel.model import wire_cost_s

    return wire_cost_s(topo, primitive, schedule, sheet_bytes)


def decide_site(program: str, entry: dict, topo: Topology) -> SiteDecision:
    """One byte-sheet collective entry → its fail-closed decision.

    ``entry`` is a row of ``sheet["collectives"]`` (commgraph
    CommSite.row()): per_shard_bytes × amplification is the site's
    per-run payload.  The chosen schedule is ALWAYS "keep"; cheaper
    alternatives only attach their flip candidate, and only when
    a) the verb can legally lower to them, b) the site's wire is still
    exact (a quantized site already took its trade), and c) a
    measure_all config exists to measure them.
    """
    sheet_bytes = int(entry["per_shard_bytes"]) * max(
        int(entry.get("amplification") or 1), 1)
    prim = entry["primitive"]
    verb = entry.get("verb")
    dec = SiteDecision(site=entry["site"], primitive=prim, verb=verb,
                       sheet_bytes=sheet_bytes)
    dec.predicted_bytes = predicted_bytes("keep", sheet_bytes)
    dec.cost_s = _site_cost(topo, prim, "keep", sheet_bytes)
    already_quantized = bool(entry.get("ledger_wire")) or (
        verb or "").endswith("_quantized")
    for alt in _VERB_ALTERNATIVES.get(verb or "", ()):
        if already_quantized and alt.startswith("wire_"):
            continue
        cost = _site_cost(topo, prim, alt, sheet_bytes)
        dec.alternatives[alt] = cost
        if cost < dec.cost_s * CANDIDATE_MARGIN:
            cfg = FLIP_CANDIDATE_CONFIGS.get((program, verb, alt))
            if cfg is not None:
                dec.candidates[alt] = cfg
    if dec.candidates:
        dec.flip_candidate = dec.candidates[
            min(dec.candidates, key=lambda a: dec.alternatives[a])]
    return dec


def plan_sheet(program: str, sheet: dict,
               topo: Topology | None = None) -> Plan:
    """Plan one program from its (already extracted) byte sheet — the
    pure-decision core, usable straight off a committed lint row."""
    topo = topo or detect()
    sites = [decide_site(program, e, topo)
             for e in sheet.get("collectives") or []]
    return Plan(program=program, topology=topo.name,
                rates_source=topo.rates_source, sites=sites)


def plan_program(name: str, topo: Topology | None = None) -> Plan:
    """Extract the registered driver program's CommGraph (the same
    walk the lint row ships) and plan it."""
    from harp_tpu.analysis import commgraph
    from harp_tpu.analysis.drivers import DRIVERS

    if name not in DRIVERS:
        raise KeyError(
            f"{name!r} is not a registered driver program "
            f"(analysis/drivers.py has: {sorted(DRIVERS)})")
    fn, args = DRIVERS[name]()
    graph = commgraph.extract(name, fn, args)
    # carry each site's matched ledger wire into the sheet rows so
    # decide_site can skip re-quantizing an already-narrow wire
    rows = []
    for s in graph.sites:
        row = s.row()
        row["ledger_wire"] = s.ledger_wire
        rows.append(row)
    return plan_sheet(name, {"collectives": rows}, topo)


def plan_all(topo: Topology | None = None) -> dict:
    """Plan every registered driver program — the acceptance check that
    planner-predicted per-site bytes match the CommGraph byte sheets
    exactly rides this (tests/test_plan.py)."""
    from harp_tpu.analysis.drivers import DRIVERS

    topo = topo or detect()
    return {name: plan_program(name, topo) for name in sorted(DRIVERS)}
