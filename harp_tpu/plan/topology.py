"""Mesh topology model — the planner's price list.

Reference parity (SURVEY.md §3.6, ROADMAP "topology-aware collective
planner"): Harp's collective algorithms were chosen by hand per app
(regroup-allgather vs. bidirectional exchange) with no model of the
fabric underneath; TACCL (PAPERS.md arXiv:2111.04867) showed that a
*profiled topology* plus a communication sketch is enough to pick the
schedule per collective, and the portable-redistribution paper
(arXiv:2112.01075) prices redistribution the same way.  This module is
the harp-tpu topology side: a :class:`Topology` names the worker ring,
its host grouping, and two **link classes** (intra-host ICI vs.
inter-host ICI/DCN) with declared-or-probed rates; :meth:`Topology.
cost_s` prices one collective site as ``bytes × hops / rate`` per link
class — deliberately a *ranking* model (which schedule is cheapest
here), not a wall-clock predictor (ROADMAP's relay-free autotuning item
is the calibration story).

Three named instances are frozen into the plan-row vocabulary
(``scripts/check_jsonl.py`` invariant 10 — a plan row naming an unknown
topology is not evidence about this repo's meshes):

- ``single_chip``   — 1 worker; every "wire" is HBM (collectives fold).
- ``sim_ring_8``    — the 8-simulated-CPU-worker test mesh (declared
  loopback rate; absolute numbers meaningless, *ratios* still rank
  schedules identically, which is all the fail-closed planner uses).
- ``v4_32``         — the north-star v4-32 slice: 16 chips over 4 hosts
  (4 chips/host), declared ICI rates with the inter-host class slower
  (the hierarchical-psum win condition).  Rates are DECLARED
  assumptions until a relay window probes them (:func:`probed`), and
  every consumer stamps ``rates_source`` so a declared ranking can
  never masquerade as a measured one.
"""

from __future__ import annotations

import dataclasses

#: the frozen topology-tag vocabulary (check_jsonl invariant 10 pins it)
TOPOLOGY_NAMES = ("single_chip", "sim_ring_8", "v4_32")

#: declared per-chip HBM by topology tag (PR 19, the memory spine's
#: denominator): v4 ships 32 GiB HBM2 per chip (public spec); the CPU
#: sim targets model a v5e-class 16 GiB so headroom_frac is meaningful
#: on the test mesh.  DECLARED, like the link rates — a relay window
#: can overwrite via memrec.set_hbm_capacity.
HBM_BYTES_PER_CHIP = {
    "single_chip": 16 << 30,
    "sim_ring_8": 16 << 30,
    "v4_32": 32 << 30,
}


def hbm_bytes(name: str) -> int:
    """Declared per-chip HBM for a topology tag (16 GiB for unknown
    tags, e.g. sim_ring_N test meshes — conservative, never zero)."""
    return HBM_BYTES_PER_CHIP.get(name, 16 << 30)

#: per-worker wire-byte multipliers for a ring lowering of each
#: primitive, as a fraction of the jaxpr operand bytes ``b`` (the byte
#: sheet's ``per_shard_bytes``).  Ring algebra: psum = reduce-scatter +
#: allgather moves 2·b·(n-1)/n; all_gather of a b-byte shard sends it
#: n-1 times; ppermute is one hop; all_to_all keeps (n-1)/n of b on the
#: wire; pmax rides the psum formula (tiny scale exchanges).
_RING_FACTORS = {
    "psum": lambda n: 2.0 * (n - 1) / n,
    "pmax": lambda n: 2.0 * (n - 1) / n,
    "pmin": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: float(n - 1),
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "reduce_scatter": lambda n: (n - 1) / n,
}


@dataclasses.dataclass(frozen=True)
class Topology:
    """One mesh's link-class price list (see module docstring)."""

    name: str                 # frozen tag (TOPOLOGY_NAMES)
    n_workers: int
    workers_per_host: int
    intra_gbs: float          # intra-host link class rate, GB/s
    inter_gbs: float          # inter-host link class rate, GB/s
    rates_source: str = "declared"   # "declared" | "probed"

    def __post_init__(self):
        if self.n_workers < 1 or self.workers_per_host < 1:
            raise ValueError("topology needs >= 1 worker per class")
        if self.n_workers % self.workers_per_host:
            raise ValueError(
                f"{self.n_workers} workers do not group into hosts of "
                f"{self.workers_per_host}")
        if self.intra_gbs <= 0 or self.inter_gbs <= 0:
            raise ValueError("link rates must be positive")

    @property
    def hosts(self) -> int:
        return self.n_workers // self.workers_per_host

    def wire_bytes(self, primitive: str, per_shard_bytes: int,
                   amplification: int = 1) -> float:
        """Per-worker bytes on the wire for one site per program run."""
        factor = _RING_FACTORS.get(primitive)
        if factor is None:
            raise ValueError(f"unknown collective primitive {primitive!r}")
        if self.n_workers == 1:
            return 0.0
        return per_shard_bytes * factor(self.n_workers) * max(
            amplification, 1)

    def cost_s(self, primitive: str, per_shard_bytes: int,
               amplification: int = 1) -> float:
        """Seconds to move one site's wire bytes: bytes × hops / rate
        per link class.  Ring steps run link-concurrently, so a flat
        ring's time is its per-link bytes over the SLOWEST link class
        it crosses — on a multi-host ring the host-boundary links gate
        every step (the hierarchical-schedule win condition); one-host
        rings ride the intra class alone."""
        wire = self.wire_bytes(primitive, per_shard_bytes, amplification)
        if wire == 0.0:
            return 0.0
        rate = (min(self.intra_gbs, self.inter_gbs) if self.hosts > 1
                else self.intra_gbs)
        return wire / (rate * 1e9)

    def hier_stage_cost_s(self, per_shard_bytes: int,
                          amplification: int = 1) -> float:
        """The hierarchical two-stage reduction's price (the bandwidth-
        optimal decomposition this model assumes the grouped-psum
        lowering achieves): stage 1 reduce-scatters inside each host
        (intra class, ring of ``workers_per_host``), stage 2 allreduces
        across hosts with each of the ``workers_per_host`` workers
        carrying its 1/g payload shard over the boundary (inter class),
        stage 3 allgathers intra — so the slow class moves
        ``2·(hosts-1)/hosts · b/g`` instead of the flat ring's full
        ``2·(n-1)/n · b``."""
        b = per_shard_bytes * max(amplification, 1)
        g, h = self.workers_per_host, self.hosts
        intra = (2.0 * (g - 1) / g) * b / (self.intra_gbs * 1e9) if g > 1 \
            else 0.0
        inter = (2.0 * (h - 1) / h) * (b / g) / (self.inter_gbs * 1e9) \
            if h > 1 else 0.0
        return intra + inter


def single_chip() -> Topology:
    """One worker: every collective folds to a copy; HBM-class rate."""
    return Topology("single_chip", 1, 1, intra_gbs=819.0, inter_gbs=819.0)


def sim_ring(n: int = 8) -> Topology:
    """The n-simulated-CPU-worker test ring (tests/conftest.py mesh).
    Declared loopback rate — ratios rank schedules, absolutes are
    meaningless, which the fail-closed planner never forgets."""
    return Topology(f"sim_ring_{n}", n, n, intra_gbs=10.0, inter_gbs=10.0)


def v4_32() -> Topology:
    """The north-star v4-32 slice: 16 chips over 4 hosts.  DECLARED
    rates, not measurements (2026-08-04, no chip touched: ~45 GB/s/dir
    intra-host ICI from the public v4 ICI spec, ~25 GB/s effective
    across the host-boundary torus links — the BASELINE.md scaling
    section's assumption class) — probe on a live relay
    (:func:`probed`) before believing absolute seconds."""
    return Topology("v4_32", 16, 4, intra_gbs=45.0, inter_gbs=25.0)


def detect(mesh=None) -> Topology:
    """The topology of the ACTIVE mesh: single_chip for one device, the
    sim ring for the CPU backend, v4_32 for a 16-chip TPU mesh; any
    other shape falls back to a one-host ring of the right size (a
    conservative price list — no inter-host class to mis-model)."""
    import jax

    from harp_tpu.parallel.mesh import current_mesh

    mesh = mesh or current_mesh()
    n = mesh.num_workers
    if n == 1:
        return single_chip()
    backend = jax.default_backend()
    if backend == "tpu" and n == 16:
        return v4_32()
    return sim_ring(n)


def probed(topo: Topology, mesh=None, size_mb: float = 4.0) -> Topology:
    """Replace a topology's DECLARED intra-class rate with one measured
    through :func:`harp_tpu.benchmark.bench_verb` (allreduce at
    ``size_mb``) — the probed-rates half of the ISSUE's "probed/declared"
    contract.  Runs wherever the mesh runs (CPU sim included); on the
    relay, probe inside a watched window only (CLAUDE.md).  The
    inter-host rate keeps its declared value until a multi-host probe
    exists — the stamp says ``probed`` either way so consumers can ask.
    """
    from harp_tpu import benchmark as B
    from harp_tpu.parallel.mesh import current_mesh

    mesh = mesh or current_mesh()
    rec = B.bench_verb("allreduce", mesh, int(size_mb * (1 << 20)), reps=2)
    rate_gbs = rec["gb_per_sec"]
    return dataclasses.replace(topo, intra_gbs=max(rate_gbs, 1e-3),
                               rates_source="probed")
