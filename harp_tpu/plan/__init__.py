"""Topology-aware collective planner (PR 11).

The decision side of PR 9's CommGraph byte sheets: a mesh topology
model (:mod:`harp_tpu.plan.topology`) prices each registered program's
collective sites per link class, and the planner
(:mod:`harp_tpu.plan.planner`) emits an explicit, serializable
:class:`~harp_tpu.plan.planner.Plan` whose every choice FAILS CLOSED —
the chosen schedule is today's exact lowering, and cheaper-priced
alternatives name their ``measure_all.py`` flip candidate instead of
flipping anything themselves.  ``python -m harp_tpu plan`` is the front
door; ``scripts/check_jsonl.py`` invariant 10 validates the rows.
"""

from harp_tpu.plan.planner import (
    FLIP_CANDIDATE_CONFIGS,
    Plan,
    SCHEDULES,
    SiteDecision,
    decide_site,
    plan_all,
    plan_program,
    plan_sheet,
    predicted_bytes,
)
from harp_tpu.plan.topology import (
    TOPOLOGY_NAMES,
    Topology,
    detect,
    probed,
    sim_ring,
    single_chip,
    v4_32,
)

__all__ = [
    "FLIP_CANDIDATE_CONFIGS",
    "Plan",
    "SCHEDULES",
    "SiteDecision",
    "TOPOLOGY_NAMES",
    "Topology",
    "decide_site",
    "detect",
    "plan_all",
    "plan_program",
    "plan_sheet",
    "predicted_bytes",
    "probed",
    "sim_ring",
    "single_chip",
    "v4_32",
]
