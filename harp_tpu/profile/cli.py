"""``python -m harp_tpu profile <app|--all>`` — the wall-attribution CLI.

Captures each requested app's registered driver under the device-trace
hook, attributes every op into the frozen mechanism buckets, and prints
a human attribution table (or, with ``--json``, one provenance-stamped
``kind:"profile"`` row per app — the exact shape check_jsonl invariant
15 validates, so ``profile --all --json > PROFILE_attrib.jsonl``
regenerates the committed baseline).

Exit codes: 0 every row reconciled; 1 any row failed a cross-check
(bucket sum, flightrec dispatch count, compile in the timed window, or
an unmatched CommLedger wire site); 2 unknown app / capture error.

Forces the 8-worker CPU backend before first backend use (the axon site
config pins ``JAX_PLATFORMS`` to the TPU relay; a profiler run from the
dev loop must never hang on it — see CLAUDE.md "Environment gotchas").
Silicon attribution rows arrive through the bench/PROFILE_local path,
graded against this CPU baseline by the health sentinel's
``profile_drift`` detector.
"""

from __future__ import annotations

import argparse
import json
import sys


def _render(row: dict) -> str:
    terms = row["terms"]
    wall = row["wall_s"] or 1e-12
    parts = "  ".join(
        f"{k[:-2]} {v:.4f}s ({100.0 * v / wall:4.1f}%)"
        for k, v in sorted(terms.items(), key=lambda kv: -kv[1])
        if v > 0)
    flag = "ok" if row["reconciled"] else "FAILED"
    return (f"{row['app']:9s} {row['program']:20s} wall {wall:.4f}s  "
            f"bound={row['bound']:11s} [{flag}]\n"
            f"          {parts}\n"
            f"          wire {row['wire_bytes']} B over "
            f"{row['wire_sites']} site(s)  dispatches "
            f"{row['dispatches']} ({row['dispatches_per_rep']}/rep)  "
            f"compiles {row['compiles_in_window']}  "
            f"sum_rel_err {row['sum_rel_err']}")


def main(argv=None) -> int:
    from harp_tpu.analysis.cli import _force_cpu_backend

    p = argparse.ArgumentParser(
        prog="python -m harp_tpu profile",
        description="capture one driver run per app and attribute its "
                    "wall to the frozen mechanism buckets")
    p.add_argument("app", nargs="?", help="app to profile "
                   "(kmeans/mfsgd/lda/rf/svm/wdamds/subgraph/serve)")
    p.add_argument("--all", action="store_true",
                   help="profile every registered app")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one kind:'profile' JSONL row per app")
    p.add_argument("--reps", type=int, default=4,
                   help="timed repetitions inside the trace (default 4)")
    args = p.parse_args(argv)

    from harp_tpu.profile.attribution import PROFILE_APPS, capture

    if args.all:
        apps = list(PROFILE_APPS)
    elif args.app:
        if args.app not in PROFILE_APPS:
            print(f"unknown app {args.app!r}; known: "
                  f"{', '.join(PROFILE_APPS)}", file=sys.stderr)
            return 2
        apps = [args.app]
    else:
        p.print_usage(sys.stderr)
        return 2

    _force_cpu_backend()
    rows = []
    for app in apps:
        try:
            rows.append(capture(app, reps=args.reps))
        except Exception as e:  # noqa: BLE001 - a broken capture is loud
            print(f"profile: capture failed for {app!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    for row in rows:
        if args.as_json:
            print(json.dumps(row), flush=True)
        else:
            print(_render(row))
    return 0 if all(r["reconciled"] for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
