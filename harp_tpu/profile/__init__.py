"""Wall-attribution observatory (PR 16): ``python -m harp_tpu profile``.

See :mod:`harp_tpu.profile.attribution` for the frozen bucket vocabulary
and the capture/reconciliation contract (check_jsonl invariant 15).
"""

from harp_tpu.profile.attribution import (  # noqa: F401
    BUCKETS,
    PROFILE_APPS,
    SUM_REL_TOL,
    attribute,
    capture,
    capture_all,
    classify,
)
