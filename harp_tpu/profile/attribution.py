"""Wall-attribution observatory — op→mechanism bucket attribution (PR 16).

Reference parity: SURVEY.md "Observability" — Harp's tuning loop starts
from a hand-read profile; here the capture→attribute→reconcile pass is a
machine-checked telemetry product (``kind:"profile"`` rows, check_jsonl
invariant 15) instead of a one-off ritual over raw ``PROFILE_local.jsonl``
traces.  HARP (arXiv:2509.24859) steers placement from exactly this kind
of automated profile→cost-model hookup.

Every device op from one captured run of a registered driver program
(:mod:`harp_tpu.analysis.drivers`) is classified into the perfmodel's
FROZEN six-term mechanism vocabulary (:data:`BUCKETS`):

- ``mxu``          — matmul/conv/einsum (the MXU roofline term)
- ``elementwise``  — memory-bound VPU work: fusions, reduces, copies, RNG
- ``gather_dus``   — gather / dynamic-slice / dynamic-update-slice traffic
- ``scatter``      — scatter / segment ops (the 25 GB/s wall measured
  2026-07-30 on 1x v5e)
- ``wire``         — collective traffic (all-reduce/all-gather/ppermute…)
- ``overhead``     — runtime/dispatch/host spans + unattributed wall

The pass is fail-closed, cross-reconciled against the other two spines:

- bucket seconds sum to the measured wall EXACTLY by construction
  (unattributed wall lands in ``overhead``; over-attribution beyond
  :data:`SUM_REL_TOL` fails the row, the residual is ``sum_rel_err``);
- dispatch counts must match the flight recorder
  (``dispatches == reps * dispatches_per_rep``, zero compiles in the
  timed window);
- every static collective site must carry a CommLedger verb match
  (``wire_unmatched == 0``); ``wire_bytes`` is the CommGraph amplified
  byte sheet for one trace of the program.

CPU-sim semantics (the default backend here — the analysis CLIs force
the 8-device CPU mesh): the trace has no per-device tracks, so each of
the N concurrent per-device executor threads re-emits the same program's
spans; attributed seconds are normalized by the device count, and the
per-device skew column degrades to a single aggregate (satellite: on a
real device capture, per-device bucket totals feed
``skew.record_execution`` so a single hot chip shows in ``skew``
reports).  Donation is ignored by the CPU sim, so re-calling a donating
serve executable with the same staged buffers is safe HERE and only
here — the capture loop is not a silicon protocol.
"""

from __future__ import annotations

import re
import tempfile
import time

# FROZEN vocabulary — check_jsonl.KNOWN_PROFILE_BUCKETS is sync-pinned to
# this tuple by tests/test_check_jsonl.py; a row's ``terms`` must carry
# exactly these keys with an ``_s`` suffix.  Order is the classifier
# priority (wire before mxu so "all-gather" never reads as gather).
BUCKETS = ("mxu", "elementwise", "gather_dus", "scatter", "wire",
           "overhead")

# Max tolerated over-attribution (sum of per-device-normalized op
# self-times exceeding the measured wall) before the row fails closed.
# On the CPU sim the device-count normalization under-divides whenever
# XLA's intra-op Eigen pool spills op spans onto threads BEYOND the N
# device-client threads (rf.grow's histogram matmuls: worst observed
# ratio 1.44x wall, 2026-08-06; every other driver ≤ 1.0x).  0.75
# bounds that concurrency blur while still failing a genuinely broken
# capture (>1.75x); on real per-device trace tracks the residual is
# ~0.  check_jsonl.PROFILE_SUM_REL_TOL is sync-pinned to this.
SUM_REL_TOL = 0.75

# app name (CLI surface) → registered driver program.  FROZEN:
# check_jsonl.KNOWN_PROFILE_APPS is sync-pinned to this mapping.
PROFILE_APPS = {
    "kmeans": "kmeans.fit",
    "mfsgd": "mfsgd.epoch",
    "lda": "lda.epoch",
    "rf": "rf.grow",
    "svm": "svm.train",
    "wdamds": "wdamds.smacof",
    "subgraph": "subgraph.count",
    "serve": "serve.kmeans_assign",
    # PR-17 kernelized arms (PR 18 closes the coverage gap): the flip
    # candidates priced off the dense rows' attribution now carry their
    # own — a kernel that moved the bound shows up here first.
    "rf_pallas": "rf.grow_pallas",
    "svm_pallas": "svm.train_pallas",
    "wdamds_pallas": "wdamds.smacof_pallas",
}

# -- the classifier ---------------------------------------------------------
# First match wins, in BUCKETS priority order.  Names come from
# op_breakdown (XLA HLO op/fusion names plus, on CPU, runtime spans like
# "TfrtCpuExecutable::Execute" / "PjitFunction(fn)" — the "::" test and
# the infra words pick those off into overhead).
_WIRE = re.compile(r"all-reduce|all-gather|all-to-all|collective-permute"
                   r"|reduce-scatter|ppermute|psum|\bsend\b|\brecv\b")
_MXU = re.compile(r"dot|conv(?!ert)|einsum|matmul")
_SCATTER = re.compile(r"scatter|segment")
_GATHER = re.compile(r"gather|dynamic-slice|dynamic_slice"
                     r"|dynamic-update-slice|dynamic_update_slice")
_INFRA = re.compile(r"::|^Pjit|^Parse|Listener|Executor|Executable|Thunk"
                    r"|^jit_|^while|^condition|^body|^region|^call[._]"
                    r"|^parameter|^constant$|^tuple|^copy-start"
                    r"|^copy-done|^infeed|^outfeed|Transfer|barrier")


def classify(op_name: str) -> str:
    """Map one trace span name to its mechanism bucket."""
    if _WIRE.search(op_name):
        return "wire"
    if _MXU.search(op_name):
        return "mxu"
    if _SCATTER.search(op_name):
        return "scatter"
    if _GATHER.search(op_name):
        return "gather_dus"
    if _INFRA.search(op_name):
        return "overhead"
    return "elementwise"


def attribute(breakdown, wall_s: float, n_devices: int) -> dict:
    """Bucket a ``op_breakdown(per_device=True)`` list against a wall.

    Pure attribution (no capture) so tests can forge breakdowns: sums
    per-device self-time into :data:`BUCKETS`, normalizes by
    ``n_devices`` (each device thread re-emits the program on the CPU
    sim; on device tracks this averages per-chip busy time), then
    reconciles to ``wall_s`` — shortfall fills ``overhead``, excess
    rescales and is reported as ``sum_rel_err``.
    """
    bucket_s = {b: 0.0 for b in BUCKETS}
    for name, _dev, sec in breakdown:
        bucket_s[classify(name)] += float(sec)
    n = max(int(n_devices), 1)
    for b in bucket_s:
        bucket_s[b] /= n
    attributed = sum(bucket_s.values())
    if attributed > wall_s > 0:
        sum_rel_err = attributed / wall_s - 1.0
        scale = wall_s / attributed
        bucket_s = {b: s * scale for b, s in bucket_s.items()}
    else:
        sum_rel_err = 0.0
        bucket_s["overhead"] += max(wall_s - attributed, 0.0)
    bound = max(BUCKETS, key=lambda b: bucket_s[b])
    return {"terms": {f"{b}_s": round(bucket_s[b], 6) for b in BUCKETS},
            "bound": bound, "sum_rel_err": round(sum_rel_err, 4)}


def _materialize(a):
    """Concrete (zeros) array for a driver ShapeDtypeStruct arg."""
    import jax
    import jax.numpy as jnp

    if isinstance(a, jax.ShapeDtypeStruct):
        x = jnp.zeros(a.shape, a.dtype)
        if a.sharding is not None:
            x = jax.device_put(x, a.sharding)
        return x
    return a


def capture(app: str, *, reps: int = 4, logdir: str | None = None) -> dict:
    """Run one app's registered driver under the device-trace hook and
    return its fully reconciled ``kind:"profile"`` row.

    Raises ``KeyError`` for an unknown app.  The row carries
    ``reconciled: False`` (never an exception) when any cross-check
    fails — the CLI turns that into exit 1.
    """
    import jax

    from harp_tpu.analysis import commgraph
    from harp_tpu.analysis.drivers import DRIVERS
    from harp_tpu.utils import flightrec, profiling, skew, telemetry

    program = PROFILE_APPS[app]
    logdir = logdir or tempfile.mkdtemp(prefix=f"harp_profile_{app}_")

    # Wire sheet: static CommGraph walk of a fresh build, trace-time
    # CommLedger records matched site-by-site (the HL301 machinery).
    b_fn, b_args = DRIVERS[program]()
    graph = commgraph.extract(program, b_fn, b_args)
    wire_bytes = int(graph.amplified_bytes())
    wire_sites = len(graph.sites)
    wire_unmatched = sum(1 for s in graph.sites if s.verb is None)

    fn, spec_args = DRIVERS[program]()
    with telemetry.scope(True, reset=False):
        args = [_materialize(a) for a in spec_args]
        jax.block_until_ready(fn(*args))          # warmup compile
        base = flightrec.snapshot()
        jax.block_until_ready(fn(*args))
        per_rep = int(flightrec.delta_since(base)["dispatches"])
        if per_rep == 0:
            # Driver callable is not flightrec-tracked — wrap it so the
            # dispatch reconciliation below has a spine to agree with.
            fn = flightrec.track(fn, f"profile.{app}")
            jax.block_until_ready(fn(*args))
            per_rep = 1

        base = flightrec.snapshot()
        with profiling.trace(logdir):
            # Wall is timed INSIDE the trace block: start_trace itself
            # costs seconds and must not pollute the attribution target.
            t0 = time.perf_counter()
            out = None
            for _ in range(reps):
                out = fn(*args)
            jax.block_until_ready(out)
            wall = time.perf_counter() - t0
        delta = flightrec.delta_since(base)

        breakdown = profiling.op_breakdown(logdir, top=10 ** 6,
                                           per_device=True)
        dev_ids = sorted({d for _, d, _ in breakdown if d is not None})
        n_devices = len(dev_ids) if len(dev_ids) >= 2 else \
            jax.device_count()
        attrib = attribute(breakdown, wall, n_devices)

        # Per-device skew of the attributed seconds into the skew spine
        # (one column per device track; single aggregate on the CPU sim).
        if dev_ids:
            vec = [sum(s for _, d, s in breakdown if d == dev)
                   for dev in dev_ids]
        else:
            vec = [sum(s for _, _, s in breakdown) / n_devices]
        skew.record_execution(f"profile.{app}", vec, unit="seconds",
                              wall_s=wall)

    dispatches = int(delta["dispatches"])
    compiles = int(delta["compiles"])
    dispatch_ok = dispatches == reps * per_rep
    reconciled = (dispatch_ok and compiles == 0 and wire_unmatched == 0
                  and attrib["sum_rel_err"] <= SUM_REL_TOL)
    return {
        "kind": "profile", "app": app, "program": program,
        "wall_s": round(wall, 6), "reps": reps,
        "n_devices": int(n_devices),
        "terms": attrib["terms"], "bound": attrib["bound"],
        "sum_rel_err": attrib["sum_rel_err"],
        "wire_bytes": wire_bytes, "wire_sites": wire_sites,
        "wire_unmatched": wire_unmatched,
        "dispatches": dispatches, "dispatches_per_rep": per_rep,
        "dispatch_reconciled": dispatch_ok,
        "compiles_in_window": compiles,
        "reconciled": reconciled,
        **flightrec.provenance_stamp(),
    }


def capture_all(*, reps: int = 4) -> list:
    """One :func:`capture` row per app, in :data:`PROFILE_APPS` order."""
    return [capture(app, reps=reps) for app in PROFILE_APPS]
