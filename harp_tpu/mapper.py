"""CollectiveApp — the ``CollectiveMapper`` residue (Harp L4).

Reference parity (SURVEY.md §3.1, §4.1): Harp apps subclass
``edu.iu.harp.mapcollective.CollectiveMapper`` whose ``run()`` bootstraps
the worker (peer discovery, socket server, membership barrier), calls the
user's ``mapCollective(reader, context)`` exactly once with the whole
iterative program inside, then tears down and writes outputs.  The mapper
exposes ``allreduce/…/getSelfID/getNumWorkers/isMaster`` to app code.

On TPU the bootstrap collapses to ``jax.distributed.initialize()`` + mesh
construction, and one Python process per *host* drives all its chips, so
the "mapper" is a thin lifecycle wrapper: config → mesh → ``map_collective``
→ metrics/checkpoint teardown.  Apps can equally use the function-style
drivers in :mod:`harp_tpu.models` directly; this class exists for ports of
Harp app code that want the familiar shape.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

from harp_tpu.parallel import collective
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh, init_distributed
from harp_tpu.utils.metrics import MetricsLogger

log = logging.getLogger("harp_tpu")


class CollectiveApp:
    """Base class for Harp-style applications.

    Subclass and override :meth:`map_collective`.  Inside it, use
    ``self.mesh`` to shard/compile, the collective verbs via
    ``harp_tpu.parallel.collective`` inside your shard_mapped step
    functions, and ``self.metrics`` for per-iteration logging (Harp's
    per-iteration wall-clock logs, structured).
    """

    def __init__(self, config: Any = None, mesh: WorkerMesh | None = None,
                 metrics_path: str | None = None):
        self.config = config
        init_distributed()  # no-op on single host (Harp's bootstrap)
        self.mesh = mesh or current_mesh()
        self.metrics = MetricsLogger(metrics_path)

    # -- Harp mapper API ----------------------------------------------------
    @property
    def num_workers(self) -> int:
        """``getNumWorkers()``."""
        return self.mesh.num_workers

    def is_master(self) -> bool:
        """``isMaster()`` — host-process view (process 0 of the job)."""
        import jax

        return jax.process_index() == 0

    # -- lifecycle ----------------------------------------------------------
    def map_collective(self) -> Any:
        """The whole iterative program — override me (Harp's mapCollective)."""
        raise NotImplementedError

    def run(self) -> Any:
        """``CollectiveMapper.run()``: setup → mapCollective → cleanup."""
        t0 = time.perf_counter()
        log.info("harp-tpu app starting: %d workers, config=%s",
                 self.num_workers, self.config)
        try:
            result = self.map_collective()
        finally:
            self.metrics.close()
        log.info("harp-tpu app finished in %.2fs", time.perf_counter() - t0)
        return result


def run_app(app_cls, config=None, **kw):
    """Launcher helper: ``hadoop jar harp-app.jar Launcher`` equivalent."""
    return app_cls(config, **kw).run()
