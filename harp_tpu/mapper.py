"""CollectiveApp — the ``CollectiveMapper`` residue (Harp L4).

Reference parity (SURVEY.md §3.1, §4.1): Harp apps subclass
``edu.iu.harp.mapcollective.CollectiveMapper`` whose ``run()`` bootstraps
the worker (peer discovery, socket server, membership barrier), calls the
user's ``mapCollective(reader, context)`` exactly once with the whole
iterative program inside, then tears down and writes outputs.  The mapper
exposes ``allreduce/…/getSelfID/getNumWorkers/isMaster`` to app code.

On TPU the bootstrap collapses to ``jax.distributed.initialize()`` + mesh
construction, and one Python process per *host* drives all its chips, so
the "mapper" is a thin lifecycle wrapper: config → mesh → ``map_collective``
→ metrics/checkpoint teardown.  Apps can equally use the function-style
drivers in :mod:`harp_tpu.models` directly; this class exists for ports of
Harp app code that want the familiar shape.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

from harp_tpu.parallel import collective
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh, init_distributed
from harp_tpu.utils import flightrec, telemetry
from harp_tpu.utils.metrics import MetricsLogger
from harp_tpu.utils.telemetry import span

log = logging.getLogger("harp_tpu")


class KeyValReader:
    """This worker's input splits — Harp's ``KeyValReader`` handed to
    ``mapCollective`` (key = file path, value = loader result).

    Harp's map-collective jobs use ``MultiFileInputFormat`` so each mapper
    receives whole files; the reader iterates them.  Here the splits come
    from :mod:`harp_tpu.fileformat` and ``value`` is produced lazily by the
    ``loader`` (default: the native C++ CSV loader, the Harp-DAAL
    ``HarpDAALDataSource`` equivalent).
    """

    def __init__(self, paths: list[str], loader=None):
        if loader is None:
            from harp_tpu.native.datasource import load_csv as loader
        self._paths = list(paths)
        self._loader = loader
        self._pos = 0
        self._value = None  # loaded lazily, cached per position

    def __iter__(self):
        for p in self._paths:
            yield p, self._loader(p)

    # Harp's imperative reader API (nextKeyValue/getCurrentKey/getCurrentValue)
    def next_key_value(self) -> bool:
        if self._pos >= len(self._paths):
            return False
        self._pos += 1
        self._value = None
        return True

    def current_key(self) -> str:
        if self._pos == 0:
            raise RuntimeError("call next_key_value() before current_key()")
        return self._paths[self._pos - 1]

    def current_value(self):
        if self._pos == 0:
            raise RuntimeError("call next_key_value() before current_value()")
        if self._value is None:
            self._value = self._loader(self._paths[self._pos - 1])
        return self._value

    @property
    def paths(self) -> list[str]:
        return list(self._paths)


class CollectiveApp:
    """Base class for Harp-style applications.

    Subclass and override :meth:`map_collective`.  Inside it, use
    ``self.mesh`` to shard/compile, the collective verbs via
    ``harp_tpu.parallel.collective`` inside your shard_mapped step
    functions, and ``self.metrics`` for per-iteration logging (Harp's
    per-iteration wall-clock logs, structured).
    """

    def __init__(self, config: Any = None, mesh: WorkerMesh | None = None,
                 metrics_path: str | None = None,
                 input_paths: list[str] | None = None, loader=None,
                 budget: dict | None = None):
        self.config = config
        # execution-discipline budget for the whole map_collective block
        # (flightrec.budget kwargs, e.g. {"compiles": 3, "readbacks": 1});
        # enforced warn-mode in run() when telemetry is enabled, so an app
        # can declare its dispatch discipline without dying mid-run
        self.budget = budget
        init_distributed()  # no-op on single host (Harp's bootstrap)
        self.mesh = mesh or current_mesh()
        self.metrics = MetricsLogger(metrics_path)
        # this host's input splits (MultiFileInputFormat semantics): split
        # the file list over *processes* — each process drives its chips
        self.reader = None
        if input_paths is not None:
            import jax

            from harp_tpu.fileformat import multi_file_splits

            splits = multi_file_splits(input_paths, jax.process_count())
            self.reader = KeyValReader(splits[jax.process_index()], loader)

    # -- Harp mapper API ----------------------------------------------------
    @property
    def num_workers(self) -> int:
        """``getNumWorkers()``."""
        return self.mesh.num_workers

    def is_master(self) -> bool:
        """``isMaster()`` — host-process view (process 0 of the job)."""
        import jax

        return jax.process_index() == 0

    # -- lifecycle ----------------------------------------------------------
    def map_collective(self) -> Any:
        """The whole iterative program — override me (Harp's mapCollective)."""
        raise NotImplementedError

    def run(self) -> Any:
        """``CollectiveMapper.run()``: setup → mapCollective → cleanup."""
        t0 = time.perf_counter()
        log.info("harp-tpu app starting: %d workers, config=%s",
                 self.num_workers, self.config)
        try:
            # MetricsLogger is a context manager (close is idempotent):
            # the file closes on ANY exit path, including mid-iteration
            # exceptions inside map_collective
            with self.metrics, span("map_collective",
                                    app=type(self).__name__), \
                    flightrec.budget(**(self.budget or {}), action="warn",
                                     tag=type(self).__name__):
                result = self.map_collective()
        finally:
            self.metrics.close()
        if telemetry.enabled():
            import jax

            from harp_tpu.utils import skew

            # the multiprocess (Gloo/DCN) path's host-phase skew stamp:
            # each process records ITS wall-clock for the superstep, so a
            # merged report can attribute a straggling host (utils/skew.py)
            skew.record_host("map_collective", jax.process_index(),
                             time.perf_counter() - t0,
                             n_workers=jax.process_count())
            fs = flightrec.snapshot()
            log.info("flight record: %d compile(s) %.3fs, H2D %d B, "
                     "%d dispatch(es), %d readback(s)",
                     fs["compiles"], fs["compile_s"], fs["h2d_bytes"],
                     fs["dispatches"], fs["readbacks"])
        log.info("harp-tpu app finished in %.2fs", time.perf_counter() - t0)
        return result


def run_app(app_cls, config=None, **kw):
    """Launcher helper: ``hadoop jar harp-app.jar Launcher`` equivalent."""
    return app_cls(config, **kw).run()
