"""Run report — the telemetry spine's one merged view.

Reference parity (SURVEY.md §6): Harp observability ends at grepping YARN
container logs; harp-tpu's pieces each emit structured records — the
CommLedger (collective bytes per call site, :mod:`harp_tpu.utils.telemetry`),
the SpanTracer (nested host phases), the flight recorder
(:mod:`harp_tpu.utils.flightrec` — compiles/transfers), the SkewLedger
(:mod:`harp_tpu.utils.skew` — per-worker load), :class:`harp_tpu.utils.
metrics.MetricsLogger` (per-iteration JSONL), and :func:`harp_tpu.utils.
profiling.op_breakdown` (per-op device time from an XLA trace).  This
module merges
them into ONE human-readable run report plus ONE machine-readable JSON line
(printed through :func:`harp_tpu.utils.metrics.benchmark_json`, so the
backend/date/commit provenance stamp rides along like every bench row).

Two entry points:

- ``python -m harp_tpu report --telemetry run.jsonl [--metrics m.jsonl]
  [--trace-logdir DIR]`` — post-hoc, from files a run exported
  (``HARP_TELEMETRY_OUT=run.jsonl`` makes instrumented CLIs write one).
- :func:`maybe_emit` — called by instrumented app CLIs at exit; with
  ``HARP_TELEMETRY=1`` the human report lands on stderr and the JSON line
  on stdout (stderr for the table so a teed BENCH_local.jsonl still only
  collects parseable lines).
"""

from __future__ import annotations

import json
import sys
from typing import Any, IO

from harp_tpu.utils import telemetry


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.0f} {unit}" if unit == "B" else f"{n:.2f} {unit}")
        n /= 1024
    raise AssertionError


def comm_summary_from_rows(rows: list[dict]) -> dict:
    """Rebuild :meth:`CommLedger.summary`'s shape from exported comm rows."""
    out: dict[str, dict] = {}
    for r in rows:
        t = out.setdefault(r["tag"], {"executions": r.get("executions", 0),
                                      "bytes_per_execution": 0,
                                      "total_bytes": 0, "sites": []})
        site = {k: r.get(k) for k in ("site", "verb", "axis", "combiner",
                                      "wire_dtype", "payload_bytes",
                                      "calls_per_trace", "leaves")}
        t["sites"].append(site)
        t["bytes_per_execution"] += site["payload_bytes"] or 0
    for name, t in out.items():
        execs = t["executions"] if name != telemetry._UNTAGGED else max(
            1, t["executions"])
        t["total_bytes"] = t["bytes_per_execution"] * execs
        t["sites"].sort(key=lambda s: -(s["payload_bytes"] or 0))
    return out


def span_summary_from_rows(rows: list[dict]) -> dict:
    agg: dict[str, list[float]] = {}
    for r in rows:
        agg.setdefault(r["span"], []).append(float(r["dur"]))
    return {k: {"mean_s": sum(v) / len(v), "total_s": sum(v), "n": len(v)}
            for k, v in agg.items()}


def compile_summary_from_rows(rows: list[dict]) -> dict:
    """Rebuild :meth:`CompileWatch.summary`'s shape from exported compile
    rows (each row is one backend compile with ``dur`` + ``span``)."""
    if not rows:
        return {}
    by_span: dict[str, dict] = {}
    total = 0.0
    for r in rows:
        d = float(r.get("dur") or 0.0)
        total += d
        s = by_span.setdefault(r.get("span") or "(no span)",
                               {"count": 0, "total_s": 0.0})
        s["count"] += 1
        s["total_s"] = round(s["total_s"] + d, 6)
    return {"count": len(rows), "total_s": round(total, 6),
            "by_span": by_span}


def transfer_summary_from_rows(rows: list[dict]) -> dict:
    """Rebuild :meth:`TransferLedger.summary`'s shape from exported
    transfer rows (one row per (op, site, span))."""
    if not rows:
        return {}
    out: dict[str, Any] = {"h2d_bytes": 0, "h2d_calls": 0, "d2h_bytes": 0,
                           "readbacks": 0, "dispatches": 0,
                           "bucket_bytes": 0, "sites": []}
    for r in rows:
        op, b = r.get("op"), int(r.get("bytes") or 0)
        calls = int(r.get("calls") or 0)
        if op == "h2d":
            out["h2d_bytes"] += b
            out["h2d_calls"] += calls
        elif op == "readback":
            out["d2h_bytes"] += b
            out["readbacks"] += calls
        elif op == "dispatch":
            out["dispatches"] += calls
        elif op == "bucket":
            out["bucket_bytes"] += b
        out["sites"].append({k: r.get(k) for k in ("op", "site", "span",
                                                   "bytes", "calls")})
    out["sites"].sort(key=lambda s: (-(s["bytes"] or 0), s["op"] or "",
                                     s["site"] or ""))
    return out


def skew_summary_from_rows(rows: list[dict]) -> dict:
    """Rebuild :meth:`harp_tpu.utils.skew.SkewLedger.summary`'s shape
    from exported ``kind: "skew"`` rows (one row per phase)."""
    out: dict[str, dict] = {}
    for r in rows:
        phase = r.get("phase", "?")
        out[phase] = {k: r.get(k) for k in (
            "source", "unit", "work", "total", "n_workers",
            "max_mean_ratio", "wasted_frac", "wasted_chip_s",
            "padding_frac", "wall_s", "runs") if r.get(k) is not None}
    return dict(sorted(out.items(),
                       key=lambda kv: -(kv[1].get("max_mean_ratio") or 0)))


def build_row(comm: dict, spans: dict, span_records: list[dict] | None = None,
              metrics_rows: list[dict] | None = None,
              top_ops: list | None = None,
              compile_info: dict | None = None,
              transfer_info: dict | None = None,
              skew_info: dict | None = None,
              trace_info: dict | None = None,
              health_info: dict | None = None,
              elastic_rows: list[dict] | None = None,
              memory_info: dict | None = None) -> dict:
    """The machine-readable merge (the dict behind the JSON line)."""
    row: dict[str, Any] = {
        "comm_total_bytes": sum(t["total_bytes"] for t in comm.values()),
        "comm_verbs": {},
        "comm_tags": comm,
        "spans": spans,
    }
    # flight-recorder sections (PR 3) only when the run recorded any —
    # pre-flight-recorder exports keep their exact old report shape
    if compile_info and compile_info.get("count"):
        row["compile"] = compile_info
    if transfer_info and (transfer_info.get("sites")
                          or any(v for k, v in transfer_info.items()
                                 if k != "sites")):
        row["transfer"] = transfer_info
    # skew section (PR 4) only when the run recorded per-worker loads
    if skew_info:
        row["skew"] = skew_info
    # request-trace section (PR 12) only when the run served requests
    if trace_info and trace_info.get("requests"):
        row["requests"] = trace_info
    # health section (PR 14) only when the sentinel recorded findings
    if health_info and health_info.get("findings"):
        row["health"] = health_info
    # memory section (PR 19) only when the run recorded buffer events
    if memory_info and (memory_info.get("events")
                        or memory_info.get("rows")):
        row["memory"] = memory_info
    # elastic section (PR 15) only when the run rebalanced/shrank/resumed
    if elastic_rows:
        by_event: dict[str, int] = {}
        for r in elastic_rows:
            by_event[r.get("event", "?")] = \
                by_event.get(r.get("event", "?"), 0) + 1
        row["elastic"] = {"events": len(elastic_rows),
                          "by_event": by_event, "rows": elastic_rows}
    for t in comm.values():
        execs = max(1, t["executions"])
        for s in t["sites"]:
            v = s["verb"]
            row["comm_verbs"][v] = (row["comm_verbs"].get(v, 0)
                                    + (s["payload_bytes"] or 0) * execs)
    if span_records:
        row["n_spans"] = len(span_records)
    if metrics_rows is not None:
        row["metrics_rows"] = len(metrics_rows)
        if metrics_rows:
            row["metrics_last"] = metrics_rows[-1]
    if top_ops:
        row["top_ops"] = [{"op": n, "sec": round(s, 5)} for n, s in top_ops]
    return row


def render(row: dict, span_records: list[dict] | None = None) -> str:
    """The human-readable run report."""
    lines = ["== harp-tpu run report =="]
    comm = row.get("comm_tags", {})
    lines.append(f"comm volume (per-shard wire bytes): "
                 f"{_fmt_bytes(row.get('comm_total_bytes', 0))}")
    for verb, b in sorted(row.get("comm_verbs", {}).items(),
                          key=lambda kv: -kv[1]):
        lines.append(f"  by verb: {verb:<20s} {_fmt_bytes(b)}")
    for tag, t in sorted(comm.items()):
        lines.append(
            f"  tag {tag}: {t['executions']} execution(s) × "
            f"{_fmt_bytes(t['bytes_per_execution'])}/exec = "
            f"{_fmt_bytes(t['total_bytes'])}")
        for s in t["sites"]:
            wire = f" wire={s['wire_dtype']}" if s.get("wire_dtype") else ""
            comb = f" op={s['combiner']}" if s.get("combiner") else ""
            lines.append(
                f"    {s['verb']:<20s} {s['site']:<24s} "
                f"{_fmt_bytes(s['payload_bytes'] or 0)}/exec "
                f"× {s['calls_per_trace']} call(s)"
                f" axis={s['axis']}{comb}{wire}")
    spans = row.get("spans", {})
    if spans:
        lines.append("spans (host phases):")
        if span_records:
            for r in sorted(span_records, key=lambda r: r["t0"]):
                lines.append(f"  {'  ' * r['depth']}{r['span']:<24s} "
                             f"{r['dur']:.4f} s")
        else:
            for name, s in sorted(spans.items(),
                                  key=lambda kv: -kv[1]["total_s"]):
                lines.append(f"  {name:<26s} total {s['total_s']:.4f} s  "
                             f"n={s['n']}  mean {s['mean_s']:.4f} s")
    comp = row.get("compile")
    if comp:
        lines.append(f"compiles (XLA backend): {comp['count']} in "
                     f"{comp['total_s']:.3f} s")
        for name, s in sorted(comp.get("by_span", {}).items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"  {name:<26s} {s['count']} compile(s)  "
                         f"total {s['total_s']:.3f} s")
    tr = row.get("transfer")
    if tr:
        lines.append(
            f"transfers (host<->device): "
            f"H2D {_fmt_bytes(tr.get('h2d_bytes', 0))} in "
            f"{tr.get('h2d_calls', 0)} call(s); "
            f"D2H {_fmt_bytes(tr.get('d2h_bytes', 0))} over "
            f"{tr.get('readbacks', 0)} readback(s); "
            f"{tr.get('dispatches', 0)} dispatch(es)")
        if tr.get("bucket_bytes"):
            lines.append(f"  staged exchange buffers (capacity slots): "
                         f"{_fmt_bytes(tr['bucket_bytes'])}/trace")
        for s in tr.get("sites", []):
            span_note = f"  span={s['span']}" if s.get("span") else ""
            lines.append(
                f"  {s['op']:<9s} {s['site'] or '?':<24s} "
                f"{_fmt_bytes(s['bytes'] or 0)} × {s['calls']} call(s)"
                f"{span_note}")
    sk = row.get("skew")
    if sk:
        lines.append("skew (per-worker load; most imbalanced first):")
        for phase, s in sk.items():
            ratio = s.get("max_mean_ratio")
            head = (f"  {phase} [{s.get('unit', '?')}, "
                    f"{s.get('source', '?')}]: total {s.get('total', 0):g} "
                    f"over {s.get('n_workers', '?')} worker(s)")
            if ratio is not None:
                head += f", max/mean {ratio:.2f}x"
            if s.get("wasted_frac") is not None:
                head += f", est. waste {100.0 * s['wasted_frac']:.1f}%"
            if s.get("wasted_chip_s") is not None:
                head += f" (~{s['wasted_chip_s']:.4f} chip-s)"
            if s.get("padding_frac") is not None:
                head += f", padding {100.0 * s['padding_frac']:.1f}%"
            lines.append(head)
            work = s.get("work") or []
            if work and len(work) <= 16:
                mx = max(work) or 1.0
                for w, v in enumerate(work):
                    bar = "#" * max(1 if v > 0 else 0,
                                    round(24.0 * v / mx))
                    lines.append(f"    w{w:<3d} {bar:<24s} {v:g}")
            elif work:  # wide meshes: summarize instead of 100 bars
                arr = sorted(work)
                lines.append(
                    f"    min {arr[0]:g}  median {arr[len(arr) // 2]:g}  "
                    f"max {arr[-1]:g}")
    rq = row.get("requests")
    if rq:
        lines.append(
            f"requests (trace): {rq.get('requests', 0)} — "
            f"{rq.get('served', 0)} served / {rq.get('shed', 0)} shed / "
            f"{rq.get('failed', 0)} failed over "
            f"{rq.get('batches', 0)} batch(es)")
        if rq.get("served_p50_ms") is not None:
            lines.append(f"  served latency p50 {rq['served_p50_ms']} ms"
                         f"  p99 {rq['served_p99_ms']} ms")
        if rq.get("unterminated"):
            lines.append(f"  UNTERMINATED spans: {rq['unterminated']} "
                         "(every offered request must end served/shed/"
                         "failed — see python -m harp_tpu trace)")
    hl = row.get("health")
    if hl:
        lines.append(
            f"health (sentinel findings): {hl.get('findings', 0)} — "
            f"{hl.get('actionable', 0)} actionable, worst severity "
            f"{hl.get('worst_severity')}")
        for r in hl.get("rows", []):
            who = r.get("tag") or r.get("phase") or r.get("config") or "?"
            extra = ""
            if r.get("detector") == "slo_burn":
                extra = (f"  burn {r.get('fast_burn')}/"
                         f"{r.get('slow_burn')}, "
                         f"{r.get('shed', 0)} shed / "
                         f"{r.get('failed', 0)} failed")
            elif r.get("detector") == "skew_trigger":
                extra = (f"  wasted {r.get('wasted_frac')}, plan: "
                         f"{len((r.get('plan') or {}).get('moves') or [])}"
                         " move(s)")
            elif r.get("detector") == "budget_drift":
                extra = (f"  {r.get('violations')}x, worst "
                         f"{r.get('worst')}")
            elif r.get("detector") == "evidence_regression":
                extra = f"  verdict {r.get('verdict')}"
            lines.append(f"  [{r.get('severity')}] "
                         f"{r.get('detector')} {who}{extra}")
    mem = row.get("memory")
    if mem:
        head = (f"memory (device ledger): peak "
                f"{_fmt_bytes(mem.get('peak_hbm_bytes', 0))} HBM")
        if mem.get("headroom_frac") is not None and mem.get("hbm_bytes"):
            head += (f"  (headroom {100.0 * mem['headroom_frac']:.1f}% "
                     f"of {_fmt_bytes(mem['hbm_bytes'])})")
        lines.append(head)
        lines.append(
            f"  staged {_fmt_bytes(mem.get('staged_bytes', 0))} / "
            f"donated {_fmt_bytes(mem.get('donated_bytes', 0))} / "
            f"freed {_fmt_bytes(mem.get('freed_bytes', 0))} / "
            f"live {_fmt_bytes(mem.get('live_hbm_bytes', 0))}")
        if mem.get("executables"):
            lines.append(
                f"  {mem['executables']} executable footprint(s), "
                f"{_fmt_bytes(mem.get('exec_hbm_bytes', 0))} static HBM")
        if mem.get("vmem_checks"):
            lines.append(
                f"  VMEM gate: {mem['vmem_checks']} check(s), "
                f"{mem.get('vmem_refusals', 0)} refused before dispatch")
        for e in mem.get("errors", []):
            lines.append(f"  IRRECONCILED: {e}")
    el = row.get("elastic")
    if el:
        lines.append(f"elastic (actions): {el.get('events', 0)} — "
                     + ", ".join(f"{k}×{v}" for k, v in
                                 sorted(el.get("by_event", {}).items())))
        for r in el.get("rows", []):
            if r.get("event") == "rebalance":
                lines.append(
                    f"  [rebalance] {r.get('phase')}: wasted "
                    f"{r.get('wasted_frac_before')} -> "
                    f"{r.get('wasted_frac_after')} "
                    f"({r.get('moves')} move(s))")
            elif r.get("event") == "shrink":
                lines.append(
                    f"  [shrink] {r.get('phase')}: lost worker "
                    f"{r.get('lost_worker')} ({r.get('site')} #"
                    f"{r.get('ordinal')}), {r.get('n_workers_before')}"
                    f" -> {r.get('n_workers_after')} workers "
                    f"(capacity {r.get('capacity_frac')})")
            else:
                lines.append(
                    f"  [resume] {r.get('phase')}: {r.get('n_workers')}"
                    f" worker(s), wasted {r.get('wasted_frac')}"
                    + (", replayed repartition plan"
                       if r.get("replayed_plan") else ""))
    if "metrics_rows" in row:
        lines.append(f"metrics: {row['metrics_rows']} row(s)")
        if row.get("metrics_last"):
            lines.append(f"  last: {json.dumps(row['metrics_last'])}")
    if row.get("top_ops"):
        lines.append("top device ops (self time):")
        for o in row["top_ops"]:
            lines.append(f"  {o['op']:<40s} {o['sec']:.5f} s")
    return "\n".join(lines)


def live_report() -> tuple[dict, list[dict]]:
    """(machine row, span records) from the in-process collectors."""
    from harp_tpu import elastic, health
    from harp_tpu.utils import flightrec, memrec, reqtrace, skew

    comm = telemetry.ledger.summary()
    spans = telemetry.tracer.summary()
    return (build_row(comm, spans, telemetry.tracer.records,
                      compile_info=flightrec.compile_watch.summary(),
                      transfer_info=flightrec.transfers.summary(),
                      skew_info=skew.ledger.summary(),
                      trace_info=reqtrace.summarize_rows(
                          reqtrace.tracer.rows()),
                      health_info=health.monitor.summary(),
                      elastic_rows=list(elastic.ledger.ledger.rows),
                      memory_info=memrec.live_summary()),
            telemetry.tracer.records)


def maybe_emit(config: str, *, out: IO | None = None,
               err: IO | None = None) -> None:
    """App-CLI exit hook: no-op unless telemetry is enabled.

    Prints the human report to ``err`` (stderr) and the provenance-stamped
    JSON line to ``out`` (stdout), and honors ``HARP_TELEMETRY_OUT`` by
    exporting the raw span+ledger JSONL for later ``report`` runs.
    """
    if not telemetry.enabled():
        return
    from harp_tpu.utils.metrics import benchmark_json

    out = sys.stdout if out is None else out
    err = sys.stderr if err is None else err
    path = telemetry.out_path()
    if path:
        telemetry.export(path)
    row, span_records = live_report()
    print(render(row, span_records), file=err, flush=True)
    print(benchmark_json(f"{config}_telemetry", row), file=out, flush=True)


def main(argv=None) -> int:
    import argparse

    from harp_tpu.utils.metrics import benchmark_json

    p = argparse.ArgumentParser(
        description="merge telemetry (comm ledger + spans) with metrics "
                    "JSONL and an optional XLA trace into one run report")
    p.add_argument("--telemetry", metavar="FILE",
                   help="JSONL written by telemetry.export / "
                        "HARP_TELEMETRY_OUT")
    p.add_argument("--metrics", metavar="FILE",
                   help="MetricsLogger JSONL to merge")
    p.add_argument("--trace-logdir", metavar="DIR",
                   help="profiling.trace() logdir: adds the op_breakdown "
                        "top-ops table")
    p.add_argument("--top", type=int, default=10,
                   help="rows of the top-ops table (default 10)")
    p.add_argument("--json-only", action="store_true",
                   help="print only the machine-readable line")
    args = p.parse_args(argv)

    span_rows: list[dict] = []
    comm_rows: list[dict] = []
    compile_rows: list[dict] = []
    transfer_rows: list[dict] = []
    skew_rows: list[dict] = []
    trace_rows: list[dict] = []
    health_rows: list[dict] = []
    elastic_rows: list[dict] = []
    memory_rows: list[dict] = []
    if args.telemetry:
        kinds = telemetry.load_rows(args.telemetry)
        span_rows, comm_rows = kinds["span"], kinds["comm"]
        compile_rows, transfer_rows = kinds["compile"], kinds["transfer"]
        skew_rows = kinds["skew"]
        trace_rows = kinds["trace"]
        health_rows = kinds["health"]
        elastic_rows = kinds["elastic"]
        memory_rows = kinds["memory"]
    metrics_rows = None
    if args.metrics:
        metrics_rows = []
        with open(args.metrics) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    metrics_rows.append(json.loads(line))
    top_ops = None
    if args.trace_logdir:
        from harp_tpu.utils.profiling import op_breakdown

        top_ops = op_breakdown(args.trace_logdir, top=args.top)

    from harp_tpu import health as health_mod
    from harp_tpu.utils import memrec
    from harp_tpu.utils.reqtrace import summarize_rows as trace_summary

    row = build_row(comm_summary_from_rows(comm_rows),
                    span_summary_from_rows(span_rows),
                    span_rows, metrics_rows, top_ops,
                    compile_info=compile_summary_from_rows(compile_rows),
                    transfer_info=transfer_summary_from_rows(transfer_rows),
                    skew_info=skew_summary_from_rows(skew_rows),
                    trace_info=(trace_summary(trace_rows)
                                if trace_rows else None),
                    health_info=(health_mod.summarize_rows(health_rows)
                                 | {"rows": health_rows}
                                 if health_rows else None),
                    elastic_rows=elastic_rows,
                    memory_info=(memrec.summarize_rows(memory_rows)
                                 if memory_rows else None))
    if not args.json_only:
        print(render(row, span_rows))
    print(benchmark_json("report", row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
