"""Unified launcher — Harp L8 (``hadoop jar harp-<app>.jar Launcher``) parity.

Harp apps each ship a ``main`` Launcher class invoked through ``hadoop
jar`` with positional args, wrapped by per-app shell scripts (SURVEY.md
§2 L8).  Here every app already has a module-level ``main(argv)``
(``python -m harp_tpu.models.kmeans …``); this dispatcher is the single
front door:

    python -m harp_tpu <app> [app args...]
    python -m harp_tpu bench [--size-mb N]       # collective micro-bench
    python -m harp_tpu --list
"""

from __future__ import annotations

import sys
from importlib import import_module

APPS = {
    "kmeans": ("harp_tpu.models.kmeans", "KMeans Lloyd iterations (allreduce)"),
    "kmeans-stream": ("harp_tpu.models.kmeans_stream",
                      "streaming KMeans for beyond-HBM datasets (1B-point path)"),
    "mfsgd": ("harp_tpu.models.mfsgd", "MF-SGD matrix factorization (rotate)"),
    "ccd": ("harp_tpu.models.ccd", "CCD++ matrix factorization (rotate)"),
    "lda": ("harp_tpu.models.lda", "LDA-CGS topic model (rotate + push/pull)"),
    "mlp": ("harp_tpu.models.mlp", "MLP neural net (gradient allreduce)"),
    "subgraph": ("harp_tpu.models.subgraph", "color-coding subgraph counting"),
    "rf": ("harp_tpu.models.rf", "random forest (allgather of trees)"),
    "svm": ("harp_tpu.models.svm", "distributed linear SVM (allreduce)"),
    "wdamds": ("harp_tpu.models.wdamds", "WDA-MDS / SMACOF embedding"),
    "stats": ("harp_tpu.models.stats",
              "classic analytics: pca/cov/moments/naive/linreg/ridge/qr/svd/als"),
    "serve": ("harp_tpu.serve.server",
              "persistent-mesh inference server (JSONL over stdio)"),
    "bench": ("harp_tpu.benchmark", "collective micro-benchmarks (edu.iu.benchmark)"),
    "report": ("harp_tpu.report",
               "merged run report: comm ledger + spans + metrics + top ops"),
    "trace": ("harp_tpu.utils.reqtrace",
              "request-level timeline: validate/summarize a trace JSONL, "
              "export Chrome/Perfetto trace.json"),
    "timeline": ("harp_tpu.utils.steptrace",
                 "training-plane timeline: validate/summarize kind:'steptrace' "
                 "superstep rows, export Chrome/Perfetto trace.json"),
    "memory": ("harp_tpu.utils.memrec",
               "device-memory ledger: validate/summarize kind:'memory' "
               "buffer-lifecycle rows, re-derive the HBM watermark"),
    "health": ("harp_tpu.health.cli",
               "health sentinel: summarize kind:'health' findings, grade "
               "fresh bench rows, run the fail-closed model gate"),
    "lint": ("harp_tpu.analysis.cli",
             "harplint: static relay-burner analysis (AST + jaxpr + Mosaic)"),
    "plan": ("harp_tpu.plan.cli",
             "topology-aware collective planner over the lint byte sheets"),
    "predict": ("harp_tpu.perfmodel.cli",
                "offline predictive cost model: price configs/programs, "
                "rank flip candidates, self-grade vs committed evidence"),
    "profile": ("harp_tpu.profile.cli",
                "wall-attribution observatory: capture a driver run, "
                "bucket every op into the mechanism vocabulary, "
                "reconcile against the flightrec/CommLedger spines"),
}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help", "--list"):
        print("usage: python -m harp_tpu <app> [args...]\n\napps:")
        for name, (_, desc) in APPS.items():
            print(f"  {name:10s} {desc}")
        return 0 if argv else 2
    app, rest = argv[0], argv[1:]
    if app not in APPS:
        print(f"unknown app {app!r}; run with --list", file=sys.stderr)
        return 2
    mod = import_module(APPS[app][0])
    return mod.main(rest) or 0


if __name__ == "__main__":
    sys.exit(main())
