"""Data source API — HarpDAALDataSource parity, native fast path.

``load_csv`` / ``load_triples`` parse with the multi-threaded C++ loader
when available (≈num_cores× a Python parse), else fall back to numpy.
Both return host arrays ready for ``WorkerMesh.shard_array``.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from harp_tpu.native.build import load_native


def _is_gz(path: str) -> bool:
    return path.endswith(".gz")


def _open_text(path: str):
    """Text handle for plain or gzip-compressed files — HDFS-style text
    splits are routinely .gz; the native C++ parser reads plain bytes
    only, so gz inputs take the Python parse path (same semantics)."""
    if _is_gz(path):
        import gzip

        return gzip.open(path, "rt")
    return open(path)


def _loadtxt_any_sep(path: str) -> np.ndarray:
    """numpy fallback accepting comma OR whitespace separators, matching the
    native parser's behavior so results don't depend on g++ availability."""
    with _open_text(path) as f:
        text = f.read().replace(",", " ")
    import io
    import warnings

    with warnings.catch_warnings():
        # empty shards are legitimate input (skipped by the glob loaders)
        warnings.filterwarnings("ignore", message=".*input contained no data.*")
        return np.loadtxt(io.StringIO(text), dtype=np.float64, ndmin=2)


def load_csv(path: str, n_threads: int = 0) -> np.ndarray:
    """Dense CSV/whitespace numeric file → float32 [rows, cols].

    ``.parquet``/``.pq`` files load columnarly through pyarrow (all
    columns must be numeric) — one front door for dense matrices
    whatever the split encoding."""
    if path.endswith((".parquet", ".pq")):
        pq = _require_pyarrow()
        t = pq.read_table(path)
        return np.stack(
            [t.column(i).to_numpy(zero_copy_only=False)
             for i in range(t.num_columns)], axis=1).astype(np.float32)
    n_threads = n_threads or (os.cpu_count() or 1)
    lib = None if _is_gz(path) else load_native()
    if lib is None:
        return _loadtxt_any_sep(path).astype(np.float32)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.harp_count_rows(path.encode(), n_threads,
                             ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise OSError(f"native loader failed to read {path!r} (rc={rc})")
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.harp_load_csv_f32(
        path.encode(), n_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value, cols.value)
    if rc != 0:
        raise OSError(f"native loader failed to parse {path!r} (rc={rc})")
    return out


def load_libsvm(path: str, n_threads: int = 0, zero_based: bool = False):
    """libsvm/CSR sparse file → (labels, indptr, indices, values, n_features).

    The HarpDAALDataSource CSR input path.  Lines are
    ``label idx:val idx:val ... [# comment]``; indices are 1-based in the
    wild (``zero_based=False`` subtracts 1, matching sklearn's default).
    Returns ``labels f32 [n]``, CSR ``indptr i64 [n+1]``,
    ``indices i32 [nnz]``, ``values f32 [nnz]``, and ``n_features``.
    """
    n_threads = n_threads or (os.cpu_count() or 1)
    lib = None if _is_gz(path) else load_native()
    n_features_native = None
    if lib is None:
        # tolerance mirrors the native parser: the label is the numeric
        # prefix of the first token (its trailing garbage is dropped, so
        # '3:1.5' is a label-only line), an unparseable label reads as 0.0
        # (header lines become zero-label rows), and stray tokens that
        # aren't idx:val pairs are skipped
        import re

        _num_prefix = re.compile(
            r"[-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?")

        def _tofloat(s):
            try:
                return float(s)  # also accepts inf/nan, like strtof
            except ValueError:
                m = _num_prefix.match(s)
                return float(m.group()) if m else 0.0

        labels, indptr, indices, values = [], [0], [], []
        with _open_text(path) as f:
            for line in f:
                toks = line.split("#", 1)[0].split()
                if not toks:
                    continue
                labels.append(_tofloat(toks[0]))
                for pair in toks[1:]:
                    idx, colon, val = pair.partition(":")
                    if not colon or not val:
                        continue
                    try:
                        i = int(idx) if idx else 0
                    except ValueError:
                        continue
                    indices.append(i)
                    values.append(_tofloat(val))
                indptr.append(len(indices))
        labels = np.asarray(labels, np.float32)
        indptr = np.asarray(indptr, np.int64)
        indices = np.asarray(indices, np.int32)
        values = np.asarray(values, np.float32)
    else:
        rows = ctypes.c_int64()
        nnz = ctypes.c_int64()
        max_idx = ctypes.c_int64()
        rc = lib.harp_count_libsvm(path.encode(), n_threads,
                                   ctypes.byref(rows), ctypes.byref(nnz),
                                   ctypes.byref(max_idx))
        if rc != 0:
            raise OSError(f"native loader failed to read {path!r} (rc={rc})")
        labels = np.empty(rows.value, np.float32)
        indptr = np.empty(rows.value + 1, np.int64)
        indices = np.empty(nnz.value, np.int32)
        values = np.empty(nnz.value, np.float32)
        rc = lib.harp_load_libsvm(
            path.encode(), n_threads,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.value, nnz.value)
        if rc != 0:
            raise OSError(f"native loader failed to parse {path!r} (rc={rc})")
        n_features_native = max_idx.value  # max 1-based index == n_features
    if not zero_based:
        indices -= 1  # freshly allocated on both paths: in-place is safe
    if len(indices) and indices.min() < 0:
        raise ValueError(
            f"{path!r}: negative feature index after 1-based correction — "
            "the file is 0-based; pass zero_based=True (CLI: --zero-based)")
    if n_features_native is not None:
        n_features = n_features_native + (1 if zero_based else 0)
        n_features = max(n_features, 0)
    else:
        n_features = int(indices.max()) + 1 if len(indices) else 0
    return labels, indptr, indices, values, n_features


def load_csv_glob(pattern_or_dir: str, n_threads: int = 0) -> np.ndarray:
    """Concatenate every file matching a glob/dir through :func:`load_csv`
    (the Harp app's multi-file HDFS input shape).  Empty shards are
    skipped (routine in HDFS-style directories); raises ``ValueError`` on
    zero matches or zero total rows — callers get a clear error, not a
    concatenate traceback."""
    from harp_tpu.fileformat import list_files

    paths = list_files(pattern_or_dir)
    if not paths:
        raise ValueError(f"{pattern_or_dir}: no input files matched")
    arrays = [a for a in (load_csv(f, n_threads) for f in paths)
              if a.shape[0] > 0]
    if not arrays:
        raise ValueError(f"{pattern_or_dir}: input files contain no rows")
    return np.concatenate(arrays)


_COLUMN_SCAN_ROWS = 10_000


def _scan_columns(path: str) -> set[int]:
    """Distinct column counts over the file's first data rows.

    Scans up to ``_COLUMN_SCAN_ROWS`` non-comment rows (ragged files are
    overwhelmingly ragged early — headers, truncated exports); rows beyond
    the scan window are not validated, which keeps huge files on the fast
    native parser.  Returns an empty set for an empty file.
    """
    seen: set[int] = set()
    with _open_text(path) as f:
        rows = 0
        for line in f:
            toks = line.split("#", 1)[0].replace(",", " ").split()
            if toks:
                seen.add(len(toks))
                rows += 1
                if rows >= _COLUMN_SCAN_ROWS:
                    break
    return seen


def load_triples_glob(pattern_or_dir: str, n_threads: int = 0):
    """Concatenate 'u i [v]' triple files matching a glob/dir — shared by
    the MF-SGD and LDA CLIs.

    Returns ``(u, i, v, has_value_column)``: v reads as 0.0 for two-column
    files, and ``has_value_column`` tells the caller whether a third
    column actually existed (an explicit 0 and a missing column are
    different facts — LDA drops explicit zero counts but treats bare
    pairs as single tokens).  All rows (within the first
    ``_COLUMN_SCAN_ROWS`` of each file, and across files) must agree on
    the column count — a ragged row would otherwise read as a fabricated
    0.0 value.  Raises ``ValueError`` on zero matches, zero total rows,
    or disagreeing column counts.
    """
    from harp_tpu.fileformat import list_files

    paths = list_files(pattern_or_dir)
    if not paths:
        raise ValueError(f"{pattern_or_dir}: no input files matched")
    ncols: set[int] = set()
    for f in paths:
        if f.endswith((".parquet", ".pq")):
            # column count from metadata — the text scanner would read
            # binary bytes as garbage tokens
            pq = _require_pyarrow()
            ncols.add(int(pq.ParquetFile(f).metadata.num_columns))
        else:
            ncols |= _scan_columns(f)
    if len(ncols) > 1:
        raise ValueError(
            f"{pattern_or_dir}: rows disagree on column count "
            f"({sorted(ncols)}) — a short row would read as a fabricated "
            "0.0 value; fix the input")
    parts = [load_triples(f, n_threads) for f in paths]
    u = np.concatenate([p[0] for p in parts])
    i = np.concatenate([p[1] for p in parts])
    v = np.concatenate([p[2] for p in parts])
    if len(u) == 0:
        raise ValueError(f"{pattern_or_dir}: input files contain no rows")
    return u, i, v, bool(ncols) and max(ncols) >= 3


def csr_to_ell(indptr, indices, values, width: int | None = None):
    """CSR → padded ELL blocks ``(ids [n, w] i32, vals [n, w] f32,
    mask [n, w] f32)`` — the static-shape layout TPU kernels consume
    (SURVEY.md §8: CSR→ELL-style padding for sparse workloads).

    ``width`` defaults to the max row length; longer rows are truncated
    (count returned by the caller comparing ``indptr`` diffs to ``width``).
    """
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices, np.int32)
    values = np.asarray(values, np.float32)
    n = len(indptr) - 1
    lens = np.diff(indptr)
    w = int(lens.max()) if width is None and n else (width or 0)
    ids = np.zeros((n, w), np.int32)
    vals = np.zeros((n, w), np.float32)
    mask = np.zeros((n, w), np.float32)
    # position of each nnz within its row, vectorized
    pos = np.arange(len(indices)) - np.repeat(indptr[:-1], lens)
    row = np.repeat(np.arange(n), lens)
    keep = pos < w
    ids[row[keep], pos[keep]] = indices[keep]
    vals[row[keep], pos[keep]] = values[keep]
    mask[row[keep], pos[keep]] = 1.0
    return ids, vals, mask


def load_triples(path: str, n_threads: int = 0):
    """'u i [v]' rating/token lines → (int32 [n], int32 [n], float32 [n]).

    A missing third column reads as v=0.0 (both paths — the native parser
    already tolerates it).  ``.parquet``/``.pq`` files load columnarly:
    first two numeric columns are the ids, an optional third is the
    value (rating tables in the wild are overwhelmingly parquet).
    """
    if path.endswith((".parquet", ".pq")):
        pq = _require_pyarrow()
        t = pq.read_table(path)
        if t.num_columns not in (2, 3):
            raise ValueError(f"{path}: triples need 2 or 3 columns, "
                             f"got {t.num_columns}")
        cols = [t.column(i).to_numpy(zero_copy_only=False)
                for i in range(t.num_columns)]
        v = (cols[2] if len(cols) == 3
             else np.zeros(len(cols[0])))
        return (cols[0].astype(np.int32), cols[1].astype(np.int32),
                v.astype(np.float32))
    n_threads = n_threads or (os.cpu_count() or 1)
    lib = None if _is_gz(path) else load_native()
    if lib is None:
        a = _loadtxt_any_sep(path)
        if a.shape[0] == 0:  # empty shard: loadtxt yields (0, 1)
            return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                    np.zeros(0, np.float32))
        v = a[:, 2] if a.shape[1] >= 3 else np.zeros(len(a))
        return (a[:, 0].astype(np.int32), a[:, 1].astype(np.int32),
                v.astype(np.float32))
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.harp_count_rows(path.encode(), n_threads,
                             ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise OSError(f"native loader failed to read {path!r} (rc={rc})")
    u = np.empty(rows.value, np.int32)
    i = np.empty(rows.value, np.int32)
    v = np.empty(rows.value, np.float32)
    rc = lib.harp_load_triples(
        path.encode(), n_threads,
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        i.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value)
    if rc != 0:
        raise OSError(f"native loader failed to parse {path!r} (rc={rc})")
    return u, i, v


# ---------------------------------------------------------------------------
# Streaming CSV — beyond-RAM text corpora for the blocked-epoch apps.
# ---------------------------------------------------------------------------


class CSVStream:
    """Iterate [≤chunk_rows, cols] float32 blocks of a CSV/whitespace file.

    Native path: the C++ reader parses the NEXT chunk on a background
    thread while the caller consumes the current one (double-buffered —
    disk+parse overlaps device compute); memory is bounded by two parsed
    slots regardless of file size.  Python fallback parses line blocks
    with the same separator/comment semantics.  Use as an iterator or a
    context manager; ``cols`` blocks until the first block is parsed.
    """

    def __init__(self, path: str, chunk_rows: int = 65_536):
        if chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
        self.path, self.chunk_rows = path, chunk_rows
        # .gz takes the Python parse path: the native reader consumes
        # plain bytes (see _open_text)
        self._lib = None if _is_gz(path) else load_native()
        self._h = None
        self._f = None
        if self._lib is not None:
            h = self._lib.harp_csv_stream_open(path.encode(), chunk_rows)
            if not h:
                raise OSError(f"native stream failed to open {path!r}")
            self._h = h
            self._cols = int(self._lib.harp_csv_stream_cols(h))
            if self._cols < 0:
                raise OSError(f"native stream failed to read {path!r}")
        else:
            self._f = _open_text(path)
            self._cols = None  # discovered on first block
            self._py_buf: list = []

    @property
    def cols(self) -> int:
        # loop: the first chunk_rows lines can be all comments/blanks —
        # matching the native reader, which scans until a data line or EOF
        while self._cols is None:
            if not self._py_fill():
                return 0
        return self._cols

    def _py_fill(self):
        """Fallback: read chunk_rows raw lines, parse non-blank ones.

        Matches the NATIVE parser's semantics, not np.loadtxt's: comments
        stripped at '#', cols fixed by the first data line, short rows
        zero-padded, extra trailing columns ignored, unparseable tokens
        read as 0.0 — so behavior never depends on g++ availability.
        """
        lines = []
        for line in self._f:
            lines.append(line)
            if len(lines) >= self.chunk_rows:
                break
        rows = []
        for line in lines:
            body = line.split("#", 1)[0].replace(",", " ").split()
            if not body:
                continue
            if self._cols is None:
                self._cols = len(body)
            vals = []
            for tok in body[: self._cols]:
                try:
                    vals.append(float(tok))
                except ValueError:
                    vals.append(0.0)
            vals += [0.0] * (self._cols - len(vals))
            rows.append(vals)
        arr = (np.asarray(rows, np.float32) if rows
               else np.zeros((0, self._cols or 0), np.float32))
        self._py_buf = [arr] if arr.size else []
        return bool(lines)

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        if self._h is not None:
            buf = np.empty((self.chunk_rows, self._cols), np.float32)
            rows = int(self._lib.harp_csv_stream_next(
                self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self.chunk_rows))
            if rows < 0:
                raise OSError(f"native stream error reading {self.path!r}")
            if rows == 0:
                raise StopIteration
            return buf[:rows]
        while True:
            if self._py_buf:
                return self._py_buf.pop()
            if not self._py_fill():
                raise StopIteration
            if not self._py_buf:   # block of blanks/comments: keep reading
                continue

    def close(self):
        if self._h is not None:
            self._lib.harp_csv_stream_close(self._h)
            self._h = None
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # belt-and-braces; close() is the real API
        try:
            self.close()
        except Exception:
            pass


class FileSplits:
    """Size-balanced file→worker assignment with per-worker sequential
    block reads — Harp's input shape (SURVEY.md §3.1 L4 input formats /
    §4.2 "load points shard"): the dataset is a DIRECTORY of splits and
    each worker streams only its own files, never the whole set.

    ``paths`` (already-resolved list; sort for a deterministic
    assignment) are dealt to workers by
    :func:`harp_tpu.fileformat.multi_file_splits` — greedy size-balanced
    by default (``by_size``), Harp's ``MultiFileInputFormat`` rule — and
    only ``local_workers`` — the workers this process serves — are
    opened, so a multi-host job touches each file exactly once across
    the fleet.  ``.npy`` files open as memmaps; ``.parquet``/``.pq``
    through :class:`ParquetPoints` (pyarrow row-group streaming);
    anything else through :class:`CSVPoints` (native streaming parser,
    bounded memory).  All files must agree on the column count.

    Per worker: ``rows(w)`` (total), ``next_block(w, count)`` (the next
    ≤count rows, crossing file boundaries), and :meth:`reset` rewinds
    every stream for the next epoch.  ``head(count)`` serves seeding
    (rows from this process's files in worker order) and resets after.
    """

    def __init__(self, paths, n_workers: int, local_workers,
                 chunk_rows: int = 65_536, by_size: bool = True):
        from harp_tpu.fileformat import multi_file_splits

        if not paths:
            raise ValueError("FileSplits needs at least one input file")
        self.paths = list(paths)
        self.n_workers = n_workers
        self.local_workers = list(local_workers)
        self._chunk_rows = chunk_rows
        assign = multi_file_splits(self.paths, n_workers, by_size=by_size)
        self._srcs: dict[int, list] = {}
        cols = {}
        for w in self.local_workers:
            srcs = []
            for p in assign[w]:
                if p.endswith(".npy"):
                    s = np.load(p, mmap_mode="r")
                elif p.endswith((".parquet", ".pq")):
                    s = ParquetPoints(p, chunk_rows)
                else:
                    s = CSVPoints(p, chunk_rows)
                if len(s.shape) != 2:
                    raise ValueError(f"{p}: expected 2-D rows, got shape "
                                     f"{s.shape}")
                srcs.append(s)
                cols[int(s.shape[1])] = p
            self._srcs[w] = srcs
        if len(cols) > 1:
            raise ValueError(
                f"input files disagree on column count {sorted(cols)} "
                f"(e.g. {list(cols.values())[:2]}) — a ragged mix would "
                "silently misalign features")
        self.cols = next(iter(cols)) if cols else 0
        self._pos = {w: [0, 0] for w in self.local_workers}  # [src, row]

    @property
    def dtype(self):
        """Common source dtype of this process's files, or None when they
        mix (or it owns none) — feeds the streaming wire-dtype choice
        (kmeans_stream._resolve_wire_dtype): a uniform f16 file set may
        ship f16 over H2D; a mixed set must not.  CSV sources parse to
        float32 and count as such."""
        names = {np.dtype(getattr(s, "dtype", np.float32)).name
                 for srcs in self._srcs.values() for s in srcs}
        return np.dtype(next(iter(names))) if len(names) == 1 else None

    def rows(self, w: int) -> int:
        return int(sum(s.shape[0] for s in self._srcs[w]))

    def reset(self) -> None:
        self._pos = {w: [0, 0] for w in self.local_workers}

    def next_block(self, w: int, count: int) -> np.ndarray:
        out = []
        si, off = self._pos[w]
        srcs = self._srcs[w]
        need = count
        while need > 0 and si < len(srcs):
            s = srcs[si]
            take = min(need, int(s.shape[0]) - off)
            if take > 0:
                out.append(np.asarray(s[off:off + take], np.float32))
                off += take
                need -= take
            if off >= s.shape[0]:
                si += 1
                off = 0
        self._pos[w] = [si, off]
        return (np.concatenate(out, 0) if out
                else np.zeros((0, self.cols), np.float32))

    def head(self, count: int) -> np.ndarray:
        """First ``count`` rows across this process's workers (worker
        order) — for shape probing; rewinds all streams afterwards."""
        self.reset()
        out = []
        need = count
        for w in self.local_workers:
            if need <= 0:
                break
            blk = self.next_block(w, need)
            out.append(blk)
            need -= blk.shape[0]
        self.reset()
        return (np.concatenate(out, 0) if out
                else np.zeros((0, self.cols), np.float32))

    def sample(self, count: int, rng=0) -> np.ndarray:
        """Up to ``count`` rows drawn RANDOMLY (without replacement per
        file) across this process's files — centroid seeding that does
        not collapse on sorted/cluster-grouped inputs the way a
        first-rows head() would.  The draw spreads an even quota over
        files (capped by file size; approximately, not exactly,
        row-uniform), via sorted index gathers (memmap fancy-index; text
        sources run one dedicated streaming pass).  Stream cursors are
        untouched.  ``rng``: seed or ``np.random.Generator``."""
        rng = (rng if isinstance(rng, np.random.Generator)
               else np.random.default_rng(rng))
        flat = [(w, i, int(s.shape[0]))
                for w in self.local_workers
                for i, s in enumerate(self._srcs[w])]
        total = sum(z for _, _, z in flat)
        remaining = min(count, total)
        out = []
        for j, (w, i, z) in enumerate(flat):
            if remaining <= 0:
                break
            quota = min(z, -(-remaining // (len(flat) - j)))
            idx = np.sort(rng.choice(z, size=quota, replace=False))
            out.append(np.asarray(self._srcs[w][i][idx], np.float32))
            remaining -= quota
        return (np.concatenate(out, 0) if out
                else np.zeros((0, self.cols), np.float32))

    def amax(self) -> np.ndarray:
        """Per-feature |max| over ALL of this process's files (one
        streaming pass in ``chunk_rows`` blocks; rewinds afterwards) —
        the local half of the int8 scale reduction."""
        out = np.zeros(self.cols, np.float32)
        self.reset()
        for w in self.local_workers:
            while True:
                blk = self.next_block(w, self._chunk_rows)
                if blk.shape[0] == 0:
                    break
                np.maximum(out, np.abs(blk).max(0), out=out)
        self.reset()
        return out

    def close(self) -> None:
        for srcs in self._srcs.values():
            for s in srcs:
                if hasattr(s, "close"):
                    s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SequentialPoints:
    """Shared engine of the ``points`` source contract of
    :func:`harp_tpu.models.kmeans_stream.fit_streaming` — a file viewed
    as a 2-D array that only supports the access pattern the streaming
    apps use:

    ``points[lo:hi]`` with ascending, contiguous ``lo`` that restarts at
    0 each epoch (each restart reopens the underlying stream), plus
    ``points[sorted_index_array]`` row gathers (one dedicated streaming
    pass — used by centroid init).  Anything else raises, loudly.

    Subclasses set ``self.shape`` in ``__init__`` and implement
    ``_open_stream() -> iterator of [n, cols] float32 blocks`` (with an
    optional ``close()``); everything else — position bookkeeping,
    skip-forward, the gather pass — lives here once
    (:class:`CSVPoints`, :class:`ParquetPoints`).
    """

    shape: tuple
    chunk_rows: int

    def _open_stream(self):
        raise NotImplementedError

    def _init_cursor(self):
        self._stream = None
        self._pos = 0
        self._pending: np.ndarray | None = None  # rows read but not consumed

    def __len__(self):
        return self.shape[0]

    def _restart(self):
        if self._stream is not None and hasattr(self._stream, "close"):
            self._stream.close()
        self._stream = self._open_stream()
        self._pos = 0
        self._pending = None

    def _read(self, count: int, keep: bool = True) -> np.ndarray:
        """Consume ``count`` rows; ``keep=False`` drains them in O(chunk)
        memory (the skip-forward path must not materialize the prefix)."""
        parts: list = []
        need = count
        while need > 0:
            if self._pending is not None and len(self._pending):
                take = self._pending[:need]
                self._pending = self._pending[need:]
                if keep:
                    parts.append(take)
                need -= len(take)
                continue
            try:
                self._pending = next(self._stream)
            except StopIteration:
                break
        self._pos += count - need
        return np.concatenate(parts, 0) if parts else \
            np.zeros((0, self.shape[1]), np.float32)

    def __getitem__(self, key):
        name = type(self).__name__
        if isinstance(key, slice):
            lo = key.start or 0
            hi = self.shape[0] if key.stop is None else key.stop
            if key.step not in (None, 1):
                raise ValueError(f"{name} slices must be contiguous")
            if lo < 0 or hi < 0:
                raise IndexError(
                    f"{name} does not support negative slice bounds "
                    f"(got {lo}:{hi})")
            hi = min(hi, self.shape[0])
            if lo == 0 or self._stream is None:
                self._restart()
                if lo:
                    self._read(lo, keep=False)  # skip forward (init paths)
            elif lo != self._pos:
                raise ValueError(
                    f"{name} is sequential: asked for rows {lo}:{hi} at "
                    f"position {self._pos} (slices must ascend contiguously "
                    "and restart at 0)")
            return self._read(hi - lo)
        idx = np.asarray(key)
        if idx.ndim != 1 or not np.issubdtype(idx.dtype, np.integer):
            raise TypeError(f"{name} supports slices or 1-D integer "
                            "index arrays")
        if len(idx) and (np.diff(idx) < 0).any():
            raise ValueError(f"{name} index arrays must be sorted")
        if len(idx) and int(idx[0]) < 0:
            raise IndexError(f"{name} does not support negative indices "
                             f"(got {int(idx[0])})")
        out = np.empty((len(idx), self.shape[1]), np.float32)
        st = self._open_stream()
        try:
            base, j = 0, 0
            for blk in st:
                hi = base + blk.shape[0]
                while j < len(idx) and idx[j] < hi:
                    out[j] = blk[idx[j] - base]
                    j += 1
                base = hi
                if j >= len(idx):
                    break
        finally:
            if hasattr(st, "close"):
                st.close()
        if j < len(idx):
            raise IndexError(f"index {int(idx[j])} out of range "
                             f"({self.shape[0]} rows)")
        return out

    def close(self):
        if self._stream is not None:
            if hasattr(self._stream, "close"):
                self._stream.close()
            self._stream = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class CSVPoints(SequentialPoints):
    """:class:`SequentialPoints` over a CSV/whitespace text file — text
    corpora too large for RAM stream through the native parser
    (:class:`CSVStream`); ``shape`` comes from the native bounded-memory
    row-count pass."""

    def __init__(self, path: str, chunk_rows: int = 65_536):
        self.path, self.chunk_rows = path, chunk_rows
        lib = None if _is_gz(path) else load_native()
        if lib is not None:
            # streaming count (bounded memory) — harp_count_rows reads the
            # whole file into RAM, which this class exists to avoid
            rows = ctypes.c_int64()
            cols = ctypes.c_int64()
            rc = lib.harp_csv_count_stream(path.encode(),
                                           ctypes.byref(rows),
                                           ctypes.byref(cols))
            if rc != 0:
                raise OSError(f"native loader failed to read {path!r}")
            self.shape = (int(rows.value), int(cols.value))
        else:
            n, c = 0, 0
            with CSVStream(path, chunk_rows) as st:
                for blk in st:
                    n += blk.shape[0]
                    c = blk.shape[1]
            self.shape = (n, c)
        self._init_cursor()

    def _open_stream(self):
        return CSVStream(self.path, self.chunk_rows)


class ParquetPoints(SequentialPoints):
    """:class:`SequentialPoints` over a Parquet file (columnar splits —
    the common modern shape of the HDFS-style datasets Harp's input
    formats consumed).  ``shape`` comes from the file METADATA (no data
    read); blocks stream via ``pyarrow.parquet.iter_batches`` in bounded
    memory.  All columns must be numeric; blocks arrive float32."""

    def __init__(self, path: str, chunk_rows: int = 65_536):
        pq = _require_pyarrow()
        self.path, self.chunk_rows = path, chunk_rows
        pf = pq.ParquetFile(path)
        try:
            md = pf.metadata
            self.shape = (int(md.num_rows), int(md.num_columns))
            import pyarrow as pa

            bad = [f for f in pf.schema_arrow
                   if not (pa.types.is_floating(f.type)
                           or pa.types.is_integer(f.type))]
            if bad:
                raise ValueError(
                    f"{path}: non-numeric parquet column(s) "
                    f"{[f.name for f in bad]} — point sources are numeric")
        finally:
            pf.close()
        self._init_cursor()

    def _open_stream(self):
        pq = _require_pyarrow()
        pf = pq.ParquetFile(self.path)

        class _Batches:
            def __init__(self, pf, chunk_rows):
                self._pf = pf
                self._it = pf.iter_batches(batch_size=chunk_rows)

            def __iter__(self):
                return self

            def __next__(self):
                batch = next(self._it)  # StopIteration propagates
                return np.stack(
                    [batch.column(i).to_numpy(zero_copy_only=False)
                     for i in range(batch.num_columns)], axis=1,
                ).astype(np.float32, copy=False)

            def close(self):
                self._pf.close()

        return _Batches(pf, self.chunk_rows)


def _require_pyarrow():
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover — pyarrow is in the image
        raise ImportError(
            "ParquetPoints needs pyarrow (not installed); convert the "
            "input to .npy/.csv or install pyarrow") from e
    return pq
