"""Data source API — HarpDAALDataSource parity, native fast path.

``load_csv`` / ``load_triples`` parse with the multi-threaded C++ loader
when available (≈num_cores× a Python parse), else fall back to numpy.
Both return host arrays ready for ``WorkerMesh.shard_array``.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from harp_tpu.native.build import load_native


def _loadtxt_any_sep(path: str) -> np.ndarray:
    """numpy fallback accepting comma OR whitespace separators, matching the
    native parser's behavior so results don't depend on g++ availability."""
    with open(path) as f:
        text = f.read().replace(",", " ")
    import io

    return np.loadtxt(io.StringIO(text), dtype=np.float64, ndmin=2)


def load_csv(path: str, n_threads: int = 0) -> np.ndarray:
    """Dense CSV/whitespace numeric file → float32 [rows, cols]."""
    n_threads = n_threads or (os.cpu_count() or 1)
    lib = load_native()
    if lib is None:
        return _loadtxt_any_sep(path).astype(np.float32)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.harp_count_rows(path.encode(), n_threads,
                             ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise OSError(f"native loader failed to read {path!r} (rc={rc})")
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.harp_load_csv_f32(
        path.encode(), n_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value, cols.value)
    if rc != 0:
        raise OSError(f"native loader failed to parse {path!r} (rc={rc})")
    return out


def load_triples(path: str, n_threads: int = 0):
    """'u i v' rating/token lines → (int32 [n], int32 [n], float32 [n])."""
    n_threads = n_threads or (os.cpu_count() or 1)
    lib = load_native()
    if lib is None:
        a = _loadtxt_any_sep(path)
        return (a[:, 0].astype(np.int32), a[:, 1].astype(np.int32),
                a[:, 2].astype(np.float32))
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.harp_count_rows(path.encode(), n_threads,
                             ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise OSError(f"native loader failed to read {path!r} (rc={rc})")
    u = np.empty(rows.value, np.int32)
    i = np.empty(rows.value, np.int32)
    v = np.empty(rows.value, np.float32)
    rc = lib.harp_load_triples(
        path.encode(), n_threads,
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        i.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value)
    if rc != 0:
        raise OSError(f"native loader failed to parse {path!r} (rc={rc})")
    return u, i, v
