"""Native C++ components, built on demand and driven via ctypes.

The reference's native layer is Intel DAAL behind JNI (SURVEY.md §3.2).
Compute moved into XLA; what remains host-side and performance-critical is
data ingest — implemented in ``loader.cpp`` and compiled here with g++ on
first use (cached ``.so``).  Falls back to numpy loaders when no compiler
is available, so the framework never hard-requires the toolchain.
"""

from harp_tpu.native.build import load_native, native_available
from harp_tpu.native.datasource import (
    CSVPoints,
    CSVStream,
    ParquetPoints,
    csr_to_ell,
    load_csv,
    load_libsvm,
    load_triples,
)

__all__ = ["load_native", "native_available", "load_csv", "load_libsvm",
           "load_triples", "csr_to_ell", "CSVStream", "CSVPoints",
           "ParquetPoints"]
