// Fast parallel text→tensor loader — HarpDAALDataSource, TPU-native.
//
// Reference parity (SURVEY.md §3.3): edu.iu.datasource.HarpDAALDataSource
// loads HDFS CSV / libsvm shards into DAAL NumericTables through JNI;
// the heavy lifting (parse + layout) is native. Here the same role is a
// small C++ library driven through ctypes (no JNI, no pybind11 — plain C
// ABI): it chunk-splits a file across std::thread workers, each parses
// its byte range with a branch-light float scanner, and rows land in one
// contiguous float32 buffer ready for jax.device_put.
//
// Exposed C ABI:
//   harp_count_rows(path, n_threads, *rows, *cols)      -> 0 on success
//   harp_load_csv_f32(path, n_threads, buf, rows, cols) -> 0 on success
//   harp_load_triples(path, n_threads, u_buf, i_buf, v_buf, n) -> 0
// Caller (Python) allocates the numpy buffers after harp_count_rows.

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Mapped {
  char* data = nullptr;
  size_t size = 0;
  bool ok = false;
};

Mapped read_file(const char* path) {
  Mapped m;
  FILE* f = std::fopen(path, "rb");
  if (!f) return m;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (sz < 0) { std::fclose(f); return m; }
  m.data = static_cast<char*>(std::malloc(sz + 1));
  if (!m.data) { std::fclose(f); return m; }
  m.size = std::fread(m.data, 1, sz, f);
  m.data[m.size] = '\0';
  std::fclose(f);
  m.ok = true;
  return m;
}

// Hand-rolled float scanner: [-+]?digits[.digits][eE[-+]digits].
// ~4× strtof (no locale, no errno); falls back to strtof for anything
// unusual (inf/nan/hex). Exact powers of ten up to |exp| 38 via table.
static const double kPow10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10, 1e11,
    1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22, 1e23,
    1e24, 1e25, 1e26, 1e27, 1e28, 1e29, 1e30, 1e31, 1e32, 1e33, 1e34, 1e35,
    1e36, 1e37, 1e38};

inline float parse_float(const char*& p) {
  const char* s = p;
  bool neg = false;
  if (*s == '-') { neg = true; ++s; }
  else if (*s == '+') { ++s; }
  if (!((*s >= '0' && *s <= '9') || *s == '.')) {
    // inf/nan/garbage: strtof, but ALWAYS advance past the token so the
    // caller's column loop can't spin forever on e.g. a header row
    char* endp = nullptr;
    float v = std::strtof(p, &endp);
    if (endp == p) {  // no conversion: skip the non-numeric token
      const char* q = p;
      while (*q && *q != ',' && *q != ' ' && *q != '\t' && *q != '\r' &&
             *q != '\n') ++q;
      p = (q == p) ? p + 1 : q;
      return 0.0f;
    }
    p = endp;
    return v;
  }
  uint64_t mant = 0;
  int frac_digits = 0;
  int ndig = 0;
  while (*s >= '0' && *s <= '9') {
    if (ndig < 19) { mant = mant * 10 + (*s - '0'); ++ndig; }
    else { --frac_digits; }  // skipped integer digit ⇒ scale up by 10
    ++s;
  }
  if (*s == '.') {
    ++s;
    while (*s >= '0' && *s <= '9') {
      if (ndig < 19) { mant = mant * 10 + (*s - '0'); ++ndig; ++frac_digits; }
      ++s;
    }
  }
  int exp10 = -frac_digits;
  if (*s == 'e' || *s == 'E') {
    ++s;
    bool eneg = false;
    if (*s == '-') { eneg = true; ++s; }
    else if (*s == '+') { ++s; }
    int e = 0;
    while (*s >= '0' && *s <= '9') {
      if (e < 100000) e = e * 10 + (*s - '0');  // clamp: no int overflow
      ++s;
    }
    exp10 += eneg ? -e : e;
  }
  // Clamp to double's decimal range BEFORE the stepped loops: a corrupt
  // "1e2000000000" token must parse in O(1) (to inf/0, like strtof), not
  // spin |exp10|/38 iterations, and a clamped exponent can never index
  // kPow10 out of bounds.
  if (exp10 > 700) exp10 = 700;
  else if (exp10 < -700) exp10 = -700;
  double v = static_cast<double>(mant);
  // Apply the decimal exponent in <=38 steps: a LONG mantissa plus a small
  // value can push the combined exponent past the table (e.g.
  // "9.9999999999999991e-31" has exp10 = -47) — the old 1e308 clamp
  // misparsed such values to 0/inf even though they are ordinary floats.
  int e = exp10;
  while (e > 0) { int step = e > 38 ? 38 : e; v *= kPow10[step]; e -= step; }
  while (e < 0) { int step = -e > 38 ? 38 : -e; v /= kPow10[step]; e += step; }
  p = s;
  return static_cast<float>(neg ? -v : v);
}

inline void skip_seps(const char*& p, const char* end) {
  while (p < end && (*p == ',' || *p == ' ' || *p == '\t' || *p == '\r')) ++p;
}

// Align a byte offset to the start of the next line.
size_t align_to_line(const char* data, size_t off, size_t size) {
  if (off == 0) return 0;
  while (off < size && data[off - 1] != '\n') ++off;
  return off;
}

// Truncate a line at '#' (numpy.loadtxt's default comment marker — the
// Python fallback inherits it, so the native parser must agree).
inline const char* strip_comment(const char* p, const char* line_end) {
  const char* hash = static_cast<const char*>(memchr(p, '#', line_end - p));
  return hash ? hash : line_end;
}

// A line is blank if it holds only separators (or was all comment).
inline bool blank_line(const char* p, const char* line_end) {
  skip_seps(p, line_end);
  return p >= line_end;
}

void count_range(const char* data, size_t begin, size_t end_, int64_t* rows,
                 int64_t* cols) {
  int64_t r = 0, c = 0;
  const char* p = data + begin;
  const char* end = data + end_;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = strip_comment(p, nl ? nl : end);
    if (line_end > p && !blank_line(p, line_end)) {
      ++r;
      if (c == 0) {
        const char* q = p;
        while (q < line_end) {
          skip_seps(q, line_end);
          if (q >= line_end) break;
          parse_float(q);
          ++c;
        }
      }
    }
    p = nl ? nl + 1 : end;
  }
  *rows = r;
  *cols = c;
}

}  // namespace

extern "C" {

// First pass: rows and columns (cols from the first non-empty line).
int harp_count_rows(const char* path, int n_threads, int64_t* rows,
                    int64_t* cols) {
  Mapped m = read_file(path);
  if (!m.ok) return 1;
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<int64_t> r(nt, 0), c(nt, 0);
  std::vector<std::thread> ts;
  size_t chunk = m.size / nt + 1;
  for (int t = 0; t < nt; ++t) {
    size_t b = align_to_line(m.data, t * chunk, m.size);
    size_t e = align_to_line(m.data, (t + 1) * chunk, m.size);
    if (e > m.size) e = m.size;
    ts.emplace_back(count_range, m.data, b, e, &r[t], &c[t]);
  }
  for (auto& t : ts) t.join();
  *rows = 0;
  *cols = 0;
  for (int t = 0; t < nt; ++t) {
    *rows += r[t];
    if (*cols == 0) *cols = c[t];
  }
  std::free(m.data);
  return 0;
}

// Second pass: parse into the caller-allocated [rows, cols] f32 buffer.
int harp_load_csv_f32(const char* path, int n_threads, float* buf,
                      int64_t rows, int64_t cols) {
  Mapped m = read_file(path);
  if (!m.ok) return 1;
  int nt = n_threads > 0 ? n_threads : 1;

  // per-thread row offsets need a prefix count first
  std::vector<size_t> begins(nt), ends(nt);
  std::vector<int64_t> r(nt, 0), c(nt, 0);
  size_t chunk = m.size / nt + 1;
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; ++t) {
      begins[t] = align_to_line(m.data, t * chunk, m.size);
      ends[t] = align_to_line(m.data, (t + 1) * chunk, m.size);
      if (ends[t] > m.size) ends[t] = m.size;
      ts.emplace_back(count_range, m.data, begins[t], ends[t], &r[t], &c[t]);
    }
    for (auto& t : ts) t.join();
  }
  std::vector<int64_t> row0(nt, 0);
  for (int t = 1; t < nt; ++t) row0[t] = row0[t - 1] + r[t - 1];
  if (row0[nt - 1] + r[nt - 1] != rows) { std::free(m.data); return 2; }

  auto parse_range = [&](int t) {
    const char* p = m.data + begins[t];
    const char* end = m.data + ends[t];
    float* out = buf + row0[t] * cols;
    while (p < end) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
      const char* line_end = strip_comment(p, nl ? nl : end);
      if (line_end > p && !blank_line(p, line_end)) {
        const char* q = p;
        for (int64_t j = 0; j < cols; ++j) {
          skip_seps(q, line_end);
          *out++ = (q < line_end) ? parse_float(q) : 0.0f;
        }
      }
      p = nl ? nl + 1 : end;
    }
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < nt; ++t) ts.emplace_back(parse_range, t);
  for (auto& t : ts) t.join();
  std::free(m.data);
  return 0;
}

// libsvm / CSR sparse: "label idx:val idx:val ..." lines (HarpDAALDataSource's
// CSR input).  Two-phase like the dense loader: count (rows, nnz, max index),
// then parse into caller-allocated CSR buffers.

namespace {

void count_libsvm_range(const char* data, size_t begin, size_t end_,
                        int64_t* rows, int64_t* nnz, int64_t* max_idx) {
  int64_t r = 0, z = 0, mi = -1;
  const char* p = data + begin;
  const char* end = data + end_;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    // '#' starts a comment anywhere on the line (parity with the Python
    // fallback's split('#', 1))
    const char* hash =
        static_cast<const char*>(memchr(p, '#', line_end - p));
    if (hash) line_end = hash;
    if (line_end > p) {
      const char* q = p;
      skip_seps(q, line_end);
      if (q < line_end) {
        ++r;
        parse_float(q);  // label: numeric prefix of the first token...
        // ...and any trailing garbage in that token is dropped whole, so
        // '3:1.5' is a label-only line, never a phantom (0, 1.5) pair
        while (q < line_end && *q != ' ' && *q != '\t' && *q != ',') ++q;
        while (q < line_end) {
          skip_seps(q, line_end);
          if (q >= line_end) break;
          long idx = std::strtol(q, const_cast<char**>(&q), 10);
          // a value exists only if something non-blank follows the ':' on
          // THIS line — "3:\n" must not let strtof's whitespace skip eat
          // the next line's label as the value
          if (q < line_end && *q == ':' && q + 1 < line_end &&
              q[1] != ' ' && q[1] != '\t' && q[1] != '\r' && q[1] != '\n') {
            ++q;
            parse_float(q);
            ++z;
            if (idx > mi) mi = idx;
          } else {
            // not an idx:val pair — skip the stray token
            while (q < line_end && *q != ' ' && *q != '\t' && *q != ',') ++q;
          }
        }
      }
    }
    p = nl ? nl + 1 : end;
  }
  *rows = r;
  *nnz = z;
  *max_idx = mi;
}

}  // namespace

int harp_count_libsvm(const char* path, int n_threads, int64_t* rows,
                      int64_t* nnz, int64_t* max_index) {
  Mapped m = read_file(path);
  if (!m.ok) return 1;
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<int64_t> r(nt, 0), z(nt, 0), mi(nt, -1);
  std::vector<std::thread> ts;
  size_t chunk = m.size / nt + 1;
  for (int t = 0; t < nt; ++t) {
    size_t b = align_to_line(m.data, t * chunk, m.size);
    size_t e = align_to_line(m.data, (t + 1) * chunk, m.size);
    if (e > m.size) e = m.size;
    ts.emplace_back(count_libsvm_range, m.data, b, e, &r[t], &z[t], &mi[t]);
  }
  for (auto& t : ts) t.join();
  *rows = 0; *nnz = 0; *max_index = -1;
  for (int t = 0; t < nt; ++t) {
    *rows += r[t];
    *nnz += z[t];
    if (mi[t] > *max_index) *max_index = mi[t];
  }
  std::free(m.data);
  return 0;
}

int harp_load_libsvm(const char* path, int n_threads, float* labels,
                     int64_t* indptr, int32_t* indices, float* values,
                     int64_t rows, int64_t nnz) {
  Mapped m = read_file(path);
  if (!m.ok) return 1;
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<size_t> begins(nt), ends(nt);
  std::vector<int64_t> r(nt, 0), z(nt, 0), mi(nt, -1);
  size_t chunk = m.size / nt + 1;
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; ++t) {
      begins[t] = align_to_line(m.data, t * chunk, m.size);
      ends[t] = align_to_line(m.data, (t + 1) * chunk, m.size);
      if (ends[t] > m.size) ends[t] = m.size;
      ts.emplace_back(count_libsvm_range, m.data, begins[t], ends[t],
                      &r[t], &z[t], &mi[t]);
    }
    for (auto& t : ts) t.join();
  }
  std::vector<int64_t> row0(nt, 0), nnz0(nt, 0);
  for (int t = 1; t < nt; ++t) {
    row0[t] = row0[t - 1] + r[t - 1];
    nnz0[t] = nnz0[t - 1] + z[t - 1];
  }
  if (row0[nt - 1] + r[nt - 1] != rows ||
      nnz0[nt - 1] + z[nt - 1] != nnz) { std::free(m.data); return 2; }

  auto parse_range = [&](int t) {
    const char* p = m.data + begins[t];
    const char* end = m.data + ends[t];
    int64_t row = row0[t], k = nnz0[t];
    while (p < end) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
      const char* line_end = nl ? nl : end;
      const char* hash =
          static_cast<const char*>(memchr(p, '#', line_end - p));
      if (hash) line_end = hash;
      if (line_end > p) {
        const char* q = p;
        skip_seps(q, line_end);
        if (q < line_end) {
          indptr[row] = k;
          labels[row] = parse_float(q);
          // drop the label token's trailing garbage (mirror the count pass)
          while (q < line_end && *q != ' ' && *q != '\t' && *q != ',') ++q;
          while (q < line_end) {
            skip_seps(q, line_end);
            if (q >= line_end) break;
            long idx = std::strtol(q, const_cast<char**>(&q), 10);
            // mirror count_libsvm_range's has-value guard exactly — the
            // prefix offsets depend on both passes agreeing
            if (q < line_end && *q == ':' && q + 1 < line_end &&
                q[1] != ' ' && q[1] != '\t' && q[1] != '\r' && q[1] != '\n') {
              ++q;
              values[k] = parse_float(q);
              indices[k] = static_cast<int32_t>(idx);
              ++k;
            } else {
              while (q < line_end && *q != ' ' && *q != '\t' && *q != ',') ++q;
            }
          }
          ++row;
        }
      }
      p = nl ? nl + 1 : end;
    }
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < nt; ++t) ts.emplace_back(parse_range, t);
  for (auto& t : ts) t.join();
  indptr[rows] = nnz;
  std::free(m.data);
  return 0;
}

// Rating/token triples "u i v" → int32/int32/float32 columns (MF-SGD, LDA).
int harp_load_triples(const char* path, int n_threads, int32_t* u_buf,
                      int32_t* i_buf, float* v_buf, int64_t n) {
  Mapped m = read_file(path);
  if (!m.ok) return 1;
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<size_t> begins(nt), ends(nt);
  std::vector<int64_t> r(nt, 0), c(nt, 0);
  size_t chunk = m.size / nt + 1;
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; ++t) {
      begins[t] = align_to_line(m.data, t * chunk, m.size);
      ends[t] = align_to_line(m.data, (t + 1) * chunk, m.size);
      if (ends[t] > m.size) ends[t] = m.size;
      ts.emplace_back(count_range, m.data, begins[t], ends[t], &r[t], &c[t]);
    }
    for (auto& t : ts) t.join();
  }
  std::vector<int64_t> row0(nt, 0);
  for (int t = 1; t < nt; ++t) row0[t] = row0[t - 1] + r[t - 1];
  if (row0[nt - 1] + r[nt - 1] != n) { std::free(m.data); return 2; }

  auto parse_range = [&](int t) {
    const char* p = m.data + begins[t];
    const char* end = m.data + ends[t];
    int64_t row = row0[t];
    while (p < end) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
      const char* line_end = strip_comment(p, nl ? nl : end);
      if (line_end > p && !blank_line(p, line_end)) {
        const char* q = p;
        skip_seps(q, line_end);
        u_buf[row] = static_cast<int32_t>(std::strtol(q, const_cast<char**>(&q), 10));
        skip_seps(q, line_end);
        i_buf[row] = static_cast<int32_t>(std::strtol(q, const_cast<char**>(&q), 10));
        skip_seps(q, line_end);
        v_buf[row] = (q < line_end) ? parse_float(q) : 0.0f;
        ++row;
      }
      p = nl ? nl + 1 : end;
    }
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < nt; ++t) ts.emplace_back(parse_range, t);
  for (auto& t : ts) t.join();
  std::free(m.data);
  return 0;
}

// ---------------------------------------------------------------------------
// Streaming CSV reader — the native ingest path for beyond-RAM text
// corpora (feeds harp_tpu.models.kmeans_stream.fit_streaming).  A single
// background thread reads + parses the NEXT chunk while the caller
// consumes the current one (two parsed slots, classic double buffer), so
// disk+parse overlaps device compute.  Bounded memory: two slots of
// [chunk_rows, cols] floats plus one byte block.
//
//   harp_csv_stream_open(path, chunk_rows)        -> handle (NULL = error)
//   harp_csv_stream_cols(h)                       -> cols (-1 error/empty)
//   harp_csv_stream_next(h, buf, buf_rows)        -> rows written
//                                                    (0 = EOF, -1 = error)
//   harp_csv_stream_close(h)
// ---------------------------------------------------------------------------

namespace {

// Parse up to max_rows non-blank lines of [begin, end) into out[cols].
// Missing trailing columns parse as 0 (matches the dense loader).
int64_t parse_block_rows(const char* p, const char* end, int64_t cols,
                         float* out, int64_t max_rows) {
  int64_t r = 0;
  while (p < end && r < max_rows) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* le = strip_comment(p, nl ? nl : end);
    if (le > p && !blank_line(p, le)) {
      const char* q = p;
      for (int64_t c = 0; c < cols; ++c) {
        skip_seps(q, le);
        out[r * cols + c] = (q < le) ? parse_float(q) : 0.0f;
      }
      ++r;
    }
    p = nl ? nl + 1 : end;
  }
  return r;
}

// Columns of the first non-blank line in [p, end); 0 if none.
int64_t first_line_cols(const char* p, const char* end) {
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* le = strip_comment(p, nl ? nl : end);
    if (le > p && !blank_line(p, le)) {
      int64_t c = 0;
      const char* q = p;
      while (q < le) {
        skip_seps(q, le);
        if (q >= le) break;
        parse_float(q);
        ++c;
      }
      return c;
    }
    p = nl ? nl + 1 : end;
  }
  return 0;
}

struct CsvStream {
  std::FILE* f = nullptr;
  int64_t chunk_rows = 0;
  int64_t cols = -1;          // -1 until the first block is seen
  std::string carry;          // bytes after the last complete line
  bool read_eof = false;
  bool io_error = false;      // fread failed (ferror), not clean EOF

  // two parsed slots (producer fills, consumer drains)
  std::vector<float> slot[2];
  int64_t slot_rows[2] = {0, 0};
  bool full[2] = {false, false};
  int prod = 0, cons = 0;
  bool finished = false;      // producer delivered EOF
  bool error = false;
  bool closing = false;
  std::mutex mu;
  std::condition_variable cv;
  std::thread worker;
};

// Gather bytes holding ~chunk_rows lines; the remainder goes to carry.
// Returns false when nothing is left (true EOF).
bool stream_build_block(CsvStream* s, std::string& block) {
  block.clear();
  block.swap(s->carry);
  int64_t nl = std::count(block.begin(), block.end(), '\n');
  std::vector<char> tmp(1 << 20);
  while (nl < s->chunk_rows && !s->read_eof) {
    size_t got = std::fread(tmp.data(), 1, tmp.size(), s->f);
    if (got == 0) {
      s->read_eof = true;
      if (std::ferror(s->f)) s->io_error = true;  // NOT a clean EOF
      break;
    }
    nl += std::count(tmp.data(), tmp.data() + got, '\n');
    block.append(tmp.data(), got);
  }
  // Split after the chunk_rows-th newline.  >= (not >): with EXACTLY
  // chunk_rows newlines plus trailing partial-line bytes, those bytes
  // must go to carry — leaving them in the block would drop them (the
  // parse caps at chunk_rows rows) and the next block would start
  // mid-number.
  if (nl >= s->chunk_rows) {
    int64_t seen = 0;
    size_t pos = 0;
    while (seen < s->chunk_rows) {
      pos = block.find('\n', pos) + 1;
      ++seen;
    }
    s->carry.assign(block, pos, std::string::npos);
    block.resize(pos);
  }
  return !block.empty();
}

void stream_worker(CsvStream* s) {
  std::string block;
  while (true) {
    {
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv.wait(lk, [s] { return s->closing || !s->full[s->prod]; });
      if (s->closing) return;
    }
    // A block can parse to ZERO data rows (all comments/blank lines —
    // including the very first block, before cols is known).  That must
    // not look like EOF: keep pulling blocks until data rows appear or
    // the file truly ends.
    int64_t rows = 0;
    bool got = false;
    do {
      got = stream_build_block(s, block);  // only this thread reads f
      if (!got) break;
      if (s->cols < 0) {
        int64_t c = first_line_cols(block.data(), block.data() + block.size());
        if (c > 0) {
          std::lock_guard<std::mutex> lk(s->mu);
          s->cols = c;
          s->cv.notify_all();
        }
      }
      if (s->cols > 0) {
        auto& sl = s->slot[s->prod];
        sl.resize(s->chunk_rows * s->cols);
        rows = parse_block_rows(block.data(), block.data() + block.size(),
                                s->cols, sl.data(), s->chunk_rows);
      }
    } while (rows == 0);
    {
      std::lock_guard<std::mutex> lk(s->mu);
      if (s->io_error) {
        s->error = true;
        s->cv.notify_all();
        return;
      }
      if (!got) {  // clean EOF (cols stays 0 for an all-blank file)
        if (s->cols < 0) s->cols = 0;
        s->finished = true;
        s->cv.notify_all();
        return;
      }
      s->slot_rows[s->prod] = rows;
      s->full[s->prod] = true;
      s->prod ^= 1;
      s->cv.notify_all();
    }
  }
}

}  // namespace

// Streaming row/column count: bounded memory (one 4 MB block + a line
// carry), unlike harp_count_rows whose read_file() malloc's the whole
// file — CSVPoints' shape pass on a beyond-RAM corpus must not OOM.
int harp_csv_count_stream(const char* path, int64_t* rows, int64_t* cols) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  std::vector<char> buf(4 << 20);
  std::string carry;
  int64_t r = 0, c = 0;
  while (true) {
    size_t got = std::fread(buf.data(), 1, buf.size(), f);
    if (got == 0) {
      if (std::ferror(f)) { std::fclose(f); return 1; }
      break;
    }
    carry.append(buf.data(), got);
    size_t last_nl = carry.rfind('\n');
    if (last_nl == std::string::npos) continue;  // no complete line yet
    int64_t br = 0, bc = 0;
    count_range(carry.data(), 0, last_nl + 1, &br, &bc);
    r += br;
    if (c == 0) c = bc;
    carry.erase(0, last_nl + 1);
  }
  if (!carry.empty()) {  // final line without trailing newline
    int64_t br = 0, bc = 0;
    count_range(carry.data(), 0, carry.size(), &br, &bc);
    r += br;
    if (c == 0) c = bc;
  }
  std::fclose(f);
  *rows = r;
  *cols = c;
  return 0;
}

void* harp_csv_stream_open(const char* path, int64_t chunk_rows) {
  if (chunk_rows < 1) return nullptr;
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  CsvStream* s = new CsvStream();
  s->f = f;
  s->chunk_rows = chunk_rows;
  s->worker = std::thread(stream_worker, s);
  return s;
}

int64_t harp_csv_stream_cols(void* h) {
  CsvStream* s = static_cast<CsvStream*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv.wait(lk, [s] { return s->cols >= 0 || s->finished || s->error; });
  return s->error ? -1 : s->cols;
}

int64_t harp_csv_stream_next(void* h, float* buf, int64_t buf_rows) {
  CsvStream* s = static_cast<CsvStream*>(h);
  int64_t rows;
  {
    std::unique_lock<std::mutex> lk(s->mu);
    s->cv.wait(lk, [s] { return s->full[s->cons] || s->finished || s->error; });
    if (s->error) return -1;
    if (!s->full[s->cons]) return 0;  // finished, queue drained
    rows = s->slot_rows[s->cons];
    if (rows > buf_rows) return -1;   // caller buffer too small
  }
  std::memcpy(buf, s->slot[s->cons].data(),
              static_cast<size_t>(rows) * s->cols * sizeof(float));
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->full[s->cons] = false;
    s->cons ^= 1;
    s->cv.notify_all();
  }
  return rows;
}

void harp_csv_stream_close(void* h) {
  CsvStream* s = static_cast<CsvStream*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->closing = true;
    s->cv.notify_all();
  }
  if (s->worker.joinable()) s->worker.join();
  std::fclose(s->f);
  delete s;
}

}  // extern "C"
