"""Compile-on-first-use for the native library (g++ → .so, ctypes ABI)."""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "loader.cpp")
_LIB = None
_TRIED = False


def _so_path() -> str:
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    # -march=native binaries are CPU-specific: key the cache on the CPU's
    # feature flags too, so a .so built on one machine never SIGILLs on
    # another sharing the package directory
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    h.update(line.encode())
                    break
    except OSError:
        import platform

        h.update(platform.processor().encode())
    return os.path.join(_DIR, f"_harp_native_{h.hexdigest()[:16]}.so")


def native_available() -> bool:
    return shutil.which("g++") is not None or os.path.exists(_so_path())


def load_native():
    """Return the ctypes library, building it if needed; None if impossible."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    so = _so_path()
    if not os.path.exists(so):
        if shutil.which("g++") is None:
            return None
        # build to a temp file then atomically rename (parallel-safe)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
               "-fPIC", "-pthread", _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp, so)
        except subprocess.CalledProcessError:
            os.unlink(tmp)
            return None
    lib = ctypes.CDLL(so)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.harp_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_int, i64p, i64p]
    lib.harp_count_rows.restype = ctypes.c_int
    lib.harp_load_csv_f32.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int64]
    lib.harp_load_csv_f32.restype = ctypes.c_int
    lib.harp_load_triples.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64]
    lib.harp_load_triples.restype = ctypes.c_int
    lib.harp_count_libsvm.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      i64p, i64p, i64p]
    lib.harp_count_libsvm.restype = ctypes.c_int
    lib.harp_load_libsvm.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        i64p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64]
    lib.harp_load_libsvm.restype = ctypes.c_int
    lib.harp_csv_count_stream.argtypes = [ctypes.c_char_p, i64p, i64p]
    lib.harp_csv_count_stream.restype = ctypes.c_int
    lib.harp_csv_stream_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.harp_csv_stream_open.restype = ctypes.c_void_p
    lib.harp_csv_stream_cols.argtypes = [ctypes.c_void_p]
    lib.harp_csv_stream_cols.restype = ctypes.c_int64
    lib.harp_csv_stream_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    lib.harp_csv_stream_next.restype = ctypes.c_int64
    lib.harp_csv_stream_close.argtypes = [ctypes.c_void_p]
    lib.harp_csv_stream_close.restype = None
    _LIB = lib
    return _LIB
