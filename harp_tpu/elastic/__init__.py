"""Elastic execution — act on the skew trigger mid-run, survive
permanent worker loss without a restart (PR 15).

Reference parity (SURVEY.md §3.1, §6): Harp's schdynamic/dymoro
schedulers rebalanced load exactly *between* supersteps, but only inside
one worker's thread pool; across workers Harp had static partitions and
YARN's kill-the-job failure model.  HARP (arXiv:2509.24859, PAPERS.md)
is the modern statement that orchestration — rebalance, shrink, resume —
should be driven by continuously monitored runtime signals.  This
package is the ACTING half of that loop (PR 14's health sentinel is the
observing half):

- **Layer 1 — mid-run rebalance** (:mod:`harp_tpu.elastic.rebalance`):
  between supersteps a driver consumes a latched ``skew_trigger``
  health finding exactly once (the sentinel↔driver handshake,
  ``health.monitor.consume_skew_trigger``), replays its inline plan
  through ``schedule.apply_rebalance`` over the corpus's movable packs,
  and repartitions — factor-table rows ride the existing ``reshard``
  wire (:mod:`harp_tpu.elastic.move`, the registered
  ``elastic.regather`` program, so the CommGraph byte sheet accounts
  the move), token/rating layouts repack on host.  SkewLedger
  before/after evidence lands as ``kind:"elastic"`` rebalance rows.
- **Layer 2 — worker-loss survival** (:mod:`harp_tpu.elastic.apps`):
  an injected :class:`~harp_tpu.utils.fault.PermanentWorkerLoss`
  shrinks the mesh to the survivors, derives a repartition plan over
  them (same plan shape, forced whole-unit), replays it from the last
  crash-atomic checkpoint, and keeps training — degraded-throughput
  ``kind:"elastic"`` shrink/resume rows instead of downtime.

Evidence: :mod:`harp_tpu.elastic.ledger` (``kind:"elastic"`` rows,
scripts/check_jsonl.py invariant 14; frozen event vocabulary
rebalance/shrink/resume).  This ``__init__`` stays light (the ledger
only — no jax): ``telemetry.export``/``scope`` import it on every run.
"""

from harp_tpu.elastic import ledger  # noqa: F401  (the module)
from harp_tpu.elastic.ledger import (  # noqa: F401
    EVENTS, ElasticLedger, export_jsonl, record, reset)
