"""Elastic driver adapters — lda/mfsgd/kmeans-stream on the elastic
loop (PR 15).

Each adapter owns the ORIGINAL corpus, the pack structure
(:class:`~harp_tpu.elastic.rebalance.Packs` over the app's partition
key: users / docs / point rows), the current pack→worker assignment,
and the live model; it knows how to

- run one superstep (``train_one`` — the model's own epoch driver,
  unchanged, with the pack grains attached to the skew execution record
  so the sentinel's trigger plan is whole-unit);
- apply a new assignment mid-run (``apply_assignment`` — Layer 1: the
  MF-SGD factor rows ride the ``reshard`` wire via
  :func:`harp_tpu.elastic.move.regather_rows`; LDA's count tables are
  reconstructed EXACTLY from the preserved per-token chain state, so no
  approximation enters the move);
- round-trip a CANONICAL, mesh-independent checkpoint state
  (``canonical_state`` / ``install`` — Layer 2: external-id numpy
  arrays plus the pack assignment, so the same checkpoint restores onto
  any survivor mesh; ``install`` is a deterministic function of
  ``(state, mesh)``, which is what makes the elastic resume BIT-identical
  to an uninterrupted survivors-only run from the same checkpoint).

:func:`elastic_fit` is the shared superstep loop: train → consume a
latched ``skew_trigger`` (``maybe_rebalance``) → checkpoint canonical
state; worker loss rides ``run_with_recovery``'s ``on_permanent`` hook
(:meth:`ElasticAdapter.shrink`), which excises the lost device, and the
next restore replays the repartition plan over the survivors.
"""

from __future__ import annotations

import contextlib
from typing import Any

import numpy as np

from harp_tpu.elastic import ledger as eledger
from harp_tpu.elastic import move
from harp_tpu.elastic.rebalance import (IdRemap, Packs, maybe_rebalance,
                                        pack_units, replay_repartition,
                                        wasted_frac, worker_loads)
from harp_tpu.parallel.mesh import WorkerMesh, current_mesh
from harp_tpu.utils import prng
from harp_tpu.utils.fault import PermanentWorkerLoss, run_with_recovery


class ElasticAdapter:
    """Shared pack/assignment/mesh state machine (see module doc)."""

    phase = "elastic"

    def __init__(self, mesh: WorkerMesh, packs: Packs, loads,
                 max_worker_loss: int = 1):
        self.mesh = mesh
        self.packs = packs
        self.loads = np.asarray(loads, np.float64)
        self.assignment = packs.home_assignment()
        self.max_worker_loss = int(max_worker_loss)
        self.losses = 0
        self._live: Any = None
        self._stale = False

    # -- layer 1: the trigger's view ---------------------------------------
    def worker_loads(self) -> np.ndarray:
        return worker_loads(self.assignment, self.loads,
                            self.mesh.num_workers)

    def pack_units(self) -> list[list[tuple]]:
        return pack_units(self.assignment, self.loads,
                          self.mesh.num_workers)

    def apply_assignment(self, assignment) -> None:  # pragma: no cover
        raise NotImplementedError

    # -- layer 2: loss + resume --------------------------------------------
    def shrink(self, e: PermanentWorkerLoss) -> None:
        """``run_with_recovery``'s ``on_permanent`` hook: excise the
        lost device, within the loss budget; the NEXT ``install`` (the
        restore at the top of the recovery loop) replays the
        repartition plan over the survivors."""
        self.losses += 1
        if self.losses > self.max_worker_loss:
            raise e  # loss budget exhausted: fail loudly, not elastically
        nb = self.mesh.num_workers
        self.mesh = self.mesh.survivors(e.worker)
        self._stale = True
        eledger.record(
            "shrink", self.phase, lost_worker=int(e.worker),
            site=e.site, ordinal=int(e.ordinal),
            n_workers_before=nb, n_workers_after=nb - 1,
            capacity_frac=round((nb - 1) / nb, 6))

    def install(self, state) -> None:
        """Restore a canonical checkpoint state onto the CURRENT mesh.

        No-op when ``state`` is this adapter's own live state and no
        shrink intervened (the steady-state path pays nothing).  A
        checkpoint written on a different mesh size replays the
        whole-unit repartition plan over the survivors
        (:func:`replay_repartition` — deterministic, the bit-identity
        pin); a same-size restore reuses the stored assignment, so a
        transient restart reproduces the pre-crash layout exactly.
        """
        if state is self._live and not self._stale:
            return
        n = self.mesh.num_workers
        # the pack GRID is canonical state too: a comparison/restore
        # adapter constructed on a survivor mesh would otherwise derive
        # a different grain (n_home = survivors) and a different layout
        grid = tuple(int(x) for x in np.asarray(state["pack_grid"]))
        if grid != (self.packs.n_ids, self.packs.n_home,
                    self.packs.per_worker):
            self.packs = Packs(*grid)
            self.loads = self._pack_loads()
        asg = np.asarray(state["assignment"], np.int64)
        shrunk = int(state["n_workers"]) != n
        if shrunk:
            asg, _ = replay_repartition(self.packs, self.loads, asg, n,
                                        self.phase)
        self.assignment = asg
        self._rebuild(state)
        lw = self.worker_loads()
        step = state.get("step")
        eledger.record(
            "resume", self.phase, n_workers=n,
            from_step=None if step is None else int(step),
            loads=[round(float(x), 4) for x in lw],
            total=round(float(lw.sum()), 4),
            wasted_frac=round(wasted_frac(lw), 4),
            replayed_plan=bool(shrunk))
        self._stale = False
        self._live = state

    def canonical_state(self) -> dict:
        st = self._extract()
        st["assignment"] = np.asarray(self.assignment, np.int64)
        st["n_workers"] = self.mesh.num_workers
        st["pack_grid"] = np.asarray(
            [self.packs.n_ids, self.packs.n_home, self.packs.per_worker],
            np.int64)
        self._live = st
        return st

    def _pack_loads(self) -> np.ndarray:  # pragma: no cover - hook
        raise NotImplementedError

    def _extract(self) -> dict:  # pragma: no cover - subclass hook
        raise NotImplementedError

    def _rebuild(self, state) -> None:  # pragma: no cover - subclass hook
        raise NotImplementedError

    def train_one(self) -> None:  # pragma: no cover - subclass hook
        raise NotImplementedError


# ---------------------------------------------------------------------------
# MF-SGD
# ---------------------------------------------------------------------------

def _user_storage_rows(model, ext_ids) -> np.ndarray:
    """External user id → W storage row (dense pads each worker's range
    to a tile multiple; scatter stores externals directly — the same
    formula covers both since there u_own == u_bound)."""
    g = np.asarray(ext_ids, np.int64)
    return (g // model.u_own) * model.u_bound + g % model.u_own


def _item_storage(model, H_ext: np.ndarray) -> np.ndarray:
    """External item table → H storage layout (per half-slice padding,
    the inverse of ``MFSGD.factors``'s strip)."""
    from harp_tpu.models.mfsgd import rotate_chunks_resolved

    nc = rotate_chunks_resolved(model.cfg)
    ibc = model.i_bound // nc
    n = model.mesh.num_workers
    out = np.zeros((model.i_bound * n, H_ext.shape[1]), np.float32)
    g = np.arange(model.n_items, dtype=np.int64)
    out[(g // model.i_own) * ibc + g % model.i_own] = H_ext
    return out


class MFSGDElastic(ElasticAdapter):
    """MF-SGD on the elastic loop: packs over user ids, loads = rating
    counts; factor-row moves ride the reshard wire."""

    phase = "mfsgd.epochs"

    def __init__(self, n_users, n_items, cfg=None, mesh=None, seed=0, *,
                 users, items, vals, packs_per_worker: int = 4,
                 max_worker_loss: int = 1):
        from harp_tpu.models.mfsgd import MFSGDConfig

        mesh = mesh or current_mesh()
        self.users = np.asarray(users, np.int64)
        self.items = np.asarray(items, np.int64)
        self.vals = np.asarray(vals, np.float32)
        self.n_items = int(n_items)
        self.cfg = cfg or MFSGDConfig()
        self.seed = seed
        packs = Packs(int(n_users), mesh.num_workers, packs_per_worker)
        super().__init__(mesh, packs, packs.loads(self.users),
                         max_worker_loss=max_worker_loss)
        self._rebuild(None)

    def _make_model(self, remap: IdRemap):
        from harp_tpu.models.mfsgd import MFSGD

        model = MFSGD(remap.new_n, self.n_items, self.cfg, self.mesh,
                      self.seed)
        model.set_ratings(remap.fwd[self.users], self.items, self.vals)
        model.skew_units = self.pack_units()
        return model

    def _rebuild(self, state) -> None:
        remap = IdRemap(self.packs, self.assignment,
                        self.mesh.num_workers)
        model = self._make_model(remap)
        if state is not None:
            r = self.cfg.rank
            W_ext = np.zeros((remap.new_n, r), np.float32)
            W_ext[remap.fwd] = np.asarray(state["W"], np.float32)
            W_store = np.zeros((model.u_bound * self.mesh.num_workers, r),
                               np.float32)
            g = np.arange(remap.new_n, dtype=np.int64)
            W_store[_user_storage_rows(model, g)] = W_ext
            model.W = self.mesh.shard_array(W_store, 0)
            model.H = self.mesh.shard_array(
                _item_storage(model, np.asarray(state["H"], np.float32)),
                0)
        self.model, self.remap = model, remap

    def apply_assignment(self, assignment) -> None:
        """Layer-1 move on the SAME mesh: W rows travel DEVICE-side over
        the reshard wire (one all_gather — the ``elastic.regather``
        byte sheet); the item slices are untouched, so H is reused
        as-is, and only the rating layout repacks on host."""
        old_model, old_remap = self.model, self.remap
        self.assignment = np.asarray(assignment, np.int64)
        remap = IdRemap(self.packs, self.assignment,
                        self.mesh.num_workers)
        model = self._make_model(remap)
        n = self.mesh.num_workers
        orig = np.arange(self.packs.n_ids, dtype=np.int64)
        rows = np.full(model.u_bound * n, -1, np.int64)
        rows[_user_storage_rows(model, remap.fwd[orig])] = \
            _user_storage_rows(old_model, old_remap.fwd[orig])
        model.W = move.regather_rows(self.mesh, old_model.W, rows)
        model.H = old_model.H  # item layout unchanged: zero wire
        self.model, self.remap = model, remap

    def _pack_loads(self) -> np.ndarray:
        return self.packs.loads(self.users)

    def _extract(self) -> dict:
        W_pad, H = self.model.factors()
        return {"W": np.asarray(W_pad)[self.remap.fwd].copy(),
                "H": np.asarray(H).copy()}

    def train_one(self) -> None:
        self.last_rmse = self.model.train_epoch()

    def metric(self) -> float:
        """Training-triple RMSE in the ORIGINAL id space (the flip-gate
        metric the drills compare at rel 1%)."""
        return self.model.predict_rmse(self.remap.fwd[self.users],
                                       self.items, self.vals)


# ---------------------------------------------------------------------------
# LDA
# ---------------------------------------------------------------------------

class LDAElastic(ElasticAdapter):
    """LDA-CGS on the elastic loop: packs over doc ids, loads = token
    counts.  The chain state is the (doc, word, z) token multiset —
    counts derive exactly from it, so a repartition preserves the chain
    bit-for-bit at the move (subsequent sweeps differ only by the
    snapshot boundaries the new layout implies, which is the parallel
    sampler's normal approximation — gated by log-likelihood)."""

    phase = "lda.epochs"

    def __init__(self, n_docs, vocab_size, cfg=None, mesh=None, seed=0, *,
                 doc_ids, word_ids, packs_per_worker: int = 4,
                 max_worker_loss: int = 1):
        from harp_tpu.models.lda import LDAConfig

        mesh = mesh or current_mesh()
        self.doc_ids = np.asarray(doc_ids, np.int64)
        self.word_ids = np.asarray(word_ids, np.int64)
        self.vocab_size = int(vocab_size)
        self.cfg = cfg or LDAConfig()
        self.seed = seed
        self.key_seed = int(seed)
        packs = Packs(int(n_docs), mesh.num_workers, packs_per_worker)
        super().__init__(mesh, packs, packs.loads(self.doc_ids),
                         max_worker_loss=max_worker_loss)
        self._rebuild(None)

    def _build_model(self, remap: IdRemap, d, w, z):
        from harp_tpu.models.lda import LDA

        model = LDA(remap.new_n, self.vocab_size, self.cfg, self.mesh,
                    self.seed)
        model._install_pack(model.pack_tokens(remap.fwd[np.asarray(d)],
                                              np.asarray(w), z0=z))
        model.skew_units = self.pack_units()
        return model

    def _rebuild(self, state) -> None:
        remap = IdRemap(self.packs, self.assignment,
                        self.mesh.num_workers)
        if state is None:
            d, w, z = self.doc_ids, self.word_ids, None
        else:
            d, w, z = state["d"], state["w"], state["z"]
            self.key_seed = int(state["key_seed"])
        self.model = self._build_model(remap, d, w, z)
        self.remap = remap

    def _triples(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Current chain state in the ORIGINAL doc-id space."""
        d_ext, w_ext, z = self.model.token_state()
        return self.remap.inv[d_ext], w_ext, z

    def apply_assignment(self, assignment) -> None:
        d, w, z = self._triples()
        self.assignment = np.asarray(assignment, np.int64)
        remap = IdRemap(self.packs, self.assignment,
                        self.mesh.num_workers)
        self.model = self._build_model(remap, d, w, z)
        self.remap = remap

    def _pack_loads(self) -> np.ndarray:
        return self.packs.loads(self.doc_ids)

    def _extract(self) -> dict:
        d, w, z = self._triples()
        return {"d": d, "w": w, "z": z, "key_seed": self.key_seed}

    def train_one(self) -> None:
        # keys re-derived from the adapter's own seed chain so the
        # canonical state fully determines the next sweep on ANY mesh
        # (prng.split_keys: a fresh derived seed never costs a compile)
        self.model._keys = prng.split_keys(self.key_seed,
                                           self.mesh.num_workers)
        self.model.sample_epoch()
        self.key_seed = (self.key_seed * 0x9E3779B1 + 0x5851) % (1 << 31)

    def metric(self) -> float:
        return self.model.log_likelihood()


# ---------------------------------------------------------------------------
# kmeans-stream
# ---------------------------------------------------------------------------

class KMeansStreamElastic(ElasticAdapter):
    """Streaming-kmeans Lloyd on the elastic loop: packs over point
    rows, loads = rows per pack; the mask-aware accum/finish pair from
    :mod:`harp_tpu.models.kmeans_stream` makes the padded survivor
    layout exact (pad rows carry mask 0, so they never touch a sum).
    Centroids are replicated — the canonical state is mesh-independent
    by construction, which is why this was the ROADMAP's "second"
    target: the repartition moves only the points."""

    phase = "kmeans_stream.epochs"

    def __init__(self, points, k: int, mesh=None, seed=0, *,
                 packs_per_worker: int = 4, max_worker_loss: int = 1):
        mesh = mesh or current_mesh()
        self.points = np.asarray(points, np.float32)
        self.k = int(k)
        n_pts = self.points.shape[0]
        packs = Packs(n_pts, mesh.num_workers, packs_per_worker)
        super().__init__(mesh, packs,
                         packs.widths().astype(np.float64),
                         max_worker_loss=max_worker_loss)
        from harp_tpu.models.kmeans_stream import _init_centroids

        self.centroids = np.asarray(
            _init_centroids(self.points, n_pts, self.k, seed, "random"),
            np.float32)
        self.inertia = float("nan")
        self._rebuild(None)

    def _rebuild(self, state) -> None:
        import jax
        import jax.numpy as jnp

        from harp_tpu.models.kmeans_stream import (StreamConfig,
                                                   _make_accum_fn,
                                                   _make_finish_fn)
        from harp_tpu.utils import flightrec

        remap = IdRemap(self.packs, self.assignment,
                        self.mesh.num_workers)
        if state is not None:
            self.centroids = np.asarray(state["centroids"], np.float32)
        n, d = self.mesh.num_workers, self.points.shape[1]
        pts = np.zeros((remap.new_n, d), np.float32)
        mask = np.zeros(remap.new_n, np.float32)
        pts[remap.fwd] = self.points
        mask[remap.fwd] = 1.0
        self._pts = self.mesh.shard_array(pts, 0)
        self._mask = self.mesh.shard_array(mask, 0)
        cfg = StreamConfig(k=self.k, chunk_points=remap.new_n)
        self._accum = flightrec.track(_make_accum_fn(self.mesh, cfg),
                                      "kmeans_stream.accum")
        self._finish = flightrec.track(_make_finish_fn(self.mesh),
                                       "kmeans_stream.finish")
        sh = self.mesh.sharding(self.mesh.spec(0))
        self._zeros = (
            jax.device_put(jnp.zeros((n, self.k, d), jnp.float32), sh),
            jax.device_put(jnp.zeros((n, self.k), jnp.float32), sh),
            jax.device_put(jnp.zeros((n,), jnp.float32), sh))
        self.remap = remap
        # the skew grains for the sentinel (one execution record/sweep)
        self._units = self.pack_units()

    def apply_assignment(self, assignment) -> None:
        self.assignment = np.asarray(assignment, np.int64)
        self._rebuild({"centroids": self.centroids})

    def _pack_loads(self) -> np.ndarray:
        return self.packs.widths().astype(np.float64)

    def _extract(self) -> dict:
        return {"centroids": self.centroids.copy()}

    def train_one(self) -> None:
        import time

        import jax

        from harp_tpu.utils import flightrec, skew, telemetry

        cents = jax.device_put(self.centroids, self.mesh.replicated())
        with telemetry.span("kmeans_stream.epoch"), \
                telemetry.ledger.run(self.phase, steps=1):
            t0 = time.perf_counter()
            sums, counts, inertia = self._accum(self._pts, self._mask,
                                                cents, *self._zeros)
            new_c, in_tot = self._finish(sums, counts, inertia, cents)
            st = flightrec.readback(new_c)
            self.centroids = np.asarray(st, np.float32)
            self.inertia = float(np.asarray(in_tot))
            skew.record_execution(
                self.phase, self.worker_loads(), unit="points",
                wall_s=time.perf_counter() - t0, units=self._units)

    def metric(self) -> float:
        return self.inertia


# ---------------------------------------------------------------------------
# The shared superstep loop
# ---------------------------------------------------------------------------

def elastic_fit(adapter: ElasticAdapter, epochs: int,
                ckpt_dir: str | None = None, *, ckpt_every: int = 1,
                max_restarts: int = 3, fault=None,
                rebalance: bool = True) -> ElasticAdapter:
    """Run ``epochs`` supersteps elastically (see module doc).

    Layer 1 runs with or without checkpoints (the trigger consumption
    is between-superstep host work); Layer 2 — surviving a
    :class:`~harp_tpu.utils.fault.PermanentWorkerLoss` — requires
    ``ckpt_dir`` (the resume replays from the last crash-atomic
    checkpoint; a ``fault`` without one is refused, the
    ``fit_epochs`` contract).  Checkpoints hold the adapter's CANONICAL
    state, so they restore onto any survivor mesh.
    """

    def sweep():
        adapter.train_one()
        if rebalance:
            maybe_rebalance(adapter)

    from harp_tpu.utils import steptrace

    arm = fault.arm() if fault is not None else contextlib.nullcontext()
    if ckpt_dir is None:
        if fault is not None:
            raise ValueError(
                "fault injection requires ckpt_dir (recovery restarts "
                "from checkpoints; without one the injector would be "
                "silently ignored)")
        with arm, steptrace.run(adapter.phase):
            for i in range(epochs):
                with steptrace.superstep(adapter.phase, i):
                    sweep()
        return adapter

    from harp_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir)

    def step(i, state):
        # install() stays OUTSIDE the span: a genuine restore emits its
        # elastic "resume" row there, which steptrace latches onto the
        # NEXT span as outcome "resumed" (the timeline's restart seam)
        adapter.install(state)
        with steptrace.superstep(adapter.phase, i):
            sweep()
        st = adapter.canonical_state()
        st["step"] = i
        return st

    with arm, steptrace.run(adapter.phase):
        run_with_recovery(adapter.canonical_state, step, epochs, mgr,
                          ckpt_every=ckpt_every,
                          max_restarts=max_restarts, fault=fault,
                          on_permanent=adapter.shrink)
    return adapter


# ---------------------------------------------------------------------------
# CLI fit entries (the --elastic / --max-worker-loss knobs route here;
# tests/test_cli.py binds these signatures through stubs so a bad kwarg
# fails without executing)
# ---------------------------------------------------------------------------

def mfsgd_elastic_fit(users, items, vals, *, n_users, n_items, cfg=None,
                      epochs=1, ckpt_dir=None, ckpt_every=1,
                      max_worker_loss=1, packs_per_worker=4, mesh=None,
                      seed=0, fault=None) -> MFSGDElastic:
    ad = MFSGDElastic(n_users, n_items, cfg, mesh, seed, users=users,
                      items=items, vals=vals,
                      packs_per_worker=packs_per_worker,
                      max_worker_loss=max_worker_loss)
    return elastic_fit(ad, epochs, ckpt_dir, ckpt_every=ckpt_every,
                       fault=fault)


def lda_elastic_fit(doc_ids, word_ids, *, n_docs, vocab_size, cfg=None,
                    epochs=1, ckpt_dir=None, ckpt_every=1,
                    max_worker_loss=1, packs_per_worker=4, mesh=None,
                    seed=0, fault=None) -> LDAElastic:
    ad = LDAElastic(n_docs, vocab_size, cfg, mesh, seed,
                    doc_ids=doc_ids, word_ids=word_ids,
                    packs_per_worker=packs_per_worker,
                    max_worker_loss=max_worker_loss)
    return elastic_fit(ad, epochs, ckpt_dir, ckpt_every=ckpt_every,
                       fault=fault)


def kmeans_stream_elastic_fit(points, *, k, iters=1, ckpt_dir=None,
                              ckpt_every=1, max_worker_loss=1,
                              packs_per_worker=4, mesh=None, seed=0,
                              fault=None) -> KMeansStreamElastic:
    ad = KMeansStreamElastic(points, k, mesh, seed,
                             packs_per_worker=packs_per_worker,
                             max_worker_loss=max_worker_loss)
    return elastic_fit(ad, iters, ckpt_dir, ckpt_every=ckpt_every,
                       fault=fault)
