"""Mid-run rebalance machinery — packs, id relabeling, trigger
consumption (PR 15 Layer 1).

The movable grain is a **pack**: a contiguous block of external ids
(docs for LDA, users for MF-SGD, point rows for kmeans-stream), aligned
with the partitioners' ``id // ceil(n_ids / n_workers)`` block
ownership so the home assignment reproduces the non-elastic layout
exactly.  Packs are the whole units the SkewLedger records (``units=``
on the execution hook), ``suggest_rebalance`` plans over, and
``schedule.apply_rebalance`` replays — closing the loop the PR-14
sentinel opened.

A rebalance (or a survivor repartition after worker loss) is an
**assignment** ``pack → worker`` plus an :class:`IdRemap`: a bijective
relabeling of the external id space such that plain block partition
``new_id // bound`` lands every pack on its planned owner.  The
existing partitioners then consume the remapped corpus UNCHANGED — no
new partitioner code paths, so every layout invariant they pin still
holds.  Model-state rows follow the same relabeling (the adapters in
:mod:`harp_tpu.elastic.apps` move them — factor tables over the
``reshard`` wire via :mod:`harp_tpu.elastic.move`, count tables by
exact host reconstruction from the preserved chain state).
"""

from __future__ import annotations

import numpy as np

from harp_tpu import schedule
from harp_tpu.utils.skew import SkewLedger


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def wasted_frac(loads) -> float:
    """The SkewLedger imbalance model on a per-worker load vector:
    the fraction of total chip-time idle-waiting at the superstep
    barrier, ``1 - mean/max`` (0.0 for empty/zero loads)."""
    w = np.asarray(loads, np.float64)
    mx = float(w.max()) if w.size else 0.0
    if mx <= 0:
        return 0.0
    return float(1.0 - w.mean() / mx)


class Packs:
    """Contiguous id-range packs over ``[0, n_ids)``.

    ``per_worker`` packs per HOME worker: worker ``w``'s ownership range
    ``[w·own, (w+1)·own)`` (``own = ceil(n_ids / n_home)`` — the exact
    rule every partitioner uses) splits into ``per_worker`` equal-width
    sub-ranges.  Pack ids are stable across any later assignment; the
    id→pack map is pure arithmetic, so pack loads are one ``bincount``
    over the corpus.
    """

    def __init__(self, n_ids: int, n_home: int, per_worker: int = 4):
        if n_ids < 1 or n_home < 1 or per_worker < 1:
            raise ValueError(
                f"need n_ids/n_home/per_worker >= 1, got "
                f"{n_ids}/{n_home}/{per_worker}")
        self.n_ids = int(n_ids)
        self.n_home = int(n_home)
        self.per_worker = int(per_worker)
        self.own = _ceil_div(self.n_ids, self.n_home)
        self.width = _ceil_div(self.own, self.per_worker)
        self.n_packs = self.n_home * self.per_worker
        ranges = []
        for pid in range(self.n_packs):
            w, j = divmod(pid, self.per_worker)
            lo = w * self.own + j * self.width
            hi = min(lo + self.width, (w + 1) * self.own, self.n_ids)
            ranges.append((min(lo, self.n_ids), max(min(lo, self.n_ids),
                                                    min(hi, self.n_ids))))
        self.ranges = ranges

    def pack_of(self, ids) -> np.ndarray:
        ids = np.asarray(ids)
        w = ids // self.own
        j = np.minimum((ids - w * self.own) // self.width,
                       self.per_worker - 1)
        return w * self.per_worker + j

    def loads(self, ids) -> np.ndarray:
        """Per-pack item counts for a corpus keyed by these ids."""
        return np.bincount(self.pack_of(ids),
                           minlength=self.n_packs).astype(np.float64)

    def widths(self) -> np.ndarray:
        return np.asarray([hi - lo for lo, hi in self.ranges], np.int64)

    def home_assignment(self) -> np.ndarray:
        """pack → its home worker (the non-elastic layout, exactly)."""
        return np.arange(self.n_packs) // self.per_worker


class IdRemap:
    """Bijective relabeling realizing a pack assignment as block
    partition.

    Worker ``w`` hosts its assigned packs' ids consecutively from
    ``w · bound`` (packs in ascending pack-id order — deterministic, so
    a survivors-only comparison run derives the identical layout);
    ``bound = max_w Σ widths`` so every worker fits, and the remapped
    id space is ``n_workers · bound`` (the trailing slots per worker
    are virtual pads no corpus item ever maps to).  ``fwd[old] = new``
    covers every original id; ``inv[new] = old`` is -1 on pads.
    """

    def __init__(self, packs: Packs, assignment, n_workers: int):
        asg = np.asarray(assignment, np.int64)
        if asg.shape != (packs.n_packs,):
            raise ValueError(
                f"assignment must map all {packs.n_packs} packs, got "
                f"shape {asg.shape}")
        if asg.min() < 0 or asg.max() >= n_workers:
            raise ValueError(
                f"assignment names workers outside [0, {n_workers})")
        widths = packs.widths()
        per_w = [np.flatnonzero(asg == w) for w in range(n_workers)]
        totals = [int(widths[p].sum()) for p in per_w]
        self.bound = max(1, max(totals))
        self.new_n = n_workers * self.bound
        fwd = np.full(packs.n_ids, -1, np.int64)
        for w, pids in enumerate(per_w):
            off = 0
            for pid in pids:
                lo, hi = packs.ranges[pid]
                if hi > lo:
                    fwd[lo:hi] = w * self.bound + off + np.arange(hi - lo)
                    off += hi - lo
        assert (fwd >= 0).all(), "remap did not cover the id space"
        self.fwd = fwd
        inv = np.full(self.new_n, -1, np.int64)
        inv[fwd] = np.arange(packs.n_ids)
        self.inv = inv


def worker_loads(assignment, pack_loads, n_workers: int) -> np.ndarray:
    return np.bincount(np.asarray(assignment, np.int64),
                       weights=np.asarray(pack_loads, np.float64),
                       minlength=n_workers)


def splits_of(assignment, n_workers: int) -> list[list[int]]:
    """Per-worker pack-id lists (ascending) — the
    ``schedule.apply_rebalance`` splits shape."""
    asg = np.asarray(assignment, np.int64)
    return [[int(p) for p in np.flatnonzero(asg == w)]
            for w in range(n_workers)]


def pack_units(assignment, pack_loads, n_workers: int) -> list[list[tuple]]:
    """Per-worker ``(pack_id, load)`` grains — the SkewLedger ``units=``
    payload the sentinel's whole-unit plan is built from."""
    loads = np.asarray(pack_loads, np.float64)
    return [[(pid, float(loads[pid])) for pid in lst]
            for lst in splits_of(assignment, n_workers)]


def replay_repartition(packs: Packs, pack_loads, stored_assignment,
                       n_workers: int, phase: str
                       ) -> tuple[np.ndarray, dict | None]:
    """Derive the survivors' repartition by REPLAYING the same plan
    machinery mid-run rebalance uses (PR 15 Layer 2).

    The stored assignment may name workers outside the survivor range
    (a checkpoint written pre-shrink), so it first folds deterministically
    onto the survivors (``worker % n``); a throwaway SkewLedger then
    records the folded layout's pack grains, ``suggest_rebalance`` emits
    the whole-unit plan, and ``schedule.apply_rebalance`` replays it —
    the exact pipeline a skew trigger rides, forced whole-unit.  Pure
    function of (packs, loads, stored assignment, n): the elastic resume
    and an uninterrupted survivors-only run from the same checkpoint
    derive BIT-identical layouts (the worker-loss drill's pin).
    """
    folded = np.asarray(stored_assignment, np.int64) % n_workers
    led = SkewLedger()  # throwaway: never feeds the sentinel
    led.record_partition(
        phase, worker_loads(folded, pack_loads, n_workers), unit="load",
        units=pack_units(folded, pack_loads, n_workers))
    plan = led.suggest_rebalance(phase)
    if plan is None or not plan["moves"]:
        return folded, plan
    asg_map = schedule.rebalance_assignment(
        splits_of(folded, n_workers), plan)
    return np.asarray([asg_map[p] for p in range(packs.n_packs)],
                      np.int64), plan


def maybe_rebalance(adapter) -> dict | None:
    """The superstep-boundary hook (PR 15 Layer 1): consume a latched
    ``skew_trigger`` for ``adapter.phase`` and act on it.

    Consumes exactly once per fired trigger (the sentinel handshake —
    no double-apply), replays the inline plan through
    ``schedule.apply_rebalance`` over the adapter's current pack
    splits, and applies the resulting assignment only when the
    projected ``wasted_frac`` actually improves (a plan that cannot
    help — e.g. one giant indivisible pack — is consumed and dropped,
    so a still-skewed phase never thrashes).  Returns the recorded
    ``kind:"elastic"`` rebalance row, or None when there was nothing
    to do (no trigger, telemetry off, fractional plan, no improvement).
    """
    from harp_tpu import health
    from harp_tpu.elastic import ledger as eledger

    row = health.monitor.consume_skew_trigger(adapter.phase)
    if row is None:
        return None
    plan = row.get("plan")
    if (not isinstance(plan, dict) or not plan.get("moves")
            or not all("id" in m for m in plan["moves"])):
        return None  # fractional or empty plan: nothing whole-unit
    n = adapter.mesh.num_workers
    asg_map = schedule.rebalance_assignment(
        splits_of(adapter.assignment, n), plan)
    new_asg = np.asarray([asg_map[p] for p in range(adapter.packs.n_packs)],
                         np.int64)
    before = worker_loads(adapter.assignment, adapter.loads, n)
    after = worker_loads(new_asg, adapter.loads, n)
    wf_b, wf_a = wasted_frac(before), wasted_frac(after)
    if wf_a >= wf_b:
        return None  # the move cannot help; keep the layout
    adapter.apply_assignment(new_asg)
    return eledger.record(
        "rebalance", adapter.phase,
        n_workers=n, moves=len(plan["moves"]),
        loads_before=[round(float(x), 4) for x in before],
        loads_after=[round(float(x), 4) for x in after],
        total=round(float(before.sum()), 4),
        wasted_frac_before=round(wf_b, 4),
        wasted_frac_after=round(wf_a, 4),
        trigger_supersteps=int(row.get("supersteps", 0)))
