"""Row movement over the reshard wire (PR 15).

A rebalance relabels external ids, so model-state rows must land on
their packs' new owners.  An arbitrary row permutation is not a
``ShardSpec``→``ShardSpec`` move, but it IS expressible as the verb's
always-legal fallback split in two: ``reshard(blocked(0) → replicated)``
(the one all_gather on the wire) followed by a purely LOCAL gather of
each worker's new rows — so the whole move rides the existing
``reshard`` verb, records in the CommLedger like every other collective,
and the registered ``elastic.regather`` driver program keeps it on the
CommGraph byte sheet (HL301/HL302-checked on every full lint).  No new
collectives.

Bit-exact: the exact wire moves f32/int rows untouched, and the local
gather is a permutation — :func:`regather_rows` equals the host
``np.take`` path element-for-element (pinned in tests/test_elastic.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.parallel.collective import ShardSpec, reshard
from harp_tpu.parallel.mesh import WorkerMesh


def make_regather_fn(mesh: WorkerMesh, ndim: int = 2):
    """The jitted regather program: ``(x blocked(0), rows blocked(0)) →
    out blocked(0)`` with ``out[i] = full(x)[rows[i]]`` (0 for
    ``rows[i] < 0`` — the new layout's pad slots own no old row).
    Registered as the ``elastic.regather`` driver so the lint byte
    sheet prices the one all_gather the move costs."""

    def gather(xs, rs):
        full = reshard(xs, ShardSpec.blocked(0), ShardSpec.replicated())
        safe = jnp.clip(rs, 0, full.shape[0] - 1)
        out = jnp.take(full, safe, axis=0)
        keep = (rs >= 0).reshape((-1,) + (1,) * (out.ndim - 1))
        return jnp.where(keep, out, jnp.zeros((), out.dtype))

    return jax.jit(mesh.shard_map(
        gather,
        in_specs=(mesh.spec(0, ndim=ndim), mesh.spec(0, ndim=1)),
        out_specs=mesh.spec(0, ndim=ndim)))


def regather_rows(mesh: WorkerMesh, x, new_rows):
    """Move table rows into a new dim-0-sharded layout over the reshard
    wire.

    ``x``: a dim-0-sharded device array (rows divisible by the mesh).
    ``new_rows``: host int array, one entry per OUTPUT row (length a
    worker multiple): the global OLD row index that lands there, or -1
    for a pad slot (zero-filled).  Output length may differ from the
    input's — a rebalanced layout usually has a different ``bound``.
    """
    from harp_tpu.utils import flightrec, telemetry

    nr = np.asarray(new_rows, np.int32)
    n = mesh.num_workers
    if nr.ndim != 1 or nr.shape[0] % n:
        raise ValueError(
            f"new_rows must be 1-D with length a multiple of {n} "
            f"workers, got shape {nr.shape}")
    fn = flightrec.track(make_regather_fn(mesh, ndim=np.ndim(x)),
                         "elastic.regather")
    with telemetry.span("elastic.regather", rows=int(nr.shape[0])), \
            telemetry.ledger.run("elastic.regather", steps=1):
        return fn(x, mesh.shard_array(nr, 0))
