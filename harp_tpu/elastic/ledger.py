"""Elastic-execution evidence — provenance-stamped ``kind:"elastic"``
rows (scripts/check_jsonl.py invariant 14).

One row per elastic ACTION, in the order they happened:

- ``rebalance`` — a consumed ``skew_trigger`` moved packs between
  workers mid-run: per-worker ``loads_before``/``loads_after`` (both
  summing to ``total`` — moves conserve work) and
  ``wasted_frac_before``/``wasted_frac_after`` (the SkewLedger
  imbalance model; after ≤ before, or the move is refused and no row
  lands);
- ``shrink`` — a permanent worker loss removed a worker:
  ``n_workers_after < n_workers_before``, the lost worker's index, the
  injection site/ordinal, and ``capacity_frac`` (the degraded-throughput
  statement: the run continues at survivors/pre-fault capacity);
- ``resume`` — a rebuild from a crash-atomic checkpoint completed:
  survivor count, the replayed per-worker ``loads`` (summing to
  ``total``), the resulting ``wasted_frac``, and whether a repartition
  plan was replayed (post-shrink) or the stored assignment reused
  (same-mesh restart).

Rows are recorded unconditionally (they describe ACTIONS, not
observations — the zero-cost-when-disabled contract governs the
sentinel that *triggers* them, not the evidence that they happened) and
exported through ``telemetry.export`` with the flight recorder's
provenance stamp, so a CPU-sim drill can never read as relay evidence
(the invariant-4 inversion guard).
"""

from __future__ import annotations

import json

#: frozen event vocabulary — check_jsonl KNOWN_ELASTIC_EVENTS mirrors
#: this tuple (drift fails tier-1 via tests/test_check_jsonl.py)
EVENTS = ("rebalance", "shrink", "resume")


class ElasticLedger:
    """Append-only action log; one dict per event (see module doc)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.rows: list[dict] = []

    def record(self, event: str, phase: str, **fields) -> dict:
        if event not in EVENTS:
            raise ValueError(f"event {event!r} not in {EVENTS}")
        # on_timeline stamps whether a steptrace run covered this event
        # (PR 18) — invariant 16 reconciles covered rows against the
        # timeline's elastic marks EXACTLY in both directions, while a
        # row recorded outside any run (e.g. a manual install() for a
        # bit-identity comparison) is legitimately unmarked
        from harp_tpu.utils import steptrace

        covered = steptrace.tracer._run is not None
        row = {"kind": "elastic", "event": event, "phase": phase,
               "on_timeline": covered, **fields}
        self.rows.append(row)
        if covered:
            steptrace.tracer.on_elastic(event, phase, row)
        return row

    def export_jsonl(self, fh, stamp: dict | None = None) -> None:
        for row in self.rows:
            fh.write(json.dumps({**row, **(stamp or {})}) + "\n")


# ---------------------------------------------------------------------------
# Module singleton + hooks (the other spines' shape)
# ---------------------------------------------------------------------------

ledger = ElasticLedger()


def reset() -> None:
    """Clear the ledger (telemetry.scope does this on entry)."""
    ledger.reset()


def record(event: str, phase: str, **fields) -> dict:
    """Module-level shorthand for :meth:`ElasticLedger.record`."""
    return ledger.record(event, phase, **fields)


def export_jsonl(fh) -> None:
    """Append elastic rows (telemetry.export calls this); stamped with
    the flight recorder's provenance triple."""
    if not ledger.rows:
        return
    from harp_tpu.utils import flightrec

    ledger.export_jsonl(fh, flightrec.provenance_stamp())
