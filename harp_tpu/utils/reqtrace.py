"""Request-level tracing — one causal timeline across the serve plane.

Reference parity (SURVEY.md §6): Harp's observability never follows a
unit of work end to end — container logs record iterations, not
requests.  harp-tpu's four telemetry spines (CommLedger, SpanTracer,
flight recorder, SkewLedger) each answer one question about a RUN; this
module answers the serving question none of them can: *what happened to
THIS request* between socket arrival and response delivery.  HARP
(PAPERS.md arXiv:2509.24859) makes orchestration decisions off exactly
this per-job end-to-end timing evidence; DrJAX (arXiv:2403.07128)
argues for keeping the whole pipeline legible as one instrumented
program — here that program is the continuous serve plane.

Three cooperating pieces:

**ReqTracer** — per-request span trees.  A trace id is minted at
transport arrival (:func:`arrive`; the sustained bench mints at
admission) and threaded through the
:class:`~harp_tpu.serve.server.ContinuousRunner`: admission, queueing,
batch membership (which scheduler batch carried which row slice, at
what padding share), dispatch, readback, reassembly, delivery — plus
every PR-10 degradation event (queue_full / deadline shed, retry-with-
restage, engine failure), so every offered request ends in exactly one
terminal outcome ∈ {served, shed, failed} and the trace reconciles
EXACTLY with the invariant-9 degraded-mode ledger
(scripts/check_jsonl.py invariant 11 enforces both).  Batches get their
own records (seq, rung, rows, dispatch/readback times, member slices) —
the other half of the causal join.  Timestamps are whatever clock the
caller drives the runner with (wall perf_counter on the TCP plane, the
virtual replay clock in ``benchmark_sustained``), so a trace is
causally ordered within its run by construction.

**LogHist / RollingWindow** — streaming percentiles in bounded memory.
Fixed log-spaced buckets (ratio :data:`HIST_RATIO` per bucket), so a
quantile read is exact to within the documented bucket error
:data:`QUANTILE_REL_ERR` (the geometric bucket midpoint is at most
``sqrt(ratio) - 1`` ≈ 9.1% from any sample in the bucket) and memory is
a fixed few KiB no matter how long the server runs — no retained
samples.  :class:`RollingWindow` keeps a ring of sub-window histogram
pairs (latency + queue depth) and expires them by time, so a sustained
run reports LIVE windowed p50/p95/p99 through the TCP ``stats`` control
line and the ``benchmark_sustained`` row (``win_*`` fields).

**Exporters** — :func:`export_jsonl` writes the collected spans as
provenance-stamped ``kind:"trace"`` rows (ridden by
``telemetry.export`` / ``HARP_TELEMETRY_OUT``), :func:`perfetto`
converts trace rows into a Chrome/Perfetto ``trace.json``
(chrome://tracing and https://ui.perfetto.dev both load the Trace Event
JSON format directly), and :func:`main` is the ``python -m harp_tpu
trace <run.jsonl>`` CLI: validate, summarize, export.

Zero-cost when disabled (the PR-3 contract): every entry point returns
before touching state unless telemetry is enabled
(``HARP_TELEMETRY=1`` / :func:`telemetry.enable`), nothing here ever
touches a traced program or adds a device op, so the flagship serve
budgets (1 dispatch / 1 readback / 0 steady compiles per batch) are
bit-identical with tracing armed or off — pinned in
tests/test_reqtrace.py.  The rolling histograms are part of the
runner's stats surface (like its latency deque) and stay on; they are
host-side O(1) per sample.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Any

from harp_tpu.utils import telemetry

#: terminal request outcomes — the invariant-11 vocabulary (frozen in
#: scripts/check_jsonl.py as KNOWN_TRACE_OUTCOMES; drift fails tier-1)
OUTCOMES = ("served", "shed", "failed")

# ---------------------------------------------------------------------------
# Streaming histograms
# ---------------------------------------------------------------------------

#: per-bucket growth ratio of the log histogram.  2^(1/4) ≈ 1.189: nine
#: decades of latency (1 µs … 1000 s) fit in ~126 buckets at a bounded
#: relative quantile error — the EXPLICIT bucket-error contract callers
#: (and the acceptance test) hold the rolling p99 to.
HIST_RATIO = 2.0 ** 0.25

#: documented quantile error bound: a quantile read returns its
#: bucket's geometric midpoint, at most sqrt(HIST_RATIO) - 1 (≈ 9.1%)
#: from any sample that landed in the bucket.
QUANTILE_REL_ERR = HIST_RATIO ** 0.5 - 1.0


class LogHist:
    """Fixed log-bucket histogram — bounded memory, no retained samples.

    Buckets are ``lo * HIST_RATIO**i`` for ``i in [0, n_buckets)``; one
    underflow bucket catches values ``<= lo`` (zeros included — a queue
    depth of 0 is a real sample) and reads back as exactly 0.0, the
    last bucket clamps overflow.  ``quantile`` returns the geometric
    midpoint of the bucket holding the requested rank — within
    :data:`QUANTILE_REL_ERR` of the exact sample percentile whenever
    the rank lands inside the histogram's range.
    """

    __slots__ = ("lo", "n", "counts", "total", "_log_lo", "_log_r")

    def __init__(self, lo: float = 1e-3, n_buckets: int = 128):
        if lo <= 0 or n_buckets < 2:
            raise ValueError(f"need lo > 0 and >= 2 buckets, got "
                             f"lo={lo} n_buckets={n_buckets}")
        self.lo = float(lo)
        self.n = int(n_buckets)
        self.counts = [0] * (self.n + 1)  # [underflow] + n log buckets
        self.total = 0
        self._log_lo = math.log(self.lo)
        self._log_r = math.log(HIST_RATIO)

    def add(self, v: float) -> None:
        if v <= self.lo:
            i = 0
        else:
            i = 1 + min(self.n - 1,
                        int((math.log(v) - self._log_lo) / self._log_r))
        self.counts[i] += 1
        self.total += 1

    def merge_into(self, acc: list[int]) -> int:
        """Add this histogram's counts into ``acc`` (the rolling-window
        merge); returns this histogram's total."""
        for i, c in enumerate(self.counts):
            acc[i] += c
        return self.total

    @staticmethod
    def quantile_of(counts: list[int], total: int, lo: float,
                    p: float) -> float | None:
        """Quantile over a (possibly merged) bucket-count vector."""
        if total <= 0:
            return None
        rank = max(1, math.ceil(p / 100.0 * total))
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank:
                if i == 0:
                    return 0.0
                return lo * HIST_RATIO ** (i - 1) * HIST_RATIO ** 0.5
        return lo * HIST_RATIO ** (len(counts) - 2)  # pragma: no cover

    def quantile(self, p: float) -> float | None:
        return self.quantile_of(self.counts, self.total, self.lo, p)


class RollingWindow:
    """Time-rolling latency + queue-depth percentiles, bounded memory.

    A ring of ``subwindows`` histogram pairs, each covering
    ``window_s / subwindows`` of the driving clock; a sample lands in
    the sub-window its timestamp selects and whole sub-windows expire
    as the clock advances — so :meth:`snapshot` always describes the
    most recent ``window_s`` (±one sub-window of quantization) without
    retaining a single sample.  The driving clock is the runner's
    (wall-time on the TCP plane, virtual in the sustained replay).
    """

    def __init__(self, window_s: float = 60.0, subwindows: int = 6,
                 lat_lo_ms: float = 1e-3, depth_lo: float = 0.5):
        if window_s <= 0 or subwindows < 1:
            raise ValueError(f"need window_s > 0 and >= 1 subwindow, "
                             f"got {window_s}/{subwindows}")
        self.window_s = float(window_s)
        self.sub_s = self.window_s / int(subwindows)
        self.k = int(subwindows)
        self.lat_lo_ms = lat_lo_ms
        self.depth_lo = depth_lo
        # ring slot -> (epoch, lat LogHist, depth LogHist); epoch is the
        # absolute sub-window index, so a stale slot is detected (not
        # merged) without ever scanning or clearing on the hot path
        self._ring: list[tuple[int, LogHist, LogHist] | None] = \
            [None] * self.k

    def _slot(self, now: float) -> tuple[int, LogHist, LogHist]:
        epoch = int(now / self.sub_s)
        i = epoch % self.k
        cur = self._ring[i]
        if cur is None or cur[0] != epoch:
            cur = (epoch, LogHist(self.lat_lo_ms), LogHist(self.depth_lo))
            self._ring[i] = cur
        return cur

    def add_latency(self, now: float, ms: float) -> None:
        self._slot(now)[1].add(ms)

    def add_qdepth(self, now: float, depth: float) -> None:
        self._slot(now)[2].add(depth)

    def _merged(self, now: float, which: int) -> tuple[list[int], int,
                                                       float]:
        epoch_now = int(now / self.sub_s)
        lo = self.lat_lo_ms if which == 1 else self.depth_lo
        acc = [0] * (LogHist(lo).n + 1)
        total = 0
        for cur in self._ring:
            if cur is not None and epoch_now - cur[0] < self.k:
                total += cur[which].merge_into(acc)
        return acc, total, lo

    def snapshot(self, now: float) -> dict:
        """Live windowed percentiles (None before any sample)."""
        out: dict[str, Any] = {"window_s": self.window_s,
                               "rel_err": round(QUANTILE_REL_ERR, 4)}
        for which, prefix, unit in ((1, "p", "_ms"), (2, "qdepth_p", "")):
            acc, total, lo = self._merged(now, which)
            out["samples" if which == 1 else "qdepth_samples"] = total
            for p in (50, 95, 99):
                q = LogHist.quantile_of(acc, total, lo, p)
                out[f"{prefix}{p}{unit}"] = (None if q is None
                                             else round(q, 4))
        return out


# ---------------------------------------------------------------------------
# ReqTracer
# ---------------------------------------------------------------------------

class ReqTracer:
    """Request span trees + batch records + free timeline marks.

    All entry points are no-ops while telemetry is disabled; ids are a
    process-local monotone counter (deterministic — no wall entropy),
    so a seeded replay yields the same trace twice.  Collection is
    unbounded by design: tracing is for instrumented runs (the rolling
    histograms are the bounded-memory surface for always-on stats).

    Thread safety (PR 20, HL403): on the TCP plane the event-loop
    thread mints ids at arrival while the dispatcher thread stamps
    deliver/outcome events — two writer roots on one spine, so every
    mutator takes ``self._lock`` (an RLock: :meth:`end` records its
    outcome event through :meth:`event`).  The lock sits AFTER the
    telemetry-enabled early return, so the disabled path stays
    zero-cost; harplint's thread-root layer verifies the lock is
    present (a spine written from ≥2 roots without it is HL403) and
    threadguard skips wrapping verified-locked spines at runtime.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self.reset()

    def reset(self) -> None:
        self._next_id = 0
        # rid -> {"req","t0","t_last","events":[{name,ts,...}],"outcome"}
        self._reqs: dict[int, dict] = {}
        # batch seq -> {"seq","rung","rows","padding_frac","members",
        #               "events":[...]}
        self._batches: dict[int, dict] = {}
        self.marks: list[dict] = []   # free events (fault plane, ...)
        self.counts = {o: 0 for o in OUTCOMES}

    # -- request spans -----------------------------------------------------
    def begin(self, ts: float, **attrs: Any) -> int | None:
        """Mint a trace id and open its span with an ``arrival`` event.
        Returns None (and records nothing) while telemetry is off."""
        if not telemetry.enabled():
            return None
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            ev = {"name": "arrival", "ts": float(ts)}
            if attrs:
                ev.update(attrs)
            self._reqs[rid] = {"req": rid, "t0": float(ts),
                               "t_last": float(ts),
                               "events": [ev], "outcome": None}
            return rid

    def event(self, rid: int | None, name: str, ts: float,
              **attrs: Any) -> None:
        """Append one event to an open (or already-terminated — e.g.
        ``deliver`` after ``served``) request span; unknown/None ids are
        ignored so tracing may arm mid-run without raising."""
        if rid is None or not telemetry.enabled():
            return
        with self._lock:
            r = self._reqs.get(rid)
            if r is None:
                return
            ev = {"name": name, "ts": float(ts)}
            if attrs:
                ev.update(attrs)
            r["events"].append(ev)
            r["t_last"] = max(r["t_last"], float(ts))

    def end(self, rid: int | None, outcome: str, ts: float,
            **attrs: Any) -> None:
        """Terminate a request span with its outcome (once; a second
        end on the same id is ignored — outcomes never flip)."""
        if rid is None or not telemetry.enabled():
            return
        if outcome not in OUTCOMES:
            raise ValueError(f"outcome {outcome!r} not in {OUTCOMES}")
        with self._lock:
            r = self._reqs.get(rid)
            if r is None or r["outcome"] is not None:
                return
            self.event(rid, outcome, ts, **attrs)
            r["outcome"] = outcome
            self.counts[outcome] += 1

    # -- batch records -----------------------------------------------------
    def batch(self, seq: int, ts: float, *, rung: int, rows: int,
              members: list[tuple[int | None, int, int]]) -> None:
        """Open one scheduler batch's record: ``members`` is
        [(trace_id, row_lo, row_hi)] — the request→batch join."""
        if not telemetry.enabled():
            return
        self._batches[seq] = {
            "seq": int(seq), "t0": float(ts), "rung": int(rung),
            "rows": int(rows),
            "padding_frac": round((rung - rows) / rung, 6) if rung else 0.0,
            "members": [[m if m is not None else -1, lo, hi]
                        for m, lo, hi in members],
            "events": [{"name": "form", "ts": float(ts)}]}

    def batch_event(self, seq: int, name: str, ts: float,
                    **attrs: Any) -> None:
        if not telemetry.enabled():
            return
        b = self._batches.get(seq)
        if b is None:
            return
        ev = {"name": name, "ts": float(ts)}
        if attrs:
            ev.update(attrs)
        b["events"].append(ev)

    def batch_event_count(self, name: str) -> int:
        """How many batch events named ``name`` the trace holds (the
        chaos-completeness tests count ``retry``/``engine_failure``)."""
        return sum(1 for b in self._batches.values()
                   for ev in b["events"] if ev["name"] == name)

    # -- free marks (fault plane etc.) -------------------------------------
    def mark(self, source: str, name: str, ts: float, **attrs: Any) -> None:
        if not telemetry.enabled():
            return
        with self._lock:
            m = {"source": source, "name": name, "ts": float(ts)}
            if attrs:
                m.update(attrs)
            self.marks.append(m)

    # -- reading / export --------------------------------------------------
    def summary(self) -> dict:
        open_spans = sum(1 for r in self._reqs.values()
                         if r["outcome"] is None)
        return {"requests": len(self._reqs), "open": open_spans,
                "batches": len(self._batches), **self.counts}

    def rows(self) -> list[dict]:
        """The trace as ``kind:"trace"`` rows, sorted by ``ts`` (the
        invariant-11 monotonicity contract).  Three row shapes share the
        kind, split by ``ev``: per-request ``event`` rows, one terminal
        ``request`` row per span (ts = its last event), and one
        ``batch`` row per scheduler batch (ts = its last event,
        carrying the member slices and dispatch/readback events)."""
        out: list[dict] = []
        for r in self._reqs.values():
            for ev in r["events"]:
                out.append({"kind": "trace", "ev": "event", "req": r["req"],
                            **ev})
            out.append({"kind": "trace", "ev": "request", "req": r["req"],
                        "ts": r["t_last"], "t0": r["t0"],
                        "outcome": r["outcome"],
                        "n_events": len(r["events"])})
        for b in self._batches.values():
            out.append({"kind": "trace", "ev": "batch",
                        "ts": max(ev["ts"] for ev in b["events"]), **b})
        for m in self.marks:
            out.append({"kind": "trace", "ev": "mark", **m})
        # stable causal order: ts first, then terminal rows after their
        # own events (event < request), batches after the events they
        # carried, marks wherever their clock put them
        rank = {"event": 0, "mark": 1, "batch": 2, "request": 3}
        out.sort(key=lambda r: (r["ts"], rank[r["ev"]]))
        return out

    def export_jsonl(self, fh) -> None:
        """Provenance-stamped trace rows (telemetry.export rides this —
        a CPU-sim request timeline must never read as relay latency
        evidence, same inversion guard as the flight recorder)."""
        rows = self.rows()
        if not rows:
            return
        from harp_tpu.utils.flightrec import provenance_stamp

        stamp = provenance_stamp()
        for row in rows:
            fh.write(json.dumps({**row, **stamp}) + "\n")


tracer = ReqTracer()


def reset() -> None:
    """Clear the request tracer (telemetry.scope does this on entry)."""
    tracer.reset()


def arrive(ts: float, **attrs: Any) -> int | None:
    """Mint a trace id at transport arrival (module-level shorthand)."""
    return tracer.begin(ts, **attrs)


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------

_PID_REQ, _PID_BATCH, _PID_MARK = 1, 2, 3


def perfetto(rows: list[dict]) -> dict:
    """Convert ``kind:"trace"`` rows into Chrome Trace Event JSON.

    Loadable by chrome://tracing and ui.perfetto.dev as-is: request
    spans are ``X`` (complete) events on one track per request (pid 1),
    batches are ``X`` events from form to readback on a
    pipeline-depth-folded track (pid 2, tid = seq % 4 so the depth-2
    overlap is visible instead of stacked), and degradation/fault
    events are instants (``i``).  Timestamps are microseconds from the
    earliest row (the Trace Event format's unit).  The event plumbing
    is the shared :mod:`harp_tpu.utils.perfetto` builder (PR 18).
    """
    from harp_tpu.utils import perfetto as pft

    trace_rows = [r for r in rows if r.get("kind") == "trace"]
    if not trace_rows:
        return pft.empty()
    b = pft.TraceBuilder(min(float(r["ts"]) for r in trace_rows))
    b.process(_PID_REQ, "requests")
    b.process(_PID_BATCH, "batches")
    b.process(_PID_MARK, "events")
    by_req: dict[int, list[dict]] = {}
    for r in trace_rows:
        ev = r.get("ev")
        if ev == "event" and "req" in r:
            by_req.setdefault(r["req"], []).append(r)
        elif ev == "request":
            b.complete(f"req {r['req']} [{r.get('outcome')}]",
                       _PID_REQ, r["req"], r.get("t0", r["ts"]), r["ts"],
                       args={"outcome": r.get("outcome"),
                             "n_events": r.get("n_events")})
        elif ev == "batch":
            evs = r.get("events") or []
            t_open = float(r.get("t0", r["ts"]))
            t_close = max((float(e["ts"]) for e in evs),
                          default=float(r["ts"]))
            b.complete(f"batch {r['seq']} rung={r.get('rung')}",
                       _PID_BATCH, int(r["seq"]) % 4, t_open, t_close,
                       args={"rows": r.get("rows"),
                             "padding_frac": r.get("padding_frac"),
                             "members": r.get("members")})
            for e in evs:
                if e["name"] in ("retry", "engine_failure"):
                    b.instant(f"{e['name']} (batch {r['seq']})",
                              _PID_BATCH, int(r["seq"]) % 4, e["ts"])
        elif ev == "mark":
            b.instant(f"{r.get('source')}:{r.get('name')}", _PID_MARK, 1,
                      r["ts"],
                      args={k: v for k, v in r.items()
                            if k not in ("kind", "ev", "ts")})
    # per-request instants for the interesting intermediate hops
    for rid, evs in by_req.items():
        for e in evs:
            if e["name"] in ("shed", "failed", "batch", "deliver"):
                b.instant(e["name"], _PID_REQ, rid, e["ts"], scope="t",
                          args={k: v for k, v in e.items()
                                if k not in ("kind", "ev", "ts", "name")})
    return b.build()


# ---------------------------------------------------------------------------
# Trace-file summary + CLI
# ---------------------------------------------------------------------------

def summarize_rows(rows: list[dict]) -> dict:
    """Validate + summarize loaded trace rows (the CLI's core and the
    report's from-file section).  Mirrors invariant 11's span checks:
    every request seen in event rows must have a terminal row with a
    known outcome."""
    reqs: dict[int, dict] = {}
    seen: set[int] = set()
    batches = 0
    marks = 0
    bad_outcomes = []
    for r in rows:
        ev = r.get("ev")
        if ev == "event" and "req" in r:
            seen.add(r["req"])
        elif ev == "request":
            if r.get("outcome") not in OUTCOMES:
                bad_outcomes.append(r.get("req"))
            reqs[r["req"]] = r
        elif ev == "batch":
            batches += 1
        elif ev == "mark":
            marks += 1
    unterminated = sorted(seen - set(reqs))
    counts = {o: sum(1 for r in reqs.values() if r.get("outcome") == o)
              for o in OUTCOMES}
    lat = sorted((r["ts"] - r["t0"]) * 1e3 for r in reqs.values()
                 if r.get("outcome") == "served" and "t0" in r)
    out = {"requests": len(reqs), "batches": batches, "marks": marks,
           **counts, "unterminated": unterminated,
           "bad_outcomes": bad_outcomes}
    if lat:
        out["served_p50_ms"] = round(
            lat[min(len(lat) - 1, int(0.50 * len(lat)))], 4)
        out["served_p99_ms"] = round(
            lat[min(len(lat) - 1, int(0.99 * len(lat)))], 4)
    return out


def _render(rows: list[dict], summary: dict, max_requests: int = 20) -> str:
    lines = ["== harp-tpu request trace =="]
    lines.append(
        f"{summary['requests']} request(s): {summary['served']} served / "
        f"{summary['shed']} shed / {summary['failed']} failed; "
        f"{summary['batches']} batch(es), {summary['marks']} mark(s)")
    if summary.get("served_p50_ms") is not None:
        lines.append(f"served latency p50 {summary['served_p50_ms']} ms, "
                     f"p99 {summary['served_p99_ms']} ms")
    if summary["unterminated"]:
        lines.append(f"UNTERMINATED spans: {summary['unterminated']}")
    by_req: dict[int, list[dict]] = {}
    outcomes: dict[int, str] = {}
    for r in rows:
        if r.get("ev") == "event" and "req" in r:
            by_req.setdefault(r["req"], []).append(r)
        elif r.get("ev") == "request":
            outcomes[r["req"]] = r.get("outcome")
    for rid in sorted(by_req)[:max_requests]:
        evs = by_req[rid]
        t0 = evs[0]["ts"]
        lines.append(f"req {rid} [{outcomes.get(rid, '?')}]:")
        for e in evs:
            extra = {k: v for k, v in e.items()
                     if k not in ("kind", "ev", "req", "name", "ts",
                                  "backend", "date", "commit")}
            note = f"  {extra}" if extra else ""
            lines.append(f"  +{(e['ts'] - t0) * 1e3:9.3f} ms  "
                         f"{e['name']}{note}")
    if len(by_req) > max_requests:
        lines.append(f"... {len(by_req) - max_requests} more request(s) "
                     "(use --perfetto for the full timeline)")
    return "\n".join(lines)


def main(argv=None) -> int:
    """``python -m harp_tpu trace run.jsonl`` — validate + summarize a
    trace export, optionally writing the Perfetto ``trace.json``.

    Exit codes: 0 clean, 1 the trace is incomplete (unterminated spans
    or unknown outcomes — the same defects invariant 11 rejects), 2
    usage / unreadable input.
    """
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m harp_tpu trace",
        description="request-level timeline: validate + summarize a "
                    "kind:'trace' JSONL export (telemetry.export / "
                    "HARP_TELEMETRY_OUT), export Chrome/Perfetto JSON")
    p.add_argument("jsonl", help="trace JSONL (telemetry.export output "
                                 "or a pure export_timeline file)")
    p.add_argument("--perfetto", metavar="OUT", default=None,
                   help="write a Chrome Trace Event JSON here (load in "
                        "chrome://tracing or ui.perfetto.dev)")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable summary line "
                        "instead of the human timeline")
    args = p.parse_args(argv)
    try:
        rows = telemetry.load_rows(args.jsonl)["trace"]
    except OSError as e:
        print(f"trace: cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    summary = summarize_rows(rows)
    if args.perfetto:
        with open(args.perfetto, "w") as fh:
            json.dump(perfetto(rows), fh)
        summary["perfetto"] = args.perfetto
    if args.json:
        from harp_tpu.utils.metrics import benchmark_json

        print(benchmark_json("trace", summary))
    else:
        print(_render(rows, summary))
    if summary["unterminated"] or summary["bad_outcomes"]:
        print(f"trace: {len(summary['unterminated'])} unterminated "
              f"span(s), {len(summary['bad_outcomes'])} unknown "
              "outcome(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover - python -m harp_tpu trace
    import sys

    sys.exit(main())
