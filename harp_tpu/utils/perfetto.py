"""Shared Chrome Trace Event plumbing for the causal tracers (PR 18).

Both timeline exporters — :func:`harp_tpu.utils.reqtrace.perfetto`
(serve plane, PR 12) and :func:`harp_tpu.utils.steptrace.perfetto`
(training plane, PR 18) — emit the same Trace Event JSON dialect that
chrome://tracing and https://ui.perfetto.dev load directly:

- one ``M`` (metadata) event naming each process track,
- ``X`` (complete) events for terminated spans, ``ts``/``dur`` in
  microseconds from the earliest row in the export,
- ``i`` (instant) events for point marks, with scope ``"g"`` (global
  line across the view) or ``"t"`` (thread-local tick).

This module is that dialect, factored out of ``reqtrace.perfetto()``
verbatim (no behavior change — the PR-12 golden_trace Perfetto test
pins the output shape): a :class:`TraceBuilder` holds the epoch ``t0``
and the growing event list; emitters append spans/instants in their
own pid/tid coordinates and call :meth:`TraceBuilder.build` for the
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` envelope.
"""

from __future__ import annotations

from typing import Any


def empty() -> dict:
    """The envelope for a rowless export (still Perfetto-loadable)."""
    return {"traceEvents": [], "displayTimeUnit": "ms"}


class TraceBuilder:
    """Accumulate Trace Event JSON events against one epoch ``t0``.

    Timestamps in are seconds on the caller's clock (wall
    ``perf_counter`` or a replay clock); timestamps out are microseconds
    from ``t0`` rounded to 3 decimals — the exact conversion the PR-12
    exporter used.
    """

    __slots__ = ("t0", "events")

    def __init__(self, t0: float = 0.0):
        self.t0 = float(t0)
        self.events: list[dict] = []

    def us(self, ts: float) -> float:
        """Seconds on the export clock → µs from the epoch."""
        return round((float(ts) - self.t0) * 1e6, 3)

    def process(self, pid: int, name: str) -> None:
        """Name a process track (``ph:"M"`` metadata event)."""
        self.events.append({"name": "process_name", "ph": "M",
                            "pid": pid, "args": {"name": name}})

    def complete(self, name: str, pid: int, tid: int, t_open: float,
                 t_close: float, args: dict[str, Any] | None = None) -> None:
        """A terminated span (``ph:"X"``); duration clamps at 0."""
        ev = {"name": name, "ph": "X", "pid": pid, "tid": int(tid),
              "ts": self.us(t_open),
              "dur": round(max(float(t_close) - float(t_open), 0.0) * 1e6,
                           3)}
        if args is not None:
            ev["args"] = args
        self.events.append(ev)

    def instant(self, name: str, pid: int, tid: int, ts: float,
                scope: str = "g",
                args: dict[str, Any] | None = None) -> None:
        """A point mark (``ph:"i"``), scope "g" global / "t" thread."""
        ev = {"name": name, "ph": "i", "s": scope, "pid": pid,
              "tid": int(tid), "ts": self.us(ts)}
        if args is not None:
            ev["args"] = args
        self.events.append(ev)

    def build(self) -> dict:
        return {"traceEvents": self.events, "displayTimeUnit": "ms"}
