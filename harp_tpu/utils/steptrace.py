"""Superstep flightpath — one causal training-plane timeline (PR 18).

Reference parity (SURVEY.md §6): Harp's unit of execution is the
Map-Collective *superstep*, but its observability never follows one —
container logs record iterations per worker with no shared clock.
harp-tpu's training runs had the same gap: six spines (flight recorder,
CommLedger, SkewLedger, health sentinel, elastic ledger, checkpoint
events) each answer one aggregate question, none of them "what happened
DURING superstep 3".  This module is the training-plane sibling of
:mod:`harp_tpu.utils.reqtrace` (PR 12, which answered the same question
for serve requests): HARP (PAPERS.md arXiv:2509.24859) schedules off
exactly this per-phase profile, and DrJAX (arXiv:2403.07128) argues the
superstep boundary is where MapReduce-shaped JAX programs are naturally
observable.

**StepTracer** — a ``run`` is minted per instrumented host loop
(``fit_epochs`` / ``elastic_fit`` / ``kmeans.fit``); every superstep
inside it is a terminated span.  Onto the one monotone timeline (the
SpanTracer's clock, shared with compile records and fault marks) the
tracer threads:

- flight marks — dispatch / h2d / readback via the flightrec observer
  hooks (registered only while a run is open, so an idle process pays
  one falsy check per event), XLA compiles via
  ``CompileWatch.on_compile``;
- wire marks — CommLedger verb records at trace time;
- checkpoint writes (observer hook) and restores
  (``run_with_recovery``'s resume point);
- fault-plane events — every :class:`~harp_tpu.utils.fault.
  FaultInjector` fire (transient, delay, permanent);
- elastic actions — ``rebalance`` / ``shrink`` / ``resume`` from the
  elastic ledger, which also terminate the covering span as
  ``rebalanced`` (plan applied mid-span) or flag the NEXT span
  ``resumed`` (restore replayed before it opened);
- health findings — new sentinel rows and the exactly-once
  ``consume_skew_trigger`` handshake;
- per-worker skew lanes — ``skew.record_execution`` vectors as
  ``ev:"lane"`` rows, one per superstep.

Every opened span terminates (the context managers close in
``finally``) with outcome ∈ :data:`OUTCOMES`; the run row carries the
run's flightrec delta and the per-span sums, and scripts/check_jsonl.py
invariant 16 re-derives both from the rows and fails closed on any
mismatch — in particular ``flight.dispatches`` must equal the run's
dispatch marks EXACTLY (two independent spines: the observer path vs
the TransferLedger counters), and elastic marks must match the file's
``kind:"elastic"`` rows event-for-event.

Zero-cost when disabled (the PR-3 contract): :func:`run` returns
before touching state unless telemetry is enabled, every hook returns
on ``tracer._run is None``, and nothing here touches a traced program
or adds a device op — the flagship flight budgets (1 dispatch / 1
stacked readback / 0 steady compiles) are bit-identical with tracing
armed or off (pinned in tests/test_steptrace.py).

Exported as provenance-stamped ``kind:"steptrace"`` rows through
``telemetry.export`` / ``telemetry.export_timeline``; ``python -m
harp_tpu timeline run.jsonl [--perfetto out.json] [--json]`` validates
and summarizes, sharing the Chrome-Trace plumbing of
:mod:`harp_tpu.utils.perfetto` with the serve-plane exporter.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any

from harp_tpu.utils import telemetry

#: terminal superstep outcomes — frozen in scripts/check_jsonl.py as
#: KNOWN_STEPTRACE_OUTCOMES (drift fails tier-1)
OUTCOMES = ("completed", "faulted", "rebalanced", "resumed")

#: row event vocabulary — frozen as KNOWN_STEPTRACE_EVS
EVS = ("run", "superstep", "mark", "lane")

#: mark sources — frozen as KNOWN_STEPTRACE_SOURCES
SOURCES = ("flight", "wire", "ckpt", "fault", "elastic", "health",
           "memory")

#: the flight counters a run/span attributes (a subset of
#: flightrec._BUDGET_KEYS — the integer ones a superstep can own);
#: frozen as KNOWN_STEPTRACE_FLIGHT_KEYS
FLIGHT_KEYS = ("dispatches", "readbacks", "h2d_calls", "compiles")


class StepTracer:
    """Run/superstep span collector (see module docstring).

    One run may be open at a time; an inner :meth:`run` or
    :meth:`superstep` is a reentrant no-op (outermost wins), so driver
    layers can instrument defensively without double-counting.
    """

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._rows: list[dict] = []
        self._run: dict | None = None
        self._span: dict | None = None
        self._run_seq = 0

    def _now(self) -> float:
        # the SpanTracer clock: shared with compile records ("t") and
        # the fault-plane marks, so every source in an export_timeline
        # merge is causally comparable
        return round(time.perf_counter() - telemetry.tracer._t0, 6)

    # -- the spans -----------------------------------------------------------
    @contextlib.contextmanager
    def run(self, phase: str):
        """Mint a run id and walk the block as one training run."""
        if not telemetry.enabled() or self._run is not None:
            yield
            return
        from harp_tpu.utils import flightrec

        self._run_seq += 1
        r = self._run = {
            "run": self._run_seq, "phase": phase, "t0": self._now(),
            "seq": 0, "supersteps": 0,
            "outcomes": {o: 0 for o in OUTCOMES},
            "span_flight": {k: 0 for k in FLIGHT_KEYS},
            "marks": 0, "lanes": 0,
            "base": flightrec.snapshot(), "resume_pending": False,
        }
        try:
            with flightrec.observe_dispatches(self._on_dispatch), \
                    flightrec.observe_h2d(self._on_h2d), \
                    flightrec.observe_readbacks(self._on_readback), \
                    flightrec.observe_ckpt_writes(self._on_ckpt_write):
                yield
        finally:
            delta = flightrec.delta_since(r["base"])
            self._run = None   # marks after this row would be orphans
            self._span = None
            self._rows.append({
                "kind": "steptrace", "ev": "run", "run": r["run"],
                "phase": r["phase"], "t0": r["t0"], "ts": self._now(),
                "supersteps": r["supersteps"], "outcomes": r["outcomes"],
                "flight": {k: int(delta[k]) for k in FLIGHT_KEYS},
                "span_flight": r["span_flight"],
                "marks": r["marks"], "lanes": r["lanes"]})

    @contextlib.contextmanager
    def superstep(self, phase: str, step: int | None = None):
        """One terminated superstep span inside the open run.

        ``step`` is the driver's loop index (repeats across a
        restart-and-replay; ``seq`` is the run-local span ordinal and
        strictly increases).  An exception terminates the span
        ``faulted`` and propagates; an elastic ``rebalance`` recorded
        mid-span terminates it ``rebalanced``; a span opened right
        after an elastic ``resume`` terminates ``resumed``.
        """
        r = self._run
        if r is None or self._span is not None:
            yield
            return
        from harp_tpu.utils import flightrec

        sp = self._span = {
            "seq": r["seq"],
            "step": int(r["seq"] if step is None else step),
            "phase": phase, "t0": self._now(),
            "base": flightrec.snapshot(),
            "rebalanced": False, "resumed": r["resume_pending"],
        }
        r["resume_pending"] = False
        r["seq"] += 1
        from harp_tpu.utils import memrec

        memrec.ledger.begin_window()
        outcome = "completed"
        try:
            yield
        except BaseException:
            outcome = "faulted"
            raise
        finally:
            delta = flightrec.delta_since(sp["base"])
            if outcome == "completed":
                if sp["rebalanced"]:
                    outcome = "rebalanced"
                elif sp["resumed"]:
                    outcome = "resumed"
            flight = {k: int(delta[k]) for k in FLIGHT_KEYS}
            for k in FLIGHT_KEYS:
                r["span_flight"][k] += flight[k]
            r["outcomes"][outcome] += 1
            r["supersteps"] += 1
            memrec.note_superstep(self)  # before the span closes: the
            self._span = None            # mark carries this seq/step
            self._rows.append({
                "kind": "steptrace", "ev": "superstep", "run": r["run"],
                "seq": sp["seq"], "step": sp["step"], "phase": phase,
                "outcome": outcome, "t0": sp["t0"], "ts": self._now(),
                "flight": flight})

    # -- marks ---------------------------------------------------------------
    def mark(self, source: str, name: str, **extra: Any) -> None:
        """One instant on the timeline (no-op outside an open run)."""
        r = self._run
        if r is None:
            return
        row = {"kind": "steptrace", "ev": "mark", "run": r["run"],
               "ts": self._now(), "source": source, "name": name}
        if self._span is not None:
            row["seq"] = self._span["seq"]
            row["step"] = self._span["step"]
        row.update(extra)
        self._rows.append(row)
        r["marks"] += 1

    # flightrec observer callbacks (registered only while a run is open;
    # a FaultInjector armed BEFORE the run registers first, so an
    # injected fault aborts the op before its mark lands — only
    # launched operations get flight marks, matching the counters)
    def _on_dispatch(self, label: str) -> None:
        self.mark("flight", "dispatch", label=label)

    def _on_h2d(self, nbytes: int, site: Any) -> None:
        self.mark("flight", "h2d", bytes=int(nbytes))

    def _on_readback(self, x: Any) -> None:
        self.mark("flight", "readback")

    def _on_ckpt_write(self, path: str) -> None:
        self.mark("ckpt", "write")

    # cross-spine hooks (each spine calls its module-level shim below)
    def on_compile(self, dur: float) -> None:
        self.mark("flight", "compile", dur=round(float(dur), 6))

    def on_comm(self, verb: str, site: str) -> None:
        self.mark("wire", verb, site=site)

    def on_fault(self, site: str, ordinal: int, action: str) -> None:
        self.mark("fault", f"injected_{action}", site=site,
                  ordinal=int(ordinal))

    def on_elastic(self, event: str, phase: str,
                   row: dict | None = None) -> None:
        r = self._run
        if r is None:
            return
        if event == "rebalance" and self._span is not None:
            self._span["rebalanced"] = True
        if event == "resume":
            if self._span is None:
                r["resume_pending"] = True   # the NEXT span is the replay
            else:
                self._span["resumed"] = True
        extra = {}
        if row:
            for k in ("lost_worker", "n_workers", "n_workers_before",
                      "n_workers_after", "wasted_frac_after",
                      "from_step", "replayed_plan"):
                if k in row:
                    extra[k] = row[k]
        self.mark("elastic", event, phase=phase, **extra)

    def on_health(self, detector: str, key: Any) -> None:
        self.mark("health", detector, key=str(key))

    def on_skew_consume(self, phase: str) -> None:
        self.mark("health", "consume_skew_trigger", phase=phase)

    def note_restore(self, step: int) -> None:
        """``run_with_recovery`` restored a checkpoint (any restart, not
        just elastic) — a ``ckpt:restore`` mark, not an outcome."""
        self.mark("ckpt", "restore", step=int(step))

    def on_execution(self, phase: str, work, *, unit: str,
                     wall_s: float | None = None) -> None:
        """Per-worker skew lane for the open span (skew spine hook)."""
        r, sp = self._run, self._span
        if r is None or sp is None:
            return
        import numpy as np

        row = {"kind": "steptrace", "ev": "lane", "run": r["run"],
               "seq": sp["seq"], "step": sp["step"], "phase": phase,
               "ts": self._now(), "unit": unit,
               "work": [round(float(w), 6)
                        for w in np.asarray(work).reshape(-1)]}
        if wall_s is not None:
            row["wall_s"] = round(float(wall_s), 6)
        self._rows.append(row)
        r["lanes"] += 1

    # -- reading -------------------------------------------------------------
    def rows(self) -> list[dict]:
        """Completed rows, in timeline order (runs close after their
        spans, so the list is ts-monotone by construction)."""
        return list(self._rows)

    def export_jsonl(self, fh, stamp: dict | None = None) -> None:
        for row in self._rows:
            fh.write(json.dumps({**row, **(stamp or {})}) + "\n")


# ---------------------------------------------------------------------------
# Module singleton + the spines' shims
# ---------------------------------------------------------------------------

tracer = StepTracer()


def reset() -> None:
    """Clear the tracer (telemetry.scope does this on entry)."""
    tracer.reset()


def run(phase: str):
    """``with steptrace.run("mfsgd.epochs"): ...`` — the driver entry."""
    return tracer.run(phase)


def superstep(phase: str, step: int | None = None):
    """``with steptrace.superstep(phase, i): train_one()``."""
    return tracer.superstep(phase, step)


def export_jsonl(fh) -> None:
    """Append steptrace rows (telemetry.export calls this); stamped
    with the flight recorder's provenance triple."""
    if not tracer._rows:
        return
    from harp_tpu.utils import flightrec

    tracer.export_jsonl(fh, flightrec.provenance_stamp())


# ---------------------------------------------------------------------------
# Perfetto export (shared Chrome-Trace plumbing, utils/perfetto.py)
# ---------------------------------------------------------------------------

_PID_STEP, _PID_MARK, _PID_LANE = 1, 2, 3

#: provenance keys stripped from Perfetto args (stamped on every row)
_STAMP_KEYS = ("backend", "date", "commit")


def perfetto(rows: list[dict]) -> dict:
    """Convert ``kind:"steptrace"`` rows into Chrome Trace Event JSON.

    Runs and their supersteps are nested ``X`` spans on one track per
    run (pid 1), marks are instants on pid 2, and the per-worker skew
    lanes fan out to one thread per worker on pid 3 — so a hot worker
    reads as a dense lane next to its idle peers.
    """
    from harp_tpu.utils import perfetto as pft

    st = [r for r in rows if r.get("kind") == "steptrace"]
    if not st:
        return pft.empty()
    b = pft.TraceBuilder(min(float(r.get("t0", r["ts"])) for r in st))
    b.process(_PID_STEP, "supersteps")
    b.process(_PID_MARK, "events")
    b.process(_PID_LANE, "skew lanes")
    for r in st:
        ev = r.get("ev")
        if ev == "run":
            b.complete(f"run {r['run']} {r.get('phase')}", _PID_STEP,
                       r["run"], r.get("t0", r["ts"]), r["ts"],
                       args={"supersteps": r.get("supersteps"),
                             "outcomes": r.get("outcomes"),
                             "flight": r.get("flight")})
        elif ev == "superstep":
            b.complete(f"step {r.get('step')} [{r.get('outcome')}]",
                       _PID_STEP, r["run"], r.get("t0", r["ts"]), r["ts"],
                       args={"outcome": r.get("outcome"),
                             "flight": r.get("flight")})
        elif ev == "mark":
            b.instant(f"{r.get('source')}:{r.get('name')}", _PID_MARK, 1,
                      r["ts"],
                      args={k: v for k, v in r.items()
                            if k not in ("kind", "ev", "ts")
                            and k not in _STAMP_KEYS})
        elif ev == "lane":
            for w, load in enumerate(r.get("work") or []):
                b.instant(f"w{w}", _PID_LANE, w, r["ts"], scope="t",
                          args={"work": load, "step": r.get("step"),
                                "unit": r.get("unit")})
    return b.build()


# ---------------------------------------------------------------------------
# Timeline-file summary + CLI
# ---------------------------------------------------------------------------

def summarize_rows(rows: list[dict]) -> dict:
    """Validate + summarize loaded steptrace rows (the CLI's core).

    Mirrors invariant 16's span checks: every run seen in
    span/mark/lane rows must terminate in exactly one run row, every
    span outcome must be known, and each run's dispatch marks must
    equal its flight-counter delta (the two-spine reconciliation).
    """
    runs: dict[int, dict] = {}
    spans: dict[int, list[dict]] = {}
    seen: set[int] = set()
    marks = lanes = 0
    bad_outcomes: list = []
    dispatch_marks: dict[int, int] = {}
    for r in rows:
        ev = r.get("ev")
        rid = r.get("run")
        if ev == "run":
            runs[rid] = r
        elif ev == "superstep":
            seen.add(rid)
            spans.setdefault(rid, []).append(r)
            if r.get("outcome") not in OUTCOMES:
                bad_outcomes.append([rid, r.get("seq")])
        elif ev == "mark":
            seen.add(rid)
            marks += 1
            if r.get("source") == "flight" and r.get("name") == "dispatch":
                dispatch_marks[rid] = dispatch_marks.get(rid, 0) + 1
        elif ev == "lane":
            seen.add(rid)
            lanes += 1
    unterminated = sorted(seen - set(runs))
    counts = {o: sum(rn.get("outcomes", {}).get(o, 0)
                     for rn in runs.values()) for o in OUTCOMES}
    dispatch_mismatch = sorted(
        rid for rid, rn in runs.items()
        if dispatch_marks.get(rid, 0)
        != rn.get("flight", {}).get("dispatches"))
    out = {"runs": len(runs),
           "supersteps": sum(rn.get("supersteps", 0)
                             for rn in runs.values()),
           **counts, "marks": marks, "lanes": lanes,
           "unterminated": unterminated, "bad_outcomes": bad_outcomes,
           "dispatch_mismatch": dispatch_mismatch}
    durs = sorted(r["ts"] - r["t0"] for rs in spans.values() for r in rs
                  if r.get("outcome") == "completed" and "t0" in r)
    if durs:
        out["step_p50_ms"] = round(
            durs[min(len(durs) - 1, int(0.50 * len(durs)))] * 1e3, 4)
    return out


def _render(rows: list[dict], summary: dict, max_steps: int = 40) -> str:
    lines = ["== harp-tpu training timeline =="]
    lines.append(
        f"{summary['runs']} run(s), {summary['supersteps']} superstep(s): "
        f"{summary['completed']} completed / {summary['faulted']} faulted "
        f"/ {summary['rebalanced']} rebalanced / {summary['resumed']} "
        f"resumed; {summary['marks']} mark(s), {summary['lanes']} lane(s)")
    if summary.get("step_p50_ms") is not None:
        lines.append(f"completed superstep p50 {summary['step_p50_ms']} ms")
    if summary["unterminated"]:
        lines.append(f"UNTERMINATED runs: {summary['unterminated']}")
    if summary["dispatch_mismatch"]:
        lines.append("dispatch marks != flight counters in runs: "
                     f"{summary['dispatch_mismatch']}")
    by_run: dict[int, list[dict]] = {}
    run_rows: dict[int, dict] = {}
    for r in rows:
        if r.get("ev") == "run":
            run_rows[r["run"]] = r
        elif r.get("ev") in ("superstep", "mark"):
            by_run.setdefault(r.get("run"), []).append(r)
    shown = 0
    for rid in sorted(by_run):
        rn = run_rows.get(rid)
        head = f"run {rid}"
        if rn is not None:
            head += (f" [{rn.get('phase')}] {rn.get('supersteps')} "
                     f"superstep(s), flight {rn.get('flight')}")
        lines.append(head + ":")
        t0 = by_run[rid][0].get("t0", by_run[rid][0]["ts"])
        for e in by_run[rid]:
            if shown >= max_steps:
                break
            shown += 1
            off = (e["ts"] - t0) * 1e3
            if e.get("ev") == "superstep":
                lines.append(f"  +{off:9.3f} ms  step {e.get('step')} "
                             f"[{e.get('outcome')}] flight "
                             f"{e.get('flight')}")
            else:
                lines.append(f"  +{off:9.3f} ms  "
                             f"{e.get('source')}:{e.get('name')}")
    n_events = sum(len(v) for v in by_run.values())
    if n_events > shown:
        lines.append(f"... {n_events - shown} more event(s) "
                     "(use --perfetto for the full timeline)")
    return "\n".join(lines)


def main(argv=None) -> int:
    """``python -m harp_tpu timeline run.jsonl`` — validate + summarize
    a training-plane timeline, optionally writing Perfetto JSON.

    Exit codes: 0 clean, 1 the timeline is incomplete or irreconciled
    (unterminated runs, unknown outcomes, dispatch marks disagreeing
    with the flight counters — the same defects invariant 16 rejects),
    2 usage / unreadable input.
    """
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m harp_tpu timeline",
        description="superstep timeline: validate + summarize a "
                    "kind:'steptrace' JSONL export (telemetry.export / "
                    "HARP_TELEMETRY_OUT), export Chrome/Perfetto JSON")
    p.add_argument("jsonl", help="timeline JSONL (telemetry.export "
                                 "output or an export_timeline file)")
    p.add_argument("--perfetto", metavar="OUT", default=None,
                   help="write a Chrome Trace Event JSON here (load in "
                        "chrome://tracing or ui.perfetto.dev)")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable summary line "
                        "instead of the human timeline")
    args = p.parse_args(argv)
    try:
        rows = telemetry.load_rows(args.jsonl)["steptrace"]
    except OSError as e:
        print(f"timeline: cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    summary = summarize_rows(rows)
    if args.perfetto:
        with open(args.perfetto, "w") as fh:
            json.dump(perfetto(rows), fh)
        summary["perfetto"] = args.perfetto
    if args.json:
        from harp_tpu.utils.metrics import benchmark_json

        print(benchmark_json("timeline", summary))
    else:
        print(_render(rows, summary))
    if (summary["unterminated"] or summary["bad_outcomes"]
            or summary["dispatch_mismatch"]):
        print(f"timeline: {len(summary['unterminated'])} unterminated "
              f"run(s), {len(summary['bad_outcomes'])} unknown "
              f"outcome(s), {len(summary['dispatch_mismatch'])} "
              "dispatch mismatch(es)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover - python -m harp_tpu timeline
    import sys

    sys.exit(main())
