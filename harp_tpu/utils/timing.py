"""Reliable device synchronization and iteration timing.

Harp apps timed iterations with wall-clock logs around collective phases
(SURVEY.md §6 "tracing").  On TPU, timing is only honest after forcing
device completion; on some transports (the axon relay on this machine)
``jax.block_until_ready`` can return early, so the portable sync is a
device→host readback of a scalar.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def device_sync(x: Any) -> float:
    """Force completion of everything ``x`` depends on; returns a scalar.

    Reduces one leaf to a scalar and reads it back to the host — a readback
    cannot complete before the producing computation has.  Use this, not
    ``block_until_ready``, around benchmark timing.
    """
    leaf = jax.tree.leaves(x)[0]
    return float(np.asarray(jnp.ravel(leaf)[0]))


class Timer:
    """Per-iteration timer table, printed like Harp's per-phase logs."""

    def __init__(self):
        self.records: dict[str, list[float]] = {}

    def time(self, name: str, fn, *args, sync: bool = True, **kw):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        if sync:
            device_sync(out)
        self.records.setdefault(name, []).append(time.perf_counter() - t0)
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            k: {"mean_s": float(np.mean(v)), "total_s": float(np.sum(v)), "n": len(v)}
            for k, v in self.records.items()
        }
