"""Reliable device synchronization and iteration timing.

Harp apps timed iterations with wall-clock logs around collective phases
(SURVEY.md §6 "tracing").  On TPU, timing is only honest after forcing
device completion; on some transports (the axon relay on this machine)
``jax.block_until_ready`` can return early, so the portable sync is a
device→host readback of a scalar.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


class HangWatchdog:
    """Hard-exit instead of hanging the caller forever.

    The axon TPU relay on this machine can hang for hours at first backend
    use (even ``jax.devices()`` blocks, uninterruptible from Python — see
    CLAUDE.md "Environment gotchas"), so benchmark entry points arm a daemon
    timer that ``os._exit``\\ s with a diagnostic after ``timeout_s``.
    ``arm`` may be called repeatedly to restart the clock per phase/config;
    ``on_fire(what)`` runs first so the caller can emit a structured record
    naming the hung phase (stdout lines already flushed are preserved).
    """

    def __init__(self, timeout_s: float | None = None, *, exit_code: int = 3,
                 on_fire: Callable[[str], None] | None = None,
                 _exit: Callable[[int], None] = os._exit):
        if timeout_s is None:
            timeout_s = float(os.environ.get("HARP_BENCH_TIMEOUT", "1200"))
        self.timeout_s = timeout_s
        self.exit_code = exit_code
        self.on_fire = on_fire
        self._exit = _exit
        self._timer: threading.Timer | None = None
        # Timer.cancel() can't stop a _fire already past the waiting stage;
        # the generation check below keeps a just-cancelled timer from
        # emitting a spurious hang record and killing a healthy process.
        self._lock = threading.Lock()
        self._gen = 0

    def arm(self, what: str = "benchmark") -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._gen += 1
            t = threading.Timer(self.timeout_s, self._fire, (what, self._gen))
            t.daemon = True
            # stable name so threadguard's ownership map (generated from
            # harplint Layer 5) can forbid jax work on the watchdog timer
            t.name = "harp-watchdog"
            self._timer = t
        t.start()

    def cancel(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._gen += 1

    def _fire(self, what: str, gen: int) -> None:
        with self._lock:
            if gen != self._gen:
                return  # cancelled or re-armed as we left the waiting stage
        print(f"watchdog: {what} produced no result after "
              f"{self.timeout_s:.0f}s — TPU relay likely hung (see CLAUDE.md "
              "'Environment gotchas'); exiting", file=sys.stderr, flush=True)
        if self.on_fire is not None:
            try:
                self.on_fire(what)
            except Exception:
                pass  # never let the diagnostic path mask the exit
        self._exit(self.exit_code)


def device_sync(x: Any) -> float:
    """Force completion of everything ``x`` depends on; returns a scalar.

    Reduces one leaf to a scalar and reads it back to the host — a readback
    cannot complete before the producing computation has.  Use this, not
    ``block_until_ready``, around benchmark timing.  Each call counts as
    one readback round trip in the flight recorder (the 20-150 ms relay
    round-trip trap this function exists to bound to one per run).
    """
    from harp_tpu.utils import flightrec

    leaf = jax.tree.leaves(x)[0]
    flightrec.record_readback(np.dtype(leaf.dtype).itemsize)
    return float(np.asarray(jnp.ravel(leaf)[0]))


class Timer:
    """Per-iteration timer table, printed like Harp's per-phase logs."""

    def __init__(self):
        self.records: dict[str, list[float]] = {}

    def time(self, name: str, fn, *args, sync: bool = True, **kw):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        if sync:
            device_sync(out)
        self.records.setdefault(name, []).append(time.perf_counter() - t0)
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            k: {"mean_s": float(np.mean(v)), "total_s": float(np.sum(v)), "n": len(v)}
            for k, v in self.records.items()
        }
