"""Failure handling — SURVEY.md §6 "failure detection / fault injection".

Reference behavior: Harp delegates failure to YARN — a dead container fails
the task, YARN retries the whole job from scratch; there is no elastic
membership and no in-framework fault injection.  The TPU plan matches that
capability and improves on "from scratch": fail-fast, then restart from the
latest orbax checkpoint (:mod:`harp_tpu.utils.checkpoint`), plus an
explicit fault-injection hook so the recovery path is testable (Harp's
never was).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable

import jax
import numpy as np

log = logging.getLogger("harp_tpu")


def check_restored_shapes(named_pairs) -> None:
    """Refuse a checkpoint whose array shapes don't match the live model.

    ``named_pairs``: iterable of ``(name, restored, live)`` pytrees.  A
    mismatched restore would not fail loudly — dynamic slices clamp and
    silently train wrong rows — so every model ``fit`` guards with this
    before installing state (shape reads only; no device transfer).
    """
    for name, restored, live in named_pairs:
        got = [np.shape(v) for v in jax.tree.leaves(restored)]
        want = [np.shape(v) for v in jax.tree.leaves(live)]
        if got != want:
            raise ValueError(
                f"checkpoint shapes {name}{got} do not match this model's "
                f"{name}{want} — was the checkpoint written with a different "
                "algo/tile/size config? (refusing to resume)")


def factor_state_io(obj, fields: dict):
    """(get_state, set_state) for models whose checkpoint state is named
    array attributes — the ONE restore contract shared by the factor
    models (MF-SGD, CCD), so shape-guarding and live-vs-numpy handling
    cannot drift between them.

    ``fields``: ``{attr_name: placer}`` where ``placer(np_array)`` puts a
    freshly-restored HOST array on the right devices (live arrays from
    the normal step-to-step flow are installed as-is, no transfers).
    """

    def get_state():
        return {k: getattr(obj, k) for k in fields}

    def set_state(state):
        check_restored_shapes(
            [(k, state[k], getattr(obj, k)) for k in fields])
        first = state[next(iter(fields))]
        if isinstance(first, jax.Array):   # normal flow: install as-is
            for k in fields:
                setattr(obj, k, state[k])
        else:                              # numpy from a fresh restore
            for k, place in fields.items():
                setattr(obj, k, place(np.asarray(state[k])))

    return get_state, set_state


class FaultInjector:
    """Deterministic fault hook for tests — raise at chosen iterations.

    Install one into a training loop via :func:`run_with_recovery`'s
    ``fault`` argument or call :meth:`check` manually inside a host loop.
    Each scheduled iteration fires exactly once (a restarted run that
    passes the same iteration again does not re-fail), mimicking a
    transient container loss rather than a deterministic crash loop.
    """

    def __init__(self, fail_at: tuple[int, ...] = ()):
        self.pending = set(fail_at)
        self.fired: list[int] = []

    def check(self, iteration: int) -> None:
        if iteration in self.pending:
            self.pending.discard(iteration)
            self.fired.append(iteration)
            raise WorkerFailure(f"injected fault at iteration {iteration}")


class WorkerFailure(RuntimeError):
    """A worker died mid-job (Harp: container failure surfaced by YARN)."""


def fit_epochs(
    train_one: Callable[[], Any],
    get_state: Callable[[], Any],
    set_state: Callable[[Any], None],
    epochs: int,
    ckpt_dir: str | None = None,
    *,
    ckpt_every: int = 5,
    max_restarts: int = 3,
    fault: "FaultInjector | None" = None,
) -> None:
    """Epoch-loop driver with optional checkpoint/resume — shared by the
    model ``fit`` methods (MF-SGD, LDA).

    ``get_state`` returns the model's checkpointable pytree (live device
    arrays are fine); ``set_state`` installs a state that may be numpy
    (fresh restore) or live arrays (normal step-to-step flow).  Contract
    guarantees, locked in by tests:
    - a crash before the first checkpoint restarts from the state at THIS
      call's entry (snapshotted host-side), never from crash-time state;
    - a resume with no epochs left still installs the restored state;
    - ``fault`` without ``ckpt_dir`` is refused rather than ignored.
    """
    if ckpt_dir is None:
        if fault is not None:
            raise ValueError(
                "fault injection requires ckpt_dir (recovery restarts from "
                "checkpoints; without one the injector would be silently "
                "ignored)")
        for _ in range(epochs):
            train_one()
        return

    import numpy as np

    from harp_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir)
    # snapshot the entry state: a crash before the first checkpoint must
    # restart from these values (double-applying epochs trains silently
    # wrong).  Skipped when a checkpoint already exists — every restart
    # then restores from disk, so the host-side copy would be dead weight.
    import jax

    init = None if mgr.latest_step() is not None \
        else jax.tree.map(np.asarray, get_state())

    def step(i, state):
        set_state(state)
        train_one()
        return get_state()

    final = run_with_recovery(lambda: init, step, epochs, mgr,
                              ckpt_every=ckpt_every,
                              max_restarts=max_restarts, fault=fault)
    # a resume that had nothing left to run still must land in the model
    set_state(final)


def run_with_recovery(
    make_state: Callable[[], Any],
    step: Callable[[int, Any], Any],
    n_iters: int,
    ckpt,
    *,
    ckpt_every: int = 10,
    max_restarts: int = 3,
    fault: FaultInjector | None = None,
) -> Any:
    """Fail-fast iterate-with-restart — the YARN retry loop, in-framework.

    Runs ``state = step(i, state)`` for ``i in [0, n_iters)``, checkpointing
    every ``ckpt_every`` iterations through ``ckpt``
    (:class:`harp_tpu.utils.checkpoint.CheckpointManager`).  On any
    exception the job restarts from the latest checkpoint — or from
    ``make_state()`` if none exists — up to ``max_restarts`` times, then
    re-raises.  Matches Harp's whole-job-retry semantics but resumes from
    the last checkpoint instead of iteration 0.
    """
    restarts = 0
    while True:
        latest = ckpt.latest_step()
        if latest is None:
            start, state = 0, make_state()
        else:
            start, state = ckpt.restore()
            start += 1
        try:
            for i in range(start, n_iters):
                if fault is not None:
                    fault.check(i)
                state = step(i, state)
                if (i + 1) % ckpt_every == 0 or i == n_iters - 1:
                    ckpt.save(i, state)
            return state
        except Exception as e:  # noqa: BLE001 - the whole point
            restarts += 1
            if restarts > max_restarts:
                log.error("job failed after %d restarts: %s", max_restarts, e)
                raise
            log.warning("worker failure (%s); restart %d/%d from step %s",
                        e, restarts, max_restarts, ckpt.latest_step())
            time.sleep(0)  # yield; real deployments would re-init devices here
