"""Failure handling — SURVEY.md §6 "failure detection / fault injection".

Reference behavior: Harp delegates failure to YARN — a dead container fails
the task, YARN retries the whole job from scratch; there is no elastic
membership and no in-framework fault injection.  The TPU plan matches that
capability and improves on "from scratch": fail-fast, then restart from the
latest orbax checkpoint (:mod:`harp_tpu.utils.checkpoint`), plus an
explicit fault-injection hook so the recovery path is testable (Harp's
never was).

Deterministic chaos (PR 10): :class:`FaultInjector` rides the flight
recorder's observer hooks (:func:`harp_tpu.utils.flightrec.
observe_dispatches` / ``observe_h2d`` / ``observe_readbacks`` /
``observe_ckpt_writes`` — the execution paths every driver already
funnels through) to fail or delay specific sites on a seeded,
reproducible schedule.  The injector is entirely host-side: it never
touches a traced program (the jaxpr with an armed-but-quiet injector is
bit-identical to the uninstrumented one — tested), and while unarmed the
only cost anywhere is the observer lists' falsy check, so production
paths pay nothing (the DrJAX rule from PAPERS.md: keep the hooks out of
the traced hot path).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Callable, Collection

import jax
import numpy as np

log = logging.getLogger("harp_tpu")

#: the observable injection sites, in the order an epoch loop hits them
SITES = ("dispatch", "h2d", "readback", "ckpt_write")


def check_restored_shapes(named_pairs) -> None:
    """Refuse a checkpoint whose array shapes don't match the live model.

    ``named_pairs``: iterable of ``(name, restored, live)`` pytrees.  A
    mismatched restore would not fail loudly — dynamic slices clamp and
    silently train wrong rows — so every model ``fit`` guards with this
    before installing state (shape reads only; no device transfer).
    """
    for name, restored, live in named_pairs:
        got = [np.shape(v) for v in jax.tree.leaves(restored)]
        want = [np.shape(v) for v in jax.tree.leaves(live)]
        if got != want:
            raise ValueError(
                f"checkpoint shapes {name}{got} do not match this model's "
                f"{name}{want} — was the checkpoint written with a different "
                "algo/tile/size config? (refusing to resume)")


def factor_state_io(obj, fields: dict):
    """(get_state, set_state) for models whose checkpoint state is named
    array attributes — the ONE restore contract shared by the factor
    models (MF-SGD, CCD), so shape-guarding and live-vs-numpy handling
    cannot drift between them.

    ``fields``: ``{attr_name: placer}`` where ``placer(np_array)`` puts a
    freshly-restored HOST array on the right devices (live arrays from
    the normal step-to-step flow are installed as-is, no transfers).
    """

    def get_state():
        return {k: getattr(obj, k) for k in fields}

    def set_state(state):
        check_restored_shapes(
            [(k, state[k], getattr(obj, k)) for k in fields])
        first = state[next(iter(fields))]
        if isinstance(first, jax.Array):   # normal flow: install as-is
            for k in fields:
                setattr(obj, k, state[k])
        else:                              # numpy from a fresh restore
            for k, place in fields.items():
                setattr(obj, k, place(np.asarray(state[k])))

    return get_state, set_state


class WorkerFailure(RuntimeError):
    """A worker died mid-job (Harp: container failure surfaced by YARN)."""


class InjectedFault(WorkerFailure):
    """A :class:`FaultInjector`-scheduled transient failure.

    Carries the site and the 1-based event ordinal at which it fired, so
    recovery code can log *which* dispatch/H2D/readback/checkpoint-write
    died — and retry layers (``serve.ContinuousRunner``) can classify it
    as transient.  Raised BEFORE the observed operation runs or is
    counted (see the flightrec observer contract), so an injected fault
    always models work that never reached the device.
    """

    def __init__(self, site: str, ordinal: int):
        super().__init__(f"injected {site} fault (event #{ordinal})")
        self.site = site
        self.ordinal = ordinal


class PermanentWorkerLoss(WorkerFailure):
    """A :class:`FaultInjector`-scheduled PERMANENT worker loss (PR 15).

    Unlike :class:`InjectedFault` (a transient the retry layers absorb
    by re-trying on the same mesh), this models a worker that is GONE
    for the rest of the run: retrying on the full mesh can only fail
    again.  Deliberately NOT a subclass of :class:`InjectedFault`, so
    transient-retry layers never swallow it; the elastic handler
    (:func:`run_with_recovery` ``on_permanent`` /
    :mod:`harp_tpu.elastic`) shrinks the mesh to the survivors instead.
    Carries the site, the 1-based event ordinal, and the lost worker's
    mesh index.
    """

    def __init__(self, site: str, ordinal: int, worker: int):
        super().__init__(f"injected permanent loss of worker {worker} "
                         f"({site} event #{ordinal})")
        self.site = site
        self.ordinal = ordinal
        self.worker = worker


def _spec_fires(spec, ordinal: int, rng: np.random.Generator) -> bool:
    """A site schedule is a probability (seeded Bernoulli per event) or a
    collection of 1-based event ordinals (exact, for pinned tests)."""
    if spec is None:
        return False
    if isinstance(spec, (int, float)) and not isinstance(spec, bool):
        return bool(rng.random() < spec)
    return ordinal in spec


class FaultInjector:
    """Deterministic chaos — fail or delay chosen sites on a seeded
    schedule.

    Two independent surfaces:

    - **iteration schedule** (the PR-0 contract, unchanged): ``fail_at``
      iterations raise from :meth:`check`, which
      :func:`run_with_recovery` calls at the top of every step.  Each
      scheduled iteration fires exactly once (a restarted run that
      passes the same iteration again does not re-fail), mimicking a
      transient container loss rather than a deterministic crash loop.
    - **site schedule** (PR 10): ``fail=`` / ``delay=`` map an
      observable site (:data:`SITES`: ``dispatch``, ``h2d``,
      ``readback``, ``ckpt_write``) to either a probability — a seeded
      Bernoulli draw per event, reproducible given the same event
      sequence — or a collection of 1-based event ordinals (exact; the
      kill/resume pin uses ``fail={"dispatch": (4,)}``).  :meth:`arm`
      registers the injector on the flightrec observer hooks for the
      scheduled sites; inside the ``with`` block a due event raises
      :class:`InjectedFault` (``fail``) or sleeps ``delay_s`` seconds
      (``delay``) before the operation proceeds.
    - **permanent schedule** (PR 15): ``permanent=`` takes the same
      spec shapes (probability or exact 1-based ordinals — the
      worker-loss drill pins ``permanent={"dispatch": (2,)}``) but
      raises :class:`PermanentWorkerLoss` for ``lost_worker`` and fires
      AT MOST ONCE: the worker is gone for the rest of the run, and
      only an elastic handler (mesh shrink + repartition replay) can
      absorb it.

    Determinism note: one seeded generator drives every probabilistic
    draw in event order, so a schedule replays exactly for the same
    event sequence — and two runs with the same seed and the same code
    path fail at the same places.  ``max_faults`` bounds the total
    injected failures (delays are not bounded), so a chaos bench can
    guarantee forward progress.  The injector never touches traced
    programs; disabled/unarmed it costs nothing (tested by jaxpr
    equality + zero counters, the PR-3 pattern).
    """

    def __init__(self, fail_at: tuple[int, ...] = (), *, seed: int = 0,
                 fail: dict[str, float | Collection[int]] | None = None,
                 delay: dict[str, float | Collection[int]] | None = None,
                 delay_s: float = 0.001, max_faults: int | None = None,
                 permanent: dict[str, float | Collection[int]] | None = None,
                 lost_worker: int | None = None):
        self.pending = set(fail_at)
        self.fired: list[int] = []
        for sched in (fail, delay, permanent):
            for site in sched or ():
                if site not in SITES:
                    raise ValueError(
                        f"unknown fault site {site!r} (sites: {SITES})")
        self.fail = dict(fail or {})
        self.delay = dict(delay or {})
        # permanent-loss schedule (PR 15): same spec contract as fail= —
        # a probability (seeded Bernoulli per event) or exact 1-based
        # event ordinals — but the injected failure is a
        # PermanentWorkerLoss for `lost_worker`, and it fires at most
        # once per injector (one schedule kills one worker; chain
        # injectors for multi-loss chaos).
        self.permanent = dict(permanent or {})
        if self.permanent and lost_worker is None:
            raise ValueError(
                "permanent= names the schedule but not the casualty: "
                "pass lost_worker=<mesh index> so the elastic handler "
                "knows which worker to exclude")
        self.lost_worker = lost_worker
        self.permanent_fired = False
        self.delay_s = float(delay_s)
        self.max_faults = max_faults
        self._rng = np.random.default_rng(seed)
        self.seen = {s: 0 for s in SITES}
        self.injected = {s: 0 for s in SITES}
        self.delayed = {s: 0 for s in SITES}
        self.events: list[tuple[str, int]] = []  # (site, ordinal) fired

    # -- iteration schedule (legacy surface) -------------------------------
    def check(self, iteration: int) -> None:
        if iteration in self.pending:
            self.pending.discard(iteration)
            self.fired.append(iteration)
            raise WorkerFailure(f"injected fault at iteration {iteration}")

    # -- site schedule -----------------------------------------------------
    def on_event(self, site: str) -> None:
        """One observed event at ``site``; raises/sleeps when due."""
        self.seen[site] += 1
        n = self.seen[site]
        if _spec_fires(self.delay.get(site), n, self._rng):
            self.delayed[site] += 1
            self._mark(site, n, "delay")
            time.sleep(self.delay_s)
        if (not self.permanent_fired
                and _spec_fires(self.permanent.get(site), n, self._rng)):
            # permanent loss is not bounded by max_faults (it is not a
            # transient the run can absorb) and fires exactly once: the
            # worker is gone, re-killing it models nothing
            self.permanent_fired = True
            self.injected[site] += 1
            self.events.append((site, n))
            self._mark(site, n, "permanent")
            raise PermanentWorkerLoss(site, n, self.lost_worker)
        if (self.max_faults is not None
                and sum(self.injected.values()) >= self.max_faults):
            return
        if _spec_fires(self.fail.get(site), n, self._rng):
            self.injected[site] += 1
            self.events.append((site, n))
            self._mark(site, n, "fail")
            raise InjectedFault(site, n)

    @staticmethod
    def _mark(site: str, ordinal: int, action: str) -> None:
        """Fault-plane events ride the unified timeline (PR 12): a
        ``source:"fault"`` mark on the SpanTracer clock, so an injected
        failure shows up next to the batch that absorbed it in
        ``telemetry.export_timeline`` / the Perfetto view — and, inside
        a training run, the same fire lands on the superstep timeline
        (PR 18).  No-op while telemetry is off, like every other spine
        hook."""
        from harp_tpu.utils import reqtrace, steptrace, telemetry

        if telemetry.enabled():
            reqtrace.tracer.mark(
                "fault", f"injected_{action}",
                time.perf_counter() - telemetry.tracer._t0,
                site=site, ordinal=ordinal)
            steptrace.tracer.on_fault(site, ordinal, action)

    @contextlib.contextmanager
    def arm(self):
        """Attach to the flightrec observer hooks for the scheduled
        sites (only those — an unscheduled site keeps its empty observer
        list and stays cost-free)."""
        from harp_tpu.utils import flightrec

        hooks = {
            "dispatch": lambda: flightrec.observe_dispatches(
                lambda label: self.on_event("dispatch")),
            "h2d": lambda: flightrec.observe_h2d(
                lambda nbytes, site: self.on_event("h2d")),
            "readback": lambda: flightrec.observe_readbacks(
                lambda x: self.on_event("readback")),
            "ckpt_write": lambda: flightrec.observe_ckpt_writes(
                lambda path: self.on_event("ckpt_write")),
        }
        active = {s for s in SITES
                  if s in self.fail or s in self.delay
                  or s in self.permanent}
        with contextlib.ExitStack() as stack:
            for site in active:
                stack.enter_context(hooks[site]())
            yield self

    def counters(self) -> dict:
        """Per-site accounting for bench rows / assertions."""
        return {"seen": dict(self.seen), "injected": dict(self.injected),
                "delayed": dict(self.delayed)}


def resolve_resume(ckpt_dir: str | None, resume: bool) -> int | None:
    """The driver CLIs' ``--resume`` contract (kmeans/mfsgd/lda share it).

    A rerun pointing at a populated ``--ckpt-dir`` always resumes (the
    recovery loop restores whatever is newest); ``--resume`` makes that
    intent CHECKED: it requires ``--ckpt-dir`` and at least one saved
    checkpoint, so a mistyped directory fails loudly instead of silently
    training a fresh model from epoch 0.  Returns the step that will be
    resumed from (None without ``--resume``); raises SystemExit with an
    actionable message otherwise.
    """
    if not resume:
        return None
    if not ckpt_dir:
        raise SystemExit(
            "--resume requires --ckpt-dir (it names the run to resume)")
    from harp_tpu.utils.checkpoint import CheckpointManager

    latest = CheckpointManager(ckpt_dir).latest_step()
    if latest is None:
        raise SystemExit(
            f"--resume: no checkpoints under {ckpt_dir} — nothing to "
            "resume from (drop --resume to start a fresh run there)")
    return latest


def fit_epochs(
    train_one: Callable[[], Any],
    get_state: Callable[[], Any],
    set_state: Callable[[Any], None],
    epochs: int,
    ckpt_dir: str | None = None,
    *,
    ckpt_every: int = 5,
    max_restarts: int = 3,
    fault: "FaultInjector | None" = None,
    phase: str = "fit",
) -> None:
    """Epoch-loop driver with optional checkpoint/resume — shared by the
    model ``fit`` methods (MF-SGD, LDA).

    ``get_state`` returns the model's checkpointable pytree (live device
    arrays are fine); ``set_state`` installs a state that may be numpy
    (fresh restore) or live arrays (normal step-to-step flow).  Contract
    guarantees, locked in by tests:
    - a crash before the first checkpoint restarts from the state at THIS
      call's entry (snapshotted host-side), never from crash-time state;
    - a resume with no epochs left still installs the restored state;
    - ``fault`` without ``ckpt_dir`` is refused rather than ignored.

    ``phase`` names the run on the superstep timeline (PR 18): with
    telemetry on, the whole call is one :func:`harp_tpu.utils.steptrace.
    run` and every ``train_one`` a terminated superstep span; zero-cost
    and span-free when telemetry is off.
    """
    from harp_tpu.utils import steptrace

    if ckpt_dir is None:
        if fault is not None:
            raise ValueError(
                "fault injection requires ckpt_dir (recovery restarts from "
                "checkpoints; without one the injector would be silently "
                "ignored)")
        with steptrace.run(phase):
            for i in range(epochs):
                with steptrace.superstep(phase, i):
                    train_one()
        return

    import numpy as np

    from harp_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir)
    # snapshot the entry state: a crash before the first checkpoint must
    # restart from these values (double-applying epochs trains silently
    # wrong).  Skipped when a checkpoint already exists — every restart
    # then restores from disk, so the host-side copy would be dead weight.
    import jax

    init = None if mgr.latest_step() is not None \
        else jax.tree.map(np.asarray, get_state())

    def step(i, state):
        set_state(state)
        with steptrace.superstep(phase, i):
            train_one()
        return get_state()

    with steptrace.run(phase):
        final = run_with_recovery(lambda: init, step, epochs, mgr,
                                  ckpt_every=ckpt_every,
                                  max_restarts=max_restarts, fault=fault)
    # a resume that had nothing left to run still must land in the model
    set_state(final)


def run_with_recovery(
    make_state: Callable[[], Any],
    step: Callable[[int, Any], Any],
    n_iters: int,
    ckpt,
    *,
    ckpt_every: int = 10,
    max_restarts: int = 3,
    fault: FaultInjector | None = None,
    on_permanent: Callable[[PermanentWorkerLoss], None] | None = None,
) -> Any:
    """Fail-fast iterate-with-restart — the YARN retry loop, in-framework.

    Runs ``state = step(i, state)`` for ``i in [0, n_iters)``, checkpointing
    every ``ckpt_every`` iterations through ``ckpt``
    (:class:`harp_tpu.utils.checkpoint.CheckpointManager`).  On any
    exception the job restarts from the latest checkpoint — or from
    ``make_state()`` if none exists — up to ``max_restarts`` times, then
    re-raises.  Matches Harp's whole-job-retry semantics but resumes from
    the last checkpoint instead of iteration 0.

    ``on_permanent`` (PR 15, the elastic half): a
    :class:`PermanentWorkerLoss` cannot be absorbed by restarting on the
    same mesh, so without a handler it re-raises immediately (fail
    loudly, not a crash loop).  With one, the handler shrinks the
    execution context to the survivors (``harp_tpu.elastic`` rebuilds
    the model on a survivor submesh and replays the repartition plan)
    and the loop resumes from the latest checkpoint like any other
    restart — the handler's own loss budget (``max_worker_loss``)
    bounds how many times this can happen, so permanent losses do not
    consume ``max_restarts``.
    """
    restarts = 0
    first = True
    while True:
        latest = ckpt.latest_step()
        if latest is None:
            start, state = 0, make_state()
        else:
            start, state = ckpt.restore()
            start += 1
            if not first:
                # any restart's restore (transient or post-shrink) is a
                # ckpt:restore mark on the superstep timeline (PR 18);
                # a fresh call resuming a populated dir is not a restart
                from harp_tpu.utils import steptrace

                steptrace.tracer.note_restore(start)
        first = False
        try:
            for i in range(start, n_iters):
                if fault is not None:
                    fault.check(i)
                state = step(i, state)
                if (i + 1) % ckpt_every == 0 or i == n_iters - 1:
                    ckpt.save(i, state)
            return state
        except PermanentWorkerLoss as e:
            if on_permanent is None:
                raise  # no elastic handler: a same-mesh retry only re-dies
            log.warning("permanent loss of worker %s (%s); shrinking to "
                        "survivors and resuming from step %s",
                        e.worker, e, ckpt.latest_step())
            on_permanent(e)  # raises when the loss budget is exhausted
        except Exception as e:  # noqa: BLE001 - the whole point
            restarts += 1
            if restarts > max_restarts:
                log.error("job failed after %d restarts: %s", max_restarts, e)
                raise
            log.warning("worker failure (%s); restart %d/%d from step %s",
                        e, restarts, max_restarts, ckpt.latest_step())
            time.sleep(0)  # yield; real deployments would re-init devices here
