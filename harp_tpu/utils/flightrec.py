"""Execution flight recorder — compile / transfer / dispatch telemetry
with enforceable budget guards.

Reference parity (SURVEY.md §6): Harp has no execution-side accounting at
all — its observability stops at per-iteration wall-clock logs, and even
harp-tpu's CommLedger (PR 1) only accounts for *collective* bytes.  Yet
the measured walls on this project are execution-side (CLAUDE.md "Relay
performance traps", all measured 2026-07-30 on the relay-attached v5e):
~140 ms per silent recompile, a 30-40 MB/s H2D ingest tunnel, 20-150 ms
per dispatch/readback round trip.  This module
is the third telemetry spine beside CommLedger/SpanTracer, turning each
of those traps into a machine-checked invariant that runs on the CPU
backend with zero hardware:

**CompileWatch** — subscribes to ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` event (fired for every
XLA backend compile, local or relay-remote; graceful no-op when a jax
version lacks the hook — see ``COMPILE_EVENTS_AVAILABLE``) and records
count, duration, and the active :class:`~harp_tpu.utils.telemetry.
SpanTracer` span — so a recompile inside a timed region is *detected*,
not re-derived by hand from wall-clock anomalies.

**TransferLedger** — counts H2D/D2H bytes and dispatch round trips per
call site and active span.  The project's transfer entry points feed it:
``WorkerMesh.shard_array``/``shard_array_local`` (H2D), :func:`readback`
and ``timing.device_sync`` (blocking D2H round trips), :func:`track`-
wrapped jitted callables (dispatches), and
``dispatch.bucket_by_destination`` (trace-time exchange-buffer bytes).

**budget()** — ``with flightrec.budget(compiles=1, readbacks=1): ...``
snapshots the counters and, on exit, raises :class:`BudgetExceeded`
(tests) or warns (bench, ``action="warn"``) when a delta exceeds its
bound.  The CLAUDE.md traps map directly: ``compiles=N`` catches
PRNGKey-specialization recompiles, ``readbacks=1`` catches per-epoch
readback loops, ``h2d_bytes=B`` catches re-uploading a resident table.

Everything shares the CommLedger's enable switch (``HARP_TELEMETRY=1`` /
``telemetry.enable()``) and its **zero-cost when disabled** contract:
every entry point returns before touching arrays or counters, byte math
comes from shape/dtype only, and no instrumentation ever adds a device
dispatch — the traced program is bit-identical with telemetry on or off
(tested in tests/test_flightrec.py).
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import warnings
from typing import Any, Callable

from harp_tpu.utils import telemetry

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_PROV_FIELDS = ("backend", "date", "commit")


def _call_site() -> str:
    """Nearest user frame outside this module / the wrapped entry-point
    modules / jax — same contract as ``telemetry._call_site`` but skipping
    the transfer wrappers (mesh/timing/dispatch) instead of collective."""
    import jax

    jax_dir = os.path.dirname(os.path.abspath(jax.__file__))
    here = os.path.dirname(os.path.abspath(__file__))  # utils/
    skip_tails = ("parallel/mesh.py", "parallel/dispatch.py")
    f = sys._getframe(1)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        base = os.path.basename(fn)
        if (not fn.startswith(jax_dir)
                and not fn.endswith(skip_tails)
                and os.path.dirname(fn) != here
                and "contextlib" not in base):
            return f"{base}:{f.f_lineno}"
        f = f.f_back
    return "?:0"


# ---------------------------------------------------------------------------
# CompileWatch
# ---------------------------------------------------------------------------

class CompileWatch:
    """Every XLA backend compile, with duration and active span."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.records: list[dict] = []  # {"dur", "span", "t"} per compile

    def on_compile(self, duration: float) -> None:
        import time

        self.count += 1
        self.total_s += float(duration)
        # "t": completion offset on the SpanTracer's clock, so the
        # compile lands on telemetry.export_timeline next to the host
        # span it fired under (PR 12)
        self.records.append({"dur": round(float(duration), 6),
                             "span": telemetry.tracer.current_path(),
                             "t": round(time.perf_counter()
                                        - telemetry.tracer._t0, 6)})
        from harp_tpu.utils import steptrace

        if steptrace.tracer._run is not None:  # PR 18 superstep mark
            steptrace.tracer.on_compile(duration)

    def summary(self) -> dict:
        """{"count", "total_s", "by_span": {span_path: {count, total_s}}}."""
        by_span: dict[str, dict] = {}
        for r in self.records:
            s = by_span.setdefault(r["span"] or "(no span)",
                                   {"count": 0, "total_s": 0.0})
            s["count"] += 1
            s["total_s"] = round(s["total_s"] + r["dur"], 6)
        return {"count": self.count, "total_s": round(self.total_s, 6),
                "by_span": by_span}

    def export_jsonl(self, fh, stamp: dict | None = None) -> None:
        """One row per compile; ``count``/``total_s`` are CUMULATIVE so
        scripts/check_jsonl.py can enforce monotonicity (invariant 4)."""
        cum = 0.0
        for i, r in enumerate(self.records):
            cum = round(cum + r["dur"], 6)
            row = {"kind": "compile", "event": "backend_compile",
                   "count": i + 1, "dur": r["dur"], "total_s": cum,
                   "span": r["span"], "t": r.get("t"), **(stamp or {})}
            fh.write(json.dumps(row) + "\n")


def _on_monitoring_event(event: str, duration: float, **kw: Any) -> None:
    # registered once per process; the enabled() check keeps the listener
    # zero-cost for every un-instrumented run in the same process
    if event == _BACKEND_COMPILE_EVENT and telemetry.enabled():
        compile_watch.on_compile(duration)


def _install_compile_listener() -> bool:
    """Subscribe to backend-compile events; False (and every CompileWatch
    stays silently empty) on a jax without the monitoring hook."""
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(_on_monitoring_event)
        return True
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        return False


# ---------------------------------------------------------------------------
# TransferLedger
# ---------------------------------------------------------------------------

class TransferLedger:
    """H2D/D2H bytes and dispatch round trips per (op, site, span).

    Ops: ``h2d`` (host→device placement), ``readback`` (blocking
    device→host fetch — the D2H path in this codebase is always a round
    trip), ``dispatch`` (one invocation of a :func:`track`-wrapped jitted
    callable), ``bucket`` (trace-time all_to_all exchange-buffer bytes
    staged by ``dispatch.bucket_by_destination`` — capacity slots ride
    the wire whether or not they carry items).
    """

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.h2d_bytes = 0
        self.h2d_calls = 0
        self.d2h_bytes = 0
        self.readbacks = 0
        self.dispatches = 0
        self.bucket_bytes = 0
        # (op, site, span) -> {"op","site","span","bytes","calls"}
        self._sites: dict[tuple, dict] = {}

    def _rec(self, op: str, nbytes: int, site: str | None) -> None:
        site = site or _call_site()
        span = telemetry.tracer.current_path()
        key = (op, site, span)
        r = self._sites.setdefault(
            key, {"op": op, "site": site, "span": span, "bytes": 0,
                  "calls": 0})
        r["bytes"] += int(nbytes)
        r["calls"] += 1

    def record_h2d(self, nbytes: int, site: str | None = None) -> None:
        self.h2d_bytes += int(nbytes)
        self.h2d_calls += 1
        self._rec("h2d", nbytes, site)

    def record_readback(self, nbytes: int = 0,
                        site: str | None = None) -> None:
        self.d2h_bytes += int(nbytes)
        self.readbacks += 1
        self._rec("readback", nbytes, site)

    def record_dispatch(self, site: str | None = None) -> None:
        self.dispatches += 1
        self._rec("dispatch", 0, site)

    def record_bucket(self, nbytes: int, site: str | None = None) -> None:
        self.bucket_bytes += int(nbytes)
        self._rec("bucket", nbytes, site)

    def summary(self) -> dict:
        sites = sorted(self._sites.values(),
                       key=lambda r: (-r["bytes"], r["op"], r["site"]))
        return {"h2d_bytes": self.h2d_bytes, "h2d_calls": self.h2d_calls,
                "d2h_bytes": self.d2h_bytes, "readbacks": self.readbacks,
                "dispatches": self.dispatches,
                "bucket_bytes": self.bucket_bytes,
                "sites": [dict(r) for r in sites]}

    def export_jsonl(self, fh, stamp: dict | None = None) -> None:
        for r in sorted(self._sites.values(),
                        key=lambda r: (r["op"], r["site"])):
            fh.write(json.dumps({"kind": "transfer", **r,
                                 **(stamp or {})}) + "\n")


# ---------------------------------------------------------------------------
# Module singletons + zero-cost entry points
# ---------------------------------------------------------------------------

compile_watch = CompileWatch()
transfers = TransferLedger()
COMPILE_EVENTS_AVAILABLE = _install_compile_listener()


def reset() -> None:
    """Clear both collectors (telemetry.scope does this on entry)."""
    compile_watch.reset()
    transfers.reset()


def record_h2d(nbytes: int, site: str | None = None) -> None:
    """Hook for host→device placement entry points (mesh.shard_array)."""
    if _H2D_OBSERVERS:
        for cb in tuple(_H2D_OBSERVERS):
            cb(nbytes, site)
    if telemetry.enabled():
        transfers.record_h2d(nbytes, site)
        from harp_tpu.utils import memrec

        memrec.on_staged(nbytes, site or _call_site())


def record_readback(nbytes: int = 0, site: str | None = None) -> None:
    """Hook for blocking device→host fetches (timing.device_sync)."""
    if telemetry.enabled():
        transfers.record_readback(nbytes, site)


def record_bucket(nbytes: int, site: str | None = None) -> None:
    """Trace-time hook for capacity-bucket staging (parallel.dispatch)."""
    if telemetry.enabled():
        transfers.record_bucket(nbytes, site)


# Observer hooks: audit/chaos layers watch the instrumented execution
# paths without riding the telemetry enable switch — the commgraph
# donation audit (HL303) watches readbacks to catch a host re-read of a
# donated buffer, and the fault plane (utils.fault.FaultInjector, PR 10)
# rides all four to fail/delay dispatch, H2D, readback, and
# checkpoint-write sites on a seeded schedule.  Every list is empty in an
# un-observed run, so the hot-path cost is one falsy check per event;
# observers see the ORIGINAL arguments (e.g. the device array, before
# np.asarray materializes it) and may raise — a raising observer aborts
# the observed operation BEFORE it is counted or performed, modeling a
# transient failure in flight.
_READBACK_OBSERVERS: list[Callable[[Any], None]] = []
_DISPATCH_OBSERVERS: list[Callable[[str], None]] = []
_H2D_OBSERVERS: list[Callable[[int, Any], None]] = []
_CKPT_WRITE_OBSERVERS: list[Callable[[str], None]] = []


@contextlib.contextmanager
def _observe(registry: list, cb: Callable):
    registry.append(cb)
    try:
        yield
    finally:
        registry.remove(cb)


def observe_readbacks(cb: Callable[[Any], None]):
    """Register ``cb`` to see every :func:`readback` argument within the
    block (the donation audit's hook; independent of the telemetry
    enable switch — an audit must see reads even with telemetry off)."""
    return _observe(_READBACK_OBSERVERS, cb)


def observe_dispatches(cb: Callable[[str], None]):
    """``cb(label)`` before every :func:`track`-wrapped dispatch — fired
    BEFORE the dispatch is counted or launched, so a raising observer
    models a dispatch that never reached the device (the counters stay
    exact: only launched dispatches count)."""
    return _observe(_DISPATCH_OBSERVERS, cb)


def observe_h2d(cb: Callable[[int, Any], None]):
    """``cb(nbytes, site)`` before every counted host→device placement
    (``mesh.shard_array``/``shard_array_local``)."""
    return _observe(_H2D_OBSERVERS, cb)


def observe_ckpt_writes(cb: Callable[[str], None]):
    """``cb(path)`` at the START of every ``CheckpointManager.save`` —
    before any byte lands on disk, so a raising observer models a crash
    mid-write (the atomic tmp-dir rename must make that unobservable to
    readers)."""
    return _observe(_CKPT_WRITE_OBSERVERS, cb)


def notify_ckpt_write(path: str) -> None:
    """Hook for checkpoint-write entry points (checkpoint.save)."""
    if _CKPT_WRITE_OBSERVERS:
        for cb in tuple(_CKPT_WRITE_OBSERVERS):
            cb(path)


def readback(x: Any):
    """``np.asarray(x)`` that counts the D2H round trip — THE instrumented
    device→host fetch for driver code (zero-cost ``np.asarray`` when
    telemetry is off)."""
    import numpy as np

    if _READBACK_OBSERVERS:
        for cb in tuple(_READBACK_OBSERVERS):
            cb(x)
    out = np.asarray(x)
    if telemetry.enabled():
        transfers.record_readback(out.nbytes)
    return out


class _Tracked:
    """:func:`track`'s wrapper — counts one dispatch per call, delegates
    every other attribute (``lower``, ``trace``, ...) to the wrapped
    callable so a tracked ``jax.jit`` keeps its full surface."""

    __slots__ = ("__wrapped__", "_label")

    def __init__(self, fn: Callable, label: str):
        self.__wrapped__ = fn
        self._label = label

    def __call__(self, *args, **kw):
        if _DISPATCH_OBSERVERS:  # BEFORE counting: a raising observer
            for cb in tuple(_DISPATCH_OBSERVERS):  # models a dispatch
                cb(self._label)                    # that never launched
        if telemetry.enabled():
            transfers.record_dispatch(self._label)
            from harp_tpu.utils import memrec

            memrec.on_dispatch(self._label, args)
            out = self.__wrapped__(*args, **kw)
            memrec.on_output(self._label, out)
            return out
        return self.__wrapped__(*args, **kw)

    def __getattr__(self, name):
        return getattr(self.__wrapped__, name)


def track(fn: Callable, label: str,
          donate_argnums: tuple[int, ...] | None = None) -> Callable:
    """Wrap a jitted callable so each invocation counts one dispatch
    round trip under ``label``.  The wrapper adds one Python ``if`` per
    call and never touches the arguments — the traced program and its
    dispatch count are identical with telemetry on or off.

    ``donate_argnums`` (PR 19) declares the callable's donation
    signature to the memory ledger: at each call memrec claims the
    newest live buffers matching the donated args' byte sizes and
    records them leaving the live set (the runtime twin of HL303) —
    metadata only, the args are never materialized."""
    if donate_argnums is not None:
        from harp_tpu.utils import memrec

        memrec.register_dispatch(label, donate_argnums)
    return _Tracked(fn, label)


# ---------------------------------------------------------------------------
# Budget guard
# ---------------------------------------------------------------------------

class BudgetExceeded(RuntimeError):
    """A flight-recorder budget was violated (see :func:`budget`)."""


_BUDGET_KEYS = ("compiles", "compile_s", "h2d_bytes", "h2d_calls",
                "dispatches", "readbacks", "d2h_bytes")


def snapshot() -> dict:
    """Current cumulative counters (the budget guard's baseline; bench.py
    uses deltas between snapshots for its per-config flight block)."""
    return {"compiles": compile_watch.count,
            "compile_s": round(compile_watch.total_s, 6),
            "h2d_bytes": transfers.h2d_bytes,
            "h2d_calls": transfers.h2d_calls,
            "dispatches": transfers.dispatches,
            "readbacks": transfers.readbacks,
            "d2h_bytes": transfers.d2h_bytes}


def delta_since(base: dict) -> dict:
    now = snapshot()
    return {k: (round(now[k] - base[k], 6) if k == "compile_s"
                else now[k] - base[k]) for k in _BUDGET_KEYS}


class _BudgetScope:
    """Yielded by :func:`budget`: ``spent()`` reads the live deltas."""

    def __init__(self, base: dict):
        self._base = base

    def spent(self) -> dict:
        return delta_since(self._base)


def _notify_health(tag: str, over: list[tuple[str, Any, Any]]) -> None:
    """WARN-mode violations also land on the health monitor's
    budget-drift detector (PR 14) — a trap that fires mid-bench leaves
    committed evidence instead of a scrolled RuntimeWarning.  Raise-mode
    violations are already loud (they kill the test); only warn mode
    needs the paper trail."""
    from harp_tpu import health

    health.monitor.observe_budget(tag, over)


@contextlib.contextmanager
def budget(compiles: int | None = None, h2d_bytes: int | None = None,
           dispatches: int | None = None, readbacks: int | None = None,
           d2h_bytes: int | None = None, h2d_calls: int | None = None,
           *, action: str = "raise", tag: str = ""):
    """Enforce execution-discipline bounds over a block.

    Each keyword is an inclusive upper bound on that counter's *delta*
    across the block (None = unbounded).  On violation: ``action="raise"``
    raises :class:`BudgetExceeded` naming every exceeded counter (the
    tests' mode); ``action="warn"`` emits a ``RuntimeWarning`` and
    continues (the bench mode — a relay sprint must record the number,
    not die).  The CLAUDE.md relay traps (measured 2026-07-30, v5e) map
    one-to-one:

    - ``compiles=N``: a silent re-trace (e.g. ``PRNGKey(python_int)``
      baked into a per-step jit) blows the compile count;
    - ``readbacks=1``: per-epoch readback loops instead of one stacked
      readback per run;
    - ``h2d_bytes=B``: re-uploading device-resident data through the
      30-40 MB/s relay tunnel;
    - ``dispatches=N``: per-epoch dispatch instead of one scanned program.

    No-op (yields without snapshotting) when telemetry is disabled —
    enable with ``HARP_TELEMETRY=1`` or ``telemetry.scope()`` first, or
    the guard guards nothing.  If the block raises, the original
    exception propagates unchecked.
    """
    if not telemetry.enabled():
        yield None
        return
    limits = {"compiles": compiles, "h2d_bytes": h2d_bytes,
              "h2d_calls": h2d_calls, "dispatches": dispatches,
              "readbacks": readbacks, "d2h_bytes": d2h_bytes}
    scope_ = _BudgetScope(snapshot())
    yield scope_
    spent = scope_.spent()
    over = [(name, spent[name], limit)
            for name, limit in limits.items()
            if limit is not None and spent[name] > limit]
    if over:
        msg = (f"flight-recorder budget exceeded"
               f"{f' [{tag}]' if tag else ''}: "
               + "; ".join(f"{n} used {s} > budget {l}"
                           for n, s, l in over))
        if action == "warn":
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
            _notify_health(tag or _call_site(), over)
        else:
            raise BudgetExceeded(msg)


class SteadyState:
    """Per-batch budget for long-lived loops (the ``harp serve`` guard).

    :func:`budget` guards one block; a serving loop needs the same bound
    re-applied to every batch forever, plus an account of how the steady
    state actually spent — so the bench row can *prove* "0 compiles in
    steady state" rather than assert it.  Usage::

        steady = flightrec.SteadyState(compiles=0, dispatches=1,
                                       readbacks=1, tag="serve.kmeans")
        for batch in batches:
            with steady.batch():
                out = exe(*state, x)        # 1 tracked dispatch
                res = flightrec.readback(out)  # 1 stacked readback
        steady.summary()  # {"batches", "violations", + counter deltas}

    ``action="raise"`` (default) raises :class:`BudgetExceeded` on the
    offending batch (tests); ``action="warn"`` warns and keeps serving,
    counting the violation (production — a server must not die because
    one batch recompiled, but the row must say it happened).  Like
    :func:`budget`, a batch is a no-op while telemetry is disabled.
    """

    def __init__(self, compiles: int | None = 0,
                 dispatches: int | None = 1, readbacks: int | None = 1,
                 h2d_bytes: int | None = None,
                 d2h_bytes: int | None = None,
                 h2d_calls: int | None = None, *,
                 action: str = "raise", tag: str = "steady"):
        self.limits = {"compiles": compiles, "dispatches": dispatches,
                       "readbacks": readbacks, "h2d_bytes": h2d_bytes,
                       "d2h_bytes": d2h_bytes, "h2d_calls": h2d_calls}
        self.action = action
        self.tag = tag
        self.reset()

    def reset(self) -> None:
        """Start a fresh steady-state window (server startup calls this
        so startup compiles never count against the steady summary)."""
        self.batches = 0
        self.violations = 0
        self._base = snapshot() if telemetry.enabled() else None

    @contextlib.contextmanager
    def batch(self):
        if not telemetry.enabled():
            yield None
            return
        if self._base is None:  # telemetry enabled after construction
            self._base = snapshot()
        base = snapshot()
        yield None
        spent = delta_since(base)
        self.batches += 1
        over = [(k, spent[k], v) for k, v in self.limits.items()
                if v is not None and spent[k] > v]
        if over:
            self.violations += 1
            msg = (f"steady-state budget exceeded [{self.tag}] batch "
                   f"{self.batches}: "
                   + "; ".join(f"{k} used {s} > budget {v}"
                               for k, s, v in over))
            if self.action == "warn":
                warnings.warn(msg, RuntimeWarning, stacklevel=3)
                _notify_health(self.tag, over)
            else:
                raise BudgetExceeded(msg)

    def summary(self) -> dict:
        """Batch/violation counts + cumulative counter deltas since
        :meth:`reset` (deltas absent when telemetry never enabled)."""
        out = {"batches": self.batches, "violations": self.violations}
        if self._base is not None:
            out.update(delta_since(self._base))
        return out

    def verify_exact(self, batches: int, *, compiles: int = 0) -> dict:
        """Overlap-mode exact accounting: the continuous serving loop
        dispatches batch t+1 before it reads batch t back, so one
        :meth:`batch` window no longer pairs a dispatch with ITS
        readback — the per-window budget still bounds each window, but
        only the totals can prove the pipeline stayed exact.  Asserts
        that since :meth:`reset` the loop spent EXACTLY one dispatch
        and one readback per dispatched batch and exactly ``compiles``
        compiles: over-spending is the classic trap, and UNDER-spending
        means work bypassed the tracked executables (equally wrong —
        an untracked dispatch is invisible to every budget).  Returns
        the spent dict; raises/warns per the instance ``action``.
        No-op ({} returned) when telemetry never enabled.
        """
        if self._base is None:
            return {}
        spent = delta_since(self._base)
        wrong = [(k, spent[k], want)
                 for k, want in (("compiles", compiles),
                                 ("dispatches", batches),
                                 ("readbacks", batches))
                 if spent[k] != want]
        if wrong:
            self.violations += 1
            msg = (f"steady-state exact accounting failed [{self.tag}] "
                   f"over {batches} batches: "
                   + "; ".join(f"{k} spent {s} != exactly {w}"
                               for k, s, w in wrong))
            if self.action == "warn":
                warnings.warn(msg, RuntimeWarning, stacklevel=2)
                _notify_health(self.tag, wrong)
            else:
                raise BudgetExceeded(msg)
        return spent


# ---------------------------------------------------------------------------
# Calibrated overheads (the perfmodel readout)
# ---------------------------------------------------------------------------

#: Fixed per-operation costs of the execution plane, calibrated from the
#: measured flight-recorder deltas (CLAUDE.md "Relay performance traps",
#: all measured 2026-07-30 on the relay-attached v5e) — the offline cost
#: model (:mod:`harp_tpu.perfmodel`) reads THESE numbers for its
#: ``overhead`` term, so the trap list and the model can never disagree
#: about what a dispatch costs.  Values are the measured FLOORS (the
#: round-trip band was 20–150 ms; a ranking model must not flatter the
#: incumbent by charging the ceiling to every candidate equally):
#:
#: - ``dispatch_s`` / ``readback_s``: one driver→device round trip /
#:   one blocking D2H fetch (the budget(dispatches=1, readbacks=1)
#:   discipline makes a run pay each exactly once);
#: - ``compile_s``: one fresh XLA backend compile shipped over the relay
#:   (the ~140 ms PRNGKey-specialization recompile, HL002);
#: - ``h2d_gbs``: the relay ingest tunnel rate (30–40 MB/s measured;
#:   the floor keeps H2D-bound predictions honest — the tunnel, not
#:   PCIe, is the wall).
CALIBRATED_OVERHEADS = {
    "dispatch_s": 0.020,
    "readback_s": 0.020,
    "compile_s": 0.140,
    "h2d_gbs": 0.030e9,
}


def calibrated_overheads() -> dict:
    """A copy of :data:`CALIBRATED_OVERHEADS` (the perfmodel entry
    point; a copy so a consumer mutating its dict cannot silently
    recalibrate everyone else's)."""
    return dict(CALIBRATED_OVERHEADS)


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------

def provenance_stamp() -> dict:
    """backend/date/commit triple for exported rows — compile/transfer
    rows are *evidence about a specific backend* (a CPU-sim compile count
    must never read as relay-compile evidence), so unlike comm/span rows
    they carry the same stamp scripts/check_jsonl.py demands of bench
    rows (invariant 4)."""
    from harp_tpu.utils.metrics import _provenance

    prov = _provenance()
    return {k: prov.get(k) for k in _PROV_FIELDS}


def export_jsonl(fh) -> None:
    """Append compile + transfer rows (telemetry.export calls this)."""
    if not compile_watch.records and not transfers._sites:
        return
    stamp = provenance_stamp()
    compile_watch.export_jsonl(fh, stamp)
    transfers.export_jsonl(fh, stamp)
