"""Superstep skew profiler — per-worker load and straggler attribution.

Reference parity (SURVEY.md §3.1, §3.5): Harp's whole reason to exist is
*balanced* Map-Collective supersteps — the timer-bounded
``schdynamic.DynamicScheduler`` and the ``edu.iu.dymoro`` rotation
pipeline are load-balancing machinery, because a BSP superstep runs at
the pace of its slowest worker.  The first three telemetry spines
(CommLedger/SpanTracer, :mod:`harp_tpu.utils.flightrec`) are
worker-blind: they can say how many bytes moved and how many dispatches
ran, but not "worker 3 holds 1.6x the nonzeros and is the wall".  This
module is the fourth spine: a **SkewLedger** recording per-worker work
volume at the three places it is cheaply knowable, an imbalance model
turning max/mean load ratios into predicted wasted chip-seconds (composed
with :mod:`harp_tpu.utils.roofline` so waste reads in percent-of-peak),
and :func:`SkewLedger.suggest_rebalance` — the greedy repartition plan
:mod:`harp_tpu.schedule` / the partitioners can apply, bridging
observation back to Harp's dynamic-scheduler behavior.

The three record points:

- **ingest** (:func:`record_partition`) — the :mod:`harp_tpu.fileformat`
  readers and the lda/mfsgd/subgraph/rf partitioners report per-shard
  real rows/nonzeros and the padding fraction at partition time.  Pure
  host arithmetic over arrays the partitioner already built: zero device
  cost.  ``units`` optionally carries the movable grains (e.g. files
  with byte sizes) so the rebalance plan can move whole units.
- **execution** (:func:`record_execution`) — the kmeans/lda/mfsgd epoch
  drivers fold a tiny per-worker work counter (active rows / tokens
  touched) into their EXISTING stacked readback, so the flagship flight
  budgets stay at 1 dispatch / 1 readback per run (pinned in
  tests/test_flightrec.py).  KMeans folds its per-worker row count into
  the same [nw, 2] stats array as the inertia — no extra collective, so
  the hand-computed comm byte sheet (tests/test_telemetry.py) is
  untouched.
- **host phases** (:func:`record_host`) — ``scripts/scaling_sweep.py``
  subprocesses and the multiprocess (Gloo) path
  (:meth:`harp_tpu.mapper.CollectiveApp.run`) stamp per-process
  wall-clock per superstep, covering skew the device counters cannot
  see (file parsing, host prep).

Everything shares the telemetry enable switch (``HARP_TELEMETRY=1`` /
``telemetry.enable()``) and the zero-cost-when-disabled contract: the
module-level hooks return before touching arrays.  The per-worker device
counters themselves are *unconditionally* part of the traced epoch
programs (a telemetry-gated output would make the traced program differ
with the flag, breaking the bit-identical on/off contract the flight
recorder tests pin) — they cost O(num_workers) floats per superstep.

The imbalance model: for per-worker work ``w`` with ``r = max(w) /
mean(w)``, a barrier superstep finishes when the max-loaded worker does,
so the fraction of total chip-time spent idle-waiting is ``1 - mean/max``
and the predicted waste for a phase that took ``wall_s`` is ``wall_s *
n_workers * (1 - mean/max)`` chip-seconds.  :func:`wasted_pct_of_peak`
composes that with the roofline annotation: of the percent-of-peak the
config achieves, the points predicted lost to skew.
"""

from __future__ import annotations

import json
from typing import Any, Sequence

import numpy as np

from harp_tpu.utils import telemetry


class SkewLedger:
    """Per-phase, per-worker work accounting (see module docstring).

    One record per phase name; re-recording a phase overwrites its work
    vector (latest superstep wins — work is per-superstep, and a rerun
    re-measures the same corpus) while ``runs`` counts how often.
    """

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._phases: dict[str, dict] = {}

    # -- recording ----------------------------------------------------------
    def _put(self, phase: str, source: str, work, unit: str, **extra) -> None:
        w = np.asarray(work, np.float64).reshape(-1)
        rec = self._phases.get(phase)
        if rec is None or rec["source"] != source or len(rec["work"]) != len(w):
            rec = self._phases[phase] = {
                "phase": phase, "source": source, "unit": unit,
                "work": w, "runs": 0, "padding_frac": None, "wall_s": None,
                "units": None}
        rec["work"] = w
        rec["unit"] = unit
        rec["runs"] += 1
        for k, v in extra.items():
            if v is not None:
                rec[k] = v

    def record_partition(self, phase: str, work, *, unit: str = "rows",
                         padded_total: int | None = None,
                         units: Sequence[Sequence[tuple]] | None = None
                         ) -> None:
        """Ingest-time record: ``work[w]`` = real items on worker ``w``.

        ``padded_total`` is the total slot count after shape padding
        (``padding_frac = 1 - sum(work)/padded_total``); ``units`` is an
        optional per-worker list of movable ``(unit_id, size)`` grains
        (e.g. files) that :meth:`suggest_rebalance` can move whole.
        """
        pf = None
        if padded_total:
            pf = max(0.0, min(1.0, 1.0 - float(np.sum(np.asarray(
                work, np.float64))) / float(padded_total)))
        self._put(phase, "ingest", work, unit, padding_frac=pf,
                  units=[list(u) for u in units] if units is not None
                  else None)

    def record_execution(self, phase: str, work, *, unit: str,
                         wall_s: float | None = None,
                         units: Sequence[Sequence[tuple]] | None = None
                         ) -> None:
        """Execution record: ``work[w]`` = work units worker ``w``
        actually processed this superstep (from the driver's stacked
        readback); ``wall_s`` is the measured host wall for the phase,
        the basis of the wasted-chip-seconds prediction.

        ``units`` (PR 15): optional per-worker movable ``(unit_id,
        size)`` grains, exactly as :meth:`record_partition` takes them.
        The elastic drivers attach their pack grains here so the health
        sentinel's ``skew_trigger`` carries a WHOLE-UNIT
        ``suggest_rebalance`` plan — the shape
        ``schedule.apply_rebalance`` replays mid-run."""
        self._put(phase, "execution", work, unit,
                  wall_s=None if wall_s is None else float(wall_s),
                  units=[list(u) for u in units] if units is not None
                  else None)

    def record_host(self, phase: str, worker: int, wall_s: float,
                    n_workers: int | None = None) -> None:
        """Host-phase record: process ``worker`` spent ``wall_s`` seconds
        in ``phase`` this superstep.  Each process stamps only its own
        column (the Gloo/multi-host path); single-process callers fill
        worker 0 of a width-``n_workers`` vector."""
        rec = self._phases.get(phase)
        n = n_workers or (len(rec["work"]) if rec else worker + 1)
        n = max(n, worker + 1)
        w = np.zeros(n, np.float64)
        if rec is not None and rec["source"] == "host":
            w[: len(rec["work"])] = rec["work"][:n]
        w[worker] = float(wall_s)
        self._put(phase, "host", w, "seconds", wall_s=float(wall_s))

    # -- the imbalance model ------------------------------------------------
    @staticmethod
    def _imbalance(rec: dict) -> dict:
        w = rec["work"]
        total = float(w.sum())
        mean = total / len(w) if len(w) else 0.0
        mx = float(w.max()) if len(w) else 0.0
        ratio = (mx / mean) if mean > 0 else None
        wasted = (1.0 - mean / mx) if mx > 0 else None
        out = {"max_mean_ratio": None if ratio is None else round(ratio, 4),
               "wasted_frac": None if wasted is None else round(wasted, 4)}
        if wasted is not None and rec.get("wall_s"):
            # a barrier superstep ends when the max-loaded worker does:
            # every other worker idles (1 - w_i/max) of the wall
            out["wasted_chip_s"] = round(
                rec["wall_s"] * len(w) * wasted, 6)
        return out

    def summary(self) -> dict:
        """{phase: {source, unit, work, total, n_workers, max_mean_ratio,
        wasted_frac, [wasted_chip_s], [padding_frac], runs, [wall_s]}},
        most-imbalanced phases first."""
        out = {}
        for phase, rec in self._phases.items():
            row = {"source": rec["source"], "unit": rec["unit"],
                   "work": [round(float(x), 4) for x in rec["work"]],
                   "total": round(float(rec["work"].sum()), 4),
                   "n_workers": len(rec["work"]),
                   "runs": rec["runs"]}
            row.update(self._imbalance(rec))
            for k in ("padding_frac", "wall_s"):
                if rec.get(k) is not None:
                    row[k] = round(rec[k], 6)
            out[phase] = row
        return dict(sorted(out.items(),
                           key=lambda kv: -(kv[1]["max_mean_ratio"] or 0)))

    # -- the scheduler bridge -----------------------------------------------
    def suggest_rebalance(self, phase: str) -> dict | None:
        """Greedy repartition plan toward equal per-worker load.

        With ``units`` recorded (movable grains), re-runs greedy
        longest-processing-time placement over every unit (the same rule
        :func:`harp_tpu.fileformat.multi_file_splits` applies to byte
        sizes, here on MEASURED loads) and emits whole-unit moves that
        :func:`harp_tpu.schedule.apply_rebalance` can apply.  Without
        units the plan is fractional: surplus flows from overloaded to
        underloaded workers until all sit at the mean — the target a
        finer-grained partitioner should aim for.  Returns ``{phase,
        unit, moves, ratio_before, ratio_after, work_after}`` or None
        when the phase is unknown/empty.
        """
        rec = self._phases.get(phase)
        if rec is None or not len(rec["work"]) or rec["work"].sum() <= 0:
            return None
        before = self._imbalance(rec)["max_mean_ratio"]
        n = len(rec["work"])
        moves: list[dict] = []
        if rec.get("units"):
            units = [(uid, float(sz), w)
                     for w, lst in enumerate(rec["units"])
                     for uid, sz in lst]
            loads = np.zeros(n)
            assign: dict[Any, int] = {}
            for uid, sz, _ in sorted(units, key=lambda t: -t[1]):
                tgt = int(loads.argmin())
                assign[uid] = tgt
                loads[tgt] += sz
            for uid, sz, src in units:
                if assign[uid] != src:
                    moves.append({"id": uid, "from": src,
                                  "to": assign[uid], "work": sz})
            after_w = loads
        else:
            w = rec["work"].copy()
            mean = w.mean()
            surplus = [(i, w[i] - mean) for i in range(n) if w[i] > mean]
            deficit = [(i, mean - w[i]) for i in range(n) if w[i] < mean]
            surplus.sort(key=lambda t: -t[1])
            deficit.sort(key=lambda t: -t[1])
            si = di = 0
            while si < len(surplus) and di < len(deficit):
                s_i, s_amt = surplus[si]
                d_i, d_amt = deficit[di]
                amt = min(s_amt, d_amt)
                if amt > 1e-12:
                    moves.append({"from": s_i, "to": d_i,
                                  "work": round(float(amt), 4)})
                    w[s_i] -= amt
                    w[d_i] += amt
                if s_amt <= d_amt:
                    si += 1
                    deficit[di] = (d_i, d_amt - amt)
                if d_amt <= s_amt:
                    di += 1
                    if s_amt > d_amt:
                        surplus[si] = (s_i, s_amt - amt)
            after_w = w
        mean = after_w.mean()
        after = round(float(after_w.max() / mean), 4) if mean > 0 else None
        return {"phase": phase, "unit": rec["unit"], "moves": moves,
                "ratio_before": before, "ratio_after": after,
                "work_after": [round(float(x), 4) for x in after_w]}

    # -- export -------------------------------------------------------------
    def export_jsonl(self, fh, stamp: dict | None = None) -> None:
        """One provenance-stamped row per phase (``kind: "skew"``) — the
        shape scripts/check_jsonl.py invariant 5 validates: per-worker
        ``work`` sums to ``total``, ``padding_frac`` in [0, 1]."""
        for phase, row in self.summary().items():
            out = {"kind": "skew", "phase": phase, **row, **(stamp or {})}
            fh.write(json.dumps(out) + "\n")


# ---------------------------------------------------------------------------
# Module singleton + zero-cost hooks
# ---------------------------------------------------------------------------

ledger = SkewLedger()


def reset() -> None:
    """Clear the ledger (telemetry.scope does this on entry)."""
    ledger.reset()


def record_partition(phase: str, work, *, unit: str = "rows",
                     padded_total: int | None = None,
                     units=None) -> None:
    """Ingest hook for readers/partitioners (no-op when telemetry off).

    Also feeds the health sentinel's skew trigger (PR 14): K consecutive
    records with ``wasted_frac`` over the threshold emit a
    ``kind:"health"`` finding carrying the ``suggest_rebalance`` plan
    inline — the elastic-execution hook the PR-15 drivers consume
    mid-run (:mod:`harp_tpu.elastic`)."""
    if telemetry.enabled():
        ledger.record_partition(phase, work, unit=unit,
                                padded_total=padded_total, units=units)
        from harp_tpu import health

        health.monitor.observe_skew(phase, ledger)


def record_execution(phase: str, work, *, unit: str,
                     wall_s: float | None = None, units=None) -> None:
    """Execution hook for the epoch drivers (no-op when telemetry off).
    Feeds the health sentinel's skew trigger like
    :func:`record_partition` — each call is one superstep's record.
    ``units`` carries the elastic drivers' movable pack grains (PR 15)
    so the fired trigger's inline plan is whole-unit replayable."""
    if telemetry.enabled():
        ledger.record_execution(phase, work, unit=unit, wall_s=wall_s,
                                units=units)
        from harp_tpu.utils import steptrace

        if steptrace.tracer._run is not None:
            # the per-worker lane for the covering superstep (PR 18)
            steptrace.tracer.on_execution(phase, work, unit=unit,
                                          wall_s=wall_s)
        from harp_tpu import health

        health.monitor.observe_skew(phase, ledger)


def record_host(phase: str, worker: int, wall_s: float,
                n_workers: int | None = None) -> None:
    """Host-phase hook (scaling sweep / Gloo path; no-op when off)."""
    if telemetry.enabled():
        ledger.record_host(phase, worker, wall_s, n_workers=n_workers)


def suggest_rebalance(phase: str) -> dict | None:
    """Module-level shorthand for :meth:`SkewLedger.suggest_rebalance`."""
    return ledger.suggest_rebalance(phase)


def wasted_pct_of_peak(config: str, result: dict,
                       phase: str) -> float | None:
    """Skew waste stated in percent-of-peak (the roofline composition).

    ``roofline.annotate(config, result)`` gives the percent of datasheet
    peak the measured rate achieves; the phase's wasted fraction says how
    much of that a balanced partition would reclaim.  None when either
    half is unavailable (no work model, phase unknown, zero work).
    """
    from harp_tpu.utils import roofline

    rec = ledger._phases.get(phase)
    if rec is None:
        return None
    imb = SkewLedger._imbalance(rec)
    if not imb.get("wasted_frac"):
        return None
    ann = roofline.annotate(config, result)
    pct = ann.get("pct_peak_flops")
    if pct is None:
        return None
    return round(pct * imb["wasted_frac"], 3)


def export_jsonl(fh) -> None:
    """Append skew rows (telemetry.export calls this); stamped with the
    flight recorder's provenance triple — a CPU-sim work sheet must never
    read as relay evidence (same inversion guard as invariant 4)."""
    if not ledger._phases:
        return
    from harp_tpu.utils import flightrec

    ledger.export_jsonl(fh, flightrec.provenance_stamp())
