"""Dataclass-based config with CLI override — Hadoop Configuration, retired.

Reference parity (SURVEY.md §6): Harp apps mix Hadoop XML Configuration
key-values with positional CLI args per app, wrapped in shell scripts.
Here each app has one config dataclass; :func:`parse_into` turns any
dataclass into an argparse CLI (field name → ``--flag``, type-checked,
defaults shown), so every launcher is two lines and knobs are
discoverable with ``--help``.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Any, Type, TypeVar

T = TypeVar("T")


def parse_into(cfg_cls: Type[T], argv=None, description: str | None = None,
               **overrides: Any) -> T:
    """Build ``cfg_cls`` from CLI args (``--field-name value``)."""
    assert dataclasses.is_dataclass(cfg_cls), cfg_cls
    p = argparse.ArgumentParser(description=description or cfg_cls.__name__)
    for f in dataclasses.fields(cfg_cls):
        if not f.init:
            continue
        flag = "--" + f.name.replace("_", "-")
        default = f.default
        if default is dataclasses.MISSING and f.default_factory is not dataclasses.MISSING:
            default = f.default_factory()
        default = overrides.get(f.name, default)
        if f.type in (bool, "bool") or isinstance(default, bool):
            p.add_argument(flag, action=argparse.BooleanOptionalAction,
                           default=default)
        elif isinstance(default, (int, float, str)):
            p.add_argument(flag, type=type(default), default=default)
        elif isinstance(default, (tuple, list)) and default:
            elem_t = type(default[0])
            ctor = type(default)

            def conv(s, _t=elem_t, _c=ctor):
                return _c(_t(tok) for tok in str(s).replace(",", " ").split())

            p.add_argument(flag, type=conv, default=default,
                           help=f"comma/space-separated {elem_t.__name__}s")
        else:
            p.add_argument(flag, default=default)
    ns = p.parse_args(argv)
    kwargs = {f.name: getattr(ns, f.name) for f in dataclasses.fields(cfg_cls)
              if f.init and hasattr(ns, f.name)}
    return cfg_cls(**kwargs)
