"""Comm ledger + span tracer — one telemetry spine for the collective layer.

Reference parity (SURVEY.md §6): Harp's observability is log4j iteration
logs plus whatever byte counters Netty exposes per socket; nothing ties "how
many bytes did allreduce move this run" to the app's phases.  TACCL-style
communication *sketches* (PAPERS.md) — structured accounting of which
collectives move how much — are the prerequisite for optimizing them, and
the quantized-wire verbs (`allreduce_quantized`, `push_quantized`) make
EQuARX-style bandwidth claims this module lets a run audit.

Two cooperating pieces:

**CommLedger** — every verb in :mod:`harp_tpu.parallel.collective` calls
:func:`record_comm` at *trace time* (the only time Python runs inside
``shard_map``/jit).  One entry per call site records verb, axis, combiner,
wire dtype, and the per-shard payload bytes summed over the pytree — byte
math comes from ``aval.shape``/``dtype`` only, never per-element work.
Because a cached executable never re-runs Python, trace-time byte counts
must be multiplied by a *host-side execution counter*: wrap each jitted
invocation in :meth:`CommLedger.run` with ``steps`` = how many times the
traced sites execute per program run (epochs of a multi-epoch scan, iters
of a ``fori_loop``, reps of a bench loop).

Re-trace/cache semantics are explicit: each ``run()`` activation opens a
new *generation*; records landing in a generation overwrite (not add to)
the same call site's bytes from earlier generations, and per-execution
volume sums only the most recent generation that recorded anything.  So a
re-traced program (new jit wrapper, same sites) does not double-count, a
cached executable keeps its last traced byte sheet, and a Python chunk loop
hitting one site several times within a single trace still sums correctly.

**SpanTracer** — nested host-level phase spans
(``with span("epoch"): ...``) with JSONL export.  Spans interoperate with
the existing tools: each enabled span also enters
``jax.profiler.TraceAnnotation`` (so host phases show on the XLA trace
timeline next to :func:`harp_tpu.utils.profiling.annotate` regions), and
:meth:`SpanTracer.summary` returns the same ``{name: {mean_s, total_s, n}}``
shape as :class:`harp_tpu.utils.timing.Timer.summary`, so report code can
merge both.

Everything is **zero-cost when disabled** (the default): ``record_comm``
returns before touching the tree, ``span`` yields without bookkeeping, and
neither ever does per-element work — so telemetry can stay on for relay
sprints without perturbing BENCH numbers.  Enable with ``HARP_TELEMETRY=1``
in the environment or :func:`enable` in code; ``HARP_TELEMETRY_OUT=<path>``
makes instrumented CLIs export the raw JSONL for ``python -m harp_tpu
report``.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import Any

_ENABLED = os.environ.get("HARP_TELEMETRY", "0").lower() not in (
    "", "0", "off", "false")


def enabled() -> bool:
    """Is telemetry collection on? (module flag; see :func:`enable`)."""
    return _ENABLED


def enable(on: bool = True) -> None:
    """Turn collection on/off process-wide (tests use :func:`scope`)."""
    global _ENABLED
    _ENABLED = bool(on)


@contextlib.contextmanager
def scope(on: bool = True, *, reset: bool = True):
    """Enable (or disable) telemetry within a block, restoring the prior
    flag on exit; ``reset`` clears every collector (ledger, tracer, and
    the flight recorder) on entry so a test sees only its own records."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    if reset:
        ledger.reset()
        tracer.reset()
        from harp_tpu import elastic, health
        from harp_tpu.utils import (flightrec, memrec, reqtrace, skew,
                                    steptrace)

        flightrec.reset()
        skew.reset()
        reqtrace.reset()
        health.reset()
        elastic.reset()
        steptrace.reset()
        memrec.reset()
    try:
        yield
    finally:
        _ENABLED = prev


def budget(**kw):
    """``with telemetry.budget(compiles=1, readbacks=1): ...`` — the
    flight recorder's budget guard (see :func:`harp_tpu.utils.flightrec.
    budget` for the counter semantics and the raise/warn actions)."""
    from harp_tpu.utils import flightrec

    return flightrec.budget(**kw)


def out_path() -> str | None:
    """Export destination for instrumented CLIs (``HARP_TELEMETRY_OUT``)."""
    return os.environ.get("HARP_TELEMETRY_OUT") or None


# ---------------------------------------------------------------------------
# CommLedger
# ---------------------------------------------------------------------------

_UNTAGGED = "(untagged)"


def _tree_wire_bytes(tree: Any, wire_dtype: Any | None) -> tuple[int, int]:
    """(payload_bytes, n_leaves) for one verb call, per shard.

    Bytes come from static shape/dtype only.  With a ``wire_dtype``, float
    leaves are accounted at the wire format's width — the verb's *logical*
    wire (the int8 wire accounts 1 byte/element even though the current
    lowering accumulates the psum in int32); non-float leaves ride exact at
    their own width, matching the quantized verbs' exact path.
    """
    import jax
    import jax.numpy as jnp

    import numpy as np

    wd = None if wire_dtype is None else jnp.dtype(wire_dtype)
    total = 0
    leaves = jax.tree.leaves(tree)
    for x in leaves:
        # leaves are usually tracers/arrays; Python scalars (a bare float
        # pushed through a verb) still account at their promoted dtype
        dt = jnp.dtype(getattr(x, "dtype", None) or jnp.result_type(x))
        size = 1
        for s in getattr(x, "shape", np.shape(x)):
            size *= int(s)
        if wd is not None and jnp.issubdtype(dt, jnp.floating):
            dt = wd
        total += size * dt.itemsize
    return total, len(leaves)


def is_ledger_user_frame(filename: str) -> bool:
    """Is an (absolute) source filename a *user* frame for collective
    call-site attribution?  Shared by :func:`record_comm`'s trace-time
    site keys and the static CommGraph matcher
    (:mod:`harp_tpu.analysis.commgraph`), which must derive the SAME key
    from a jaxpr eqn's traceback or the HL301/HL302 site matching would
    compare apples to oranges.  Excluded: this module, the collective
    verb layer, anything under the jax package, and contextlib glue."""
    import jax

    jax_dir = os.path.dirname(os.path.abspath(jax.__file__))
    here = os.path.abspath(__file__)
    return (filename != here
            and not filename.endswith("parallel/collective.py")
            and not filename.startswith(jax_dir)
            and "contextlib" not in os.path.basename(filename))


def site_key(filename: str, lineno: int) -> str:
    """The ledger's call-site key shape: ``basename.py:lineno``."""
    return f"{os.path.basename(filename)}:{lineno}"


def _call_site() -> str:
    """Stable key for the user frame that invoked the verb: the nearest
    stack frame outside this module, the collective module, and the jax
    package (jit/shard_map tracing interposes jax frames between the
    verb and the user's code)."""
    f = sys._getframe(1)
    while f is not None:
        fn = os.path.abspath(f.f_code.co_filename)
        if is_ledger_user_frame(fn):
            return site_key(fn, f.f_lineno)
        f = f.f_back
    return "?:0"


class CommLedger:
    """Per-call-site collective byte accounting (see module docstring)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        # tag -> {"gen", "last_record_gen", "executions", "sites"}
        # sites: (site, verb, axis, combiner, wire) -> record dict
        self._tags: dict[str, dict] = {}
        self._tag_stack: list[str] = []

    # -- recording (trace time) --------------------------------------------
    def record(self, verb: str, tree: Any, *, axis: str,
               combiner: str | None = None,
               wire_dtype: Any | None = None) -> None:
        if not _ENABLED:
            return
        payload, n_leaves = _tree_wire_bytes(tree, wire_dtype)
        import jax.numpy as jnp

        wire = None if wire_dtype is None else jnp.dtype(wire_dtype).name
        site = _call_site()
        tag = self._tag_stack[-1] if self._tag_stack else _UNTAGGED
        t = self._tags.setdefault(
            tag, {"gen": 0, "last_record_gen": 0, "executions": 0,
                  "sites": {}})
        key = (site, verb, axis, combiner, wire)
        rec = t["sites"].get(key)
        if rec is None or rec["gen"] != t["gen"]:
            # first record for this site in this generation: a re-trace of
            # a cached program overwrites its old sheet instead of adding
            rec = {"site": site, "verb": verb, "axis": axis,
                   "combiner": combiner, "wire_dtype": wire,
                   "payload_bytes": 0, "calls_per_trace": 0,
                   "leaves": n_leaves, "gen": t["gen"]}
            t["sites"][key] = rec
        rec["payload_bytes"] += payload
        rec["calls_per_trace"] += 1
        rec["leaves"] = n_leaves
        t["last_record_gen"] = t["gen"]

    # -- execution counting (host side) ------------------------------------
    @contextlib.contextmanager
    def run(self, tag: str, *, steps: int = 1):
        """Attribute trace-time records inside the block to ``tag`` and
        count ``steps`` executions of its traced sites.

        ``steps`` is how many times the sites recorded under this tag
        execute during the block: the epoch count of a multi-epoch scan,
        the ``fori_loop`` trip count, the rep count of a bench loop —
        ``steps=0`` attributes a trace without counting executions (AOT
        ``.lower().compile()`` warmup).
        """
        if not _ENABLED:
            yield self
            return
        t = self._tags.setdefault(
            tag, {"gen": 0, "last_record_gen": 0, "executions": 0,
                  "sites": {}})
        t["gen"] += 1
        self._tag_stack.append(tag)
        try:
            yield self
        finally:
            self._tag_stack.pop()
            t["executions"] += int(steps)

    # -- reading ------------------------------------------------------------
    def _live_sites(self, t: dict) -> list[dict]:
        g = t["last_record_gen"]
        return [r for r in t["sites"].values() if r["gen"] == g]

    def bytes_per_execution(self, tag: str) -> int:
        t = self._tags.get(tag)
        return 0 if t is None else sum(
            r["payload_bytes"] for r in self._live_sites(t))

    def executions(self, tag: str) -> int:
        t = self._tags.get(tag)
        return 0 if t is None else t["executions"]

    def volume(self, tag: str | None = None) -> int:
        """Total comm bytes: per-execution bytes × executions (one tag, or
        summed over all tags when ``tag`` is None; untagged sites have no
        execution counter and contribute their per-trace bytes once)."""
        tags = [tag] if tag is not None else list(self._tags)
        total = 0
        for name in tags:
            t = self._tags.get(name)
            if t is None:
                continue
            per = sum(r["payload_bytes"] for r in self._live_sites(t))
            total += per * (t["executions"] if name != _UNTAGGED
                            else max(1, t["executions"]))
        return total

    def summary(self) -> dict:
        """Machine-readable ledger: one entry per tag with live sites."""
        out = {}
        for name, t in sorted(self._tags.items()):
            sites = [
                {k: r[k] for k in ("site", "verb", "axis", "combiner",
                                   "wire_dtype", "payload_bytes",
                                   "calls_per_trace", "leaves")}
                for r in sorted(self._live_sites(t),
                                key=lambda r: -r["payload_bytes"])]
            out[name] = {
                "executions": t["executions"],
                "bytes_per_execution": sum(s["payload_bytes"]
                                           for s in sites),
                "total_bytes": self.volume(name),
                "sites": sites,
            }
        return out

    def export_jsonl(self, fh) -> None:
        for tag, t in sorted(self._tags.items()):
            for r in self._live_sites(t):
                row = {"kind": "comm", "tag": tag,
                       "executions": t["executions"]}
                row.update({k: r[k] for k in (
                    "site", "verb", "axis", "combiner", "wire_dtype",
                    "payload_bytes", "calls_per_trace", "leaves")})
                fh.write(json.dumps(row) + "\n")


# ---------------------------------------------------------------------------
# SpanTracer
# ---------------------------------------------------------------------------

class SpanTracer:
    """Nested host-level spans with JSONL export (see module docstring)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._stack: list[str] = []
        self.records: list[dict] = []

    def current_path(self) -> str | None:
        """The live span path ("epoch/ingest"), or None outside any span —
        the flight recorder stamps compile/transfer records with this."""
        return "/".join(self._stack) or None

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any):
        """``with span("epoch"): ...`` — records {span, path, t0, dur,
        depth} plus any ``attrs``; nesting comes from the live stack.  Also
        enters ``jax.profiler.TraceAnnotation(name)`` so the phase shows on
        an XLA trace captured by :func:`harp_tpu.utils.profiling.trace`."""
        if not _ENABLED:
            yield
            return
        import jax

        path = "/".join(self._stack + [name])
        depth = len(self._stack)
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield
        finally:
            dur = time.perf_counter() - t0
            self._stack.pop()
            rec = {"span": name, "path": path,
                   "t0": round(t0 - self._t0, 6),
                   "dur": round(dur, 6), "depth": depth}
            if attrs:
                rec.update(attrs)
            self.records.append(rec)

    def summary(self) -> dict[str, dict[str, float]]:
        """Per-name aggregate in :meth:`Timer.summary`'s shape, so span and
        timer tables merge in the run report."""
        agg: dict[str, list[float]] = {}
        for r in self.records:
            agg.setdefault(r["span"], []).append(r["dur"])
        return {
            k: {"mean_s": sum(v) / len(v), "total_s": sum(v), "n": len(v)}
            for k, v in agg.items()
        }

    def export_jsonl(self, fh) -> None:
        for r in self.records:
            fh.write(json.dumps({"kind": "span", **r}) + "\n")


# ---------------------------------------------------------------------------
# Module singletons + the verbs' hook
# ---------------------------------------------------------------------------

ledger = CommLedger()
tracer = SpanTracer()


def span(name: str, **attrs: Any):
    """Module-level shorthand for ``tracer.span`` (the common import)."""
    return tracer.span(name, **attrs)


def record_comm(verb: str, tree: Any, *, axis: str,
                combiner: str | None = None,
                wire_dtype: Any | None = None) -> None:
    """The one hook the collective verbs call (trace time only)."""
    if not _ENABLED:
        return
    ledger.record(verb, tree, axis=axis, combiner=combiner,
                  wire_dtype=wire_dtype)
    from harp_tpu.utils import steptrace

    if steptrace.tracer._run is not None:
        steptrace.tracer.on_comm(verb, _call_site())


def export(path: str) -> None:
    """Write every collected record (spans + ledger + flight recorder +
    skew ledger + request traces + health findings + elastic actions +
    memory ledger) as one JSONL file — the input format of ``python -m
    harp_tpu report``, ``python -m harp_tpu trace``, ``python -m
    harp_tpu timeline``, ``python -m harp_tpu health``, and ``python -m
    harp_tpu memory``."""
    from harp_tpu import elastic, health
    from harp_tpu.utils import (flightrec, memrec, reqtrace, skew,
                                steptrace)

    with open(path, "w") as fh:
        tracer.export_jsonl(fh)
        ledger.export_jsonl(fh)
        flightrec.export_jsonl(fh)
        skew.export_jsonl(fh)
        reqtrace.tracer.export_jsonl(fh)
        health.export_jsonl(fh)
        elastic.export_jsonl(fh)
        steptrace.export_jsonl(fh)
        memrec.export_jsonl(fh)


def export_timeline(path: str) -> None:
    """Merge EVERY spine into one causally-ordered ``kind:"trace"``
    JSONL (PR 12) — request spans + batch records + fault-plane marks
    (already timestamped trace rows), host spans (ts = span t0) and XLA
    compiles (ts = the compile's wall offset on the span clock) folded
    in as marks, and the timestamp-less aggregate spines (comm ledger,
    transfer sites, skew phases) appended at the end as ``summary``
    rows riding the final timestamp — they describe the whole run, so
    the causal slot they occupy is "after everything".

    Clock domains are normalized per source to its own origin (the
    serve replay drives a virtual clock; spans/compiles ride the
    SpanTracer's wall offset), so ordering is exact within a source and
    aligned-at-zero across sources.  The output passes
    scripts/check_jsonl.py invariant 11 and loads in
    ``python -m harp_tpu trace`` / Perfetto via :func:`harp_tpu.utils.
    reqtrace.perfetto`.

    Training-plane spans (PR 18): any collected ``kind:"steptrace"``
    rows ride the same file after the trace rows, unmodified (they are
    already one causal block on the SpanTracer clock and pass
    invariant 16 as exported) — ``python -m harp_tpu timeline`` reads
    them out of the merged file directly.
    """
    from harp_tpu.utils import flightrec, reqtrace, skew, steptrace

    def _normalized(rows: list[dict]) -> list[dict]:
        if not rows:
            return []
        t0 = min(float(r["ts"]) for r in rows)
        return [{**r, "ts": round(float(r["ts"]) - t0, 6)} for r in rows]

    rows = _normalized(reqtrace.tracer.rows())
    host: list[dict] = [
        {"kind": "trace", "ev": "mark", "source": "span", "ts": r["t0"],
         "name": r["span"], "path": r["path"], "dur": r["dur"],
         "depth": r["depth"]}
        for r in tracer.records]
    host += [
        {"kind": "trace", "ev": "mark", "source": "compile",
         "ts": r.get("t", 0.0), "name": "backend_compile",
         "dur": r["dur"], "span": r["span"]}
        for r in flightrec.compile_watch.records]
    rows += _normalized(host)
    rows.sort(key=lambda r: r["ts"])
    t_end = rows[-1]["ts"] if rows else 0.0
    for tag, t in sorted(ledger.summary().items()):
        rows.append({"kind": "trace", "ev": "summary", "source": "comm",
                     "ts": t_end, "name": tag,
                     "executions": t["executions"],
                     "total_bytes": t["total_bytes"]})
    tr = flightrec.transfers.summary()
    if tr["sites"]:
        rows.append({"kind": "trace", "ev": "summary",
                     "source": "transfer", "ts": t_end, "name": "totals",
                     "h2d_bytes": tr["h2d_bytes"],
                     "dispatches": tr["dispatches"],
                     "readbacks": tr["readbacks"]})
    for phase, s in skew.ledger.summary().items():
        rows.append({"kind": "trace", "ev": "summary", "source": "skew",
                     "ts": t_end, "name": phase,
                     "max_mean_ratio": s.get("max_mean_ratio")})
    stamp = flightrec.provenance_stamp()
    with open(path, "w") as fh:
        for row in rows:
            fh.write(json.dumps({**row, **stamp}) + "\n")
        steptrace.tracer.export_jsonl(fh, stamp)


def load_rows(path: str) -> dict[str, list[dict]]:
    """Read an :func:`export` file back, keyed by record kind:
    ``{"span": [...], "comm": [...], "compile": [...], "transfer":
    [...], "skew": [...], "trace": [...], "health": [...],
    "elastic": [...], "steptrace": [...], "memory": [...]}`` (unknown
    kinds land under ``"comm"`` for backward compatibility with
    pre-flight-recorder exports, whose only unmarked rows were the
    ledger's)."""
    out: dict[str, list[dict]] = {"span": [], "comm": [], "compile": [],
                                  "transfer": [], "skew": [],
                                  "trace": [], "health": [],
                                  "elastic": [], "steptrace": [],
                                  "memory": []}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            kind = row.get("kind")
            out[kind if kind in out else "comm"].append(row)
    return out


def load_jsonl(path: str) -> tuple[list[dict], list[dict]]:
    """Back-compat loader: (span rows, comm rows) only."""
    rows = load_rows(path)
    return rows["span"], rows["comm"]
