"""Host-side utilities: timing/sync, metrics, config, checkpointing.

Replaces Harp's L8/aux surface (SURVEY.md §6): log4j iteration logs →
metrics JSONL; Hadoop Configuration → config dataclasses; app-level HDFS
model dumps → orbax checkpoints.
"""

from harp_tpu.utils.timing import device_sync, Timer

__all__ = ["device_sync", "Timer"]

# Also available (imported lazily by apps to keep startup light):
#   harp_tpu.utils.checkpoint  — orbax CheckpointManager (resume support)
#   harp_tpu.utils.config      — dataclass → argparse CLI configs
#   harp_tpu.utils.metrics     — per-iteration JSONL metrics logger
#   harp_tpu.utils.profiling   — jax.profiler trace/annotate helpers
#   harp_tpu.utils.fault       — fault injection + restart-from-checkpoint
#   harp_tpu.utils.check       — checkify sanitizers (NaN / OOB / asserts)
#   harp_tpu.utils.skew        — superstep skew profiler (per-worker load)
