"""Host-side utilities: timing/sync, metrics, config, checkpointing.

Replaces Harp's L8/aux surface (SURVEY.md §6): log4j iteration logs →
metrics JSONL; Hadoop Configuration → config dataclasses; app-level HDFS
model dumps → orbax checkpoints.
"""

from harp_tpu.utils.timing import device_sync, Timer

__all__ = ["device_sync", "Timer"]
