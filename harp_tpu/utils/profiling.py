"""Profiler hook — Harp's "tracing" subsystem, on XLA's profiler.

Reference parity (SURVEY.md §6): the reference has per-iteration wall-clock
log lines and DAAL verbose timing; no structured tracer.  Here one context
manager captures a TensorBoard-viewable XLA trace (op timeline, HBM
allocations, ICI traffic on real pods), plus :class:`harp_tpu.utils.timing.
Timer` for the Harp-style per-phase table.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/harp_tpu_trace"):
    """``with trace("dir"): run_steps()`` → TensorBoard trace in dir."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up in the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


def op_breakdown(logdir: str, top: int = 15, host_events: bool = False,
                 self_time: bool = True, per_device: bool = False):
    """Top device ops by total duration from the LATEST :func:`trace`
    capture under ``logdir``.

    Parses the newest profile session's ``*.trace.json.gz`` event dump
    (each ``trace()`` writes a fresh ``plugins/profile/<ts>/`` session, so
    reusing a logdir must not double-count older runs) and sums durations
    per op name — the quick "where did the time go" table behind
    BASELINE.md's measurements.  Spans are filtered to device tracks via
    the trace's process metadata; when no device track exists (CPU
    backend) all non-Python-frame spans are kept instead.  Set
    ``host_events`` to include everything.  Returns
    ``[(name, total_seconds)]``, largest first.

    TPU device tracks nest: the module span (``jit_fn(...)``) contains
    loop spans (``while.N``) which contain the fusions that actually run
    — summing raw durations triple-counts, and the first real TPU capture
    (kmeans, 2026-07-31) read 28%/23% for ``jit_run``/``while.2`` with
    the true fusions squeezed below.  ``self_time=True`` (default) makes
    the table flame-graph-style: each span is charged only the time not
    covered by spans nested inside it on the same track, so shares sum to
    the traced wall and parents shrink to their scheduling overhead.

    ``per_device=True`` returns ``[(name, device_id, total_seconds)]``
    with the device ordinal parsed from the trace's process metadata
    (``/device:TPU:3`` → 3; host/CPU-backend tracks → None) — so a
    multichip capture's breakdown can be split per worker (the skew
    profiler's trace-side view, utils/skew.py).  The default call keeps
    its exact old shape and numbers: the same per-(op, device) totals,
    summed over devices (a no-op on single-device traces).
    """
    import glob
    import gzip
    import json
    import re

    sessions = sorted(glob.glob(f"{logdir}/plugins/profile/*/"))
    root = sessions[-1] if sessions else logdir  # newest session only
    files = sorted(glob.glob(f"{root}/**/*.trace.json.gz", recursive=True))
    if not files:
        raise FileNotFoundError(f"no *.trace.json.gz under {logdir!r} — "
                                "was this directory written by trace()?")
    totals: dict[tuple, float] = {}  # (name, device_id_or_None) -> sec
    for f in files:
        events = json.loads(gzip.open(f).read()).get("traceEvents", [])
        dev_of_pid: dict = {}  # pid -> device ordinal, device tracks only
        for e in events:
            if (e.get("ph") == "M" and e.get("name") == "process_name"
                    and "/device:" in str(e.get("args", {}).get("name",
                                                                ""))):
                m = re.search(r"/device:[^:]+:(\d+)",
                              str(e["args"]["name"]))
                dev_of_pid[e.get("pid")] = int(m.group(1)) if m else None
        tracks: dict[tuple, list] = {}
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            name = e.get("name", "?")
            if not host_events:
                if dev_of_pid:
                    if e.get("pid") not in dev_of_pid:
                        continue
                elif name.startswith("$"):  # CPU backend: no device track
                    continue
            if not self_time:
                key = (name, dev_of_pid.get(e.get("pid")))
                totals[key] = totals.get(key, 0.0) + e["dur"] / 1e6
            else:
                tracks.setdefault((e.get("pid"), e.get("tid")), []).append(
                    (float(e["ts"]), float(e["dur"]), name))
        # flame-graph self time per track: a span's children are the spans
        # it fully contains; charge each span dur − Σ(child dur)
        for (pid, _tid), evs in tracks.items():
            dev = dev_of_pid.get(pid)
            evs.sort(key=lambda t: (t[0], -t[1]))
            stack: list[list] = []  # [end_ts, child_dur_sum, name, dur]

            def pop(rec, dev=dev):
                self_us = max(rec[3] - rec[1], 0.0)
                key = (rec[2], dev)
                totals[key] = totals.get(key, 0.0) + self_us / 1e6
                if stack:
                    stack[-1][1] += rec[3]

            for ts, dur, name in evs:
                while stack and ts >= stack[-1][0] - 1e-9:
                    pop(stack.pop())
                stack.append([ts + dur, 0.0, name, dur])
            while stack:
                pop(stack.pop())
    if per_device:
        return sorted(((n, d, t) for (n, d), t in totals.items()),
                      key=lambda x: -x[2])[:top]
    agg: dict[str, float] = {}
    for (name, _dev), t in totals.items():
        agg[name] = agg.get(name, 0.0) + t
    return sorted(agg.items(), key=lambda kv: -kv[1])[:top]
