"""Profiler hook — Harp's "tracing" subsystem, on XLA's profiler.

Reference parity (SURVEY.md §6): the reference has per-iteration wall-clock
log lines and DAAL verbose timing; no structured tracer.  Here one context
manager captures a TensorBoard-viewable XLA trace (op timeline, HBM
allocations, ICI traffic on real pods), plus :class:`harp_tpu.utils.timing.
Timer` for the Harp-style per-phase table.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/harp_tpu_trace"):
    """``with trace("dir"): run_steps()`` → TensorBoard trace in dir."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up in the trace timeline."""
    return jax.profiler.TraceAnnotation(name)
