"""memrec — the device-memory ledger, eighth telemetry spine (PR 19).

Reference parity (SURVEY.md §6 Fault tolerance / resource accounting):
Harp on YARN only ever saw CONTAINER-level memory — `yarn.nodemanager`
limits killed a worker after the fact, and nothing inside the Harp
runtime could say which table or rotation buffer held the bytes.  This
spine is strictly finer: every device buffer's lifecycle (stage →
dispatch-input → donated → output → freed) is an evidence row, the live
watermark re-derives from the event stream EXACTLY (check_jsonl
invariant 17), and a Pallas launch that would not fit its registered
VMEM budget is REFUSED before dispatch — the `_tile_rows_int8` OOM of
2026-08-01 became a pre-silicon check instead of a relay burn.

How the ledger is fed (all hooks are zero-cost when telemetry is off —
each returns before touching state, and none adds a device op, so the
traced program is bit-identical on/off):

- H2D staging: ``flightrec.record_h2d`` (mesh.shard_array /
  serve put_input / ingest) calls :func:`on_staged` inside its
  ``telemetry.enabled()`` branch — the same bytes flightrec already
  counts enter the live set as a ``staged`` buffer event.
- Dispatch + donation: ``flightrec.track(fn, label, donate_argnums=…)``
  registers the donation signature (module-level, survives
  ``telemetry.scope`` resets exactly like the tracked callable itself);
  at call time :func:`on_dispatch` claims the newest live buffers whose
  byte sizes match the donated args (shape × itemsize only — nothing is
  materialized) and emits ``donated`` events: the runtime twin of the
  HL303 donation audit.  :func:`on_output` adds the dispatch results
  back as ``output`` buffers, so a depth-2 donated pipeline stays a
  bounded live set.
- Executables: the serve AOT cache records ``memory_analysis()``
  footprints (argument/output/temp/generated-code bytes) via
  :func:`note_executable` — the literal input the multi-tenant
  "does tenant N fit" admission check needs.
- Checkpoint restore: :func:`on_restored` records the bytes as a
  zero-delta ``restored`` event (restore lands in host RAM; the
  subsequent shard_array H2D enters the live set — never counted
  twice).
- Supersteps: ``steptrace.superstep`` opens a per-span window
  (:func:`begin_window`) and threads the window peak onto the timeline
  as a ``memory`` mark (:func:`note_superstep`).

VMEM gate: :func:`require_vmem_fit` raises ``MemoryError`` naming the
predicted footprint BEFORE any dispatch when a kernel config exceeds
its budget — regardless of telemetry state (it is a safety gate, not a
collector).  ``perfmodel.presize``'s predicted bytes must bound the
measured tile footprint within ``PRESIZE_BAND`` (the same band harplint
HL205 enforces on the kernel-registry declarations at lint time).

CLI: ``python -m harp_tpu memory run.jsonl [--json]`` — exit 0 clean /
1 irreconciled / 2 unreadable, the trace/timeline/health pattern.
"""

from __future__ import annotations

import argparse
import json
import sys

from harp_tpu.utils import telemetry

# perfmodel.presize predictions must bound a measured/declared tile
# footprint within this band (measured ∈ [model, model × BAND]); the
# HL205 lint rule applies the same band to kernel-registry vmem_bytes
# declarations so a stale declaration fails tier-1.
PRESIZE_BAND = 1.25
# Per-core VMEM on every shipped target (v4/v5e: 16 MiB) — registry
# declarations and presize budgets must sit below it.
VMEM_CEILING = 16 << 20

# Buffer lifecycle vocabulary (check_jsonl invariant 17 pins it).
BUFFER_EVENTS = ("staged", "restored", "output", "freed", "donated")
# Row sub-kinds under kind:"memory".
EVS = ("buffer", "dispatch", "executable", "vmem_check", "summary")

# label -> donate_argnums tuple.  Deliberately NOT cleared by reset():
# like the tracked callable it describes, a donation signature is
# configuration, not run state — Server.startup registers before
# serve --bench opens its telemetry scope.
_DISPATCH_SIGS: dict[str, tuple[int, ...]] = {}


def _leaf_nbytes(a) -> int:
    """Byte size of one array-like from shape/dtype only (no sync)."""
    try:
        shape = a.shape
        import numpy as np
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(a.dtype).itemsize
    except Exception:
        return int(getattr(a, "nbytes", 0) or 0)


def _tree_nbytes(x) -> int:
    import jax
    return sum(_leaf_nbytes(leaf) for leaf in jax.tree_util.tree_leaves(x)
               if hasattr(leaf, "shape"))


class MemLedger:
    """Live-set + watermark ledger over device-buffer lifecycle events."""

    def __init__(self):
        self._rows: list[dict] = []
        self._live: dict[int, dict] = {}   # buf id -> {bytes, label}
        self._seq = 0
        self._buf = 0
        self.live_bytes = 0
        self.peak_bytes = 0
        self._win_peak = 0
        self.staged_bytes = 0
        self.freed_bytes = 0
        self.donated_bytes = 0
        self.vmem_checks = 0
        self.vmem_refusals = 0
        self._execs: dict[str, dict] = {}
        self._pressure_fired = False
        from harp_tpu.plan import topology
        self.hbm_bytes = topology.hbm_bytes("single_chip")

    # -- internals ----------------------------------------------------
    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _buffer_row(self, event: str, buf: int, nbytes: int,
                    label: str | None) -> None:
        self._rows.append({
            "kind": "memory", "ev": "buffer", "event": event,
            "buf": buf, "bytes": int(nbytes), "label": label or "?",
            "seq": self._next_seq(), "live_bytes": self.live_bytes,
            "peak_bytes": self.peak_bytes,
        })

    def _note_peak(self) -> None:
        if self.live_bytes > self.peak_bytes:
            self.peak_bytes = self.live_bytes
        if self.live_bytes > self._win_peak:
            self._win_peak = self.live_bytes
        if not self._pressure_fired and self.hbm_bytes > 0:
            from harp_tpu.health import sentinel
            if self.peak_bytes >= ((1.0 - sentinel.HEADROOM_WARN_FRAC)
                                   * self.hbm_bytes):
                self._pressure_fired = True
                sentinel.monitor.observe_memory(
                    "run", self.peak_bytes, self.hbm_bytes)

    def _add(self, event: str, nbytes: int, label: str | None) -> int:
        self._buf += 1
        self._live[self._buf] = {"bytes": int(nbytes), "label": label}
        self.live_bytes += int(nbytes)
        self._note_peak()
        self._buffer_row(event, self._buf, nbytes, label)
        return self._buf

    def _remove(self, event: str, buf: int) -> None:
        info = self._live.pop(buf)
        self.live_bytes -= info["bytes"]
        self._buffer_row(event, buf, info["bytes"], info["label"])

    # -- event surface ------------------------------------------------
    def staged(self, nbytes: int, label: str | None = None) -> int:
        self.staged_bytes += int(nbytes)
        return self._add("staged", nbytes, label)

    def restored(self, nbytes: int, label: str | None = None) -> None:
        # Zero-delta: restore lands in host RAM; the H2D that follows
        # enters the live set as its own staged event.
        self._rows.append({
            "kind": "memory", "ev": "buffer", "event": "restored",
            "buf": 0, "bytes": int(nbytes), "label": label or "?",
            "seq": self._next_seq(), "live_bytes": self.live_bytes,
            "peak_bytes": self.peak_bytes,
        })

    def output(self, nbytes: int, label: str | None = None) -> int:
        return self._add("output", nbytes, label)

    def freed(self, buf: int | None = None, nbytes: int | None = None,
              label: str | None = None) -> bool:
        """Free an explicit buf id, or the newest live match."""
        if buf is None:
            buf = self._match(nbytes, label)
            if buf is None:
                return False
        self.freed_bytes += self._live[buf]["bytes"]
        self._remove("freed", buf)
        return True

    def _match(self, nbytes: int | None, label: str | None) -> int | None:
        for b in reversed(self._live):
            info = self._live[b]
            if nbytes is not None and info["bytes"] != int(nbytes):
                continue
            if label is not None and info["label"] != label:
                continue
            return b
        return None

    def dispatch(self, label: str, donated_nbytes: list[int]) -> None:
        """Record a dispatch; claim newest live buffers for donations."""
        claimed: list[int] = []
        claimed_bytes = 0
        for nb in donated_nbytes:
            b = self._match(nb, None)
            if b is None:
                continue  # telemetry may have enabled mid-run
            claimed_bytes += self._live[b]["bytes"]
            self.donated_bytes += self._live[b]["bytes"]
            self._remove("donated", b)
            claimed.append(b)
        self._rows.append({
            "kind": "memory", "ev": "dispatch", "label": label,
            "seq": self._next_seq(), "donated": claimed,
            "donated_bytes": claimed_bytes,
            "live_bytes": self.live_bytes, "peak_bytes": self.peak_bytes,
        })

    def executable(self, name: str, footprint: dict, source: str) -> None:
        total = sum(int(footprint.get(k, 0)) for k in (
            "argument_bytes", "output_bytes", "temp_bytes",
            "generated_code_bytes"))
        row = {
            "kind": "memory", "ev": "executable", "name": name,
            "seq": self._next_seq(), "source": source,
            "argument_bytes": int(footprint.get("argument_bytes", 0)),
            "output_bytes": int(footprint.get("output_bytes", 0)),
            "temp_bytes": int(footprint.get("temp_bytes", 0)),
            "generated_code_bytes":
                int(footprint.get("generated_code_bytes", 0)),
            "exec_hbm_bytes": total,
        }
        self._execs[name] = row
        self._rows.append(row)

    def vmem_check(self, kernel: str, predicted: int, budget: int,
                   fits: bool) -> None:
        self.vmem_checks += 1
        if not fits:
            self.vmem_refusals += 1
        self._rows.append({
            "kind": "memory", "ev": "vmem_check", "kernel": kernel,
            "seq": self._next_seq(), "predicted_bytes": int(predicted),
            "budget_bytes": int(budget), "fits": bool(fits),
            "refused": not fits,
        })

    # -- superstep window ---------------------------------------------
    def begin_window(self) -> None:
        self._win_peak = self.live_bytes

    def window_peak(self) -> int:
        return self._win_peak

    # -- summaries ----------------------------------------------------
    def headroom_frac(self) -> float:
        if self.hbm_bytes <= 0:
            return 1.0
        return max(0.0, 1.0 - self.peak_bytes / self.hbm_bytes)

    def exec_total(self) -> int:
        return sum(r["exec_hbm_bytes"] for r in self._execs.values())

    def summary_row(self) -> dict:
        return {
            "kind": "memory", "ev": "summary",
            "seq": self._next_seq(), "events": len(self._rows),
            "staged_bytes": self.staged_bytes,
            "freed_bytes": self.freed_bytes,
            "donated_bytes": self.donated_bytes,
            "peak_hbm_bytes": self.peak_bytes,
            "live_hbm_bytes": self.live_bytes,
            "hbm_bytes": self.hbm_bytes,
            "headroom_frac": round(self.headroom_frac(), 6),
            "executables": len(self._execs),
            "exec_hbm_bytes": self.exec_total(),
            "vmem_checks": self.vmem_checks,
            "vmem_refusals": self.vmem_refusals,
        }


ledger = MemLedger()


def reset() -> None:
    """Fresh ledger (telemetry.scope).  _DISPATCH_SIGS survives."""
    global ledger
    ledger = MemLedger()


# ---------------------------------------------------------------------
# Hook surface (every entry point returns before touching state when
# telemetry is off — the PR-3 zero-cost contract).
# ---------------------------------------------------------------------

def on_staged(nbytes: int, label: str | None = None) -> None:
    if not telemetry.enabled():
        return
    ledger.staged(nbytes, label)


def on_restored(nbytes: int, label: str | None = None) -> None:
    if not telemetry.enabled():
        return
    ledger.restored(nbytes, label)


def register_dispatch(label: str,
                      donate_argnums: tuple[int, ...] | None) -> None:
    """Declare a tracked callable's donation signature (config, not
    run state — survives reset()).  Called by flightrec.track."""
    if donate_argnums:
        _DISPATCH_SIGS[label] = tuple(int(i) for i in donate_argnums)


def on_dispatch(label: str, args: tuple) -> None:
    if not telemetry.enabled():
        return
    sig = _DISPATCH_SIGS.get(label)
    if sig is None:
        return
    donated = [_tree_nbytes(args[i]) for i in sig if i < len(args)]
    ledger.dispatch(label, donated)


def on_output(label: str, result) -> None:
    if not telemetry.enabled():
        return
    if label not in _DISPATCH_SIGS:
        return
    nb = _tree_nbytes(result)
    if nb > 0:
        ledger.output(nb, label)


def note_freed(nbytes: int | None = None, label: str | None = None) -> None:
    if not telemetry.enabled():
        return
    ledger.freed(nbytes=nbytes, label=label)


def footprint_from_analysis(exe) -> dict | None:
    """Extract the HBM footprint from compiled.memory_analysis().

    Returns None when the backend does not expose the analysis (the
    CPU sim sometimes does not) — callers degrade gracefully."""
    try:
        ma = exe.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for field, key in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("generated_code_size_in_bytes", "generated_code_bytes")):
        try:
            out[key] = int(getattr(ma, field, 0) or 0)
        except Exception:
            out[key] = 0
    return out


def note_executable(name: str, footprint: dict | None,
                    source: str = "compile") -> None:
    if not telemetry.enabled() or not footprint:
        return
    ledger.executable(name, footprint, source)


def note_superstep(tracer) -> None:
    """Thread the window peak onto an open steptrace span as a mark.

    No-op while the ledger has recorded nothing — a run without memory
    activity keeps its pre-PR-19 mark counts bit-identical."""
    if not telemetry.enabled() or not ledger._rows:
        return
    tracer.mark("memory", "superstep_peak",
                peak_hbm_bytes=ledger.window_peak(),
                live_hbm_bytes=ledger.live_bytes)


def require_vmem_fit(kernel: str, predicted_bytes: int, *,
                     budget: int) -> None:
    """Refuse an over-VMEM kernel config BEFORE dispatch.

    Raises MemoryError regardless of telemetry state (safety gate, not
    a collector); records a vmem_check evidence row when armed."""
    fits = int(predicted_bytes) <= int(budget)
    if telemetry.enabled():
        ledger.vmem_check(kernel, predicted_bytes, budget, fits)
    if not fits:
        raise MemoryError(
            f"memrec: {kernel} predicted VMEM footprint "
            f"{int(predicted_bytes)} B "
            f"({predicted_bytes / (1 << 20):.2f} MB) exceeds the "
            f"{int(budget) >> 20} MB budget — refused before dispatch "
            "(pre-size with perfmodel.presize)")


def set_hbm_capacity(nbytes: int) -> None:
    ledger.hbm_bytes = int(nbytes)


def snapshot() -> dict:
    """Cheap counters for bench submetric deltas."""
    return {"peak_hbm_bytes": ledger.peak_bytes,
            "staged_bytes": ledger.staged_bytes,
            "donated_bytes": ledger.donated_bytes,
            "events": len(ledger._rows)}


def delta_since(base: dict | None) -> dict:
    base = base or {"peak_hbm_bytes": 0, "staged_bytes": 0,
                    "donated_bytes": 0, "events": 0}
    return {
        "peak_hbm_bytes": ledger.peak_bytes,
        "headroom_frac": round(ledger.headroom_frac(), 6),
        "staged_bytes": ledger.staged_bytes - base["staged_bytes"],
        "donated_bytes": ledger.donated_bytes - base["donated_bytes"],
        "events": len(ledger._rows) - base["events"],
    }


def live_summary() -> dict | None:
    """Report-section view of the in-process ledger.

    Unlike :meth:`MemLedger.summary_row` this does NOT bump the event
    seq — the report may render the same run any number of times
    without perturbing a later export."""
    if not ledger._rows:
        return None
    return {
        "events": len(ledger._rows),
        "staged_bytes": ledger.staged_bytes,
        "freed_bytes": ledger.freed_bytes,
        "donated_bytes": ledger.donated_bytes,
        "peak_hbm_bytes": ledger.peak_bytes,
        "live_hbm_bytes": ledger.live_bytes,
        "hbm_bytes": ledger.hbm_bytes,
        "headroom_frac": round(ledger.headroom_frac(), 6),
        "executables": len(ledger._execs),
        "exec_hbm_bytes": ledger.exec_total(),
        "vmem_checks": ledger.vmem_checks,
        "vmem_refusals": ledger.vmem_refusals,
    }


def export_jsonl(fh) -> None:
    """Provenance-stamped kind:'memory' rows + ONE closing summary."""
    if not ledger._rows:
        return
    from harp_tpu.utils import flightrec
    stamp = flightrec.provenance_stamp()
    for row in ledger._rows:
        fh.write(json.dumps({**row, **stamp}) + "\n")
    fh.write(json.dumps({**ledger.summary_row(), **stamp}) + "\n")


# ---------------------------------------------------------------------
# Offline summarize / CLI (exit 0 clean, 1 irreconciled, 2 unreadable)
# ---------------------------------------------------------------------

def summarize_rows(rows: list[dict]) -> dict:
    """Re-derive the watermark from the event stream; collect errors.

    The same replay check_jsonl invariant 17 runs — live/peak on every
    row must equal the derived value EXACTLY, donated buffers must have
    left the live set, and the one summary row must match the final
    derived state."""
    errors: list[str] = []
    live: dict[int, int] = {}
    live_b = peak = 0
    staged = freed = donated = 0
    execs = exec_b = checks = refusals = 0
    last_seq = 0
    summary = None
    buffers = dispatches = 0
    for i, row in enumerate(rows, 1):
        ev = row.get("ev")
        seq = row.get("seq", 0)
        if isinstance(seq, int) and seq <= last_seq:
            errors.append(f"row {i}: seq {seq} not increasing")
        last_seq = seq if isinstance(seq, int) else last_seq
        if summary is not None and ev != "summary":
            errors.append(f"row {i}: {ev} row after the summary row")
        if ev == "buffer":
            buffers += 1
            e, b = row.get("event"), row.get("buf")
            nb = int(row.get("bytes", 0))
            if e in ("staged", "output"):
                live[b] = nb
                live_b += nb
                peak = max(peak, live_b)
                if e == "staged":
                    staged += nb
            elif e in ("freed", "donated"):
                if b not in live:
                    errors.append(
                        f"row {i}: {e} buf {b} is not in the live set")
                else:
                    live_b -= live.pop(b)
                if e == "freed":
                    freed += nb
                else:
                    donated += nb
            elif e == "restored":
                pass  # zero-delta by design
            else:
                errors.append(f"row {i}: unknown buffer event {e!r}")
            if row.get("live_bytes") != live_b:
                errors.append(
                    f"row {i}: live_bytes {row.get('live_bytes')} != "
                    f"derived {live_b}")
            if row.get("peak_bytes") != peak:
                errors.append(
                    f"row {i}: peak_bytes {row.get('peak_bytes')} != "
                    f"derived {peak}")
        elif ev == "dispatch":
            dispatches += 1
            for b in row.get("donated", []):
                if b in live:
                    errors.append(
                        f"row {i}: donated buf {b} still in the live "
                        "set after dispatch")
            if row.get("live_bytes") != live_b:
                errors.append(
                    f"row {i}: dispatch live_bytes "
                    f"{row.get('live_bytes')} != derived {live_b}")
        elif ev == "executable":
            execs += 1
            parts = sum(int(row.get(k, 0)) for k in (
                "argument_bytes", "output_bytes", "temp_bytes",
                "generated_code_bytes"))
            if parts != row.get("exec_hbm_bytes"):
                errors.append(
                    f"row {i}: exec_hbm_bytes != component sum")
            exec_b += int(row.get("exec_hbm_bytes", 0))
        elif ev == "vmem_check":
            checks += 1
            if row.get("refused"):
                refusals += 1
            fits = (int(row.get("predicted_bytes", 0))
                    <= int(row.get("budget_bytes", 0)))
            if bool(row.get("fits")) != fits:
                errors.append(f"row {i}: fits flag contradicts bytes")
        elif ev == "summary":
            if summary is not None:
                errors.append(f"row {i}: second summary row")
            summary = row
            for k, v in (("peak_hbm_bytes", peak),
                         ("live_hbm_bytes", live_b),
                         ("staged_bytes", staged),
                         ("freed_bytes", freed),
                         ("donated_bytes", donated),
                         ("vmem_checks", checks),
                         ("vmem_refusals", refusals)):
                if row.get(k) != v:
                    errors.append(
                        f"row {i}: summary {k}={row.get(k)} != "
                        f"derived {v}")
    if rows and summary is None:
        errors.append("no summary row — the export is unterminated")
    return {
        "rows": len(rows), "buffers": buffers, "dispatches": dispatches,
        "executables": execs, "exec_hbm_bytes": exec_b,
        "vmem_checks": checks, "vmem_refusals": refusals,
        "staged_bytes": staged, "freed_bytes": freed,
        "donated_bytes": donated, "peak_hbm_bytes": peak,
        "live_hbm_bytes": live_b,
        "hbm_bytes": (summary or {}).get("hbm_bytes"),
        "headroom_frac": (summary or {}).get("headroom_frac"),
        "errors": errors,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m harp_tpu memory",
        description="device-memory ledger: validate/summarize "
                    "kind:'memory' rows from a run export")
    p.add_argument("jsonl", help="telemetry export (HARP_TELEMETRY_OUT)")
    p.add_argument("--json", action="store_true",
                   help="print the summary as one JSON line")
    args = p.parse_args(argv)
    try:
        rows = telemetry.load_rows(args.jsonl)["memory"]
    except OSError as e:
        print(f"memory: unreadable: {e}", file=sys.stderr)
        return 2
    s = summarize_rows(rows)
    if args.json:
        from harp_tpu.utils import flightrec
        print(json.dumps({**s, **flightrec.provenance_stamp()}))
    else:
        print(f"memory: {s['rows']} row(s), {s['buffers']} buffer "
              f"event(s), {s['dispatches']} dispatch(es), "
              f"{s['executables']} executable(s)")
        print(f"  peak HBM      {s['peak_hbm_bytes']} B"
              + (f"  (headroom {s['headroom_frac']:.1%} of "
                 f"{s['hbm_bytes']} B)"
                 if s.get("headroom_frac") is not None else ""))
        print(f"  staged {s['staged_bytes']} B / donated "
              f"{s['donated_bytes']} B / freed {s['freed_bytes']} B / "
              f"live {s['live_hbm_bytes']} B")
        print(f"  exec footprints {s['exec_hbm_bytes']} B; vmem checks "
              f"{s['vmem_checks']} ({s['vmem_refusals']} refused)")
        for e in s["errors"]:
            print(f"  IRRECONCILED: {e}", file=sys.stderr)
    if not rows:
        print("memory: no kind:'memory' rows in the export",
              file=sys.stderr)
        return 1
    return 1 if s["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
