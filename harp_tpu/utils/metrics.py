"""Per-iteration metrics as JSONL — Harp's log4j iteration logs, structured.

Reference parity (SURVEY.md §6): Harp apps print per-iteration wall-clock
lines into container logs; observability is grepping YARN logs.  Here every
iteration appends one JSON object to a file (and mirrors to the Python
logger), so the north-star metrics (iter/sec, updates/sec/chip) are
machine-readable.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, IO

import numpy as np

log = logging.getLogger("harp_tpu.metrics")


class MetricsLogger:
    """Use as a context manager (``with MetricsLogger(path) as m: ...``)
    so the file handle closes on any exit path; :meth:`close` is
    idempotent, so drivers that close explicitly (``CollectiveApp.run``'s
    ``finally``) and a surrounding ``with`` can coexist."""

    def __init__(self, path: str | None = None):
        self._fh: IO | None = open(path, "a") if path else None
        self._t0 = time.perf_counter()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def log(self, step: int | None = None, **metrics: Any) -> dict:
        rec = {"t": round(time.perf_counter() - self._t0, 6), **metrics}
        if step is not None:
            rec["step"] = step
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        log.info("%s", rec)
        return rec

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


_PROVENANCE: dict | None = None


def _provenance() -> dict:
    """backend/date/jax/commit stamp, computed once per process.

    Round 5 (review finding): `flip_decision.latest_rows` and bench.py's
    `_last_measured` exclude CPU-sim evidence via ``backend == "cpu"`` —
    a config-keyed CLI row WITHOUT the field (e.g. the teed
    `kmeans_stream_cli` 1B record) would pass as TPU evidence, exactly
    the CPU-inversion failure those filters exist for.  Stamping here
    covers every CLI that prints through benchmark_json.
    """
    global _PROVENANCE
    if _PROVENANCE is None:
        import datetime
        import subprocess

        import jax

        try:
            commit = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or None
        except (OSError, subprocess.SubprocessError):
            # TimeoutExpired included: a hung git must not crash
            # benchmark_json at print time and lose an hours-long
            # measurement (ADVICE r5)
            commit = None
        _PROVENANCE = {
            "date": datetime.date.today().isoformat(),
            "backend": jax.default_backend(),
            "n_devices": jax.device_count(),
            "jax": jax.__version__,
            "commit": commit,
        }
    return _PROVENANCE


def benchmark_json(config: str, result: dict) -> str:
    """One JSON line for a CLI benchmark result.

    Every app CLI prints its benchmark dict through this (round 4): the
    relay sprint tees CLI output into BENCH_local.jsonl, and a Python
    dict repr there is an unparseable line every JSONL reader must skip.
    numpy scalars coerce to plain Python so json never chokes.  Rows
    carry the same provenance fields measure_all stamps (round 5), so
    downstream TPU-evidence filters can classify them.
    """
    def _plain(v: Any):
        if isinstance(v, (np.floating, float)):
            return round(float(v), 4)
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.ndarray):
            return v.tolist()
        return v

    # provenance first: a measured result key that collides with a stamp
    # field (date/backend/n_devices/...) must win over the ambient stamp
    return json.dumps({"config": config,
                       **_provenance(),
                       **{k: _plain(v) for k, v in result.items()}})
