"""Roofline annotation — "X% of chip peak", not "faster than yesterday".

Reference parity (SURVEY.md §7 / round-1 VERDICT item 9): BASELINE.md's
numbers need a roofline column so a measured rate reads as a fraction of
what the chip can do.  Each graded config gets an ANALYTIC work model
(FLOPs and minimum HBM bytes per unit of its throughput metric); paired
with a measured benchmark dict it yields achieved TFLOP/s, achieved
GB/s, percent-of-peak for both, and which wall the config is against.

The models are deliberately lower-bound byte models (inputs read once,
outputs written once — XLA fusion can't do better) and exact FLOP
counts for the dominant kernels; percentages can therefore slightly
UNDERSTATE achieved bandwidth but never flatter it.  Peaks are the
public TPU v5e datasheet figures.

PR 13: these work models are also the FLOOR layer of the predictive
cost model (:mod:`harp_tpu.perfmodel.model`), which adds per-variant
mechanism terms on top and self-grades the combined ranking against
the committed bench rows — change a formula here and the perfmodel
grading (tier-1) re-checks every committed ranking it feeds.
"""

from __future__ import annotations

# Public v5e (v5 lite) per-chip datasheet peaks.
V5E_PEAKS = {
    "bf16_flops": 197e12,   # MXU bf16 FLOP/s
    "int8_ops": 394e12,     # MXU int8 OP/s
    "f32_flops": 49.25e12,  # bf16/4: HIGHEST-precision f32 (3+ MXU passes)
    "hbm_gbs": 819e9,       # HBM bandwidth, bytes/s
}

# Matmul-dominated configs with f32 arrays compare against the bf16 peak:
# jax's DEFAULT matmul precision executes f32 dots as single bf16 MXU
# passes (none of the hot kernels request HIGHEST), so the compute wall
# really is 197 TF/s.  Proven on silicon 2026-07-31: kmeans_stream
# measured 131 TF/s ex-gen — impossible against the 49.25 TF/s f32 peak
# the annotator used before this fix (it reported 129% of peak).
_DEFAULT_PRECISION_PEAK = "bf16_flops"


def _kmeans_work(r):
    """Per iteration: distance matmul 2ndk + one-hot sums matmul 2nkd;
    min bytes = points read once (dtype-sized) + assignments written once
    (int32) — the fused kernel never materializes the [n,k] scores in
    HBM, so charging 8nk would INFLATE achieved bandwidth (at k=1000 it
    reported >100% of HBM peak, impossible).  iters_per_sec is a
    WHOLE-MESH rate over the whole-n workload, so the per-chip comparison
    divides by num_workers.  The streaming benchmark reports
    ``iters_per_sec_ex_gen`` (Lloyd time with the synthetic
    chunk-generation scaffolding subtracted) — prefer it when present,
    since generation is benchmark overhead outside this work model."""
    n, d, k = r["n"], r["d"], r["k"]
    dsize = 1 if r.get("quantize") == "int8" else 4
    # value check, not key presence: the streaming benchmark reports
    # ex_gen=None when gen time swamps the epoch (relay noise)
    metric = ("iters_per_sec_ex_gen"
              if r.get("iters_per_sec_ex_gen") is not None
              else "iters_per_sec")
    return {
        "flops": 4.0 * n * d * k,
        "bytes": n * d * dsize + 4.0 * n,
        "per": (metric, 1.0 / r.get("num_workers", 1)),
        "peak": ("int8_ops" if r.get("quantize") == "int8"
                 else _DEFAULT_PRECISION_PEAK),
    }


def _mfsgd_work(r):
    """Per update (one rating): dot(W_u, H_i) + two axpy rows ≈ 6·rank
    FLOPs; min bytes = both rows read + written = 16·rank."""
    rank = r.get("rank", 64)
    return {"flops": 6.0 * rank, "bytes": 16.0 * rank,
            "per": ("updates_per_sec_per_chip", 1.0),
            "peak": _DEFAULT_PRECISION_PEAK}


def _lda_work(r):
    """Per token: K-wide posterior (two logs + gumbel argmax ≈ 10K flops)
    + one-hot delta matmuls ≈ 4K; min bytes = 3 K-rows read + 2 written."""
    K = r["n_topics"]
    return {"flops": 14.0 * K, "bytes": 20.0 * K,
            "per": ("tokens_per_sec_per_chip", 1.0),
            "peak": _DEFAULT_PRECISION_PEAK}


def _mlp_work(r):
    """Per sample: ≈ 6·params FLOPs (fwd 2P + bwd 4P), MNIST-shape MLP
    (784·512 + 512·256 + 256·10 ≈ 535k params); min bytes per sample =
    16·params/batch (params read fwd + bwd, grads written + optimizer
    read-modify-write ≈ 4 param-sized streams of 4 B, amortized over the
    batch).  samples_per_sec is whole-mesh → divide by num_workers."""
    params = 535_818
    return {"flops": 6.0 * params,
            "bytes": 16.0 * params / r.get("batch", 8192),
            "per": ("samples_per_sec", 1.0 / r.get("num_workers", 1)),
            "peak": _DEFAULT_PRECISION_PEAK}


# configs without a trustworthy closed-form model (irregular access
# patterns dominate) are intentionally absent: no number beats a wrong one
WORK_MODELS = {
    "kmeans": _kmeans_work,
    "kmeans_int8": _kmeans_work,
    "kmeans_int8_fused": _kmeans_work,
    # PR 11: the planner's hier-psum candidate only reschedules the
    # collective — compute and HBM floors are the family's
    "kmeans_hier_psum": _kmeans_work,
    "kmeans_stream": _kmeans_work,
    "kmeans_stream_int8": _kmeans_work,
    "mfsgd": _mfsgd_work,
    "mfsgd_scatter": _mfsgd_work,
    "mfsgd_pallas": _mfsgd_work,
    # the carry/approx/hot variants share their family's model.  NB the
    # floor's meaning shifts for carry rows: without carry every entry
    # re-pays its tile, so actual HBM bytes >= the per-update floor and
    # achieved_gbs is a lower bound; WITH carry a run's rows amortize and
    # actual bytes can drop BELOW the floor, so a carry row's
    # achieved_gbs/pct_peak_bw read as the ALGORITHMIC traffic rate (an
    # upper bound on real DRAM), not an achieved-bandwidth claim — the
    # trace pass, not this model, settles real bytes for those rows
    "mfsgd_carry": _mfsgd_work,
    "mfsgd_chunked_rotate": _mfsgd_work,
    "lda": _lda_work,
    "lda_carry": _lda_work,
    "lda_exprace": _lda_work,
    "lda_fast": _lda_work,
    "lda_pallas": _lda_work,
    "lda_pallas_approx": _lda_work,
    "lda_pallas_carry": _lda_work,
    "lda_pallas_hot": _lda_work,
    "lda_pallas_approx_hot": _lda_work,
    "lda_rotate_int8": _lda_work,
    # PR 11: the planner's bf16 wire — same compute, narrower ring only
    "lda_planner_wire": _lda_work,
    "lda_scale": _lda_work,
    "lda_scale_1m": _lda_work,
    "lda_scale_1m_pallas": _lda_work,
    "lda_scatter": _lda_work,
    "mlp": _mlp_work,
}


def annotate(config: str, result: dict, peaks: dict = V5E_PEAKS) -> dict:
    """Add roofline fields to a benchmark result dict (returns a copy).

    Adds ``achieved_tflops``, ``achieved_gbs``, ``pct_peak_flops``,
    ``pct_peak_bw`` and ``bound`` ("compute" | "memory" — whichever wall
    is closer).  Configs without a work model pass through unchanged.
    """
    model = WORK_MODELS.get(config)
    if model is None:
        return dict(result)
    try:
        w = model(result)
    except KeyError:  # result lacks the shape fields (partial/error record)
        return dict(result)
    metric, scale = w["per"]
    if metric not in result:
        return dict(result)
    rate = float(result[metric]) * scale          # units/s
    flops_s = rate * w["flops"]
    bytes_s = rate * w["bytes"]
    peak_f = peaks[w["peak"]]
    pf = 100.0 * flops_s / peak_f
    pb = 100.0 * bytes_s / peaks["hbm_gbs"]
    out = dict(result)
    out.update({
        "achieved_tflops": round(flops_s / 1e12, 3),
        "achieved_gbs": round(bytes_s / 1e9, 2),
        "pct_peak_flops": round(pf, 2),
        "pct_peak_bw": round(pb, 2),
        "roofline_peak": w["peak"],
        "bound": "compute" if pf >= pb else "memory",
    })
    return out
