"""Roofline annotation — "X% of chip peak", not "faster than yesterday".

Reference parity (SURVEY.md §7 / round-1 VERDICT item 9): BASELINE.md's
numbers need a roofline column so a measured rate reads as a fraction of
what the chip can do.  Each graded config gets an ANALYTIC work model
(FLOPs and minimum HBM bytes per unit of its throughput metric); paired
with a measured benchmark dict it yields achieved TFLOP/s, achieved
GB/s, percent-of-peak for both, and which wall the config is against.

The models are deliberately lower-bound byte models (inputs read once,
outputs written once — XLA fusion can't do better) and exact FLOP
counts for the dominant kernels; percentages can therefore slightly
UNDERSTATE achieved bandwidth but never flatter it.  Peaks are the
public TPU v5e datasheet figures.
"""

from __future__ import annotations

# Public v5e (v5 lite) per-chip datasheet peaks.
V5E_PEAKS = {
    "bf16_flops": 197e12,   # MXU bf16 FLOP/s
    "int8_ops": 394e12,     # MXU int8 OP/s
    "f32_flops": 49.25e12,  # bf16/4: f32 matmul passes through the MXU
    "hbm_gbs": 819e9,       # HBM bandwidth, bytes/s
}


def _kmeans_work(r):
    """Per iteration: distance matmul 2ndk + one-hot sums matmul 2nkd;
    min bytes = points once (dtype-sized) + scores [n,k] write+read.
    iters_per_sec is a WHOLE-MESH rate over the whole-n workload, so the
    per-chip comparison divides by num_workers."""
    n, d, k = r["n"], r["d"], r["k"]
    dsize = 1 if r.get("quantize") == "int8" else 4
    return {
        "flops": 4.0 * n * d * k,
        "bytes": n * d * dsize + 8.0 * n * k,
        "per": ("iters_per_sec", 1.0 / r.get("num_workers", 1)),
        "peak": ("int8_ops" if r.get("quantize") == "int8" else "f32_flops"),
    }


def _mfsgd_work(r):
    """Per update (one rating): dot(W_u, H_i) + two axpy rows ≈ 6·rank
    FLOPs; min bytes = both rows read + written = 16·rank."""
    rank = r.get("rank", 64)
    return {"flops": 6.0 * rank, "bytes": 16.0 * rank,
            "per": ("updates_per_sec_per_chip", 1.0), "peak": "f32_flops"}


def _lda_work(r):
    """Per token: K-wide posterior (two logs + gumbel argmax ≈ 10K flops)
    + one-hot delta matmuls ≈ 4K; min bytes = 3 K-rows read + 2 written."""
    K = r["n_topics"]
    return {"flops": 14.0 * K, "bytes": 20.0 * K,
            "per": ("tokens_per_sec_per_chip", 1.0), "peak": "f32_flops"}


def _mlp_work(r):
    """Per sample: ≈ 6·params FLOPs (fwd 2P + bwd 4P), MNIST-shape MLP
    (784·512 + 512·256 + 256·10 ≈ 535k params); min bytes per sample =
    16·params/batch (params read fwd + bwd, grads written + optimizer
    read-modify-write ≈ 4 param-sized streams of 4 B, amortized over the
    batch).  samples_per_sec is whole-mesh → divide by num_workers."""
    params = 535_818
    return {"flops": 6.0 * params,
            "bytes": 16.0 * params / r.get("batch", 8192),
            "per": ("samples_per_sec", 1.0 / r.get("num_workers", 1)),
            "peak": "f32_flops"}


# configs without a trustworthy closed-form model (irregular access
# patterns dominate) are intentionally absent: no number beats a wrong one
WORK_MODELS = {
    "kmeans": _kmeans_work,
    "kmeans_int8": _kmeans_work,
    "kmeans_stream": _kmeans_work,
    "mfsgd": _mfsgd_work,
    "mfsgd_scatter": _mfsgd_work,
    "lda": _lda_work,
    "lda_scale": _lda_work,
    "lda_scale_1m": _lda_work,
    "lda_scatter": _lda_work,
    "mlp": _mlp_work,
}


def annotate(config: str, result: dict, peaks: dict = V5E_PEAKS) -> dict:
    """Add roofline fields to a benchmark result dict (returns a copy).

    Adds ``achieved_tflops``, ``achieved_gbs``, ``pct_peak_flops``,
    ``pct_peak_bw`` and ``bound`` ("compute" | "memory" — whichever wall
    is closer).  Configs without a work model pass through unchanged.
    """
    model = WORK_MODELS.get(config)
    if model is None:
        return dict(result)
    try:
        w = model(result)
    except KeyError:  # result lacks the shape fields (partial/error record)
        return dict(result)
    metric, scale = w["per"]
    if metric not in result:
        return dict(result)
    rate = float(result[metric]) * scale          # units/s
    flops_s = rate * w["flops"]
    bytes_s = rate * w["bytes"]
    peak_f = peaks[w["peak"]]
    pf = 100.0 * flops_s / peak_f
    pb = 100.0 * bytes_s / peaks["hbm_gbs"]
    out = dict(result)
    out.update({
        "achieved_tflops": round(flops_s / 1e12, 3),
        "achieved_gbs": round(bytes_s / 1e9, 2),
        "pct_peak_flops": round(pf, 2),
        "pct_peak_bw": round(pb, 2),
        "roofline_peak": w["peak"],
        "bound": "compute" if pf >= pb else "memory",
    })
    return out
