"""Raw threefry key bits — the PRNGKey-specialization trap, fixed at the
source.

CLAUDE.md relay trap: ``jax.random.PRNGKey(python_int)`` specializes on
the int — a step function that bakes a fresh seed into its traced program
pays a fresh (~140 ms remote) compile per seed.  The fix is always the
same two lines: build the key's raw uint32[2] bits with numpy (no jax
computation at all), and pass them *as an argument* so the compiled
program is seed-independent.  Before this module each driver open-coded
that (mlp ``fit_resident``, lda ``_advance_keys`` comment); now they all
share one helper whose bit-exactness against ``PRNGKey`` is pinned by
tests/test_prng.py, and whose no-recompile-across-seeds property is
checked by the flight recorder's CompileWatch.
"""

from __future__ import annotations

import numpy as np


def key_bits(seed: int) -> np.ndarray:
    """uint32[2] raw threefry key, bit-identical to
    ``np.asarray(jax.random.PRNGKey(seed))`` — built entirely in numpy so
    a NEW seed never costs a compile.

    In x32 mode (this repo's default) ``PRNGKey`` truncates the seed to
    its low 32 bits and the high word lowers to 0 (``shift_right_logical``
    by 32 on an int32); with ``jax_enable_x64`` the full 64-bit split
    applies.  Negative seeds follow two's complement in both modes,
    matching jax exactly (pinned in tests/test_prng.py).
    """
    import jax

    seed = int(seed)
    if not jax.config.jax_enable_x64:
        return np.array([0, seed & 0xFFFFFFFF], np.uint32)
    return np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)


def split_keys(seed: int, num: int) -> np.ndarray:
    """[num, 2] uint32 host keys, bit-identical to
    ``np.asarray(jax.random.split(jax.random.PRNGKey(seed), num))``.

    The split program traces on the key *array* (shape-specialized only),
    so it compiles once per ``num`` and is cache-hit for every subsequent
    seed — unlike ``split(PRNGKey(s), num)``, which pays the PRNGKey
    specialization per distinct ``s``.  The result is a host array, ready
    for ``mesh.shard_array`` (the per-worker key pattern lda/rf use).
    """
    import jax
    import jax.numpy as jnp

    return np.asarray(jax.random.split(jnp.asarray(key_bits(seed)), num))
