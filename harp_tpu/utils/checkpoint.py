"""Checkpoint / resume — strictly better than the reference.

Reference parity (SURVEY.md §6): Harp has no framework checkpoint API; apps
hand-write model tables to HDFS every k iterations and a failed YARN task
restarts the whole job from the last dump.  Here checkpointing is a
framework utility on `orbax-checkpoint`: model pytree + iteration counter,
atomic directories, keep-last-k, and a ``latest_step``/restore pair that a
driver's ``--resume`` flag plugs into.  Failure model matches the
reference (fail-fast, restart from checkpoint; no elasticity).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


class CheckpointManager:
    """Save/restore a model pytree + step counter under ``root``."""

    def __init__(self, root: str, keep: int = 3):
        self.root = os.path.abspath(root)
        self.keep = keep
        os.makedirs(self.root, exist_ok=True)
        self._ckptr = _checkpointer()

    def _path(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step:012d}")

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.root):
            if name.startswith("step_"):
                try:
                    out.append(int(name.split("_")[1]))
                except (IndexError, ValueError):
                    continue
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def save(self, step: int, state: Any) -> str:
        """Write state (any pytree of arrays) for ``step``; prunes old."""
        path = self._path(step)
        # device arrays → host before orbax (works for sharded arrays too);
        # wrap in a dict so bare-array / scalar states are valid orbax trees
        # (the dunder key cannot collide with a user pytree's own keys)
        host_state = {"__harp_state__": jax.tree.map(np.asarray, state)}
        self._ckptr.save(path, host_state, force=True)
        for old in self.steps()[: -self.keep] if self.keep else []:
            import shutil

            shutil.rmtree(self._path(old), ignore_errors=True)
        return path

    def restore_latest(self) -> tuple[int, Any]:
        """(newest step, state) — the ``harp serve`` load path: a server
        wants "the newest trained model under this root" without
        enumerating steps itself.  Raises FileNotFoundError when the
        root holds no checkpoints (same contract as :meth:`restore`)."""
        return self.restore(None)

    def restore(self, step: int | None = None) -> tuple[int, Any]:
        """Restore (step, state); latest if step is None."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        tree = self._ckptr.restore(self._path(step))
        if isinstance(tree, dict) and set(tree) == {"__harp_state__"}:
            return step, tree["__harp_state__"]
        raise ValueError(
            f"{self._path(step)} is not a harp-tpu checkpoint "
            f"(missing the __harp_state__ wrapper)")
