"""Checkpoint / resume — strictly better than the reference.

Reference parity (SURVEY.md §6): Harp has no framework checkpoint API; apps
hand-write model tables to HDFS every k iterations and a failed YARN task
restarts the whole job from the last dump.  Here checkpointing is a
framework utility on `orbax-checkpoint`: model pytree + iteration counter,
atomic directories, keep-last-k, and a ``latest_step``/restore pair that a
driver's ``--resume`` flag plugs into.  Failure model matches the
reference (fail-fast, restart from checkpoint; no elasticity).

Crash-mid-write hardening (PR 10): :meth:`CheckpointManager.save` writes
into a ``tmp.<step>`` staging directory and atomic-renames it into
``step_<step>`` only once the write completed — a process killed mid-save
leaves a ``tmp.*`` dir every reader ignores, never a half-written
``step_*``.  Against checkpoints damaged by OTHER means (a truncated
copy, a torn filesystem), :meth:`restore_latest` / :meth:`restore` with
``step=None`` fall back step-by-step to the newest checkpoint that
actually restores, so one bad directory cannot strand a ``--resume``.
The write path notifies ``flightrec.notify_ckpt_write`` first, which is
the fault plane's ``ckpt_write`` injection site: an injected fault there
models the crash-mid-write this layout exists for.
"""

from __future__ import annotations

import os
import shutil
import warnings
from typing import Any

import jax
import numpy as np

from harp_tpu.utils import flightrec


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


class CheckpointManager:
    """Save/restore a model pytree + step counter under ``root``."""

    def __init__(self, root: str, keep: int = 3):
        self.root = os.path.abspath(root)
        self.keep = keep
        os.makedirs(self.root, exist_ok=True)
        self._ckptr = _checkpointer()

    def _path(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step:012d}")

    def _tmp_path(self, step: int) -> str:
        return os.path.join(self.root, f"tmp.{step:012d}")

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.root):
            if name.startswith("step_"):
                try:
                    out.append(int(name.split("_")[1]))
                except (IndexError, ValueError):
                    continue
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def save(self, step: int, state: Any) -> str:
        """Write state (any pytree of arrays) for ``step``; prunes old.

        Crash-atomic: everything lands in ``tmp.<step>`` first and only a
        completed write is renamed into ``step_<step>`` (one directory-
        entry swap — atomic on POSIX), so a kill at ANY point during the
        write leaves either the previous checkpoint set intact or the
        previous set plus one ignorable ``tmp.*`` (swept on the next
        save of the same step).
        """
        final = self._path(step)
        tmp = self._tmp_path(step)
        # the fault plane's ckpt_write site: BEFORE any byte lands, so an
        # injected fault is exactly the crash-mid-write the tmp-dir
        # layout must make unobservable
        flightrec.notify_ckpt_write(final)
        shutil.rmtree(tmp, ignore_errors=True)  # stale from a crashed save
        # device arrays → host before orbax (works for sharded arrays too);
        # wrap in a dict so bare-array / scalar states are valid orbax trees
        # (the dunder key cannot collide with a user pytree's own keys)
        host_state = {"__harp_state__": jax.tree.map(np.asarray, state)}
        self._ckptr.save(tmp, host_state, force=True)
        if os.path.exists(final):  # force semantics, preserved
            shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        for old in self.steps()[: -self.keep] if self.keep else []:
            shutil.rmtree(self._path(old), ignore_errors=True)
        return final

    def restore_latest(self) -> tuple[int, Any]:
        """(newest restorable step, state) — the ``harp serve`` load path
        and every ``--resume``'s entry.  A damaged newest checkpoint
        (truncated files, missing metadata) is skipped with a warning
        and the previous step restores instead — one bad directory must
        not strand a resume.  Raises FileNotFoundError when the root
        holds no restorable checkpoint at all."""
        steps = self.steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        last_err: Exception | None = None
        for step in reversed(steps):
            try:
                return self._restore_step(step)
            except Exception as e:  # noqa: BLE001 - fall back, loudly
                last_err = e
                warnings.warn(
                    f"checkpoint {self._path(step)} failed to restore "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous step", RuntimeWarning, stacklevel=2)
        raise FileNotFoundError(
            f"no restorable checkpoint under {self.root} "
            f"(newest error: {last_err})")

    def restore(self, step: int | None = None) -> tuple[int, Any]:
        """Restore (step, state); latest *restorable* if step is None."""
        if step is None:
            return self.restore_latest()
        return self._restore_step(step)

    def _restore_step(self, step: int) -> tuple[int, Any]:
        tree = self._ckptr.restore(self._path(step))
        if isinstance(tree, dict) and set(tree) == {"__harp_state__"}:
            state = tree["__harp_state__"]
            # memory spine (PR 19): restore lands in HOST RAM, so the
            # ledger records the bytes as a zero-delta "restored" event
            # — the shard_array H2D that follows is the staged entry
            from harp_tpu.utils import memrec, telemetry

            if telemetry.enabled():
                nbytes = sum(
                    int(getattr(leaf, "nbytes", 0) or 0)
                    for leaf in jax.tree.leaves(state))
                memrec.on_restored(nbytes, f"ckpt:step_{step}")
            return step, state
        raise ValueError(
            f"{self._path(step)} is not a harp-tpu checkpoint "
            f"(missing the __harp_state__ wrapper)")
