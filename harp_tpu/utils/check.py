"""Runtime checking — SURVEY.md §6 "race detection / sanitizers".

Reference behavior: Harp has no framework-level race detection — the JVM
memory model plus a synchronized event queue, with data races possible in
user ``Task`` threads.  On TPU the collectives and jitted steps are
pure-functional and deterministic by construction, so the race class
disappears; what remains worth sanitizing is numerics (NaN/inf) and
out-of-bounds indexing in gather/scatter-heavy kernels (MF-SGD, LDA).
``checkify`` instruments those at the XLA level.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.experimental import checkify

SANITIZE = checkify.float_checks | checkify.index_checks | checkify.user_checks


def checked_jit(fn: Callable, *, errors=SANITIZE, **jit_kwargs) -> Callable:
    """``jit`` with NaN / OOB-index / user-assert sanitizers compiled in.

    Returns a callable with the same signature as ``fn`` that raises
    ``checkify.JaxRuntimeError`` (on the host, at call time) if any check
    trips on device.  Debug/test builds pay the instrumentation cost; hot
    production loops should jit the raw ``fn``.
    """
    checked = checkify.checkify(fn, errors=errors)
    compiled = jax.jit(checked, **jit_kwargs)

    def wrapper(*args, **kw):
        err, out = compiled(*args, **kw)
        checkify.check_error(err)
        return out

    return wrapper


def assert_finite(tree: Any, name: str = "value") -> None:
    """In-kernel user check: every leaf finite (use inside checked fns)."""
    import jax.numpy as jnp

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        checkify.check(jnp.all(jnp.isfinite(leaf)),
                       f"{name}{jax.tree_util.keystr(path)} has non-finite values")
