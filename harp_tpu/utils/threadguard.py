"""Runtime twin of harplint Layer 5 — thread-ownership assertions.

Reference parity (SURVEY.md §6; the static half is
``harp_tpu/analysis/threadgraph.py``): HL401–HL405 prove at lint time
that no forbidden thread root can *reach* a jax-touching call or an
unlocked spine mutator.  This module proves the same contract at RUN
time, the way the flight recorder's budgets are the runtime twin of the
HL0xx traps: when armed, every flightrec observer site (dispatch / h2d
/ readback / ckpt-write) and every mutator of a spine the static layer
could NOT verify as internally locked asserts that the current thread's
name does not match any forbidden pattern, and raises
:class:`ThreadOwnershipError` if it does.

The ownership map is **generated from the static layer**
(:func:`harp_tpu.analysis.threadgraph.ownership_map`) — the forbidden
patterns are the name patterns of the named non-owner thread roots the
graph discovered, and the spine wrap list is exactly the spines whose
mutators the graph could not verify as locked.  The two halves are
sync-pinned by tests/test_threadguard.py (the HL303/``flightrec.track``
pattern): hand-editing the runtime map is impossible by construction.

Cost contract (the PR-3 pattern, pinned by the flagship budget tests):
disarmed, this module installs NOTHING — no observer callbacks, no
wrapped mutators, zero per-op work — so the serve sustained bench and
the mfsgd/lda/kmeans budgets are bit-identical with the guard present.
Armed (tests, chaos runs), each guarded site costs one thread-name
fnmatch sweep.

Usage::

    with threadguard.armed():          # raising, for tests
        run_serve_plane()
    assert threadguard.stats()["checks"] > 0   # non-vacuous
"""

from __future__ import annotations

import contextlib
import fnmatch
import functools
import importlib
import threading
from typing import Any


class ThreadOwnershipError(AssertionError):
    """A jax-touching op or unlocked-spine mutation ran on a thread the
    static thread-root graph forbids (HL401/HL403 at runtime)."""


class _Guard:
    def __init__(self) -> None:
        self.patterns: tuple[str, ...] = ()
        self.checks = 0
        self.violations: list[str] = []
        self._installed: list[tuple[list, Any]] = []      # (registry, cb)
        self._wrapped: list[tuple[Any, str, Any]] = []    # (obj, attr, orig)
        self.active = False

    def check(self, what: str) -> None:
        self.checks += 1
        name = threading.current_thread().name
        for pat in self.patterns:
            if fnmatch.fnmatch(name, pat):
                msg = (f"{what} on forbidden thread {name!r} "
                       f"(matches ownership pattern {pat!r}) — this "
                       "thread root is not a jax owner on its plane; "
                       "route the op through the designated owner "
                       "(see harp_tpu/analysis/threadgraph.py)")
                self.violations.append(msg)
                raise ThreadOwnershipError(msg)


_guard = _Guard()


def arm(omap: dict | None = None) -> None:
    """Install the ownership assertions.  ``omap`` defaults to the map
    generated from the static layer — pass one explicitly only in tests
    that sabotage it on purpose."""
    if _guard.active:
        return
    if omap is None:
        from harp_tpu.analysis import threadgraph

        omap = threadgraph.ownership_map()
    _guard.patterns = tuple(omap.get("forbidden_thread_patterns", ()))
    _guard.checks = 0
    _guard.violations = []
    from harp_tpu.utils import flightrec

    sites = (
        (flightrec._DISPATCH_OBSERVERS,
         lambda label: _guard.check(f"dispatch {label!r}")),
        (flightrec._READBACK_OBSERVERS,
         lambda x: _guard.check("readback")),
        (flightrec._H2D_OBSERVERS,
         lambda nbytes, site: _guard.check(f"h2d staging ({site})")),
        (flightrec._CKPT_WRITE_OBSERVERS,
         lambda path: _guard.check("ckpt write")),
    )
    for registry, cb in sites:
        registry.append(cb)
        _guard._installed.append((registry, cb))
    # spines the static layer could NOT verify as internally locked get
    # their mutators wrapped; verified-locked spines are skipped — the
    # runtime honors the static verdict (that asymmetry is the sync pin)
    for sp_name, sp in sorted(omap.get("spines", {}).items()):
        if sp.get("locked"):
            continue
        mod = importlib.import_module(sp["module"])
        target = getattr(mod, sp["obj"]) if sp.get("obj") else mod
        for mut in sp["mutators"]:
            orig = getattr(target, mut)

            def wrapper(*a, __orig=orig, __what=f"{sp_name}.{mut}",
                        **kw):
                _guard.check(f"spine mutation {__what}")
                return __orig(*a, **kw)

            functools.update_wrapper(wrapper, orig)
            setattr(target, mut, wrapper)
            _guard._wrapped.append((target, mut, orig))
    _guard.active = True


def disarm() -> None:
    """Remove everything :func:`arm` installed (restores the exact
    original callables — the zero-cost pin checks identity)."""
    for registry, cb in _guard._installed:
        if cb in registry:
            registry.remove(cb)
    _guard._installed.clear()
    for target, attr, orig in reversed(_guard._wrapped):
        setattr(target, attr, orig)
    _guard._wrapped.clear()
    _guard.patterns = ()
    _guard.active = False


@contextlib.contextmanager
def armed(omap: dict | None = None):
    """``with threadguard.armed(): ...`` — arm for the block, always
    disarm on exit."""
    arm(omap)
    try:
        yield _guard
    finally:
        disarm()


def stats() -> dict:
    """Non-vacuity evidence: how many ownership checks actually ran."""
    return {"active": _guard.active, "checks": _guard.checks,
            "patterns": list(_guard.patterns),
            "violations": list(_guard.violations)}
