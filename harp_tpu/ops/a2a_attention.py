"""Ulysses-style all-to-all sequence parallelism for attention.

Long-context support (SURVEY.md §6), the second canonical scheme next to
:mod:`harp_tpu.ops.ring_attention`: instead of rotating K/V blocks around
the ring, one ``all_to_all`` (Harp's *regroup* verb — the same collective
``C.regroup`` lowers to) re-shards the tensors from sequence-sharded to
head-sharded, every worker runs exact local attention over the FULL
sequence for its subset of heads, and a second ``all_to_all`` restores
sequence sharding.

Trade-offs vs ring (why both exist):
- a2a moves each of Q, K, V, O exactly once (4·bytes/chip) regardless of
  worker count; ring moves K/V (n−1) times — a2a wins on fabrics where
  latency dominates and for small n.
- ring never materializes full-sequence K/V on a chip; a2a holds full
  K/V for h/n heads, so K/V memory is O(seq) — ring is the one that
  scales to million-token contexts (its per-chip memory is O(seq/n)).
  The local attention here is blockwise online-softmax (ring attention's
  recurrence over resident K/V blocks), so scores stay O(seq·block_k),
  not O(seq²); ``block_k=None`` falls back to one dense block.
- a2a needs ``heads % n_workers == 0`` — and under GQA also
  ``kv_heads % n_workers == 0``, since the all_to_all reshards the KV
  head dim (so MQA's single KV head only works single-worker); ring has
  no head constraint and carries GQA/MQA at the small head count.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WORKER_AXIS, WorkerMesh
from harp_tpu.ops.ring_attention import online_softmax_block


def _local_attention(q, k, v, scale, causal, block_k, window=None):
    """Exact attention, everything resident ([b, s, h, d] each), computed
    blockwise over K/V with the online-softmax recurrence so the score
    tensor is [b, h, s, block_k], never [b, h, s, s]."""
    b, s, h, d = q.shape
    hk = k.shape[2]  # may be < h under GQA
    bk = s if block_k is None else block_k
    if s % bk != 0:
        raise ValueError(f"block_k={bk} must divide the sequence length {s}")
    pos = jnp.arange(s)
    m0 = jnp.full((b, h, s), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    acc0 = jnp.zeros((b, s, h, d), jnp.float32)
    kb = k.reshape(b, s // bk, bk, hk, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, s // bk, bk, hk, d).transpose(1, 0, 2, 3, 4)

    def body(carry, inp):
        m, l, acc = carry
        kt, vt, t = inp
        m, l, acc = online_softmax_block(
            q, kt, vt, m, l, acc, pos, t * bk + jnp.arange(bk), scale,
            causal, window)
        return (m, l, acc), None

    (m, l, acc), _ = lax.scan(body, (m0, l0, acc0),
                              (kb, vb, jnp.arange(s // bk)))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def a2a_attention(q, k, v, *, causal: bool = False, axis: str = WORKER_AXIS,
                  scale: float | None = None, block_k: int | None = None,
                  window: int | None = None):
    """Exact multi-head attention, sequence sharded, via all-to-all (device view).

    Args (per-worker shards, call inside ``shard_map``):
      q, k, v: [batch, seq_local, heads, head_dim]; heads must be divisible
      by the worker count.
    Returns: [batch, seq_local, heads, head_dim].
    """
    n = lax.axis_size(axis)
    b, nq, h, d = q.shape
    g = k.shape[2]
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window} (window=0 would "
                         "mask every key and silently return zeros)")
    if h % n != 0:
        raise ValueError(
            f"a2a attention needs heads ({h}) divisible by workers ({n}); "
            "use ring_attention for head counts that don't divide")
    if g != h and (h % g != 0 or g % n != 0):
        raise ValueError(
            f"a2a GQA needs KV heads ({g}) dividing query heads ({h}) AND "
            f"divisible by workers ({n}) — the all_to_all reshards the KV "
            "head dim too; use ring_attention otherwise")
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    # seq-sharded → head-sharded ([b, s/n, h, d] → [b, s, h/n, d]) is one
    # regroup (Harp's shuffle verb); the inverse restores sequence sharding
    qh, kh, vh = C.regroup((q, k, v), axis=axis, split_dim=2, concat_dim=1)
    out = _local_attention(qh, kh, vh, scale, causal, block_k, window)
    return C.regroup(out, axis=axis, split_dim=1, concat_dim=2)


def make_a2a_attention_fn(mesh: WorkerMesh, causal: bool = False,
                          block_k: int | None = None,
                          window: int | None = None):
    """Host-view compile: full arrays in, sequence-sharded underneath."""
    fn = functools.partial(a2a_attention, causal=causal, axis=mesh.axis,
                           block_k=block_k, window=window)
    spec = mesh.spec(1, ndim=4)  # shard the sequence dim
    return jax.jit(mesh.shard_map(fn, in_specs=(spec,) * 3, out_specs=spec))
