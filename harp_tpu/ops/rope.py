"""Rotary position embeddings for sequence-sharded tensors.

Long-context support (SURVEY.md §6, companion to
:mod:`harp_tpu.ops.ring_attention` / :mod:`harp_tpu.ops.a2a_attention`):
RoPE needs each token's GLOBAL position, but under sequence parallelism a
worker holds only its local shard — the helper derives global positions
from the worker index the same way the attention schemes derive their
mask positions, so Q/K can be rotated shard-locally before attention with
no gather of position tables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel.mesh import WORKER_AXIS, WorkerMesh


def rope_angles(positions, head_dim: int, base: float = 10000.0):
    """[S] positions → (cos [S, head_dim/2], sin [S, head_dim/2])."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    inv_freq = 1.0 / (base ** (jnp.arange(head_dim // 2) / (head_dim // 2)))
    ang = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, *, axis: str = WORKER_AXIS, base: float = 10000.0):
    """Rotate a sequence-SHARDED [batch, seq_local, heads, head_dim] tensor
    by its tokens' global positions (device view — call inside shard_map,
    before :func:`ring_attention` / :func:`a2a_attention`).

    Pairs dimension ``2i`` with ``2i+1`` (the interleaved convention).
    """
    b, nq, h, d = x.shape
    pos = lax.axis_index(axis) * nq + jnp.arange(nq)
    cos, sin = rope_angles(pos, d, base)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(b, nq, h, d).astype(x.dtype)


def make_rope_fn(mesh: WorkerMesh, base: float = 10000.0):
    """Host-view compile: full array in, sequence-sharded underneath."""
    fn = functools.partial(apply_rope, axis=mesh.axis, base=base)
    spec = mesh.spec(1, ndim=4)
    return jax.jit(mesh.shard_map(fn, in_specs=(spec,), out_specs=spec))
