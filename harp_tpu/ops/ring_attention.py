"""Ring attention: exact attention over a sequence-sharded ring.

Long-context support (SURVEY.md §6): the sequence axis is sharded across
workers (sequence/context parallelism); K/V blocks travel the ring via
``rotate`` (the dymoro ppermute pattern) while each worker's resident Q
block accumulates **online softmax** statistics (the flash-attention
recurrence), so attention over the full sequence is exact without any
worker ever materializing full K/V — memory per chip is O(seq/n), enabling
sequences n× longer than a single chip holds.

The rotation is issued before the block compute each step, so XLA overlaps
the ICI transfer with the attention math (K/V are read-only — the easy
case of the rotate pipeline).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WORKER_AXIS, WorkerMesh


def online_softmax_block(q, k, v, m, l, acc, q_pos, k_pos, scale, causal,
                         window=None):
    """One online-softmax update of (m, l, acc) with a K/V block.

    q: [B, nq, H, D]; k, v: [B, nk, G, D] with ``H % G == 0`` (G < H is
    grouped-query attention: each KV head serves ``H/G`` query heads —
    K/V are stored, rotated, and resharded with G heads, the whole point
    of GQA's memory/traffic saving; the head expansion happens only here,
    inside the block compute, where XLA keeps it fused); m, l: [B, H, nq];
    acc like q.

    Shared API: this is the flash-attention recurrence both sequence-parallel
    schemes build on — ring attention scans it over rotating K/V blocks,
    a2a attention (:mod:`harp_tpu.ops.a2a_attention`) over resident ones.
    """
    h, g = q.shape[2], k.shape[2]
    if h != g:
        if h % g != 0:
            raise ValueError(
                f"query heads ({h}) must be a multiple of KV heads ({g}) "
                "for grouped-query attention")
        k = jnp.repeat(k, h // g, axis=2)
        v = jnp.repeat(v, h // g, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    delta = q_pos[None, None, :, None] - k_pos[None, None, None, :]
    mask = None
    if causal:
        mask = delta >= 0
    if window is not None:
        # sliding window: causal form attends to the last `window` keys
        # (incl. self); bidirectional to |q_pos - k_pos| < window
        near = (delta < window) if causal else (jnp.abs(delta) < window)
        mask = near if mask is None else (mask & near)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_blk = scores.max(-1)                               # [B, H, nq]
    m_new = jnp.maximum(m, m_blk)
    # guard: fully-masked rows keep m=-inf; exp(-inf - -inf) → use where
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(scores),
                          scores - m_new[..., None], -jnp.inf))
    l_new = l * alpha + p.sum(-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(q, k, v, *, causal: bool = False, axis: str = WORKER_AXIS,
                   scale: float | None = None, window: int | None = None):
    """Exact multi-head attention, sequence sharded (device view).

    Args (per-worker shards, call inside ``shard_map``):
      q: [batch, seq_local, heads, head_dim]; k, v: same with ``kv_heads``
      dividing ``heads`` (GQA/MQA — K/V travel the ring with the smaller
      head count, so ring traffic shrinks by the group factor).
      causal: apply causal masking using *global* positions.
      window: sliding-window attention — each query attends to the last
        ``window`` keys (incl. itself) when causal, or to keys within
        ``window - 1`` positions either side when not.  Exact: blocks
        fully outside the window contribute -inf scores and drop out of
        the online softmax.
    Returns: [batch, seq_local, heads, head_dim] attention output.
    """
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, nq, h, d = q.shape
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window} (window=0 would "
                         "mask every key and silently return zeros)")
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    steps = n
    if window is not None and causal:
        # a causal window only reaches back ceil((window-1)/nq) shards, so
        # later ring steps hold fully-masked blocks — truncating the scan
        # is exact and cuts compute/ICI from O(n) to O(window/nq) steps
        steps = min(n, -(-(window - 1) // nq) + 1)

    q_pos = me * nq + jnp.arange(nq)
    m0 = jnp.full((b, h, nq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, nq), jnp.float32)
    acc0 = jnp.zeros((b, nq, h, d), jnp.float32)

    def body(carry, t):
        m, l, acc, k_cur, v_cur = carry
        # rotate first: transfer has no dependency on this step's compute,
        # so it rides ICI while the MXU does the block attention
        k_nxt = C.rotate(k_cur, axis=axis)
        v_nxt = C.rotate(v_cur, axis=axis)
        src = (me - t) % n                      # whose block is resident
        k_pos = src * nq + jnp.arange(k_cur.shape[1])
        m, l, acc = online_softmax_block(q, k_cur, v_cur, m, l, acc,
                                  q_pos, k_pos, scale, causal, window)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = lax.scan(body, (m0, l0, acc0, k, v),
                                    jnp.arange(steps))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention_fn(mesh: WorkerMesh, causal: bool = False,
                           window: int | None = None):
    """Host-view compile: full arrays in, sequence-sharded underneath."""
    fn = functools.partial(ring_attention, causal=causal, axis=mesh.axis,
                           window=window)
    spec = mesh.spec(1, ndim=4)  # shard the sequence dim
    return jax.jit(mesh.shard_map(fn, in_specs=(spec,) * 3, out_specs=spec))
