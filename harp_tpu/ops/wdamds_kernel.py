"""Fused SMACOF distance + B(X)·X row block — Pallas TPU kernel.

Reference parity: Harp's ``edu.iu.wdamds`` unweighted Guttman transform
(SURVEY.md §3.4), in-tree as the XLA path (`models/wdamds.py:
make_smacof_fn`'s ``body``).  The PR-16 wall attribution billed the
committed wdamds iteration to gather_dus/HBM: XLA materialises the
[n_loc, N] distance block D, then the [n_loc, N] ratio block, each
round-tripping HBM between fusions before the B·X contraction reads
them back.  This kernel fuses the whole row-block update — x²/y² norms,
the Xl·Xᵀ cross matmul, sqrt, the guarded δ/D ratio, live masking, and
the −ratio·X + rowsum·Xl Guttman contraction — into one VMEM-resident
program per row tile: D and ratio never exist in HBM.

Layout (the `ops/kmeans_kernel.py` rules): the replicated coordinate
block rides TRANSPOSED as X^T [dimp, N] (dim zero-padded to one 128
lane register) and stays whole in VMEM with a constant index map, so
both matmuls contract over legal Mosaic patterns —

    cross [tn, N]   = Xl [tn, dimp] @ XT [dimp, N]  (A-lanes × B-sublanes)
    bx    [tn, dimp] −= ratio [tn, N] · XT [dimp, N]  (lanes of BOTH)

Grid/memory plan (1-D sequential grid over row tiles): X^T resident;
δ/Xl/row-mask stream tn rows at a time; each grid step writes its own
output tile (no accumulation across steps).  Zero-padded rows carry
row_mask = 0 and zero-padded dims are zero in both Xl and X^T, so pads
contribute nothing and are sliced off outside.  The bf16 arm composes
with ``MDSConfig.delta_dtype``: a bf16-staged δ streams half the tile
bytes and promotes to f32 in-kernel (same promotion as the XLA path).

Expected headroom (analytic, 2026-08-06 — NOT yet a measurement; the
tile comes from ``perfmodel.presize("wdamds.smacof_dist", ...)`` and
the kernel is Mosaic-proven via HL201 only): removes ~5 of the 7
[n_loc, N] HBM passes per iteration the perfmodel's WDAMDS_NN_PASSES
charges the XLA schedule.  A TPU measurement goes in BASELINE.md when
a relay window runs flip candidate ``wdamds_dist_pallas`` — until then
prefer ``algo="xla"``, whose numbers are real.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128
# resident X^T + streamed δ tiles + the in-flight D/ratio registers must
# fit beside Mosaic's own buffers; 14 MB leaves ~2 MB slack under the
# 16 MB/core ceiling the registry test pins.
VMEM_BUDGET = 14 << 20
TILE_CANDIDATES = (512, 256, 128, 64, 32, 16, 8)


def vmem_bytes(dimp: int, N: int, tn: int, dsize: int) -> int:
    """Analytic VMEM byte model (also what ``perfmodel.presize``
    consults): resident X^T + double-buffered δ tile + the cross/D/ratio
    intermediates + Xl/output tiles + fixed slack."""
    return (dimp * N * 4            # resident X^T
            + 2 * tn * N * dsize    # double-buffered δ tile
            + 3 * tn * N * 4        # cross / D / ratio registers
            + 4 * tn * dimp * 4     # Xl + output tiles (double-buffered)
            + (64 << 10))


def fit_tiles(N: int, dsize: int, budget: int = VMEM_BUDGET) -> list[int]:
    """Row-tile candidates whose working set fits the VMEM budget."""
    return [t for t in TILE_CANDIDATES
            if vmem_bytes(_LANE, N, t, dsize) <= budget]


def pick_tile(n_loc: int, N: int, dsize: int) -> int:
    """Largest fitting tile no taller than the (padded) local row count
    — the rule ``perfmodel.presize`` reproduces from the price model
    (per-grid-program overhead is monotone in 1/tn)."""
    fits = fit_tiles(N, dsize)
    if not fits:
        raise ValueError(
            f"pallas wdamds: no row tile fits N={N} (dsize={dsize}) under "
            f"the {VMEM_BUDGET >> 20} MB VMEM budget; use algo='xla' or "
            f"shard over more workers")
    cap = 8 * -(-max(n_loc, 1) // 8)
    small = [t for t in fits if t <= cap]
    return max(small) if small else min(fits)


def _kernel(xT_ref, xl_ref, dlt_ref, rm_ref, nr_ref, out_ref, *, eps):
    dot = functools.partial(lax.dot_general,
                            preferred_element_type=jnp.float32)
    XT = xT_ref[...]                                    # [dimp, N]
    Xl = xl_ref[...]                                    # [tn, dimp]
    dlt = dlt_ref[...].astype(jnp.float32)              # [tn, N]
    rm = rm_ref[...]                                    # [tn, 1]
    # keep nr a [1, 1] vector: a 0-d scalar read mixes vector<f32> with
    # f32 in arith.maximumf and fails Mosaic verification
    nr = nr_ref[...]                                    # [1, 1]
    tn, N = dlt.shape
    # distances, exactly dist_block's formula (models/wdamds.py): padded
    # dims are zero in BOTH Xl and X^T, so they add nothing to any norm
    x2 = (Xl * Xl).sum(axis=1, keepdims=True)           # [tn, 1]
    y2 = (XT * XT).sum(axis=0, keepdims=True)           # [1, N]
    cross = dot(Xl, XT, (((1,), (0,)), ((), ())))       # [tn, N]
    D = jnp.sqrt(jnp.maximum(x2 - 2.0 * cross + y2, 0.0))
    colm = (lax.broadcasted_iota(jnp.int32, (tn, N), 1).astype(jnp.float32)
            < nr).astype(jnp.float32)
    ratio = jnp.where(D > eps, dlt / jnp.maximum(D, eps), 0.0) * rm * colm
    # Guttman row block: off@X + diag_fix·Xl with off = −ratio
    bx = (-dot(ratio, XT, (((1,), (1,)), ((), ())))
          + ratio.sum(axis=1, keepdims=True) * Xl)      # [tn, dimp]
    out_ref[...] = bx / jnp.maximum(nr, 1.0)


def smacof_bx(delta_rows, row_mask, Xl, X, n_real, *, eps: float,
              tn: int | None = None, interpret: bool = False):
    """One fused Guttman row-block update: returns Xl_new [n_loc, dim].

    ``delta_rows`` [n_loc, N] f32/bf16, ``row_mask`` [n_loc] f32 (0 for
    padded rows), ``Xl`` [n_loc, dim] this worker's coordinate slice,
    ``X`` [N, dim] the replicated coordinates, ``n_real`` scalar live
    count — matching `models/wdamds.py:make_smacof_fn`'s ``body`` up to
    the coordinate reshard (which stays outside).
    """
    n_loc, N = delta_rows.shape
    dim = X.shape[1]
    dimp = _LANE
    dsize = jnp.dtype(delta_rows.dtype).itemsize
    if tn is None:
        tn = pick_tile(n_loc, N, dsize)
    if not interpret:
        if N % _LANE:
            raise ValueError(
                f"pallas wdamds: N={N} must be a multiple of {_LANE} on "
                f"TPU (use algo='xla' for odd shapes)")
        if tn % 8:
            raise ValueError(
                f"pallas wdamds: row tile tn={tn} must be a multiple of 8")
    if dim > dimp:
        raise ValueError(f"pallas wdamds: dim={dim} > {dimp} unsupported")
    if vmem_bytes(dimp, N, tn, dsize) > VMEM_BUDGET:
        raise ValueError(
            f"pallas wdamds: tile ({tn}, {N}) needs "
            f"{vmem_bytes(dimp, N, tn, dsize) / 2**20:.1f} MB > "
            f"{VMEM_BUDGET >> 20} MB VMEM budget; shrink tn "
            f"(perfmodel.presize picks a fitting tile)")
    nlp = tn * -(-n_loc // tn)
    Xt = jnp.pad(X.astype(jnp.float32),
                 ((0, 0), (0, dimp - dim))).T            # [dimp, N]
    Xl_p = jnp.pad(Xl.astype(jnp.float32),
                   ((0, nlp - n_loc), (0, dimp - dim)))
    dlt_p = jnp.pad(delta_rows, ((0, nlp - n_loc), (0, 0)))
    rm_p = jnp.pad(row_mask.astype(jnp.float32).reshape(n_loc, 1),
                   ((0, nlp - n_loc), (0, 0)))
    nr = jnp.asarray(n_real, jnp.float32).reshape(1, 1)
    out = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=(nlp // tn,),
        in_specs=[
            pl.BlockSpec((dimp, N), lambda i: (0, 0)),
            pl.BlockSpec((tn, dimp), lambda i: (i, 0)),
            pl.BlockSpec((tn, N), lambda i: (i, 0)),
            pl.BlockSpec((tn, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tn, dimp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nlp, dimp), jnp.float32),
        interpret=interpret,
    )(Xt, Xl_p, dlt_p, rm_p, nr)
    return out[:n_loc, :dim]
