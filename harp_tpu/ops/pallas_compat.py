"""Shared interpret-mode policy for the Pallas kernels.

Kernels run compiled (Mosaic) on TPU and in interpret mode everywhere
else — except when ``HARP_PALLAS_FORCE_MOSAIC=1``, which forces the
compiled path regardless of backend.  That override exists for ONE
purpose: cross-platform lowering pins (`.lower(lowering_platforms=
("tpu",))` on the CPU host) that verify the full epoch programs —
transposes, scans, scalar-prefetch grids AND the Mosaic kernels —
at true graded shapes without hardware (see CLAUDE.md "Environment
gotchas" and tests/test_lda_scale.py).  Executing with the override on
a non-TPU backend will fail; that is the point.
"""

from __future__ import annotations

import os

import jax


def interpret_default() -> bool:
    """True = run the kernel in interpret mode (non-TPU backends)."""
    if os.environ.get("HARP_PALLAS_FORCE_MOSAIC") == "1":
        return False
    return jax.default_backend() != "tpu"
