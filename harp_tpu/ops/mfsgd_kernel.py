"""Fused MF-SGD dense-tile update — Pallas TPU kernel.

Reference parity: the MF-SGD inner loop Harp-DAAL ran inside Intel DAAL's
C++ kernel (SURVEY.md §3.2, §4.3).  The in-tree XLA ``algo="dense"`` path
(`models/mfsgd.py:_tile_block_update`) already replaced TPU scatter with
one-hot MXU matmuls; this kernel fuses one whole entry update — one-hot
build, two gather dots, error/gradient math, two scatter dots, W/H tile
apply — into a single VMEM-resident Pallas program, so the ~4 MB of
one-hot operands and [C, rank] intermediates per entry never round-trip
HBM between XLA fusions.

Layout (follows the hard-won notes in ``ops/kmeans_kernel.py``): all
arrays live transposed, rank-major — W^T [R, u_bound], H^T [R, ib2] —
so every matmul contracts over lanes (or A-lanes with B-sublanes, the
other legal Mosaic pattern) and only ONE one-hot orientation per side is
ever built:

    ohu  [u_tile, C]  = (iota_rows == cu_row)           (VPU, in VMEM)
    wuT  [R, C]   = WbT [R, u_tile] @ ohu                (A-lane × B-sublane)
    gWT  [R, u_tile] = gwT [R, C] @ ohu  (contract lanes of BOTH)

Grid/memory plan (2-D sequential grid: entries × token chunks — chunking
rides the grid because Mosaic supports neither value-level dynamic_slice
nor mixed int+ds ref reads in-kernel):
- The resident H half-slice rides whole in VMEM (copied in at step 0,
  flushed once at the end); entry ``oi`` offsets index it with ``pl.ds``.
- W streams as [R, u_tile] blocks chosen by a scalar-prefetched block
  index (``ou // u_tile``).  Host prep guarantees each W block occupies
  ONE contiguous run of grid steps (entries are tile-sorted u-major and
  ``insert_coverage_entries`` inserts no-op entries for empty blocks), so
  accumulated updates stay in the live VMEM output buffer for the whole
  run and every output block is written at least once — correctness never
  depends on buffer aliasing or on cross-run revisit ordering.
- Entry-snapshot state (tile snapshots + gradient accumulators) lives in
  VMEM scratch, which persists across the sequential grid: every chunk
  scores against the entry-start factors and ONE apply lands per entry —
  update order IDENTICAL to the XLA dense path (same entries, same
  sequence), so results match it to accumulation-order rounding.

Expected headroom (analytic, 2026-07-31 — NOT yet a measurement; the
relay was down when this landed): the dense path's per-entry one-hot
operands and [C, rank] intermediates round-trip HBM between fusions,
~8 MB/entry at the ML-20M tiling vs ~0.5 MB of tile traffic here.  A TPU
measurement goes in BASELINE.md the moment the relay answers — until
then prefer algo="dense", whose numbers are real.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _kernel(ou_blk_ref, oi_ref, w_in, h_in, cu_ref, ci_ref, cv_ref,
            w_out, h_out, se_ref, cnt_ref, wsnap, hsnap, gw_acc, gh_acc,
            *, lr, reg, i_tile, compute_dtype):
    R, UR = w_in.shape
    IR = i_tile
    cc = cu_ref.shape[-1]
    e = pl.program_id(0)   # entry
    j = pl.program_id(1)   # chunk within entry
    nc = pl.num_programs(1)

    blk = ou_blk_ref[e]
    prev = ou_blk_ref[jnp.maximum(e - 1, 0)]

    @pl.when((e == 0) & (j == 0))
    def _init():
        h_out[...] = h_in[...]
        se_ref[...] = jnp.zeros_like(se_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)

    # First entry of this W block's contiguous run: seed the output buffer
    # from the pristine input block.  Later entries of the run read back
    # their predecessors' updates from the (still-resident) output buffer.
    @pl.when(((e == 0) | (blk != prev)) & (j == 0))
    def _start_run():
        w_out[...] = w_in[...]

    toi = pl.multiple_of(oi_ref[e], IR)

    # Entry start: snapshot the tiles (all chunks score against the
    # entry-start factors, matching the XLA dense path's whole-entry
    # snapshot) and zero the gradient accumulators.  Scratch persists
    # across the sequential grid, so the state survives the chunk steps.
    @pl.when(j == 0)
    def _start_entry():
        wsnap[...] = w_out[...]
        hsnap[...] = h_out[:, pl.ds(toi, IR)]
        gw_acc[...] = jnp.zeros_like(gw_acc)
        gh_acc[...] = jnp.zeros_like(gh_acc)

    cd = compute_dtype
    dot = functools.partial(lax.dot_general,
                            preferred_element_type=jnp.float32)
    Wb_c = wsnap[...].astype(cd)
    Hb_c = hsnap[...].astype(cd)
    cu = cu_ref[...].reshape(1, cc)                    # [1, cc] i32
    ci = ci_ref[...].reshape(1, cc)
    cv = cv_ref[...].reshape(1, cc)                    # [1, cc] f32

    ohu = (lax.broadcasted_iota(jnp.int32, (UR, cc), 0) == cu
           ).astype(cd)                                # [UR, cc]
    ohi = (lax.broadcasted_iota(jnp.int32, (IR, cc), 0) == ci
           ).astype(cd)                                # [IR, cc]
    wuT = dot(Wb_c, ohu, (((1,), (0,)), ((), ())))     # [R, cc] gather
    hiT = dot(Hb_c, ohi, (((1,), (0,)), ((), ())))
    cm = (cu < UR).astype(jnp.float32)                 # pad slots drop out
    err = cm * (cv - (wuT * hiT).sum(0, keepdims=True))
    gwT = (err * hiT - reg * cm * wuT).astype(cd)      # [R, cc]
    ghT = (err * wuT - reg * cm * hiT).astype(cd)
    gw_acc[...] += dot(gwT, ohu, (((1,), (1,)), ((), ())))  # [R, UR]
    gh_acc[...] += dot(ghT, ohi, (((1,), (1,)), ((), ())))
    se_ref[...] += (err * err).sum().reshape(1, 1)
    cnt_ref[...] += cm.sum().reshape(1, 1)

    # Entry end: one apply per entry, from the snapshot — identical update
    # order to the XLA dense path.
    @pl.when(j == nc - 1)
    def _end_entry():
        w_out[...] = wsnap[...] + lr * gw_acc[...]
        h_out[:, pl.ds(toi, IR)] = hsnap[...] + lr * gh_acc[...]


def sgd_tile_update(Wt, Ht, eu, ei, ev, ou, oi, *, lr, reg, u_tile, i_tile,
                    compute_dtype=jnp.bfloat16, chunk_c=512,
                    interpret: bool = False):
    """One rotation-step block update on transposed factors.

    ``Wt`` [R, u_bound] / ``Ht`` [R, ib2] f32; ``eu/ei`` [NE, C] tile-local
    ids (pad = tile width); ``ev`` [NE, C] values; ``ou/oi`` [NE] tile row
    offsets.  Entries MUST be u-major with full W-block coverage — run
    host arrays through :func:`insert_coverage_entries` first.
    Returns ``(Wt', Ht', se, cnt)`` matching
    ``mfsgd._tile_block_update``'s math entry-for-entry.
    """
    R, UB = Wt.shape
    _, IB = Ht.shape
    NE, C = eu.shape
    cc = min(C, chunk_c)
    if C % cc:
        raise ValueError(f"C={C} not a multiple of chunk_c={cc}; pad "
                         f"entries with insert_coverage_entries first")
    if not interpret:
        for name, v, m in (("u_tile", u_tile, _LANE),
                           ("i_tile", i_tile, _LANE), ("C chunk", cc, _LANE),
                           ("rank", R, 8)):
            if v % m:
                raise ValueError(
                    f"pallas mfsgd: {name}={v} must be a multiple of {m} "
                    f"on TPU (use algo='dense' for odd shapes)")
    # the kernel keeps TWO resident H copies in VMEM (h_in + h_out) plus
    # ~2 MB of W blocks/one-hots/entry streams — budget both copies
    if 2 * IB * R * 4 > 10 << 20:
        raise ValueError(
            f"pallas mfsgd: resident H half-slice is {IB * R * 4 / 2**20:.1f}"
            f" MB ×2 VMEM copies > 10 MB VMEM budget; shard over more "
            f"workers or use algo='dense'")

    # 2-D grid: entries × chunks.  Chunking rides the grid (not an
    # in-kernel loop — Mosaic supports neither value-level dynamic_slice
    # nor mixed int+ds ref reads); entry-snapshot state lives in scratch,
    # which persists across the sequential grid steps.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(NE, C // cc),
        in_specs=[
            pl.BlockSpec((R, u_tile), lambda e, j, ob, oo: (0, ob[e])),
            pl.BlockSpec((R, IB), lambda e, j, ob, oo: (0, 0)),
            # entry streams ride [NE, 1, C]: Mosaic requires block dim -2
            # to divide 8 or equal the array dim — (1, cc) over [NE, C]
            # is illegal, (1, 1, cc) over [NE, 1, C] is exact in dim -2
            pl.BlockSpec((1, 1, cc), lambda e, j, ob, oo: (e, 0, j)),
            pl.BlockSpec((1, 1, cc), lambda e, j, ob, oo: (e, 0, j)),
            pl.BlockSpec((1, 1, cc), lambda e, j, ob, oo: (e, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((R, u_tile), lambda e, j, ob, oo: (0, ob[e])),
            pl.BlockSpec((R, IB), lambda e, j, ob, oo: (0, 0)),
            pl.BlockSpec((1, 1), lambda e, j, ob, oo: (0, 0)),
            pl.BlockSpec((1, 1), lambda e, j, ob, oo: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, u_tile), jnp.float32),  # W snapshot
            pltpu.VMEM((R, i_tile), jnp.float32),  # H snapshot
            pltpu.VMEM((R, u_tile), jnp.float32),  # gW accumulator
            pltpu.VMEM((R, i_tile), jnp.float32),  # gH accumulator
        ],
    )
    ou_blk = (ou // u_tile).astype(jnp.int32)
    Wt2, Ht2, se, cnt = pl.pallas_call(
        functools.partial(_kernel, lr=lr, reg=reg, i_tile=i_tile,
                          compute_dtype=compute_dtype),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((R, UB), jnp.float32),
            jax.ShapeDtypeStruct((R, IB), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(ou_blk, oi.astype(jnp.int32),
      Wt, Ht, eu.reshape(NE, 1, C), ei.reshape(NE, 1, C),
      ev.reshape(NE, 1, C))
    return Wt2, Ht2, se[0, 0], cnt[0, 0]


def insert_coverage_entries(eu, ei, ev, ou, oi, u_bound, u_tile,
                            chunk_c=512):
    """Host prep: make entry lists kernel-safe (numpy, worker-major).

    Guarantees, per [WS, NE, C] row: (a) every W block ``0..u_bound/u_tile``
    appears at least once, (b) entries stay u-major so each block is one
    contiguous grid run, (c) trailing pads repeat the last entry's offsets
    (never jump back to block 0), (d) C is a multiple of ``chunk_c`` when
    it exceeds it.  Inserted entries are all-pad (ids = tile width) — the
    kernel's mask turns them into pure copy-through steps.
    """
    ws, ne, c = eu.shape
    # C must satisfy the kernel's TPU lane gate (multiples of 128) at ANY
    # size — small-corpus C values like 200 otherwise pass coverage
    # unpadded and fail at first Mosaic compile (caught by review,
    # 2026-07-31); above chunk_c it must also be a chunk multiple
    # (chunk_c is itself a 128-multiple, so both cases satisfy the gate)
    if c > chunk_c:
        c2 = chunk_c * -(-c // chunk_c)
    else:
        c2 = 128 * -(-c // 128)
    nblk = u_bound // u_tile
    # Per row: list of (src_entry_index | None, ou, oi); None = inserted pad.
    rows: list[list[tuple]] = []
    for w in range(ws):
        real = (eu[w] < u_tile).any(axis=-1)
        nreal = int(real.sum())
        assert real[:nreal].all(), "real entries must be a prefix"
        blks = ou[w, :nreal] // u_tile
        out: list[tuple] = []
        last_oi = 0
        for b in range(nblk):
            sel = np.nonzero(blks == b)[0]
            if sel.size:
                out.extend((int(s), int(ou[w, s]), int(oi[w, s]))
                           for s in sel)
                last_oi = int(oi[w, sel[-1]])
            else:
                out.append((None, b * u_tile, last_oi))
        rows.append(out)
    ne2 = max(len(r) for r in rows)
    # Pad slots need only eu = u_tile: the u-side mask (cm) and the all-zero
    # one-hot column zero out every W/H contribution whatever ei/ev hold.
    eu2 = np.full((ws, ne2, c2), u_tile, eu.dtype)
    ei2 = np.zeros((ws, ne2, c2), ei.dtype)
    ev2 = np.zeros((ws, ne2, c2), ev.dtype)
    ou2 = np.zeros((ws, ne2), np.int32)
    oi2 = np.zeros((ws, ne2), np.int32)
    for w, out in enumerate(rows):
        for j, (src, rou, roi) in enumerate(out):
            ou2[w, j], oi2[w, j] = rou, roi
            if src is not None:
                eu2[w, j, :c] = eu[w, src]
                ei2[w, j, :c] = ei[w, src]
                ev2[w, j, :c] = ev[w, src]
        # tail pads: repeat the last entry's offsets (never jump back to
        # block 0 — that would break run contiguity)
        if len(out) < ne2:
            ou2[w, len(out):] = out[-1][1]
            oi2[w, len(out):] = out[-1][2]
    return eu2, ei2, ev2, ou2, oi2
