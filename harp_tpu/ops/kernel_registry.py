"""Registry of every Pallas kernel in ``ops/`` — the Mosaic audit's input
AND the cost model's kernel work sheet.

Reference parity note (SURVEY.md §3.2): Harp's native compute kernels
lived behind DAAL's JNI boundary with no enumeration — auditing them
meant reading C++.  Here each kernel registers a **builder** returning
``(fn, args)`` at a small proven shape with ``interpret=False``, so
:mod:`harp_tpu.analysis.mosaic_audit` can (a) run the full Pallas→Mosaic
lowering via ``.trace(...).lower(lowering_platforms=("tpu",))`` on the
CPU backend and (b) walk the traced jaxpr for the silicon limits local
lowering does NOT enforce (≤2 ``prng_seed`` words, sublane-aligned block
dims, no uint32→f32 cast).  Shapes mirror the smallest cases already
pinned by the kernel test files, so an audit failure means the kernel
changed, not the harness.

PR 13 (perfmodel): registration now REQUIRES a declared work model —
``flops`` (arithmetic at the registered shape), ``min_hbm_bytes`` (the
roofline-style lower-bound HBM traffic: inputs read once, outputs
written once), and ``vmem_bytes`` (the kernel's own scoped-VMEM budget
estimate at the registered shape, the same byte algebra its dispatch
gate enforces — e.g. ``kmeans_kernel._tile_rows_int8``'s OOM-calibrated
model).  The Mosaic audit and :mod:`harp_tpu.perfmodel` read ONE source
of truth: a new kernel registered without its work model raises HERE,
at import/lint time, not twenty minutes into a predict run
(tests/test_perfmodel.py pins that every entry prices without a
fallback and fits the 16 MiB VMEM ceiling).

Builders are lazy (imports inside) — registering costs nothing until an
audit actually runs, and the registry module itself imports without jax.
"""

from __future__ import annotations

from typing import Any, Callable

# name -> zero-arg builder returning (fn, args_tuple)
KERNELS: dict[str, Callable[[], tuple[Callable, tuple[Any, ...]]]] = {}

#: name -> {"flops", "min_hbm_bytes", "vmem_bytes"} at the builder's
#: registered shape (ints; every field required and positive)
KERNEL_WORK: dict[str, dict] = {}

_WORK_FIELDS = ("flops", "min_hbm_bytes", "vmem_bytes")


def register_kernel(name: str, *, flops: int, min_hbm_bytes: int,
                    vmem_bytes: int):
    """Register a kernel builder WITH its declared work model.

    The keyword fields are mandatory by signature: a kernel that cannot
    state its FLOPs, HBM floor, and VMEM footprint at its own registered
    shape is not auditable or priceable, and the failure happens at
    import time (``python -m harp_tpu lint`` imports this module) —
    loudly, before any relay window is spent discovering it.
    """
    work = {"flops": flops, "min_hbm_bytes": min_hbm_bytes,
            "vmem_bytes": vmem_bytes}
    for k in _WORK_FIELDS:
        v = work[k]
        if isinstance(v, bool) or not isinstance(v, int) or v <= 0:
            raise ValueError(
                f"kernel {name!r}: work field {k}={v!r} must be a "
                "positive int — declare the kernel's work model at its "
                "registered shape (see module docstring)")

    def deco(build):
        KERNELS[name] = build
        KERNEL_WORK[name] = work
        return build
    return deco


# kmeans.partials at (n=128, d=256, k=8, kp=128): one Lloyd partial pass.
# flops = 4ndk (distance matmul 2ndk + one-hot sums matmul 2ndk);
# min bytes = points once (f32) + centroid operand + sums/counts out;
# vmem = point tile (tn=128, double-buffered) + padded centroid operand
# + [kp, d] sums + [tn, kp] score/one-hot temporaries, all f32.
@register_kernel("kmeans.partials",
                 flops=4 * 128 * 256 * 8,
                 min_hbm_bytes=4 * (128 * 256 + 128 * 256 + 8 * 256 + 8 + 1),
                 vmem_bytes=4 * (2 * 128 * 256 + 2 * 128 * 256
                                 + 2 * 128 * 128))
def _kmeans_f32():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.kmeans_kernel import kmeans_partials

    fn = functools.partial(kmeans_partials, interpret=False)
    return fn, (jnp.zeros((128, 256), jnp.float32),
                jnp.zeros((8, 256), jnp.float32))


# kmeans.partials_int8 at (n=128, d=256, k=8, kp=128): int8 OPs on the
# MXU (same 4ndk count), int8 points read once; vmem = the kernel's own
# OOM-calibrated byte model (kmeans_kernel._tile_rows_int8, measured
# 2026-08-01): tn·(2d + 8kp) + 5·kp·d + 64 KiB at tn=128.
@register_kernel("kmeans.partials_int8",
                 flops=4 * 128 * 256 * 8,
                 min_hbm_bytes=(128 * 256 + 128 * 256
                                + 4 * (8 * 256 + 8 + 1)),
                 vmem_bytes=128 * (2 * 256 + 8 * 128) + 5 * 128 * 256
                 + (64 << 10))
def _kmeans_int8():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.kmeans_kernel import kmeans_partials_int8

    fn = functools.partial(kmeans_partials_int8, interpret=False)
    return fn, (jnp.zeros((128, 256), jnp.int8),
                jnp.zeros((8, 256), jnp.int8),
                jnp.zeros(8, jnp.float32),
                jnp.zeros(8, jnp.float32),
                jnp.ones(256, jnp.float32))


# lda.cgs_entry_update at (K=64, DR=WR=128, C=256): per token ~14K flops
# (posterior + draw + delta matmuls) over C tokens; min bytes = both
# table tiles in/out + token streams; vmem = the kernel's own est(cc)
# budget model (lda_kernel.py) at cc=C=256 with exact-gather planes.
@register_kernel("lda.cgs_entry_update",
                 flops=14 * 64 * 256,
                 min_hbm_bytes=(2 * 4 * (64 * 128 + 64 * 128) + 4 * 64
                                + 3 * 4 * 256),
                 vmem_bytes=(4 + 4) * 64 * 128 + 8 * 64 * 128
                 + 6 * 4 * 64 * 256 + 6 * 64 * 128)
def _lda_cgs():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    # compiled path (interpret=False): exercises the REAL pltpu.prng_seed
    # / prng_random_bits lowering the silicon checks exist for
    fn = functools.partial(cgs_entry_update, alpha=0.5, beta=0.1,
                           vbeta=12.8, interpret=False)
    K, DR, WR, C = 64, 128, 128, 256
    return fn, (jnp.zeros((K, DR), jnp.float32),
                jnp.zeros((K, WR), jnp.float32),
                jnp.zeros(K, jnp.float32),
                jnp.zeros(C, jnp.int32),
                jnp.full((C,), DR, jnp.int32),
                jnp.full((C,), WR, jnp.int32),
                jnp.zeros(2, jnp.int32))


# mfsgd.sgd_tile_update at the 8-worker-sim smoke tiling (R=64,
# UB=2048, IB=13440, NE=8, C=2048, tile=256): 6·R flops per rating over
# NE·C rating slots; min bytes = W/H blocks in+out (f32) + entry
# streams; vmem = the kernel's own budget algebra: TWO resident H
# copies (h_in + h_out) + four [R, tile] scratch tiles + chunk streams.
@register_kernel("mfsgd.sgd_tile_update",
                 flops=6 * 64 * 8 * 2048,
                 min_hbm_bytes=(2 * 4 * (64 * 2048 + 64 * 13440)
                                + 3 * 4 * 8 * 2048),
                 vmem_bytes=2 * 13440 * 64 * 4 + 4 * 64 * 256 * 4
                 + 3 * 4 * 512)
def _mfsgd_tile():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.mfsgd_kernel import sgd_tile_update

    # the 8-worker-sim smoke tiling pinned in tests/test_mfsgd_kernel.py
    R, UB, IB, NE, C, tile = 64, 2048, 13440, 8, 2048, 256
    fn = functools.partial(sgd_tile_update, lr=0.01, reg=0.05,
                           u_tile=tile, i_tile=tile, interpret=False)
    return fn, (jnp.zeros((R, UB), jnp.float32),
                jnp.zeros((R, IB), jnp.float32),
                jnp.zeros((NE, C), jnp.int32),
                jnp.zeros((NE, C), jnp.int32),
                jnp.zeros((NE, C), jnp.float32),
                jnp.zeros(NE, jnp.int32),
                jnp.zeros(NE, jnp.int32))


# flash_attention at (batch=2, T=256, d=128), causal: 4·T²·d flops per
# batch row (QK^T + PV, halved by causality, ×2 ops per MAC cancels);
# min bytes = Q/K/V read + O written (f32); vmem = Q block + K/V blocks
# + online-softmax scratch (m, l, acc) at the kernel's default blocks.
@register_kernel("flash_attention",
                 flops=2 * 4 * 256 * 256 * 128 // 2,
                 min_hbm_bytes=4 * 4 * 2 * 256 * 128,
                 vmem_bytes=4 * (3 * 256 * 128 + 256 * 128 + 2 * 256))
def _flash():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.flash_attention import flash_attention

    fn = functools.partial(flash_attention, causal=True, interpret=False)
    q = jnp.zeros((2, 256, 128), jnp.float32)
    return fn, (q, q, q)


# svm.kernel_row at (dp=128, n_pad=512, tn=128): fused Pegasos hinge
# gradient — two MXU dots (score + gradient contraction) = 4·dp·n
# flops; min bytes = x^T read once (the fusion's whole point: ONE pass,
# not SVM_X_PASSES_PER_STEP=2) + w/b/y/sw streams + gw/gs out; vmem =
# the kernel's own byte model (svm_kernel.vmem_bytes) at tn=128.
@register_kernel("svm.kernel_row",
                 flops=4 * 128 * 512,
                 min_hbm_bytes=4 * (128 * 512 + 2 * 512 + 2 * 128 + 2),
                 vmem_bytes=2 * 128 * 128 * 4 + 6 * 128 * 4
                 + 2 * 128 * 4 + (64 << 10))
def _svm_kernel_row():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.svm_kernel import pegasos_grad

    # the small proven shape pinned in tests/test_svm_kernel.py
    fn = functools.partial(pegasos_grad, tn=128, interpret=False)
    return fn, (jnp.zeros((128,), jnp.float32),
                jnp.float32(0.0),
                jnp.zeros((128, 512), jnp.float32),
                jnp.zeros((512,), jnp.float32),
                jnp.zeros((512,), jnp.float32))


# wdamds.smacof_dist at (N=256, n_loc=32, tn=32, dim=2): fused distance
# + Guttman B·X row block — two MXU matmuls (cross + ratio·X) =
# 4·n_loc·N·dimp flops at the padded dimp=128; min bytes = δ rows + the
# real (unpadded) X/Xl/out coordinates (D and ratio never touch HBM —
# the fusion's point); vmem = the kernel's own byte model
# (wdamds_kernel.vmem_bytes) at tn=32.
@register_kernel("wdamds.smacof_dist",
                 flops=4 * 32 * 256 * 128,
                 min_hbm_bytes=4 * (32 * 256 + 256 * 2 + 2 * 32 * 2),
                 vmem_bytes=128 * 256 * 4 + 2 * 32 * 256 * 4
                 + 3 * 32 * 256 * 4 + 4 * 32 * 128 * 4 + (64 << 10))
def _wdamds_smacof_dist():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.wdamds_kernel import smacof_bx

    # the small proven shape pinned in tests/test_wdamds_kernel.py
    fn = functools.partial(smacof_bx, eps=1e-9, tn=32, interpret=False)
    return fn, (jnp.zeros((32, 256), jnp.float32),
                jnp.zeros((32,), jnp.float32),
                jnp.zeros((32, 2), jnp.float32),
                jnp.zeros((256, 2), jnp.float32),
                jnp.float32(256.0))


# rf.hist_bins at (n=512, fB=512, tn=128, nodeC=8): on-chip one-hot
# histogram — one int8 MXU dot per tile = 2·n·nodeCp·fB OPs (the
# transposed one-hot build is VPU); min bytes = int8 BO read once +
# row-code/weight streams + int32 histogram out (the [nodeCp, tn]
# one-hot never touches HBM — the fusion's point); vmem = the kernel's
# own byte model (rf_kernel.vmem_bytes) at tn=128.
@register_kernel("rf.hist_bins",
                 flops=2 * 512 * 8 * 512,
                 min_hbm_bytes=512 * 512 + 2 * 4 * 512 + 4 * 8 * 512,
                 vmem_bytes=2 * 128 * 512 + 4 * 128 * 4 + 8 * 128
                 + 8 * 128 * 4 + 8 * 512 * 4 + (64 << 10))
def _rf_hist_bins():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.rf_kernel import hist_bins

    # the small proven shape pinned in tests/test_rf_kernel.py
    fn = functools.partial(hist_bins, n_node_classes=8, tn=128,
                           interpret=False)
    return fn, (jnp.zeros((512, 512), jnp.int8),
                jnp.zeros((512,), jnp.int32),
                jnp.zeros((512,), jnp.int32))
