"""Registry of every Pallas kernel in ``ops/`` — the Mosaic audit's input.

Reference parity note (SURVEY.md §3.2): Harp's native compute kernels
lived behind DAAL's JNI boundary with no enumeration — auditing them
meant reading C++.  Here each kernel registers a **builder** returning
``(fn, args)`` at a small proven shape with ``interpret=False``, so
:mod:`harp_tpu.analysis.mosaic_audit` can (a) run the full Pallas→Mosaic
lowering via ``.trace(...).lower(lowering_platforms=("tpu",))`` on the
CPU backend and (b) walk the traced jaxpr for the silicon limits local
lowering does NOT enforce (≤2 ``prng_seed`` words, sublane-aligned block
dims, no uint32→f32 cast).  Shapes mirror the smallest cases already
pinned by the kernel test files, so an audit failure means the kernel
changed, not the harness.

Builders are lazy (imports inside) — registering costs nothing until an
audit actually runs, and the registry module itself imports without jax.
"""

from __future__ import annotations

from typing import Any, Callable

# name -> zero-arg builder returning (fn, args_tuple)
KERNELS: dict[str, Callable[[], tuple[Callable, tuple[Any, ...]]]] = {}


def register_kernel(name: str):
    def deco(build):
        KERNELS[name] = build
        return build
    return deco


@register_kernel("kmeans.partials")
def _kmeans_f32():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.kmeans_kernel import kmeans_partials

    fn = functools.partial(kmeans_partials, interpret=False)
    return fn, (jnp.zeros((128, 256), jnp.float32),
                jnp.zeros((8, 256), jnp.float32))


@register_kernel("kmeans.partials_int8")
def _kmeans_int8():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.kmeans_kernel import kmeans_partials_int8

    fn = functools.partial(kmeans_partials_int8, interpret=False)
    return fn, (jnp.zeros((128, 256), jnp.int8),
                jnp.zeros((8, 256), jnp.int8),
                jnp.zeros(8, jnp.float32),
                jnp.zeros(8, jnp.float32),
                jnp.ones(256, jnp.float32))


@register_kernel("lda.cgs_entry_update")
def _lda_cgs():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    # compiled path (interpret=False): exercises the REAL pltpu.prng_seed
    # / prng_random_bits lowering the silicon checks exist for
    fn = functools.partial(cgs_entry_update, alpha=0.5, beta=0.1,
                           vbeta=12.8, interpret=False)
    K, DR, WR, C = 64, 128, 128, 256
    return fn, (jnp.zeros((K, DR), jnp.float32),
                jnp.zeros((K, WR), jnp.float32),
                jnp.zeros(K, jnp.float32),
                jnp.zeros(C, jnp.int32),
                jnp.full((C,), DR, jnp.int32),
                jnp.full((C,), WR, jnp.int32),
                jnp.zeros(2, jnp.int32))


@register_kernel("mfsgd.sgd_tile_update")
def _mfsgd_tile():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.mfsgd_kernel import sgd_tile_update

    # the 8-worker-sim smoke tiling pinned in tests/test_mfsgd_kernel.py
    R, UB, IB, NE, C, tile = 64, 2048, 13440, 8, 2048, 256
    fn = functools.partial(sgd_tile_update, lr=0.01, reg=0.05,
                           u_tile=tile, i_tile=tile, interpret=False)
    return fn, (jnp.zeros((R, UB), jnp.float32),
                jnp.zeros((R, IB), jnp.float32),
                jnp.zeros((NE, C), jnp.int32),
                jnp.zeros((NE, C), jnp.int32),
                jnp.zeros((NE, C), jnp.float32),
                jnp.zeros(NE, jnp.int32),
                jnp.zeros(NE, jnp.int32))


@register_kernel("flash_attention")
def _flash():
    import functools

    import jax.numpy as jnp

    from harp_tpu.ops.flash_attention import flash_attention

    fn = functools.partial(flash_attention, causal=True, interpret=False)
    q = jnp.zeros((2, 256, 128), jnp.float32)
    return fn, (q, q, q)
