"""Fused Pegasos hinge-gradient — Pallas TPU kernel for the SVM inner loop.

Reference parity: Harp's ``edu.iu.svm`` local solve (SURVEY.md §3.4),
in-tree as the XLA path (`models/svm.py:_pegasos`).  The PR-16 wall
attribution priced svm on exactly two big dots per Pegasos step —
f(x) = x·w and g = (viol·y)ᵀx — which the XLA schedule runs as TWO
separate passes over the [n, d] feature block (the perfmodel's
``SVM_X_PASSES_PER_STEP = 2``).  This kernel fuses both dots into ONE
pass: each [dp, tn] feature tile is read once, scored against the
resident (w, b), and immediately contracted back into the gradient
accumulator, so the margin/violator intermediates never touch HBM.

Layout (the hard-won `ops/kmeans_kernel.py` rules): features ride
TRANSPOSED as x^T [dp, n_pad] so both matmuls contract over the legal
Mosaic patterns —

    fx [1, tn]  = w [1, dp] @ xT [dp, tn]        (A-lanes × B-sublanes)
    gw [1, dp] += coef [1, tn] · xT [dp, tn]     (lanes of BOTH)

Grid/memory plan (1-D sequential grid over sample tiles): w/b ride
whole in VMEM with constant index maps; xT/y/sw stream tn-wide; the
gw/gs outputs zero-init at step 0 and accumulate across the sequential
grid (`ops/mfsgd_kernel.py` precedent).  The bf16 arm composes with
``SVMConfig.x_dtype``: a bf16-staged x streams half the HBM bytes and
both dots run bf16×bf16→f32 (accumulation stays f32 via
``preferred_element_type``).

Expected headroom (analytic, 2026-08-06 — NOT yet a measurement; the
tile comes from ``perfmodel.presize("svm.kernel_row", ...)`` and the
kernel is Mosaic-proven via HL201 only): one feature pass per step
instead of two at the graded 500k×128 shape.  A TPU measurement goes
in BASELINE.md when a relay window runs flip candidate
``svm_kernel_pallas`` — until then prefer ``algo="xla"``, whose
numbers are real.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128
# xT tile + vector streams + residents must fit beside Mosaic's own
# buffers; 14 MB leaves ~2 MB slack under the 16 MB/core ceiling the
# registry test pins (same headroom rule as ops/wdamds_kernel.py).
VMEM_BUDGET = 14 << 20
TILE_CANDIDATES = (8192, 4096, 2048, 1024, 512, 256, 128)


def vmem_bytes(dp: int, tn: int, xsize: int) -> int:
    """Analytic VMEM byte model (also what ``perfmodel.presize``
    consults): double-buffered xT tile + streamed y/sw tiles and the
    fx/margin/coef intermediates + resident w/gw rows + fixed slack."""
    return 2 * dp * tn * xsize + 6 * tn * 4 + 2 * dp * 4 + (64 << 10)


def fit_tiles(d: int, xsize: int, budget: int = VMEM_BUDGET) -> list[int]:
    """Sample-tile candidates whose working set fits the VMEM budget."""
    dp = _LANE * -(-d // _LANE)
    return [t for t in TILE_CANDIDATES if vmem_bytes(dp, t, xsize) <= budget]


def pick_tile(n: int, d: int, xsize: int) -> int:
    """Largest fitting tile no wider than the (padded) sample count —
    the same "largest fits" rule ``perfmodel.presize`` reproduces from
    the price model (per-grid-program overhead is monotone in 1/tn)."""
    fits = fit_tiles(d, xsize)
    if not fits:
        dp = _LANE * -(-d // _LANE)
        raise ValueError(
            f"pallas svm: no sample tile fits dp={dp} (xsize={xsize}) under "
            f"the {VMEM_BUDGET >> 20} MB VMEM budget; use algo='xla'")
    cap = _LANE * -(-max(n, 1) // _LANE)
    small = [t for t in fits if t <= cap]
    return max(small) if small else min(fits)


def _kernel(w_ref, b_ref, xT_ref, y_ref, sw_ref, gw_ref, gs_ref, *,
            compute_dtype):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        gw_ref[...] = jnp.zeros_like(gw_ref)
        gs_ref[...] = jnp.zeros_like(gs_ref)

    cd = compute_dtype
    dot = functools.partial(lax.dot_general,
                            preferred_element_type=jnp.float32)
    xT = xT_ref[...].astype(cd)                         # [dp, tn]
    fx = dot(w_ref[...].astype(cd), xT,
             (((1,), (0,)), ((), ())))                  # [1, tn] f32
    margin = y_ref[...] * (fx + b_ref[...])
    # pad samples carry sw = 0, so they drop out of both sums here
    coef = jnp.where(margin < 1.0, sw_ref[...], 0.0) * y_ref[...]
    gw_ref[...] += dot(coef.astype(cd), xT,
                       (((1,), (1,)), ((), ())))        # [1, dp]
    gs_ref[...] += coef.sum().reshape(1, 1)


def pegasos_grad(w, b, xT, y, sw, *, tn: int,
                 compute_dtype=jnp.float32, interpret: bool = False):
    """One fused hinge-gradient pass over all samples.

    ``w`` [dp] f32, ``b`` scalar, ``xT`` [dp, n_pad] f32/bf16
    (transposed features; pad samples MUST carry ``sw = 0``),
    ``y``/``sw`` [n_pad] f32.  Returns ``(gw [dp], gs scalar)`` with
    gw = Σ coef·x and gs = Σ coef for coef = 1[y·(x·w+b) < 1]·sw·y —
    exactly the per-step sums of `models/svm.py:_pegasos` (whose update
    is w' = w − lr·(l2·w − gw/Σsw), b' = b + lr·gs/Σsw).
    """
    dp, n_pad = xT.shape
    if not interpret:
        for name, v, m in (("feature pad dp", dp, _LANE),
                           ("sample tile tn", tn, _LANE)):
            if v % m:
                raise ValueError(
                    f"pallas svm: {name}={v} must be a multiple of {m} on "
                    f"TPU (use algo='xla' for odd shapes)")
    if n_pad % tn:
        raise ValueError(
            f"pallas svm: n_pad={n_pad} not a multiple of tn={tn}; pad "
            f"samples (with sw=0) to a tile multiple first")
    xsize = jnp.dtype(xT.dtype).itemsize
    if vmem_bytes(dp, tn, xsize) > VMEM_BUDGET:
        raise ValueError(
            f"pallas svm: tile ({dp}, {tn}) needs "
            f"{vmem_bytes(dp, tn, xsize) / 2**20:.1f} MB > "
            f"{VMEM_BUDGET >> 20} MB VMEM budget; shrink tn "
            f"(perfmodel.presize picks a fitting tile)")
    gw, gs = pl.pallas_call(
        functools.partial(_kernel, compute_dtype=compute_dtype),
        grid=(n_pad // tn,),
        in_specs=[
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((dp, tn), lambda i: (0, i)),
            pl.BlockSpec((1, tn), lambda i: (0, i)),
            pl.BlockSpec((1, tn), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, dp), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(w.reshape(1, dp).astype(jnp.float32),
      jnp.asarray(b, jnp.float32).reshape(1, 1),
      xT,
      y.reshape(1, n_pad).astype(jnp.float32),
      sw.reshape(1, n_pad).astype(jnp.float32))
    return gw.reshape(dp), gs[0, 0]
