"""Device kernels: long-context attention, expert dispatch, Pallas kernels.

Harp's rotate collective is structurally the ring-attention primitive
(SURVEY.md §3.5, §6 "long-context"): a ppermute ring with compute/transfer
overlap.  :mod:`harp_tpu.ops.ring_attention` makes that concrete — exact
blockwise attention over a sequence-sharded mesh — and
:mod:`harp_tpu.ops.a2a_attention` is the Ulysses all-to-all alternative
(regroup to head-sharded, full-sequence local attention, regroup back).
:mod:`harp_tpu.ops.moe` rides the same regroup verb for expert-parallel
MoE dispatch.  :mod:`harp_tpu.ops.flash_attention` is the single-chip
Pallas kernel (VMEM-blocked online softmax) the local steps can use;
:mod:`harp_tpu.ops.kmeans_kernel` is the fused single-pass KMeans kernel.
"""

from harp_tpu.ops.a2a_attention import a2a_attention, make_a2a_attention_fn
from harp_tpu.ops.moe import moe_ffn
from harp_tpu.ops.ring_attention import make_ring_attention_fn, ring_attention
from harp_tpu.ops.rope import apply_rope, make_rope_fn

__all__ = ["ring_attention", "make_ring_attention_fn", "a2a_attention",
           "make_a2a_attention_fn", "moe_ffn", "apply_rope", "make_rope_fn"]
