"""Device kernels: ring attention (long-context) and Pallas TPU kernels.

Harp's rotate collective is structurally the ring-attention primitive
(SURVEY.md §3.5, §6 "long-context"): a ppermute ring with compute/transfer
overlap.  :mod:`harp_tpu.ops.ring_attention` makes that concrete — exact
blockwise attention over a sequence-sharded mesh — so long-context models
scale across chips with the same machinery the classic apps use.
:mod:`harp_tpu.ops.flash_attention` is the single-chip Pallas kernel
(VMEM-blocked online softmax) the ring's local step can use.
"""

from harp_tpu.ops.ring_attention import ring_attention

__all__ = ["ring_attention"]
