"""Dense one-hot MXU label histogram — Pallas TPU kernel for RF growth.

Reference parity: Harp's ``edu.iu.rf`` level-wise histogram growth
(SURVEY.md §3.4), in-tree as the XLA ``hist_algo="dense"`` path
(`models/rf.py:_grow_level`).  The dense arm already replaced the
25 GB/s TPU scatter with a one-hot int8 MXU matmul (CLAUDE.md trap
list), but XLA materialises the [n, node·C] one-hot lhs in HBM every
level before the contraction reads it back — and its contraction
``(((0,), (0,)), ((), ()))`` (sublanes of BOTH) is exactly the pattern
Mosaic has no legal lowering for, so it cannot be ported as-is.  This
kernel builds the one-hot TRANSPOSED per tile in VMEM and accumulates
bins on-chip: the [node·C, tn] one-hot never exists in HBM and the
contraction becomes the legal lanes × sublanes pattern —

    nc   [nodeCp, tn]  = (iota_rows == node·C + y) · w   (VPU, int8)
    hist [nodeCp, fB] += nc · BO [tn, fB]     (A-lanes × B-sublanes, MXU)

Grid/memory plan (1-D sequential grid over sample tiles): the int8 BO
bin one-hots and the fused row codes / weights stream tn samples at a
time; the [nodeCp, fB] int32 histogram output zero-inits at step 0 and
accumulates across the sequential grid (`ops/mfsgd_kernel.py`
precedent).  Integer products ≤ 127 summed in int32 — counts are
BIT-IDENTICAL to the dense XLA arm (asserted in tests/test_rf_kernel.py),
so the ``hist_algo="pallas"`` knob changes no model output, only the
memory schedule.  Padded samples carry the row-code sentinel nodeCp
(outside the iota range) AND weight 0, so they never count.

Expected headroom (analytic, 2026-08-06 — NOT yet a measurement; the
tile comes from ``perfmodel.presize("rf.hist_bins", ...)`` and the
kernel is Mosaic-proven via HL201 only): removes the per-level
[n, node·C] one-hot HBM round-trip (the operand traffic the mfsgd
kernel removed for the same pattern).  A TPU measurement goes in
BASELINE.md when a relay window runs flip candidate ``rf_hist_pallas``
— until then prefer ``hist_algo="dense"``, whose numbers are real.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANE = 128
# streamed BO tiles + the transposed one-hot + the resident histogram
# must fit beside Mosaic's own buffers; 14 MB leaves ~2 MB slack under
# the 16 MB/core ceiling the registry test pins.
VMEM_BUDGET = 14 << 20
TILE_CANDIDATES = (4096, 2048, 1024, 512, 256, 128)


def vmem_bytes(tn: int, fB: int, nodeCp: int) -> int:
    """Analytic VMEM byte model (also what ``perfmodel.presize``
    consults): double-buffered int8 BO tile + row-code/weight streams +
    the iota/one-hot registers + resident int32 histogram + slack."""
    return (2 * tn * fB             # double-buffered int8 BO tile
            + 4 * tn * 4            # row-code + weight tiles (i32, ×2)
            + nodeCp * tn           # transposed int8 one-hot
            + nodeCp * tn * 4      # its int32 iota/compare register
            + nodeCp * fB * 4      # resident histogram accumulator
            + (64 << 10))


def fit_tiles(fB: int, nodeCp: int, budget: int = VMEM_BUDGET) -> list[int]:
    """Sample-tile candidates whose working set fits the VMEM budget."""
    return [t for t in TILE_CANDIDATES if vmem_bytes(t, fB, nodeCp) <= budget]


def pick_tile(n: int, fB: int, nodeCp: int) -> int:
    """Largest fitting tile no wider than the (padded) sample count —
    the rule ``perfmodel.presize`` reproduces from the price model
    (per-grid-program overhead is monotone in 1/tn)."""
    fits = fit_tiles(fB, nodeCp)
    if not fits:
        raise ValueError(
            f"pallas rf: no sample tile fits fB={fB}, nodeCp={nodeCp} "
            f"under the {VMEM_BUDGET >> 20} MB VMEM budget; use "
            f"hist_algo='dense'")
    cap = _LANE * -(-max(n, 1) // _LANE)
    small = [t for t in fits if t <= cap]
    return max(small) if small else min(fits)


def _kernel(bo_ref, rc_ref, w_ref, hist_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        hist_ref[...] = jnp.zeros_like(hist_ref)

    nodeCp = hist_ref.shape[0]
    rc = rc_ref[...]                                    # [1, tn] i32
    wt = w_ref[...]                                     # [1, tn] i32
    tn = rc.shape[-1]
    # transposed weighted one-hot, built in VMEM: pad samples carry the
    # sentinel rc = nodeCp (never matches iota ∈ [0, nodeCp)) and w = 0
    nc = ((lax.broadcasted_iota(jnp.int32, (nodeCp, tn), 0) == rc)
          .astype(jnp.int32) * wt).astype(jnp.int8)     # [nodeCp, tn]
    hist_ref[...] += lax.dot_general(
        nc, bo_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)               # [nodeCp, fB]


def hist_bins(BO, rowcode, weights, n_node_classes: int, *,
              tn: int | None = None, interpret: bool = False):
    """Weighted label histogram over bin one-hots: returns
    ``hist [n_node_classes, fB] int32`` with
    hist[r, c] = Σ_i 1[rowcode_i == r] · w_i · BO[i, c] — bit-identical
    to `models/rf.py:_grow_level`'s dense int8 matmul arm.

    ``BO`` [n, fB] int8 bin one-hots, ``rowcode`` [n] int32
    (node·C + y), ``weights`` [n] int32 already clipped to [0, 127].
    """
    n, fB = BO.shape
    nodeCp = 8 * -(-n_node_classes // 8)
    if tn is None:
        tn = pick_tile(n, fB, nodeCp)
    if not interpret:
        for name, v, m in (("feature·bin width fB", fB, _LANE),
                           ("sample tile tn", tn, _LANE)):
            if v % m:
                raise ValueError(
                    f"pallas rf: {name}={v} must be a multiple of {m} on "
                    f"TPU (use hist_algo='dense' for odd shapes)")
    if vmem_bytes(tn, fB, nodeCp) > VMEM_BUDGET:
        raise ValueError(
            f"pallas rf: tile ({tn}, {fB}) at nodeCp={nodeCp} needs "
            f"{vmem_bytes(tn, fB, nodeCp) / 2**20:.1f} MB > "
            f"{VMEM_BUDGET >> 20} MB VMEM budget; shrink tn "
            f"(perfmodel.presize picks a fitting tile)")
    n_pad = tn * -(-n // tn)
    BO_p = jnp.pad(BO, ((0, n_pad - n), (0, 0)))
    rc_p = jnp.pad(rowcode.astype(jnp.int32), (0, n_pad - n),
                   constant_values=nodeCp).reshape(1, n_pad)
    w_p = jnp.pad(weights.astype(jnp.int32), (0, n_pad - n)).reshape(1, n_pad)
    hist = pl.pallas_call(
        _kernel,
        grid=(n_pad // tn,),
        in_specs=[
            pl.BlockSpec((tn, fB), lambda i: (i, 0)),
            pl.BlockSpec((1, tn), lambda i: (0, i)),
            pl.BlockSpec((1, tn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((nodeCp, fB), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((nodeCp, fB), jnp.int32),
        interpret=interpret,
    )(BO_p, rc_p, w_p)
    return hist[:n_node_classes]
