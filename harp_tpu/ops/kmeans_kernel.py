"""Fused KMeans assignment+accumulation — Pallas TPU kernel.

One Lloyd iteration as a single pass: each tile of points streams HBM→VMEM
once, and the scores, assignment one-hot, and [k, d]/[k] accumulators all
stay on-chip.

**Measured outcome (1M×300 k=100, 1× v5e, 2026-07-29): the XLA path wins.**
XLA fuses the `dots → argmin → one_hot → matmul` chain into its own blocked
single-pass program: 2.45 ms/iter (bf16 points) / 2.67 ms (f32) vs this
kernel's best 2.83 ms (bf16, tile=2000).  Both sit near the chip's measured
effective HBM read bandwidth (~250–310 GB/s on this relay-attached v5e), so
the iteration is bandwidth-floor-bound and hand-fusion has no headroom left
— the kernel is kept as an opt-in (`KMeansConfig(use_pallas=True)`) and as
the in-tree template for single-pass streaming-accumulation kernels.

Reference parity: this corresponds to the distance/assignment inner loop
that Harp-DAAL executed in Intel DAAL's C++ KMeans kernel (SURVEY.md §3.2).

Layout notes (hard-won, keep in mind for future kernels):
- Never contract a matmul over a *sublane* dimension: Mosaic lowers the
  point-major one-hot reduction (contracting dim 0 of [tn, k]ᵀ×[tn, d]) via
  a scoped-VMEM relayout that scales with tile rows (62 MB at tn=1000 — an
  instant VMEM OOM).  Everything here is therefore centroid-major
  ([k, tile] scores), where both matmuls contract over lanes.
- Full-tile reductions to scalars (e.g. a per-tile ||x||² sum) cost more
  than the matmuls at these shapes; inertia is instead reassembled from the
  accumulated sums/counts where possible.
- Centroids are padded to a full 128-row MXU tile; padded rows are excluded
  from the argmin by +inf scores.  Ties pick the lowest centroid index,
  matching numpy argmin semantics.
- The grid is sequential on a TensorCore, so the output refs double as
  accumulators across tiles (init at program 0).

Numerics: distances are scored in bf16 (MXU-native), so (a) boundary points
between overlapping clusters may assign differently than an f32 reference,
and (b) the returned inertia — built from the ``||x||² − 2x·c + ||c||²``
decomposition — carries an absolute error of order ``4e-3 · Σ||x||²`` from
cancellation when cluster spread ≫ within-cluster distance.  Sums/counts are
f32-accumulated and exact for unambiguous assignments.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _kernel(pts_ref, c_ref, sums_ref, counts_ref, inertia_ref, *, k: int):
    kp = c_ref.shape[0]
    # bf16 operands, f32 accumulation: the MXU's native mode (~4× the f32
    # matmul rate).  XLA's default matmul precision makes the same trade for
    # f32 inputs; Pallas dots run at the literal input dtype, so the cast
    # must be explicit here.  Exactness of the one-hot is unaffected (0/1).
    pts = pts_ref[:].astype(jnp.bfloat16)              # [tn, d]
    c = c_ref[:].astype(jnp.bfloat16)                  # [kp, d]

    dots = jax.lax.dot_general(
        c, pts, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [kp, tn]
    c2 = (c.astype(jnp.float32) ** 2).sum(axis=1, keepdims=True)  # [kp, 1]
    row = jax.lax.broadcasted_iota(jnp.int32, dots.shape, 0)
    scores = jnp.where(row >= k, jnp.inf, c2 - 2.0 * dots)

    best = scores.min(axis=0, keepdims=True)           # [1, tn]
    # lowest index among ties (argmin semantics) without a 1-D argmin; the
    # min runs in f32 (exact for indices ≤ kp < 2^24) because Mosaic lacks
    # integer reduce_min on older toolchains
    assign = jnp.where(scores == best, row, kp).astype(jnp.float32) \
        .min(axis=0, keepdims=True).astype(jnp.int32)
    onehot = (row == assign).astype(pts.dtype)         # [kp, tn]

    tile_sums = jax.lax.dot_general(
        onehot, pts, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [kp, d]
    tile_counts = onehot.astype(jnp.float32).sum(axis=1, keepdims=True)
    x2 = (pts_ref[:].astype(jnp.float32) ** 2).sum()  # full-precision ||x||²
    tile_inertia = (x2 + best.sum()).reshape(1, 1)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        inertia_ref[:] = jnp.zeros_like(inertia_ref)

    sums_ref[:] += tile_sums
    counts_ref[:] += tile_counts
    inertia_ref[:] += tile_inertia


def _tile_rows(n: int) -> int | None:
    """Largest point-tile size (multiple of 8 sublanes) dividing n."""
    for tn in (2048, 2000, 1024, 1000, 512, 500, 256, 250, 200, 128, 120,
               64, 40, 16, 8):
        if n % tn == 0 and tn % 8 == 0:
            return tn
    return None


def supported(n: int) -> bool:
    """Whether the fused kernel can handle a local shard of n points."""
    return _tile_rows(n) is not None


def kmeans_partials(points, centroids, *, interpret: bool = False):
    """Fused per-shard partials: (sums [k, d] f32, counts [k] f32, inertia).

    Drop-in for the XLA `_partials_block` path: identical math (||x||² kept
    out of the argmin, re-added to inertia), single HBM pass over ``points``.
    """
    n, d = points.shape
    k = centroids.shape[0]
    tn = _tile_rows(n)
    if tn is None:
        raise ValueError(f"no supported tile size divides n={n}")
    kp = -(-k // _LANE) * _LANE
    cpad = jnp.pad(centroids, ((0, kp - k), (0, 0)))

    sums, counts, inertia = pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(n // tn,),
        in_specs=[
            pl.BlockSpec((tn, d), lambda i: (i, 0)),
            pl.BlockSpec((kp, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((kp, d), lambda i: (0, 0)),
            pl.BlockSpec((kp, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, d), jnp.float32),
            jax.ShapeDtypeStruct((kp, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(points, cpad)
    return sums[:k], counts[:k, 0], inertia[0, 0]


def _kernel_int8(pts_ref, cq_ref, cscale_ref, c2_ref, sums_ref, counts_ref,
                 best_ref, *, k: int):
    """int8-points twin of :func:`_kernel` (round 3).

    Same centroid-major single-pass layout; the point stream is int8 in
    HBM (¼ the f32 bytes — the measured wall of the XLA int8 path is the
    [n, k] intermediates it materializes, ~2 GB/iter at 1M×300 k=100,
    which this kernel never writes).  Operands are cast int8→bf16 in
    VMEM: |q| ≤ 127 is EXACT in bf16, products ≤ 127² and row sums
    ≤ 127²·d < 2²⁴ are exact in the f32 MXU accumulator, so the dots and
    one-hot sums equal the XLA path's int32 matmuls bit-for-bit; sums
    accumulate across tiles as int32 (per-tile values ≤ 127·tn < 2²⁴
    round-trip f32→int32 exactly).

    Score/assignment math matches ``kmeans._partials_block_int8``:
    ``scores = ||c||² − 2·(q·c_q)·c_scale`` with the same per-row
    centroid requantization — assignments are identical by construction.
    ``Σ‖x‖²`` is NOT computed here: it is iteration-invariant, so the
    caller hoists it out of the Lloyd loop (the XLA path re-reads the
    whole point stream for it every iteration).
    """
    kp = cq_ref.shape[0]
    qb = pts_ref[:].astype(jnp.bfloat16)               # [tn, d], exact
    cb = cq_ref[:].astype(jnp.bfloat16)                # [kp, d], exact
    dots_q = jax.lax.dot_general(
        cb, qb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [kp, tn], exact ints
    dots = dots_q * cscale_ref[:]                      # [kp, 1] broadcast
    row = jax.lax.broadcasted_iota(jnp.int32, dots.shape, 0)
    scores = jnp.where(row >= k, jnp.inf, c2_ref[:] - 2.0 * dots)

    best = scores.min(axis=0, keepdims=True)           # [1, tn]
    # f32 tie-break min: see _kmeans_kernel (no integer reduce_min in Mosaic)
    assign = jnp.where(scores == best, row, kp).astype(jnp.float32) \
        .min(axis=0, keepdims=True).astype(jnp.int32)
    onehot = (row == assign).astype(jnp.bfloat16)      # [kp, tn] 0/1

    tile_sums = jax.lax.dot_general(
        onehot, qb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [kp, d], exact ints
    tile_counts = onehot.astype(jnp.float32).sum(axis=1, keepdims=True)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        best_ref[:] = jnp.zeros_like(best_ref)

    sums_ref[:] += tile_sums.astype(jnp.int32)
    counts_ref[:] += tile_counts.astype(jnp.int32)
    best_ref[:] += best.sum().reshape(1, 1)


#: scoped-VMEM budget for the int8 tile search (the OOM-calibrated
#: headroom under the 16 MB/core ceiling — see vmem_bytes_int8)
_VMEM_BUDGET_INT8 = 14 << 20


def vmem_bytes_int8(tn: int, d: int, kp: int) -> int:
    """The int8 kernel's scoped-VMEM byte model at point tile ``tn``.

    Calibrated by the 2026-08-01 silicon OOM (10000-row tiles die at
    16.23 MB): the compiler's scoped stack is ≈ tn·(2·d + 8·kp) B
    (double-buffered int8 in-blocks plus the [tn, kp] score/one-hot
    temporaries), + the [kp, d]-class operands, + a 64 KiB fixed floor.
    This is the expression the kernel-registry ``vmem_bytes``
    declaration pins at the registered shape (harplint HL205) and the
    memrec pre-dispatch VMEM gate prices explicit tiles with."""
    return tn * (2 * d + 8 * kp) + 5 * kp * d + (64 << 10)


def _tile_rows_int8(n: int, d: int, kp: int) -> int | None:
    """Largest sublane-aligned point tile dividing ``n`` that fits VMEM.

    Bigger tiles amortize the per-program centroid reload, and the int8
    kernel keeps winning with size until the scoped-VMEM wall: measured
    2026-08-01 (1M×300 k=100, 1× v5e) 557.9 iter/s @8000 vs 537.2
    @4000 / 521.5 @2000 / 464.9 @1000, while 10000 OOMs at 16.23 MB —
    which calibrates :func:`vmem_bytes_int8`.  14 MB budget leaves the
    same headroom the LDA kernel's estimator keeps.
    """
    for tn in (64000, 50000, 40000, 32000, 25000, 20000, 16000, 10000,
               8000, 5000, 4000, 2048, 2000, 1024, 1000, 512, 256, 200,
               128, 120, 64, 40, 16, 8):
        if n % tn or tn % 8:
            continue
        if vmem_bytes_int8(tn, d, kp) <= _VMEM_BUDGET_INT8:
            return tn
    return None


def int8_supported(n: int, d: int, k: int) -> bool:
    """Whether the fused int8 kernel can handle a local (n, d, k) shard:
    a sublane-aligned tile must divide n AND fit the VMEM budget, and d
    must stay inside the exact-f32-accumulation bound.  The dispatch
    gate (kmeans._use_pallas auto path) consults this and falls back to
    the XLA int8 path — shapes the kernel can't take must not start
    raising just because the default flipped (review finding, round 5)."""
    if 127 * 127 * d >= 1 << 24:  # d ≤ 1040
        return False
    return _tile_rows_int8(n, d, -(-k // _LANE) * _LANE) is not None


def kmeans_partials_int8(pts_q, c_q, c_scale, c2, col_scale, *,
                         interpret: bool = False,
                         tile_rows: int | None = None):
    """Fused int8 per-shard partials → (sums [k, d] f32, counts [k] f32,
    best_sum f32 scalar).

    ``pts_q`` [n, d] int8 with per-feature ``col_scale`` [d]; ``c_q`` /
    ``c_scale`` [k, d] int8 / [k] from the shared per-row centroid
    requantization (``kmeans._quantize_centroids``); ``c2`` [k] the
    ORIGINAL-space ‖c‖².  Returns dequantized sums (int32 accumulation ×
    col_scale) and the Σ over points of the assigned score;
    ``inertia = best_sum + Σ‖x‖²`` where the caller supplies the
    iteration-invariant second term.  int32 exactness bound: a cluster
    may absorb at most 2³¹/127 ≈ 16.9M local rows (same rule as the XLA
    path's ``_INT8_SUM_ROW_LIMIT``).

    ``tile_rows`` overrides the auto tile search (sweeps, tests); an
    explicit tile is priced through :func:`vmem_bytes_int8` and an
    over-VMEM choice is REFUSED before dispatch by
    :func:`harp_tpu.utils.memrec.require_vmem_fit` — the 2026-08-01
    silicon OOM as a pre-silicon MemoryError naming the predicted
    bytes."""
    n, d = pts_q.shape
    k = c_q.shape[0]
    kp = -(-k // _LANE) * _LANE
    if tile_rows is not None:
        tn = int(tile_rows)
        if n % tn or tn % 8:
            raise ValueError(
                f"tile_rows={tn} must divide n={n} and align to 8")
        from harp_tpu.utils import memrec

        memrec.require_vmem_fit(
            "kmeans.partials_int8", vmem_bytes_int8(tn, d, kp),
            budget=_VMEM_BUDGET_INT8)
    else:
        tn = _tile_rows_int8(n, d, kp)
    if tn is None:
        raise ValueError(f"no supported tile size divides n={n} "
                         f"within the VMEM budget (d={d}, kp={kp})")
    if 127 * 127 * d >= 1 << 24:  # d ≤ 1040
        # beyond this the bf16-operand dot's f32 partial sums exceed the
        # 2²⁴ exact-integer range and the bit-for-bit promise vs the XLA
        # int32 path silently breaks — refuse loudly, like the row limit
        raise ValueError(
            f"fused int8 kernel: d={d} exceeds the exact-f32-accumulation "
            f"bound (127²·d < 2²⁴ ⇒ d ≤ 1040); use the XLA int8 path")
    cq_pad = jnp.pad(c_q, ((0, kp - k), (0, 0)))
    cs_pad = jnp.pad(c_scale.reshape(-1, 1), ((0, kp - k), (0, 0)))
    c2_pad = jnp.pad(c2.reshape(-1, 1), ((0, kp - k), (0, 0)))

    sums_i, counts_i, best_sum = pl.pallas_call(
        functools.partial(_kernel_int8, k=k),
        grid=(n // tn,),
        in_specs=[
            pl.BlockSpec((tn, d), lambda i: (i, 0)),
            pl.BlockSpec((kp, d), lambda i: (0, 0)),
            pl.BlockSpec((kp, 1), lambda i: (0, 0)),
            pl.BlockSpec((kp, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((kp, d), lambda i: (0, 0)),
            pl.BlockSpec((kp, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, d), jnp.int32),
            jax.ShapeDtypeStruct((kp, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(pts_q, cq_pad, cs_pad, c2_pad)
    sums = sums_i[:k].astype(jnp.float32) * col_scale[None, :]
    return sums, counts_i[:k, 0].astype(jnp.float32), best_sum[0, 0]
