"""Fused LDA-CGS entry resample — Pallas TPU kernel.

Reference parity: the CGS inner loop Harp ran in ``edu.iu.lda``'s
sampler threads (SURVEY.md §3.4 #3, §4.4).  The XLA ``algo="dense"``
path (`models/lda.py:_sample_entry`) materializes six-plus [C, K]
intermediates per tile entry in HBM (gathered count rows, the removed
self-assignment, posterior, noise) — ~30 MB per 2048-token entry at the
graded 1k topics.  This kernel runs the whole entry — count-row
gathers, posterior, topic draw, count-delta scatters — inside VMEM, so
HBM sees only the two count tiles in and out plus the token stream.

Layout (the kmeans/mfsgd kernels' lane rules): everything is
**topic-major** — count tiles arrive transposed ([K, d_tile]/[K, w_tile],
the epoch transposes the tables once), token ids/assignments ride rows
[1, C], all one-hots are built in [tile, C] orientation, and every
matmul contracts over lanes or A-lane×B-sublane.

Sampling stack (fixed, by construction — the kernel exists because of
it): exponential-race draw (``LDAConfig.sampler="exprace"`` — identical
distribution to Gumbel-argmax) over hardware random bits
(``pltpu.prng_random_bits`` — the ``rng_impl="rbg"`` analogue), seeded
per entry+chunk so runs are deterministic per backend.

Numerics — read before trusting counts:
- Count GATHERS are EXACT by default (``exact_gathers=True``, ADVICE r3):
  each table splits into base-256 planes (int16 doc tiles: 2 planes,
  exact to 2^15; f32 word tiles: 3 planes, exact to 2^24 — the f32
  table's own integer ceiling), every plane holds integers ≤ 256 (bf16-
  exact), one bf16 dot per plane, exact f32 recombination.  Cost: +1/+2
  gather dots and ~6·K·max(DR, WR) bytes of plane temporaries per tile.
  Static ``ndk/nwk_count_bound``\\ s shrink the plane counts (chain
  invariants — doc-topic ≤ doc length, word-topic ≤ word frequency;
  ``LDA._install_pack`` derives them per corpus): enwiki-shape doc
  lengths ≤ 256 make the Db gather ONE plain bf16 dot, still exact.
  ``exact_gathers=False`` keeps the single-dot bf16 gather — counts >
  256 round (≤ 0.4% relative, *in the posterior only*); the
  ``lda_pallas_approx`` sweep config measures whether that buys ≥10% at
  equal chain likelihood (the flip gate's job).
- Count UPDATES stay exact on both paths: deltas are 0/±1 (bf16-exact),
  scatter dots accumulate in f32, int16 tables round-trip exactly.
  Tables remain integer-valued — the invariant the tests pin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _gather_planes(tbl_f32, oh, dot, nplanes: int):
    """One-hot gather ``tbl @ oh`` with bf16 dots, exact for integer
    tables below ``256 ** nplanes``.

    ``nplanes == 0``: single bf16 dot of the raw table (values > 256
    round).  Otherwise the table splits into base-256 digit planes —
    every plane holds integers in [0, 256], which bf16 represents
    exactly — each plane gathers with its own bf16 dot (one-hot columns
    select single values, so the f32 accumulation is exact), and the
    digits recombine in f32 (exact below 2^24).  Plain jnp/lax math, so
    the same function runs inside the Pallas kernel and in numpy-backed
    unit tests.
    """
    if nplanes == 0:
        return dot(tbl_f32.astype(jnp.bfloat16), oh)
    acc = None
    rem = tbl_f32
    scale = 1.0
    for _ in range(nplanes - 1):
        hi = jnp.floor(rem * (1.0 / 256.0))
        lo = rem - hi * 256.0           # integer in [0, 255]: bf16-exact
        part = dot(lo.astype(jnp.bfloat16), oh) * scale
        acc = part if acc is None else acc + part
        rem = hi
        scale = scale * 256.0
    top = dot(rem.astype(jnp.bfloat16), oh) * scale
    # nplanes == 1: the caller proved values ≤ 256 (bf16-exact), so the
    # top "plane" IS the whole gather
    return top if acc is None else acc + top


def _kernel(seed_ref, db_in, wb_in, nk_in, z_in, cd_in, cw_in, *rest,
            alpha, beta, vbeta, has_noise, nplanes_d, nplanes_w):
    if has_noise:
        # CPU/interpret test path: pltpu.prng_random_bits is stubbed to
        # zeros off-TPU, so uniforms arrive as a sliced input instead
        noise_in, db_out, wb_out, z_out, dnk_out = rest
    else:
        db_out, wb_out, z_out, dnk_out = rest
    K, DR = db_in.shape
    _, WR = wb_in.shape
    cc = z_in.shape[1]
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        db_out[...] = db_in[...]
        wb_out[...] = wb_in[...]
        dnk_out[...] = jnp.zeros_like(dnk_out)

    cd = cd_in[...]                                      # [1, cc] i32
    cw = cw_in[...]
    z = z_in[...]
    m = (cd < DR).astype(jnp.float32)                    # pad slots drop out

    ohd = (lax.broadcasted_iota(jnp.int32, (DR, cc), 0) == cd
           ).astype(jnp.bfloat16)                        # [DR, cc]
    ohw = (lax.broadcasted_iota(jnp.int32, (WR, cc), 0) == cw
           ).astype(jnp.bfloat16)
    rows_k = lax.broadcasted_iota(jnp.int32, (K, cc), 0)
    oh_old = (rows_k == z).astype(jnp.float32) * m       # [K, cc]

    dot = functools.partial(lax.dot_general,
                            preferred_element_type=jnp.float32)
    gdot = functools.partial(dot,
                             dimension_numbers=(((1,), (0,)), ((), ())))
    # snapshot gathers — exact digit planes or single rounded bf16 dot
    # per the nplanes_* statics (see module doc / _gather_planes)
    ndkT = _gather_planes(db_out[...].astype(jnp.float32), ohd, gdot,
                          nplanes_d) - oh_old            # [K, cc]
    nwkT = _gather_planes(wb_out[...].astype(jnp.float32), ohw, gdot,
                          nplanes_w) - oh_old
    nkT = (nk_in[...] + dnk_out[...]) - oh_old           # [K, 1] bcast

    a = jnp.maximum(ndkT + alpha, 1e-10)
    b = jnp.maximum(nwkT + beta, 1e-10)
    c = jnp.maximum(nkT + vbeta, 1e-10)
    # exponential race: argmin E/p, E ~ Exp(1), p ∝ a·b/c
    if has_noise:
        u = noise_in[...]                                # [K, cc] in (0,1)
    else:
        # distinct stream per (entry, chunk).  The real TPU compiler
        # accepts at most TWO seed words ("Setting seed with more than
        # 2 values is not supported", silicon 2026-08-01; the CPU
        # Mosaic lowering pass does NOT enforce this), so the chunk id
        # is folded into the second entry-key word with an odd-constant
        # multiply (golden-ratio 0x9E3779B9, int32 wraparound) + xor —
        # distinct j stay distinct, streams stay decorrelated
        pltpu.prng_seed(seed_ref[0],
                        seed_ref[1] ^ (j * jnp.int32(-1640531527)))
        bits = pltpu.prng_random_bits((K, cc))
        # logical shift keeps int32 (Mosaic has no uint32->f32 cast):
        # 24 uniform bits -> (0, 1)
        u = lax.shift_right_logical(bits, 8).astype(jnp.float32) \
            * (2.0 ** -24) + 2.0 ** -25
    ratio = -jnp.log(u) * c / (a * b)                    # [K, cc]

    best = ratio.min(axis=0, keepdims=True)              # [1, cc]
    # tie-break min runs in f32 (exact for indices ≤ K < 2^24): Mosaic has
    # no integer reduce_min on older toolchains
    z_new = jnp.where(ratio == best, rows_k, K).astype(jnp.float32) \
        .min(axis=0, keepdims=True).astype(jnp.int32)
    z_new = jnp.where(m > 0, z_new, z)
    z_out[...] = z_new

    oh_new = (rows_k == z_new).astype(jnp.float32) * m
    delta = (oh_new - oh_old).astype(jnp.bfloat16)       # 0/±1: exact
    dDb = dot(delta, ohd, (((1,), (1,)), ((), ())))      # [K, DR] exact f32
    dWb = dot(delta, ohw, (((1,), (1,)), ((), ())))
    db_out[...] = (db_out[...].astype(jnp.float32) + dDb
                   ).astype(db_out.dtype)
    wb_out[...] = wb_out[...] + dWb
    dnk_out[...] += delta.astype(jnp.float32).sum(axis=1, keepdims=True)


def _planes_for(count_bound, dtype) -> int:
    """Fewest base-256 digit planes that gather a count table EXACTLY.

    ``count_bound`` is a static upper bound on any table value — a chain
    INVARIANT when supplied (doc-topic counts ≤ doc length, word-topic
    counts ≤ word frequency; row sums never change under Gibbs), so the
    caller may derive it once from the initial tables.  None falls back
    to what the dtype can hold.
    """
    if count_bound is not None:
        if count_bound <= 256:
            return 1        # bf16 holds 0..256 exactly: one plain dot
        if count_bound < 2 ** 16:
            return 2
        return 3
    return 2 if jnp.dtype(dtype) == jnp.int16 else 3


def cgs_entry_update(DbT, WbT, nk, z, cd, cw, seed2, *, alpha, beta, vbeta,
                     # 256 measured best on the full kernel+carry stack
                     # (2026-08-01, 1× v5e, 100k docs × 1k topics:
                     # 10.5M tok/s vs 10.39M @128 / 10.29M @512)
                     chunk_c: int = 256, interpret: bool = False,
                     exact_gathers: bool = True, ndk_count_bound=None,
                     nwk_count_bound=None):
    """Resample one dense tile entry's tokens; return updated tiles.

    ``DbT`` [K, d_tile] (float32 or int16), ``WbT`` [K, w_tile] float32 —
    topic-major count tiles; ``nk`` [K] topic totals the entry should
    sample against; ``z/cd/cw`` [C] current topics + tile-local ids (pad
    id = tile width); ``seed2`` [2] int32.  Returns
    ``(DbT', WbT', z_new [C], dnk [K])``.

    Blocked-Gibbs granularity is ``chunk_c`` tokens, FINER than the XLA
    path's whole-entry snapshot: tiles and dnk accumulate in VMEM across
    the chunk grid, so chunk j samples against counts that already
    include chunks < j — strictly fresher than ``lda._sample_entry``
    (same approximation family the reference's timer-bounded scheduler
    sets; convergence tests cover it).
    """
    K, DR = DbT.shape
    _, WR = WbT.shape
    C = z.shape[0]
    # digit planes sized by the tightest static bound available: a
    # corpus-derived count bound (see _planes_for — chain-invariant),
    # else what the dtype can hold
    nplanes_d = (_planes_for(ndk_count_bound, DbT.dtype)
                 if exact_gathers else 0)
    nplanes_w = (_planes_for(nwk_count_bound, WbT.dtype)
                 if exact_gathers else 0)

    def est(cc):
        # tiles in+out (+4: f32 out even for int16 in) + ~6 live [K, cc]
        # + exact-gather plane temporaries (f32 remainder + bf16 plane of
        # the currently-gathered table: ~6 B/elem, tables gathered in
        # turn; single-plane gathers only pay the bf16 cast)
        per_elem = 6 if max(nplanes_d, nplanes_w) >= 2 else 2
        planes = per_elem * K * max(DR, WR) if exact_gathers else 0
        return ((DbT.dtype.itemsize + 4) * K * DR + 8 * K * WR
                + 6 * 4 * K * cc + planes)

    # shrink the chunk before refusing: halving cc trades grid steps for
    # VMEM and keeps C % cc == 0 (C is padded to a 256-multiple)
    cc = min(C, chunk_c)
    while est(cc) > 14 << 20 and cc > _LANE and cc % 2 == 0:
        cc //= 2
    if C % cc:
        raise ValueError(f"C={C} must be a multiple of chunk_c={cc} "
                         f"(pad entries with DR/WR ids)")
    if not interpret:
        for name, v, mlt in (("d_tile", DR, _LANE), ("w_tile", WR, _LANE),
                             ("chunk", cc, _LANE), ("n_topics", K, 8)):
            if v % mlt:
                raise ValueError(
                    f"pallas lda: {name}={v} must be a multiple of {mlt} "
                    f"on TPU (use algo='dense' for odd shapes)")
    if est(cc) > 14 << 20:
        raise ValueError(
            f"pallas lda: ~{est(cc) >> 20} MB VMEM estimate exceeds the "
            f"14 MB budget even at chunk {cc}; lower d_tile/w_tile or "
            f"use algo='dense'")

    in_specs = [
        pl.BlockSpec((K, DR), lambda j, s: (0, 0)),
        pl.BlockSpec((K, WR), lambda j, s: (0, 0)),
        pl.BlockSpec((K, 1), lambda j, s: (0, 0)),
        pl.BlockSpec((1, cc), lambda j, s: (0, j)),
        pl.BlockSpec((1, cc), lambda j, s: (0, j)),
        pl.BlockSpec((1, cc), lambda j, s: (0, j)),
    ]
    operands = [DbT, WbT, nk.reshape(K, 1), z.reshape(1, C),
                cd.reshape(1, C), cw.reshape(1, C)]
    if interpret:
        # off-TPU the hardware PRNG is unavailable (pltpu.prng_random_bits
        # stubs to zeros in interpret mode) — draw the uniforms outside
        # and stream them in per chunk; the TPU path never pays this HBM
        key = jax.random.wrap_key_data(seed2.astype(jnp.uint32)[:2])
        u_all = jax.random.uniform(key, (K, C), jnp.float32,
                                   minval=2.0 ** -25, maxval=1.0)
        in_specs.append(pl.BlockSpec((K, cc), lambda j, s: (0, j)))
        operands.append(u_all)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # seed2
        grid=(C // cc,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((K, DR), lambda j, s: (0, 0)),
            pl.BlockSpec((K, WR), lambda j, s: (0, 0)),
            pl.BlockSpec((1, cc), lambda j, s: (0, j)),
            pl.BlockSpec((K, 1), lambda j, s: (0, 0)),
        ],
    )
    Db2, Wb2, z_new, dnk = pl.pallas_call(
        functools.partial(_kernel, alpha=alpha, beta=beta, vbeta=vbeta,
                          has_noise=bool(interpret),
                          nplanes_d=nplanes_d, nplanes_w=nplanes_w),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((K, DR), DbT.dtype),
            jax.ShapeDtypeStruct((K, WR), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.int32),
            jax.ShapeDtypeStruct((K, 1), jnp.float32),
        ],
        interpret=interpret,
    )(seed2.astype(jnp.int32), *operands)
    return Db2, Wb2, z_new.reshape(C), dnk.reshape(K)
