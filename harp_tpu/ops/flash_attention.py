"""Flash attention — Pallas TPU kernel (single-chip blockwise softmax).

The native-kernel counterpart of the XLA path in
:mod:`harp_tpu.ops.ring_attention`: Q/K/V blocks stream HBM→VMEM, the
online-softmax accumulators live in VMEM scratch, and the MXU consumes
[block_q, d] × [d, block_k] tiles.  Grid = (batch·heads, q_blocks,
k_blocks) with K innermost so accumulators carry across the K sweep.

This is the playbook kernel from /opt/skills/guides/pallas_guide.md
(Grid/BlockSpec + scratch + @pl.when init/flush); it exists both as a
usable op and as the template for future hand-written kernels (MF-SGD
fused gather-update, LDA sampling).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale, causal, window, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Skip whole K blocks that cannot contribute (≈2× for causal; O(W/N)
    # of the work for sliding windows): above the diagonal, or entirely
    # outside the window on either side.
    q0, q1 = qi * block_q, qi * block_q + block_q - 1
    k0, k1 = ki * block_k, ki * block_k + block_k - 1
    fully_masked = False
    if causal:
        fully_masked = k0 > q1
    if window is not None:
        if causal:
            fully_masked = fully_masked | (k1 < q0 - window + 1)
        else:
            min_dist = jnp.maximum(0, jnp.maximum(k0 - q1, q0 - k1))
            fully_masked = min_dist >= window

    @pl.when(jnp.logical_not(fully_masked))
    def _compute():
        q = q_ref[0]                      # [bq, d]
        k = k_ref[0]                      # [bk, d]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]

        if causal or window is not None:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1)
            delta = q_pos - k_pos
            keep = jnp.ones_like(delta, jnp.bool_)
            if causal:
                keep = delta >= 0
            if window is not None:
                near = (delta < window) if causal else (jnp.abs(delta) < window)
                keep = keep & near
            masked = jnp.where(keep, scores, -jnp.inf)
        else:
            masked = scores

        m_prev = m_ref[:, 0]                              # [bq]
        m_blk = masked.max(axis=1)
        m_new = jnp.maximum(m_prev, m_blk)
        # `> -inf`, not isfinite: Mosaic has no is_finite lowering (caught
        # by the HL201 kernel audit — this kernel had only ever compiled
        # in interpret mode), and the accumulators' only non-finite value
        # is the -inf init / fully-masked score, so the guards are
        # equivalent
        alpha = jnp.where(m_prev > -jnp.inf, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.exp(jnp.where(masked > -jnp.inf,
                              masked - m_new[:, None], -jnp.inf))
        l_new = l_ref[:, 0] * alpha + p.sum(axis=1)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _flush():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:, 0], 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = False, scale: float | None = None,
                    window: int | None = None,
                    block_q: int = 256, block_k: int = 256,
                    interpret: bool = False):
    """Blockwise attention. q/k/v: [BH, N, D] (fold batch×heads upstream;
    for GQA repeat the K/V heads before folding — the kernel sees folded
    rows).  ``window`` follows the ring/a2a mask contract: last ``window``
    keys when causal, ``window − 1`` either side when not; out-of-window
    K blocks are skipped entirely."""
    bh, n, d = q.shape
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    block_q = min(block_q, n)
    block_k = min(block_k, n)
    assert n % block_q == 0 and n % block_k == 0, (n, block_q, block_k)
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    grid = (bh, n // block_q, n // block_k)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # m
            pltpu.VMEM((block_q, 1), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
        ],
        interpret=interpret,
    )(q, k, v)


def reference_attention(q, k, v, *, causal=False, scale=None, window=None):
    """Straight-line reference for tests."""
    bh, n, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    delta = jnp.arange(n)[:, None] - jnp.arange(n)[None, :]
    mask = jnp.ones((n, n), bool)
    if causal:
        mask = delta >= 0
    if window is not None:
        mask = mask & ((delta < window) if causal else (jnp.abs(delta) < window))
    s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)
