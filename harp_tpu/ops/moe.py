"""Expert parallelism — Switch-style top-1 MoE FFN, one expert per worker.

Completes the parallelism inventory (SURVEY.md §3.5: Harp has no EP, but
its ``regroup``/all-to-all is exactly the EP dispatch pattern — this module
makes that concrete): tokens are routed to experts by a gating argmax,
packed into capacity-bounded per-expert buffers, exchanged with ONE
``regroup`` (all-to-all) so each worker receives every token routed to ITS
expert, run through the local expert FFN, and returned by the inverse
``regroup``; the gate probability scales the combined output.

Static shapes throughout (XLA requirement): each worker sends exactly
``capacity`` token slots to every expert; tokens beyond capacity are
DROPPED (standard Switch behavior) — their output is zero, and
:func:`moe_ffn` reports how many.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import WORKER_AXIS


def moe_ffn(x, gate_w, w1, b1, w2, b2, *, capacity: int,
            axis: str = WORKER_AXIS):
    """Top-1 MoE feed-forward (device view, inside ``shard_map``).

    Args (per worker):
      x: [n_loc, d] local tokens.
      gate_w: [d, E] router weights, replicated (E = worker count).
      w1 [d, h], b1 [h], w2 [h, d], b2 [d]: THIS worker's expert.
      capacity: token slots this worker may send to EACH expert.
    Returns ``(y [n_loc, d], dropped)`` — dropped is the GLOBAL (already
    allreduced) count of tokens that exceeded a capacity bucket on any
    worker; their y rows are zero.
    """
    e = jax.lax.axis_size(axis)
    n_loc, d = x.shape
    if gate_w.shape[-1] != e:
        raise ValueError(
            f"gate_w routes to {gate_w.shape[-1]} experts but the mesh has "
            f"{e} workers (one expert per worker) — shapes must match or "
            "tokens would silently clamp to wrong experts")

    logits = x @ gate_w  # [n_loc, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(logits, axis=-1)         # [n_loc]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]

    from harp_tpu.parallel.dispatch import bucket_by_destination

    (send,), keep, slot, dropped_local = bucket_by_destination(
        expert_idx, (x,), capacity, e)                        # [E, cap, d]
    dropped = C.allreduce(dropped_local)  # global drop count (all workers)

    # the EP exchange: block e of `send` goes to worker e; received block s
    # holds worker s's tokens for MY expert — Harp's regroup, verbatim
    recv = C.regroup(send, axis=axis, split_dim=0, concat_dim=0)

    h = jax.nn.relu(recv @ w1 + b1)
    out = h @ w2 + b2                                          # [E, cap, d]

    # inverse exchange: block s returns to worker s
    back = C.regroup(out, axis=axis, split_dim=0, concat_dim=0)

    # un-dispatch: token t reads its expert's returned slot; dropped → 0
    y = back[expert_idx, jnp.clip(slot, 0, capacity - 1)]
    return y * (gate * keep)[:, None], dropped


def reference_moe(x, gate_w, w1_all, b1_all, w2_all, b2_all, capacity, n_workers):
    """Host reference: same routing/capacity semantics, dense numpy-style.

    ``x`` is the GLOBAL [n, d] token array laid out worker-major (worker w
    owns rows ``w*n_loc:(w+1)*n_loc``); ``*_all`` stack all experts on dim 0.
    """
    import numpy as np

    x = np.asarray(x)
    n, d = x.shape
    n_loc = n // n_workers
    logits = x @ np.asarray(gate_w)
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    probs = np.asarray(probs)
    idx = logits.argmax(-1)
    y = np.zeros_like(x)
    # per (source worker, expert) capacity buckets, in token order
    counts = np.zeros((n_workers, len(b1_all)), np.int64)
    for t in range(n):
        w = t // n_loc
        ei = idx[t]
        if counts[w, ei] >= capacity:
            continue  # dropped
        counts[w, ei] += 1
        h = np.maximum(x[t] @ np.asarray(w1_all[ei]) + np.asarray(b1_all[ei]), 0)
        y[t] = (h @ np.asarray(w2_all[ei]) + np.asarray(b2_all[ei])) * probs[t, ei]
    return y
