"""The offline predictive cost model — price a config without a relay.

Reference parity (SURVEY.md §7, ROADMAP "relay-free autotuning"): the
repo's scarcest resource is relay time — tile sizes, chunk counts, and
wire choices are hand-swept during precious windows (the 2026-08-01
sprint spent part of its window calibrating ``_tile_rows_int8`` off an
OOM).  TACCL (PAPERS.md arXiv:2111.04867) prunes a combinatorial
schedule space with exactly this kind of sketch-plus-profile model.
This module composes the ingredients that already landed:

- **compute/memory** terms from the roofline work models
  (:mod:`harp_tpu.utils.roofline`) extended with per-variant *mechanism
  terms* — each one the measured wall of a committed PROFILE/BENCH row
  (the dense one-hot operand traffic the MF-SGD kernel removes, the
  XLA ``[n, k]`` intermediates the fused kmeans kernel never writes,
  the per-entry tile handoff ``carry_db`` amortizes);
- **wire** terms from the CommGraph byte sheets (PR 9) × the
  :mod:`harp_tpu.plan.topology` link rates (PR 11), with the planner's
  frozen schedule scaling (``predicted_bytes``) for narrow wires;
- **overhead** terms from the calibrated flight-recorder deltas
  (:data:`harp_tpu.utils.flightrec.CALIBRATED_OVERHEADS`);
- **kernel shapes** from :mod:`harp_tpu.ops.kernel_registry`'s declared
  work fields and the kernels' own OOM-calibrated VMEM byte models
  (the pre-sizer, :func:`presize`).

**Combination is additive (serial roofline), not max().**  The classic
``max(compute, memory, wire)`` assumes perfect overlap; the committed
evidence refutes that here — ``lda_fast`` (cheaper RNG, same bytes) and
``lda_pallas`` (fewer bytes, same RNG) each measured >1.2× over the
same incumbent, which is impossible if one shared wall dominated both.
The Gibbs/SGD inner phases serialize through VMEM dependencies
(PROFILE_local's op rows are sequential), so the model charges the SUM
of the four terms; ``bound`` names the largest (the diagnosis), and the
per-term breakdown sums to the total exactly — which is what
``scripts/check_jsonl.py`` invariant 12 verifies on every exported
``kind: "model"`` row.

**This is a RANKING model, not a wall-clock predictor** (same contract
as ``plan.topology``): absolute seconds carry declared/floor rates and
are graded only to a loose magnitude band, but the *ordering* of
configs is machine-checked against every committed BENCH_local /
FLIP_DECISIONS / SWEEP_pallas row the model can price
(:mod:`harp_tpu.perfmodel.grade`) — a model that silently drifts from
the evidence fails tier-1, exactly like invariants 1–11.

Calibrated constants each cite their committed evidence inline.  Every
exported row is provenance-stamped and carries ``rates_source``
(declared | probed) so a declared ranking can never masquerade as a
measured one.
"""

from __future__ import annotations

import dataclasses

from harp_tpu.utils.flightrec import CALIBRATED_OVERHEADS
from harp_tpu.utils.roofline import V5E_PEAKS

#: frozen vocabularies (check_jsonl invariant 12 pins them standalone;
#: tests/test_perfmodel.py asserts the sync)
BOUNDS = ("compute", "memory", "wire", "overhead")
RATES_SOURCES = ("declared", "probed")

# ---------------------------------------------------------------------------
# Chip-class rates (beyond the roofline peaks)
# ---------------------------------------------------------------------------

#: VPU (vector unit) flop rate — DECLARED from the public v5e layout
#: (8×128 lanes × 2 ops × ~1 GHz); the transcendental/PRNG work that
#: never touches the MXU prices against this, not the 197 TF/s matmul
#: peak.
VPU_FLOPS = 2.0e12

#: XLA scatter of small rows — MEASURED 2026-07-30 on v5e (CLAUDE.md:
#: the reason the dense one-hot formulation exists at all).
SCATTER_GBS = 25.0e9

HBM_GBS = float(V5E_PEAKS["hbm_gbs"])

# ---------------------------------------------------------------------------
# Calibrated mechanism constants (each cites its committed evidence)
# ---------------------------------------------------------------------------

#: threefry2x32 cost per 32-bit word on the VPU (~20 rounds × ~3 ops +
#: key schedule).  The binding term behind the measured lda_fast flip:
#: rng_impl="rbg" was +24% where sampler="exprace" alone was ±2%
#: (BENCH_local 2026-08-01) — bit GENERATION, not sampler math, was the
#: wall, so the model must price it.
THREEFRY_FLOPS_PER_WORD = 96.0
#: the hardware RBG path: effectively free next to threefry.
RBG_FLOPS_PER_WORD = 4.0

#: per-topic VPU flops of the two samplers (roofline's 10K gumbel
#: estimate; exprace measured "~5× fewer VPU transcendentals",
#: measure_all.py comment).
GUMBEL_VPU_FLOPS_PER_TOPIC = 10.0
EXPRACE_VPU_FLOPS_PER_TOPIC = 2.0

#: HBM round trips of the XLA [n, k] intermediates the dense kmeans
#: formulation materializes per iteration (score write/read, one-hot
#: write, two matmul operand reads) — "the XLA int8 path's wall is the
#: ~2 GB/iter [n, k] intermediates" (measure_all.py; at the graded
#: 1M×100 shape 5 × 4nk = 2.0 GB exactly).  The fused Pallas kernels
#: never write them (single HBM pass, ops/kmeans_kernel.py).
KMEANS_XLA_NK_PASSES = 5

#: HBM round trips of the per-token [chunk, K] posteriors the dense XLA
#: LDA path materializes between fusions (scores, noise, one-hot) —
#: the traffic the VMEM-resident kernel absorbs (PROFILE_local
#: 2026-08-01: the kernel row's win is exactly this term).
LDA_XLA_TOKEN_ROUNDTRIPS = 6

#: per-(tile-pair) entry handoff cost for the tiled LDA algos, in HBM
#: byte-equivalents: tile load/flush + kernel program overhead per
#: entry.  CALIBRATED once against the committed SWEEP_pallas d_tile
#: pair (2026-08-01: 8.02M tok/s @512 vs 4.56M @256 — smaller tiles
#: mean quadratically more tile pairs); the self-grading pins the
#: ranking, so drift fails tier-1.
LDA_ENTRY_OVERHEAD_BYTES = float(1 << 20)

#: per-grid-program fixed cost of the MF-SGD Pallas kernel, in HBM
#: byte-equivalents (the grid is (users/tile)·(items/tile) programs —
#: quadratic in 1/tile).  CALIBRATED once against the committed
#: SWEEP_pallas tile sweep (2026-08-01: 250.2M @256 > 195.5M @512 >
#: 163.3M @1024 > 147.3M @128); the self-grading pins the full
#: 4-point ranking.
MFSGD_GRID_OVERHEAD_BYTES = float(24 << 10)

#: relay-tunnel host→device staging rate — MEASURED by the committed
#: probe_h2d row (2026-08-01: 29.9–40.5 MB/s across the 16–157 MB
#: probes; same 30 MB/s flightrec.CALIBRATED_OVERHEADS["h2d_gbs"]
#: pins).  The PR-16 attribution pass (python -m harp_tpu profile)
#: priced the unpriced half of the codebase by exposing WHERE this
#: term belongs: svm/wdamds/subgraph/rf committed metrics time
#: fit()/count() INCLUDING the per-run shard_array staging, so their
#: models must charge it — while the kmeans/mfsgd/lda epoch metrics
#: stage once outside the timed region and never pay it.
RELAY_H2D_GBS = float(CALIBRATED_OVERHEADS["h2d_gbs"])

#: svm pegasos x-shard passes per (outer × inner) step: the margin
#: read and the violator-gradient read (models/svm._pegasos) — storing
#: the shard bf16 (x_dtype knob) halves both.
SVM_X_PASSES_PER_STEP = 2.0

#: wdamds SMACOF [n_loc, N] elementwise passes per iteration (distance
#: write+read, ratio write+read, the two delta reads, sqrt mask) —
#: counted from models/wdamds.make_smacof_fn; the delta reads (2 of
#: the passes) shrink with the staged dtype (delta_dtype knob).
WDAMDS_NN_PASSES = 7.0
#: VPU flops per [n_loc, N] entry (sqrt + div + where + guards).
WDAMDS_VPU_FLOPS_PER_ENTRY = 16.0

#: subgraph overflow-arm constants, CALIBRATED once against the two
#: committed segment-vs-onehot A/B deltas (BENCH_local 2026-08-01): at
#: 100k powerlaw (719,074 overflow entries) onehot won by 0.330
#: s/trial; at graded 1M (3,682,709 entries) segment won by 0.456
#: s/trial.  Solving the two-term model for both deltas gives the
#: per-overflow-entry segment-sum cost and the per-tile onehot program
#: cost; grade.py pins the resulting direction at both scales (the
#: round-5 joint gate refused the flip for exactly this crossover).
SUBGRAPH_SEG_ENTRY_S = 2.162e-6
SUBGRAPH_ONEHOT_TILE_S = 2.244e-3
SUBGRAPH_ROW_TILE = 512.0       # models/subgraph row_tile default
SUBGRAPH_ENTRY_TILE = 2048.0    # onehot tile entry capacity
#: DP traversal gather width per vertex per trial: one [deg] neighbor
#: row per template child, ~20 effective DP columns for graded u5-tree.
SUBGRAPH_DP_COLS = 20.0


#: per-grid-program centroid-operand reload of the fused int8 kmeans
#: kernel: the 5·kp·d term of ``_tile_rows_int8``'s OOM-calibrated
#: byte model (bigger tiles amortize it — the mechanism behind the
#: measured monotone tile sweep 557.9 @8000 > ... > 464.9 @1000).
def _kmeans_reload_bytes(d: int, kp: int) -> float:
    return 5.0 * kp * d


def _lane_pad(k: int) -> int:
    return -(-k // 128) * 128


# ---------------------------------------------------------------------------
# Price: the per-config term sheet
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Price:
    """One config's predicted per-unit time, with the term breakdown."""

    config: str
    metric: str              # the throughput metric 1/predicted_s predicts
    compute_s: float
    memory_s: float
    wire_s: float
    overhead_s: float

    @property
    def predicted_s(self) -> float:
        return (self.compute_s + self.memory_s + self.wire_s
                + self.overhead_s)

    @property
    def predicted_rate(self) -> float:
        return 1.0 / self.predicted_s

    @property
    def bound(self) -> str:
        terms = self.terms()
        return max(BOUNDS, key=lambda b: terms[f"{b}_s"])

    def terms(self) -> dict:
        return {"compute_s": self.compute_s, "memory_s": self.memory_s,
                "wire_s": self.wire_s, "overhead_s": self.overhead_s}


def _mk_price(config, metric, *, mxu_flops=0.0, mxu_peak="bf16_flops",
              vpu_flops=0.0, hbm_bytes=0.0, scatter_bytes=0.0,
              wire_s=0.0, units_per_run=1.0, compiles=0.0,
              h2d_bytes=0.0) -> Price:
    compute = mxu_flops / V5E_PEAKS[mxu_peak] + vpu_flops / VPU_FLOPS
    memory = hbm_bytes / HBM_GBS + scatter_bytes / SCATTER_GBS
    # h2d_bytes: per-RUN staging over the relay tunnel, charged only by
    # families whose committed metric times it (see RELAY_H2D_GBS)
    ovh = (CALIBRATED_OVERHEADS["dispatch_s"]
           + CALIBRATED_OVERHEADS["readback_s"]
           + compiles * CALIBRATED_OVERHEADS["compile_s"]
           + h2d_bytes / RELAY_H2D_GBS) / units_per_run
    return Price(config, metric, compute, memory, wire_s, ovh)


def wire_cost_s(topo, primitive: str, schedule: str,
                sheet_bytes: int) -> float:
    """Price one (collective site, schedule) pair on a topology — THE
    shared wire oracle: ``plan.planner._site_cost`` delegates here (the
    Plan rows' cost column and the model's wire term are one function),
    and the config models below reuse it for their analytic payloads.
    The sheet's bytes are already amplification-folded, so the topology
    sees amplification=1."""
    from harp_tpu.plan.planner import predicted_bytes

    if schedule == "hier_psum":
        return topo.hier_stage_cost_s(sheet_bytes)
    return topo.cost_s(primitive, predicted_bytes(schedule, sheet_bytes))


def _wire_schedule(wire: str | None) -> str:
    return {None: "keep", "bf16": "wire_bf16",
            "int8": "wire_int8"}[wire]


# ---------------------------------------------------------------------------
# Family models
# ---------------------------------------------------------------------------

def _price_kmeans(row, topo, *, quantize=None, fused=False, hier=False,
                  tile=None, config, metric="iters_per_sec"):
    """Per Lloyd iteration over the local shard."""
    nw = max(int(row.get("num_workers") or 1), 1)
    n = float(row.get("n", 1_000_000)) / nw
    d = float(row.get("d", 300))
    k = float(row.get("k", 100))
    dsize = 1 if quantize == "int8" else 4
    mxu_peak = "int8_ops" if quantize == "int8" else "bf16_flops"
    hbm = n * d * dsize + 4.0 * n
    if fused:
        kp = _lane_pad(int(k))
        # the measured-best default tile; sweep pricing overrides by row
        tn = float(tile or row.get("tile") or 8000)
        hbm += (n / tn) * _kmeans_reload_bytes(int(d), kp)
    else:
        # the XLA formulation's [n, k] intermediates (see constant)
        hbm += KMEANS_XLA_NK_PASSES * 4.0 * n * k
    psum_bytes = int(4 * (k * d + k + 1))
    wire = (topo.hier_stage_cost_s(psum_bytes) if hier
            else wire_cost_s(topo, "psum", "keep", psum_bytes))
    return _mk_price(config, metric, mxu_flops=4.0 * n * d * k,
                     mxu_peak=mxu_peak, hbm_bytes=hbm, wire_s=wire,
                     units_per_run=float(row.get("iters", 100)))


def _price_mfsgd(row, topo, *, algo="dense", tile=None, wire=None,
                 config, metric="updates_per_sec_per_chip"):
    """Per rating update (one (w_u, h_i) SGD pair)."""
    rank = float(row.get("rank", 64))
    nnz = float(row.get("nnz", 20_000_000))
    n_items = float(row.get("n_items", 26_744))
    n_users = float(row.get("n_users", 138_493))
    ec = float(row.get("entry_cap", 2048))
    nw = max(int(row.get("num_workers") or 1), 1)
    floor = 16.0 * rank                       # both rows read + written
    hbm, scat = floor, 0.0
    if algo == "dense":
        # one-hot operand traffic: the ohu/ohi rows the MXU reads per
        # update (PROFILE_local 2026-08-01: "MF-SGD's wall was one-hot
        # operand traffic (kernel removes it)"); dense auto-tiles 512.
        t = float(tile or row.get("tile") or 512)
        hbm += 4.0 * 2 * t
    elif algo == "pallas":
        # the kernel keeps one-hots in VMEM; what remains is the W/H
        # slice handoff per entry (grows with tile) and the grid-program
        # overhead ((users/t)·(items/t) programs — shrinks with tile²):
        # the U-shape the committed SWEEP_pallas tile sweep measured.
        t = float(tile or row.get("tile") or 256)  # measured-best default
        hbm += 8.0 * rank * t / ec
        hbm += (MFSGD_GRID_OVERHEAD_BYTES
                * (n_users / nw) * (n_items / t / t) / (nnz / nw))
    else:                                     # scatter
        scat = floor                          # rows move at the scatter wall
        hbm = 0.0
    rot_bytes = int(n_items * rank * 4 / nw)  # one H slice per hop
    wire_s = wire_cost_s(topo, "ppermute", _wire_schedule(wire),
                         rot_bytes * nw) / (nnz / nw)
    units = float(row.get("epochs", 3)) * nnz / nw
    return _mk_price(config, metric, mxu_flops=6.0 * rank,
                     vpu_flops=0.0, hbm_bytes=hbm, scatter_bytes=scat,
                     wire_s=wire_s, units_per_run=units)


def _price_lda(row, topo, *, algo="dense", carry=False, sampler="gumbel",
               rng="threefry", wire=None, config,
               metric="tokens_per_sec_per_chip"):
    """Per Gibbs token."""
    K = float(row.get("n_topics", 1000))
    n_tokens = float(row.get("n_tokens", 10_000_000))
    n_docs = float(row.get("n_docs", 100_000))
    vocab = float(row.get("vocab_size", 50_000))
    dt = float(row.get("d_tile", 512))
    wt = float(row.get("w_tile", 512))
    ec = float(row.get("entry_cap", 2048))
    nw = max(int(row.get("num_workers") or 1), 1)
    vpu = (GUMBEL_VPU_FLOPS_PER_TOPIC if sampler == "gumbel"
           else EXPRACE_VPU_FLOPS_PER_TOPIC) * K
    vpu += (THREEFRY_FLOPS_PER_WORD if rng == "threefry"
            else RBG_FLOPS_PER_WORD) * K
    hbm, scat = 12.0, 0.0                     # the token id stream
    if algo == "scatter":
        scat = 8.0 * K                        # two K-rows at the scatter wall
    else:
        # tiled algos: per-entry tile traffic (carry_db removes the
        # doc-tile load+flush inside an od-run — VERDICT r3 item 2) ...
        hbm += 4.0 * K * ((2 * wt) if carry else (2 * dt + 2 * wt)) / ec
        # ... plus the per-(tile-pair) entry handoff, quadratic in
        # 1/tile (see LDA_ENTRY_OVERHEAD_BYTES)
        hbm += (LDA_ENTRY_OVERHEAD_BYTES
                * (n_docs * vocab / nw) / (dt * wt) / (n_tokens / nw))
        if algo == "dense":
            # XLA inter-fusion [chunk, K] materializations the kernel
            # absorbs (see LDA_XLA_TOKEN_ROUNDTRIPS)
            hbm += LDA_XLA_TOKEN_ROUNDTRIPS * 4.0 * K
    rot_bytes = int(vocab * K * 4 / nw)       # one Nwk slice per hop
    wire_s = wire_cost_s(topo, "ppermute", _wire_schedule(wire),
                         rot_bytes * nw) / (n_tokens / nw)
    units = float(row.get("epochs", 2)) * n_tokens / nw
    return _mk_price(config, metric, mxu_flops=4.0 * K, vpu_flops=vpu,
                     hbm_bytes=hbm, scatter_bytes=scat, wire_s=wire_s,
                     units_per_run=units)


def _price_mlp(row, topo, *, wire=None, config, metric="samples_per_sec"):
    """Per training sample (MNIST-shape MLP, roofline's param count)."""
    params = 535_818.0
    batch = float(row.get("batch", 8192))
    steps = float(row.get("steps", 50))
    psum_bytes = int(4 * params)
    wire_s = wire_cost_s(topo, "psum", _wire_schedule(wire),
                         psum_bytes) / batch
    return _mk_price(config, metric, mxu_flops=6.0 * params,
                     hbm_bytes=16.0 * params / batch, wire_s=wire_s,
                     units_per_run=batch * steps)


def _price_rf(row, topo, *, hist="dense", config, metric="trees_per_sec"):
    """Per grown tree (models/rf: level-synchronous growth + forest
    allgather).  The hist knob makes CLAUDE.md's 25 GB/s scatter-wall
    claim (measured 2026-07-30 on 1x v5e) a priced A/B on THIS app:
    the dense arm is one int8 one-hot MXU matmul per level (node count
    doubles per level, so the flop sum telescopes to ``2^depth - 1``
    node-columns) re-reading the [n, f·B] bin-onehot operand each
    level — PLUS the [n, node·C] one-hot operand it materialises in HBM
    between the fusion and the contraction; the scatter arm moves the
    same ``depth·n·f`` histogram updates at SCATTER_GBS instead; the
    pallas arm (PR 17, ops/rf_kernel.py) builds the one-hot in VMEM, so
    only the per-grid-program fixed cost remains of that term."""
    nw = max(int(row.get("num_workers") or 1), 1)
    n = float(row.get("n", 200_000)) / nw
    f = float(row.get("features", 64))
    bins = float(row.get("n_bins", 32))
    classes = float(row.get("n_classes", 2))
    depth = float(row.get("depth", 6))
    n_trees = float(row.get("n_trees", 32))
    nodes = 2.0 ** depth - 1.0
    mxu, hbm, scat = 0.0, 0.0, 0.0
    if hist in ("dense", "pallas"):
        mxu = 2.0 * n * classes * f * bins * nodes
        hbm = depth * n * f * bins
        if hist == "pallas":
            # presize-predicted default tile (2026-08-06, unmeasured)
            tn = float(row.get("tile") or 2048)
            hbm += depth * (n / tn) * MFSGD_GRID_OVERHEAD_BYTES
        else:
            # the [n, node·C] one-hot write + MXU read-back, telescoped
            # over levels — the traffic the kernel keeps in VMEM
            hbm += 2.0 * n * classes * nodes
    else:
        scat = depth * n * f * 4.0
    tree_bytes = (2.0 ** depth) * 4.0 * 4.0   # feat/thresh/route/leaf
    wire = wire_cost_s(topo, "all_gather", "keep",
                       int(n_trees * tree_bytes / nw)) / n_trees
    # fit() stages the binned shard + labels per run; the committed rf
    # row's fit_sec times that staging (see RELAY_H2D_GBS)
    return _mk_price(config, metric, mxu_flops=mxu, mxu_peak="int8_ops",
                     hbm_bytes=hbm, scatter_bytes=scat, wire_s=wire,
                     units_per_run=n_trees,
                     h2d_bytes=n * nw * (f * 4.0 + 4.0))


def _price_svm(row, topo, *, x_dtype="f32", algo="xla", wire=None, config,
               metric="samples_per_sec"):
    """Per training sample over the full dataset (models/svm: the whole
    multi-round pegasos run is ONE jit; ``fit`` re-stages the x shard
    per call, so the committed samples_per_sec includes the staging —
    at the relay tunnel rate that term dominates, which is why the
    bf16-shard knob is the flip candidate).  The pallas arm (PR 17,
    ops/svm_kernel.py) fuses the two per-step feature passes into one
    plus the sequential grid's per-program cost."""
    nw = max(int(row.get("num_workers") or 1), 1)
    n = float(row.get("n", 500_000))
    d = float(row.get("d", 128))
    steps = (float(row.get("inner_steps", 200))
             * float(row.get("outer_rounds", 5)))
    sv = float(row.get("sv_per_worker", 256))
    xsize = 2.0 if x_dtype == "bf16" else 4.0
    if algo == "pallas":
        # presize-predicted default tile (2026-08-06, unmeasured)
        tn = float(row.get("tile") or 8192)
        hbm = steps * (d * xsize + MFSGD_GRID_OVERHEAD_BYTES / tn) / nw
    else:
        hbm = steps * SVM_X_PASSES_PER_STEP * d * xsize / nw
    sv_bytes = int(sv * d * 4 * nw)           # SV exchange, all shards
    wire_s = (float(row.get("outer_rounds", 5))
              * (wire_cost_s(topo, "ppermute", _wire_schedule(wire),
                             sv_bytes)
                 + wire_cost_s(topo, "psum", "keep", int(d * 4)))) / n
    return _mk_price(config, metric,
                     mxu_flops=steps * 4.0 * d / nw,
                     hbm_bytes=hbm,
                     wire_s=wire_s, units_per_run=n,
                     h2d_bytes=n * (d * xsize + 4.0))


def _price_wdamds(row, topo, *, delta_dtype="f32", algo="xla", wire=None,
                  config, metric="iters_per_sec"):
    """Per SMACOF iteration (models/wdamds: one jit scan over iters;
    ``fit`` stages the [n, n] delta per run — at the relay tunnel rate
    that staging IS the committed wall, so the bf16-delta knob that
    halves it is the flip candidate).  The pallas arm (PR 17,
    ops/wdamds_kernel.py) fuses the D/ratio blocks into VMEM: δ streams
    once, X^T loads once, only the per-grid-program cost remains of the
    WDAMDS_NN_PASSES round-trips."""
    nw = max(int(row.get("num_workers") or 1), 1)
    n = float(row.get("n", 4096))
    dim = float(row.get("dim", 3))
    iters = float(row.get("iters", 30))
    dsize = 2.0 if delta_dtype == "bf16" else 4.0
    n_loc = n / nw
    if algo == "pallas":
        # presize-predicted default tile (2026-08-06, unmeasured)
        tn = float(row.get("tile") or 128)
        hbm = (n_loc * n * dsize            # the one δ stream
               + n * 128.0 * 4.0            # resident X^T load
               + (n_loc / tn) * MFSGD_GRID_OVERHEAD_BYTES)
    else:
        hbm = n_loc * n * ((WDAMDS_NN_PASSES - 2.0) * 4.0 + 2.0 * dsize)
    wire_s = (wire_cost_s(topo, "ppermute", _wire_schedule(wire),
                          int(n * dim * 4))
              + wire_cost_s(topo, "psum", "keep", 4))
    return _mk_price(config, metric,
                     # distance + Guttman-transform matmuls
                     mxu_flops=4.0 * n_loc * n * dim,
                     vpu_flops=WDAMDS_VPU_FLOPS_PER_ENTRY * n_loc * n,
                     hbm_bytes=hbm,
                     wire_s=wire_s, units_per_run=iters,
                     h2d_bytes=n * n * dsize)


def _price_subgraph(row, topo, *, overflow="segment", deg=64.0,
                    ovf_default=0.0, config, metric="vertices_per_sec"):
    """Per vertex per color-coding trial (models/subgraph).  The padded
    [n, deg] CSR (nbr int32 + msk f32) ships per run — the dominant
    committed term — plus the calibrated overflow arm: segment-sum cost
    linear in overflow entries vs the onehot arm's per-tile program
    cost (tiles grow with BOTH n/row_tile windows and entries/tile
    capacity — the crossover the 1M A/B measured)."""
    n = float(row.get("n_vertices", 100_000))
    ovf = float(row.get("overflow_edges", ovf_default))
    base = _mk_price(config, metric,
                     hbm_bytes=deg * 4.0 * SUBGRAPH_DP_COLS,
                     wire_s=wire_cost_s(topo, "psum", "keep", 8) / n,
                     units_per_run=n,
                     h2d_bytes=n * deg * 8.0 + ovf * 12.0)
    if overflow == "onehot":
        tiles = n / SUBGRAPH_ROW_TILE + ovf / SUBGRAPH_ENTRY_TILE
        extra = SUBGRAPH_ONEHOT_TILE_S * tiles / n
    else:
        extra = SUBGRAPH_SEG_ENTRY_S * ovf / n
    return dataclasses.replace(base, memory_s=base.memory_s + extra)


def _price_serve(row, topo, *, app="kmeans", batch_default=64.0,
                 config, metric="qps"):
    """Per served request — the serve-plane queueing term: one
    dispatch+readback per batch window amortized over its rows, plus
    the app's per-row executor work (state reload amortized per
    window).  Batch shapes come from the row's own
    ``n_requests/steady_dispatches`` when present; defaults are
    CALIBRATED from the committed sustained rows (2026-08-04 CPU sim:
    serve_kmeans_sustained 4096 req / 23 dispatches ≈ 178 rows/window
    at 30,183 qps; serve_mfsgd_sustained 4096/15 ≈ 273 at 7,011 qps)
    and the burst rung (burst_admit=64).  CPU rows are excluded from
    magnitude grading — this term RANKS batching configs against the
    relay-calibrated dispatch cost, it does not reproduce CPU walls."""
    nr, sd = row.get("n_requests"), row.get("steady_dispatches")
    batch = (float(nr) / float(sd)) if nr and sd else float(batch_default)
    rows = float(row.get("rows_per_request", 1))
    if app == "kmeans":
        k, d = float(row.get("k", 100)), float(row.get("d", 300))
        mxu = 2.0 * d * k * rows
        hbm = (d + k) * 4.0 * rows + k * d * 4.0 / batch
    else:                                     # mfsgd top-k scorer
        rank = float(row.get("rank", 64))
        items = float(row.get("n_items", 26_744))
        mxu = 2.0 * rank * items * rows
        hbm = items * 4.0 * rows + items * rank * 4.0 / batch
    return _mk_price(config, metric, mxu_flops=mxu, hbm_bytes=hbm,
                     units_per_run=batch)


# ---------------------------------------------------------------------------
# The config table
# ---------------------------------------------------------------------------

def _k(**kw):
    return ("kmeans", kw)


def _m(**kw):
    return ("mfsgd", kw)


def _l(**kw):
    return ("lda", kw)


def _p(**kw):
    return ("mlp", kw)


def _r(**kw):
    return ("rf", kw)


def _s(**kw):
    return ("svm", kw)


def _w(**kw):
    return ("wdamds", kw)


def _g(**kw):
    return ("subgraph", kw)


def _q(**kw):
    return ("serve", kw)


#: config -> (family, variant kwargs).  PR 16's attribution pass
#: (``python -m harp_tpu profile``) priced the previously-UNPRICEABLE
#: half — rf/svm/wdamds/subgraph and the serve plane now carry
#: mechanism terms — so the only configs still absent are the
#: host-bound ingest twins (kmeans_ingest*: disk generation dominates,
#: no device mechanism to rank): no number beats a wrong one, the same
#: rule as roofline.WORK_MODELS.
CONFIG_MODELS = {
    "kmeans": _k(),
    "kmeans_int8": _k(quantize="int8"),
    "kmeans_int8_fused": _k(quantize="int8", fused=True),
    "kmeans_hier_psum": _k(hier=True),
    "kmeans_stream": _k(metric="iters_per_sec_ex_gen"),
    "kmeans_stream_int8": _k(quantize="int8",
                             metric="iters_per_sec_ex_gen"),
    "mfsgd": _m(),
    "mfsgd_scatter": _m(algo="scatter"),
    "mfsgd_pallas": _m(algo="pallas"),
    "mfsgd_carry": _m(),                      # carry_w: dense ±epsilon
    "mfsgd_chunked_rotate": _m(algo="pallas"),  # chunking re-times hops
    "lda": _l(),
    "lda_carry": _l(carry=True),
    "lda_exprace": _l(sampler="exprace"),
    "lda_fast": _l(sampler="exprace", rng="rbg"),
    "lda_pallas": _l(algo="pallas"),
    "lda_pallas_approx": _l(algo="pallas"),   # gather width: MXU-side only
    "lda_pallas_hot": _l(algo="pallas"),
    "lda_pallas_approx_hot": _l(algo="pallas"),
    "lda_pallas_carry": _l(algo="pallas", carry=True),
    "lda_rotate_int8": _l(algo="pallas", carry=True, wire="int8"),
    "lda_planner_wire": _l(algo="pallas", carry=True, wire="bf16"),
    "lda_scatter": _l(algo="scatter"),
    "lda_scale": _l(),
    "lda_scale_1m": _l(),
    "lda_scale_1m_pallas": _l(algo="pallas", carry=True),
    "mlp": _p(),
    "mlp_grad_bf16": _p(wire="bf16"),
    "mlp_grad_int8": _p(wire="int8"),
    # PR 16: the attribution observatory's newly priced half.
    "rf": _r(),
    "rf_dense_hist": _r(),                    # the hist_algo A/B, dense arm
    "rf_scatter_hist": _r(hist="scatter"),
    # PR 17: the kernelized arms (presize-predicted, unmeasured — flip
    # candidates in SPRINT_ORDER; silicon verdicts pending)
    "rf_hist_pallas": _r(hist="pallas"),
    "svm": _s(),
    "svm_sv_bf16": _s(wire="bf16"),
    "svm_sv_int8": _s(wire="int8"),
    "svm_x_bf16": _s(x_dtype="bf16"),         # halve the staged shard
    "svm_kernel_pallas": _s(algo="pallas"),   # PR 17: one fused x pass
    "wdamds": _w(),
    "wdamds_coord_bf16": _w(wire="bf16"),
    "wdamds_coord_int8": _w(wire="int8"),
    "wdamds_delta_bf16": _w(delta_dtype="bf16"),
    "wdamds_dist_pallas": _w(algo="pallas"),  # PR 17: fused D/ratio
    "subgraph": _g(deg=64),
    "subgraph_csr32": _g(deg=32),             # halve the padded-CSR ship
    "subgraph_pl": _g(deg=16, ovf_default=719_074),
    "subgraph_onehot": _g(deg=16, ovf_default=719_074,
                          overflow="onehot"),
    "subgraph_1m": _g(deg=16, ovf_default=3_682_709),
    "subgraph_1m_onehot": _g(deg=16, ovf_default=3_682_709,
                             overflow="onehot"),
    "serve_kmeans": _q(app="kmeans"),
    "serve_kmeans_sustained": _q(app="kmeans", batch_default=178.0),
    "serve_mfsgd_topk": _q(app="mfsgd"),
    "serve_mfsgd_sustained": _q(app="mfsgd", batch_default=273.0),
}

#: committed BENCH_local rows whose config name is a CLI metrics tag,
#: not a sprint config (svm_cli/wdamds_cli landed 2026-08-01 via the
#: app CLIs) — the magnitude band grades them through the incumbent's
#: model.  CONFIG_MODELS itself stays ⊆ measure_all.SPRINT_ORDER
#: (tests/test_perfmodel.py): a predict row must never name a config
#: the sprint cannot run.
CLI_ROW_ALIASES = {"svm_cli": "svm", "wdamds_cli": "wdamds"}

_FAMILY_FNS = {"kmeans": _price_kmeans, "mfsgd": _price_mfsgd,
               "lda": _price_lda, "mlp": _price_mlp,
               "rf": _price_rf, "svm": _price_svm,
               "wdamds": _price_wdamds, "subgraph": _price_subgraph,
               "serve": _price_serve}

#: full-shape overrides for configs whose graded shape differs from the
#: family benchmark defaults (mirrors measure_all.py's full kwargs);
#: everything else prices at the family defaults baked into the
#: ``_price_*`` row.get defaults.
FULL_SHAPES = {
    "kmeans_stream": {"n": 100_000_000, "k": 1000, "iters": 2},
    "kmeans_stream_int8": {"n": 100_000_000, "k": 1000, "iters": 2},
    "lda_pallas_hot": {"n_docs": 20_000, "vocab_size": 256,
                       "n_topics": 32, "n_tokens": 4_000_000,
                       "d_tile": 128, "w_tile": 128},
    "lda_pallas_approx_hot": {"n_docs": 20_000, "vocab_size": 256,
                              "n_topics": 32, "n_tokens": 4_000_000,
                              "d_tile": 128, "w_tile": 128},
    "lda_scale": {"n_docs": 500_000, "n_tokens": 50_000_000,
                  "epochs": 1},
    "lda_scale_1m": {"n_docs": 1_000_000, "n_tokens": 100_000_000,
                     "epochs": 1},
    "lda_scale_1m_pallas": {"n_docs": 1_000_000, "n_tokens": 100_000_000,
                            "epochs": 1},
    "subgraph_1m": {"n_vertices": 1_000_000},
    "subgraph_1m_onehot": {"n_vertices": 1_000_000},
}


def price(config: str, row: dict | None = None, topo=None) -> Price:
    """Price one config: predicted per-unit seconds + term breakdown.

    ``row`` supplies shape fields (a committed BENCH_local row works
    as-is — the grading harness replays them); absent fields fall back
    to the graded full shapes.  Raises ``KeyError`` for unpriceable
    configs — callers that prune must surface that, never swallow it.
    """
    if config not in CONFIG_MODELS:
        raise KeyError(f"{config!r} has no cost model (unpriceable — "
                       "see CONFIG_MODELS)")
    if topo is None:
        from harp_tpu.plan.topology import single_chip

        topo = single_chip()
    family, kw = CONFIG_MODELS[config]
    merged = dict(FULL_SHAPES.get(config) or {})
    merged.update({k: v for k, v in (row or {}).items() if v is not None})
    return _FAMILY_FNS[family](merged, topo, config=config, **kw)


# ---------------------------------------------------------------------------
# kind:"model" rows
# ---------------------------------------------------------------------------

#: byte-sheet program -> the SPRINT_ORDER configs that execute it
#: (tests pin every value against measure_all.SPRINT_ORDER — invariant
#: 12 refuses a model row referencing a config the sprint cannot run).
PROGRAM_CONFIGS = {
    "kmeans.fit": ("kmeans", "kmeans_int8", "kmeans_int8_fused"),
    "kmeans.fit_hier": ("kmeans_hier_psum",),
    "ingest.accum_chunk": ("kmeans_ingest", "kmeans_ingest_int8"),
    "ingest.finish_epoch": ("kmeans_stream", "kmeans_stream_int8"),
    "mfsgd.epoch": ("mfsgd", "mfsgd_scatter", "mfsgd_pallas",
                    "mfsgd_carry", "mfsgd_chunked_rotate"),
    "lda.epoch": ("lda", "lda_carry", "lda_exprace", "lda_fast",
                  "lda_pallas", "lda_pallas_carry", "lda_rotate_int8",
                  "lda_planner_wire", "lda_scatter"),
    "serve.kmeans_assign": ("serve_kmeans", "serve_kmeans_sustained"),
    "serve.mfsgd_topk": ("serve_mfsgd_topk", "serve_mfsgd_sustained"),
    "svm.train": ("svm", "svm_sv_bf16", "svm_sv_int8", "svm_x_bf16"),
    "svm.train_pallas": ("svm_kernel_pallas",),
    "wdamds.smacof": ("wdamds", "wdamds_coord_bf16",
                      "wdamds_coord_int8", "wdamds_delta_bf16"),
    "wdamds.smacof_pallas": ("wdamds_dist_pallas",),
    "rf.grow": ("rf", "rf_dense_hist", "rf_scatter_hist"),
    "rf.grow_pallas": ("rf_hist_pallas",),
    "subgraph.count": ("subgraph", "subgraph_csr32", "subgraph_pl",
                       "subgraph_onehot", "subgraph_1m",
                       "subgraph_1m_onehot"),
    "collective.reshard": (), "collective.reshard_wire": (),
    "elastic.regather": (),
    "ring_attention": (), "rotate.pipeline_chunked": (),
    "serve.lda_infer": (), "serve.mlp_logits": (),
    "serve.rf_vote": (), "serve.svm_scores": (),
}


def price_sheet(program: str, sheet: dict, topo) -> Price:
    """Price one program's byte sheet: the wire term summed over every
    collective site (amplification-folded, "keep" schedule — fail
    closed like the planner) plus the per-dispatch overheads.  Compute
    and memory are 0 here: a byte sheet knows wires, not FLOPs — the
    config models above carry those."""
    wire = 0.0
    for e in sheet.get("collectives") or []:
        amped = int(e["per_shard_bytes"]) * max(
            int(e.get("amplification") or 1), 1)
        wire += wire_cost_s(topo, e["primitive"], "keep", amped)
    ovh = (CALIBRATED_OVERHEADS["dispatch_s"]
           + CALIBRATED_OVERHEADS["readback_s"])
    return Price(program, "program_runs_per_sec", 0.0, 0.0, wire, ovh)


def model_row(p: Price, topo, *, program: str | None = None,
              config: str | None = None) -> dict:
    """One serializable ``kind: "model"`` record (invariant 12 shape;
    the caller stamps provenance via metrics.benchmark_json)."""
    terms = {k: round(v, 12) for k, v in p.terms().items()}
    return {
        "kind": "model",
        "program": program,
        "config": config,
        "configs": sorted(PROGRAM_CONFIGS.get(program, ()))
        if program else ([config] if config else []),
        "topology": topo.name,
        "rates_source": topo.rates_source,
        "metric": p.metric,
        "predicted_s": round(sum(terms.values()), 12),
        "predicted_rate": round(p.predicted_rate, 4),
        "bound": max(BOUNDS, key=lambda b: terms[f"{b}_s"]),
        "terms": terms,
    }


# ---------------------------------------------------------------------------
# Candidate ranking (the sprint-pruning input)
# ---------------------------------------------------------------------------

def rank_candidates(pairs: dict, topo, rows: dict | None = None) -> dict:
    """Predicted speedup per flip candidate: ``pairs`` maps candidate →
    incumbent config (the flip_decision CANDIDATES surface); returns
    {candidate: speedup} for every pair the model can price, pricing
    both sides at the SAME shape (the incumbent's committed row when
    ``rows`` has one, else the graded full shape).  Unpriceable
    candidates are simply absent — the caller must report them, not
    guess."""
    out = {}
    for cand, inc in pairs.items():
        if cand not in CONFIG_MODELS or inc not in CONFIG_MODELS:
            continue
        shape = (rows or {}).get(inc)
        t_inc = price(inc, shape, topo).predicted_s
        t_cand = price(cand, shape, topo).predicted_s
        out[cand] = round(t_inc / t_cand, 4)
    return out


# ---------------------------------------------------------------------------
# VMEM pre-sizer
# ---------------------------------------------------------------------------

def presize(kernel: str, **shape) -> dict:
    """Pick a new-silicon-safe tile for a registered Pallas kernel —
    the thing the 2026-08-01 window calibrated by hand off an OOM.

    Consults the kernel's OWN VMEM byte model (one source of truth:
    ``kmeans_kernel._tile_rows_int8``'s OOM-calibrated algebra, the
    mfsgd kernel's resident-H budget) for which tiles FIT, then ranks
    the fitting tiles with the cost model and returns the predicted
    fastest.  Pinned against the measured evidence: 8000 rows for the
    int8 kmeans kernel at the graded shape, 256×256 for MF-SGD
    (tests/test_perfmodel.py).
    """
    if kernel == "kmeans.partials_int8":
        from harp_tpu.ops.kmeans_kernel import _tile_rows_int8

        n, d, k = shape["n"], shape["d"], shape["k"]
        kp = _lane_pad(k)
        tn = _tile_rows_int8(n, d, kp)
        if tn is None:
            return {"kernel": kernel, "tile": None,
                    "reason": "no sublane-aligned tile fits the "
                              "calibrated VMEM budget"}
        # the fused model is monotone in tile (reload amortization), so
        # the largest fitting tile is also the predicted fastest
        return {"kernel": kernel, "tile": tn, "vmem_model":
                "kmeans_kernel._tile_rows_int8 (OOM-calibrated "
                "2026-08-01)"}
    if kernel == "mfsgd.sgd_tile_update":
        rank = shape.get("rank", 64)
        # the kernel holds ONE rotation half-slice of H resident (the
        # chunked rotator hands it 1/(nw * rotate_chunks) of the items)
        ib = shape.get("i_shard") or (
            shape.get("n_items", 26_744)
            // (shape.get("num_workers", 1)
                * shape.get("rotate_chunks", 2)))
        if 2 * ib * rank * 4 > 10 << 20:
            return {"kernel": kernel, "tile": None,
                    "reason": "resident H half-slice exceeds the 10 MB "
                              "VMEM budget; shard over more workers"}
        fits = [t for t in (1024, 512, 256, 128)
                if t % 128 == 0 and 4 * rank * t * 4 + 2 * ib * rank * 4
                <= 14 << 20]
        best = min(fits, key=lambda t: price(
            "mfsgd_pallas", {"tile": t, **shape}).predicted_s)
        return {"kernel": kernel, "tile": best,
                "fits": fits, "vmem_model":
                "mfsgd_kernel resident-H + scratch budget"}
    if kernel == "svm.kernel_row":
        from harp_tpu.ops import svm_kernel

        d = shape["d"]
        xsize = 2 if shape.get("x_dtype") == "bf16" else 4
        fits = svm_kernel.fit_tiles(d, xsize)
        if not fits:
            return {"kernel": kernel, "tile": None,
                    "reason": "no lane-aligned sample tile fits the "
                              "VMEM budget; use algo='xla'"}
        row = {"tile": None, "n": shape.get("n"), "d": d,
               "num_workers": shape.get("num_workers")}
        best = min(fits, key=lambda t: price(
            "svm_kernel_pallas", {**row, "tile": t}).predicted_s)
        return {"kernel": kernel, "tile": best, "fits": fits,
                "vmem_model": "svm_kernel.vmem_bytes (analytic, "
                              "2026-08-06 — unmeasured)"}
    if kernel == "wdamds.smacof_dist":
        from harp_tpu.ops import wdamds_kernel

        n = shape["n"]
        dsize = 2 if shape.get("delta_dtype") == "bf16" else 4
        fits = wdamds_kernel.fit_tiles(n, dsize)
        if not fits:
            return {"kernel": kernel, "tile": None,
                    "reason": "no row tile fits the [tn, N] working set "
                              "under the VMEM budget; use algo='xla' or "
                              "shard over more workers"}
        row = {"tile": None, "n": n, "dim": shape.get("dim"),
               "num_workers": shape.get("num_workers")}
        best = min(fits, key=lambda t: price(
            "wdamds_dist_pallas", {**row, "tile": t}).predicted_s)
        return {"kernel": kernel, "tile": best, "fits": fits,
                "vmem_model": "wdamds_kernel.vmem_bytes (analytic, "
                              "2026-08-06 — unmeasured)"}
    if kernel == "rf.hist_bins":
        from harp_tpu.ops import rf_kernel

        f, bins = shape["f"], shape["n_bins"]
        classes = int(shape.get("n_classes", 2))
        depth = int(shape.get("depth", 6))
        fB = f * bins
        # the deepest grown level holds the most node-classes resident:
        # 2^(depth-1) nodes × C labels, sublane-padded
        nodeCp = 8 * -(-(2 ** (depth - 1) * classes) // 8)
        fits = rf_kernel.fit_tiles(fB, nodeCp)
        if not fits:
            return {"kernel": kernel, "tile": None,
                    "reason": "no sample tile fits fB plus the deepest "
                              "level's histogram under the VMEM budget; "
                              "use hist_algo='dense'"}
        row = {"tile": None, "n": shape.get("n"), "features": f,
               "n_bins": bins, "n_classes": classes, "depth": depth,
               "num_workers": shape.get("num_workers")}
        best = min(fits, key=lambda t: price(
            "rf_hist_pallas", {**row, "tile": t}).predicted_s)
        return {"kernel": kernel, "tile": best, "fits": fits,
                "vmem_model": "rf_kernel.vmem_bytes (analytic, "
                              "2026-08-06 — unmeasured)"}
    raise KeyError(f"no pre-size model for kernel {kernel!r} — register "
                   "one here when the kernel lands (see module doc)")
