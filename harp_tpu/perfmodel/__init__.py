"""Predictive performance observatory (PR 13).

An offline cost model over the landed evidence planes — CommGraph byte
sheets (PR 9), topology link rates (PR 11), roofline work models,
kernel-registry shapes, and calibrated flight-recorder deltas — that
prices every config, grades itself against the committed bench rows,
and prunes relay sprints (``measure_all.py --predicted-top``).  See
:mod:`harp_tpu.perfmodel.model` for the model and its additive-roofline
rationale, :mod:`harp_tpu.perfmodel.grade` for the self-grading
contract (``grade.grade()`` — the function keeps its module's name, so
the package re-exports it as :func:`grade_evidence`).
"""

from harp_tpu.perfmodel import grade, model  # noqa: F401
from harp_tpu.perfmodel.model import (  # noqa: F401
    BOUNDS, CONFIG_MODELS, FULL_SHAPES, PROGRAM_CONFIGS, RATES_SOURCES,
    Price, model_row, presize, price, price_sheet, rank_candidates,
    wire_cost_s,
)
from harp_tpu.perfmodel.grade import (  # noqa: F401
    DEAD_BAND, FAMILY_PAIRS, MAGNITUDE_TOL, RANK_FLOOR, SWEEPS,
)

grade_evidence = grade.grade
