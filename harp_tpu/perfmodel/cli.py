"""``python -m harp_tpu predict`` — price configs and programs offline.

Three modes, all CPU-only (a *predictor* must never touch — or hang on
— the relay, exactly like the lint and plan CLIs):

- default / ``--json``: one provenance-stamped ``kind: "model"`` row
  per registered byte-sheet program (the CommGraph extraction the lint
  row ships, priced wire+overhead) AND one per priceable config (full
  compute/memory/wire/overhead breakdown at the graded shape) —
  ``scripts/check_jsonl.py`` invariant 12 validates every row.
- ``--top N``: the flip-candidate ranking (predicted speedup over each
  candidate's incumbent) that ``measure_all.py --predicted-top`` maps
  onto ``--only``; unpriceable candidates are listed loudly, never
  silently dropped.
- ``--grade``: replay the model against ALL committed BENCH_local /
  FLIP_DECISIONS / SWEEP_pallas evidence it can price; exit 1 with the
  term breakdowns on any disagreement (the honesty gate — see
  :mod:`harp_tpu.perfmodel.grade`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _topology(name: str):
    from harp_tpu import plan as P

    if name == "auto":
        return P.detect()
    if name == "single_chip":
        return P.single_chip()
    if name == "sim_ring_8":
        return P.sim_ring(8)
    if name == "v4_32":
        return P.v4_32()
    raise ValueError(name)


def candidate_ranking(topo, bench_rows=None) -> tuple:
    """(ranked [(candidate, speedup)...] desc, unpriced [names...]) over
    the grading harness's family table."""
    from harp_tpu.perfmodel import grade as G
    from harp_tpu.perfmodel import model as M

    pairs = {c: inc for c, (inc, _, _) in G.FAMILY_PAIRS.items()}
    speedups = M.rank_candidates(pairs, topo, bench_rows)
    ranked = sorted(speedups.items(), key=lambda kv: (-kv[1], kv[0]))
    unpriced = sorted(set(pairs) - set(speedups))
    return ranked, unpriced


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m harp_tpu predict",
        description="offline predictive cost model over the byte "
                    "sheets, rooflines, and calibrated flight deltas "
                    "(ranking model; self-graded against the committed "
                    "bench rows)")
    p.add_argument("--topology",
                   choices=("auto", "single_chip", "sim_ring_8", "v4_32"),
                   default="v4_32",
                   help="price list to predict against (default: the "
                        "north-star v4_32 slice — wire terms matter "
                        "there; committed evidence grades at "
                        "single_chip)")
    p.add_argument("--json", action="store_true",
                   help="print only the machine-readable rows")
    p.add_argument("--top", type=int, default=None, metavar="N",
                   help="print only the top-N flip-candidate ranking")
    p.add_argument("--grade", action="store_true",
                   help="replay the model against the committed "
                        "evidence; exit 1 on any disagreement")
    p.add_argument("--repo", default=None,
                   help="repo root for --grade evidence files "
                        "(default: cwd)")
    args = p.parse_args(argv)

    from harp_tpu.analysis.cli import _force_cpu_backend

    _force_cpu_backend()

    from harp_tpu.perfmodel import grade as G
    from harp_tpu.perfmodel import model as M

    topo = _topology(args.topology)

    if args.grade:
        repo = args.repo or os.getcwd()
        report = G.grade(repo, topo=None)  # evidence is 1x v5e
        print(json.dumps({"kind": "model_grade", "ok": report["ok"],
                          "pairs": report["pairs"],
                          "sweeps": report["sweeps"]}))
        if not report["ok"]:
            for f in report["failures"]:
                print(f"GRADE FAIL: {json.dumps(f)}", file=sys.stderr)
            return 1
        n_ok = sum(1 for e in report["pairs"]
                   if e.get("status") == "agrees")
        print(f"model grade: OK ({n_ok} ranking agreements, "
              f"{len(report['sweeps'])} sweeps, "
              f"{len(report['magnitude'])} rows in band)",
              file=sys.stderr)
        return 0

    if args.top is not None:
        bench = G.latest_tpu_rows(
            os.path.join(args.repo or os.getcwd(), "BENCH_local.jsonl"))
        ranked, unpriced = candidate_ranking(topo, bench)
        for cand, speedup in ranked[:args.top]:
            print(json.dumps({"kind": "model_rank", "candidate": cand,
                              "predicted_speedup": speedup,
                              "topology": topo.name,
                              "rates_source": topo.rates_source}))
        if unpriced:
            print(f"unpriced candidates (no cost model — measure, "
                  f"don't guess): {unpriced}", file=sys.stderr)
        return 0

    from harp_tpu.analysis import commgraph
    from harp_tpu.analysis.drivers import DRIVERS
    from harp_tpu.utils.flightrec import provenance_stamp

    # NOT metrics.benchmark_json: its top-level float rounding (4 dp)
    # would zero a nanosecond-scale predicted_s — stamp the same
    # backend/date/commit triple at full precision instead
    def emit(row):
        print(json.dumps({**row, **provenance_stamp()}), flush=True)

    # program rows: byte sheet (the same Layer-4 walk the lint row
    # ships) x topology
    for name in sorted(DRIVERS):
        fn, prog_args = DRIVERS[name]()
        graph = commgraph.extract(name, fn, prog_args)
        sheet = {"collectives": [s.row() for s in graph.sites]}
        price = M.price_sheet(name, sheet, topo)
        row = M.model_row(price, topo, program=name)
        if not args.json:
            print(f"== {name}: wire {price.wire_s:.3g}s/run "
                  f"({len(graph.sites)} sites), bound {row['bound']}")
        emit(row)

    # config rows: full compute/memory/wire/overhead breakdown
    for cfg in sorted(M.CONFIG_MODELS):
        price = M.price(cfg, None, topo)
        row = M.model_row(price, topo, config=cfg)
        if not args.json:
            t = price.terms()
            print(f"== {cfg}: {price.predicted_rate:.4g} {price.metric} "
                  f"predicted, bound {row['bound']} "
                  f"(c={t['compute_s']:.3g} m={t['memory_s']:.3g} "
                  f"w={t['wire_s']:.3g} o={t['overhead_s']:.3g})")
        emit(row)
    return 0


if __name__ == "__main__":
    sys.exit(main())
