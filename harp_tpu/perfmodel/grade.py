"""Self-grading: replay the model against the committed evidence.

The honesty layer (ROADMAP autotuning item: "validated against the
committed BENCH_local rows and the PROFILE_local traces"): before the
model is allowed to prune a relay sprint, it must agree with every
measurement this repo already paid for.  Three machine checks, all
CPU-only, all fail-closed (a row the harness cannot price is reported,
never silently skipped into a pass):

1. **Family ranking** — for every flip-candidate pair in
   :data:`FAMILY_PAIRS` whose candidate AND incumbent have committed
   full-shape TPU rows, the model's predicted winner must match the
   measured speedup direction.  Pairs whose measured speedup sits
   inside the ``DEAD_BAND`` (±10% — the flip threshold's own margin)
   are recorded as ``too_close`` and not direction-graded: the
   evidence itself calls them a coin flip.  Additionally every
   measured ``FLIP`` verdict in FLIP_DECISIONS.jsonl that the model
   can price must be predicted ≥ even — a model that would have pruned
   a measured winner is broken in the one way that costs real windows.

2. **Sweep rank correlation** — the committed knob sweeps (the
   SWEEP_pallas MF-SGD tile and LDA d_tile rows; the kmeans int8 tile
   sweep recorded in ``_tile_rows_int8``'s docstring, measured
   2026-08-01) must rank identically under the model: Spearman rho ≥
   :data:`RANK_FLOOR` per sweep.

3. **Magnitude band** — every committed full-shape TPU row the model
   prices must land within ``MAGNITUDE_TOL``× of the measured rate.  A
   ranking model is allowed to be wrong by a factor; it is not allowed
   to be wrong by three orders of magnitude and still call itself a
   model of this hardware.

``grade()`` returns a report dict; any failure flips ``ok`` to False
and carries the full term breakdown of both sides, so a wrong
prediction is diagnosable, not just wrong (tests/test_perfmodel.py
pins ``ok`` on the committed evidence — model drift fails tier-1).
"""

from __future__ import annotations

import json
import os

from harp_tpu.perfmodel import model as M

#: |measured speedup - 1| at or below this is "the evidence calls it a
#: tie" — the same 10% margin the flip rule itself uses.
DEAD_BAND = 0.10

#: predicted rate must land within this factor of the measured rate.
MAGNITUDE_TOL = 50.0

#: minimum Spearman rho per committed sweep.
RANK_FLOOR = 0.9

#: candidate -> (incumbent, metric, metric_fallback|None): the subset of
#: scripts/flip_decision.py's CANDIDATES the model can price
#: (tests/test_perfmodel.py pins each entry against that table — the
#: two must never tell different stories about who competes with whom).
FAMILY_PAIRS = {
    "mfsgd_pallas": ("mfsgd", "updates_per_sec_per_chip", None),
    "mfsgd_carry": ("mfsgd", "updates_per_sec_per_chip", None),
    "mfsgd_chunked_rotate": ("mfsgd_pallas", "updates_per_sec_per_chip",
                             None),
    "lda_exprace": ("lda", "tokens_per_sec_per_chip", None),
    "lda_fast": ("lda", "tokens_per_sec_per_chip", None),
    "lda_pallas": ("lda", "tokens_per_sec_per_chip", None),
    "lda_pallas_approx": ("lda_pallas", "tokens_per_sec_per_chip", None),
    "lda_pallas_approx_hot": ("lda_pallas_hot", "tokens_per_sec_per_chip",
                              None),
    "lda_carry": ("lda", "tokens_per_sec_per_chip", None),
    "lda_pallas_carry": ("lda_pallas", "tokens_per_sec_per_chip", None),
    "lda_rotate_int8": ("lda_pallas_carry", "tokens_per_sec_per_chip",
                        None),
    "lda_planner_wire": ("lda_pallas_carry", "tokens_per_sec_per_chip",
                         None),
    "kmeans_hier_psum": ("kmeans", "iters_per_sec", None),
    "kmeans_int8_fused": ("kmeans_int8", "iters_per_sec", None),
    "kmeans_stream_int8": ("kmeans_stream", "iters_per_sec_ex_gen",
                           "iters_per_sec"),
    "mlp_grad_bf16": ("mlp", "samples_per_sec", None),
    "mlp_grad_int8": ("mlp", "samples_per_sec", None),
    # PR 16: the attribution observatory priced the remaining half —
    # these pairs were in CANDIDATES all along but unpriceable until
    # the profile pass named their walls (H2D staging + the rf
    # hist/subgraph overflow mechanisms).
    "svm_sv_bf16": ("svm", "samples_per_sec", None),
    "svm_sv_int8": ("svm", "samples_per_sec", None),
    "svm_x_bf16": ("svm", "samples_per_sec", None),
    "wdamds_coord_bf16": ("wdamds", "iters_per_sec", None),
    "wdamds_coord_int8": ("wdamds", "iters_per_sec", None),
    "wdamds_delta_bf16": ("wdamds", "iters_per_sec", None),
    "rf_dense_hist": ("rf_scatter_hist", "trees_per_sec", None),
    # PR 17: the kernelized arms — priced from birth (presize-predicted
    # tiles, no silicon rows yet, so they report "unmeasured" until a
    # relay window runs their flip candidates).
    "svm_kernel_pallas": ("svm", "samples_per_sec", None),
    "wdamds_dist_pallas": ("wdamds", "iters_per_sec", None),
    "rf_hist_pallas": ("rf_dense_hist", "trees_per_sec", None),
    "subgraph_csr32": ("subgraph", "vertices_per_sec", None),
    "subgraph_onehot": ("subgraph_pl", "vertices_per_sec", None),
    "subgraph_1m_onehot": ("subgraph_1m", "vertices_per_sec", None),
}

#: the committed knob sweeps: name -> (config, knob, [(value, measured
#: rate)]).  The kmeans int8 points are the OOM-window sweep recorded
#: in ops/kmeans_kernel._tile_rows_int8's docstring (2026-08-01, 1M×300
#: k=100, 1× v5e); the MF-SGD/LDA tile points are cross-checked against
#: the committed SWEEP_pallas.jsonl rows by load_sweep_points.
SWEEPS = {
    "kmeans_int8_tile": ("kmeans_int8_fused",
                         [({"tile": 8000}, 557.9), ({"tile": 4000}, 537.2),
                          ({"tile": 2000}, 521.5),
                          ({"tile": 1000}, 464.9)]),
    "mfsgd_pallas_tile": ("mfsgd_pallas",
                          [({"tile": 256}, 250233874.8),
                           ({"tile": 512}, 195512085.3),
                           ({"tile": 1024}, 163255187.4),
                           ({"tile": 128}, 147271764.4)]),
    "lda_pallas_tile": ("lda_pallas",
                        [({"d_tile": 512, "w_tile": 512}, 8018332.5),
                         ({"d_tile": 256, "w_tile": 256}, 4559994.0)]),
}


def latest_tpu_rows(path: str) -> dict:
    """config -> last full-shape non-error TPU row (the same filter as
    flip_decision.latest_rows: CPU-sim speeds are explicitly
    non-predictive of TPU here and must not grade the model either)."""
    rows: dict = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                cfg = row.get("config")
                if (not cfg or row.get("smoke") or "error" in row
                        or row.get("backend") == "cpu"):
                    continue
                rows[cfg] = row
    except OSError:
        pass
    return rows


def flip_verdicts(path: str) -> dict:
    """flip_decision name -> verdict row (FLIP_DECISIONS.jsonl)."""
    out: dict = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "flip_decision" in row:
                    out[row["flip_decision"]] = row
    except OSError:
        pass
    return out


def load_sweep_points(repo: str) -> dict:
    """The declared SWEEPS, with the tile points cross-checked against
    the committed SWEEP_pallas.jsonl rows: a declared point that
    disagrees with the file it cites is itself a grading failure."""
    sweeps = {k: (cfg, list(pts)) for k, (cfg, pts) in SWEEPS.items()}
    path = os.path.join(repo, "SWEEP_pallas.jsonl")
    measured: dict = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                t = row.get("tile")
                if t is None:
                    continue
                if row.get("updates_per_sec_per_chip") is not None:
                    measured[("mfsgd_pallas_tile", t)] = float(
                        row["updates_per_sec_per_chip"])
                elif row.get("tokens_per_sec_per_chip") is not None:
                    measured[("lda_pallas_tile", t)] = float(
                        row["tokens_per_sec_per_chip"])
    except OSError:
        pass
    errors = []
    for name in ("mfsgd_pallas_tile", "lda_pallas_tile"):
        for knobs, rate in sweeps[name][1]:
            v = knobs.get("tile") or knobs.get("d_tile")
            got = measured.get((name, v))
            if got is not None and abs(got - rate) > 0.01 * rate:
                errors.append(f"{name} tile={v}: declared {rate} but "
                              f"SWEEP_pallas.jsonl says {got}")
    return {"sweeps": sweeps, "errors": errors}


def spearman(xs, ys) -> float:
    """Spearman rank correlation (no-ties case — knob sweeps)."""
    def ranks(vals):
        order = sorted(range(len(vals)), key=lambda i: vals[i])
        r = [0] * len(vals)
        for rank_, i in enumerate(order):
            r[i] = rank_
        return r

    rx, ry = ranks(xs), ranks(ys)
    n = len(xs)
    if n < 2:
        return 1.0
    d2 = sum((a - b) ** 2 for a, b in zip(rx, ry))
    return 1.0 - 6.0 * d2 / (n * (n * n - 1))


def _metric_value(row, metric, fallback):
    v = row.get(metric)
    if v is None and fallback:
        v = row.get(fallback)
    return float(v) if v is not None else None


def grade(repo: str | None = None, topo=None) -> dict:
    """Run all three checks against the committed evidence files."""
    if repo is None:
        repo = os.getcwd()
    if topo is None:
        from harp_tpu.plan.topology import single_chip

        topo = single_chip()  # every committed row is 1× v5e
    bench = latest_tpu_rows(os.path.join(repo, "BENCH_local.jsonl"))
    verdicts = flip_verdicts(os.path.join(repo, "FLIP_DECISIONS.jsonl"))
    report = {"ok": True, "pairs": [], "sweeps": [], "magnitude": [],
              "failures": []}

    def fail(msg, **detail):
        report["ok"] = False
        report["failures"].append({"what": msg, **detail})

    # 1. family ranking ----------------------------------------------------
    for cand, (inc, metric, fb) in sorted(FAMILY_PAIRS.items()):
        crow, irow = bench.get(cand), bench.get(inc)
        entry = {"candidate": cand, "incumbent": inc}
        if crow is None or irow is None:
            entry["status"] = "unmeasured"
            report["pairs"].append(entry)
            continue
        cv, iv = (_metric_value(crow, metric, fb),
                  _metric_value(irow, metric, fb))
        if not cv or not iv:
            entry["status"] = "unmeasured"
            report["pairs"].append(entry)
            continue
        measured = cv / iv
        pc, pi = price(cand, crow, topo), price(inc, irow, topo)
        predicted = pi.predicted_s / pc.predicted_s
        entry.update({"measured": round(measured, 4),
                      "predicted": round(predicted, 4),
                      "candidate_terms": pc.terms(),
                      "incumbent_terms": pi.terms()})
        if abs(measured - 1.0) <= DEAD_BAND:
            entry["status"] = "too_close"
        elif (measured > 1.0) == (predicted > 1.0):
            entry["status"] = "agrees"
        else:
            entry["status"] = "DISAGREES"
            fail(f"ranking: {cand} vs {inc} measured {measured:.3f}x "
                 f"but model predicts {predicted:.3f}x", pair=entry)
        # a measured FLIP the model would have pruned is the costly
        # failure mode — check it even when the pair re-derives it
        v = verdicts.get(cand)
        if v is not None and v.get("flip") and predicted < 1.0:
            entry["status"] = "DISAGREES"
            fail(f"verdict: {cand} FLIPPED on silicon "
                 f"({v.get('speedup')}x) but the model predicts "
                 f"{predicted:.3f}x — pruning would have dropped a "
                 "measured winner", pair=entry)
        report["pairs"].append(entry)

    # 2. sweep rank correlation --------------------------------------------
    loaded = load_sweep_points(repo)
    for err in loaded["errors"]:
        fail(f"sweep points drifted from their committed file: {err}")
    for name, (cfg, pts) in sorted(loaded["sweeps"].items()):
        meas = [r for _, r in pts]
        pred = [price(cfg, knobs, topo).predicted_rate
                for knobs, _ in pts]
        rho = spearman(meas, pred)
        entry = {"sweep": name, "config": cfg, "points": len(pts),
                 "rho": round(rho, 4),
                 "measured_rates": meas, "predicted_rates":
                 [round(p, 2) for p in pred]}
        report["sweeps"].append(entry)
        if rho < RANK_FLOOR:
            fail(f"sweep {name}: rho {rho:.3f} < floor {RANK_FLOOR}",
                 sweep=entry)

    # 3. magnitude band ----------------------------------------------------
    for cfg, row in sorted(bench.items()):
        # *_cli rows (the app CLIs' committed 2026-08-01 evidence) grade
        # through their incumbent's model (PR 16)
        cfg_model = M.CLI_ROW_ALIASES.get(cfg, cfg)
        if cfg_model not in M.CONFIG_MODELS:
            continue
        p = price(cfg_model, row, topo)
        mv = _metric_value(row, p.metric, None)
        if mv is None or mv <= 0:
            continue
        factor = max(p.predicted_rate / mv, mv / p.predicted_rate)
        entry = {"config": cfg, "measured": round(mv, 2),
                 "predicted": round(p.predicted_rate, 2),
                 "factor": round(factor, 2)}
        report["magnitude"].append(entry)
        if factor > MAGNITUDE_TOL:
            fail(f"magnitude: {cfg} predicted {p.predicted_rate:.3g} vs "
                 f"measured {mv:.3g} ({factor:.0f}x off > "
                 f"{MAGNITUDE_TOL}x)", row=entry,
                 terms=p.terms())
    return report


def price(config, row, topo):
    """Module-level alias (kept here so grade-side callers and tests
    monkeypatch one surface)."""
    return M.price(config, row, topo)
