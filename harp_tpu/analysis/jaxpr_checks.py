"""Layer 2 — jaxpr analyzers: trace on the CPU backend, zero hardware.

Two detectors over ``jax.jit(fn).trace(...).jaxpr`` (a ``ClosedJaxpr``):

**Scan-carry copy trap** (HL101) — a ``scan``/``while`` whose body both
gathers from and ``dynamic_update_slice``s the same carried array forces
XLA to copy the WHOLE table every iteration (the aliasing analysis cannot
prove the gather reads pre-update values).  This exact pattern cost LDA
20 s of a 29 s epoch before the tile-local fix (CLAUDE.md "XLA copy
trap"); the fixed form — ``dynamic_slice`` the tile first, gather
tile-locally — is clean because the gather operand is the slice result,
not the carry.  Taint propagates through dtype casts and into inner
call jaxprs (``jnp.take`` hides its gather inside a ``pjit``), but NOT
through ``dynamic_slice``: that boundary is precisely what makes the
fixed form safe.

**Oversized closed-over constant** (HL102) — arrays captured by value
into the jaxpr's ``consts`` ship as compile-time literals: over the
relay that is the HTTP-413 wall (>~50 MB) and a recompile every time the
host value changes.  The threshold defaults well below the wall so the
lint fires before the relay does.
"""

from __future__ import annotations

from typing import Any

from harp_tpu.analysis import Violation

# 1 MiB: generous for genuine epsilon tables / iota caches, far below the
# ~50 MB relay literal wall — anything bigger should be an argument
DEFAULT_CONST_BYTES = 1 << 20

_GATHER_PRIMS = frozenset({"gather", "dynamic_slice_with_gather"})
_DUS_PRIMS = frozenset({"dynamic_update_slice", "scatter", "scatter-add",
                        "scatter_add"})
# ops that forward the carried buffer itself (not a copy/slice of it)
_PASSTHROUGH_PRIMS = frozenset({"convert_element_type", "copy",
                                "optimization_barrier"})


def _is_var(v) -> bool:
    """jaxpr invars mix Vars with (unhashable) Literals; only Vars can
    carry taint."""
    return not hasattr(v, "val")


def _inner_jaxprs(eqn):
    """(param_name, jaxpr) pairs hiding inside an eqn's params."""
    out = []
    for k, v in eqn.params.items():
        core = getattr(v, "jaxpr", None)      # ClosedJaxpr
        if core is not None and hasattr(core, "eqns"):
            out.append((k, core))
        elif hasattr(v, "eqns"):              # bare Jaxpr
            out.append((k, v))
    return out


def _body_flags(jaxpr, tainted: set) -> tuple[bool, bool]:
    """(gathers_from_tainted, dus_into_tainted) over a body jaxpr,
    recursing into inner call jaxprs with positional invar mapping."""
    gathered = dused = False
    tainted = set(tainted)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        op0 = eqn.invars[0] if eqn.invars else None
        hot = op0 is not None and _is_var(op0) and op0 in tainted
        if name in _GATHER_PRIMS and hot:
            gathered = True
        elif name in _DUS_PRIMS and hot:
            dused = True
        elif name in _PASSTHROUGH_PRIMS and hot:
            tainted.add(eqn.outvars[0])
        for _, inner in _inner_jaxprs(eqn):
            if len(inner.invars) != len(eqn.invars):
                continue  # boundary with repacked args: stop the taint
            inner_taint = {iv for iv, ov in zip(inner.invars, eqn.invars)
                           if _is_var(ov) and ov in tainted}
            if inner_taint:
                g, d = _body_flags(inner, inner_taint)
                gathered |= g
                dused |= d
    return gathered, dused


def _eqn_loc(eqn) -> str:
    """Best-effort user frame of an eqn (for the violation message)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{frame.file_name}:{frame.start_line}"
    except Exception:
        pass
    return "?"


def find_scan_copy_traps(closed_jaxpr, target: str = "jaxpr"
                         ) -> list[Violation]:
    """HL101 over every scan/while (at any nesting depth) in a traced
    program.  ``target`` labels the program in the violation's path."""
    out: list[Violation] = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                nc = eqn.params["num_consts"]
                ncarry = eqn.params["num_carry"]
                carries = set(body.invars[nc:nc + ncarry])
                _flag(eqn, body, carries)
            elif name == "while":
                body = eqn.params["body_jaxpr"].jaxpr
                nconsts = eqn.params.get("body_nconsts", 0)
                carries = set(body.invars[nconsts:])
                _flag(eqn, body, carries)
            # nested scans are reached here too: a scan's body jaxpr is
            # one of its param jaxprs
            for _, inner in _inner_jaxprs(eqn):
                walk(inner)

    def _flag(eqn, body, carries):
        # per-carry attribution: one finding per carried buffer that is
        # both gathered from and updated in place
        for c in carries:
            g, d = _body_flags(body, {c})
            if g and d:
                out.append(Violation(
                    "HL101", f"{target}", 0,
                    f"scan/while body at {_eqn_loc(eqn)} gathers from AND "
                    f"dynamic_update_slices the same carried array "
                    f"{c.aval.str_short()} — XLA will copy the whole "
                    "table every iteration; dynamic_slice the tile "
                    "first, gather tile-locally"))

    walk(closed_jaxpr.jaxpr)
    return out


def find_large_constants(closed_jaxpr, target: str = "jaxpr",
                         threshold_bytes: int = DEFAULT_CONST_BYTES
                         ) -> list[Violation]:
    """HL102: closed-over array constants above ``threshold_bytes``."""
    out: list[Violation] = []
    for c in closed_jaxpr.consts:
        nbytes = getattr(c, "nbytes", 0)
        if nbytes and nbytes > threshold_bytes:
            shape = getattr(c, "shape", ())
            dtype = getattr(c, "dtype", "?")
            out.append(Violation(
                "HL102", target, 0,
                f"closed-over constant {dtype}{list(shape)} = "
                f"{nbytes / (1 << 20):.1f} MiB ships as a compile-time "
                f"literal (threshold {threshold_bytes >> 20} MiB; the "
                "relay rejects >~50 MB with HTTP 413) — pass it as an "
                "argument via device_put/shard_array"))
    return out


def trace_for_analysis(fn, *args, **kwargs) -> Any:
    """``jax.jit(fn).trace(*args).jaxpr`` — the one tracing entry point
    (accepts concrete arrays or ShapeDtypeStructs; runs on whatever
    backend is active — the CLI forces CPU first)."""
    import jax

    return jax.jit(fn).trace(*args, **kwargs).jaxpr


def analyze_program(fn, args, target: str,
                    threshold_bytes: int = DEFAULT_CONST_BYTES
                    ) -> list[Violation]:
    """Run both Layer-2 detectors over one traced program."""
    closed = fn.trace(*args).jaxpr if hasattr(fn, "trace") \
        else trace_for_analysis(fn, *args)
    return (find_scan_copy_traps(closed, target)
            + find_large_constants(closed, target, threshold_bytes))
