"""harplint — static relay-burner analysis for harp-tpu.

Reference parity (SURVEY.md §6): Harp has no static analysis; its
communication discipline is convention only.  This package machine-checks
the conventions (CLAUDE.md traps) in five layers — source AST lints
(:mod:`.astlints`), jaxpr analyzers (:mod:`.jaxpr_checks`), a
no-hardware Mosaic kernel audit (:mod:`.mosaic_audit`), the static
communication-graph auditor (:mod:`.commgraph`, the CommLedger
cross-check + donation audit whose per-program byte sheets ride the
lint JSON row), and the thread-root concurrency auditor
(:mod:`.threadgraph`, whose ownership map also arms the runtime twin
:mod:`harp_tpu.utils.threadguard`) — behind one rule registry
(:mod:`.rules`), one committed
allowlist (``analysis/allowlist.toml``), and one CLI
(``python -m harp_tpu lint``, :mod:`.cli`).

The core currency is :class:`Violation`: every layer emits them, the
allowlist suppresses reviewed exceptions, and the CLI renders the rest as
a human report plus one provenance-stamped ``kind: "lint"`` JSON line
(validated by ``scripts/check_jsonl.py`` invariant 6).
"""

from __future__ import annotations

import dataclasses

from harp_tpu.analysis.rules import RULES, Rule, rule_ids


@dataclasses.dataclass
class Violation:
    """One finding.  ``path`` is repo-relative for source findings, a
    pseudo-path (``kernel:<name>``, ``driver:<name>``) for traced ones —
    allowlist entries match on it either way."""

    rule: str
    path: str
    line: int
    message: str
    source: str = ""     # the offending source line / jaxpr snippet

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{loc}: {self.rule} {self.message}"
        if self.source:
            out += f"\n    {self.source.strip()}"
        return out


__all__ = ["Violation", "Rule", "RULES", "rule_ids"]
