"""Committed allowlist — intentional lint exceptions, explicit and reviewed.

``analysis/allowlist.toml`` (next to this module) holds one ``[[allow]]``
table per exception:

.. code-block:: toml

    [[allow]]
    rule = "HL001"
    path = "harp_tpu/parallel/mesh.py"
    match = "lax.psum(1, axis_name)"   # optional line-content anchor
    reason = "old-jax axis_size shim; psum(1) is the documented fallback"

``rule`` + ``path`` are required and must match the violation exactly;
``match`` (optional) additionally requires the flagged source line to
contain the substring — entries stay pinned to the code they excuse even
as line numbers drift.  ``reason`` is required: an allowlist entry
without a justification is itself a violation of the review contract, so
loading fails loudly.  Entries that match nothing are reported as stale
by the CLI (``--prune`` lists them) so the file cannot silently rot.
"""

from __future__ import annotations

import os

from harp_tpu.analysis import Violation

try:
    import tomllib as _toml
except ModuleNotFoundError:  # pragma: no cover - py<3.11 (this image)
    import tomli as _toml

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "allowlist.toml")


class AllowlistError(ValueError):
    """Malformed allowlist file (missing rule/path/reason)."""


def load(path: str | None = None) -> list[dict]:
    path = path or DEFAULT_PATH
    if not os.path.exists(path):
        return []
    with open(path, "rb") as fh:
        data = _toml.load(fh)
    entries = data.get("allow", [])
    for i, e in enumerate(entries):
        for field in ("rule", "path", "reason"):
            if not e.get(field):
                raise AllowlistError(
                    f"{os.path.basename(path)}: [[allow]] entry #{i + 1} "
                    f"missing required field {field!r} — every exception "
                    "needs a rule, a path, and a one-line justification")
        e.setdefault("_hits", 0)
    return entries


def matches(entry: dict, v: Violation) -> bool:
    if entry["rule"] != v.rule or entry["path"] != v.path:
        return False
    m = entry.get("match")
    return m is None or m in (v.source or "")


def apply(violations: list[Violation], entries: list[dict]
          ) -> tuple[list[Violation], list[Violation], list[dict]]:
    """(kept, suppressed, stale_entries) — entries count their hits so
    stale ones (matched nothing this run) can be reported."""
    kept: list[Violation] = []
    suppressed: list[Violation] = []
    for v in violations:
        hit = None
        for e in entries:
            if matches(e, v):
                hit = e
                break
        if hit is None:
            kept.append(v)
        else:
            hit["_hits"] += 1
            suppressed.append(v)
    stale = [e for e in entries if e["_hits"] == 0]
    return kept, suppressed, stale
