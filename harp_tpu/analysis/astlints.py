"""Layer 1 — source AST lints (pure ``ast``, no jax import).

Each checker encodes one CLAUDE.md trap (see :mod:`harp_tpu.analysis.rules`
for the id → trap map).  Everything here is static text analysis: the
whole repo lints in well under a second, so tier-1 runs it on every test
invocation and the lint CLI runs it with no backend at all.

Scoping is per rule, not per run: raw-collective calls are legal inside
the verb layer itself (``parallel/collective.py`` + ``parallel/rotate.py``),
``PRNGKey`` is legal inside the helper that wraps it (``utils/prng.py``),
and the flight-tracking rule only binds the driver layer
(``harp_tpu/models/``).  Intentional exceptions elsewhere go in
``analysis/allowlist.toml`` with a reviewed one-line justification —
never in code.
"""

from __future__ import annotations

import ast
import os
import re

from harp_tpu.analysis import Violation

# the data-moving XLA collectives the verb layer wraps; axis_index /
# axis_size are topology queries, not collectives, and stay legal
RAW_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle",
    "psum_scatter", "all_gather", "all_to_all",
})

# files where each rule does NOT apply (repo-relative, forward slashes)
HL001_EXEMPT = ("harp_tpu/parallel/collective.py",
                "harp_tpu/parallel/rotate.py")
HL002_EXEMPT = ("harp_tpu/utils/prng.py",)
HL004_SCOPE = ("harp_tpu/models/",)
HL005_SCOPE = ("harp_tpu/",)

# transfer entry points whose wrapping legitimizes a jnp.asarray (the
# array lands on device through a counted H2D path, not a jit literal)
_DEVICE_PUT_FUNCS = frozenset({"device_put", "shard_array",
                               "shard_array_local"})

# perf-claim shape: a measured rate ("246.5M ups/s", "2.45 ms/iter",
# "30-40 MB/s") or an explicit speedup-vs claim ("2.97× dense")
_PERF_RE = re.compile(
    r"\d[\d,.]*\s*[kKMG]?\s*"
    r"(?:iter|tok|ups|updates|points?|pts|rows|GB|MB)\s*/\s*(?:s\b|sec\b)"
    r"|\d[\d.,]*\s*ms\s*/\s*(?:iter|epoch|call)"
    # the repo writes measured speedups with the multiplication sign
    # ("2.97× dense"); ascii "1.6x the nonzeros" prose stays unflagged
    r"|\d[\d.]*\s*×\s*(?:dense|the|vs|faster|speedup|XLA)")
_DATE_RE = re.compile(r"20\d\d-\d\d-\d\d")
_CHIP_RE = re.compile(r"\bv[2-6][ep]?(?:-\d+)?\b|\bCPU\b|\bcpu\b|\bTPU\b"
                      r"|\bchip\b|\bhost\b|\brelay\b")


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an Attribute/Name chain ("jax.lax.psum"), or ""."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _subtree_mentions_numpy(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in ("np", "numpy"):
            return True
    return False


class _Linter:
    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.violations: list[Violation] = []

    # -- helpers -----------------------------------------------------------
    def _src(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return self.lines[ln - 1] if 0 < ln <= len(self.lines) else ""

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.violations.append(Violation(
            rule, self.relpath, getattr(node, "lineno", 0), msg,
            self._src(node)))

    def _ancestors(self, node: ast.AST):
        n = self.parents.get(node)
        while n is not None:
            yield n
            n = self.parents.get(n)

    def _in_call_to(self, node: ast.AST, names: frozenset[str]) -> bool:
        """Is ``node`` somewhere inside a Call whose callee's last dotted
        component is in ``names``?  (e.g. jax.device_put(jnp.asarray(x)))"""
        for anc in self._ancestors(node):
            if isinstance(anc, ast.Call):
                chain = _attr_chain(anc.func)
                if chain and chain.split(".")[-1] in names:
                    return True
        return False

    def _returned(self, node: ast.AST) -> bool:
        for anc in self._ancestors(node):
            if isinstance(anc, ast.Return):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False

    def _scoped(self, prefixes) -> bool:
        return any(self.relpath.startswith(p) for p in prefixes)

    def _exempt(self, files) -> bool:
        return self.relpath in files

    # -- the rules ---------------------------------------------------------
    def run(self) -> list[Violation]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                self._check_call(node)
        if self._scoped(HL005_SCOPE):
            self._check_docstrings()
        return self.violations

    def _check_call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        last = chain.split(".")[-1] if chain else ""

        if (last in RAW_COLLECTIVES and ".lax." in f".{chain}"
                and not self._exempt(HL001_EXEMPT)):
            self._emit("HL001", node,
                       f"raw lax.{last} outside the collective verb layer "
                       "— route through harp_tpu.parallel.collective so "
                       "CommLedger coverage stays total")

        if last == "PRNGKey" and not self._exempt(HL002_EXEMPT):
            self._emit("HL002", node,
                       "jax.random.PRNGKey specializes the program on the "
                       "seed (~140 ms recompile per seed over the relay) "
                       "— use utils.prng.key_bits / split_keys")

        if (last == "asarray" and chain in ("jnp.asarray",
                                            "jax.numpy.asarray")
                and node.args
                and _subtree_mentions_numpy(node.args[0])
                and not self._in_call_to(node, _DEVICE_PUT_FUNCS)):
            self._emit("HL003", node,
                       "jnp.asarray on host numpy data can bake the array "
                       "into the program as a compile-time literal (HTTP "
                       "413 >~50 MB) — use jax.device_put / "
                       "mesh.shard_array")

        if (chain == "jax.jit" and self._scoped(HL004_SCOPE)
                and not self._in_call_to(node, frozenset({"track"}))
                and not self._returned(node)):
            self._emit("HL004", node,
                       "jitted driver callable not wrapped in "
                       "flightrec.track (factories that `return jax.jit("
                       "...)` are exempt: their call sites wrap) — the "
                       "dispatch/readback budgets cannot see this program")

    def _check_docstrings(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            doc = ast.get_docstring(node, clean=False)
            if not doc or not _PERF_RE.search(doc):
                continue
            missing = []
            if not _DATE_RE.search(doc):
                missing.append("date (YYYY-MM-DD)")
            if not _CHIP_RE.search(doc):
                missing.append("chip (e.g. 1× v5e / CPU)")
            if missing:
                where = (node.body[0] if not isinstance(node, ast.Module)
                         else node.body[0])
                name = getattr(node, "name", "<module>")
                self._emit("HL005", where,
                           f"docstring of {name} carries a perf claim but "
                           f"no {' or '.join(missing)} — perf numbers "
                           "must be re-auditable (CLAUDE.md conventions)")


def lint_source(relpath: str, text: str) -> list[Violation]:
    """Lint one file's source.  ``relpath`` decides rule scoping."""
    try:
        return _Linter(relpath, text).run()
    except SyntaxError as e:
        return [Violation("HL000", relpath, e.lineno or 0,
                          f"unparseable Python: {e.msg}")]


# default scan set: library + drivers + tooling; tests are reference/golden
# code (PRNGKey as the equivalence oracle etc.) and lint their own fixtures
DEFAULT_ROOTS = ("harp_tpu", "scripts", "examples",
                 "bench.py", "__graft_entry__.py")


def iter_python_files(repo: str, roots=DEFAULT_ROOTS):
    for root in roots:
        p = os.path.join(repo, root)
        if os.path.isfile(p):
            yield root
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.relpath(os.path.join(dirpath, fn),
                                              repo).replace(os.sep, "/")


def lint_paths(repo: str, relpaths=None) -> list[Violation]:
    """Lint ``relpaths`` (default: the whole default scan set)."""
    out: list[Violation] = []
    for rel in (relpaths if relpaths is not None
                else iter_python_files(repo)):
        with open(os.path.join(repo, rel), encoding="utf-8") as fh:
            out.extend(lint_source(rel, fh.read()))
    return out
