"""Layer 3 — Mosaic kernel audit: no hardware, two complementary checks.

**Cross-platform lowering** (HL201): each kernel in
:mod:`harp_tpu.ops.kernel_registry` is traced and lowered with
``lowering_platforms=("tpu",)`` on the CPU backend — the full
Pallas→Mosaic pass (block-shape rules, missing primitives, unsupported
casts) that caught three relay-burners on 2026-07-31 without a chip.

**Silicon-limit jaxpr checks** (HL202/HL203/HL204): the REAL toolchain
enforces rules the local Mosaic pass does not — ``pltpu.prng_seed``
accepts at most TWO seed words on silicon (the 2026-08-01 in-window
failure: 3 words lowered fine locally, failed the relay compile), Mosaic
has no uint32→f32 cast, and block dim −2 must be a multiple of 8 or the
full array dim.  These are checked by walking the traced jaxpr's
``pallas_call`` eqns directly, so they fire even where local lowering
stays green.

Both run over the same trace, so one registry sweep audits everything.
"""

from __future__ import annotations

from typing import Any

from harp_tpu.analysis import Violation

_MAX_PRNG_SEED_WORDS = 2  # silicon limit, 2026-08-01


def _walk_jaxprs(jaxpr):
    """Yield (eqn, enclosing_jaxpr) for every eqn at any nesting depth."""
    for eqn in jaxpr.eqns:
        yield eqn, jaxpr
        for v in eqn.params.values():
            core = getattr(v, "jaxpr", None)
            if core is not None and hasattr(core, "eqns"):
                yield from _walk_jaxprs(core)
            elif hasattr(v, "eqns"):
                yield from _walk_jaxprs(v)


def _block_shape(bm) -> tuple:
    shape = getattr(bm, "block_shape", ()) or ()
    return tuple(d if isinstance(d, int) else None for d in shape)


def check_kernel_jaxpr(closed_jaxpr, target: str) -> list[Violation]:
    """HL202/HL203/HL204 over one traced program's pallas_call eqns."""
    out: list[Violation] = []
    for eqn, _ in _walk_jaxprs(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name == "prng_seed" and len(eqn.invars) > _MAX_PRNG_SEED_WORDS:
            out.append(Violation(
                "HL202", target, 0,
                f"pltpu.prng_seed called with {len(eqn.invars)} seed "
                f"words — the real TPU toolchain accepts at most "
                f"{_MAX_PRNG_SEED_WORDS} ('Setting seed with more than 2 "
                "values is not supported', silicon 2026-08-01); fold "
                "extra stream ids into a word with an odd-constant "
                "multiply + xor"))
        if name == "convert_element_type":
            import jax.numpy as jnp

            src = getattr(eqn.invars[0], "aval", None)
            dst = eqn.params.get("new_dtype")
            if (src is not None and dst is not None
                    and jnp.dtype(src.dtype) == jnp.dtype(jnp.uint32)
                    and jnp.issubdtype(jnp.dtype(dst), jnp.floating)):
                out.append(Violation(
                    "HL203", target, 0,
                    "uint32→float cast — Mosaic has no such lowering on "
                    "TPU; shift_right_logical on int32 instead (see "
                    "ops/lda_kernel.py's prng-bits→uniform idiom)"))
        if name == "pallas_call":
            out.extend(_check_block_shapes(eqn, target))
    return out


def _check_block_shapes(eqn, target: str) -> list[Violation]:
    out: list[Violation] = []
    gm = eqn.params.get("grid_mapping")
    mappings = getattr(gm, "block_mappings", ()) if gm is not None else ()
    for bm in mappings:
        bs = _block_shape(bm)
        if len(bs) < 2 or bs[-2] is None:
            continue
        arr = getattr(getattr(bm, "array_shape_dtype", None), "shape", None)
        full = arr[-2] if arr is not None and len(arr) >= 2 else None
        if bs[-2] % 8 != 0 and bs[-2] != full:
            origin = getattr(bm, "origin", "?")
            out.append(Violation(
                "HL204", target, 0,
                f"pallas block_shape {bs} for {origin}: dim -2 = "
                f"{bs[-2]} is neither a multiple of 8 (sublanes) nor "
                f"the full array dim ({full}) — fails the real Mosaic "
                "layout rules"))
    return out


def audit_kernel(name: str, fn, args) -> list[Violation]:
    """Trace + silicon checks + full Mosaic lowering for one kernel."""
    import jax

    target = f"kernel:{name}"
    try:
        traced = jax.jit(fn).trace(*args)
    except Exception as e:  # noqa: BLE001 - any trace failure is a finding
        return [Violation("HL201", target, 0,
                          f"kernel failed to trace: {type(e).__name__}: "
                          f"{e}")]
    out = check_kernel_jaxpr(traced.jaxpr, target)
    try:
        text = traced.lower(lowering_platforms=("tpu",)).as_text()
        if "tpu_custom_call" not in text:
            out.append(Violation(
                "HL201", target, 0,
                "lowered program contains no tpu_custom_call — the "
                "Pallas kernel fell out of the compiled path (interpret "
                "mode leaked in?)"))
    except Exception as e:  # noqa: BLE001
        out.append(Violation(
            "HL201", target, 0,
            f"Pallas→Mosaic lowering failed on the CPU backend: "
            f"{type(e).__name__}: {e}"))
    return out


def _declared_vmem_models() -> dict[str, int]:
    """Kernel-name → the kernel's OWN byte model evaluated at the
    registry's registered shape — the cross-check source for HL205.

    Only kernels exposing an analytic scoped-VMEM function participate;
    shapes mirror the registry builders' comments (a registry shape
    change must update BOTH or the audit fires, which is the point)."""
    from harp_tpu.ops import (kmeans_kernel, rf_kernel, svm_kernel,
                              wdamds_kernel)

    return {
        # tn=128, d=256, kp=128 (kmeans.partials_int8 builder shape)
        "kmeans.partials_int8": kmeans_kernel.vmem_bytes_int8(128, 256,
                                                              128),
        # dp=128, tn=128, xsize=4 (f32 operand)
        "svm.kernel_row": svm_kernel.vmem_bytes(128, 128, 4),
        # dimp=128, N=256, tn=32, dsize=4
        "wdamds.smacof_dist": wdamds_kernel.vmem_bytes(128, 256, 32, 4),
        # tn=128, fB=512, nodeCp=8
        "rf.hist_bins": rf_kernel.vmem_bytes(128, 512, 8),
    }


def check_work_declarations() -> list[Violation]:
    """HL205 — registry ``vmem_bytes`` declarations vs the kernels' own
    byte models.  A declaration must sit within ``memrec.PRESIZE_BAND``
    of the model at the registered shape (stale = mis-priced sprints
    AND a lying memrec VMEM gate) and under the 16 MB/core ceiling."""
    from harp_tpu.ops.kernel_registry import KERNEL_WORK
    from harp_tpu.utils import memrec

    out: list[Violation] = []
    for name, model in sorted(_declared_vmem_models().items()):
        work = KERNEL_WORK.get(name)
        if work is None:
            out.append(Violation(
                "HL205", f"kernel:{name}", 0,
                "kernel has an analytic VMEM byte model but no registry "
                "entry — register it (kernel_registry.py) so the audit "
                "and the perfmodel see one source of truth"))
            continue
        declared = work["vmem_bytes"]
        if not model <= declared <= model * memrec.PRESIZE_BAND:
            out.append(Violation(
                "HL205", f"kernel:{name}", 0,
                f"registry vmem_bytes={declared} is stale against the "
                f"kernel's own byte model ({model} B at the registered "
                f"shape; allowed band [{model}, "
                f"{int(model * memrec.PRESIZE_BAND)}]) — re-derive the "
                "declaration (perfmodel.presize) when the kernel "
                "changes"))
        if declared > memrec.VMEM_CEILING:
            out.append(Violation(
                "HL205", f"kernel:{name}", 0,
                f"registry vmem_bytes={declared} exceeds the "
                f"{memrec.VMEM_CEILING >> 20} MB/core VMEM ceiling — "
                "the registered shape itself cannot launch"))
    return out


def audit_registry(names: list[str] | None = None) -> list[Violation]:
    """Audit every registered kernel (or the named subset).  A full
    sweep (names=None) also cross-checks the registry work declarations
    against the kernels' own byte models (HL205)."""
    from harp_tpu.ops.kernel_registry import KERNELS

    out: list[Violation] = []
    for name in sorted(KERNELS if names is None else names):
        try:
            fn, args = KERNELS[name]()
        except Exception as e:  # noqa: BLE001 - a broken builder is loud
            out.append(Violation("HL201", f"kernel:{name}", 0,
                                 f"kernel builder failed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        out.extend(audit_kernel(name, fn, args))
    if names is None:
        out.extend(check_work_declarations())
    return out


def registered_kernels() -> list[str]:
    from harp_tpu.ops.kernel_registry import KERNELS

    return sorted(KERNELS)
