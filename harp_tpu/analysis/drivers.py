"""Registered driver programs for the Layer-2 jaxpr and Layer-4
CommGraph sweeps.

The copy-trap / literal detectors (:mod:`harp_tpu.analysis.jaxpr_checks`)
and the communication auditor (:mod:`harp_tpu.analysis.commgraph`) need
*traced programs* to walk.  This registry builds the flagship driver
programs at small proven shapes on the active (CPU-forced) backend —
mirroring how the lowering tests pin them — so ``python -m harp_tpu
lint`` sweeps real epoch programs, not just synthetic fixtures:

- ``kmeans.fit`` — the full T-iteration Lloyd program (fori_loop body:
  the dense one-hot pattern, no gathers; its hand-computed allreduce
  byte sheet is the Layer-4 HL302 cross-check fixture);
- ``ring_attention`` — the rotate-scan K/V pipeline (a scan that carries
  and *reads* buffers every step: the structural cousin of the LDA trap
  that must stay clean);
- ``mfsgd.epoch`` — the rotation epoch with dynamic_update_slice'd
  factor tables: the closest in-tree relative of the pre-fix LDA
  copy-trap, pinned clean;
- ``serve.*`` — every serving engine's batched step at one ladder rung
  (the steady-state programs the budget guard pins);
- ``rotate.pipeline_chunked`` — PR 2's generic software double buffer
  (n_chunks=2, the former bespoke two-halves schedule);
- ``ingest.accum_chunk`` / ``ingest.finish_epoch`` — the program pair
  every IngestPipeline-shipped kmeans chunk rides: per-chunk accumulate
  (deliberately collective-free — registering it pins that emptiness in
  the byte sheet) and the epoch-end allreduce;
- ``elastic.regather`` — PR 15's mid-run state move (one all_gather
  over the reshard verb + a wire-free local gather), so an elastic
  rebalance's cost stays on the byte sheet.

Builders return ``(traced_fn_or_fn, args)``; args may be concrete arrays
or sharded ``ShapeDtypeStruct``s.  Each runs in a couple hundred ms on
the 8-sim-worker CPU mesh.

``PROTOCOLS`` registers *host-protocol* drives for the Layer-4 donation
audit (HL303): each builder returns ``drive(audit)`` which wraps its
donating executables via ``audit.wrap`` and runs the real pipeline — the
serve ``ContinuousRunner`` depth-2 in-flight loop is the motivating
case, pinned here in its correct discipline (the sabotaged twin lives in
tests/test_lint.py).
"""

from __future__ import annotations

from typing import Any, Callable

DRIVERS: dict[str, Callable[[], tuple[Callable, tuple[Any, ...]]]] = {}

#: host-protocol drives for the donation audit: name -> builder,
#: builder() -> drive, drive(commgraph.DonationAudit) -> None
PROTOCOLS: dict[str, Callable[[], Callable]] = {}


def register_driver(name: str):
    def deco(build):
        DRIVERS[name] = build
        return build
    return deco


def register_protocol(name: str):
    def deco(build):
        PROTOCOLS[name] = build
        return build
    return deco


def _mesh():
    from harp_tpu.parallel.mesh import WorkerMesh

    return WorkerMesh()


@register_driver("kmeans.fit")
def _kmeans_fit():
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.kmeans import KMeansConfig, make_fit_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_fit_fn(mesh, KMeansConfig(k=8, iters=2))
    pts = jax.ShapeDtypeStruct((16 * nw, 32), jnp.float32,
                               sharding=mesh.sharding(mesh.spec(0)))
    cents = jax.ShapeDtypeStruct((8, 32), jnp.float32,
                                 sharding=mesh.replicated())
    return fn, (pts, cents)


@register_driver("ring_attention")
def _ring_attention():
    import jax
    import jax.numpy as jnp

    from harp_tpu.ops.ring_attention import make_ring_attention_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_ring_attention_fn(mesh, causal=True)
    qkv = jax.ShapeDtypeStruct((2, 8 * nw, 4, 16), jnp.float32,
                               sharding=mesh.sharding(mesh.spec(1, ndim=4)))
    return fn, (qkv, qkv, qkv)


@register_driver("serve.kmeans_assign")
def _serve_kmeans_assign():
    """The serving step for kmeans at one ladder rung — the steady-state
    program the budget guard pins; registered so HL101/HL102 sweep the
    serve path like every other driver."""
    import numpy as np

    from harp_tpu.serve.engines import KMeansAssign

    mesh = _mesh()
    rng = np.random.default_rng(0)
    eng = KMeansAssign(KMeansAssign.synthetic_state(rng, k=8, d=32), mesh)
    return eng.jitted(), eng.trace_args(8)


@register_driver("serve.mfsgd_topk")
def _serve_mfsgd_topk():
    """The sharded-H top-k recommendation step (local top-k + one pull
    merge) — the serve path's model-parallel program."""
    import numpy as np

    from harp_tpu.serve.engines import MFSGDTopK

    mesh = _mesh()
    nw = mesh.num_workers
    rng = np.random.default_rng(0)
    eng = MFSGDTopK(
        MFSGDTopK.synthetic_state(rng, n_users=16 * nw,
                                  n_items=8 * nw, rank=8),
        mesh, topk=4)
    return eng.jitted(), eng.trace_args(8)


@register_driver("serve.lda_infer")
def _serve_lda_infer():
    """The LDA fold-in step (fixed-iteration EM over phi): the only
    serve engine with a device-side loop, so its byte sheet pins that
    fold-in stays collective-free at every trip count."""
    import numpy as np

    from harp_tpu.serve.engines import LDAInfer

    mesh = _mesh()
    rng = np.random.default_rng(0)
    eng = LDAInfer(LDAInfer.synthetic_state(rng, vocab_size=64,
                                            n_topics=8),
                   mesh, em_iters=4)
    return eng.jitted(), eng.trace_args(8)


@register_driver("serve.mlp_logits")
def _serve_mlp_logits():
    """The MLP forward pass through models/mlp.forward — the serve
    engine that calls back into trainer code, so the sweep sees the
    shared forward program."""
    import numpy as np

    from harp_tpu.serve.engines import MLPPredict

    mesh = _mesh()
    rng = np.random.default_rng(0)
    eng = MLPPredict(MLPPredict.synthetic_state(rng, sizes=(32, 16, 4)),
                     mesh)
    return eng.jitted(), eng.trace_args(8)


@register_driver("serve.rf_vote")
def _serve_rf_vote():
    """Majority-vote forest routing (host binize feeds device routing)."""
    import numpy as np

    from harp_tpu.serve.engines import RFPredict

    mesh = _mesh()
    rng = np.random.default_rng(0)
    eng = RFPredict(RFPredict.synthetic_state(rng, n_trees=4,
                                              max_depth=3, n_features=8),
                    mesh)
    return eng.jitted(), eng.trace_args(8)


@register_driver("serve.svm_scores")
def _serve_svm_scores():
    """The linear decision function — smallest serve program, pinned so
    the sweep covers the whole engine table."""
    import numpy as np

    from harp_tpu.serve.engines import SVMPredict

    mesh = _mesh()
    rng = np.random.default_rng(0)
    eng = SVMPredict(SVMPredict.synthetic_state(rng, d=32), mesh)
    return eng.jitted(), eng.trace_args(8)


@register_driver("rotate.pipeline_chunked")
def _rotate_pipeline_chunked():
    """PR 2's generic chunked rotation epoch (n_chunks=2 — the former
    bespoke two-halves schedule) with a slice-updating step, so the
    ppermute rides a scan whose carry the step mutates: the byte sheet
    must show the ring traffic amplified by n_chunks * ring size and the
    hoist detector (HL304) must stay quiet (the payload is the updated
    carry)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.parallel.rotate import rotate_pipeline

    mesh = _mesh()
    nw = mesh.num_workers

    def epoch(acc, sl):
        def step(c, chunk, t):
            return c + chunk.sum(), chunk * 1.01

        return rotate_pipeline(step, acc, sl, n_chunks=2)

    fn = jax.jit(mesh.shard_map(
        epoch, in_specs=(mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), mesh.spec(0))))
    acc = jax.ShapeDtypeStruct((nw,), jnp.float32,
                               sharding=mesh.sharding(mesh.spec(0)))
    sl = jax.ShapeDtypeStruct((8 * nw, 16), jnp.float32,
                              sharding=mesh.sharding(mesh.spec(0)))
    return fn, (acc, sl)


def _ingest_shapes(mesh):
    import jax
    import jax.numpy as jnp

    nw = mesh.num_workers
    k, d, chunk = 8, 16, 8 * nw
    sh0 = mesh.sharding(mesh.spec(0))
    return {
        "pts": jax.ShapeDtypeStruct((chunk, d), jnp.float32, sharding=sh0),
        "mask": jax.ShapeDtypeStruct((chunk,), jnp.float32, sharding=sh0),
        "cents": jax.ShapeDtypeStruct((k, d), jnp.float32,
                                      sharding=mesh.replicated()),
        "sums": jax.ShapeDtypeStruct((nw, k, d), jnp.float32, sharding=sh0),
        "counts": jax.ShapeDtypeStruct((nw, k), jnp.float32, sharding=sh0),
        "inertia": jax.ShapeDtypeStruct((nw,), jnp.float32, sharding=sh0),
    }


@register_driver("ingest.accum_chunk")
def _ingest_accum_chunk():
    """The per-chunk accumulate every IngestPipeline-shipped kmeans chunk
    rides (kmeans_stream._make_accum_fn) — deliberately collective-free
    (partials land in the per-worker accumulator; the epoch-end finish
    carries the ONE allreduce).  Registering it pins that emptiness: a
    collective leaking into the per-chunk path would multiply by the
    whole chunk count and show up in this byte sheet first."""
    from harp_tpu.models.kmeans_stream import StreamConfig, _make_accum_fn

    mesh = _mesh()
    s = _ingest_shapes(mesh)
    fn = _make_accum_fn(mesh, StreamConfig(k=8))
    return fn, (s["pts"], s["mask"], s["cents"], s["sums"], s["counts"],
                s["inertia"])


@register_driver("ingest.finish_epoch")
def _ingest_finish_epoch():
    """The streaming epoch tail: the one allreduce the whole chunk loop
    amortizes (kmeans_stream._make_finish_fn)."""
    from harp_tpu.models.kmeans_stream import _make_finish_fn

    mesh = _mesh()
    s = _ingest_shapes(mesh)
    fn = _make_finish_fn(mesh)
    return fn, (s["sums"], s["counts"], s["inertia"], s["cents"])


@register_driver("mfsgd.epoch")
def _mfsgd_epoch():
    from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig, synthetic_ratings

    mesh = _mesh()
    nw = mesh.num_workers
    users, items, vals = synthetic_ratings(8 * nw, 16 * nw, 64 * nw,
                                           rank=4)
    model = MFSGD(8 * nw, 16 * nw, MFSGDConfig(rank=4, algo="dense"),
                  mesh=mesh)
    model.set_ratings(users, items, vals)
    # the tracked epoch program + the device operands set_ratings staged
    return model._epoch_fn, (model.W, model.H) + model._blocks


@register_driver("lda.epoch")
def _lda_epoch():
    """The third flagship rotation epoch (PR 11): Gibbs sweep on the
    dense tiled algo, word-topic slices riding the reshard-shimmed ring
    — registering it closes the flagship set (kmeans/mfsgd/lda all
    byte-sheeted) and gives the planner its lda_planner_wire /
    lda_rotate_int8 candidate site."""
    from harp_tpu.models.lda import LDA, LDAConfig, synthetic_corpus

    mesh = _mesh()
    nw = mesh.num_workers
    d_ids, w_ids = synthetic_corpus(n_docs=6 * nw, vocab_size=8 * nw,
                                    n_topics_true=3, tokens_per_doc=16,
                                    seed=0)
    model = LDA(6 * nw, 8 * nw,
                LDAConfig(n_topics=4, algo="dense", d_tile=8, w_tile=8,
                          entry_cap=32), mesh, seed=0)
    model.set_tokens(d_ids, w_ids)
    keys = mesh.shard_array(model._keys, 0)
    return model._epoch_fn, (model.Ndk, model.Nwk, model.Nk,
                             model.z_grid) + model._tokens + (keys,)


@register_driver("kmeans.fit_hier")
def _kmeans_fit_hier():
    """The planner's hierarchical two-stage psum schedule on the kmeans
    fit program (flip candidate kmeans_hier_psum) — registered so
    HL301/HL302 byte-exact cross-checking covers the alternative
    schedule the planner can emit, not just the incumbent: the
    allreduce_hier site's sheet must show BOTH psum stages and agree
    with the ledger to the byte."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.kmeans import KMeansConfig, make_fit_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_fit_fn(mesh, KMeansConfig(k=8, iters=2,
                                        psum_schedule="hier"))
    pts = jax.ShapeDtypeStruct((16 * nw, 32), jnp.float32,
                               sharding=mesh.sharding(mesh.spec(0)))
    cents = jax.ShapeDtypeStruct((8, 32), jnp.float32,
                                 sharding=mesh.replicated())
    return fn, (pts, cents)


@register_driver("collective.reshard")
def _collective_reshard():
    """The reshard verb's exact lowerings in one traced program (PR 11):
    ring rotation (ppermute), dim change (all_to_all), replication
    (all_gather), and the local slice (deliberately wire-free — its
    absence from the sheet pins that a replicated→blocked move costs
    nothing).  One program, four sites, each HL301/HL302-checked."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.parallel.collective import ShardSpec, reshard
    from jax.sharding import PartitionSpec as P

    mesh = _mesh()
    nw = mesh.num_workers

    def prog(x):
        rot = reshard(x, ShardSpec.blocked(0), ShardSpec.blocked(0, 1))
        swap = reshard(x, ShardSpec.blocked(0), ShardSpec.blocked(1))
        full = reshard(x, ShardSpec.blocked(0), ShardSpec.replicated())
        back = reshard(full, ShardSpec.replicated(), ShardSpec.blocked(0))
        return rot, swap, full.sum(), back

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0, ndim=2),),
        out_specs=(mesh.spec(0, ndim=2), mesh.spec(1, ndim=2), P(),
                   mesh.spec(0, ndim=2))))
    x = jax.ShapeDtypeStruct((8 * nw, nw), jnp.float32,
                             sharding=mesh.sharding(mesh.spec(0, ndim=2)))
    return fn, (x,)


@register_driver("collective.reshard_wire")
def _collective_reshard_wire():
    """The planner's non-default reshard schedules (PR 11): the chunked
    ppermute pipeline (n_chunks=2 — the sheet must show the hop at
    chunk size with 2x amplification) and the int8 quantized wire (the
    stacked-pmax scale exchange plus the narrow hop; ledger wire_dtype
    exempts it from the exact-byte cross-check, exactly like the
    *_quantized verbs)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.parallel.collective import ShardSpec, reshard

    mesh = _mesh()
    nw = mesh.num_workers

    def prog(x):
        chunked = reshard(x, ShardSpec.blocked(0), ShardSpec.blocked(0, 1),
                          n_chunks=2)
        narrow = reshard(x, ShardSpec.blocked(0), ShardSpec.blocked(0, 2),
                         wire="int8")
        return chunked, narrow

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0, ndim=2),),
        out_specs=(mesh.spec(0, ndim=2),) * 2))
    x = jax.ShapeDtypeStruct((8 * nw, 16), jnp.float32,
                             sharding=mesh.sharding(mesh.spec(0, ndim=2)))
    return fn, (x,)


@register_driver("elastic.regather")
def _elastic_regather():
    """The PR-15 elastic row move: rebalanced model-state rows ride the
    reshard verb's always-legal split — ONE all_gather (blocked →
    replicated) then a purely local gather of each worker's new rows.
    Registering it keeps the mid-run move on the CommGraph byte sheet:
    the sheet must show exactly the replication hop (no second
    collective — the local gather is wire-free), HL301/HL302-checked on
    every full lint like the other reshard programs."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.elastic.move import make_regather_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_regather_fn(mesh, ndim=2)
    x = jax.ShapeDtypeStruct((8 * nw, 16), jnp.float32,
                             sharding=mesh.sharding(mesh.spec(0, ndim=2)))
    rows = jax.ShapeDtypeStruct((8 * nw,), jnp.int32,
                                sharding=mesh.sharding(mesh.spec(0)))
    return fn, (x, rows)


@register_driver("svm.train")
def _svm_train():
    """The SVM outer loop (PR 12): per-round SV exchange riding
    ``reshard`` blocked→replicated (SVMConfig.sv_wire's site — the
    planner's svm_sv_bf16/_int8 candidates price it) amplified by
    ``outer_rounds``, plus the final model-average allreduce pair.  One
    of the two per-app wires that had no byte sheet (ROADMAP planner
    item, with wdamds.smacof)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.svm import SVMConfig, make_train_fn

    mesh = _mesh()
    nw = mesh.num_workers
    n_loc = 8
    fn = make_train_fn(mesh, SVMConfig(inner_steps=4, outer_rounds=2,
                                       sv_per_worker=4),
                       d=16, n_loc=n_loc)
    sh0 = mesh.sharding(mesh.spec(0))
    x = jax.ShapeDtypeStruct((n_loc * nw, 16), jnp.float32, sharding=sh0)
    y = jax.ShapeDtypeStruct((n_loc * nw,), jnp.float32, sharding=sh0)
    sw = jax.ShapeDtypeStruct((n_loc * nw,), jnp.float32, sharding=sh0)
    return fn, (x, y, sw)


@register_driver("svm.train_pallas")
def _svm_train_pallas():
    """The PR-17 kernelized inner solve (SVMConfig.algo='pallas' —
    ops/svm_kernel.py, flip candidate svm_kernel_pallas): same outer
    wires as svm.train, but the per-round Pegasos scan dispatches the
    fused hinge-gradient pallas_call instead of the two-pass XLA dots.
    Registered so the jaxpr sweep and the Layer-4 byte sheet cover the
    kernel arm's program — the sheet must match svm.train's (the kernel
    changes the memory schedule, not the wires)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.svm import SVMConfig, make_train_fn

    mesh = _mesh()
    nw = mesh.num_workers
    n_loc = 8
    fn = make_train_fn(mesh, SVMConfig(algo="pallas", inner_steps=4,
                                       outer_rounds=2, sv_per_worker=4),
                       d=16, n_loc=n_loc)
    sh0 = mesh.sharding(mesh.spec(0))
    x = jax.ShapeDtypeStruct((n_loc * nw, 16), jnp.float32, sharding=sh0)
    y = jax.ShapeDtypeStruct((n_loc * nw,), jnp.float32, sharding=sh0)
    sw = jax.ShapeDtypeStruct((n_loc * nw,), jnp.float32, sharding=sh0)
    return fn, (x, y, sw)


@register_driver("wdamds.smacof")
def _wdamds_smacof():
    """The unweighted SMACOF run (PR 12): the per-iteration coordinate
    exchange riding ``reshard`` blocked→replicated
    (MDSConfig.coord_wire's site — wdamds_coord_bf16/_int8 candidates)
    amplified by ``iters``, plus the final stress allreduce.  Closes
    the per-app wire coverage (ROADMAP planner item, with svm.train)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.wdamds import MDSConfig, make_smacof_fn

    mesh = _mesh()
    nw = mesh.num_workers
    n_pad = 4 * nw
    fn = make_smacof_fn(mesh, MDSConfig(dim=2, iters=2), n_pad)
    sh0 = mesh.sharding(mesh.spec(0))
    delta = jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32,
                                 sharding=sh0)
    mask = jax.ShapeDtypeStruct((n_pad,), jnp.float32, sharding=sh0)
    x0 = jax.ShapeDtypeStruct((n_pad, 2), jnp.float32,
                              sharding=mesh.replicated())
    n_real = jax.ShapeDtypeStruct((), jnp.float32,
                                  sharding=mesh.replicated())
    return fn, (delta, mask, x0, n_real)


@register_driver("wdamds.smacof_pallas")
def _wdamds_smacof_pallas():
    """The PR-17 fused Guttman step (MDSConfig.algo='pallas' —
    ops/wdamds_kernel.py, flip candidate wdamds_dist_pallas).  n_pad is
    16·nw = 128 here, NOT the xla driver's 4·nw: the pallas branch
    engages only on 128-multiple N (smaller shapes fall back to XLA and
    the sweep would silently re-trace the incumbent program)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.wdamds import MDSConfig, make_smacof_fn

    mesh = _mesh()
    nw = mesh.num_workers
    n_pad = 16 * nw
    fn = make_smacof_fn(mesh, MDSConfig(algo="pallas", dim=2, iters=2),
                        n_pad)
    sh0 = mesh.sharding(mesh.spec(0))
    delta = jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32,
                                 sharding=sh0)
    mask = jax.ShapeDtypeStruct((n_pad,), jnp.float32, sharding=sh0)
    x0 = jax.ShapeDtypeStruct((n_pad, 2), jnp.float32,
                              sharding=mesh.replicated())
    n_real = jax.ShapeDtypeStruct((), jnp.float32,
                                  sharding=mesh.replicated())
    return fn, (delta, mask, x0, n_real)


@register_driver("rf.grow")
def _rf_grow():
    """Per-worker forest growth + the tree allgather (PR 16): the
    level-wise one-hot histogram matmuls (the dense MXU formulation the
    perfmodel's rf term prices against the 25 GB/s scatter wall,
    measured 2026-07-30 on 1x v5e) and the forest allgather wire.
    Gives rf a Layer-2/Layer-4 byte sheet and the wall-attribution
    observatory a capture target."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.rf import RFConfig, make_train_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_train_fn(mesh, RFConfig(n_trees=2 * nw, max_depth=2,
                                      n_bins=8, seed=0), n_features=8)
    sh0 = mesh.sharding(mesh.spec(0))
    bins = jax.ShapeDtypeStruct((16 * nw, 8), jnp.int32, sharding=sh0)
    y = jax.ShapeDtypeStruct((16 * nw,), jnp.int32, sharding=sh0)
    keys = jax.ShapeDtypeStruct((nw, 2, 2), jnp.uint32, sharding=sh0)
    return fn, (bins, y, keys)


@register_driver("rf.grow_pallas")
def _rf_grow_pallas():
    """The PR-17 on-chip histogram arm (RFConfig.hist_algo='pallas' —
    ops/rf_kernel.py, flip candidate rf_hist_pallas).  n_features=16 at
    n_bins=8 gives fB = 128: the pallas branch engages only on
    128-multiple f·B (odd widths fall through to dense and the sweep
    would silently re-trace the incumbent program).  Counts are
    bit-identical to rf.grow's dense arm, so the byte sheet must match
    it too — only the memory schedule differs."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.rf import RFConfig, make_train_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_train_fn(mesh, RFConfig(hist_algo="pallas", n_trees=2 * nw,
                                      max_depth=2, n_bins=8, seed=0),
                       n_features=16)
    sh0 = mesh.sharding(mesh.spec(0))
    bins = jax.ShapeDtypeStruct((16 * nw, 16), jnp.int32, sharding=sh0)
    y = jax.ShapeDtypeStruct((16 * nw,), jnp.int32, sharding=sh0)
    keys = jax.ShapeDtypeStruct((nw, 2, 2), jnp.uint32, sharding=sh0)
    return fn, (bins, y, keys)


@register_driver("subgraph.count")
def _subgraph_count():
    """One color-coding DP chunk over the padded CSR + exact segment
    overflow tail, ending in the counts allreduce (PR 16).  The fn
    comes back flightrec-tracked (tag "subgraph.count"), matching the
    real driver loop; colors ride spec(1), everything else spec(0) —
    the traversal gather pattern the perfmodel's subgraph term prices.

    Two lint-facing constraints: the model's `_FN_CACHE` is cleared so
    every analysis layer re-traces (a cache hit skips the Python body
    and the CommLedger never records — HL301 fires on a wire that IS
    verb-routed); and the trial chunk is 1 because the per-trial DP
    allgather sits under `jax.vmap`, where the ledger records the
    UNBATCHED payload — any larger chunk makes the static (batched)
    sheet disagree with the ledger by exactly the chunk factor
    (HL302)."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.models import subgraph as SG
    from harp_tpu.models.subgraph import TEMPLATES, make_colorful_count_fn

    mesh = _mesh()
    nw = mesh.num_workers
    n_pad, deg = 8 * nw, 4
    SG._FN_CACHE.clear()
    fn = make_colorful_count_fn(TEMPLATES["u3-path"], 3, mesh, "segment")
    sh0 = mesh.sharding(mesh.spec(0))
    nbr = jax.ShapeDtypeStruct((n_pad, deg), jnp.int32, sharding=sh0)
    msk = jax.ShapeDtypeStruct((n_pad, deg), jnp.float32, sharding=sh0)
    o_nbr = jax.ShapeDtypeStruct((nw,), jnp.int32, sharding=sh0)
    o_row = jax.ShapeDtypeStruct((nw,), jnp.int32, sharding=sh0)
    o_msk = jax.ShapeDtypeStruct((nw,), jnp.float32, sharding=sh0)
    colors = jax.ShapeDtypeStruct(
        (1, n_pad), jnp.int32, sharding=mesh.sharding(mesh.spec(1)))
    return fn, (nbr, msk, o_nbr, o_row, o_msk, colors)


# ---------------------------------------------------------------------------
# Donation-audit protocols (Layer 4, HL303)
# ---------------------------------------------------------------------------

def _serve_continuous_drive(app: str, engine_cls, state_kw: dict,
                            req_rows: int):
    """Build+drive the real ContinuousRunner depth-2 pipeline for one
    app under a DonationAudit: synthetic state, two-rung ladder, six
    requests interleaved with steps so batches genuinely overlap in
    flight — the correct staging discipline (a FRESH buffer per batch,
    donated exactly once, never touched after) must come out clean."""

    def drive(audit):
        import numpy as np

        from harp_tpu.serve.server import Server

        rng = np.random.default_rng(0)
        srv = Server(app, state=engine_cls.synthetic_state(rng, **state_kw),
                     mesh=_mesh(), ladder=(1, 8))
        srv.startup()
        n_state = len(srv.engine.state_args())
        srv.wrap_executables(
            lambda rung, exe: audit.wrap(exe, (n_state,),
                                         f"serve.{app}.b{rung}"))
        runner = srv.make_runner(depth=2)
        for i in range(6):
            runner.submit(i, srv.engine.synthetic_request(rng, req_rows))
            runner.step()
        runner.drain()

    return drive


@register_protocol("serve.kmeans_continuous")
def _serve_kmeans_protocol():
    from harp_tpu.serve.engines import KMeansAssign

    return _serve_continuous_drive("kmeans", KMeansAssign,
                                   {"k": 8, "d": 32}, req_rows=3)


@register_protocol("serve.mfsgd_continuous")
def _serve_mfsgd_protocol():
    """The model-parallel engine (sharded H, donated user-id batch) —
    the depth-2 pipeline the HL303 rule exists for."""
    from harp_tpu.serve.engines import MFSGDTopK

    return _serve_continuous_drive(
        "mfsgd", MFSGDTopK,
        {"n_users": 64, "n_items": 32, "rank": 8}, req_rows=3)


@register_protocol("serve.retry_restage")
def _serve_retry_restage_protocol():
    """The fault plane's retry path (PR 10): a seeded FaultInjector kills
    dispatches mid-pipeline and the ContinuousRunner retries each failed
    batch — ALWAYS through a freshly staged input buffer, because the
    failed attempt's buffer was already donated to the dead dispatch.
    Driving the retry loop here proves that discipline under the HL303
    audit on every full lint run (the sabotaged twin — re-dispatching
    the donated buffer on retry — lives in tests/test_lint.py); the
    drive also asserts the faults actually fired, so a refactor that
    silently unhooks the injector fails the lint instead of passing
    vacuously."""

    def drive(audit):
        import numpy as np

        from harp_tpu.serve.engines import KMeansAssign
        from harp_tpu.serve.server import Server
        from harp_tpu.utils.fault import FaultInjector

        rng = np.random.default_rng(0)
        srv = Server("kmeans",
                     state=KMeansAssign.synthetic_state(rng, k=8, d=32),
                     mesh=_mesh(), ladder=(1, 8))
        srv.startup()
        n_state = len(srv.engine.state_args())
        srv.wrap_executables(
            lambda rung, exe: audit.wrap(exe, (n_state,),
                                         f"serve.kmeans.b{rung}"))
        runner = srv.make_runner(depth=2, max_retries=2)
        inj = FaultInjector(seed=0, fail={"dispatch": (2,)})
        with inj.arm():
            for i in range(6):
                runner.submit(i, srv.engine.synthetic_request(rng, 3))
                runner.step()
            runner.drain()
        assert inj.injected["dispatch"] == 1, "no fault fired: vacuous"
        assert runner.fault_retries == 1, "fault fired but no retry ran"
        assert runner.completed == 6, "retry path lost responses"

    return drive


@register_protocol("elastic.rebalance_restage")
def _elastic_rebalance_restage_protocol():
    """The PR-15 restage-after-shrink path (HL303): a host loop donates
    a freshly staged batch per dispatch; an injected PERMANENT worker
    loss kills a dispatch mid-run, the loop shrinks to the survivor
    mesh, rebuilds its executable there, and must RESTAGE every
    post-shrink input from host data — the pre-shrink buffer was
    already donated to the dead dispatch (and lives on a mesh that no
    longer exists).  Driving it here proves the discipline under the
    donation audit on every full lint; the sabotaged twin
    (re-dispatching the pre-shrink donated buffer on the survivors)
    lives in tests/test_lint.py.  The drive asserts the loss actually
    fired, so a refactor that unhooks the injector fails the lint
    instead of passing vacuously."""

    def drive(audit):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from harp_tpu.parallel.mesh import WorkerMesh
        from harp_tpu.utils import flightrec
        from harp_tpu.utils.fault import (FaultInjector,
                                          PermanentWorkerLoss)

        def build(mesh, tag):
            fn = jax.jit(lambda c, x: (c + x.sum(), x * 2.0),
                         donate_argnums=(1,))
            return audit.wrap(flightrec.track(fn, tag), (1,), tag)

        mesh = WorkerMesh()
        exe = build(mesh, "elastic.step_full")
        carry = jax.device_put(jnp.float32(0.0), mesh.replicated())
        rng = np.random.default_rng(0)
        # 56 rows: divisible by the 8-worker mesh AND any 7-survivor one
        batches = [rng.normal(size=(56, 4)).astype(np.float32)
                   for _ in range(4)]
        inj = FaultInjector(seed=0, permanent={"dispatch": (2,)},
                            lost_worker=mesh.num_workers - 1)
        survived = False
        with inj.arm():
            try:
                for b in batches[:2]:
                    staged = mesh.shard_array(b, 0)  # fresh per dispatch
                    carry, _ = exe(carry, staged)
            except PermanentWorkerLoss as e:
                surv = WorkerMesh([d for i, d in enumerate(mesh.devices)
                                   if i != e.worker])
                exe2 = build(surv, "elastic.step_surv")
                carry = jax.device_put(
                    jnp.float32(float(np.asarray(carry))),
                    surv.replicated())
                for b in batches[2:]:
                    staged = surv.shard_array(b, 0)  # RESTAGE on survivors
                    carry, _ = exe2(carry, staged)
                survived = True
        assert inj.permanent_fired, "no permanent loss fired: vacuous"
        assert survived, "loss fired but the survivor loop never ran"

    return drive
