"""Registered driver programs for the Layer-2 jaxpr sweep.

The copy-trap / literal detectors (:mod:`harp_tpu.analysis.jaxpr_checks`)
need *traced programs* to walk.  This registry builds the flagship driver
programs at small proven shapes on the active (CPU-forced) backend —
mirroring how the lowering tests pin them — so ``python -m harp_tpu
lint`` sweeps real epoch programs, not just synthetic fixtures:

- ``kmeans.fit`` — the full T-iteration Lloyd program (fori_loop body:
  the dense one-hot pattern, no gathers);
- ``ring_attention`` — the rotate-scan K/V pipeline (a scan that carries
  and *reads* buffers every step: the structural cousin of the LDA trap
  that must stay clean);
- ``mfsgd.epoch`` — the rotation epoch with dynamic_update_slice'd
  factor tables: the closest in-tree relative of the pre-fix LDA
  copy-trap, pinned clean.

Builders return ``(traced_fn_or_fn, args)``; args may be concrete arrays
or sharded ``ShapeDtypeStruct``s.  Each runs in a couple hundred ms on
the 8-sim-worker CPU mesh.
"""

from __future__ import annotations

from typing import Any, Callable

DRIVERS: dict[str, Callable[[], tuple[Callable, tuple[Any, ...]]]] = {}


def register_driver(name: str):
    def deco(build):
        DRIVERS[name] = build
        return build
    return deco


def _mesh():
    from harp_tpu.parallel.mesh import WorkerMesh

    return WorkerMesh()


@register_driver("kmeans.fit")
def _kmeans_fit():
    import jax
    import jax.numpy as jnp

    from harp_tpu.models.kmeans import KMeansConfig, make_fit_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_fit_fn(mesh, KMeansConfig(k=8, iters=2))
    pts = jax.ShapeDtypeStruct((16 * nw, 32), jnp.float32,
                               sharding=mesh.sharding(mesh.spec(0)))
    cents = jax.ShapeDtypeStruct((8, 32), jnp.float32,
                                 sharding=mesh.replicated())
    return fn, (pts, cents)


@register_driver("ring_attention")
def _ring_attention():
    import jax
    import jax.numpy as jnp

    from harp_tpu.ops.ring_attention import make_ring_attention_fn

    mesh = _mesh()
    nw = mesh.num_workers
    fn = make_ring_attention_fn(mesh, causal=True)
    qkv = jax.ShapeDtypeStruct((2, 8 * nw, 4, 16), jnp.float32,
                               sharding=mesh.sharding(mesh.spec(1, ndim=4)))
    return fn, (qkv, qkv, qkv)


@register_driver("serve.kmeans_assign")
def _serve_kmeans_assign():
    """The serving step for kmeans at one ladder rung — the steady-state
    program the budget guard pins; registered so HL101/HL102 sweep the
    serve path like every other driver."""
    import numpy as np

    from harp_tpu.serve.engines import KMeansAssign

    mesh = _mesh()
    rng = np.random.default_rng(0)
    eng = KMeansAssign(KMeansAssign.synthetic_state(rng, k=8, d=32), mesh)
    return eng.jitted(), eng.trace_args(8)


@register_driver("serve.mfsgd_topk")
def _serve_mfsgd_topk():
    """The sharded-H top-k recommendation step (local top-k + one pull
    merge) — the serve path's model-parallel program."""
    import numpy as np

    from harp_tpu.serve.engines import MFSGDTopK

    mesh = _mesh()
    nw = mesh.num_workers
    rng = np.random.default_rng(0)
    eng = MFSGDTopK(
        MFSGDTopK.synthetic_state(rng, n_users=16 * nw,
                                  n_items=8 * nw, rank=8),
        mesh, topk=4)
    return eng.jitted(), eng.trace_args(8)


@register_driver("mfsgd.epoch")
def _mfsgd_epoch():
    from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig, synthetic_ratings

    mesh = _mesh()
    nw = mesh.num_workers
    users, items, vals = synthetic_ratings(8 * nw, 16 * nw, 64 * nw,
                                           rank=4)
    model = MFSGD(8 * nw, 16 * nw, MFSGDConfig(rank=4, algo="dense"),
                  mesh=mesh)
    model.set_ratings(users, items, vals)
    # the tracked epoch program + the device operands set_ratings staged
    return model._epoch_fn, (model.W, model.H) + model._blocks
