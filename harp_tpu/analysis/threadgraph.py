"""Layer 5 — host-concurrency auditor (HL401–HL405): the thread-root graph.

Reference parity (SURVEY.md §6 has no analogue — Harp's threading
discipline, like its communication discipline, lived in code review):
the serve/ingest/schedule/timing planes each hand-roll a host threading
model that is documented in comments ("the dispatcher thread owns the
jax work", "the event loop owns every socket", "stat writes take
self._lock") and enforced nowhere.  These are exactly the HL303 class
of bug: the CPU sim and every tier-1 test pass, then the plane corrupts
state or deadlocks under real concurrent traffic on silicon.  This
module turns each comment into a machine-checked invariant, the same
move HL0xx–HL3xx made for the relay traps.

The analysis is pure ``ast`` over a small set of **planes** (module
groups that share a threading model).  Per plane it discovers every
**thread root**:

- ``main`` — the residual root: everything no other root reaches;
- ``thread:<target>`` — each ``threading.Thread(target=...)``;
- ``timer:<target>`` — each ``threading.Timer(...)``;
- ``pool:<name>`` — each ``ThreadPoolExecutor`` submit site (grouped by
  the pool variable, carrying its ``thread_name_prefix``);
- ``eventloop`` — ALL ``async def`` coroutines plus every callback
  handed to ``call_soon_threadsafe`` (cooperative concurrency is one
  root: one thread runs it).  A ``Thread`` whose target wraps
  ``asyncio.run`` donates its ``name=`` to the eventloop root.

then computes each root's **reachable call set** by name-based call
resolution bounded to the plane's modules (an over-approximation by
design: a method name that resolves to two plane classes is counted in
both — reviewed exceptions go in ``allowlist.toml``), and checks:

- **HL401** — a jax-touching call (tracked dispatch via an ``_exec``
  table, ``device_put``/``shard_array``, readback/``device_sync``)
  reachable from a root that is not one of the plane's designated
  jax owners.  The transport dispatcher thread
  (``harp-serve-dispatch``) is the pinned clean fixture.
- **HL402** — a blocking call (readback/device sync, ``socket.recv``,
  zero-arg ``Queue.get``, unbounded ``join``/``result``/``wait``,
  ``time.sleep``) reachable from the eventloop root and not awaited: a
  20–150 ms relay round trip inside a coroutine freezes every socket
  the loop owns.
- **HL403** — shared mutable state written from ≥2 roots (or from a
  multi-instance root: a pool, or threads created in a loop) with no
  common lock on the write path.  Telemetry spines get first-class
  treatment: a spine written from several roots is clean ONLY if the
  spine's own mutators are verified internally locked (the module body
  is parsed — the single-writer contract becomes a checked invariant,
  and :mod:`harp_tpu.utils.threadguard` derives its runtime wrap list
  from the same verdict, so the two can never drift).
- **HL404** — a lock held across a dispatch/readback boundary: a
  ``with <lock>:`` whose body reaches a jax-touching call serializes a
  20–150 ms relay round trip under the lock (serve-plane head-of-line
  blocking).
- **HL405** — a thread started with neither ``daemon=True`` (at the
  constructor or via a later ``.daemon = True``) nor a bounded
  ``join(timeout)`` on a shutdown path: a forgotten non-daemon thread
  hangs process exit — on this machine, typically inside a relay call.

:func:`ownership_map` exports the graph's runtime face — the
jax-owner/forbidden thread-name patterns per plane plus the spine lock
verdicts — which :mod:`harp_tpu.utils.threadguard` arms as raising
assertions on the flightrec observer sites (the HL303/`flightrec.track`
sync-pin pattern: the map is *generated from* this analysis, never
written by hand).

Per-plane graphs are cached on (path, mtime, size) so ``lint
--changed`` re-analyzes only planes whose files changed (the ~2 s dev
loop survives; tests/test_lint.py pins the cache behavior).
"""

from __future__ import annotations

import ast
import dataclasses
import os

from harp_tpu.analysis import Violation

# ---------------------------------------------------------------------------
# Vocabulary
# ---------------------------------------------------------------------------

#: call-chain tails that touch the device: transfers, readbacks, syncs.
JAX_TOUCH_FUNCS = frozenset({
    "device_put", "shard_array", "shard_array_local",
    "block_until_ready", "device_sync", "readback",
})

#: dotted-chain prefixes that are jax by construction.
JAX_PREFIXES = ("jax.", "jnp.", "lax.")

#: attributes holding tracked-executable tables — ``self._exec[rung](...)``
#: is a dispatch (the serve plane's AOT ladder).
DISPATCH_TABLE_ATTRS = frozenset({"_exec"})

#: method tails that block their thread when called unbounded.  ``get``
#: is special-cased (zero-arg only: ``d.get(key)`` is a dict read);
#: any positional arg or a ``timeout=`` keyword is a bounded wait and
#: therefore exempt everywhere.
BLOCKING_SUFFIXES = frozenset({"join", "result", "recv", "accept",
                               "acquire", "wait"})

#: in-place mutator method tails that count as a write to their
#: receiver (the shared-state half of HL403).  ``put``/``get`` are NOT
#: here: ``queue.Queue``/``asyncio.Queue`` are the sanctioned
#: internally-locked cross-thread channels.
MUTATOR_METHODS = frozenset({"append", "extend", "insert", "add",
                             "update", "setdefault", "appendleft",
                             "remove", "discard", "popleft"})


@dataclasses.dataclass(frozen=True)
class PlaneSpec:
    """One plane: modules sharing a threading model + its jax owners."""

    name: str
    modules: tuple[str, ...]       # repo-relative paths
    jax_owners: tuple[str, ...]    # root ids allowed to touch jax


#: the audited planes.  ``main`` is a jax owner everywhere (drivers and
#: tests run on it); each plane adds its designated worker root.
PLANES: tuple[PlaneSpec, ...] = (
    PlaneSpec("serve",
              ("harp_tpu/serve/transport.py", "harp_tpu/serve/server.py"),
              ("main", "thread:_dispatch_loop")),
    PlaneSpec("ingest", ("harp_tpu/ingest.py",), ("main",)),
    PlaneSpec("schedule", ("harp_tpu/schedule.py",), ("main",)),
    PlaneSpec("timing", ("harp_tpu/utils/timing.py",), ("main",)),
    PlaneSpec("fault", ("harp_tpu/utils/fault.py",), ("main",)),
    # bench-config-worker RUNS each config thunk (bench.py `_run_boxed`
    # pattern: main only joins with a timeout), so it is the bench
    # plane's jax thread by design
    PlaneSpec("bench", ("bench.py", "harp_tpu/serve/bench.py"),
              ("main", "thread:run")),
)


@dataclasses.dataclass(frozen=True)
class SpineSpec:
    """One telemetry spine: where it lives, how plane code mutates it,
    and how the runtime twin reaches its singleton."""

    name: str
    module: str                    # repo-relative source path
    cls: str | None                # class owning the mutators (None = module fns)
    mutators: tuple[str, ...]      # mutator function/method names
    chains: tuple[str, ...]        # call-chain suffixes that hit them
    import_path: str               # runtime import path
    obj: str | None                # module attr holding the singleton


SPINES: tuple[SpineSpec, ...] = (
    SpineSpec("reqtrace", "harp_tpu/utils/reqtrace.py", "ReqTracer",
              ("begin", "event", "end", "mark"),
              ("reqtrace.arrive", "reqtrace.tracer.begin",
               "reqtrace.tracer.event", "reqtrace.tracer.end",
               "reqtrace.tracer.mark", "tracer.begin", "tracer.event",
               "tracer.end"),
              "harp_tpu.utils.reqtrace", "tracer"),
    SpineSpec("comm_ledger", "harp_tpu/utils/telemetry.py", "CommLedger",
              ("record",),
              ("telemetry.record_comm", "record_comm", "ledger.record"),
              "harp_tpu.utils.telemetry", "ledger"),
    SpineSpec("span_tracer", "harp_tpu/utils/telemetry.py", "SpanTracer",
              ("span",),
              ("telemetry.span", "tracer.span", "span"),
              "harp_tpu.utils.telemetry", "tracer"),
    SpineSpec("flightrec", "harp_tpu/utils/flightrec.py", None,
              ("record_h2d", "record_readback", "record_bucket"),
              ("flightrec.record_h2d", "flightrec.record_readback",
               "flightrec.record_bucket"),
              "harp_tpu.utils.flightrec", None),
)


# ---------------------------------------------------------------------------
# AST plumbing
# ---------------------------------------------------------------------------

def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an Attribute/Name chain ("self._inq.put"), or ""."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _chain_matches(chain: str, suffix: str) -> bool:
    return chain == suffix or chain.endswith("." + suffix)


def _name_pattern(node: ast.AST | None) -> str | None:
    """An fnmatch pattern for a thread-name expression: constants stay
    verbatim, f-string holes become ``*`` (``f"{tag}-read"`` → ``*-read``)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.append(v.value)
            else:
                out.append("*")
        return "".join(out) or None
    return None


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


@dataclasses.dataclass
class _Site:
    relpath: str
    line: int
    source: str
    desc: str
    locks: frozenset[str] = frozenset()


@dataclasses.dataclass
class _FuncInfo:
    name: str
    qualname: str
    relpath: str
    node: ast.AST                      # FunctionDef / AsyncFunctionDef / Lambda
    is_async: bool
    # populated by _scan
    calls: list[tuple[str, ast.Call, frozenset, bool]] = \
        dataclasses.field(default_factory=list)  # (chain, node, locks, awaited)
    jax_sites: list[_Site] = dataclasses.field(default_factory=list)
    blocking_sites: list[_Site] = dataclasses.field(default_factory=list)
    spine_sites: dict[str, list[_Site]] = dataclasses.field(
        default_factory=dict)
    writes: list[tuple[str, _Site, bool]] = dataclasses.field(
        default_factory=list)          # (key, site, in_init)
    lock_regions: list[tuple[str, ast.With, frozenset]] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class _Root:
    id: str
    kind: str                          # main|thread|timer|pool|eventloop
    entries: list[str] = dataclasses.field(default_factory=list)
    # several constructions can share one root id (StaticScheduler and
    # DynamicScheduler both start `worker` targets) — keep EVERY name
    # pattern: the runtime map must forbid all of them
    name_patterns: set[str] = dataclasses.field(default_factory=set)
    multi_instance: bool = False
    decl_site: _Site | None = None


def _is_lock_chain(chain: str) -> bool:
    last = chain.split(".")[-1].lower()
    return "lock" in last


class _PlaneGraph:
    """The per-plane static analysis: functions, roots, reachability."""

    def __init__(self, spec: PlaneSpec, sources: dict[str, str]):
        self.spec = spec
        self.sources = sources
        self.violations: list[Violation] = []
        self.funcs: list[_FuncInfo] = []
        self.by_name: dict[str, list[_FuncInfo]] = {}
        self.class_init: dict[str, str] = {}   # class name -> __init__ name
        self.roots: dict[str, _Root] = {}
        self._touches_jax: dict[int, bool] = {}
        self._locals_cache: dict[int, set[str]] = {}
        for rel, text in sorted(sources.items()):
            try:
                tree = ast.parse(text, filename=rel)
            except SyntaxError as e:
                self.violations.append(Violation(
                    "HL000", rel, e.lineno or 0,
                    f"unparseable source: {e.msg}"))
                continue
            self._index(rel, text.splitlines(), tree)
        self._discover_roots()
        self._reach_cache: dict[str, set[int]] = {}

    # -- indexing -----------------------------------------------------------

    def _index(self, rel: str, lines: list[str], tree: ast.Module) -> None:
        def src(node: ast.AST) -> str:
            ln = getattr(node, "lineno", 0)
            return lines[ln - 1].strip() if 0 < ln <= len(lines) else ""

        def add_func(node, qual):
            fi = _FuncInfo(name=getattr(node, "name", "<lambda>"),
                           qualname=qual, relpath=rel, node=node,
                           is_async=isinstance(node, ast.AsyncFunctionDef))
            self.funcs.append(fi)
            self.by_name.setdefault(fi.name, []).append(fi)
            self._scan(fi, src)
            return fi

        def walk_defs(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add_func(child, f"{prefix}{child.name}")
                    walk_defs(child, f"{prefix}{child.name}.")
                elif isinstance(child, ast.ClassDef):
                    self.class_init[child.name] = "__init__"
                    walk_defs(child, f"{prefix}{child.name}.")
                else:
                    walk_defs(child, prefix)

        walk_defs(tree, f"{rel}::")

    def _scan(self, fi: _FuncInfo, src) -> None:
        """One pass over ``fi``'s own body (nested defs excluded — they
        are functions of their own), tracking the lexical lock stack."""
        node = fi.node
        in_init = fi.name == "__init__"
        local_names = self._func_locals(fi)

        def site(n, desc, locks):
            return _Site(fi.relpath, getattr(n, "lineno", 0), src(n), desc,
                         locks)

        def visit(n, locks: frozenset, awaited: bool = False):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return
            if isinstance(n, ast.With):
                lock_names = frozenset(
                    _attr_chain(item.context_expr.func
                                if isinstance(item.context_expr, ast.Call)
                                else item.context_expr).split(".")[-1]
                    for item in n.items
                    if _is_lock_chain(
                        _attr_chain(item.context_expr.func
                                    if isinstance(item.context_expr, ast.Call)
                                    else item.context_expr)))
                if lock_names:
                    for ln in lock_names:
                        fi.lock_regions.append((ln, n, locks))
                    inner = locks | lock_names
                    for item in n.items:
                        visit(item.context_expr, locks)
                    for stmt in n.body:
                        visit(stmt, inner)
                    return
            if isinstance(n, ast.Await):
                visit(n.value, locks, awaited=True)
                return
            if isinstance(n, ast.Call):
                self._scan_call(fi, n, locks, awaited, site)
                for ch in ast.iter_child_nodes(n):
                    if ch is not n.func:
                        visit(ch, locks)
                # still record nested calls inside the func expression
                if isinstance(n.func, ast.Call):
                    visit(n.func, locks)
                return
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        fi.writes.append((t.attr, site(t, f"write to "
                                                       f".{t.attr}", locks),
                                          in_init))
                    elif (isinstance(t, ast.Subscript)
                          and isinstance(t.value, ast.Name)
                          and t.value.id not in local_names):
                        fi.writes.append((f"closure:{t.value.id}",
                                          site(t, f"item write to closure "
                                               f"var {t.value.id!r}", locks),
                                          in_init))
                visit(n.value, locks)
                return
            for ch in ast.iter_child_nodes(n):
                visit(ch, locks)

        for stmt in (node.body if not isinstance(node, ast.Lambda)
                     else [node.body]):
            visit(stmt, frozenset())

        self._local_names = local_names  # last-scanned (debug aid)

    def _scan_call(self, fi: _FuncInfo, call: ast.Call, locks: frozenset,
                   awaited: bool, site) -> None:
        chain = _attr_chain(call.func)
        # dispatch through a tracked-executable table: self._exec[r](...)
        if isinstance(call.func, ast.Subscript):
            base = _attr_chain(call.func.value)
            if base.split(".")[-1] in DISPATCH_TABLE_ATTRS:
                fi.jax_sites.append(site(call, "tracked dispatch through "
                                         f"{base}[...]", locks))
            return
        if not chain:
            if isinstance(call.func, ast.Call):
                # e.g. pool.submit(chained_prep(rf)) — scanned by caller
                pass
            return
        last = chain.split(".")[-1]
        fi.calls.append((chain, call, locks, awaited))
        # jax-touching?
        if (last in JAX_TOUCH_FUNCS
                or any(chain.startswith(p) for p in JAX_PREFIXES)):
            fi.jax_sites.append(site(call, f"jax-touching call {chain}()",
                                     locks))
            if not awaited:
                fi.blocking_sites.append(site(
                    call, f"device round trip {chain}() blocks its thread",
                    locks))
        # blocking?
        elif not awaited:
            has_bound = (bool(call.args)
                         or _kw(call, "timeout") is not None)
            if last == "get" and not call.args and not call.keywords:
                fi.blocking_sites.append(site(
                    call, f"unbounded {chain}() — a zero-arg Queue.get "
                    "blocks forever", locks))
            elif last in BLOCKING_SUFFIXES and not has_bound:
                fi.blocking_sites.append(site(
                    call, f"unbounded {chain}() blocks its thread", locks))
            elif chain == "time.sleep":
                fi.blocking_sites.append(site(
                    call, "time.sleep() inside a coroutine stalls the "
                    "whole loop — use asyncio.sleep", locks))
        # spine mutator?
        for sp in SPINES:
            if any(_chain_matches(chain, c) for c in sp.chains):
                fi.spine_sites.setdefault(sp.name, []).append(
                    site(call, f"{sp.name} spine write via {chain}()",
                         locks))
        # in-place mutator on a shared receiver
        if last in MUTATOR_METHODS:
            recv = chain.rsplit(".", 1)[0]
            parts = recv.split(".")
            if len(parts) == 1:
                if recv not in self._func_locals(fi):
                    fi.writes.append((f"closure:{recv}",
                                      site(call, f"mutating call "
                                           f"{chain}() on closure var",
                                           locks),
                                      fi.name == "__init__"))
            else:
                fi.writes.append((parts[-1],
                                  site(call, f"mutating call {chain}()",
                                       locks),
                                  fi.name == "__init__"))

    def _func_locals(self, fi: _FuncInfo) -> set[str]:
        """Names bound inside ``fi`` (params + every assignment form) —
        a write to anything NOT in this set is closure/global state."""
        cached = self._locals_cache.get(id(fi))
        if cached is not None:
            return cached
        node = fi.node
        out: set[str] = {a.arg for a in node.args.args}
        out.update(a.arg for a in node.args.kwonlyargs)
        out.update(a.arg for a in getattr(node.args, "posonlyargs", []))
        if node.args.vararg:
            out.add(node.args.vararg.arg)
        if node.args.kwarg:
            out.add(node.args.kwarg.arg)
        nonlocals: set[str] = set()

        def names_in(tgt):
            # binding targets only: a subscript/attribute store
            # (results[i] = x) does NOT bind the receiver name
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    names_in(el)
            elif isinstance(tgt, ast.Starred):
                names_in(tgt.value)

        for ch in ast.walk(node):
            if isinstance(ch, (ast.Nonlocal, ast.Global)):
                nonlocals.update(ch.names)
            elif isinstance(ch, ast.Assign):
                for t in ch.targets:
                    names_in(t)
            elif isinstance(ch, (ast.AnnAssign, ast.AugAssign,
                                 ast.NamedExpr)):
                names_in(ch.target)
            elif isinstance(ch, (ast.For, ast.AsyncFor, ast.comprehension)):
                names_in(ch.target)
            elif isinstance(ch, (ast.With, ast.AsyncWith)):
                for item in ch.items:
                    if item.optional_vars is not None:
                        names_in(item.optional_vars)
            elif isinstance(ch, ast.ExceptHandler) and ch.name:
                out.add(ch.name)
        res = out - nonlocals
        self._locals_cache[id(fi)] = res
        return res

    # -- roots --------------------------------------------------------------

    def _discover_roots(self) -> None:
        ev_entries: list[str] = [f.name for f in self.funcs if f.is_async]
        ev_name: str | None = None
        # receivers that hold a ThreadPoolExecutor: construction targets
        # (self._read_pool = ThreadPoolExecutor(...)) — a `.submit` on
        # anything else (e.g. runner.submit, a plain method) is NOT a
        # pool root; names containing pool/executor also count, covering
        # locals unpacked from a factory (read_pool, prep_pool = ...)
        self._executor_vars: set[str] = set()
        for fi in self.funcs:
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Assign):
                    continue
                has_pool = any(
                    isinstance(sub, ast.Call)
                    and _attr_chain(sub.func).split(".")[-1]
                    == "ThreadPoolExecutor"
                    for sub in ast.walk(n.value))
                if has_pool:
                    for t in n.targets:
                        if isinstance(t, ast.Attribute):
                            self._executor_vars.add(t.attr)
                        elif isinstance(t, ast.Name):
                            self._executor_vars.add(t.id)
        for fi in self.funcs:
            for chain, call, locks, _aw in fi.calls:
                last = chain.split(".")[-1]
                if last == "Thread" and "hread" in chain.split(".")[-1]:
                    self._thread_root(fi, call, "thread")
                elif last == "Timer" and _chain_matches(chain,
                                                        "threading.Timer"):
                    self._thread_root(fi, call, "timer")
                elif last == "submit" and len(chain.split(".")) > 1:
                    recv = chain.rsplit(".", 1)[0].split(".")[-1]
                    if (recv in self._executor_vars
                            or "pool" in recv.lower()
                            or "executor" in recv.lower()):
                        self._pool_root(fi, call, chain)
                elif last == "call_soon_threadsafe" and call.args:
                    tgt = self._target_names(call.args[0])
                    ev_entries.extend(tgt)
        # a Thread whose target wraps asyncio.run donates its name to
        # the eventloop root (the loop runs ON that thread)
        for rid, root in list(self.roots.items()):
            if root.kind == "thread" and root.entries == ["<asyncio.run>"]:
                ev_name = ev_name or (min(root.name_patterns)
                                      if root.name_patterns else None)
                del self.roots[rid]
        if ev_entries:
            self.roots["eventloop"] = _Root(
                "eventloop", "eventloop", entries=sorted(set(ev_entries)),
                name_patterns={ev_name} if ev_name else set())
        self.roots.setdefault("main", _Root("main", "main"))

    def _target_names(self, node: ast.AST) -> list[str]:
        """Entry function names for a thread/task target expression."""
        if isinstance(node, ast.Lambda):
            # lambda: asyncio.run(self._run()) → the coroutine; else the
            # functions the lambda body calls
            for n in ast.walk(node.body):
                if (isinstance(n, ast.Call)
                        and _chain_matches(_attr_chain(n.func),
                                           "asyncio.run")):
                    return ["<asyncio.run>"]
            return [_attr_chain(n.func).split(".")[-1]
                    for n in ast.walk(node.body)
                    if isinstance(n, ast.Call) and _attr_chain(n.func)]
        chain = _attr_chain(node)
        if chain:
            return [chain.split(".")[-1]]
        return []

    def _in_loop_or_comp(self, fi: _FuncInfo, call: ast.Call) -> bool:
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.ListComp, ast.GeneratorExp, ast.For,
                              ast.While)):
                for sub in ast.walk(n):
                    if sub is call:
                        return True
        return False

    def _thread_root(self, fi: _FuncInfo, call: ast.Call,
                     kind: str) -> None:
        target = _kw(call, "target")
        if target is None and kind == "timer" and len(call.args) >= 2:
            target = call.args[1]
        entries = self._target_names(target) if target is not None else []
        name_pat = _name_pattern(_kw(call, "name"))
        # a later `t.name = "..."` in the same function also names it
        if name_pat is None:
            for n in ast.walk(fi.node):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Attribute)
                        and n.targets[0].attr == "name"):
                    name_pat = _name_pattern(n.value)
        ent = entries[0] if entries else f"@{fi.qualname}:{call.lineno}"
        rid = f"{kind}:{ent}"
        src = self.sources.get(fi.relpath, "").splitlines()
        line = src[call.lineno - 1].strip() if call.lineno <= len(src) else ""
        decl = _Site(fi.relpath, call.lineno, line,
                     f"{kind} root {rid}")
        root = self.roots.setdefault(rid, _Root(rid, kind,
                                                decl_site=decl))
        root.entries = sorted(set(root.entries) | set(entries))
        if name_pat:
            root.name_patterns.add(name_pat)
        if self._in_loop_or_comp(fi, call):
            root.multi_instance = True
        # HL405: daemon flag or bounded join
        self._check_hl405(fi, call, kind, decl)

    def _check_hl405(self, fi: _FuncInfo, call: ast.Call, kind: str,
                     decl: _Site) -> None:
        d = _kw(call, "daemon")
        if isinstance(d, ast.Constant) and d.value is True:
            return
        for n in ast.walk(fi.node):
            # X.daemon = True after construction
            if (isinstance(n, ast.Assign)
                    and any(isinstance(t, ast.Attribute)
                            and t.attr == "daemon" for t in n.targets)
                    and isinstance(n.value, ast.Constant)
                    and n.value.value is True):
                return
            # bounded join anywhere in the constructing module scope
            if (isinstance(n, ast.Call)
                    and _attr_chain(n.func).split(".")[-1] == "join"
                    and (n.args or _kw(n, "timeout") is not None)):
                return
        self.violations.append(Violation(
            "HL405", decl.relpath, decl.line,
            f"{kind} started with neither daemon=True nor a bounded "
            "join(timeout) on a shutdown path — a forgotten non-daemon "
            "thread hangs process exit (typically inside a relay call)",
            decl.source))

    def _pool_root(self, fi: _FuncInfo, call: ast.Call,
                   chain: str) -> None:
        recv = chain.rsplit(".", 1)[0].split(".")[-1]
        norm = recv.lstrip("_").removesuffix("_pool").removesuffix("pool") \
            .strip("_") or recv
        if not call.args:
            return
        entries = self._target_names(call.args[0])
        if isinstance(call.args[0], ast.Call):
            # pool.submit(chained_prep(rf)): the factory's nested defs run
            fac = _attr_chain(call.args[0].func).split(".")[-1]
            entries = [fac]
            for f in self.by_name.get(fac, []):
                for ch in ast.walk(f.node):
                    if isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        entries.append(ch.name)
        if not entries:
            return
        rid = f"pool:{norm}"
        # the pool's thread_name_prefix (from its construction, matched
        # by the normalized variable name) → "prefix*" runtime pattern
        name_pat = None
        for f in self.funcs:
            for c2, call2, _locks2, _aw2 in f.calls:
                if c2.split(".")[-1] == "ThreadPoolExecutor":
                    pref = _name_pattern(_kw(call2, "thread_name_prefix"))
                    tgt = None
                    for n in ast.walk(f.node):
                        if (isinstance(n, ast.Assign)
                                and any(isinstance(t, ast.Attribute)
                                        for t in n.targets)):
                            for sub in ast.walk(n.value):
                                if sub is call2:
                                    t0 = n.targets[0]
                                    if isinstance(t0, ast.Attribute):
                                        tgt = t0.attr
                    if pref and tgt is not None:
                        tnorm = (tgt.lstrip("_").removesuffix("_pool")
                                 .removesuffix("pool").strip("_") or tgt)
                        if tnorm == norm:
                            name_pat = pref + "*"
        src = self.sources.get(fi.relpath, "").splitlines()
        line = (src[call.lineno - 1].strip()
                if call.lineno <= len(src) else "")
        decl = _Site(fi.relpath, call.lineno, line, f"pool root {rid}")
        root = self.roots.setdefault(
            rid, _Root(rid, "pool", multi_instance=True, decl_site=decl))
        root.entries = sorted(set(root.entries) | set(entries))
        if name_pat:
            root.name_patterns.add(name_pat)

    # -- reachability -------------------------------------------------------

    def reach(self, rid: str) -> set[int]:
        """ids of _FuncInfo reachable from root ``rid`` (main = residual:
        every function no other root reaches)."""
        if rid in self._reach_cache:
            return self._reach_cache[rid]
        if rid == "main":
            others: set[int] = set()
            for other in self.roots:
                if other != "main":
                    others |= self.reach(other)
            out = {id(f) for f in self.funcs} - others
            self._reach_cache[rid] = out
            return out
        root = self.roots[rid]
        seen: set[int] = set()
        frontier: list[_FuncInfo] = []
        for name in root.entries:
            frontier.extend(self.by_name.get(name, []))
        while frontier:
            fi = frontier.pop()
            if id(fi) in seen:
                continue
            seen.add(id(fi))
            for chain, call, _locks, awaited in fi.calls:
                last = chain.split(".")[-1]
                cands = list(self.by_name.get(last, []))
                if awaited:
                    # an awaited call targets a coroutine — a sync plane
                    # method sharing the name (ContinuousRunner.drain vs
                    # asyncio's writer.drain()) is NOT the callee
                    cands = [c for c in cands if c.is_async]
                if last in self.class_init or chain in self.class_init:
                    cls = last if last in self.class_init else chain
                    cands.extend(f for f in self.by_name.get("__init__", [])
                                 if f.qualname.startswith(f"{f.relpath}::")
                                 and f".{cls}." in "." + f.qualname
                                 .split("::", 1)[1] + ".")
                frontier.extend(c for c in cands if id(c) not in seen)
        self._reach_cache[rid] = seen
        return seen

    def roots_of(self, fi: _FuncInfo) -> list[str]:
        out = [rid for rid in self.roots
               if rid != "main" and id(fi) in self.reach(rid)]
        return out or ["main"]

    def funcs_in(self, ids: set[int]) -> list[_FuncInfo]:
        return [f for f in self.funcs if id(f) in ids]


# ---------------------------------------------------------------------------
# Checks
# ---------------------------------------------------------------------------

def _check_hl401(g: _PlaneGraph) -> None:
    owners = set(g.spec.jax_owners)
    for rid, root in sorted(g.roots.items()):
        if rid in owners:
            continue
        for fi in g.funcs_in(g.reach(rid)):
            for s in fi.jax_sites:
                g.violations.append(Violation(
                    "HL401", s.relpath, s.line,
                    f"[{g.spec.name}] {s.desc} reachable from thread root "
                    f"{rid!r} — only {sorted(owners)} may touch jax on "
                    "this plane (route the work through the designated "
                    "owner, e.g. the dispatcher queue)", s.source))


def _check_hl402(g: _PlaneGraph) -> None:
    if "eventloop" not in g.roots:
        return
    for fi in g.funcs_in(g.reach("eventloop")):
        for s in fi.blocking_sites:
            g.violations.append(Violation(
                "HL402", s.relpath, s.line,
                f"[{g.spec.name}] {s.desc} — reachable from the event "
                "loop: every socket the loop owns freezes for the "
                "duration (await it, bound it, or move it to the "
                "dispatcher thread)", s.source))


def _check_hl403(g: _PlaneGraph,
                 spine_locked: dict[str, bool]) -> None:
    # spines first: multi-root writers are clean ONLY if the spine's own
    # mutators are verified internally locked
    spine_writers: dict[str, dict[str, list[_Site]]] = {}
    for fi in g.funcs:
        for sp_name, sites in fi.spine_sites.items():
            for rid in g.roots_of(fi):
                spine_writers.setdefault(sp_name, {}).setdefault(
                    rid, []).extend(sites)
    for sp_name, by_root in sorted(spine_writers.items()):
        multi = (len(by_root) > 1
                 or any(g.roots[r].multi_instance for r in by_root))
        if not multi or spine_locked.get(sp_name, False):
            continue
        first = min((s for ss in by_root.values() for s in ss),
                    key=lambda s: (s.relpath, s.line))
        g.violations.append(Violation(
            "HL403", first.relpath, first.line,
            f"[{g.spec.name}] telemetry spine {sp_name!r} written from "
            f"roots {sorted(by_root)} but its mutators are not "
            "internally locked — the single-writer contract is broken "
            "(add a lock inside the spine's mutators, or route all "
            "writes through one root)", first.source))
    # plain shared state: attr / closure keys
    writers: dict[str, dict[str, list[_Site]]] = {}
    for fi in g.funcs:
        for key, s, in_init in fi.writes:
            if in_init:
                continue  # construction happens-before any thread start
            for rid in g.roots_of(fi):
                writers.setdefault(key, {}).setdefault(rid, []).append(s)
    for key, by_root in sorted(writers.items()):
        multi = (len(by_root) > 1
                 or any(g.roots[r].multi_instance for r in by_root))
        if not multi:
            continue
        lock_sets = [s.locks for ss in by_root.values() for s in ss]
        if lock_sets and frozenset.intersection(*lock_sets):
            continue  # every write path shares a lock
        first = min((s for ss in by_root.values() for s in ss),
                    key=lambda s: (s.relpath, s.line))
        which = (f"roots {sorted(by_root)}" if len(by_root) > 1
                 else f"multi-instance root {next(iter(by_root))!r}")
        g.violations.append(Violation(
            "HL403", first.relpath, first.line,
            f"[{g.spec.name}] shared state {key!r} written from {which} "
            "with no common lock on the write path — take one lock "
            "around every write, or confine the state to one root",
            first.source))


def _check_hl404(g: _PlaneGraph) -> None:
    # transitive within-plane: does a function touch jax itself or via
    # plane-resolvable calls?
    touches: dict[int, bool] = {}

    def fn_touches(fi: _FuncInfo, stack: set[int]) -> bool:
        if id(fi) in touches:
            return touches[id(fi)]
        if id(fi) in stack:
            return False
        stack.add(id(fi))
        out = bool(fi.jax_sites)
        if not out:
            for chain, call, _locks, awaited in fi.calls:
                last = chain.split(".")[-1]
                cands = g.by_name.get(last, [])
                if awaited:
                    cands = [c for c in cands if c.is_async]
                if any(fn_touches(c, stack) for c in cands):
                    out = True
                    break
        touches[id(fi)] = out
        return out

    for fi in g.funcs:
        for lock_name, with_node, _outer in fi.lock_regions:
            for n in ast.walk(with_node):
                if n is with_node:
                    continue
                if isinstance(n, ast.Call):
                    chain = _attr_chain(n.func)
                    direct = (isinstance(n.func, ast.Subscript)
                              and _attr_chain(n.func.value).split(".")[-1]
                              in DISPATCH_TABLE_ATTRS)
                    last = chain.split(".")[-1] if chain else ""
                    via = (last in JAX_TOUCH_FUNCS
                           or any(chain.startswith(p)
                                  for p in JAX_PREFIXES)
                           or any(fn_touches(c, set())
                                  for c in g.by_name.get(last, [])))
                    if direct or via:
                        src = g.sources.get(fi.relpath, "").splitlines()
                        line = getattr(n, "lineno", 0)
                        text = (src[line - 1].strip()
                                if 0 < line <= len(src) else "")
                        g.violations.append(Violation(
                            "HL404", fi.relpath, line,
                            f"[{g.spec.name}] dispatch/readback reachable "
                            f"while holding {lock_name!r} — a 20-150 ms "
                            "relay round trip under a lock is "
                            "head-of-line blocking for every other "
                            "thread wanting it (release the lock before "
                            "touching the device)", text))


# ---------------------------------------------------------------------------
# Spine lock verification
# ---------------------------------------------------------------------------

def _spine_locked_from_source(spec: SpineSpec, text: str) -> bool:
    """True iff every mutator of ``spec`` guards its body with a lock
    (``with self._lock`` / any attr whose name contains "lock")."""
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return False
    bodies: list[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == spec.cls:
            for ch in node.body:
                if (isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and ch.name in spec.mutators):
                    bodies.append(ch)
        elif (spec.cls is None
              and isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
              and node.name in spec.mutators
              and isinstance(tree, ast.Module) and node in tree.body):
            bodies.append(node)
    if len(bodies) < len(spec.mutators):
        return False
    for fn in bodies:
        locked = False
        for n in ast.walk(fn):
            if isinstance(n, ast.With):
                for item in n.items:
                    ctx = (item.context_expr.func
                           if isinstance(item.context_expr, ast.Call)
                           else item.context_expr)
                    if _is_lock_chain(_attr_chain(ctx)):
                        locked = True
        if not locked:
            return False
    return True


def spine_lock_verdicts(repo: str) -> dict[str, bool]:
    out: dict[str, bool] = {}
    for sp in SPINES:
        path = os.path.join(repo, sp.module)
        try:
            with open(path, encoding="utf-8") as fh:
                out[sp.name] = _spine_locked_from_source(sp, fh.read())
        except OSError:
            out[sp.name] = False
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

#: plane name -> (cache key, built graph); keyed on (path, mtime, size)
#: so ``lint --changed`` and repeated in-process runs (tier-1 calls the
#: CLI many times) re-analyze only planes whose files changed.
_CACHE: dict[str, tuple[tuple, _PlaneGraph]] = {}


def _plane_key(repo: str, spec: PlaneSpec) -> tuple:
    out = []
    for rel in spec.modules:
        path = os.path.join(repo, rel)
        try:
            st = os.stat(path)
            out.append((rel, st.st_mtime_ns, st.st_size))
        except OSError:
            out.append((rel, 0, 0))
    return tuple(out)


def _plane_graph(repo: str, spec: PlaneSpec) -> _PlaneGraph:
    key = _plane_key(repo, spec)
    hit = _CACHE.get(spec.name)
    if hit is not None and hit[0] == key:
        return hit[1]
    sources: dict[str, str] = {}
    for rel in spec.modules:
        path = os.path.join(repo, rel)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                sources[rel] = fh.read()
    g = _analyze(spec, sources, spine_lock_verdicts(repo))
    _CACHE[spec.name] = (key, g)
    return g


def _analyze(spec: PlaneSpec, sources: dict[str, str],
             spine_locked: dict[str, bool]) -> _PlaneGraph:
    g = _PlaneGraph(spec, sources)
    _check_hl401(g)
    _check_hl402(g)
    _check_hl403(g, spine_locked)
    _check_hl404(g)
    return g


def analyze_sources(spec: PlaneSpec, sources: dict[str, str],
                    spine_locked: dict[str, bool] | None = None
                    ) -> list[Violation]:
    """Fixture entry: analyze in-memory sources as one plane (the
    sabotaged-twin tests drive every rule through this)."""
    return _analyze(spec, sources, spine_locked or {}).violations


def planes_for_paths(relpaths) -> list[str]:
    """Plane names owning any of ``relpaths`` — the ``lint --changed``
    scope (a spine module change re-runs every plane: the lock verdicts
    feed all of them)."""
    rels = {p.replace(os.sep, "/") for p in relpaths}
    spine_mods = {sp.module for sp in SPINES}
    if rels & spine_mods:
        return [p.name for p in PLANES]
    return [p.name for p in PLANES if rels & set(p.modules)]


def analyze_repo(repo: str, only: list[str] | None = None
                 ) -> list[Violation]:
    """Run Layer 5 over the repo's planes (all, or the ``only`` subset
    for ``--changed`` runs)."""
    out: list[Violation] = []
    for spec in PLANES:
        if only is not None and spec.name not in only:
            continue
        out.extend(_plane_graph(repo, spec).violations)
    return out


def ownership_map(repo: str | None = None) -> dict:
    """The runtime twin's contract, generated from the static graph:
    per-plane jax owners, the forbidden thread-name patterns (named
    non-owner roots), and the spine lock verdicts.  threadguard arms
    exactly this — hand-editing it is impossible by construction."""
    if repo is None:
        import harp_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(harp_tpu.__file__)))
    planes: dict[str, dict] = {}
    forbidden: set[str] = set()
    for spec in PLANES:
        g = _plane_graph(repo, spec)
        pats = sorted({p for rid, root in g.roots.items()
                       if rid not in spec.jax_owners
                       for p in root.name_patterns})
        planes[spec.name] = {
            "jax_owners": sorted(spec.jax_owners),
            "roots": sorted(g.roots),
            "forbidden_thread_patterns": pats,
        }
        forbidden.update(pats)
    verdicts = spine_lock_verdicts(repo)
    spines = {sp.name: {"locked": bool(verdicts.get(sp.name)),
                        "module": sp.import_path, "obj": sp.obj,
                        "mutators": list(sp.mutators)}
              for sp in SPINES}
    return {"planes": planes,
            "forbidden_thread_patterns": sorted(forbidden),
            "spines": spines}
