"""Layer 4 — CommGraph: the static communication-graph auditor.

Reference parity (SURVEY.md §6, ROADMAP "topology-aware collective
planner"): Harp's collective schedule exists only at runtime, inside
Netty socket handlers; harp-tpu's CommLedger (PR 1) made the schedule
*observable* at trace time, but still only as a side effect of running
the tracer.  TACCL (PAPERS.md arXiv:2111.04867) synthesizes collective
algorithms from exactly the artifact this module extracts: a static,
program-level *communication sketch* — the ordered collective schedule
with per-call-site payloads and loop amplification.  The extractor walks
each registered driver jaxpr (:mod:`harp_tpu.analysis.drivers`) through
``pjit``/``shard_map``/``scan``/``while``/``cond`` boundaries and emits
one :class:`CommGraph` per program; ``python -m harp_tpu lint`` ships
every program's byte sheet in its JSON row — the planner's future input.

The same walk closes the two audit gaps no earlier layer sees:

**HL301 / HL302 — the ledger cross-check.**  Extraction traces the
program with telemetry enabled, so the CommLedger records land next to
the static schedule.  Both sides key call sites identically
(:func:`harp_tpu.utils.telemetry.site_key` over the nearest frame that
:func:`~harp_tpu.utils.telemetry.is_ledger_user_frame` accepts — the
verbs' ``record_comm`` walks the live stack, this module walks the jaxpr
eqn's traceback).  A static collective with no ledger record at its site
is an untracked wire (HL301 — today the ledger can under-report and
nothing notices); a matched *exact-wire* site whose static per-shard
bytes disagree with the ledger payload is a lying byte sheet (HL302 —
the kmeans hand-computed sheet is the pinned fixture).  Quantized sites
(ledger ``wire_dtype`` set) skip the byte comparison: the ledger counts
the *logical* wire (int8 = 1 B/elem) while the lowering accumulates in
int32 — a documented, deliberate divergence.

**HL304 — hoistable collectives.**  A collective inside a loop body
whose operands depend on neither the carry nor the scanned inputs moves
identical bytes every iteration; the loop's static trip count multiplies
the wire for nothing.  Detected by forward taint from each loop's
variant invars, positionally mapped through inner call boundaries.

**HL303 — use-after-donate** is a *host-protocol* hazard, not a jaxpr
property: the serve engines donate their batch buffer
(``donate_argnums``), the CPU sim ignores donation (so tests stay
green), and silicon does not.  :class:`DonationAudit` wraps the
donating executables of a real driven pipeline (the registered
``PROTOCOLS`` in drivers.py run the serve ``ContinuousRunner`` depth-2
loop at lint time) and flags any donated buffer that is later
re-dispatched or read back through :func:`harp_tpu.utils.flightrec.
readback` — the counted D2H path all driver code uses.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from harp_tpu.analysis import Violation


def _collective_prims() -> frozenset:
    from harp_tpu.parallel.collective import COLLECTIVE_PRIMS

    return COLLECTIVE_PRIMS


# ---------------------------------------------------------------------------
# The graph
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CommSite:
    """One call site's collective traffic in one program (possibly
    several jaxpr eqns: a pytree verb emits one primitive per leaf)."""

    site: str               # telemetry.site_key shape ("kmeans.py:324")
    primitive: str          # jaxpr primitive name ("psum", "ppermute"...)
    axis: str               # mesh axis name(s) the collective runs over
    path: str               # enclosing-structure trail ("shard_map/scan")
    shapes: list[str]       # operand aval short-strings, in eqn order
    wire_dtype: str         # lowered operand dtype of the first eqn
    per_shard_bytes: int    # per-execution operand bytes, summed over eqns
    calls_per_trace: int    # number of eqns folded into this record
    amplification: int      # product of enclosing static trip counts
    dynamic: bool           # inside a while loop (trip count unknown)
    in_loop: bool           # inside any scan/while body
    loop_invariant: bool    # no operand depends on a loop-variant value
    verb: str | None = None          # matched CommLedger verb
    ledger_wire: str | None = None   # matched ledger wire_dtype

    def row(self) -> dict:
        return {
            "site": self.site, "primitive": self.primitive,
            "verb": self.verb, "axis": self.axis,
            "wire_dtype": self.wire_dtype,
            "per_shard_bytes": self.per_shard_bytes,
            "calls_per_trace": self.calls_per_trace,
            "amplification": self.amplification,
            "dynamic": self.dynamic, "path": self.path,
        }


@dataclasses.dataclass
class CommGraph:
    """One program's static communication sketch + donation aliasing."""

    program: str
    sites: list[CommSite]               # schedule order (first appearance)
    donated_args: list[int]             # flat arg indices with donation
    donated_avals: list[str]            # their aval short-strings
    ledger_sites: dict[str, list[dict]]  # site key -> trace-time records

    def bytes_per_trace(self) -> int:
        return sum(s.per_shard_bytes for s in self.sites)

    def amplified_bytes(self) -> int:
        """Per-program-execution wire bytes: each site's payload times
        its enclosing static trip counts (dynamic loops count once and
        carry the ``dynamic`` flag — a floor, not a total)."""
        return sum(s.per_shard_bytes * max(s.amplification, 1)
                   for s in self.sites)

    def sheet(self) -> dict:
        """The machine-readable byte sheet the lint JSON row carries —
        scripts/check_jsonl.py invariant 6 validates its shape."""
        return {
            "collectives": [s.row() for s in self.sites],
            "bytes_per_trace": self.bytes_per_trace(),
            "amplified_bytes": self.amplified_bytes(),
            "donated_args": list(self.donated_args),
            "donated_avals": list(self.donated_avals),
        }


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------

def _is_var(v) -> bool:
    return not hasattr(v, "val")  # Literals carry .val, Vars do not


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * dtype.itemsize


def _eqn_axis(eqn) -> str:
    ax = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(ax, (tuple, list)):
        return ",".join(str(a) for a in ax)
    return str(ax)


def _eqn_site(eqn) -> str:
    """The eqn's user call site, under the SAME frame-exclusion rules as
    the CommLedger's ``record_comm`` — the whole point of the matcher."""
    from harp_tpu.utils.telemetry import is_ledger_user_frame, site_key

    try:
        from jax._src import source_info_util

        for f in source_info_util.user_frames(eqn.source_info):
            if is_ledger_user_frame(f.file_name):
                return site_key(f.file_name, f.start_line)
    except Exception:  # pragma: no cover - jax internals moved
        pass
    return "?:0"


def _map_taint(inner_invars, outer_invars, tainted: set) -> set:
    return {iv for iv, ov in zip(inner_invars, outer_invars)
            if _is_var(ov) and ov in tainted}


def _generic_inner_jaxprs(eqn):
    """Core jaxprs hiding in an eqn's params (pjit/shard_map/custom_*),
    for primitives without special-cased control flow."""
    out = []
    for v in eqn.params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            core = getattr(item, "jaxpr", None)
            if core is not None and hasattr(core, "eqns"):
                out.append(core)
            elif hasattr(item, "eqns"):
                out.append(item)
    return out


class _Walker:
    def __init__(self):
        self.entries: list[CommSite] = []
        self._prims = _collective_prims()

    def walk(self, jaxpr, *, mult: int, dynamic: bool, in_loop: bool,
             tainted: set, path: str) -> None:
        tainted = set(tainted)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            hot = any(_is_var(v) and v in tainted for v in eqn.invars)
            if name in self._prims:
                self._record(eqn, name, mult, dynamic, in_loop, path,
                             loop_invariant=in_loop and not hot)
            if name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                length = int(eqn.params.get("length") or 1)
                nc = eqn.params["num_consts"]
                inner_t = _map_taint(body.invars, eqn.invars, tainted)
                inner_t |= set(body.invars[nc:])  # carries + xs slices
                self.walk(body, mult=mult * length, dynamic=dynamic,
                          in_loop=True, tainted=inner_t,
                          path=path + "/scan")
            elif name == "while":
                for key, nck in (("cond_jaxpr", "cond_nconsts"),
                                 ("body_jaxpr", "body_nconsts")):
                    bj = eqn.params[key].jaxpr
                    nc = eqn.params.get(nck, 0)
                    # while invars = cond_consts + body_consts + carries;
                    # positional zip only lines up for the jaxpr whose
                    # consts lead, so taint conservatively: carries are
                    # variant either way
                    inner_t = set(bj.invars[nc:])
                    self.walk(bj, mult=mult, dynamic=True, in_loop=True,
                              tainted=inner_t, path=path + "/while")
            elif name == "cond":
                for br in eqn.params["branches"]:
                    bj = getattr(br, "jaxpr", br)
                    inner_t = _map_taint(bj.invars, eqn.invars[1:],
                                         tainted)
                    self.walk(bj, mult=mult, dynamic=dynamic,
                              in_loop=in_loop, tainted=inner_t,
                              path=path + "/cond")
            else:
                for inner in _generic_inner_jaxprs(eqn):
                    if len(inner.invars) == len(eqn.invars):
                        inner_t = _map_taint(inner.invars, eqn.invars,
                                             tainted)
                    else:
                        # repacked boundary: conservative — everything
                        # variant if any operand is (never misses a
                        # variant dependency, may miss a hoist)
                        inner_t = set(inner.invars) if hot else set()
                    self.walk(inner, mult=mult, dynamic=dynamic,
                              in_loop=in_loop, tainted=inner_t,
                              path=path + "/" + name)
            if hot:
                tainted.update(eqn.outvars)

    def _record(self, eqn, name, mult, dynamic, in_loop, path,
                loop_invariant):
        site = _eqn_site(eqn)
        nbytes = sum(_aval_bytes(v) for v in eqn.invars)
        shape = [getattr(getattr(v, "aval", None), "str_short",
                         lambda: "?")() for v in eqn.invars]
        dtype = next((str(getattr(getattr(v, "aval", None), "dtype", ""))
                      for v in eqn.invars
                      if getattr(getattr(v, "aval", None), "dtype", None)
                      is not None), "?")
        for e in self.entries:
            if (e.site == site and e.primitive == name and e.path == path
                    and e.amplification == mult and e.dynamic == dynamic
                    and e.loop_invariant == loop_invariant):
                e.per_shard_bytes += nbytes
                e.calls_per_trace += 1
                e.shapes.extend(shape)
                return
        self.entries.append(CommSite(
            site=site, primitive=name, axis=_eqn_axis(eqn), path=path,
            shapes=shape, wire_dtype=dtype, per_shard_bytes=nbytes,
            calls_per_trace=1, amplification=mult, dynamic=dynamic,
            in_loop=in_loop, loop_invariant=loop_invariant))


def _donation_info(traced) -> tuple[list[int], list[str]]:
    """Flat donated-arg indices + avals from a ``.trace()`` result's
    ``args_info`` (ArgInfo carries the ``donated`` flag)."""
    try:
        import jax

        flat = jax.tree.leaves(traced.args_info)
        idx = [i for i, a in enumerate(flat)
               if bool(getattr(a, "donated", False))]
        # ArgInfo stores its aval as _aval (no public accessor)
        avals = [getattr(flat[i], "aval", None) or flat[i]._aval
                 for i in idx]
        return idx, [a.str_short() for a in avals]
    except Exception:  # pragma: no cover - older jax without args_info
        return [], []


def extract(name: str, fn, args) -> CommGraph:
    """Trace one driver program (CommLedger enabled, so the trace-time
    records land beside the static walk) and extract its CommGraph."""
    import jax

    from harp_tpu.utils import telemetry as T

    with T.scope():
        with T.ledger.run(name, steps=0):
            traced = (fn.trace(*args) if hasattr(fn, "trace")
                      else jax.jit(fn).trace(*args))
        ledger_sites: dict[str, list[dict]] = {}
        tag = T.ledger.summary().get(name, {"sites": []})
        for rec in tag["sites"]:
            ledger_sites.setdefault(rec["site"], []).append(rec)

    donated, donated_avals = _donation_info(traced)
    walker = _Walker()
    closed = traced.jaxpr
    walker.walk(closed.jaxpr, mult=1, dynamic=False, in_loop=False,
                tainted=set(), path="")
    graph = CommGraph(program=name, sites=walker.entries,
                      donated_args=donated, donated_avals=donated_avals,
                      ledger_sites=ledger_sites)
    _match_ledger(graph)
    return graph


def _match_ledger(graph: CommGraph) -> None:
    """Attach the matched ledger verb/wire to each static site."""
    from harp_tpu.parallel.collective import PRIMITIVE_VERBS

    for s in graph.sites:
        recs = graph.ledger_sites.get(s.site)
        if not recs:
            continue
        allowed = PRIMITIVE_VERBS.get(s.primitive, ())
        rec = next((r for r in recs if r["verb"] in allowed), recs[0])
        s.verb = rec["verb"]
        s.ledger_wire = rec["wire_dtype"]


# ---------------------------------------------------------------------------
# Checks (HL301 / HL302 / HL304)
# ---------------------------------------------------------------------------

def check_graph(graph: CommGraph) -> list[Violation]:
    out: list[Violation] = []
    target = f"driver:{graph.program}"

    by_site: dict[str, list[CommSite]] = {}
    for s in graph.sites:
        by_site.setdefault(s.site, []).append(s)

    for site, entries in by_site.items():
        recs = graph.ledger_sites.get(site)
        if not recs:
            prims = sorted({e.primitive for e in entries})
            nbytes = sum(e.per_shard_bytes for e in entries)
            out.append(Violation(
                "HL301", target, 0,
                f"collective(s) {prims} at {site} ({nbytes} B/shard per "
                "trace) have no CommLedger record — an untracked wire "
                "the report's bytes-on-wire claims never see; route the "
                "call through a harp_tpu.parallel.collective verb"))
            continue
        if all(r["wire_dtype"] is None for r in recs):
            static_bytes = sum(e.per_shard_bytes for e in entries)
            ledger_bytes = sum(r["payload_bytes"] for r in recs)
            if static_bytes != ledger_bytes:
                verbs = sorted({r["verb"] for r in recs})
                out.append(Violation(
                    "HL302", target, 0,
                    f"static byte sheet disagrees with the ledger at "
                    f"{site}: jaxpr operands move {static_bytes} B/shard "
                    f"per trace but the CommLedger recorded "
                    f"{ledger_bytes} B for {verbs} — one sheet is lying "
                    "(quantized wires are exempt; exact verbs must "
                    "agree to the byte)"))

    for s in graph.sites:
        if s.in_loop and s.loop_invariant and not s.dynamic:
            out.append(Violation(
                "HL304", target, 0,
                f"loop-invariant {s.primitive} at {s.site} (inside "
                f"{s.path or '/'}, trip count {s.amplification}) — its "
                f"operands depend on neither the carry nor the scanned "
                f"inputs, so {s.per_shard_bytes} B/shard re-ship every "
                "iteration; hoist the collective above the loop"))
        elif s.in_loop and s.loop_invariant and s.dynamic:
            out.append(Violation(
                "HL304", target, 0,
                f"loop-invariant {s.primitive} at {s.site} inside a "
                f"while loop ({s.path or '/'}) — identical bytes every "
                "iteration of a dynamic loop; hoist it above the loop"))
    return out


def analyze_program(name: str, fn, args) -> tuple[list[Violation],
                                                  CommGraph]:
    """Extract + check one program (the CLI's per-driver entry)."""
    graph = extract(name, fn, args)
    return check_graph(graph), graph


# ---------------------------------------------------------------------------
# HL303 — the donation audit
# ---------------------------------------------------------------------------

class DonationAudit:
    """Use-after-donate protocol recorder (HL303).

    Wrap each donating executable with :meth:`wrap`; run the host loop
    inside the audit's context (which watches
    :func:`harp_tpu.utils.flightrec.readback`, the counted D2H path).
    After a buffer rides a donated argument position, any later
    appearance — as an argument to ANY wrapped executable, or as a
    readback operand — is a violation.  Object identity is the buffer
    key; the audit holds a reference to every donated buffer so ids are
    never recycled within a run.

    The CPU sim *ignores* donation (XLA warns "Some donated buffers were
    not usable"), which is exactly why this must be a lint-time check:
    a host loop that re-reads a donated buffer passes every CPU test and
    dies (or silently reads freed memory) the first time it runs on TPU.
    """

    def __init__(self, target: str):
        self.target = target
        self.violations: list[Violation] = []
        self._donated: dict[int, str] = {}   # id(buffer) -> donor label
        self._keep: list[Any] = []           # pin ids for the run

    # -- wiring ------------------------------------------------------------
    def wrap(self, exe: Callable, donate_argnums: tuple[int, ...],
             label: str) -> Callable:
        """Wrap a donating callable: flags donated args re-dispatched
        through ANY wrapped callable, then marks this call's donated
        positions.  Delegates every other attribute (``lower``,
        ``trace``, ...) like ``flightrec.track``'s wrapper."""
        return _DonationWrapped(self, exe, tuple(donate_argnums), label)

    def __enter__(self):
        from harp_tpu.utils import flightrec

        self._obs = flightrec.observe_readbacks(self._note_readback)
        self._obs.__enter__()
        return self

    def __exit__(self, *exc):
        self._obs.__exit__(*exc)
        return False

    # -- events ------------------------------------------------------------
    def _note_readback(self, x: Any) -> None:
        donor = self._donated.get(id(x))
        if donor is not None:
            self._flag(f"host read (flightrec.readback) of a buffer "
                       f"donated to {donor} — on TPU that buffer no "
                       "longer exists; read the dispatch OUTPUT, stage "
                       "a fresh input per batch")

    def _note_dispatch(self, label: str, args: tuple,
                       donate_argnums: tuple[int, ...]) -> None:
        for pos, a in enumerate(args):
            donor = self._donated.get(id(a))
            if donor is not None:
                self._flag(f"arg {pos} of {label} was already donated "
                           f"to {donor} — a donated buffer cannot be "
                           "re-dispatched; stage a fresh buffer per "
                           "batch")
        for pos in donate_argnums:
            if pos < len(args):
                self._donated[id(args[pos])] = label
                self._keep.append(args[pos])

    def _flag(self, msg: str) -> None:
        self.violations.append(Violation("HL303", self.target, 0, msg))


class _DonationWrapped:
    __slots__ = ("_audit", "__wrapped__", "_donate", "_label")

    def __init__(self, audit: DonationAudit, exe: Callable,
                 donate_argnums: tuple[int, ...], label: str):
        self._audit = audit
        self.__wrapped__ = exe
        self._donate = donate_argnums
        self._label = label

    def __call__(self, *args, **kw):
        self._audit._note_dispatch(self._label, args, self._donate)
        return self.__wrapped__(*args, **kw)

    def __getattr__(self, name):
        return getattr(self.__wrapped__, name)


def audit_protocol(name: str, drive: Callable[[DonationAudit], None]
                   ) -> list[Violation]:
    """Run one registered host protocol under a :class:`DonationAudit`
    (the CLI's HL303 entry; ``drive`` wraps its donating executables via
    ``audit.wrap`` and runs the real pipeline on the CPU mesh)."""
    audit = DonationAudit(f"protocol:{name}")
    try:
        with audit:
            drive(audit)
    except Exception as e:  # noqa: BLE001 - a broken protocol is loud
        audit._flag(f"protocol run failed: {type(e).__name__}: {e}")
    return audit.violations
