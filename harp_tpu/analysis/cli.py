"""``python -m harp_tpu lint`` — the harplint front door.

Runs the five analysis layers (AST lints / jaxpr detectors / Mosaic
kernel audit / CommGraph communication audit / thread-root concurrency
audit), applies the committed
allowlist, prints a human report plus ONE provenance-stamped machine
line (``kind: "lint"``, printed through
:func:`harp_tpu.utils.metrics.benchmark_json` so it carries the same
backend/date/commit stamp as every bench row — ``scripts/check_jsonl.py``
invariant 6 validates the shape, including the per-program byte sheets
the CommGraph layer ships in the row), and exits non-zero when any
unallowlisted violation remains OR the allowlist carries a stale entry
(an exception excusing nothing is a rotten review record — prune it).

Fixture mode for tests / pre-commit checks of a single file:

- positional ``paths`` restrict the AST layer to those files;
- ``--changed`` restricts the AST layer to files changed vs git HEAD
  (plus untracked) — the fast dev loop; the traced layers still run in
  full, because they are program-keyed, not file-keyed;
- ``--audit-module FILE`` imports a Python file and sweeps its
  ``HARPLINT_DRIVERS`` (jaxpr + commgraph layers) / ``HARPLINT_KERNELS``
  (Mosaic layer) / ``HARPLINT_PROTOCOLS`` (donation audit) /
  ``HARPLINT_PLANES`` (thread-root layer: name -> (PlaneSpec, sources))
  dicts — the hook the seeded-fixture tests drive the traced layers
  through.

``paths`` / ``--audit-module`` skip the repo-wide default sweeps, so the
exit code reflects only the requested targets (``--changed`` does NOT:
it is a scoped full run, and only staleness reporting is disabled since
an unswept file cannot prove an entry stale).

The jax-touching layers force the CPU backend (8 simulated workers)
before first backend use — the axon site config pins ``JAX_PLATFORMS``
to the TPU relay, and a *linter* must never touch (or hang on) the
relay; see CLAUDE.md "Environment gotchas".
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

from harp_tpu.analysis import RULES, Violation, rule_ids
from harp_tpu.analysis import allowlist as allowlist_mod
from harp_tpu.analysis.astlints import iter_python_files, lint_paths
from harp_tpu.analysis.jaxpr_checks import (DEFAULT_CONST_BYTES,
                                            analyze_program)


def repo_root() -> str:
    import harp_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        harp_tpu.__file__)))


def _force_cpu_backend() -> None:
    """CPU, 8 simulated workers — BEFORE first backend use (no-op when a
    harness like tests/conftest.py already initialized the backend)."""
    import jax

    try:
        if "--xla_force_host_platform_device_count" not in os.environ.get(
                "XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                       + " --xla_force_host_platform_"
                                         "device_count=8")
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - backend already initialized
        pass


def _load_audit_module(path: str):
    import importlib.util

    name = f"_harplint_fixture_{os.path.basename(path).removesuffix('.py')}"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_jaxpr_layer(builders: dict, threshold: int) -> list[Violation]:
    out: list[Violation] = []
    for name in sorted(builders):
        target = f"driver:{name}"
        try:
            fn, args = builders[name]()
        except Exception as e:  # noqa: BLE001 - a broken builder is loud
            out.append(Violation("HL101", target, 0,
                                 f"driver builder failed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        out.extend(analyze_program(fn, args, target, threshold))
    return out


def run_commgraph_layer(builders: dict) -> tuple[list[Violation], dict]:
    """Layer 4 over driver programs: extract each CommGraph, run the
    HL301/HL302/HL304 checks, and return the per-program byte sheets
    (the lint row ships them — the future planner input)."""
    from harp_tpu.analysis import commgraph

    out: list[Violation] = []
    sheets: dict[str, dict] = {}
    for name in sorted(builders):
        target = f"driver:{name}"
        try:
            fn, args = builders[name]()
        except Exception as e:  # noqa: BLE001 - a broken builder is loud
            out.append(Violation("HL301", target, 0,
                                 f"driver builder failed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        try:
            violations, graph = commgraph.analyze_program(name, fn, args)
        except Exception as e:  # noqa: BLE001
            out.append(Violation("HL301", target, 0,
                                 f"commgraph extraction failed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        out.extend(violations)
        sheets[name] = graph.sheet()
    return out, sheets


def run_protocol_layer(builders: dict) -> list[Violation]:
    """Layer 4's donation audit (HL303) over registered host protocols
    — the serve ContinuousRunner depth-2 pipelines at lint time."""
    from harp_tpu.analysis import commgraph

    out: list[Violation] = []
    for name in sorted(builders):
        try:
            drive = builders[name]()
        except Exception as e:  # noqa: BLE001
            out.append(Violation("HL303", f"protocol:{name}", 0,
                                 f"protocol builder failed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        out.extend(commgraph.audit_protocol(name, drive))
    return out


def run_threads_layer(builders: dict | None, repo: str,
                      only: list[str] | None = None) -> list[Violation]:
    """Layer 5 (HL401-HL405) — pure ast, no jax import.  ``builders``
    maps fixture names to ``(PlaneSpec, {relpath: source})`` pairs (the
    ``HARPLINT_PLANES`` hook); ``None`` sweeps the repo's registered
    planes, restricted to ``only`` on ``--changed`` runs."""
    from harp_tpu.analysis import threadgraph

    if builders is None:
        return threadgraph.analyze_repo(repo, only=only)
    out: list[Violation] = []
    for name in sorted(builders):
        try:
            spec, sources = builders[name]
        except Exception as e:  # noqa: BLE001
            out.append(Violation("HL401", f"plane:{name}", 0,
                                 f"plane fixture malformed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        out.extend(threadgraph.analyze_sources(spec, sources))
    return out


def run_mosaic_layer(builders: dict | None) -> list[Violation]:
    from harp_tpu.analysis.mosaic_audit import audit_kernel, audit_registry

    if builders is None:
        return audit_registry()
    out: list[Violation] = []
    for name in sorted(builders):
        try:
            fn, args = builders[name]()
        except Exception as e:  # noqa: BLE001
            out.append(Violation("HL201", f"kernel:{name}", 0,
                                 f"kernel builder failed: "
                                 f"{type(e).__name__}: {e}"))
            continue
        out.extend(audit_kernel(name, fn, args))
    return out


def render(kept: list[Violation], suppressed: list[Violation],
           stale: list[dict], scanned: int) -> str:
    lines = ["== harplint report =="]
    by_rule: dict[str, list[Violation]] = {}
    for v in kept:
        by_rule.setdefault(v.rule, []).append(v)
    for rid in sorted(by_rule):
        rule = RULES.get(rid)
        title = rule.title if rule else "(unregistered rule)"
        lines.append(f"{rid} {title} — {len(by_rule[rid])} violation(s)")
        for v in by_rule[rid]:
            lines.append("  " + v.format().replace("\n", "\n  "))
    lines.append(f"{scanned} file(s) scanned; {len(kept)} violation(s), "
                 f"{len(suppressed)} allowlisted")
    for e in stale:
        lines.append(f"STALE allowlist entry: {e['rule']} {e['path']} "
                     f"({e['reason']}) matched nothing — remove it "
                     "(stale entries fail the lint)")
    lines.append("harplint: " + ("FAILED" if kept or stale else "clean"))
    return "\n".join(lines)


def build_row(kept, suppressed, stale, scanned,
              byte_sheets: dict | None = None) -> dict:
    per_rule = Counter(v.rule for v in kept)
    per_file = Counter(v.path for v in kept)
    row = {
        "kind": "lint",
        "rules": rule_ids(),
        "files_scanned": scanned,
        "violations": len(kept),
        "allowlisted": len(suppressed),
        "stale_allowlist": len(stale),
        "per_rule": dict(sorted(per_rule.items())),
        "per_file": dict(sorted(per_file.items())),
        "clean": not kept,
    }
    if byte_sheets is not None:
        # per-program static comm sheets (full-registry runs only: the
        # program names must come from analysis/drivers.py — check_jsonl
        # invariant 6 pins that, so fixture rows omit the block)
        row["byte_sheets"] = byte_sheets
    return row


def _changed_paths(repo: str) -> list[str]:
    """Repo-relative .py files changed vs git HEAD, plus untracked —
    the ``--changed`` AST scope.  Intersected with the default sweep
    set so deleted/ignored files never error."""
    import subprocess

    changed: set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=repo, capture_output=True,
                                 text=True, timeout=30)
        except Exception:  # pragma: no cover - no git in env
            return []
        if res.returncode != 0:  # pragma: no cover - not a git checkout
            return []
        changed.update(ln.strip() for ln in res.stdout.splitlines()
                       if ln.strip())
    swept = set(iter_python_files(repo))
    return sorted(p.replace(os.sep, "/") for p in changed
                  if p.replace(os.sep, "/") in swept)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m harp_tpu lint",
        description="static relay-burner analysis (AST lints + jaxpr "
                    "detectors + Mosaic kernel audit)")
    p.add_argument("paths", nargs="*",
                   help="restrict the AST layer to these files "
                        "(repo-relative or absolute); skips the default "
                        "repo-wide sweeps")
    p.add_argument("--changed", action="store_true",
                   help="restrict the AST layer to changed files and the "
                        "thread-root layer to planes owning them (vs git "
                        "HEAD, plus untracked) — the ~2 s dev loop as "
                        "the repo grows; the traced layers still run in "
                        "full (program-keyed, not file-keyed)")
    p.add_argument("--layer",
                   choices=("ast", "jaxpr", "mosaic", "commgraph",
                            "threads", "all"),
                   default="all")
    p.add_argument("--json", action="store_true",
                   help="print only the machine-readable line")
    p.add_argument("--audit-module", action="append", default=[],
                   metavar="FILE",
                   help="sweep FILE's HARPLINT_DRIVERS / HARPLINT_KERNELS "
                        "instead of the repo registries (fixture mode)")
    p.add_argument("--allowlist", default=None,
                   help="allowlist TOML (default: analysis/allowlist.toml)")
    p.add_argument("--no-allowlist", action="store_true")
    p.add_argument("--const-threshold-mb", type=float, default=None,
                   help="HL102 closed-over-constant threshold (default "
                        f"{DEFAULT_CONST_BYTES >> 20} MiB)")
    args = p.parse_args(argv)
    if args.changed and args.paths:
        p.error("--changed and explicit paths are mutually exclusive")

    repo = repo_root()
    # unconditional: even an AST-only run prints a provenance-stamped
    # line (jax.default_backend()), which must never touch the relay
    _force_cpu_backend()
    fixture_mode = bool(args.paths or args.audit_module)
    threshold = (int(args.const_threshold_mb * (1 << 20))
                 if args.const_threshold_mb is not None
                 else DEFAULT_CONST_BYTES)

    violations: list[Violation] = []
    scanned = 0
    changed_rels = (_changed_paths(repo)
                    if args.changed and not fixture_mode else None)

    if args.layer in ("ast", "all"):
        if args.paths:
            rels = [os.path.relpath(os.path.abspath(x), repo)
                    .replace(os.sep, "/") for x in args.paths]
            violations += lint_paths(repo, rels)
            scanned += len(rels)
        elif not fixture_mode:
            rels = (changed_rels if changed_rels is not None
                    else list(iter_python_files(repo)))
            violations += lint_paths(repo, rels)
            scanned += len(rels)

    fixture_drivers: dict = {}
    fixture_kernels: dict = {}
    fixture_protocols: dict = {}
    fixture_planes: dict = {}
    for mod_path in args.audit_module:
        mod = _load_audit_module(mod_path)
        fixture_drivers.update(getattr(mod, "HARPLINT_DRIVERS", {}))
        fixture_kernels.update(getattr(mod, "HARPLINT_KERNELS", {}))
        fixture_protocols.update(getattr(mod, "HARPLINT_PROTOCOLS", {}))
        fixture_planes.update(getattr(mod, "HARPLINT_PLANES", {}))

    if args.layer in ("threads", "all"):
        # pure ast — no backend, no jax import; --changed scopes to the
        # planes owning the changed files (graphs are cached per plane)
        if fixture_mode:
            if fixture_planes:
                violations += run_threads_layer(fixture_planes, repo)
        else:
            from harp_tpu.analysis.threadgraph import planes_for_paths

            only = (planes_for_paths(changed_rels)
                    if changed_rels is not None else None)
            violations += run_threads_layer(None, repo, only=only)

    if args.layer in ("jaxpr", "all"):
        if fixture_mode:
            if fixture_drivers:
                violations += run_jaxpr_layer(fixture_drivers, threshold)
        else:
            _force_cpu_backend()
            from harp_tpu.analysis.drivers import DRIVERS

            violations += run_jaxpr_layer(DRIVERS, threshold)

    if args.layer in ("mosaic", "all"):
        if fixture_mode:
            if fixture_kernels:
                violations += run_mosaic_layer(fixture_kernels)
        else:
            _force_cpu_backend()
            violations += run_mosaic_layer(None)

    byte_sheets: dict | None = None
    if args.layer in ("commgraph", "all"):
        if fixture_mode:
            if fixture_drivers:
                vs, _ = run_commgraph_layer(fixture_drivers)
                violations += vs
            if fixture_protocols:
                violations += run_protocol_layer(fixture_protocols)
        else:
            _force_cpu_backend()
            from harp_tpu.analysis.drivers import DRIVERS, PROTOCOLS

            vs, byte_sheets = run_commgraph_layer(DRIVERS)
            violations += vs
            violations += run_protocol_layer(PROTOCOLS)

    entries = [] if args.no_allowlist else allowlist_mod.load(args.allowlist)
    kept, suppressed, stale = allowlist_mod.apply(violations, entries)
    # staleness only means something when every layer swept everything:
    # a fixture run or a --changed AST scope cannot prove an entry dead,
    # and a --layer run can only judge entries of the layers that ran
    # (an AST-only run matching no HL4xx entry proves nothing about it)
    if fixture_mode or args.changed:
        stale = []
    elif args.layer != "all":
        stale = [e for e in stale
                 if RULES.get(e["rule"]) is not None
                 and RULES[e["rule"]].layer == args.layer]

    row = build_row(kept, suppressed, stale, scanned, byte_sheets)
    from harp_tpu.utils.metrics import benchmark_json

    if not args.json:
        print(render(kept, suppressed, stale, scanned))
    print(benchmark_json("lint", row), flush=True)
    # stale allowlist entries are a hard failure (same exit as an
    # unallowlisted violation): an exception excusing nothing either
    # outlived its fix or was always wrong — both need a human
    return 1 if kept or stale else 0


if __name__ == "__main__":
    sys.exit(main())
