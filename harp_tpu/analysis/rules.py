"""harplint rule registry — every trap gets an id, a layer, and its story.

Reference parity (SURVEY.md §6 has no analogue — Harp shipped no static
analysis at all; correctness discipline lived in code review): the rules
below are the CLAUDE.md "Relay performance traps" / "Environment gotchas"
folklore turned into machine-enforced invariants.  Each rule names the
trap it prevents so a violation message teaches the fix instead of just
rejecting the diff; MIGRATING.md "Running the linter" maps ids to the
original trap prose.

Five layers (see the sibling modules):

- ``HL0xx`` — source AST lints (:mod:`harp_tpu.analysis.astlints`; pure
  ``ast``, no jax import, fast enough for tier-1);
- ``HL1xx`` — jaxpr analyzers (:mod:`harp_tpu.analysis.jaxpr_checks`;
  trace on the CPU backend, zero hardware);
- ``HL2xx`` — Mosaic kernel audit (:mod:`harp_tpu.analysis.mosaic_audit`;
  cross-platform lowering plus jaxpr checks for the silicon limits local
  lowering does NOT enforce);
- ``HL3xx`` — CommGraph communication audit
  (:mod:`harp_tpu.analysis.commgraph`; the static per-call-site
  collective schedule of every registered driver program, cross-checked
  against the CommLedger's trace-time records, plus the use-after-donate
  protocol audit over the serve pipelines);
- ``HL4xx`` — thread-root concurrency audit
  (:mod:`harp_tpu.analysis.threadgraph`; the static thread-root graph of
  the serve/ingest/schedule/timing/fault/bench planes — jax ownership,
  event-loop blocking, shared-state locking, lock-across-dispatch, and
  thread lifecycle — whose ownership map also arms the runtime twin
  :mod:`harp_tpu.utils.threadguard`).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    layer: str          # "ast" | "jaxpr" | "mosaic" | "commgraph" | "threads"
    title: str
    trap: str           # the CLAUDE.md trap this rule machine-checks


RULES: dict[str, Rule] = {r.id: r for r in [
    Rule("HL000", "ast", "unparseable Python source",
         "a file the AST lints cannot parse is a file no rule protects — "
         "fix the syntax error first"),
    Rule("HL001", "ast", "raw XLA collective outside the verb layer",
         "collectives must go through harp_tpu.parallel.collective verbs "
         "(CLAUDE.md conventions) — a raw lax.p*/all_gather/all_to_all "
         "call is invisible to the CommLedger, so bytes-on-wire claims "
         "and the quantized-wire audit silently under-count"),
    Rule("HL002", "ast", "jax.random.PRNGKey in library/driver code",
         "PRNGKey(python_int) specializes the traced program on the seed "
         "— every new seed is a fresh ~140 ms remote compile; use "
         "utils.prng.key_bits / split_keys (raw uint32[2] via numpy)"),
    Rule("HL003", "ast", "jnp.asarray on host numpy data in ingest paths",
         "jnp.asarray(big_numpy) can ship the array as a compile-time "
         "literal (HTTP 413 on >~50 MB over the relay); use "
         "jax.device_put / mesh.shard_array, the counted ingest entry "
         "points"),
    Rule("HL004", "ast", "jitted driver callable not flight-tracked",
         "a jax.jit program dispatched from a driver loop without "
         "flightrec.track (or a telemetry.budget around the loop) is "
         "invisible to the dispatch/readback budgets — the 20-150 ms "
         "round-trip trap returns as soon as someone loops it"),
    Rule("HL005", "ast", "perf claim without date + chip provenance",
         "perf claims must carry measured numbers with date + chip in "
         "the docstring (CLAUDE.md conventions; see models/kmeans.py for "
         "the form) — an undated number cannot be re-audited after a "
         "toolchain or default flip"),
    Rule("HL101", "jaxpr", "scan-carry gather+DUS copy trap",
         "gathering from a scan-carried table the body also "
         "dynamic_update_slice's makes XLA copy the WHOLE table every "
         "iteration (cost LDA 20 s of a 29 s epoch) — dynamic_slice the "
         "tile first, gather tile-locally"),
    Rule("HL102", "jaxpr", "oversized closed-over constant",
         "a large array baked into the jaxpr as a compile-time constant "
         "ships with the program over the relay (HTTP 413 >~50 MB) and "
         "recompiles when it changes — pass it as an argument via "
         "device_put/shard_array"),
    Rule("HL201", "mosaic", "kernel fails Pallas→Mosaic lowering",
         "every registered Pallas kernel must lower via "
         ".trace(...).lower(lowering_platforms=('tpu',)) on the CPU "
         "backend — the no-hardware check that caught three relay "
         "burners on 2026-07-31"),
    Rule("HL202", "mosaic", "pltpu.prng_seed with >2 seed words",
         "the real TPU toolchain accepts at most TWO seed words (silicon "
         "failure 2026-08-01; local lowering does NOT enforce it) — fold "
         "extra stream ids into a word with an odd-constant multiply + "
         "xor"),
    Rule("HL203", "mosaic", "uint32→float cast inside a kernel",
         "Mosaic has no uint32→f32 cast — shift_right_logical on int32 "
         "instead (the prng-bits→uniform idiom in ops/lda_kernel.py)"),
    Rule("HL204", "mosaic", "block dim -2 not sublane-aligned",
         "a block shape whose second-to-last dim is neither a multiple "
         "of 8 nor the full array dim fails the real Mosaic layout rules "
         "— pad or retile (CLAUDE.md Mosaic limits)"),
    Rule("HL205", "mosaic", "stale kernel work declaration",
         "a kernel-registry vmem_bytes declaration that no longer "
         "matches the kernel's own byte model at the registered shape "
         "mis-prices every perfmodel ranking and memrec VMEM gate "
         "built on it — declarations must sit within memrec.PRESIZE_BAND "
         "of the model (and under the 16 MB/core VMEM ceiling); "
         "re-derive with perfmodel.presize when the kernel changes"),
    Rule("HL301", "commgraph", "collective with no CommLedger record",
         "a collective primitive in a driver jaxpr whose call site has "
         "no trace-time CommLedger record is an untracked wire — every "
         "bytes-on-wire claim the report makes silently under-counts; "
         "route it through a harp_tpu.parallel.collective verb (the "
         "verbs record; raw lax.p* does not)"),
    Rule("HL302", "commgraph", "static byte sheet disagrees with ledger",
         "the statically computed per-shard bytes of a collective site "
         "differ from the CommLedger's trace-time payload for the same "
         "site — one of the two sheets is lying, and the planner/report "
         "numbers built on them are wrong (the kmeans hand-computed "
         "sheet is the cross-check fixture)"),
    Rule("HL303", "commgraph", "use-after-donate on a dispatched buffer",
         "a buffer donated to a dispatch (donate_argnums) was read by "
         "host code or re-dispatched afterwards — the CPU sim ignores "
         "donation so tests stay green, but on TPU the buffer is gone "
         "(the serve ContinuousRunner depth-2 in-flight pipeline is the "
         "motivating case: stage a FRESH buffer per batch, never touch "
         "a donated one)"),
    Rule("HL304", "commgraph", "hoistable loop-invariant collective",
         "a collective inside a scan/fori body whose operands do not "
         "depend on the loop carry or scanned inputs re-ships identical "
         "bytes every iteration — hoist it above the loop (trip count "
         "multiplies the wire for nothing)"),
    Rule("HL401", "threads", "jax touched from a non-owner thread root",
         "a jax-touching call (tracked dispatch, device_put/shard_array, "
         "readback) reachable from a thread root other than the plane's "
         "designated jax owner — the CPU sim tolerates concurrent "
         "runtime access that corrupts state or deadlocks on silicon; "
         "route the work through the owner (the transport dispatcher "
         "thread is the pinned clean fixture)"),
    Rule("HL402", "threads", "blocking call inside the event loop",
         "a blocking call (device round trip, socket recv, unbounded "
         "Queue.get/join/wait, time.sleep) reachable from an event-loop "
         "coroutine and not awaited — a 20-150 ms relay round trip "
         "freezes every socket the loop owns; await it, bound it, or "
         "move it to the dispatcher thread"),
    Rule("HL403", "threads", "multi-root write with no common lock",
         "shared mutable state (a telemetry spine, scheduler "
         "results/queues, pipeline stats) written from two or more "
         "thread roots with no common lock on the write path — the "
         "spines' single-writer contract becomes a checked invariant "
         "instead of a comment"),
    Rule("HL404", "threads", "lock held across a dispatch/readback",
         "a lock held across a dispatch/readback boundary serializes a "
         "20-150 ms relay round trip under the lock — serve-plane "
         "head-of-line blocking; release the lock before touching the "
         "device"),
    Rule("HL405", "threads", "thread with neither daemon nor bounded join",
         "a thread started with neither daemon=True nor a bounded "
         "join(timeout) on a shutdown path hangs process exit when it "
         "blocks — on this machine, typically inside a relay call"),
]}


def rule_ids() -> list[str]:
    return sorted(RULES)
