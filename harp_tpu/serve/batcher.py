"""Request micro-batcher — a fixed ladder of padded batch shapes.

A serving loop that traces a fresh program per request size would pay the
~140 ms remote-compile trap on every novel batch (CLAUDE.md relay traps);
one that pads everything to the maximum batch would waste most of its
compute on padding at low load.  The ladder is the standard middle
ground: requests coalesce into the smallest rung that fits, so the
steady state only ever dispatches |ladder| distinct shapes — all of them
AOT-compiled at startup — and the padding fraction is bounded by the
ladder's geometry (see :meth:`ShapeLadder.bucket`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, Sequence

DEFAULT_LADDER = (1, 8, 64, 512)


class ShapeLadder:
    """The sorted set of batch sizes the server compiles for."""

    def __init__(self, rungs: Sequence[int] = DEFAULT_LADDER):
        rungs = sorted(set(int(r) for r in rungs))
        if not rungs or rungs[0] < 1:
            raise ValueError(f"ladder rungs must be >= 1, got {rungs}")
        self.rungs = tuple(rungs)

    @property
    def max_rung(self) -> int:
        return self.rungs[-1]

    def bucket(self, n: int) -> int:
        """Smallest rung >= n (n must fit under the max rung).

        Minimality bounds the padding: for the chosen rung ``s`` with
        predecessor ``p``, ``n > p`` so ``(s - n)/s < 1 - p/s`` — e.g.
        7/8 worst-case for the default 1/8/64/512 ladder, and exactly 0
        whenever ``n`` lands on a rung.
        """
        if n < 1:
            raise ValueError(f"batch of {n} rows")
        for r in self.rungs:
            if r >= n:
                return r
        raise ValueError(
            f"{n} rows exceeds the max ladder rung {self.max_rung} — "
            "split before bucketing (MicroBatcher.batches does)")

    def split(self, n: int) -> list[int]:
        """Row counts per batch for ``n`` queued rows: full max-size
        batches first, then one ragged tail batch (padded to its rung)."""
        out = [self.max_rung] * (n // self.max_rung)
        if n % self.max_rung:
            out.append(n % self.max_rung)
        return out


@dataclasses.dataclass
class Batch:
    """One padded batch: ``requests`` is [(request, row_lo, row_hi)] —
    the slice of each request's rows that landed in this batch."""

    rung: int                    # padded row count (the compiled shape)
    rows: int                    # real rows (<= rung)
    requests: list[tuple[Any, int, int]]

    @property
    def padding_frac(self) -> float:
        return (self.rung - self.rows) / self.rung


class MicroBatcher:
    """Coalesce queued (request, n_rows) pairs into ladder-shaped batches.

    Requests are answered in arrival order; a request larger than the max
    rung spans several batches (the per-request ``(lo, hi)`` row slices
    let the server reassemble it).  The batcher never holds work back:
    :meth:`batches` drains the whole queue, greedily filling max-rung
    batches and padding only the final ragged one — under sustained load
    padding tends to zero, at one queued single-row request the batch is
    the 1-rung (zero padding again).
    """

    def __init__(self, ladder: ShapeLadder | Sequence[int] = DEFAULT_LADDER):
        self.ladder = (ladder if isinstance(ladder, ShapeLadder)
                       else ShapeLadder(ladder))
        self._queue: list[tuple[Any, int]] = []
        # running padding accounting (the skew spine's padding_frac idiom)
        self.padded_rows = 0
        self.real_rows = 0

    def put(self, request: Any, n_rows: int) -> None:
        if n_rows < 1:
            raise ValueError(f"request with {n_rows} rows")
        self._queue.append((request, int(n_rows)))

    def __len__(self) -> int:
        return sum(n for _, n in self._queue)

    def batches(self) -> Iterator[Batch]:
        """Drain the queue into ladder-shaped batches (arrival order)."""
        queue, self._queue = self._queue, []
        pending: list[tuple[Any, int, int]] = []  # (request, lo, hi)
        pending_rows = 0

        def flush() -> Batch:
            nonlocal pending, pending_rows
            rung = self.ladder.bucket(pending_rows)
            b = Batch(rung=rung, rows=pending_rows, requests=pending)
            self.real_rows += pending_rows
            self.padded_rows += rung - pending_rows
            pending, pending_rows = [], 0
            return b

        for req, n in queue:
            taken = 0
            while taken < n:
                room = self.ladder.max_rung - pending_rows
                take = min(n - taken, room)
                pending.append((req, taken, taken + take))
                pending_rows += take
                taken += take
                if pending_rows == self.ladder.max_rung:
                    yield flush()
        if pending_rows:
            yield flush()

    def padding_frac(self) -> float:
        """Cumulative padded / dispatched rows (0.0 before any batch)."""
        total = self.real_rows + self.padded_rows
        return self.padded_rows / total if total else 0.0
