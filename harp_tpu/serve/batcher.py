"""Request micro-batcher — a fixed ladder of padded batch shapes.

A serving loop that traces a fresh program per request size would pay the
~140 ms remote-compile trap on every novel batch (CLAUDE.md relay traps);
one that pads everything to the maximum batch would waste most of its
compute on padding at low load.  The ladder is the standard middle
ground: requests coalesce into the smallest rung that fits, so the
steady state only ever dispatches |ladder| distinct shapes — all of them
AOT-compiled at startup — and the padding fraction is bounded by the
ladder's geometry (see :meth:`ShapeLadder.bucket`).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Iterator, Sequence

DEFAULT_LADDER = (1, 8, 64, 512)


class ShapeLadder:
    """The sorted set of batch sizes the server compiles for."""

    def __init__(self, rungs: Sequence[int] = DEFAULT_LADDER):
        rungs = sorted(set(int(r) for r in rungs))
        if not rungs or rungs[0] < 1:
            raise ValueError(f"ladder rungs must be >= 1, got {rungs}")
        self.rungs = tuple(rungs)

    @property
    def max_rung(self) -> int:
        return self.rungs[-1]

    def bucket(self, n: int) -> int:
        """Smallest rung >= n (n must fit under the max rung).

        Minimality bounds the padding: for the chosen rung ``s`` with
        predecessor ``p``, ``n > p`` so ``(s - n)/s < 1 - p/s`` — e.g.
        7/8 worst-case for the default 1/8/64/512 ladder, and exactly 0
        whenever ``n`` lands on a rung.
        """
        if n < 1:
            raise ValueError(f"batch of {n} rows")
        for r in self.rungs:
            if r >= n:
                return r
        raise ValueError(
            f"{n} rows exceeds the max ladder rung {self.max_rung} — "
            "split before bucketing (MicroBatcher.batches does)")

    def floor_rung(self, n: int) -> int:
        """Largest rung <= n (n >= 1; rung 1 is the floor of floors)."""
        if n < 1:
            raise ValueError(f"batch of {n} rows")
        best = self.rungs[0]
        for r in self.rungs:
            if r <= n:
                best = r
        return best

    def split(self, n: int) -> list[int]:
        """Row counts per batch for ``n`` queued rows: full max-size
        batches first, then one ragged tail batch (padded to its rung)."""
        out = [self.max_rung] * (n // self.max_rung)
        if n % self.max_rung:
            out.append(n % self.max_rung)
        return out


@dataclasses.dataclass
class Batch:
    """One padded batch: ``requests`` is [(request, row_lo, row_hi)] —
    the slice of each request's rows that landed in this batch."""

    rung: int                    # padded row count (the compiled shape)
    rows: int                    # real rows (<= rung)
    requests: list[tuple[Any, int, int]]
    #: scheduler-assigned formation ordinal (continuous plane only):
    #: the request→batch join key the request tracer records, so a
    #: trace can say WHICH batch carried which row slice (PR 12)
    seq: int = -1

    @property
    def padding_frac(self) -> float:
        return (self.rung - self.rows) / self.rung


class MicroBatcher:
    """Coalesce queued (request, n_rows) pairs into ladder-shaped batches.

    Requests are answered in arrival order; a request larger than the max
    rung spans several batches (the per-request ``(lo, hi)`` row slices
    let the server reassemble it).  The batcher never holds work back:
    :meth:`batches` drains the whole queue, greedily filling max-rung
    batches and padding only the final ragged one — under sustained load
    padding tends to zero, at one queued single-row request the batch is
    the 1-rung (zero padding again).
    """

    def __init__(self, ladder: ShapeLadder | Sequence[int] = DEFAULT_LADDER):
        self.ladder = (ladder if isinstance(ladder, ShapeLadder)
                       else ShapeLadder(ladder))
        self._queue: list[tuple[Any, int]] = []
        # running padding accounting (the skew spine's padding_frac idiom)
        self.padded_rows = 0
        self.real_rows = 0

    def put(self, request: Any, n_rows: int) -> None:
        if n_rows < 1:
            raise ValueError(f"request with {n_rows} rows")
        self._queue.append((request, int(n_rows)))

    def __len__(self) -> int:
        return sum(n for _, n in self._queue)

    def batches(self) -> Iterator[Batch]:
        """Drain the queue into ladder-shaped batches (arrival order)."""
        queue, self._queue = self._queue, []
        pending: list[tuple[Any, int, int]] = []  # (request, lo, hi)
        pending_rows = 0

        def flush() -> Batch:
            nonlocal pending, pending_rows
            rung = self.ladder.bucket(pending_rows)
            b = Batch(rung=rung, rows=pending_rows, requests=pending)
            self.real_rows += pending_rows
            self.padded_rows += rung - pending_rows
            pending, pending_rows = [], 0
            return b

        for req, n in queue:
            taken = 0
            while taken < n:
                room = self.ladder.max_rung - pending_rows
                take = min(n - taken, room)
                pending.append((req, taken, taken + take))
                pending_rows += take
                taken += take
                if pending_rows == self.ladder.max_rung:
                    yield flush()
        if pending_rows:
            yield flush()

    def padding_frac(self) -> float:
        """Cumulative padded / dispatched rows (0.0 before any batch)."""
        total = self.real_rows + self.padded_rows
        return self.padded_rows / total if total else 0.0


@dataclasses.dataclass
class _Pending:
    """One admitted request in the continuous queue."""

    key: Any
    n_rows: int
    taken: int        # rows already placed into dispatched batches
    arrival: float    # scheduler-clock admission time


class ContinuousScheduler:
    """Admit-while-in-flight ladder scheduler (the continuous half of
    ``harp serve``).

    :class:`MicroBatcher` models PR 6's burst-drain plane: the queue is
    filled once, drained to empty, and nothing can be admitted until the
    drain completes.  This scheduler keeps one persistent FIFO of
    request rows that :meth:`put` may extend at ANY time — in
    particular while device batches are in flight — and hands out one
    ladder-shaped batch per :meth:`next_batch` call, so admission,
    staging and compute overlap instead of alternating.

    Two measured policy knobs (CPU-sim sweep 2026-08-04, 8 sim workers,
    kmeans k=100 d=300 — see ``serve/bench.py`` sustained mode):

    - ``max_queue_delay_s`` — the flush deadline: a queued row never
      waits longer than this for a fuller rung.  Binds only in the
      mid-load regime (at low load the idle-mesh rule dispatches
      immediately; at saturation the depth rule fires first); raising
      it past ~2 batch times bought no extra batching at 2× the queue
      p99 in the sweep, so the default stays at 5 ms ≈ one 512-rung
      batch time.
    - ``rung_policy`` — ``"adaptive"`` (default) holds work back while
      a batch is in flight until the max rung fills or the deadline
      expires: deep queues ride full max-rung batches (the 1.7× qps
      lever of the sustained A/B: 512-rungs at ~54k rows/s vs the
      64-rung burst plane's ~18k).  ``"greedy"`` dispatches whatever is
      queued at the minimal covering rung (PR 6's no-holding-back rule
      with continuous admission) — lowest queueing delay, worst
      padding; the A/B bench row records the tradeoff.

    The dispatch decision needs to know whether the mesh is busy, so
    :meth:`ready` takes ``idle``: work is NEVER held back while the
    mesh idles (a lone 1-row request still gets its 1-rung latency).
    Arrival order is FIFO — rows leave in admission order, so responses
    complete in admission order and per-connection ordering is free.
    """

    def __init__(self, ladder: ShapeLadder | Sequence[int] = DEFAULT_LADDER,
                 *, max_queue_delay_s: float = 0.005,
                 rung_policy: str = "adaptive", overhead_rows: int = 64):
        if rung_policy not in ("adaptive", "greedy"):
            raise ValueError(f"rung_policy {rung_policy!r} must be "
                             "'adaptive' or 'greedy'")
        self.ladder = (ladder if isinstance(ladder, ShapeLadder)
                       else ShapeLadder(ladder))
        self.max_queue_delay_s = float(max_queue_delay_s)
        self.rung_policy = rung_policy
        # batch cost model: cost(rung) ∝ overhead_rows + rung.  Measured
        # 2026-08-04 (8-sim-worker CPU, kmeans k=100 d=300): ~1.0 ms
        # fixed dispatch overhead vs ~17 µs/row marginal ≈ 59 rows →
        # 64.  Drives the nibble-vs-pad rung choice in next_batch: tiny
        # rungs are overhead-dominated (padding 3 rows up to the 8-rung
        # beats three 1-rung dispatches), big rungs are compute-
        # dominated (two full 64-rungs beat one 20%-filled 512).
        self.overhead_rows = int(overhead_rows)
        self._queue: collections.deque[_Pending] = collections.deque()
        self.queued_rows = 0
        self.padded_rows = 0
        self.real_rows = 0
        self.batches_formed = 0  # monotone Batch.seq source

    def put(self, key: Any, n_rows: int, now: float) -> None:
        """Admit a request (legal mid-flight — that is the point)."""
        if n_rows < 1:
            raise ValueError(f"request with {n_rows} rows")
        self._queue.append(_Pending(key, int(n_rows), 0, float(now)))
        self.queued_rows += int(n_rows)

    def __len__(self) -> int:
        return self.queued_rows

    def oldest_wait(self, now: float) -> float:
        return (now - self._queue[0].arrival) if self._queue else 0.0

    def next_deadline(self) -> float | None:
        """Scheduler-clock instant at which the flush rule fires, or
        None when nothing is queued (the TCP pump sleeps until this)."""
        if not self._queue:
            return None
        return self._queue[0].arrival + self.max_queue_delay_s

    def ready(self, now: float, idle: bool) -> bool:
        """Should the caller dispatch a batch right now?"""
        if not self.queued_rows:
            return False
        if idle or self.rung_policy == "greedy":
            return True
        if self.queued_rows >= self.ladder.max_rung:
            return True
        return self.oldest_wait(now) >= self.max_queue_delay_s

    def next_batch(self, now: float) -> Batch | None:
        """Pop one ladder-shaped batch off the queue head (FIFO rows).

        Rung choice is cost-aware (the burst batcher's minimal-cover
        rule is wrong for a PERSISTENT queue: covering a 100-row
        backlog with the 512 rung computes 5× the needed rows — the
        first sustained sweep measured exactly that, 0.76 padding_frac
        and a 0.81× qps REGRESSION before this rule; 2026-08-04,
        8-sim-worker CPU mesh):

        - backlog >= max rung → one full max-rung batch;
        - else compare, under ``cost(rung) ∝ overhead_rows + rung``,
          serving the backlog as full ``floor_rung`` nibbles vs one
          padded covering batch, and take whichever is cheaper: a full
          64-rung nibble off a 100-row backlog, but 3 rows padded up
          to the 8-rung (three 1-rung dispatches cost 3× the fixed
          overhead for the same work).

        Oversized requests span successive calls via their ``(lo, hi)``
        slices exactly as the burst batcher's batches do.  Returns None
        on an empty queue — the ``ready`` policy, not this method,
        decides *whether* now is a good time.  ``rung_policy="greedy"``
        always covers the whole queue at the minimal rung (PR 6's
        rule), which is the knob's other arm in the sustained A/B.
        """
        if not self.queued_rows:
            return None
        rows = min(self.queued_rows, self.ladder.max_rung)
        if (self.rung_policy == "adaptive"
                and rows < self.ladder.max_rung):
            floor = self.ladder.floor_rung(rows)
            if floor < rows:  # not an exact rung fit
                nibble_cost = ((self.overhead_rows + floor)
                               * -(-rows // floor))
                pad_cost = self.overhead_rows + self.ladder.bucket(rows)
                if nibble_cost < pad_cost:
                    rows = floor
        rung = self.ladder.bucket(rows)
        requests: list[tuple[Any, int, int]] = []
        left = rows
        while left:
            p = self._queue[0]
            take = min(left, p.n_rows - p.taken)
            requests.append((p.key, p.taken, p.taken + take))
            p.taken += take
            left -= take
            if p.taken == p.n_rows:
                self._queue.popleft()
        self.queued_rows -= rows
        self.real_rows += rows
        self.padded_rows += rung - rows
        seq = self.batches_formed
        self.batches_formed += 1
        return Batch(rung=rung, rows=rows, requests=requests, seq=seq)

    def padding_frac(self) -> float:
        """Cumulative padded / dispatched rows (0.0 before any batch)."""
        total = self.real_rows + self.padded_rows
        return self.padded_rows / total if total else 0.0

    def expire(self, now: float, deadline_s: float) -> list:
        """Remove queued requests whose deadline already passed — the
        load-shedding half of the fault plane (PR 10).  Only requests
        with NO rows in a dispatched batch are removable (``taken > 0``
        means earlier segments are in flight and the reassembly contract
        owns them — those complete late and count as deadline misses);
        expired keys are returned so the caller answers each with a
        structured shed error instead of unbounded latency."""
        expired: list = []
        keep: collections.deque[_Pending] = collections.deque()
        for p in self._queue:
            if p.taken == 0 and (now - p.arrival) > deadline_s:
                expired.append(p.key)
                self.queued_rows -= p.n_rows
            else:
                keep.append(p)
        self._queue = keep
        return expired

    def discard(self, keys: set) -> None:
        """Drop the still-queued rows of ``keys`` (a hard-failed batch's
        requests must not leave tail segments behind to dispatch into a
        request that was already answered with an error)."""
        keep: collections.deque[_Pending] = collections.deque()
        for p in self._queue:
            if p.key in keys:
                self.queued_rows -= p.n_rows - p.taken
            else:
                keep.append(p)
        self._queue = keep
