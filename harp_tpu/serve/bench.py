"""Serving benchmark — qps + latency percentiles as ``kind:"serve"`` rows.

Self-contained (synthetic state + synthetic requests), so it runs on the
relay without a checkpoint on disk — the ``serve_kmeans`` /
``serve_mfsgd_topk`` configs in scripts/measure_all.py and the
``python -m harp_tpu serve <app> --bench`` CLI both route here.  The
emitted row is validated by scripts/check_jsonl.py invariant 7: latency
percentiles monotone (p50 ≤ p95 ≤ p99), qps > 0, and — the serving
loop's whole point — ``steady_compiles == 0`` (the CompileWatch delta
over the timed region; a row claiming serve throughput while silently
recompiling per batch must fail the checker, not enter BASELINE.md).

Latency accounting: requests are issued in bursts (the micro-batcher
sees a real queue, not one request at a time); a request's latency is
the time from its burst's submission to the completion of the batch
that produced its last row — queueing plus service, the number a client
would observe.

:func:`benchmark_sustained` is the continuous-batching A/B (PR 7): one
seeded deterministic arrival trace replayed through BOTH request planes
— the PR-6 burst-drain plane (admission quantum ``burst_admit``, PR 6's
own bench burst knob; a real stdio deployment is bounded harder by the
~64 KiB pipe window) and the continuous plane (admit-while-in-flight,
:class:`~harp_tpu.serve.server.ContinuousRunner`).  Latency here is
honest per-request ARRIVAL→response (not burst submit), throughput is
offered vs achieved qps (empirical offered from the trace, so
``achieved <= offered`` by construction), and queue depth percentiles
are sampled every scheduler window.  Service times are measured live;
arrivals ride a virtual timeline (event-driven replay: ``now`` advances
by each window's measured wall time or jumps to the next arrival when
idle), so the replay is deterministic up to real service-time noise and
never sleeps.  Measured CPU-sim A/B (2026-08-04, 8 sim workers, kmeans
k=100 d=300, single-row requests): continuous fills 512-rungs from the
backlog (~54k rows/s) where the burst plane is capped at its admission
window (64-rung batches, ~18k rows/s) — the committed row's
``qps_ratio_vs_burst`` carries the number.
"""

from __future__ import annotations

import tempfile
import time

import numpy as np

from harp_tpu import health as health_mod
from harp_tpu.serve.engines import ENGINES
from harp_tpu.serve.server import Server
from harp_tpu.utils import flightrec, memrec, telemetry
from harp_tpu.utils.fault import FaultInjector

DEFAULT_LADDER = (1, 8, 64, 512)


def benchmark(app: str = "kmeans", n_requests: int = 256,
              rows_per_request: int = 1, burst: int = 64,
              ladder=DEFAULT_LADDER, mesh=None, seed: int = 0,
              state_shape: dict | None = None, topk: int = 10,
              cache_dir: str | None = None) -> dict:
    """Serve ``n_requests`` synthetic requests; return the bench row.

    ``state_shape`` forwards to the engine's ``synthetic_state`` (e.g.
    ``{"n_users": 138_493, "n_items": 26_744, "rank": 64}`` for the
    ML-20M-shaped mfsgd config).  ``cache_dir=None`` uses a fresh temp
    dir, so the AOT cache path (compile → persist → it's a cold start)
    is exercised without polluting a real cache.
    """
    from harp_tpu.parallel.mesh import current_mesh

    if app not in ENGINES:
        raise ValueError(f"unknown serve app {app!r}")
    mesh = mesh or current_mesh()
    rng = np.random.default_rng(seed)
    state = ENGINES[app].synthetic_state(rng, **(state_shape or {}))
    engine_opts = {"topk": topk} if app == "mfsgd" else {}

    tmp = None
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="harp_serve_aot_")
        cache_dir = tmp.name
    try:
        srv = Server(app, state=state, mesh=mesh, ladder=ladder,
                     cache_dir=cache_dir, budget_action="warn",
                     engine_opts=engine_opts)
        # telemetry ON (without resetting ambient collectors: bench.py /
        # measure_all deltas over the same counters must stay monotone)
        # so CompileWatch evidence backs the steady_compiles claim
        with telemetry.scope(True, reset=False):
            t0 = time.perf_counter()
            info = srv.startup()
            startup_s = time.perf_counter() - t0
            # static HBM footprint of this app's executables (memrec /
            # AOT sidecar, PR 19) — the multi-tenant admission input;
            # 0 when the backend exposes no memory_analysis
            exec_hbm = memrec.ledger.exec_total()

            reqs = [srv.engine.synthetic_request(rng, rows_per_request)
                    for _ in range(n_requests)]
            # warmup burst: first dispatch of every executable off-clock
            warm = [srv.engine.synthetic_request(rng, rows_per_request)
                    for _ in range(min(burst, 8))]
            srv.process(warm)

            srv.steady.reset()
            base = flightrec.snapshot()
            latencies_ms: list[float] = []
            t0 = time.perf_counter()
            for lo in range(0, n_requests, burst):
                chunk = reqs[lo:lo + burst]
                responses = srv.process(chunk)
                bad = [r for r in responses if r and "error" in r]
                if bad:
                    raise RuntimeError(f"serve bench request failed: "
                                       f"{bad[0]['error']}")
                latencies_ms.extend(_request_latencies_ms(srv, chunk))
            wall = time.perf_counter() - t0
            steady = flightrec.delta_since(base)
        p50, p95, p99 = np.percentile(latencies_ms, [50, 95, 99])
        return {
            "kind": "serve", "app": app,
            "qps": n_requests / wall,
            "rows_per_sec": n_requests * rows_per_request / wall,
            "p50_ms": round(float(p50), 4),
            "p95_ms": round(float(p95), 4),
            "p99_ms": round(float(p99), 4),
            "steady_compiles": steady["compiles"],
            "steady_dispatches": steady["dispatches"],
            "steady_readbacks": steady["readbacks"],
            "budget_violations": srv.steady.violations,
            "batches": srv.steady.batches,
            "padding_frac": round(srv.batcher.padding_frac(), 6),
            "startup_sec": round(startup_s, 4),
            "startup_compiles": info["compiles"],
            "cache_hits": info["cache_hits"],
            "cache_misses": info["cache_misses"],
            "exec_hbm_bytes": exec_hbm,
            "n_requests": n_requests,
            "rows_per_request": rows_per_request,
            "burst": burst,
            "ladder": list(srv.ladder.rungs),
            "num_workers": mesh.num_workers,
        }
    finally:
        if tmp is not None:
            tmp.cleanup()


def _pctls(xs, ps=(50, 95, 99)) -> tuple[float, ...]:
    if not len(xs):
        return tuple(0.0 for _ in ps)
    return tuple(round(float(v), 4) for v in np.percentile(list(xs), ps))


def _rank_pctls(xs, ps=(50, 95, 99)) -> tuple[float, ...]:
    """Ceil-rank (inverse-CDF) percentiles — the SAME rank convention
    :class:`~harp_tpu.utils.reqtrace.LogHist` uses, so the win_* vs
    exact comparison is bucketization error alone, no interpolation
    slack."""
    import math

    if not len(xs):
        return tuple(0.0 for _ in ps)
    arr = sorted(float(v) for v in xs)
    return tuple(round(arr[max(1, math.ceil(p / 100 * len(arr))) - 1], 4)
                 for p in ps)


def _burst_replay(srv: Server, reqs: list[dict], arrivals: np.ndarray,
                  burst_admit: int) -> dict:
    """The PR-6 plane on the trace: admit up to ``burst_admit`` arrived
    requests, ``process()`` the burst to completion (no admission while
    its batches are in flight), repeat.  Completion time for every
    request in a burst is the burst's end — exactly when serve_stdio
    writes the responses."""
    n = len(reqs)
    now, i = 0.0, 0
    lat_ms: list[float] = []
    qdepth: list[int] = []
    pad0 = (srv.batcher.real_rows, srv.batcher.padded_rows)
    while i < n:
        if arrivals[i] > now:
            now = float(arrivals[i])
        arrived = int(np.searchsorted(arrivals, now, side="right"))
        take = min(arrived - i, burst_admit)
        qdepth.append(arrived - i - take)  # backlog the window left out
        t0 = time.perf_counter()
        responses = srv.process(reqs[i:i + take])
        now += time.perf_counter() - t0
        bad = [r for r in responses if r and "error" in r]
        if bad:
            raise RuntimeError(f"burst replay request failed: "
                               f"{bad[0]['error']}")
        lat_ms.extend((now - arrivals[j]) * 1e3
                      for j in range(i, i + take))
        i += take
    p50, p95, p99 = _pctls(lat_ms)
    q50, q95, q99 = _pctls(qdepth)
    real = srv.batcher.real_rows - pad0[0]
    padded = srv.batcher.padded_rows - pad0[1]
    return {"qps": n / now, "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
            "qdepth_p50": q50, "qdepth_p95": q95, "qdepth_p99": q99,
            "padding_frac": round(padded / max(1, real + padded), 6),
            "span_s": now}


def _continuous_replay(srv: Server, runner, reqs: list[dict],
                       arrivals: np.ndarray) -> dict:
    """The continuous plane on the same trace: every request is admitted
    the moment it has arrived — including while batches are in flight —
    and the runner's window pipeline does the rest.

    Degraded-mode accounting (PR 10): a response is either a *serve*
    (``result``), a structured *shed* (``shed: true`` — queue bound or
    deadline), or — only when the runner exhausted its dispatch retries
    — a hard failure.  Anything else raises: even under chaos, EVERY
    admitted request must come back as exactly one of the three, and
    ``served + shed + failed == offered`` is the identity check_jsonl
    invariant 9 enforces on the committed row.
    """
    n = len(reqs)
    now, i = 0.0, 0
    answered = served = shed = failed = 0
    lat_ms: list[float] = []
    qdepth: list[int] = []

    def account(pairs):
        nonlocal answered, served, shed, failed
        for key, resp in pairs:
            answered += 1
            if "result" in resp:
                served += 1
                lat_ms.append((now - arrivals[key]) * 1e3)
            elif resp.get("shed"):
                shed += 1
            elif "error" in resp and "engine failure" in resp["error"]:
                failed += 1
            else:
                raise RuntimeError(f"continuous replay request failed: "
                                   f"{resp.get('error')}")

    while answered < n:
        while i < n and arrivals[i] <= now:
            account(runner.submit(i, reqs[i], now=float(arrivals[i])))
            i += 1
        if not len(runner.sched) and not runner._in_flight and i < n:
            now = float(arrivals[i])  # idle: jump to the next arrival
            continue
        qdepth.append(i - answered)  # arrived-but-unanswered occupancy
        t0 = time.perf_counter()
        out = runner.step(now)
        now += time.perf_counter() - t0
        account(out)
    p50, p95, p99 = _pctls(lat_ms)
    q50, q95, q99 = _pctls(qdepth)
    return {"qps": served / now if now > 0 else 0.0,
            "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
            "qdepth_p50": q50, "qdepth_p95": q95, "qdepth_p99": q99,
            "padding_frac": round(runner.sched.padding_frac(), 6),
            "served": served, "shed": shed, "failed": failed,
            # the STREAMING percentiles at end-of-replay (PR 12):
            # bounded-memory log-bucket histograms fed at the runner's
            # completion clock.  Their exact-sample accuracy reference
            # is runner.latencies_ms (the SAME events, same clock) —
            # p50/p95/p99 above additionally include the completing
            # window's host wall (the client-observed basis), so only
            # the runner-basis pair is a pure bucket-error comparison
            # (win_rel_err is that documented bound).
            "window": runner.win.snapshot(now),
            "runner_pctls_ms": _rank_pctls(runner.latencies_ms),
            "span_s": now}


def benchmark_sustained(app: str = "kmeans", n_requests: int = 512,
                        rows_per_request: int = 1,
                        offered_qps: float | None = None,
                        offered_factor: float = 2.0,
                        burst_admit: int = 64,
                        max_queue_delay_ms: float = 5.0,
                        rung_policy: str = "adaptive",
                        ladder=DEFAULT_LADDER, mesh=None, seed: int = 0,
                        state_shape: dict | None = None, topk: int = 10,
                        cache_dir: str | None = None,
                        deadline_ms: float | None = None,
                        max_queue_rows: int | None = None,
                        max_retries: int = 3,
                        fault_rate: float = 0.0,
                        fault_ordinals: tuple[int, ...] | None = None,
                        fault_seed: int = 0) -> dict:
    """Sustained-load burst-vs-continuous A/B on one seeded trace.

    ``offered_qps=None`` calibrates: a short closed-loop burst run
    measures the burst plane's capacity and the trace offers
    ``offered_factor``× it, so both planes run saturated (the regime
    where admission policy, not arrival luck, decides throughput).  The
    returned row is the CONTINUOUS plane's evidence (``qps`` == its
    achieved qps, so check_jsonl invariant 7 grades the new plane), with
    the burst plane's numbers alongside as ``burst_*`` and the headline
    ``qps_ratio_vs_burst``.

    Degraded mode (PR 10): ``deadline_ms`` / ``max_queue_rows`` turn on
    the continuous plane's shedding, and ``fault_rate`` arms a seeded
    :class:`~harp_tpu.utils.fault.FaultInjector` on the dispatch site
    for the continuous replay — so "the server degrades instead of
    dying" is a measured number: the row's ``shed_frac`` /
    ``deadline_miss_frac`` / ``fault_retries`` fields, with the
    ``served + shed + failed == offered`` identity and the usual
    ``steady_compiles == 0`` both machine-checked by check_jsonl
    (invariants 9 and 7).  Faults are injected on the CONTINUOUS plane
    only (the burst arm stays the clean incumbent); ``fault_ordinals``
    pins EXACT 1-based dispatch events instead of a probability (the
    deterministic chaos the health acceptance test drives).

    Health sentinel (PR 14): the continuous replay runs with the SLO
    burn detector live on the runner AND a warn-mode "one staging per
    batch window" budget (``steady.h2d_calls=1`` — a retry-with-restage
    legitimately stages twice, and that drift lands in a budget_drift
    health row instead of a scrolled RuntimeWarning).  The row's
    ``health_*`` fields summarize the run's findings; a fault-free,
    unshed run reports zero.
    """
    from harp_tpu.parallel.mesh import current_mesh

    if app not in ENGINES:
        raise ValueError(f"unknown serve app {app!r}")
    mesh = mesh or current_mesh()
    rng = np.random.default_rng(seed)
    state = ENGINES[app].synthetic_state(rng, **(state_shape or {}))
    engine_opts = {"topk": topk} if app == "mfsgd" else {}

    tmp = None
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="harp_serve_aot_")
        cache_dir = tmp.name
    try:
        srv = Server(app, state=state, mesh=mesh, ladder=ladder,
                     cache_dir=cache_dir, budget_action="warn",
                     engine_opts=engine_opts)
        with telemetry.scope(True, reset=False):
            t0 = time.perf_counter()
            info = srv.startup()
            startup_s = time.perf_counter() - t0
            exec_hbm = memrec.ledger.exec_total()

            # warm EVERY rung off-clock (first dispatch of an executable
            # can transfer constants)
            for rung in srv.ladder.rungs:
                srv.process([_rows_request(srv, rng, rung)])

            reqs = [srv.engine.synthetic_request(rng, rows_per_request)
                    for _ in range(n_requests)]
            nominal = offered_qps
            calibrated = None
            if nominal is None:
                cal = [srv.engine.synthetic_request(rng, rows_per_request)
                       for _ in range(min(4 * burst_admit, n_requests))]
                t0 = time.perf_counter()
                for lo in range(0, len(cal), burst_admit):
                    srv.process(cal[lo:lo + burst_admit])
                calibrated = len(cal) / (time.perf_counter() - t0)
                nominal = offered_factor * calibrated
            gaps = rng.exponential(1.0 / nominal, size=n_requests)
            arrivals = np.cumsum(gaps)
            arrivals -= arrivals[0]

            burst = _burst_replay(srv, reqs, arrivals, burst_admit)

            runner = srv.make_runner(
                max_queue_delay_s=max_queue_delay_ms / 1e3,
                rung_policy=rung_policy,
                deadline_s=(deadline_ms / 1e3 if deadline_ms else None),
                max_queue_rows=max_queue_rows, max_retries=max_retries,
                # window sized past any replay so the win_* fields and
                # the exact percentiles describe the SAME sample set —
                # the bucket-error comparison is apples-to-apples (live
                # servers keep the 60 s rolling default)
                stats_window_s=3600.0)
            fault_spec = (fault_ordinals if fault_ordinals
                          else fault_rate if fault_rate else None)
            injector = FaultInjector(
                seed=fault_seed,
                fail={"dispatch": fault_spec}
                if fault_spec is not None else None)
            srv.steady.reset()
            # the staging discipline as a warn-mode budget: one counted
            # put_input per batch window.  A retry-with-restage breaks
            # it BY DESIGN (HL303 demands the fresh buffer) — the point
            # is that the drift becomes a budget_drift health row, i.e.
            # committed evidence that this run restaged under faults.
            srv.steady.limits["h2d_calls"] = 1
            hmark = health_mod.monitor.mark()
            base = flightrec.snapshot()
            with injector.arm():
                cont = _continuous_replay(srv, runner, reqs, arrivals)
            steady = flightrec.delta_since(base)
            runner.verify_exact()  # exact accounting even under faults:
            # injected faults fire BEFORE the dispatch counts, so the
            # totals stay one dispatch + one readback per clean batch
        offered_emp = (n_requests / float(arrivals[-1])
                       if arrivals[-1] > 0 else float(nominal))
        return {
            "kind": "serve", "app": app, "mode": "sustained",
            "rung_policy": rung_policy,
            "offered_qps": round(min(offered_emp, 1e12), 4),
            "offered_qps_nominal": round(float(nominal), 4),
            "calibrated_burst_qps": (round(calibrated, 4)
                                     if calibrated else None),
            "achieved_qps": round(cont["qps"], 4),
            "qps": round(cont["qps"], 4),
            "p50_ms": cont["p50_ms"], "p95_ms": cont["p95_ms"],
            "p99_ms": cont["p99_ms"],
            "qdepth_p50": cont["qdepth_p50"],
            "qdepth_p95": cont["qdepth_p95"],
            "qdepth_p99": cont["qdepth_p99"],
            # rolling-window (streaming-histogram) twins of the exact
            # percentiles above — what a LIVE server reports through the
            # TCP stats line; agreement is bounded by win_rel_err
            # (reqtrace.QUANTILE_REL_ERR, the log-bucket width)
            "win_p50_ms": cont["window"]["p50_ms"],
            "win_p95_ms": cont["window"]["p95_ms"],
            "win_p99_ms": cont["window"]["p99_ms"],
            "win_qdepth_p99": cont["window"]["qdepth_p99"],
            "win_samples": cont["window"]["samples"],
            "win_rel_err": cont["window"]["rel_err"],
            # exact ceil-rank percentiles over the SAME samples/clock
            # the streaming histogram ingested — |win_pXX - runner_pXX|
            # <= win_rel_err * runner_pXX is the machine-checked
            # agreement contract (invariant 11 / tests)
            "runner_p50_ms": cont["runner_pctls_ms"][0],
            "runner_p95_ms": cont["runner_pctls_ms"][1],
            "runner_p99_ms": cont["runner_pctls_ms"][2],
            "padding_frac": cont["padding_frac"],
            "burst_qps": round(burst["qps"], 4),
            "burst_p50_ms": burst["p50_ms"],
            "burst_p99_ms": burst["p99_ms"],
            "burst_qdepth_p99": burst["qdepth_p99"],
            "burst_padding_frac": burst["padding_frac"],
            "burst_admit": burst_admit,
            "qps_ratio_vs_burst": round(cont["qps"] / burst["qps"], 4),
            # degraded-mode evidence (invariant 9): every offered request
            # was served, shed, or hard-failed — nothing vanished
            "offered_requests": n_requests,
            "served_requests": cont["served"],
            "shed_requests": cont["shed"],
            "failed_requests": cont["failed"],
            "shed_frac": round(cont["shed"] / n_requests, 6),
            "deadline_miss_frac": round(
                runner.deadline_misses / n_requests, 6),
            "fault_retries": runner.fault_retries,
            "engine_failures": runner.engine_failures,
            "faults_injected": injector.injected["dispatch"],
            # health sentinel evidence (PR 14): findings NEW to this
            # replay (the monitor is monotone like the flight counters),
            # the SLO burn peaks, and the staging-discipline violations
            # — all zero on a clean run (the acceptance pin)
            "health_findings": len(health_mod.monitor.since(hmark)),
            "health_worst_severity": health_mod.summarize_rows(
                health_mod.monitor.since(hmark))["worst_severity"],
            "health_fast_burn": round(runner.health.peak_fast, 3),
            "health_slow_burn": round(runner.health.peak_slow, 3),
            "health_breaches": runner.health.breaches,
            "health_budget_drift": srv.steady.violations,
            "deadline_ms": deadline_ms,
            "max_queue_rows": max_queue_rows,
            "fault_rate": fault_rate,
            "steady_compiles": steady["compiles"],
            "steady_dispatches": steady["dispatches"],
            "steady_readbacks": steady["readbacks"],
            "budget_violations": srv.steady.violations,
            "batches": runner.dispatched,
            "max_queue_delay_ms": max_queue_delay_ms,
            "startup_sec": round(startup_s, 4),
            "startup_compiles": info["compiles"],
            "cache_hits": info["cache_hits"],
            "cache_misses": info["cache_misses"],
            "exec_hbm_bytes": exec_hbm,
            "n_requests": n_requests,
            "rows_per_request": rows_per_request,
            "ladder": list(srv.ladder.rungs),
            "num_workers": mesh.num_workers,
        }
    finally:
        if tmp is not None:
            tmp.cleanup()


def _rows_request(srv: Server, rng: np.random.Generator,
                  n_rows: int) -> dict:
    return srv.engine.synthetic_request(rng, n_rows)


def _request_latencies_ms(srv: Server, chunk: list[dict]) -> list[float]:
    """Per-request latency for one processed burst: completion time of
    the LAST batch that carried any of the request's rows."""
    if not srv.last_batch_times:
        return [0.0] * len(chunk)
    # rows are batched in arrival order; walk batches assigning requests
    done_at: list[float] = []
    rows_left = []
    for req in chunk:
        key = srv.engine.REQUEST_KEY
        val = req.get(key, req.get("x", []))
        rows_left.append(max(1, len(val)))
    it = iter(srv.last_batch_times)
    _, avail, t_done = next(it)
    for n in rows_left:
        while n > 0:
            take = min(n, avail)
            n -= take
            avail -= take
            if n > 0 and avail == 0:
                _, avail, t_done = next(it)
        done_at.append(t_done)
        if avail == 0:
            nxt = next(it, None)
            if nxt is None:
                # trailing requests (shouldn't happen) share the last time
                avail = 1 << 30
            else:
                _, avail, t_done = nxt
    return [t * 1e3 for t in done_at]
