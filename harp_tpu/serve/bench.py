"""Serving benchmark — qps + latency percentiles as ``kind:"serve"`` rows.

Self-contained (synthetic state + synthetic requests), so it runs on the
relay without a checkpoint on disk — the ``serve_kmeans`` /
``serve_mfsgd_topk`` configs in scripts/measure_all.py and the
``python -m harp_tpu serve <app> --bench`` CLI both route here.  The
emitted row is validated by scripts/check_jsonl.py invariant 7: latency
percentiles monotone (p50 ≤ p95 ≤ p99), qps > 0, and — the serving
loop's whole point — ``steady_compiles == 0`` (the CompileWatch delta
over the timed region; a row claiming serve throughput while silently
recompiling per batch must fail the checker, not enter BASELINE.md).

Latency accounting: requests are issued in bursts (the micro-batcher
sees a real queue, not one request at a time); a request's latency is
the time from its burst's submission to the completion of the batch
that produced its last row — queueing plus service, the number a client
would observe.
"""

from __future__ import annotations

import tempfile
import time

import numpy as np

from harp_tpu.serve.engines import ENGINES
from harp_tpu.serve.server import Server
from harp_tpu.utils import flightrec, telemetry

DEFAULT_LADDER = (1, 8, 64, 512)


def benchmark(app: str = "kmeans", n_requests: int = 256,
              rows_per_request: int = 1, burst: int = 64,
              ladder=DEFAULT_LADDER, mesh=None, seed: int = 0,
              state_shape: dict | None = None, topk: int = 10,
              cache_dir: str | None = None) -> dict:
    """Serve ``n_requests`` synthetic requests; return the bench row.

    ``state_shape`` forwards to the engine's ``synthetic_state`` (e.g.
    ``{"n_users": 138_493, "n_items": 26_744, "rank": 64}`` for the
    ML-20M-shaped mfsgd config).  ``cache_dir=None`` uses a fresh temp
    dir, so the AOT cache path (compile → persist → it's a cold start)
    is exercised without polluting a real cache.
    """
    from harp_tpu.parallel.mesh import current_mesh

    if app not in ENGINES:
        raise ValueError(f"unknown serve app {app!r}")
    mesh = mesh or current_mesh()
    rng = np.random.default_rng(seed)
    state = ENGINES[app].synthetic_state(rng, **(state_shape or {}))
    engine_opts = {"topk": topk} if app == "mfsgd" else {}

    tmp = None
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="harp_serve_aot_")
        cache_dir = tmp.name
    try:
        srv = Server(app, state=state, mesh=mesh, ladder=ladder,
                     cache_dir=cache_dir, budget_action="warn",
                     engine_opts=engine_opts)
        # telemetry ON (without resetting ambient collectors: bench.py /
        # measure_all deltas over the same counters must stay monotone)
        # so CompileWatch evidence backs the steady_compiles claim
        with telemetry.scope(True, reset=False):
            t0 = time.perf_counter()
            info = srv.startup()
            startup_s = time.perf_counter() - t0

            reqs = [srv.engine.synthetic_request(rng, rows_per_request)
                    for _ in range(n_requests)]
            # warmup burst: first dispatch of every executable off-clock
            warm = [srv.engine.synthetic_request(rng, rows_per_request)
                    for _ in range(min(burst, 8))]
            srv.process(warm)

            srv.steady.reset()
            base = flightrec.snapshot()
            latencies_ms: list[float] = []
            t0 = time.perf_counter()
            for lo in range(0, n_requests, burst):
                chunk = reqs[lo:lo + burst]
                responses = srv.process(chunk)
                bad = [r for r in responses if r and "error" in r]
                if bad:
                    raise RuntimeError(f"serve bench request failed: "
                                       f"{bad[0]['error']}")
                latencies_ms.extend(_request_latencies_ms(srv, chunk))
            wall = time.perf_counter() - t0
            steady = flightrec.delta_since(base)
        p50, p95, p99 = np.percentile(latencies_ms, [50, 95, 99])
        return {
            "kind": "serve", "app": app,
            "qps": n_requests / wall,
            "rows_per_sec": n_requests * rows_per_request / wall,
            "p50_ms": round(float(p50), 4),
            "p95_ms": round(float(p95), 4),
            "p99_ms": round(float(p99), 4),
            "steady_compiles": steady["compiles"],
            "steady_dispatches": steady["dispatches"],
            "steady_readbacks": steady["readbacks"],
            "budget_violations": srv.steady.violations,
            "batches": srv.steady.batches,
            "padding_frac": round(srv.batcher.padding_frac(), 6),
            "startup_sec": round(startup_s, 4),
            "startup_compiles": info["compiles"],
            "cache_hits": info["cache_hits"],
            "cache_misses": info["cache_misses"],
            "n_requests": n_requests,
            "rows_per_request": rows_per_request,
            "burst": burst,
            "ladder": list(srv.ladder.rungs),
            "num_workers": mesh.num_workers,
        }
    finally:
        if tmp is not None:
            tmp.cleanup()


def _request_latencies_ms(srv: Server, chunk: list[dict]) -> list[float]:
    """Per-request latency for one processed burst: completion time of
    the LAST batch that carried any of the request's rows."""
    if not srv.last_batch_times:
        return [0.0] * len(chunk)
    # rows are batched in arrival order; walk batches assigning requests
    done_at: list[float] = []
    rows_left = []
    for req in chunk:
        key = srv.engine.REQUEST_KEY
        val = req.get(key, req.get("x", []))
        rows_left.append(max(1, len(val)))
    it = iter(srv.last_batch_times)
    _, avail, t_done = next(it)
    for n in rows_left:
        while n > 0:
            take = min(n, avail)
            n -= take
            avail -= take
            if n > 0 and avail == 0:
                _, avail, t_done = next(it)
        done_at.append(t_done)
        if avail == 0:
            nxt = next(it, None)
            if nxt is None:
                # trailing requests (shouldn't happen) share the last time
                avail = 1 << 30
            else:
                _, avail, t_done = nxt
    return [t * 1e3 for t in done_at]
