"""``harp serve`` — persistent-mesh inference for the trained apps.

Reference parity (SURVEY.md §1, ROADMAP "harp serve"): Harp has NO serving
path at all — every reference app is batch fit-and-exit, and the "serve
heavy traffic" leg of the north star has no upstream analogue.  This
subsystem is therefore strictly beyond-reference (PARITY.md serving row):
a long-lived server process that loads a trained model through
:class:`harp_tpu.utils.checkpoint.CheckpointManager`, keeps the mesh and
the (sharded) model state device-resident across requests, and answers
inference queries for the trained apps.

The relay traps (CLAUDE.md, all measured 2026-07-30) are *hard
invariants* of the steady state here, not advice:

- the micro-batcher (:mod:`harp_tpu.serve.batcher`) coalesces queued
  requests into a small ladder of fixed padded shapes, so the steady
  state never sees a new shape → never recompiles
  (``flightrec.budget(compiles=0)`` wraps every batch);
- every batch is ONE dispatch of a cached executable and ONE stacked
  readback (``dispatches=1, readbacks=1`` — engines fold multi-output
  results into a single array on device);
- the AOT executable cache (:mod:`harp_tpu.serve.cache`) persists
  compiled executables to disk keyed by (jax version, topology, shape,
  code fingerprint), so a warm restart performs ZERO XLA compiles before
  its first response (CompileWatch-proven in tests/test_serve.py);
- the continuous plane (:class:`~harp_tpu.serve.server.
  ContinuousRunner` over :class:`~harp_tpu.serve.batcher.
  ContinuousScheduler`, fronted by asyncio TCP in
  :mod:`harp_tpu.serve.transport`) admits requests WHILE batches are in
  flight and dispatches batch t+1 before batch t's readback, so the
  mesh never drains between bursts — same budgets, proven EXACT by
  ``SteadyState.verify_exact``.
"""

from harp_tpu.serve.batcher import (ContinuousScheduler, MicroBatcher,
                                    ShapeLadder)
from harp_tpu.serve.cache import ExecutableCache
from harp_tpu.serve.server import ContinuousRunner, Server

__all__ = ["ContinuousScheduler", "ContinuousRunner", "MicroBatcher",
           "ShapeLadder", "ExecutableCache", "Server"]
