"""AOT executable cache — compile once, restart warm.

The flight recorder measured ~140 ms per XLA backend compile over the
relay (CLAUDE.md traps, 2026-07-30); a server with a 4-rung ladder and
several apps pays that cold-start cost on every restart unless the
compiled artifact outlives the process.  This cache persists each
``jit(...).trace(...).lower().compile()`` result to disk via
``jax.experimental.serialize_executable`` and loads it back with
``deserialize_and_load`` — which performs NO backend compile (pinned by
tests/test_serve.py with CompileWatch), so a warm restart answers its
first request with zero compiles.

Keys bind the artifact to everything that could invalidate it:

- ``jax.__version__`` (serialized executables are not stable across
  releases),
- the topology (platform + device kinds + device count — an executable
  compiled for 8 sim-CPU devices must not load on a v5e),
- the batch shape signature (every input aval, so model shapes AND the
  ladder rung participate),
- the program name the caller passes, which the server builds from the
  app plus the engine's ``cache_tag()`` — options that are baked into
  the compiled program as constants (mfsgd's ``topk``, lda's
  ``em_iters``/``alpha``) shape the executable without changing any
  aval, so they must key separately or a restart with different flags
  would silently serve the old program,
- a code fingerprint (sha1 over the serve package sources plus the
  engine's model module — a changed step function must miss, never
  silently serve stale code).

Entries are atomic-rename pickle files (the _save_pack discipline from
models/lda.py: the sprint environment routinely kills processes
mid-write, and a truncated entry must never poison later restarts).
A corrupt or stale entry falls back to a fresh compile — the cache can
lose, never lie.

Memory sidecar (PR 19): each entry persists its ``memory_analysis()``
HBM footprint (argument/output/temp/generated-code bytes) beside the
pickle as ``aot_<key>.mem.json`` — the literal input the multi-tenant
"does tenant N fit" admission check needs, surfaced on ``serve
--bench`` rows as ``exec_hbm_bytes`` and recorded on the memrec spine
(``kind:"memory"`` executable rows) on both the compile and the warm
cache-hit path.  Backends that do not expose the analysis (some CPU
sims) simply skip the sidecar — the footprint can be absent, never
wrong.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import warnings

import jax


def _topology_tag() -> str:
    devs = jax.devices()
    kinds = sorted({d.device_kind for d in devs})
    return f"{jax.default_backend()}:{len(devs)}:{','.join(kinds)}"


def code_fingerprint(extra_modules: tuple = ()) -> str:
    """sha1 over the serve package sources (+ any engine model modules):
    the executable is a compilation of this code, so the key must change
    when it does.  The parallel layer is always included — the sharded
    step programs compile through shard_map and the collective verbs, so
    a semantic change there must also miss."""
    import harp_tpu.parallel.collective as _coll
    import harp_tpu.parallel.mesh as _mesh
    import harp_tpu.serve as pkg

    h = hashlib.sha1()
    paths = []
    pkg_dir = os.path.dirname(os.path.abspath(pkg.__file__))
    for fn in sorted(os.listdir(pkg_dir)):
        if fn.endswith(".py"):
            paths.append(os.path.join(pkg_dir, fn))
    for mod in (_coll, _mesh) + tuple(extra_modules):
        f = getattr(mod, "__file__", None)
        if f and f.endswith(".py"):
            paths.append(f)
    for p in paths:
        with open(p, "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:16]


def _aval_sig(args) -> str:
    parts = []
    for a in jax.tree.leaves(args):
        shape = tuple(getattr(a, "shape", ()))
        dtype = getattr(a, "dtype", None)
        parts.append(f"{shape}/{dtype}")
    return ";".join(parts)


class ExecutableCache:
    """Disk-backed cache of serialized XLA executables.

    ``get_or_compile(name, jitted, args)`` returns a loaded executable:
    on a hit it deserializes (0 compiles); on a miss it compiles, then
    persists.  ``hits``/``misses`` count per instance so server startup
    can report cache effectiveness next to the CompileWatch delta.
    """

    def __init__(self, cache_dir: str, fingerprint: str | None = None):
        self.cache_dir = os.path.abspath(cache_dir)
        os.makedirs(self.cache_dir, exist_ok=True)
        self.fingerprint = fingerprint or code_fingerprint()
        self.hits = 0
        self.misses = 0

    def _key(self, name: str, args) -> str:
        sig = "|".join([name, jax.__version__, _topology_tag(),
                        self.fingerprint, _aval_sig(args)])
        return hashlib.sha1(sig.encode()).hexdigest()[:24]

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"aot_{key}.pkl")

    def _mem_path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"aot_{key}.mem.json")

    def footprint(self, name: str, args) -> dict | None:
        """The persisted memory_analysis() footprint for (name, arg
        shapes), or None (pre-PR-19 entry / backend without the
        analysis).  Read-only — admission checks call this without
        loading the executable."""
        try:
            with open(self._mem_path(self._key(name, args))) as fh:
                fp = json.load(fh)
            return fp if isinstance(fp, dict) else None
        except (OSError, ValueError):
            return None

    def load(self, name: str, args):
        """The cached executable for (name, arg shapes), or None."""
        from jax.experimental import serialize_executable

        key = self._key(name, args)
        path = self._path(key)
        try:
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
            ser, in_tree, out_tree = payload
            exe = serialize_executable.deserialize_and_load(
                ser, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — any bad entry (truncated
            # pickle, jaxlib XlaRuntimeError on a payload the key didn't
            # invalidate, ...) must degrade to a fresh compile: the cache
            # can lose, never lie — and never crash startup
            if os.path.exists(path):
                warnings.warn(
                    f"serve cache entry {os.path.basename(path)} "
                    f"unreadable ({type(e).__name__}: {e}) — recompiling",
                    RuntimeWarning)
            return None
        self.hits += 1
        from harp_tpu.utils import memrec

        fp = self.footprint(name, args) \
            or memrec.footprint_from_analysis(exe)
        memrec.note_executable(name, fp, source="cache")
        return exe

    def compile_and_store(self, name: str, jitted, args):
        from jax.experimental import serialize_executable

        with warnings.catch_warnings():
            # CPU XLA cannot honor buffer donation and warns per compile;
            # the donation is real on TPU (the double-buffer contract) and
            # harmlessly ignored on the sim backend
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            exe = jitted.trace(*args).lower().compile()
        self.misses += 1
        payload = serialize_executable.serialize(exe)
        key = self._key(name, args)
        path = self._path(key)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as fh:
                pickle.dump(payload, fh)
            os.replace(tmp, path)
        except (OSError, pickle.PicklingError) as e:
            warnings.warn(f"serve cache write failed ({e}) — executable "
                          "stays in-memory only", RuntimeWarning)
            try:
                os.unlink(tmp)
            except OSError:
                pass
        from harp_tpu.utils import memrec

        fp = memrec.footprint_from_analysis(exe)
        if fp is not None:
            mem_path = self._mem_path(key)
            mem_tmp = f"{mem_path}.{os.getpid()}.tmp"
            try:
                with open(mem_tmp, "w") as fh:
                    json.dump(fp, fh)
                os.replace(mem_tmp, mem_path)
            except OSError:
                try:
                    os.unlink(mem_tmp)
                except OSError:
                    pass
        memrec.note_executable(name, fp, source="compile")
        return exe

    def get_or_compile(self, name: str, jitted, args):
        exe = self.load(name, args)
        if exe is None:
            exe = self.compile_and_store(name, jitted, args)
        return exe
