"""asyncio TCP front end — the continuous request plane's wire.

Reference parity: none (ROADMAP "harp serve" next rungs; Harp is batch
fit-and-exit).  PR 6 kept the JSONL protocol deliberately socket-shaped;
this module puts it on a real socket without changing a byte of it:
``{"id": ..., "x"/"users": ...}`` in, ``{"id": ..., "result"/"error":
...}`` out, one JSON object per line.

Threading model — one event loop, one dispatcher thread:

- the **asyncio event loop** owns every socket.  Per connection it
  reads lines, stamps each request's ARRIVAL time at the socket (the
  honest latency origin — not burst submit), and pushes ``(conn, seq,
  req, t_arrival)`` onto a thread-safe queue.  Admission therefore
  never waits on the device: requests keep streaming in while batches
  are in flight, which is the entire point of the continuous plane.
- the **dispatcher thread** owns the jax work.  It drains the admission
  queue into the server's :class:`~harp_tpu.serve.server.
  ContinuousRunner`, steps the dispatch pipeline (batch t+1 launches
  right after batch t's dispatch returns), and posts completed
  responses back to the event loop, which delivers them to the owning
  connection via a per-connection writer task.

Ordering: responses are delivered **in admission order per
connection** (FIFO rows through FIFO batches through an order-
preserving ``call_soon_threadsafe`` hop).  Control lines: ``{"cmd":
"stats"}`` answers immediately from the reader (out of band — it may
interleave with in-flight data responses, unlike the stdio plane's
flush-first rule), ``{"cmd": "quit"}`` (or EOF) closes that connection
once its outstanding responses have flushed, ``{"cmd": "shutdown"}``
drains the pipeline and stops the whole server — scripts/drive_check.py
uses it to exercise the transport end to end without a relay.

Failure behavior (PR 10, the fault plane): a client that disconnects —
cleanly or mid-flight with responses outstanding — costs exactly its
own work: the dispatcher finishes any batch its rows already share
(other requests in that batch still need the answer), the orphaned
responses are dropped at delivery (``_Conn.closed``), and every other
connection is untouched.  Engine failures never reach this layer as
exceptions: the :class:`~harp_tpu.serve.server.ContinuousRunner`
isolates them into per-request structured error responses, so the
dispatcher thread — and with it the whole server — survives any batch
crashing (plus shedding/deadlines via the ``deadline_s`` /
``max_queue_rows`` knobs it forwards).
"""

from __future__ import annotations

import asyncio
import json
import queue
import sys
import threading
import time
from typing import Any

from harp_tpu.serve.server import Server
from harp_tpu.utils import reqtrace

_STOP = object()   # dispatcher-queue sentinel
_CLOSE = object()  # per-connection writer sentinel


class _Conn:
    """Per-connection bookkeeping, touched only from the event loop
    (except the hashable identity the dispatcher uses as a key)."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.q: asyncio.Queue = asyncio.Queue()
        self.outstanding = 0
        self.draining = False
        self.closed = False  # writer gone: drop orphaned responses
        self.seq = 0


class TCPFrontEnd:
    """One server's TCP front end.  ``port=0`` binds a free port (read
    it back from ``.port`` after startup); ``start_in_thread`` runs the
    whole loop on a daemon thread for tests and drive scripts."""

    def __init__(self, server: Server, host: str = "127.0.0.1",
                 port: int = 0, *, max_queue_delay_s: float = 0.005,
                 rung_policy: str = "adaptive", depth: int = 2,
                 deadline_s: float | None = None,
                 max_queue_rows: int | None = None, max_retries: int = 2):
        self.srv = server
        self.host, self.port = host, port
        self._knobs = dict(max_queue_delay_s=max_queue_delay_s,
                           rung_policy=rung_policy, depth=depth,
                           deadline_s=deadline_s,
                           max_queue_rows=max_queue_rows,
                           max_retries=max_retries)
        self._inq: queue.Queue = queue.Queue()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._thread: threading.Thread | None = None
        self._conns: set[_Conn] = set()
        self._rids: dict[Any, int] = {}  # (conn, seq) -> trace id
        self.runner = None

    # -- event-loop side ---------------------------------------------------
    async def _run(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._closed = asyncio.Event()
        self.runner = self.srv.make_runner(**self._knobs)
        self._aserver = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._aserver.sockets[0].getsockname()[1]
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="harp-serve-dispatch",
            daemon=True)
        self._dispatcher.start()
        self._started.set()
        await self._closed.wait()
        self._aserver.close()  # stop accepting; live conns drain below
        self._inq.put(_STOP)
        # join on an executor thread — joining inline would block the
        # loop the dispatcher needs for its final response deliveries
        await self._loop.run_in_executor(None, self._dispatcher.join)
        # deliveries the dispatcher scheduled before exiting are already
        # queued ahead of this callback, so every response is in its
        # connection queue by now: release the readers still blocked
        for conn in list(self._conns):
            conn.draining = True
            if conn.outstanding == 0:
                conn.q.put_nowait(_CLOSE)
        await self._aserver.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        conn = _Conn(writer)
        self._conns.add(conn)
        wtask = asyncio.ensure_future(self._write_loop(conn))
        try:
            while not conn.draining:
                try:
                    line = await reader.readline()
                except (ConnectionError, asyncio.IncompleteReadError,
                        OSError):
                    break  # peer vanished mid-flight: same as EOF
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except ValueError:
                    self._deliver(conn, {"id": None,
                                         "error": "unparseable JSON"})
                    continue
                cmd = req.get("cmd") if isinstance(req, dict) else None
                if cmd == "stats":
                    stats = self.srv.stats()
                    if self.runner is not None:
                        stats["continuous"] = self.runner.stats()
                        # health sentinel (PR 14) at top level too: the
                        # SLO-burn snapshot an operator polls for —
                        # also nested under continuous.health
                        stats["health"] = self.runner.health.snapshot(
                            time.perf_counter())
                    conn.q.put_nowait(stats)
                    continue
                if cmd == "quit":
                    break
                if cmd == "shutdown":
                    self._closed.set()
                    break
                conn.outstanding += 1
                conn.seq += 1
                # trace id minted AT the socket (PR 12): the honest span
                # origin is transport arrival, not dispatcher admission
                t = time.perf_counter()
                rid = reqtrace.arrive(t, transport="tcp",
                                      conn=id(conn), seq=conn.seq)
                self._inq.put((conn, conn.seq, req, t, rid))
        finally:
            conn.draining = True
            if conn.outstanding == 0 or conn.closed:
                conn.q.put_nowait(_CLOSE)
            await wtask
            self._conns.discard(conn)

    async def _write_loop(self, conn: _Conn) -> None:
        while True:
            resp = await conn.q.get()
            if resp is _CLOSE:
                break
            conn.writer.write((json.dumps(resp) + "\n").encode())
            try:
                await conn.writer.drain()
            except (ConnectionError, OSError):
                break  # peer gone: remaining responses become orphans
        conn.closed = True
        try:
            conn.writer.close()
        except Exception:  # noqa: BLE001 - already-gone peer is fine
            pass

    def _deliver(self, conn: _Conn, resp: dict,
                 data_response: bool = False) -> None:
        """Runs on the event loop; per-conn order is the queue order.

        A response for a connection whose writer already closed (client
        disconnected mid-flight) is DROPPED — the batch that produced it
        still served every live request in it, and the accounting below
        still releases the reader so the connection tears down."""
        if not conn.closed:
            conn.q.put_nowait(resp)
        if data_response:
            conn.outstanding -= 1
            if conn.draining and conn.outstanding == 0:
                conn.q.put_nowait(_CLOSE)

    # -- dispatcher side ---------------------------------------------------
    def _post(self, key: Any, resp: dict) -> None:
        conn, _seq = key
        # delivery closes the causal chain: the span already terminated
        # (served/shed/failed) when the runner answered; this stamps the
        # moment the response left the dispatcher for the owning socket
        reqtrace.tracer.event(self._rids.pop(key, None), "deliver",
                              time.perf_counter())
        self._loop.call_soon_threadsafe(self._deliver, conn, resp, True)

    def _submit(self, item) -> None:
        conn, seq, req, t, rid = item
        key = (conn, seq)
        if rid is not None:
            self._rids[key] = rid
        for k, resp in self.runner.submit(key, req, now=t, trace_id=rid):
            self._post(k, resp)

    def _dispatch_loop(self) -> None:
        r = self.runner
        stop = False
        while True:
            while True:  # drain every admission already queued
                try:
                    item = self._inq.get_nowait()
                except queue.Empty:
                    break
                if item is _STOP:
                    stop = True
                    break
                self._submit(item)
            if stop:
                for key, resp in r.drain():
                    self._post(key, resp)
                return
            for key, resp in r.step():
                self._post(key, resp)
            if r.pending() == 0 and not r._in_flight:
                item = self._inq.get()  # idle: block for work
                if item is _STOP:
                    for key, resp in r.drain():
                        self._post(key, resp)
                    return
                self._submit(item)

    # -- lifecycle ---------------------------------------------------------
    def start_in_thread(self) -> "TCPFrontEnd":
        self._thread = threading.Thread(
            target=lambda: asyncio.run(self._run()),
            name="harp-serve-tcp", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=120):
            raise RuntimeError("TCP front end failed to start")
        return self

    def shutdown(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._closed.set)

    def join(self, timeout: float | None = 120) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


def serve_forever(server: Server, host: str, port: int, *,
                  max_queue_delay_s: float = 0.005,
                  rung_policy: str = "adaptive",
                  deadline_s: float | None = None,
                  max_queue_rows: int | None = None,
                  max_retries: int = 2) -> None:
    """CLI entry: serve until a ``{"cmd": "shutdown"}`` line arrives
    (prints one ``serve_listening`` JSON line to stderr with the bound
    port so callers of ``--tcp 0`` can find it)."""
    fe = TCPFrontEnd(server, host, port,
                     max_queue_delay_s=max_queue_delay_s,
                     rung_policy=rung_policy, deadline_s=deadline_s,
                     max_queue_rows=max_queue_rows,
                     max_retries=max_retries)

    async def _main():
        task = asyncio.ensure_future(fe._run())
        await asyncio.sleep(0)  # let _run bind before announcing
        while not fe._started.is_set():
            await asyncio.sleep(0.01)
        print(json.dumps({"kind": "serve_listening", "host": host,
                          "port": fe.port}), file=sys.stderr, flush=True)
        await task

    asyncio.run(_main())
