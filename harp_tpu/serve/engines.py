"""Per-app inference engines — the batched predict step programs.

Each engine owns three things for one trained app:

- **state**: which arrays a checkpoint must provide (host-validated with
  numpy only — the warm-restart contract forbids any device math outside
  the cached executables, or startup would compile), and where each
  lives on the resident mesh (replicated, or sharded for the model-
  parallel engines);
- **step**: the jitted batched-inference program at one ladder rung.
  Every step folds its outputs into ONE device array so the serving
  loop's ``readbacks=1`` budget holds, and takes the batch input as its
  LAST argument with ``donate_argnums`` set — the in-flight batch buffer
  is donated back to XLA so double-buffered batches reuse it (honored
  on TPU; the CPU sim ignores donation with a suppressed warning);
- **protocol**: how request JSON rows become the padded input array and
  how the stacked output array becomes per-row results.

State layout conventions (what :mod:`harp_tpu.utils.checkpoint` should
hold — MIGRATING.md "Serving a trained model" shows the export snippet
per app):

==========  ==========================================================
app         required checkpoint keys
==========  ==========================================================
``kmeans``  ``centroids`` [k, d]
``mfsgd``   ``W`` [n_users, r], ``H`` [n_items, r] (stripped factors,
            i.e. ``MFSGD.factors()`` output — not the padded device
            layout the training checkpoint holds)
``lda``     ``Nwk`` [vocab, K] word-topic counts (``Nk`` optional,
            recomputed when absent)
``mlp``     ``params`` (the trainer's layer list of ``{"w", "b"}``)
``rf``      ``feats``/``thresh``/``leaves`` (the allgathered forest) +
            ``edges`` (the quantile bin edges)
``svm``     ``w`` [d], ``b`` scalar
==========  ==========================================================

Trainer fit-checkpoints that already contain these keys (mlp's
``fit_ckpt``, lda's ``fit``) load directly; extra keys are ignored.
"""

from __future__ import annotations

import numpy as np

from harp_tpu.parallel.mesh import WorkerMesh

_F32 = np.float32


def _require(state: dict, keys: tuple, app: str) -> None:
    missing = [k for k in keys if k not in state]
    if missing:
        raise KeyError(
            f"serve[{app}]: checkpoint state is missing {missing} "
            f"(has {sorted(state)}) — see harp_tpu/serve/engines.py for "
            "the per-app state layout")


def _np(x, dtype=None):
    a = np.asarray(x)
    return a.astype(dtype) if dtype is not None and a.dtype != dtype else a


class Engine:
    """Base: replicated state, ``x`` rows as f32 feature vectors."""

    app = "?"
    #: request key carrying the rows (list-of-lists unless overridden)
    REQUEST_KEY = "x"

    def fingerprint_modules(self) -> tuple:
        """Model modules whose source joins the cache fingerprint (the
        engines that call into models/ must recompile when it changes)."""
        return ()

    def cache_tag(self) -> str:
        """Engine options baked into the compiled program as constants
        (not visible in any input aval) — joins the AOT cache key via
        the program name, so ``--topk 20`` never hits a ``--topk 10``
        executable.  Empty when the step has no such options."""
        return ""

    def __init__(self, state: dict, mesh: WorkerMesh):
        self.mesh = mesh
        self._dev_state: tuple | None = None
        self._load(dict(state))

    # -- subclass surface --------------------------------------------------
    def _load(self, state: dict) -> None:
        raise NotImplementedError

    def _step_fn(self):
        """The batched step: ``fn(*state_args, x) -> stacked out``."""
        raise NotImplementedError

    def _input_cols(self) -> tuple[int, ...]:
        """Trailing input dims (input is [batch, *cols])."""
        raise NotImplementedError

    def _input_dtype(self):
        return _F32

    def output_rows(self, out: np.ndarray, n_rows: int) -> list:
        raise NotImplementedError

    # -- shared machinery --------------------------------------------------
    def jitted(self):
        import jax

        fn = self._step_fn()
        n_state = len(self.state_args())
        return jax.jit(fn, donate_argnums=(n_state,))

    def state_args(self) -> tuple:
        """Resident device arrays, placed once (replicated by default)."""
        import jax

        if self._dev_state is None:
            self._dev_state = tuple(
                jax.device_put(a, self.mesh.replicated())
                for a in self._host_state())
        return self._dev_state

    def _host_state(self) -> tuple:
        raise NotImplementedError

    def trace_args(self, rung: int) -> tuple:
        """ShapeDtypeStructs for AOT trace at one ladder rung."""
        import jax

        state = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype,
                                 sharding=self.mesh.replicated())
            for a in self._host_state())
        x = jax.ShapeDtypeStruct((rung,) + self._input_cols(),
                                 np.dtype(self._input_dtype()),
                                 sharding=self.mesh.replicated())
        return state + (x,)

    def rows_from_request(self, req: dict) -> np.ndarray:
        if self.REQUEST_KEY not in req:
            raise ValueError(
                f"serve[{self.app}]: request needs {self.REQUEST_KEY!r}")
        rows = _np(req[self.REQUEST_KEY], self._input_dtype())
        want = (None,) + self._input_cols()
        if rows.ndim != len(want) or rows.shape[1:] != want[1:]:
            raise ValueError(
                f"serve[{self.app}]: rows shaped {rows.shape}, expected "
                f"[n, {', '.join(str(c) for c in want[1:])}]")
        return rows

    def make_input(self, rows: np.ndarray, rung: int) -> np.ndarray:
        """Pad the real rows up to the rung with zeros (row 0 semantics
        are harmless in every engine; padded outputs are sliced off)."""
        if rows.shape[0] == rung:
            return np.ascontiguousarray(rows)
        pad = np.zeros((rung - rows.shape[0],) + rows.shape[1:],
                       rows.dtype)
        return np.concatenate([rows, pad], axis=0)

    def put_input(self, arr: np.ndarray):
        import jax

        from harp_tpu.utils import flightrec

        # flight recorder: staging IS the serve plane's bulk H2D — one
        # counted placement per batch window, so the "one staging per
        # batch" discipline (h2d_calls=1) is budget-enforceable and a
        # retry-with-restage shows up in the budget-drift health row
        flightrec.record_h2d(arr.nbytes)
        return jax.device_put(arr, self.mesh.replicated())

    # -- bench/test helpers ------------------------------------------------
    @classmethod
    def synthetic_state(cls, rng: np.random.Generator, **shape) -> dict:
        raise NotImplementedError

    def synthetic_request(self, rng: np.random.Generator,
                          n_rows: int) -> dict:
        raise NotImplementedError


class KMeansAssign(Engine):
    """Nearest-centroid assignment — the serving half of edu.iu.kmeans.

    Same MXU decomposition as training (models/kmeans.py): the argmin
    drops the assignment-invariant row norms, so the score matrix is one
    ``x @ centroidsᵀ`` dot per batch.
    """

    app = "kmeans"

    def _load(self, state: dict) -> None:
        _require(state, ("centroids",), self.app)
        self.centroids = _np(state["centroids"], _F32)
        if self.centroids.ndim != 2:
            raise ValueError("centroids must be [k, d]")
        self.k, self.d = self.centroids.shape

    def _host_state(self):
        return (self.centroids,)

    def _input_cols(self):
        return (self.d,)

    def _step_fn(self):
        import jax.numpy as jnp
        from jax import lax

        def step(centroids, x):
            dots = lax.dot_general(
                x, centroids.T, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            c2 = (centroids.astype(jnp.float32) ** 2).sum(-1)
            return jnp.argmin(c2[None, :] - 2.0 * dots,
                              axis=1).astype(jnp.int32)

        return step

    def output_rows(self, out, n_rows):
        return [int(c) for c in out[:n_rows]]

    @classmethod
    def synthetic_state(cls, rng, k=16, d=32, **_):
        return {"centroids": rng.normal(size=(k, d)).astype(_F32)}

    def synthetic_request(self, rng, n_rows):
        return {"x": rng.normal(size=(n_rows, self.d)).astype(
            _F32).tolist()}


class MFSGDTopK(Engine):
    """Dot-product top-k recommendation over rotated MF factors.

    Model-parallel on the resident mesh: ``H`` shards over workers (the
    item axis), each worker scores its slice and keeps a local top-k,
    and one ``pull`` (allgather) merges the per-worker candidates into
    the exact global top-k — the wire carries [nw, batch, k] candidate
    pairs instead of the full [batch, n_items] score matrix.
    """

    app = "mfsgd"
    REQUEST_KEY = "users"

    def __init__(self, state: dict, mesh: WorkerMesh, topk: int = 10):
        self.topk = int(topk)
        super().__init__(state, mesh)

    def cache_tag(self) -> str:
        # n_items too: it masks the padded tail as a program constant,
        # and 255 vs 256 items pad to the same H_padded aval on 8 workers
        return f"topk={self.topk},n_items={self.n_items}"

    def _load(self, state: dict) -> None:
        _require(state, ("W", "H"), self.app)
        self.W = _np(state["W"], _F32)
        H = _np(state["H"], _F32)
        if self.W.ndim != 2 or H.ndim != 2 or self.W.shape[1] != H.shape[1]:
            raise ValueError("W/H must be [n, r] with matching rank")
        self.n_users, self.rank = self.W.shape
        self.n_items = H.shape[0]
        self.topk = min(self.topk, self.n_items)
        nw = self.mesh.num_workers
        ipw = -(-self.n_items // nw)
        pad = nw * ipw - self.n_items
        self.H_padded = (np.concatenate(
            [H, np.zeros((pad, self.rank), _F32)]) if pad else H)
        self.items_per_worker = ipw

    def _host_state(self):
        return (self.W, self.H_padded)

    def state_args(self):
        import jax

        if self._dev_state is None:
            self._dev_state = (
                jax.device_put(self.W, self.mesh.replicated()),
                jax.device_put(self.H_padded,
                               self.mesh.sharding(self.mesh.spec(0))),
            )
        return self._dev_state

    def trace_args(self, rung: int):
        import jax

        return (
            jax.ShapeDtypeStruct(self.W.shape, np.dtype(_F32),
                                 sharding=self.mesh.replicated()),
            jax.ShapeDtypeStruct(self.H_padded.shape, np.dtype(_F32),
                                 sharding=self.mesh.sharding(
                                     self.mesh.spec(0))),
            jax.ShapeDtypeStruct((rung,), np.dtype(np.int32),
                                 sharding=self.mesh.replicated()),
        )

    def _input_cols(self):
        return ()

    def _input_dtype(self):
        return np.int32

    def _step_fn(self):
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from harp_tpu.parallel import collective as C
        from harp_tpu.parallel.mesh import worker_id

        kk = self.topk
        ipw = self.items_per_worker
        n_items = self.n_items
        k_local = min(kk, ipw)

        def prog(W, H_loc, users):
            w = W[users]                                   # [b, r]
            scores = lax.dot_general(
                w, H_loc.T, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [b, ipw]
            gid = (worker_id() * ipw
                   + lax.broadcasted_iota(jnp.int32, scores.shape, 1))
            scores = jnp.where(gid < n_items, scores, -jnp.inf)
            s_loc, i_loc = lax.top_k(scores, k_local)      # [b, k_local]
            id_loc = jnp.take_along_axis(gid, i_loc, axis=1)
            # merge: every worker pulls all candidates, takes the exact
            # global top-k over nw*k_local (replicated result)
            s_all, id_all = C.allgather(
                (s_loc[None], id_loc.astype(jnp.float32)[None]),
                tiled=False)                               # [nw, 1, b, k]
            b = s_loc.shape[0]
            s_all = jnp.moveaxis(s_all[:, 0], 0, 1).reshape(b, -1)
            id_all = jnp.moveaxis(id_all[:, 0], 0, 1).reshape(b, -1)
            s_top, pick = lax.top_k(s_all, kk)             # [b, kk]
            id_top = jnp.take_along_axis(id_all, pick, axis=1)
            return jnp.concatenate([id_top, s_top], axis=1)  # [b, 2*kk]

        return self.mesh.shard_map(
            prog,
            in_specs=(P(), self.mesh.spec(0), P()),
            out_specs=P(),
        )

    def rows_from_request(self, req: dict) -> np.ndarray:
        if "users" not in req:
            raise ValueError("serve[mfsgd]: request needs 'users'")
        users = _np(req["users"], np.int32)
        if users.ndim != 1:
            raise ValueError("serve[mfsgd]: 'users' must be a flat list")
        if users.size and (users.min() < 0 or users.max() >= self.n_users):
            raise ValueError(
                f"serve[mfsgd]: user ids must lie in [0, {self.n_users})")
        return users

    def output_rows(self, out, n_rows):
        kk = self.topk
        ids = out[:n_rows, :kk].astype(np.int64)
        scores = out[:n_rows, kk:]
        return [{"items": r_ids.tolist(),
                 "scores": [round(float(s), 6) for s in r_s]}
                for r_ids, r_s in zip(ids, scores)]

    @classmethod
    def synthetic_state(cls, rng, n_users=512, n_items=256, rank=16, **_):
        return {"W": rng.normal(size=(n_users, rank)).astype(_F32),
                "H": rng.normal(size=(n_items, rank)).astype(_F32)}

    def synthetic_request(self, rng, n_rows):
        return {"users": rng.integers(0, self.n_users,
                                      n_rows).astype(int).tolist()}


class LDAInfer(Engine):
    """Fold-in topic inference from trained word-topic counts.

    Requests carry bag-of-words count vectors over the training vocab;
    the step runs a fixed number of EM iterations of the standard
    fold-in (phi held fixed, per-doc theta re-estimated) — two MXU
    matmuls per iteration, no per-token work.
    """

    app = "lda"

    def __init__(self, state: dict, mesh: WorkerMesh, em_iters: int = 16,
                 beta: float = 0.01, alpha: float = 0.0):
        self.em_iters = int(em_iters)
        self.beta = float(beta)
        self.alpha = float(alpha)
        super().__init__(state, mesh)

    def cache_tag(self) -> str:
        # beta is absent on purpose: it only smooths phi host-side, and
        # phi is an input aval — not a constant of the program
        return f"em={self.em_iters},a={self.alpha}"

    def _load(self, state: dict) -> None:
        _require(state, ("Nwk",), self.app)
        Nwk = _np(state["Nwk"], _F32)
        if Nwk.ndim != 2:
            raise ValueError("Nwk must be [vocab, K]")
        self.vocab_size, self.n_topics = Nwk.shape
        Nk = (_np(state["Nk"], _F32) if "Nk" in state else Nwk.sum(0))
        # phi[w, k] = p(w | k), smoothed exactly as training's sampler
        self.phi = ((Nwk + self.beta)
                    / (Nk[None, :] + self.vocab_size * self.beta)
                    ).astype(_F32)

    def _host_state(self):
        return (self.phi,)

    def _input_cols(self):
        return (self.vocab_size,)

    def _step_fn(self):
        import jax.numpy as jnp
        from jax import lax

        K = self.n_topics
        iters = self.em_iters
        alpha = self.alpha

        def step(phi, x):
            theta = jnp.full((x.shape[0], K), 1.0 / K, jnp.float32)

            def body(_, theta):
                denom = lax.dot_general(
                    theta, phi.T, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)     # [b, V]
                r = x / jnp.maximum(denom, 1e-30)
                theta = theta * lax.dot_general(
                    r, phi, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) + alpha
                return theta / jnp.maximum(
                    theta.sum(-1, keepdims=True), 1e-30)

            return lax.fori_loop(0, iters, body, theta)

        return step

    def output_rows(self, out, n_rows):
        return [{"theta": [round(float(t), 6) for t in row]}
                for row in out[:n_rows]]

    @classmethod
    def synthetic_state(cls, rng, vocab_size=128, n_topics=8, **_):
        return {"Nwk": rng.integers(
            0, 50, (vocab_size, n_topics)).astype(_F32)}

    def synthetic_request(self, rng, n_rows):
        return {"x": rng.integers(
            0, 4, (n_rows, self.vocab_size)).astype(_F32).tolist()}


class MLPPredict(Engine):
    """Forward pass through the trained DP MLP (logits + argmax class)."""

    app = "mlp"

    def fingerprint_modules(self):
        from harp_tpu.models import mlp

        return (mlp,)

    def _load(self, state: dict) -> None:
        _require(state, ("params",), self.app)
        params = state["params"]
        if isinstance(params, dict):  # orbax may restore a list as a dict
            params = [params[k] for k in sorted(params, key=_int_if_digit)]
        self.params = [{"w": _np(l["w"], _F32), "b": _np(l["b"], _F32)}
                       for l in params]
        self.d_in = self.params[0]["w"].shape[0]
        self.n_classes = self.params[-1]["w"].shape[1]

    def _host_state(self):
        out = []
        for layer in self.params:
            out += [layer["w"], layer["b"]]
        return tuple(out)

    def _input_cols(self):
        return (self.d_in,)

    def _step_fn(self):
        from harp_tpu.models.mlp import MLPConfig, forward

        sizes = [self.d_in] + [l["w"].shape[1] for l in self.params]
        cfg = MLPConfig(sizes=tuple(sizes))
        n_layers = len(self.params)

        def step(*args):
            flat, x = args[:-1], args[-1]
            params = [{"w": flat[2 * i], "b": flat[2 * i + 1]}
                      for i in range(n_layers)]
            return forward(params, x, cfg)                 # [b, classes]

        return step

    def output_rows(self, out, n_rows):
        out = out[:n_rows]
        return [{"class": int(np.argmax(row)),
                 "logits": [round(float(v), 6) for v in row]}
                for row in out]

    @classmethod
    def synthetic_state(cls, rng, sizes=(32, 16, 4), **_):
        params = []
        for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
            params.append({
                "w": (rng.normal(size=(fan_in, fan_out))
                      * np.sqrt(2.0 / fan_in)).astype(_F32),
                "b": np.zeros((fan_out,), _F32)})
        return {"params": params}

    def synthetic_request(self, rng, n_rows):
        return {"x": rng.normal(size=(n_rows, self.d_in)).astype(
            _F32).tolist()}


def _int_if_digit(k):
    return (0, int(k)) if str(k).isdigit() else (1, str(k))


class RFPredict(Engine):
    """Majority-vote forest prediction (host binize + device routing)."""

    app = "rf"

    def fingerprint_modules(self):
        from harp_tpu.models import rf

        return (rf,)

    def _load(self, state: dict) -> None:
        _require(state, ("feats", "thresh", "leaves", "edges"), self.app)
        self.feats = _np(state["feats"], np.int32)
        self.thresh = _np(state["thresh"], np.int32)
        self.leaves = _np(state["leaves"], np.int32)
        self.edges = _np(state["edges"], _F32)
        inner = self.feats.shape[1]
        self.max_depth = int(np.log2(inner + 1))
        if 2 ** self.max_depth - 1 != inner:
            raise ValueError(f"feats width {inner} is not 2^d - 1")
        self.n_classes = int(state.get("n_classes",
                                       int(self.leaves.max()) + 1))
        self.n_features = self.edges.shape[0]

    def _host_state(self):
        return (self.feats, self.thresh, self.leaves)

    def _input_cols(self):
        return (self.n_features,)

    def _input_dtype(self):
        return np.int32

    def _step_fn(self):
        from harp_tpu.models.rf import predict_forest

        max_depth, n_classes = self.max_depth, self.n_classes

        def step(feats, thresh, leaves, bins):
            return predict_forest((feats, thresh, leaves), bins,
                                  max_depth, n_classes)

        return step

    def rows_from_request(self, req: dict) -> np.ndarray:
        from harp_tpu.models.rf import binize

        if "x" not in req:
            raise ValueError("serve[rf]: request needs 'x'")
        x = _np(req["x"], _F32)
        if x.ndim != 2 or x.shape[1] != self.n_features:
            raise ValueError(
                f"serve[rf]: rows shaped {x.shape}, expected "
                f"[n, {self.n_features}]")
        return binize(x, self.edges)

    def output_rows(self, out, n_rows):
        return [int(c) for c in out[:n_rows]]

    @classmethod
    def synthetic_state(cls, rng, n_trees=4, max_depth=3, n_features=8,
                        n_bins=16, n_classes=2, **_):
        inner = 2 ** max_depth - 1
        return {
            "feats": rng.integers(0, n_features,
                                  (n_trees, inner)).astype(np.int32),
            "thresh": rng.integers(0, n_bins - 1,
                                   (n_trees, inner)).astype(np.int32),
            "leaves": rng.integers(0, n_classes,
                                   (n_trees, 2 ** max_depth)
                                   ).astype(np.int32),
            "edges": np.sort(rng.normal(size=(n_features, n_bins - 1)),
                             axis=1).astype(_F32),
            "n_classes": np.int64(n_classes),
        }

    def synthetic_request(self, rng, n_rows):
        return {"x": rng.normal(size=(n_rows, self.n_features)).astype(
            _F32).tolist()}


class SVMPredict(Engine):
    """Linear decision function; label is the host-side sign."""

    app = "svm"

    def _load(self, state: dict) -> None:
        _require(state, ("w", "b"), self.app)
        self.w = _np(state["w"], _F32).reshape(-1)
        self.b = _F32(np.asarray(state["b"]).reshape(()))
        self.d = self.w.shape[0]

    def _host_state(self):
        return (self.w, np.asarray(self.b, _F32))

    def _input_cols(self):
        return (self.d,)

    def _step_fn(self):
        def step(w, b, x):
            return x @ w + b                                # [b]

        return step

    def output_rows(self, out, n_rows):
        return [{"score": round(float(s), 6),
                 "label": 1 if s >= 0 else -1} for s in out[:n_rows]]

    @classmethod
    def synthetic_state(cls, rng, d=32, **_):
        return {"w": rng.normal(size=d).astype(_F32), "b": _F32(0.1)}

    def synthetic_request(self, rng, n_rows):
        return {"x": rng.normal(size=(n_rows, self.d)).astype(
            _F32).tolist()}


ENGINES: dict[str, type[Engine]] = {
    e.app: e for e in (KMeansAssign, MFSGDTopK, LDAInfer, MLPPredict,
                       RFPredict, SVMPredict)}


def make_engine(app: str, state: dict, mesh: WorkerMesh,
                **opts) -> Engine:
    if app not in ENGINES:
        raise ValueError(
            f"no serve engine for {app!r}; choose from {sorted(ENGINES)}")
    return ENGINES[app](state, mesh, **opts)
