"""The ``harp serve`` server — persistent mesh, JSONL over stdio.

Reference parity: none (ROADMAP "harp serve"; Harp is batch fit-and-exit
— PARITY.md serving row).  Lifecycle:

1. **startup** — load the newest checkpoint
   (:meth:`~harp_tpu.utils.checkpoint.CheckpointManager.restore_latest`),
   place the engine's model state on the resident mesh, and obtain one
   executable per ladder rung through the AOT cache
   (:mod:`harp_tpu.serve.cache`) — on a warm restart every rung is a
   cache hit and startup performs ZERO XLA compiles;
2. **steady state** — two request planes share the cached executables:

   - *burst* (:meth:`Server.process` / :meth:`Server.serve_stdio`, the
     PR-6 plane): a burst is admitted, drained to completion through
     the micro-batcher, and only then is the next burst admitted;
   - *continuous* (:class:`ContinuousRunner`, this PR): requests are
     admitted into the :class:`~harp_tpu.serve.batcher.
     ContinuousScheduler` WHILE device batches are in flight, and the
     dispatcher launches batch t+1 as soon as batch t's dispatch
     returns — before t's readback — so admission, staging and compute
     overlap and the mesh never drains between bursts (the serving-
     plane analogue of PR 2's chunked-rotate overlap).

   Either way every scheduler window runs under the flight-recorder
   steady-state guard (``compiles=0, dispatches<=1, readbacks<=1`` —
   :class:`harp_tpu.utils.flightrec.SteadyState`; the continuous loop
   additionally proves EXACT totals via ``verify_exact``), so the
   relay traps are enforced invariants of the loop, not advice.  While
   batch *t* executes, batch *t+1*'s padded input is staged onto the
   device (the donate-argnums double buffer: the step donates its
   batch buffer, so XLA can reuse it for the next staging on TPU).

The request protocol is line-delimited JSON — over stdin/stdout (no
network stack, so the whole server is testable and benchmarkable in
process) or over asyncio TCP with per-connection response routing
(:mod:`harp_tpu.serve.transport`, ``--tcp PORT``):

- request: ``{"id": <any>, "x": [[...], ...]}`` (``"users"`` for
  mfsgd); rows beyond the max ladder rung span several batches;
- response: ``{"id": <same>, "result": [<one entry per row>]}`` in
  request order, or ``{"id": ..., "error": "..."}``;
- control: ``{"cmd": "stats"}`` emits a stats line, ``{"cmd": "quit"}``
  (or EOF) shuts down.
"""

from __future__ import annotations

import collections
import json
import sys
import time
from typing import IO, Any, Callable, Sequence

import numpy as np

from harp_tpu import health as health_mod
from harp_tpu.serve.batcher import (DEFAULT_LADDER, ContinuousScheduler,
                                    MicroBatcher, ShapeLadder)
from harp_tpu.serve.cache import ExecutableCache, code_fingerprint
from harp_tpu.serve.engines import make_engine
from harp_tpu.utils import flightrec, reqtrace, telemetry


class Server:
    """One app's inference server on a resident mesh.

    ``state`` (a checkpoint pytree) or ``ckpt`` (a CheckpointManager
    root; newest step restored) must be given.  ``cache_dir=None``
    disables persistence (every startup compiles); with a directory the
    AOT cache makes warm restarts compile-free.  ``budget_action`` is
    "raise" (tests) or "warn" (production/bench: record, don't die).
    """

    def __init__(self, app: str, state: dict | None = None, *,
                 ckpt: str | None = None, mesh=None,
                 ladder: Sequence[int] = DEFAULT_LADDER,
                 cache_dir: str | None = None,
                 budget_action: str = "raise", engine_opts: dict | None = None):
        from harp_tpu.parallel.mesh import current_mesh

        if state is None:
            if ckpt is None:
                raise ValueError("Server needs state= or ckpt=")
            from harp_tpu.utils.checkpoint import CheckpointManager

            self.ckpt_step, state = CheckpointManager(ckpt).restore_latest()
        else:
            self.ckpt_step = None
        self.app = app
        self.mesh = mesh or current_mesh()
        self.engine = make_engine(app, state, self.mesh,
                                  **(engine_opts or {}))
        self.ladder = (ladder if isinstance(ladder, ShapeLadder)
                       else ShapeLadder(ladder))
        self.batcher = MicroBatcher(self.ladder)
        self.cache = (ExecutableCache(
            cache_dir,
            code_fingerprint(self.engine.fingerprint_modules()))
            if cache_dir else None)
        self.steady = flightrec.SteadyState(
            compiles=0, dispatches=1, readbacks=1,
            action=budget_action, tag=f"serve.{app}")
        self._exec: dict[int, object] = {}
        self.requests_served = 0
        self.rows_served = 0
        self.last_batch_times: list[tuple[int, int, float]] = []

    # -- startup -----------------------------------------------------------
    def startup(self) -> dict:
        """Place state + obtain every rung's executable (AOT cache first).

        Returns ``{"rungs", "cache_hits", "cache_misses", "compiles"}``;
        ``compiles`` is the CompileWatch delta across startup (needs
        telemetry enabled; None otherwise) — on a warm restart it is 0.
        """
        base = flightrec.snapshot() if telemetry.enabled() else None
        n_state = len(self.engine.state_args())  # resident placement
        jitted = self.engine.jitted()
        tag = self.engine.cache_tag()
        name = f"{self.app}[{tag}]" if tag else self.app
        for rung in self.ladder.rungs:
            args = self.engine.trace_args(rung)
            if self.cache is not None:
                exe = self.cache.get_or_compile(name, jitted, args)
            else:
                exe = self.cache_less_compile(jitted, args)
            # donate_argnums mirrors engines.jitted(): the batch buffer
            # (arg n_state) is donated, so the memory ledger sees it
            # leave the live set at dispatch (runtime twin of HL303)
            self._exec[rung] = flightrec.track(
                exe, f"serve.{self.app}.b{rung}",
                donate_argnums=(n_state,))
        self.steady.reset()
        return {
            "rungs": list(self.ladder.rungs),
            "cache_hits": self.cache.hits if self.cache else 0,
            "cache_misses": self.cache.misses if self.cache else 0,
            "compiles": (flightrec.delta_since(base)["compiles"]
                         if base is not None else None),
        }

    def wrap_executables(self, wrap_fn) -> None:
        """Re-wrap every rung's executable: ``exe -> wrap_fn(rung, exe)``.

        The hook instrumentation layers use to observe the dispatch
        plane without touching the serving loop — harplint's CommGraph
        donation audit (HL303: the engine donates its batch buffer, so
        the depth-2 in-flight pipeline must stage a FRESH buffer per
        batch and never re-read a donated one) wraps here at lint time;
        tests wrap here to sabotage the discipline and prove the audit
        catches it.  Wrappers must delegate attribute access like
        ``flightrec.track``'s do.
        """
        if not self._exec:
            raise RuntimeError("call startup() before wrap_executables()")
        self._exec = {rung: wrap_fn(rung, exe)
                      for rung, exe in self._exec.items()}

    @staticmethod
    def cache_less_compile(jitted, args):
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return jitted.trace(*args).lower().compile()

    # -- steady state ------------------------------------------------------
    def _stage(self, batch, rows_by_slot: dict):
        parts = [rows_by_slot[slot][lo:hi]
                 for slot, lo, hi in batch.requests]
        rows = (np.concatenate(parts, axis=0) if len(parts) > 1
                else parts[0])
        return self.engine.put_input(
            self.engine.make_input(rows, batch.rung))

    def process(self, requests: list[dict]) -> list[dict]:
        """Answer a burst of requests (arrival order preserved)."""
        if not self._exec:
            raise RuntimeError("call startup() before process()")
        t0 = time.perf_counter()
        responses: list[dict | None] = [None] * len(requests)
        rows_by_slot: dict[int, np.ndarray] = {}
        out_segs: dict[int, list[np.ndarray]] = {}
        for slot, req in enumerate(requests):
            if not isinstance(req, dict):
                responses[slot] = {"id": None,
                                   "error": "request must be a JSON object"}
                continue
            try:
                rows = self.engine.rows_from_request(req)
                if rows.shape[0] == 0:
                    responses[slot] = {"id": req.get("id"), "result": []}
                    continue
            except (ValueError, KeyError, TypeError) as e:
                responses[slot] = {"id": req.get("id"), "error": str(e)}
                continue
            rows_by_slot[slot] = rows
            out_segs[slot] = []
            self.batcher.put(slot, rows.shape[0])

        batches = list(self.batcher.batches())
        self.last_batch_times = []
        state_args = self.engine.state_args()
        staged = self._stage(batches[0], rows_by_slot) if batches else None
        for i, batch in enumerate(batches):
            with self.steady.batch():
                out_dev = self._exec[batch.rung](*state_args, staged)
                # double buffer: stage batch i+1 while i is in flight
                staged = (self._stage(batches[i + 1], rows_by_slot)
                          if i + 1 < len(batches) else None)
                out = flightrec.readback(out_dev)
            self.last_batch_times.append(
                (batch.rung, batch.rows, time.perf_counter() - t0))
            cursor = 0
            for slot, lo, hi in batch.requests:
                out_segs[slot].append(out[cursor:cursor + (hi - lo)])
                cursor += hi - lo
            self.rows_served += batch.rows

        for slot, segs in out_segs.items():
            full = (np.concatenate(segs, axis=0) if len(segs) > 1
                    else segs[0])
            n = rows_by_slot[slot].shape[0]
            responses[slot] = {
                "id": requests[slot].get("id"),
                "result": self.engine.output_rows(full, n)}
        self.requests_served += sum(r is not None and "result" in r
                                    for r in responses)
        return responses  # type: ignore[return-value]

    def stats(self) -> dict:
        return {
            "kind": "serve_stats", "app": self.app,
            "requests_served": self.requests_served,
            "rows_served": self.rows_served,
            "padding_frac": round(self.batcher.padding_frac(), 6),
            "steady": self.steady.summary(),
        }

    def make_runner(self, *, max_queue_delay_s: float = 0.005,
                    rung_policy: str = "adaptive", depth: int = 2,
                    clock: Callable[[], float] = time.perf_counter,
                    deadline_s: float | None = None,
                    max_queue_rows: int | None = None,
                    max_retries: int = 2,
                    stats_window_s: float = 60.0) -> "ContinuousRunner":
        """A continuous request plane over this server's executables."""
        if not self._exec:
            raise RuntimeError("call startup() before make_runner()")
        return ContinuousRunner(self, max_queue_delay_s=max_queue_delay_s,
                                rung_policy=rung_policy, depth=depth,
                                clock=clock, deadline_s=deadline_s,
                                max_queue_rows=max_queue_rows,
                                max_retries=max_retries,
                                stats_window_s=stats_window_s)

    # -- stdio loop --------------------------------------------------------
    def serve_stdio(self, stdin: IO, stdout: IO) -> int:
        """Blocking JSONL loop; returns the number of requests answered.

        Consecutive already-available lines coalesce into one burst (so
        the micro-batcher sees the real queue depth, not one request at
        a time); a line arriving alone is its own burst — the 1-rung.
        """
        reader = _BurstReader(stdin)
        while True:
            lines = reader.read_burst()
            if not lines:
                return self.requests_served
            burst: list[dict] = []
            for line in lines:
                try:
                    req = json.loads(line)
                except ValueError:
                    # flush first: responses must come out in input order
                    self._flush(burst, stdout)
                    burst = []
                    stdout.write(json.dumps(
                        {"id": None, "error": "unparseable JSON"}) + "\n")
                    continue
                cmd = req.get("cmd") if isinstance(req, dict) else None
                if cmd == "quit":
                    self._flush(burst, stdout)
                    stdout.flush()
                    return self.requests_served
                if cmd == "stats":
                    self._flush(burst, stdout)
                    burst = []
                    stdout.write(json.dumps(self.stats()) + "\n")
                    continue
                burst.append(req)
            self._flush(burst, stdout)
            stdout.flush()

    def _flush(self, burst: list[dict], stdout: IO) -> None:
        if burst:
            for resp in self.process(burst):
                stdout.write(json.dumps(resp) + "\n")


class ContinuousRunner:
    """Admit-while-in-flight dispatcher — the continuous request plane.

    Owns one :class:`~harp_tpu.serve.batcher.ContinuousScheduler` and a
    bounded pipeline of in-flight device batches (``depth``, default 2:
    the donated-buffer double buffer).  The driving loop is three verbs:

    - :meth:`submit` admits a request at its arrival time (legal at any
      moment — between :meth:`step` calls of an active pipeline);
    - :meth:`step` performs ONE scheduler-window action: dispatch the
      next batch when the policy says go and the pipeline has room,
      else read back the oldest in-flight batch, else nothing.  Batch
      t+1 therefore dispatches right after batch t's dispatch returns,
      BEFORE t's readback — on hardware with async dispatch the mesh
      never drains while the host admits/stages/formats;
    - completed responses come back from :meth:`step` as ``(key,
      response)`` pairs, in admission order (FIFO rows through FIFO
      batches — per-connection ordering is the transport's for free).

    Every window runs under the server's :class:`~harp_tpu.utils.
    flightrec.SteadyState` budget (``compiles=0, dispatches<=1,
    readbacks<=1``), and :meth:`verify_exact` proves the run's totals
    were exactly one dispatch + one readback per batch.  ``clock`` is
    injected so tests and the sustained-load bench drive the policy on
    a deterministic timeline.

    Graceful degradation (PR 10) — under overload or faults the plane
    degrades instead of dying, and every degradation is a counted,
    structured response (never unbounded latency, never a dead server):

    - **bounded admission** (``max_queue_rows``): a request that would
      push the queue past the bound is SHED at submit with
      ``{"error": ..., "shed": true, "reason": "queue_full"}``;
    - **per-request deadlines** (``deadline_s``): a queued request whose
      deadline passes before any of its rows dispatch is shed with
      ``reason: "deadline"`` (dispatching it would waste a rung on an
      answer the client already gave up on); a request that completes
      late is still answered but counted in ``deadline_misses``;
    - **retry-with-restage** (``max_retries``): a transient dispatch
      failure (an :class:`~harp_tpu.utils.fault.InjectedFault`, a relay
      hiccup) retries the batch — ALWAYS through a freshly staged input
      buffer, because the failed attempt's buffer was already donated
      (HL303: a donated buffer can never be re-dispatched; the
      ``serve.retry_restage`` protocol drive in analysis/drivers.py
      proves this discipline at lint time);
    - **failure isolation**: when retries are exhausted the batch's
      requests get structured error responses and the runner keeps
      serving — one engine crash answers errors for its requests, it
      does not kill the server (``engine_failures`` counts).
    """

    #: exceptions never treated as transient: budget violations are the
    #: guard speaking, not the device failing — retrying would bury them
    _NON_TRANSIENT = (flightrec.BudgetExceeded,)

    def __init__(self, server: Server, *,
                 max_queue_delay_s: float = 0.005,
                 rung_policy: str = "adaptive", depth: int = 2,
                 clock: Callable[[], float] = time.perf_counter,
                 deadline_s: float | None = None,
                 max_queue_rows: int | None = None,
                 max_retries: int = 2,
                 stats_window_s: float = 60.0):
        if depth < 1:
            raise ValueError(f"pipeline depth {depth} must be >= 1")
        if max_retries < 0:
            raise ValueError(f"max_retries {max_retries} must be >= 0")
        self.srv = server
        self.sched = ContinuousScheduler(
            server.ladder, max_queue_delay_s=max_queue_delay_s,
            rung_policy=rung_policy)
        self.depth = int(depth)
        self.clock = clock
        self.deadline_s = deadline_s
        self.max_queue_rows = max_queue_rows
        self.max_retries = int(max_retries)
        self._in_flight: collections.deque = collections.deque()
        # key -> {"req", "rows", "segs", "rid"} admitted-not-answered
        self._asm: dict[Any, dict] = {}
        self.dispatched = 0
        self.completed = 0
        self.shed = 0
        self.deadline_misses = 0
        self.fault_retries = 0
        self.engine_failures = 0
        self.failed = 0  # requests answered with a hard-failure error
        self.latencies_ms: collections.deque = collections.deque(
            maxlen=4096)
        # streaming windowed percentiles (PR 12): bounded-memory rolling
        # latency/queue-depth histograms on the runner's own clock —
        # live p50/p95/p99 for the TCP stats line and the sustained
        # bench row without retaining samples
        self.win = reqtrace.RollingWindow(window_s=stats_window_s)
        # health sentinel (PR 14): multi-window SLO burn over this
        # plane's terminal outcomes, on the same clock/window geometry
        # as the rolling percentiles.  No-op while telemetry is off; the
        # flagship budgets are pinned UNCHANGED with it armed.
        self.health = health_mod.SLOBurn(
            tag=f"serve.{server.app}", window_s=stats_window_s,
            latency_slo_ms=(deadline_s * 1e3 if deadline_s else None))

    # -- admission ---------------------------------------------------------
    def submit(self, key: Any, req: Any, now: float | None = None,
               trace_id: int | None = None) -> list[tuple[Any, dict]]:
        """Admit one request; returns immediately-answerable responses
        (malformed / empty / shed requests), else [] with the rows
        queued.  ``trace_id`` carries a request-tracer span minted at
        transport arrival (PR 12); without one, a span is minted here
        at admission time — either way every offered request ends in a
        terminated span with outcome served/shed/failed."""
        now = self.clock() if now is None else now
        rid = (trace_id if trace_id is not None
               else reqtrace.tracer.begin(now))
        if not isinstance(req, dict):
            reqtrace.tracer.end(rid, "failed", now, reason="bad_request")
            self.health.observe(now, "failed", rid=rid)
            return [(key, {"id": None,
                           "error": "request must be a JSON object"})]
        try:
            rows = self.srv.engine.rows_from_request(req)
        except (ValueError, KeyError, TypeError) as e:
            reqtrace.tracer.end(rid, "failed", now, reason="bad_request")
            self.health.observe(now, "failed", rid=rid)
            return [(key, {"id": req.get("id"), "error": str(e)})]
        if rows.shape[0] == 0:
            reqtrace.tracer.end(rid, "served", now, rows=0)
            self.health.observe(now, "served", latency_ms=0.0)
            return [(key, {"id": req.get("id"), "result": []})]
        if key in self._asm:
            raise ValueError(f"request key {key!r} already in flight")
        if (self.max_queue_rows is not None
                and self.sched.queued_rows + rows.shape[0]
                > self.max_queue_rows):
            self.shed += 1
            reqtrace.tracer.end(rid, "shed", now, reason="queue_full",
                                queued_rows=self.sched.queued_rows)
            self.health.observe(now, "shed", rid=rid)
            return [(key, {
                "id": req.get("id"), "shed": True, "reason": "queue_full",
                "error": f"shed: admission queue full "
                         f"({self.sched.queued_rows} rows queued, bound "
                         f"{self.max_queue_rows})"})]
        reqtrace.tracer.event(rid, "admit", now, rows=int(rows.shape[0]),
                              queued_rows=self.sched.queued_rows)
        self._asm[key] = {"req": req, "rows": rows, "segs": [],
                          "arrival": now, "rid": rid}
        self.sched.put(key, rows.shape[0], now)
        return []

    # -- the scheduler window ----------------------------------------------
    def pending(self) -> int:
        """Admitted-not-answered requests (queued or in flight)."""
        return len(self._asm)

    def next_deadline(self) -> float | None:
        return self.sched.next_deadline()

    def step(self, now: float | None = None) -> list[tuple[Any, dict]]:
        """One window: dispatch if the policy fires and the pipeline has
        room, else read back the oldest in-flight batch.  Returns the
        responses completed by this window (shed/error responses for a
        degraded window; [] for a clean dispatch window or an idle
        call)."""
        now = self.clock() if now is None else now
        self.win.add_qdepth(now, self.sched.queued_rows)
        out: list[tuple[Any, dict]] = []
        if self.deadline_s is not None:
            out += self._shed_expired(now)
        idle = not self._in_flight
        if (len(self._in_flight) < self.depth
                and self.sched.ready(now, idle)):
            batch = self.sched.next_batch(now)
            if batch is None:  # everything expired out of the queue
                return out
            rows_by_key = {key: self._asm[key]["rows"]
                           for key, _, _ in batch.requests}
            tr = reqtrace.tracer
            tr.batch(batch.seq, now, rung=batch.rung, rows=batch.rows,
                     members=[(self._asm[key]["rid"], lo, hi)
                              for key, lo, hi in batch.requests])
            for key, lo, hi in batch.requests:
                tr.event(self._asm[key]["rid"], "batch", now,
                         seq=batch.seq, lo=lo, hi=hi, rung=batch.rung)
            attempt = 0
            fatal: Exception | None = None
            # ONE steady window for the whole dispatch-with-retries
            # phase ("produce one dispatched batch"), so a retry's
            # second staging is VISIBLE to the per-window budget — in
            # warn mode it lands in the budget-drift health row (PR 14)
            # as committed restage evidence instead of vanishing with
            # the aborted window
            with self.srv.steady.batch():
                while True:
                    try:
                        # a FRESH staged buffer per attempt: the previous
                        # attempt's buffer was donated to the failed
                        # dispatch and can never be re-dispatched (HL303)
                        staged = self.srv._stage(batch, rows_by_key)
                        out_dev = self.srv._exec[batch.rung](
                            *self.srv.engine.state_args(), staged)
                        break
                    except self._NON_TRANSIENT:
                        raise
                    except Exception as e:  # noqa: BLE001 - isolate
                        attempt += 1
                        if attempt > self.max_retries:
                            fatal = e
                            break
                        self.fault_retries += 1
                        # timestamps stay on the CALLER's clock (`now`):
                        # the sustained replay drives a virtual timeline,
                        # and a wall-clock stamp here would break the
                        # trace's monotone-ts contract (invariant 11)
                        tr.batch_event(batch.seq, "retry", now,
                                       attempt=attempt,
                                       error=f"{type(e).__name__}: {e}")
            if fatal is not None:
                return out + self._fail_batch(batch, fatal, now)
            self._in_flight.append((batch, out_dev))
            self.dispatched += 1
            self.srv.rows_served += batch.rows
            tr.batch_event(batch.seq, "dispatch", now)
            return out
        if self._in_flight:
            with self.srv.steady.batch():
                batch, out_dev = self._in_flight.popleft()
                res = flightrec.readback(out_dev)
            reqtrace.tracer.batch_event(batch.seq, "readback", now)
            return out + self._complete(batch, res, now)
        return out

    def _shed_expired(self, now: float) -> list[tuple[Any, dict]]:
        """Deadline shedding: queued requests past their deadline get a
        structured error NOW — never a dispatch, never silent latency."""
        out: list[tuple[Any, dict]] = []
        for key in self.sched.expire(now, self.deadline_s):
            a = self._asm.pop(key)
            self.shed += 1
            reqtrace.tracer.end(a["rid"], "shed", now, reason="deadline")
            self.health.observe(now, "shed", rid=a["rid"])
            out.append((key, {
                "id": a["req"].get("id"), "shed": True,
                "reason": "deadline",
                "error": f"shed: deadline ({self.deadline_s * 1e3:.1f} "
                         f"ms) exceeded before dispatch"}))
        return out

    def _fail_batch(self, batch, exc: Exception,
                    now: float) -> list[tuple[Any, dict]]:
        """Retries exhausted: isolate the failure to this batch's
        requests (structured errors) and keep the runner serving."""
        self.engine_failures += 1
        reqtrace.tracer.batch_event(batch.seq, "engine_failure", now,
                                    error=f"{type(exc).__name__}: {exc}")
        keys = {key for key, _, _ in batch.requests}
        self.sched.discard(keys)  # tail segments must not dispatch later
        out: list[tuple[Any, dict]] = []
        for key in dict.fromkeys(k for k, _, _ in batch.requests):
            a = self._asm.pop(key, None)
            if a is None:
                continue
            self.failed += 1
            reqtrace.tracer.end(a["rid"], "failed", now,
                                reason="engine_failure", seq=batch.seq)
            self.health.observe(now, "failed", rid=a["rid"])
            out.append((key, {
                "id": a["req"].get("id"),
                "error": f"engine failure after {self.max_retries} "
                         f"retries: {type(exc).__name__}: {exc}"}))
        return out

    def _complete(self, batch, out: np.ndarray,
                  now: float) -> list[tuple[Any, dict]]:
        responses: list[tuple[Any, dict]] = []
        cursor = 0
        for key, lo, hi in batch.requests:
            a = self._asm.get(key)
            if a is None:  # answered with an error by a failed batch
                cursor += hi - lo
                continue
            a["segs"].append(out[cursor:cursor + (hi - lo)])
            cursor += hi - lo
            if hi == a["rows"].shape[0]:  # final segment (FIFO rows)
                segs = a["segs"]
                full = (np.concatenate(segs, axis=0) if len(segs) > 1
                        else segs[0])
                responses.append((key, {
                    "id": a["req"].get("id"),
                    "result": self.srv.engine.output_rows(
                        full, hi)}))
                lat = now - a["arrival"]
                self.latencies_ms.append(lat * 1e3)
                self.win.add_latency(now, lat * 1e3)
                missed = (self.deadline_s is not None
                          and lat > self.deadline_s)
                if missed:
                    self.deadline_misses += 1  # answered, but late
                reqtrace.tracer.end(a["rid"], "served", now,
                                    latency_ms=round(lat * 1e3, 4))
                self.health.observe(now, "served",
                                    latency_ms=lat * 1e3,
                                    deadline_missed=missed,
                                    rid=a["rid"])
                del self._asm[key]
                self.completed += 1
                self.srv.requests_served += 1
        return responses

    def drain(self, now: float | None = None) -> list[tuple[Any, dict]]:
        """Run windows until nothing is queued or in flight (shutdown /
        end-of-trace flush)."""
        out: list[tuple[Any, dict]] = []
        while self._asm or self._in_flight:
            out.extend(self.step(now))
        return out

    def verify_exact(self, *, compiles: int = 0) -> dict:
        """Prove the run's totals: exactly one dispatch + one readback
        per dispatched batch (see ``SteadyState.verify_exact``)."""
        return self.srv.steady.verify_exact(self.dispatched,
                                            compiles=compiles)

    def stats(self) -> dict:
        lat = sorted(self.latencies_ms)

        def pct(p):
            return round(lat[min(len(lat) - 1,
                                 int(p / 100 * len(lat)))], 3) if lat \
                else None

        return {"mode": "continuous", "dispatched": self.dispatched,
                "completed": self.completed,
                "queued_rows": len(self.sched),
                "in_flight": len(self._in_flight),
                "padding_frac": round(self.sched.padding_frac(), 6),
                "shed": self.shed,
                "deadline_misses": self.deadline_misses,
                "fault_retries": self.fault_retries,
                "engine_failures": self.engine_failures,
                "failed": self.failed,
                "p50_ms": pct(50), "p99_ms": pct(99),
                # live rolling-window percentiles (PR 12): bounded-memory
                # log-bucket histograms, error documented in the field
                "window": self.win.snapshot(self.clock()),
                # live SLO burn (PR 14): multi-window error-budget burn
                # over this plane's outcomes — the stats-line surface of
                # the health sentinel (zeros while telemetry is off)
                "health": self.health.snapshot(self.clock())}


class _BurstReader:
    """Burst reads: one blocking line, then every line already available.

    Real files are read with ``os.read`` on the raw fd plus our own line
    splitting, NOT text-layer ``readline`` — a TextIOWrapper buffers
    whole chunks internally, so lines it has already pulled off the pipe
    don't make the fd selectable and a select()-gated readline loop
    would push them into the NEXT burst, under-batching the real queue
    depth.  The byte buffer lives on the reader so a partial trailing
    line carries over to the next burst.  In-memory streams (no fileno)
    fall back to greedy readline, which never blocks.  Empty list = EOF.
    """

    def __init__(self, stdin: IO):
        self.stdin = stdin
        try:
            self.fd = stdin.fileno()
        except (OSError, ValueError, AttributeError):
            self.fd = None
        self._buf = b""

    def read_burst(self) -> list[str]:
        if self.fd is None:
            lines = []
            while True:  # StringIO etc.: reads never block, drain to EOF
                nxt = self.stdin.readline()
                if not nxt:
                    break
                lines.append(nxt)
            return [ln for ln in lines if ln.strip()]
        import os
        import select

        lines: list[str] = []
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                lines.append(self._buf[:nl + 1].decode("utf-8", "replace"))
                self._buf = self._buf[nl + 1:]
                continue
            if lines:  # burst started: only take bytes already available
                ready, _, _ = select.select([self.fd], [], [], 0)
                if not ready:
                    break
            chunk = os.read(self.fd, 65536)  # blocks only for line one
            if not chunk:
                if self._buf:  # EOF terminates a final unterminated line
                    lines.append(self._buf.decode("utf-8", "replace"))
                    self._buf = b""
                break
            self._buf += chunk
        return [ln for ln in lines if ln.strip()]


def main(argv=None) -> int:
    import argparse

    from harp_tpu.serve.engines import ENGINES

    p = argparse.ArgumentParser(
        prog="python -m harp_tpu serve",
        description="persistent-mesh inference server (JSONL over stdio)")
    p.add_argument("app", choices=sorted(ENGINES))
    p.add_argument("--ckpt", default=None,
                   help="checkpoint root (CheckpointManager layout); "
                        "newest step is restored")
    p.add_argument("--cache-dir", default=None,
                   help="AOT executable cache directory (default: "
                        "<ckpt>/.aot_cache; omit both for no persistence)")
    p.add_argument("--ladder", default=None,
                   help="comma-separated batch rungs (default 1,8,64,512)")
    p.add_argument("--topk", type=int, default=10,
                   help="mfsgd: recommendations per user")
    p.add_argument("--em-iters", type=int, default=16,
                   help="lda: fold-in EM iterations")
    p.add_argument("--bench", action="store_true",
                   help="measure qps + latency percentiles on synthetic "
                        "state/requests and print ONE provenance-stamped "
                        'kind:"serve" JSON row instead of serving stdio')
    p.add_argument("--sustained", action="store_true",
                   help="--bench variant: sustained-load A/B on one "
                        "seeded arrival trace — burst-drain vs the "
                        "continuous plane (offered vs achieved qps, "
                        "queue-depth percentiles, arrival->response "
                        "latency)")
    p.add_argument("--requests", type=int, default=256,
                   help="--bench: number of synthetic requests")
    p.add_argument("--rows-per-request", type=int, default=1)
    p.add_argument("--offered-qps", type=float, default=None,
                   help="--sustained: arrival rate; default calibrates "
                        "burst capacity and offers 2x it")
    p.add_argument("--burst-admit", type=int, default=64,
                   help="--sustained: burst-plane admission quantum "
                        "(PR 6's bench burst size / the stdio pipe "
                        "window)")
    p.add_argument("--tcp", type=int, default=None, metavar="PORT",
                   help="serve the JSONL protocol over asyncio TCP on "
                        "this port with the CONTINUOUS plane (stdio "
                        "stays burst-drained); port 0 picks a free one")
    p.add_argument("--host", default="127.0.0.1",
                   help="--tcp bind address")
    p.add_argument("--max-queue-delay-ms", type=float, default=5.0,
                   help="continuous plane: flush deadline — a queued "
                        "row never waits longer for a fuller rung "
                        "(measured: ~one 512-rung batch time; see "
                        "ContinuousScheduler)")
    p.add_argument("--rung-policy", choices=["adaptive", "greedy"],
                   default="adaptive",
                   help="continuous plane: adaptive holds work while "
                        "in flight to fill larger rungs; greedy "
                        "dispatches immediately at the minimal rung")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="continuous plane: per-request deadline — a "
                        "request still queued past it is SHED with a "
                        "structured error (never unbounded latency); a "
                        "late completion is served but counted")
    p.add_argument("--max-queue-rows", type=int, default=None,
                   help="continuous plane: admission bound — a request "
                        "that would push the queue past this many rows "
                        "is shed at submit (reason: queue_full)")
    p.add_argument("--max-retries", type=int, default=2,
                   help="continuous plane: retry-with-restage attempts "
                        "for a transient dispatch failure before the "
                        "batch's requests get error responses")
    p.add_argument("--fault-rate", type=float, default=0.0,
                   help="--sustained: seeded chaos — probability that "
                        "any dispatch fails transiently (the injector "
                        "rides flightrec.observe_dispatches; ~0.01 is "
                        "the graded degraded-mode bench)")
    p.add_argument("--platform", choices=["cpu"], default=None,
                   help="force the CPU backend (the axon site pin would "
                        "otherwise route to the TPU relay — CLAUDE.md)")
    args = p.parse_args(argv)
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    ladder = (tuple(int(r) for r in args.ladder.split(","))
              if args.ladder else DEFAULT_LADDER)

    if args.bench or args.sustained:
        from harp_tpu.serve.bench import benchmark, benchmark_sustained
        from harp_tpu.utils.metrics import benchmark_json

        if args.sustained:
            res = benchmark_sustained(
                app=args.app, n_requests=args.requests,
                rows_per_request=args.rows_per_request, ladder=ladder,
                offered_qps=args.offered_qps,
                burst_admit=args.burst_admit,
                max_queue_delay_ms=args.max_queue_delay_ms,
                rung_policy=args.rung_policy,
                deadline_ms=args.deadline_ms,
                max_queue_rows=args.max_queue_rows,
                max_retries=args.max_retries,
                fault_rate=args.fault_rate)
            config = f"serve_{args.app}_sustained"
            print(benchmark_json(config, res))
        else:
            res = benchmark(app=args.app, n_requests=args.requests,
                            rows_per_request=args.rows_per_request,
                            ladder=ladder)
            config = f"serve_{args.app}"
            print(benchmark_json(config, res))
        # under HARP_TELEMETRY=1 the request trace rides the standard
        # exit report (HARP_TELEMETRY_OUT exports kind:"trace" rows for
        # python -m harp_tpu trace), like every instrumented app CLI
        from harp_tpu import report

        report.maybe_emit(config)
        return 0

    if args.ckpt is None:
        p.error("--ckpt is required (or use --bench)")
    engine_opts = {}
    if args.app == "mfsgd":
        engine_opts["topk"] = args.topk
    if args.app == "lda":
        engine_opts["em_iters"] = args.em_iters
    cache_dir = args.cache_dir
    if cache_dir is None and args.ckpt:
        import os

        cache_dir = os.path.join(args.ckpt, ".aot_cache")
    srv = Server(args.app, ckpt=args.ckpt, ladder=ladder,
                 cache_dir=cache_dir, budget_action="warn",
                 engine_opts=engine_opts)
    info = srv.startup()
    print(json.dumps({"kind": "serve_ready", "app": args.app,
                      "step": srv.ckpt_step, **info}),
          file=sys.stderr, flush=True)
    if args.tcp is not None:
        from harp_tpu.serve.transport import serve_forever

        serve_forever(srv, args.host, args.tcp,
                      max_queue_delay_s=args.max_queue_delay_ms / 1e3,
                      rung_policy=args.rung_policy,
                      deadline_s=(args.deadline_ms / 1e3
                                  if args.deadline_ms else None),
                      max_queue_rows=args.max_queue_rows,
                      max_retries=args.max_retries)
        return 0
    srv.serve_stdio(sys.stdin, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
