"""The ``harp serve`` server — persistent mesh, JSONL over stdio.

Reference parity: none (ROADMAP "harp serve"; Harp is batch fit-and-exit
— PARITY.md serving row).  Lifecycle:

1. **startup** — load the newest checkpoint
   (:meth:`~harp_tpu.utils.checkpoint.CheckpointManager.restore_latest`),
   place the engine's model state on the resident mesh, and obtain one
   executable per ladder rung through the AOT cache
   (:mod:`harp_tpu.serve.cache`) — on a warm restart every rung is a
   cache hit and startup performs ZERO XLA compiles;
2. **steady state** — drain queued requests through the micro-batcher
   (:mod:`harp_tpu.serve.batcher`); every batch runs under the
   flight-recorder steady-state guard (``compiles=0, dispatches=1,
   readbacks=1`` — :class:`harp_tpu.utils.flightrec.SteadyState`), so
   the relay traps are enforced invariants of the loop, not advice.
   While batch *t* executes, batch *t+1*'s padded input is staged onto
   the device (the donate-argnums double buffer: the step donates its
   batch buffer, so XLA can reuse it for the next staging on TPU).

The request protocol is line-delimited JSON on stdin/stdout — no
network stack, so the whole server is testable (and benchmarkable) in
process:

- request: ``{"id": <any>, "x": [[...], ...]}`` (``"users"`` for
  mfsgd); rows beyond the max ladder rung span several batches;
- response: ``{"id": <same>, "result": [<one entry per row>]}`` in
  request order, or ``{"id": ..., "error": "..."}``;
- control: ``{"cmd": "stats"}`` emits a stats line, ``{"cmd": "quit"}``
  (or EOF) shuts down.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Sequence

import numpy as np

from harp_tpu.serve.batcher import DEFAULT_LADDER, MicroBatcher, ShapeLadder
from harp_tpu.serve.cache import ExecutableCache, code_fingerprint
from harp_tpu.serve.engines import make_engine
from harp_tpu.utils import flightrec, telemetry


class Server:
    """One app's inference server on a resident mesh.

    ``state`` (a checkpoint pytree) or ``ckpt`` (a CheckpointManager
    root; newest step restored) must be given.  ``cache_dir=None``
    disables persistence (every startup compiles); with a directory the
    AOT cache makes warm restarts compile-free.  ``budget_action`` is
    "raise" (tests) or "warn" (production/bench: record, don't die).
    """

    def __init__(self, app: str, state: dict | None = None, *,
                 ckpt: str | None = None, mesh=None,
                 ladder: Sequence[int] = DEFAULT_LADDER,
                 cache_dir: str | None = None,
                 budget_action: str = "raise", engine_opts: dict | None = None):
        from harp_tpu.parallel.mesh import current_mesh

        if state is None:
            if ckpt is None:
                raise ValueError("Server needs state= or ckpt=")
            from harp_tpu.utils.checkpoint import CheckpointManager

            self.ckpt_step, state = CheckpointManager(ckpt).restore_latest()
        else:
            self.ckpt_step = None
        self.app = app
        self.mesh = mesh or current_mesh()
        self.engine = make_engine(app, state, self.mesh,
                                  **(engine_opts or {}))
        self.ladder = (ladder if isinstance(ladder, ShapeLadder)
                       else ShapeLadder(ladder))
        self.batcher = MicroBatcher(self.ladder)
        self.cache = (ExecutableCache(
            cache_dir,
            code_fingerprint(self.engine.fingerprint_modules()))
            if cache_dir else None)
        self.steady = flightrec.SteadyState(
            compiles=0, dispatches=1, readbacks=1,
            action=budget_action, tag=f"serve.{app}")
        self._exec: dict[int, object] = {}
        self.requests_served = 0
        self.rows_served = 0
        self.last_batch_times: list[tuple[int, int, float]] = []

    # -- startup -----------------------------------------------------------
    def startup(self) -> dict:
        """Place state + obtain every rung's executable (AOT cache first).

        Returns ``{"rungs", "cache_hits", "cache_misses", "compiles"}``;
        ``compiles`` is the CompileWatch delta across startup (needs
        telemetry enabled; None otherwise) — on a warm restart it is 0.
        """
        base = flightrec.snapshot() if telemetry.enabled() else None
        self.engine.state_args()  # resident placement (device_put only)
        jitted = self.engine.jitted()
        tag = self.engine.cache_tag()
        name = f"{self.app}[{tag}]" if tag else self.app
        for rung in self.ladder.rungs:
            args = self.engine.trace_args(rung)
            if self.cache is not None:
                exe = self.cache.get_or_compile(name, jitted, args)
            else:
                exe = self.cache_less_compile(jitted, args)
            self._exec[rung] = flightrec.track(
                exe, f"serve.{self.app}.b{rung}")
        self.steady.reset()
        return {
            "rungs": list(self.ladder.rungs),
            "cache_hits": self.cache.hits if self.cache else 0,
            "cache_misses": self.cache.misses if self.cache else 0,
            "compiles": (flightrec.delta_since(base)["compiles"]
                         if base is not None else None),
        }

    @staticmethod
    def cache_less_compile(jitted, args):
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return jitted.trace(*args).lower().compile()

    # -- steady state ------------------------------------------------------
    def _stage(self, batch, rows_by_slot: dict):
        parts = [rows_by_slot[slot][lo:hi]
                 for slot, lo, hi in batch.requests]
        rows = (np.concatenate(parts, axis=0) if len(parts) > 1
                else parts[0])
        return self.engine.put_input(
            self.engine.make_input(rows, batch.rung))

    def process(self, requests: list[dict]) -> list[dict]:
        """Answer a burst of requests (arrival order preserved)."""
        if not self._exec:
            raise RuntimeError("call startup() before process()")
        t0 = time.perf_counter()
        responses: list[dict | None] = [None] * len(requests)
        rows_by_slot: dict[int, np.ndarray] = {}
        out_segs: dict[int, list[np.ndarray]] = {}
        for slot, req in enumerate(requests):
            if not isinstance(req, dict):
                responses[slot] = {"id": None,
                                   "error": "request must be a JSON object"}
                continue
            try:
                rows = self.engine.rows_from_request(req)
                if rows.shape[0] == 0:
                    responses[slot] = {"id": req.get("id"), "result": []}
                    continue
            except (ValueError, KeyError, TypeError) as e:
                responses[slot] = {"id": req.get("id"), "error": str(e)}
                continue
            rows_by_slot[slot] = rows
            out_segs[slot] = []
            self.batcher.put(slot, rows.shape[0])

        batches = list(self.batcher.batches())
        self.last_batch_times = []
        state_args = self.engine.state_args()
        staged = self._stage(batches[0], rows_by_slot) if batches else None
        for i, batch in enumerate(batches):
            with self.steady.batch():
                out_dev = self._exec[batch.rung](*state_args, staged)
                # double buffer: stage batch i+1 while i is in flight
                staged = (self._stage(batches[i + 1], rows_by_slot)
                          if i + 1 < len(batches) else None)
                out = flightrec.readback(out_dev)
            self.last_batch_times.append(
                (batch.rung, batch.rows, time.perf_counter() - t0))
            cursor = 0
            for slot, lo, hi in batch.requests:
                out_segs[slot].append(out[cursor:cursor + (hi - lo)])
                cursor += hi - lo
            self.rows_served += batch.rows

        for slot, segs in out_segs.items():
            full = (np.concatenate(segs, axis=0) if len(segs) > 1
                    else segs[0])
            n = rows_by_slot[slot].shape[0]
            responses[slot] = {
                "id": requests[slot].get("id"),
                "result": self.engine.output_rows(full, n)}
        self.requests_served += sum(r is not None and "result" in r
                                    for r in responses)
        return responses  # type: ignore[return-value]

    def stats(self) -> dict:
        return {
            "kind": "serve_stats", "app": self.app,
            "requests_served": self.requests_served,
            "rows_served": self.rows_served,
            "padding_frac": round(self.batcher.padding_frac(), 6),
            "steady": self.steady.summary(),
        }

    # -- stdio loop --------------------------------------------------------
    def serve_stdio(self, stdin: IO, stdout: IO) -> int:
        """Blocking JSONL loop; returns the number of requests answered.

        Consecutive already-available lines coalesce into one burst (so
        the micro-batcher sees the real queue depth, not one request at
        a time); a line arriving alone is its own burst — the 1-rung.
        """
        reader = _BurstReader(stdin)
        while True:
            lines = reader.read_burst()
            if not lines:
                return self.requests_served
            burst: list[dict] = []
            for line in lines:
                try:
                    req = json.loads(line)
                except ValueError:
                    # flush first: responses must come out in input order
                    self._flush(burst, stdout)
                    burst = []
                    stdout.write(json.dumps(
                        {"id": None, "error": "unparseable JSON"}) + "\n")
                    continue
                cmd = req.get("cmd") if isinstance(req, dict) else None
                if cmd == "quit":
                    self._flush(burst, stdout)
                    stdout.flush()
                    return self.requests_served
                if cmd == "stats":
                    self._flush(burst, stdout)
                    burst = []
                    stdout.write(json.dumps(self.stats()) + "\n")
                    continue
                burst.append(req)
            self._flush(burst, stdout)
            stdout.flush()

    def _flush(self, burst: list[dict], stdout: IO) -> None:
        if burst:
            for resp in self.process(burst):
                stdout.write(json.dumps(resp) + "\n")


class _BurstReader:
    """Burst reads: one blocking line, then every line already available.

    Real files are read with ``os.read`` on the raw fd plus our own line
    splitting, NOT text-layer ``readline`` — a TextIOWrapper buffers
    whole chunks internally, so lines it has already pulled off the pipe
    don't make the fd selectable and a select()-gated readline loop
    would push them into the NEXT burst, under-batching the real queue
    depth.  The byte buffer lives on the reader so a partial trailing
    line carries over to the next burst.  In-memory streams (no fileno)
    fall back to greedy readline, which never blocks.  Empty list = EOF.
    """

    def __init__(self, stdin: IO):
        self.stdin = stdin
        try:
            self.fd = stdin.fileno()
        except (OSError, ValueError, AttributeError):
            self.fd = None
        self._buf = b""

    def read_burst(self) -> list[str]:
        if self.fd is None:
            lines = []
            while True:  # StringIO etc.: reads never block, drain to EOF
                nxt = self.stdin.readline()
                if not nxt:
                    break
                lines.append(nxt)
            return [ln for ln in lines if ln.strip()]
        import os
        import select

        lines: list[str] = []
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                lines.append(self._buf[:nl + 1].decode("utf-8", "replace"))
                self._buf = self._buf[nl + 1:]
                continue
            if lines:  # burst started: only take bytes already available
                ready, _, _ = select.select([self.fd], [], [], 0)
                if not ready:
                    break
            chunk = os.read(self.fd, 65536)  # blocks only for line one
            if not chunk:
                if self._buf:  # EOF terminates a final unterminated line
                    lines.append(self._buf.decode("utf-8", "replace"))
                    self._buf = b""
                break
            self._buf += chunk
        return [ln for ln in lines if ln.strip()]


def main(argv=None) -> int:
    import argparse

    from harp_tpu.serve.engines import ENGINES

    p = argparse.ArgumentParser(
        prog="python -m harp_tpu serve",
        description="persistent-mesh inference server (JSONL over stdio)")
    p.add_argument("app", choices=sorted(ENGINES))
    p.add_argument("--ckpt", default=None,
                   help="checkpoint root (CheckpointManager layout); "
                        "newest step is restored")
    p.add_argument("--cache-dir", default=None,
                   help="AOT executable cache directory (default: "
                        "<ckpt>/.aot_cache; omit both for no persistence)")
    p.add_argument("--ladder", default=None,
                   help="comma-separated batch rungs (default 1,8,64,512)")
    p.add_argument("--topk", type=int, default=10,
                   help="mfsgd: recommendations per user")
    p.add_argument("--em-iters", type=int, default=16,
                   help="lda: fold-in EM iterations")
    p.add_argument("--bench", action="store_true",
                   help="measure qps + latency percentiles on synthetic "
                        "state/requests and print ONE provenance-stamped "
                        'kind:"serve" JSON row instead of serving stdio')
    p.add_argument("--requests", type=int, default=256,
                   help="--bench: number of synthetic requests")
    p.add_argument("--rows-per-request", type=int, default=1)
    p.add_argument("--platform", choices=["cpu"], default=None,
                   help="force the CPU backend (the axon site pin would "
                        "otherwise route to the TPU relay — CLAUDE.md)")
    args = p.parse_args(argv)
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    ladder = (tuple(int(r) for r in args.ladder.split(","))
              if args.ladder else DEFAULT_LADDER)

    if args.bench:
        from harp_tpu.serve.bench import benchmark
        from harp_tpu.utils.metrics import benchmark_json

        res = benchmark(app=args.app, n_requests=args.requests,
                        rows_per_request=args.rows_per_request,
                        ladder=ladder)
        print(benchmark_json(f"serve_{args.app}", res))
        return 0

    if args.ckpt is None:
        p.error("--ckpt is required (or use --bench)")
    engine_opts = {}
    if args.app == "mfsgd":
        engine_opts["topk"] = args.topk
    if args.app == "lda":
        engine_opts["em_iters"] = args.em_iters
    cache_dir = args.cache_dir
    if cache_dir is None and args.ckpt:
        import os

        cache_dir = os.path.join(args.ckpt, ".aot_cache")
    srv = Server(args.app, ckpt=args.ckpt, ladder=ladder,
                 cache_dir=cache_dir, budget_action="warn",
                 engine_opts=engine_opts)
    info = srv.startup()
    print(json.dumps({"kind": "serve_ready", "app": args.app,
                      "step": srv.ckpt_step, **info}),
          file=sys.stderr, flush=True)
    srv.serve_stdio(sys.stdin, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
