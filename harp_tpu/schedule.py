"""Intra-worker task scheduling — Harp L5 (schstatic / schdynamic) parity.

Reference parity (SURVEY.md §3.1): ``edu.iu.harp.schstatic.StaticScheduler``
and ``edu.iu.harp.schdynamic.DynamicScheduler`` run user ``Task`` objects
over a thread pool inside one worker — Harp's answer to multicore.  The
static scheduler pre-assigns inputs to tasks; the dynamic one feeds a shared
input queue and drains an output queue (``ComputeUtil`` has the
wait/accounting helpers).  The third L5 component, the ``edu.iu.dymoro``
rotation pipeline, lives in :mod:`harp_tpu.parallel.rotate`.

TPU-native design: *device* multicore is XLA's job — regular per-item
compute should be ``jax.vmap``-ed into one kernel (:func:`device_map`), not
threaded.  What legitimately remains host-side is irregular Python work that
feeds or drains the device: file parsing, per-tree/per-partition host prep,
output writing.  For that, these schedulers give Harp's exact API shape on a
``ThreadPoolExecutor`` (threads, not processes: loaders release the GIL in
numpy/native code, and device dispatch is async anyway).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Callable, Generic, Iterable, Sequence, TypeVar

import jax

from harp_tpu.utils.telemetry import span

I = TypeVar("I")
O = TypeVar("O")

_SENTINEL = object()


class Task(Generic[I, O]):
    """User compute unit — ``edu.iu.harp.schdynamic.Task`` equivalent.

    Subclass and override :meth:`run`.  Pass a *list* of instances (one per
    thread) to a scheduler for thread-private per-task state (buffers,
    models), exactly like Harp's task objects; passing a single
    callable/instance shares it across every thread, so it must be
    stateless or thread-safe.
    """

    def run(self, item: I) -> O:
        raise NotImplementedError

    def __call__(self, item: I) -> O:
        return self.run(item)


def _n_threads(n: int | None) -> int:
    return n if n and n > 0 else (os.cpu_count() or 1)


class StaticScheduler(Generic[I, O]):
    """Pre-partitioned thread-pool execution — ``schstatic.StaticScheduler``.

    Inputs are split round-robin across task instances *before* execution
    (Harp: each task owns a fixed submission list); results return in input
    order.  Use when per-item cost is uniform; otherwise prefer
    :class:`DynamicScheduler`.  A single callable is shared by all threads
    (see :class:`Task`); pass one instance per thread for private state.
    """

    def __init__(self, tasks: Sequence[Callable[[I], O]] | Callable[[I], O],
                 n_threads: int | None = None):
        if callable(tasks):
            n = _n_threads(n_threads)
            self.tasks: list[Callable[[I], O]] = [tasks] * n
        else:
            self.tasks = list(tasks)
        if not self.tasks:
            raise ValueError("StaticScheduler needs at least one task")

    def schedule(self, items: Sequence[I]) -> list[O]:
        """Run every item; item *i* goes to task ``i % len(tasks)``."""
        with span("schedule.static", items=len(items), tasks=len(self.tasks)):
            return self._schedule(items)

    def _schedule(self, items: Sequence[I]) -> list[O]:
        n = len(self.tasks)
        results: list[Any] = [None] * len(items)
        errors: list[BaseException] = []

        def worker(t: int) -> None:
            try:
                for idx in range(t, len(items), n):
                    results[idx] = self.tasks[t](items[idx])
            except BaseException as e:  # noqa: BLE001 - re-raised on main thread
                errors.append(e)

        # named so threadguard's ownership map (generated from harplint
        # Layer 5) can forbid jax work on scheduler workers by pattern
        threads = [threading.Thread(target=worker, args=(t,), daemon=True,
                                    name=f"harp-sched-static-{t}")
                   for t in range(min(n, len(items)))]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errors:
            raise errors[0]
        return results


class DynamicScheduler(Generic[I, O]):
    """Work-stealing queue execution — ``schdynamic.DynamicScheduler``.

    Tasks pull from a shared input queue and push to an output queue; the
    Harp lifecycle (``start`` → ``submit``\\* → ``waitForOutput``/``stop``)
    is preserved for streaming use, and :meth:`schedule` wraps it for the
    common submit-all-then-drain pattern (results in completion order,
    tagged with input index).
    """

    def __init__(self, tasks: Sequence[Callable[[I], O]] | Callable[[I], O],
                 n_threads: int | None = None):
        if callable(tasks):
            self.tasks: list[Callable[[I], O]] = [tasks] * _n_threads(n_threads)
        else:
            self.tasks = list(tasks)
        if not self.tasks:
            raise ValueError("DynamicScheduler needs at least one task")
        self._in: queue.Queue = queue.Queue()
        self._out: queue.Queue = queue.Queue()
        self._threads: list[threading.Thread] = []
        self._submitted = 0
        self._drained = 0

    # -- Harp lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._threads:
            raise RuntimeError("scheduler already started")

        def worker(task: Callable[[I], O]) -> None:
            while True:
                got = self._in.get()
                if got is _SENTINEL:
                    return
                idx, item = got
                try:
                    self._out.put((idx, task(item), None))
                except BaseException as e:  # noqa: BLE001 - surfaced in wait_output
                    self._out.put((idx, None, e))

        self._threads = [threading.Thread(target=worker, args=(t,),
                                          daemon=True,
                                          name=f"harp-sched-dyn-{i}")
                         for i, t in enumerate(self.tasks)]
        for th in self._threads:
            th.start()

    def submit(self, item: I) -> None:
        self._in.put((self._submitted, item))
        self._submitted += 1

    def wait_output(self) -> tuple[int, O]:
        """Block for one result — ``waitForOutput``; raises task exceptions."""
        idx, out, err = self._out.get()
        self._drained += 1
        if err is not None:
            raise err
        return idx, out

    def stop(self) -> None:
        for _ in self._threads:
            self._in.put(_SENTINEL)
        for th in self._threads:
            th.join()
        self._threads = []

    # -- convenience --------------------------------------------------------
    def schedule(self, items: Iterable[I]) -> list[O]:
        """submit-all → drain-all → stop; results re-ordered to input order.

        On an externally-started scheduler every prior submission must have
        been drained first — otherwise a stale result would be mis-slotted
        into this batch.
        """
        with span("schedule.dynamic", tasks=len(self.tasks)):
            return self._schedule(items)

    def _schedule(self, items: Iterable[I]) -> list[O]:
        started = bool(self._threads)
        if started and self._submitted != self._drained:
            raise RuntimeError(
                f"schedule() with {self._submitted - self._drained} undrained "
                f"submissions outstanding; wait_output() them first")
        if not started:
            self.start()
        base = self._submitted
        n = 0
        for item in items:
            self.submit(item)
            n += 1
        out: list[Any] = [None] * n
        first_err: BaseException | None = None
        try:
            # drain the WHOLE batch even when a task failed — leaving results
            # queued would mis-slot them into the next schedule() call
            for _ in range(n):
                idx, val, err = self._out.get()
                self._drained += 1
                if err is not None:
                    first_err = first_err or err
                    continue
                assert base <= idx < base + n, (idx, base, n)
                out[idx - base] = val
        finally:
            if not started:
                self.stop()
                while True:  # interrupted drain: discard leftovers
                    try:
                        self._out.get_nowait()
                    except queue.Empty:
                        break
                    self._drained += 1
        if first_err is not None:
            raise first_err
        return out


def apply_rebalance(splits: Sequence[Sequence[Any]], plan: dict) -> list[list]:
    """Apply a :func:`harp_tpu.utils.skew.suggest_rebalance` plan to
    per-worker item lists (the :func:`harp_tpu.fileformat.
    multi_file_splits` shape) — the bridge from *observing* skew back to
    Harp's schdynamic/dymoro load-balancing behavior: measure a run,
    ask the SkewLedger for the greedy repartition, replay it here before
    the next run.

    Only whole-unit moves apply (plans built from recorded ``units``,
    e.g. files); a fractional plan raises — it is a *target* for a
    finer-grained partitioner, not an item shuffle.  Returns new lists;
    the input is not mutated.
    """
    out = [list(s) for s in splits]
    for m in plan.get("moves", []):
        if "id" not in m:
            raise ValueError(
                "fractional rebalance plan (no unit ids): re-record the "
                "phase with units=..., or repartition toward the plan's "
                "work_after targets instead")
        try:
            out[m["from"]].remove(m["id"])
        except ValueError:
            raise ValueError(
                f"rebalance unit {m['id']!r} not found on worker "
                f"{m['from']} — the plan does not match these splits")
        out[m["to"]].append(m["id"])
    return out


def rebalance_assignment(splits: Sequence[Sequence[Any]],
                         plan: dict) -> dict:
    """:func:`apply_rebalance` flattened to ``{unit_id: worker}`` — the
    consumption shape of the elastic drivers (PR 15): a fired
    ``skew_trigger``'s inline plan replays over the current per-worker
    unit lists, and the resulting assignment drives the repartition
    (:mod:`harp_tpu.elastic.rebalance`).  Same whole-unit contract as
    :func:`apply_rebalance` (fractional plans raise)."""
    return {uid: w
            for w, lst in enumerate(apply_rebalance(splits, plan))
            for uid in lst}


def device_map(fn: Callable, items, *, batched: bool = True):
    """The TPU-native replacement for thread schedulers on *regular* work.

    Harp threads exist to use a worker's cores on per-item compute; on TPU
    the same per-item function should be ``vmap``-ed into one XLA kernel so
    the scalar/vector units and MXU see the whole batch.  ``items`` is a
    pytree whose leaves have a leading item axis.
    """
    if batched:
        return jax.vmap(fn)(items)
    return jax.lax.map(fn, items)
